"""Comparison figures for the eval harness output (eval.py --json).

    python plot_eval.py --json eval_r02.json --outdir eval_figures

Produces, per BASELINE config in the JSON:

* ``energy_by_algo_config{N}.png`` — total energy per algorithm (the
  BASELINE.md "RL return >= baseline policies" criterion is read off this
  bar chart at comparable p99);
* ``energy_vs_p99_config{N}.png`` — the efficiency/latency trade-off
  scatter: energy per unit of work vs p99 inference sojourn, one point per
  algorithm.

The reference answers this question with its paper plot suite
(`/root/reference/plot_sim_result.py`); this script is the one-look summary
over the committed eval artifact instead of raw CSV logs.
"""

import argparse
import json
import math
import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

# fixed algorithm -> color assignment (identity follows the entity across
# every figure; never re-assigned when a config lacks some algorithm)
ALGO_COLOR = {
    "default_policy": "#2a78d6",
    "joint_nf": "#eb6834",
    "bandit": "#1baf7a",
    "carbon_cost": "#eda100",
    "eco_route": "#e87ba4",
    "chsac_af": "#008300",
    "debug": "#4a3aa7",
    "cap_uniform": "#b65b12",
    "cap_greedy": "#856e00",
    "chsac_af_cold": "#6db36d",
    "chsac_af_warm": "#008300",
}
SURFACE = "#fcfcfb"
TEXT = "#0b0b0b"
TEXT2 = "#52514e"
GRID = "#e4e3df"
BAR = "#2a78d6"  # magnitude bars: one hue; identity lives on the axis


def _style(ax):
    ax.set_facecolor(SURFACE)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=TEXT2, labelsize=9)
    ax.yaxis.grid(True, color=GRID, linewidth=0.8)
    ax.set_axisbelow(True)


def _norm_rows(entry):
    """Accept both eval JSON shapes: a flat row list (single seed) or the
    multi-seed {"per_seed", "aggregate"} dict — aggregates carry mean±sd,
    rendered as error bars."""
    if isinstance(entry, list):
        return entry
    if isinstance(entry, dict) and "aggregate" in entry:
        rows = []
        for agg in entry["aggregate"]:
            row = {"algo": agg["algo"]}
            for k in ("energy_kwh", "p99_lat_inf_s", "energy_per_unit_wh"):
                row[k] = agg.get(f"{k}_mean")
                row[f"{k}_sd"] = agg.get(f"{k}_sd")
            rows.append(row)
        return rows
    return None


def _sd(r, k):
    v = r.get(f"{k}_sd")
    return v if isinstance(v, (int, float)) and not math.isnan(v) else None


def energy_bar(rows, config, outdir):
    algos = [r["algo"] for r in rows]
    kwh = [r["energy_kwh"] for r in rows]
    sds = [_sd(r, "energy_kwh") for r in rows]
    fig, ax = plt.subplots(figsize=(5.6, 3.4), dpi=150)
    fig.patch.set_facecolor(SURFACE)
    _style(ax)
    x = range(len(algos))
    yerr = [s if s is not None else 0.0 for s in sds]
    ax.bar(x, kwh, width=0.62, color=BAR, zorder=2,
           yerr=yerr if any(yerr) else None, ecolor=TEXT2, capsize=3)
    for i, v in enumerate(kwh):
        off = yerr[i]
        ax.text(i, v + off, f"{v:,.1f}", ha="center", va="bottom",
                fontsize=9, color=TEXT)
    ax.set_xticks(list(x), algos, rotation=12, color=TEXT)
    ax.set_ylabel("total energy (kWh)", color=TEXT2, fontsize=9)
    title = f"BASELINE config {config}: energy by algorithm"
    if any(s is not None for s in sds):
        title += " (mean±sd)"
    ax.set_title(title, color=TEXT, fontsize=11, loc="left")
    fig.tight_layout()
    path = os.path.join(outdir, f"energy_by_algo_config{config}.png")
    fig.savefig(path, facecolor=SURFACE)
    plt.close(fig)
    return path


def tradeoff_scatter(rows, config, outdir):
    fig, ax = plt.subplots(figsize=(5.6, 3.8), dpi=150)
    fig.patch.set_facecolor(SURFACE)
    _style(ax)
    ax.xaxis.grid(True, color=GRID, linewidth=0.8)
    for r in rows:
        p99 = r.get("p99_lat_inf_s")
        if p99 is None or (isinstance(p99, float) and math.isnan(p99)):
            continue
        y = r["energy_per_unit_wh"]
        c = ALGO_COLOR.get(r["algo"], TEXT2)
        xe, ye = _sd(r, "p99_lat_inf_s"), _sd(r, "energy_per_unit_wh")
        if xe is not None or ye is not None:
            ax.errorbar([p99], [y], xerr=xe, yerr=ye, fmt="none",
                        ecolor=c, alpha=0.45, capsize=2, zorder=2)
        ax.scatter([p99], [y], s=64, color=c, zorder=3,
                   edgecolors=SURFACE, linewidths=2)
        ax.annotate(r["algo"], (p99, y), xytext=(6, 4),
                    textcoords="offset points", fontsize=9, color=TEXT)
    ax.set_xlabel("p99 inference sojourn (s, sliding window)",
                  color=TEXT2, fontsize=9)
    ax.set_ylabel("energy per unit (Wh)", color=TEXT2, fontsize=9)
    ax.set_title(f"BASELINE config {config}: efficiency vs latency",
                 color=TEXT, fontsize=11, loc="left")
    fig.tight_layout()
    path = os.path.join(outdir, f"energy_vs_p99_config{config}.png")
    fig.savefig(path, facecolor=SURFACE)
    plt.close(fig)
    return path


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="eval_r02.json")
    ap.add_argument("--outdir", default="eval_figures")
    a = ap.parse_args(argv)

    with open(a.json) as f:
        results = json.load(f)
    os.makedirs(a.outdir, exist_ok=True)

    for key, entry in results.items():
        rows = _norm_rows(entry)
        if rows is None:
            continue
        if key.startswith("config"):
            config = key.removeprefix("config")
        elif key == "warmstart":  # eval.py --warmstart artifact
            config = "warmstart"
        else:
            continue
        print(energy_bar(rows, config, a.outdir))
        print(tradeoff_scatter(rows, config, a.outdir))


if __name__ == "__main__":
    main()

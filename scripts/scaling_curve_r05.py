"""Config-5 scaling curve: PPO events/s vs rollout count on the 8-device
virtual CPU mesh (VERDICT r04 item 5).

    python scripts/scaling_curve_r05.py        # writes eval_results/scaling_r05.json

The round-4 artifact had a single R=1024 point measured on one contended
CPU core; this produces the full R=128/256/512/1024 curve through the same
`evaluation.eval_config5` path (PPOTrainer, shard_map over the mesh), with
the 8-device virtual mesh the parallel tests use — scaling SHAPE evidence
(all virtual devices share one physical core, so absolute rates are not
chip projections; bench.py's cost model and the recovery suite's on-chip
R=1024 stage carry those).  Rows are idempotent: an (R) already in the
JSON is skipped, so a killed run resumes where it stopped.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # axon overrides the env var

OUT = "eval_results/scaling_r05.json"
ROLLOUTS = (128, 256, 512, 1024)
TIMED_CHUNKS = int(os.environ.get("DCG_SCALE_CHUNKS", 2))


def main():
    from distributed_cluster_gpus_tpu.evaluation import eval_config5

    done = {}
    if os.path.exists(OUT):
        try:
            with open(OUT) as f:
                done = json.load(f).get("points", {})
        except (json.JSONDecodeError, OSError):
            done = {}

    for r in ROLLOUTS:
        if str(r) in done:
            print(f"skip R={r} (already measured)")
            continue
        print(f"=== R={r}")
        out = eval_config5(duration_chunks=TIMED_CHUNKS, n_rollouts=r)
        out["n_devices"] = len(jax.devices())
        out["timed_chunks"] = TIMED_CHUNKS
        done[str(r)] = out
        # strict JSON (NaN -> null) like every other artifact writer
        from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

        dump_json_atomic(OUT, {
            "note": "config-5 PPO scaling curve on the 8-device "
                    "virtual CPU mesh (one physical core: shape "
                    "evidence, not absolute chip rates); reproduce: "
                    "python scripts/scaling_curve_r05.py",
            "points": done,
        })
        print(f"R={r}: {out['events_per_sec']:,.0f} ev/s")
    print("scaling curve complete")


if __name__ == "__main__":
    main()

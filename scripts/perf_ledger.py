"""Continuous perf ledger CLI: banked rounds -> ledger.jsonl + gate.

    python scripts/perf_ledger.py                     # idempotent ingest
    python scripts/perf_ledger.py --rebuild           # regenerate from scratch
    python scripts/perf_ledger.py --trend             # per-config ev/s trend
    python scripts/perf_ledger.py --check CURRENT.json [--threshold 0.3]
    python scripts/perf_ledger.py --json out.json

Ingests every banked round (BENCH_r*.json / MULTICHIP_r*.json driver
wrappers at the repo root, plus bench_results/*.json) into the
append-only ``bench_results/ledger.jsonl`` (schema ``dcg.perf_ledger.v1``,
one flat record per measurement).  Ingest is idempotent — re-running
adds nothing — and ``--rebuild`` regenerates the file byte-identically
from the same banked set.  Corrupt/foreign files degrade to one summary
line, never a traceback.

``--check`` is the regression gate: the given bench JSON (a driver
wrapper or a raw bench line) is compared against the banked best per
(kind, config) within the same platform class (CPU fallback numbers
never gate against on-chip rounds); any ev/s drop beyond --threshold
exits 1.  bench.py runs the same comparison per round (BENCH_LEDGER=1,
evidence-only); this CLI is the enforcing exit code for CI/driver use.

``--json`` writes the shared ``dcg.lint_report.v1`` shape with the
ledger action summary under ``extra``.  Exit status: 0 clean, 1 on a
regression (or an unreadable --check file), 2 on usage errors.
"""

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

from distributed_cluster_gpus_tpu.analysis import ledger, report  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=HERE,
                    help="repo root holding the banked artifacts")
    ap.add_argument("--ledger", default=None,
                    help="ledger path (default <root>/bench_results/"
                         "ledger.jsonl)")
    ap.add_argument("--rebuild", action="store_true",
                    help="regenerate the ledger from scratch "
                         "(byte-identical per banked set) instead of "
                         "appending")
    ap.add_argument("--trend", action="store_true",
                    help="print the per-config ev/s trend tables")
    ap.add_argument("--check", default=None, metavar="BENCH_JSON",
                    help="regression-gate this bench result against the "
                         "banked best (nonzero exit on a drop beyond "
                         "--threshold)")
    ap.add_argument("--threshold", type=float, default=0.3,
                    help="allowed fractional ev/s drop vs the banked "
                         "best (default 0.3)")
    ap.add_argument("--kinds", default="headline",
                    help="comma-separated record kinds the gate covers")
    ap.add_argument("--json", default=None,
                    help="write the dcg.lint_report.v1 report here")
    a = ap.parse_args(argv)
    path = a.ledger or ledger.ledger_path(a.root)

    if a.rebuild:
        res = ledger.rebuild(a.root, path)
        action = f"rebuilt {path}: {res['total']} records"
    else:
        res = ledger.ingest(a.root, path)
        action = (f"ingested {res['added']} new record(s) into {path} "
                  f"({res['total']} total)")
    print(action)
    skipped = res.get("skipped") or []
    if skipped:
        print("skipped (1 line, no tracebacks): "
              + "; ".join(f"{rel}: {why}" for rel, why in skipped))

    records = ledger.read_ledger(path)
    if a.trend:
        print("\n".join(ledger.format_trend(records)))

    violations = []
    checked = [path]
    if a.check:
        checked.append(a.check)
        doc, reason = ledger.load_banked(
            os.path.dirname(os.path.abspath(a.check)) or ".",
            os.path.basename(a.check))
        if doc is None:
            violations.append(report.violation(
                f"--check file unreadable: {reason}",
                rule="ledger-check-input", where=a.check))
        else:
            current = ledger.records_from(
                os.path.basename(a.check), doc)
            kinds = tuple(k for k in a.kinds.split(",") if k)
            for v in ledger.check(records, current,
                                  threshold=a.threshold, kinds=kinds):
                violations.append(report.violation(
                    f"{v['config']} ({v['platform_class']}): "
                    f"{v['current_ev_s']:,.0f} ev/s is "
                    f"{v['drop_fraction'] * 100:.0f}% below the banked "
                    f"best {v['best_ev_s']:,.0f} ({v['best_source']}; "
                    f"threshold {a.threshold * 100:.0f}%)",
                    rule="ledger-regression", config=v["config"],
                    where=a.check))
            if not violations:
                print(f"check OK: {a.check} holds the banked "
                      f"trajectory (threshold "
                      f"{a.threshold * 100:.0f}%)")

    rep = report.make_report(
        "perf_ledger", checked, violations,
        extra={"action": action,
               "skipped": [list(s) for s in skipped],
               "records": len(records)})
    if a.json:
        report.write_report(rep, a.json)
        print(f"wrote {a.json}")
    if violations:
        for v in violations:
            print(f"REGRESSION [{v['rule']}] {v['message']}")
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

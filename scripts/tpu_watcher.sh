#!/bin/bash
# Poll the axon TPU tunnel; when it answers, immediately run the bench
# recovery suite (scripts/tpu_recovery.sh).  The tunnel wedges such that
# jax.devices() HANGS, so every probe runs under `timeout -k`.
#
# Usage: mkdir -p bench_results && \
#        nohup scripts/tpu_watcher.sh >> bench_results/watcher.log 2>&1 &
# Stops when the recovery suite completes (or MAX_POLLS exhausted); a
# partially-completed suite (tunnel re-wedged mid-run, week run timed out)
# resumes from its idempotent stage markers on the next good probe.
set -u
cd "$(dirname "$0")/.."
mkdir -p bench_results

POLL_S=${POLL_S:-180}
PROBE_TIMEOUT=${PROBE_TIMEOUT:-90}
MAX_POLLS=${MAX_POLLS:-200}
# STOP_EPOCH: stand down before the round driver needs the chip for its
# own end-of-round bench (propagated to the suite as DEADLINE)
STOP_EPOCH=${STOP_EPOCH:-}

now() { date -u +%H:%M:%S; }

probe_err=$(mktemp)
trap 'rm -f "$probe_err"' EXIT

for i in $(seq 1 "$MAX_POLLS"); do
  if [ -n "$STOP_EPOCH" ] && \
     [ "$(date -u +%s)" -ge $(( STOP_EPOCH - 300 )) ]; then
    echo "[$(now)] standing down: driver bench deadline reached"; exit 0
  fi
  if timeout -k 15 "$PROBE_TIMEOUT" python -c \
      "import jax; d=jax.devices(); assert d[0].platform in ('tpu','axon')" \
      2>"$probe_err"; then
    echo "[$(now)] probe OK (poll $i) - launching recovery suite"
    # WEEK_ONEHOT defaults to 0: the 8-hour week stage is opt-in (set
    # WEEK_ONEHOT=1, and set STOP_EPOCH so it cannot hold the chip
    # past the round driver's own bench window)
    if WEEK_ONEHOT="${WEEK_ONEHOT:-0}" DEADLINE="$STOP_EPOCH" \
        bash scripts/tpu_recovery.sh; then
      echo "[$(now)] recovery suite done"; exit 0
    fi
    echo "[$(now)] recovery suite incomplete; resuming polling"
  else
    echo "[$(now)] probe wedged/failed (poll $i)"
    # a wedge times out silently; an instant failure (broken env, import
    # error) leaves a traceback — surface it on the first and every 10th
    # poll so 200 polls of a non-tunnel problem aren't undiagnosable
    if [ -s "$probe_err" ] && [ $((i % 10)) -eq 1 ]; then
      sed 's/^/    probe stderr: /' "$probe_err" | grep -v WARNING | tail -3
    fi
  fi
  sleep "$POLL_S"
done
echo "[$(now)] watcher: gave up after $MAX_POLLS polls"
exit 1

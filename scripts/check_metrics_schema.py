"""Lint the obs/ metric registry: stable ids, unique names, declared units.

    python scripts/check_metrics_schema.py

The registry (`obs.metrics.METRIC_TABLE`) is the single source of truth
for every exporter and banked artifact — a rename, a reused id, or an
undeclared unit silently corrupts downstream dashboards and perf-gate
diffs.  This linter enforces the table's contract and runs as a tier-1
test (tests/test_obs.py::test_metrics_schema_lint):

* ids are unique AND contiguous 0..N-1 in table order (append-only: a
  hole or permutation means an entry was deleted or reordered, which
  re-keys every banked artifact);
* names are unique, Prometheus-legal (`[a-z_][a-z0-9_]*`), and carry the
  ``obs_`` namespace prefix;
* counters end in ``_total`` or a unit suffix (``_s``/``_j``) — the
  Prometheus naming convention scrapers alert on;
* units and label schemes come from the declared vocabularies;
* every label scheme renders: `label_values` yields exactly `size`
  tuples for a probe fleet shape, and the flat snapshot layout is gap-
  free (offsets partition [0, width)).

Exit 0 and a one-line summary when clean; exit 1 with one line per
violation otherwise.  ``--json PATH`` additionally writes a
``dcg.lint_report.v1`` report — the machine-readable shape all four
static checkers share (lint_graph / validate_chaos / validate_workload;
see docs/static_analysis.md).
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROM_NAME = re.compile(r"^[a-z_][a-z0-9_]*$")
COUNTER_SUFFIXES = ("_total", "_s", "_j")


def lint_table():
    """Returns a list of violation strings (empty when the table is clean)."""
    from distributed_cluster_gpus_tpu.obs.health import N_PROBES, PROBE_NAMES
    from distributed_cluster_gpus_tpu.obs.metrics import (
        KIND_NAMES, LABEL_SCHEMES, METRIC_TABLE, UNITS, build_registry,
        label_values, registry_width)

    errs = []
    ids = [s.mid for s in METRIC_TABLE]
    if ids != list(range(len(METRIC_TABLE))):
        errs.append(
            f"ids must be contiguous 0..{len(METRIC_TABLE) - 1} in table "
            f"order (append-only, never reuse/reorder); got {ids}")
    names = [s.name for s in METRIC_TABLE]
    for name in sorted({n for n in names if names.count(n) > 1}):
        errs.append(f"duplicate metric name {name!r}")
    for s in METRIC_TABLE:
        where = f"metric {s.mid} ({s.name})"
        if not PROM_NAME.match(s.name):
            errs.append(f"{where}: name is not Prometheus-legal")
        if not s.name.startswith("obs_"):
            errs.append(f"{where}: missing the obs_ namespace prefix")
        if s.kind not in ("counter", "gauge", "ema", "histogram"):
            errs.append(f"{where}: unknown kind {s.kind!r}")
        if s.kind == "counter" and not s.name.endswith(COUNTER_SUFFIXES):
            errs.append(
                f"{where}: counters must end in "
                f"{'/'.join(COUNTER_SUFFIXES)} (Prometheus convention)")
        if s.unit not in UNITS:
            errs.append(f"{where}: undeclared unit {s.unit!r} "
                        f"(UNITS: {', '.join(UNITS)})")
        if s.labels not in LABEL_SCHEMES:
            errs.append(f"{where}: unknown label scheme {s.labels!r}")
        if not s.help.strip():
            errs.append(f"{where}: empty help string")

    # exercise every scheme on a probe shape: sizes, offsets, and label
    # tuples must agree (the exporters slice the flat row by these)
    n_dc, n_bins, k = 4, 8, 4
    dc_names = [f"dc{i}" for i in range(n_dc)]
    assert len(PROBE_NAMES) == N_PROBES
    for faults_on in (False, True):
        for signals_on in (False, True):
            reg = build_registry(n_dc=n_dc, n_bins=n_bins, superstep_k=k,
                                 faults_on=faults_on, signals_on=signals_on)
            where = f"faults_on={faults_on}, signals_on={signals_on}"
            off = 0
            for e in reg:
                if e.offset != off:
                    errs.append(f"registry ({where}): gap before "
                                f"{e.spec.name} (offset {e.offset}, "
                                f"want {off})")
                off = e.offset + e.size
                labels = label_values(e, dc_names=dc_names, n_bins=n_bins,
                                      probe_names=PROBE_NAMES)
                if len(labels) != e.size:
                    errs.append(
                        f"metric {e.spec.mid} ({e.spec.name}): label "
                        f"scheme {e.spec.labels!r} yields {len(labels)} "
                        f"tuples for size {e.size}")
            if registry_width(reg) != off:
                errs.append(f"registry_width({where}) != last offset+size")
    assert KIND_NAMES  # the event-kind axis the by-kind counter labels

    # the HOST-side twin gauge table (obs_twin_*, exported by
    # write_twin_metrics) obeys the same naming/unit contract, has its
    # own contiguous id space, and must never collide with the in-graph
    # table's names
    from distributed_cluster_gpus_tpu.obs.metrics import TWIN_METRIC_TABLE

    tids = [s.mid for s in TWIN_METRIC_TABLE]
    if tids != list(range(len(TWIN_METRIC_TABLE))):
        errs.append(f"twin table ids must be contiguous "
                    f"0..{len(TWIN_METRIC_TABLE) - 1}; got {tids}")
    for s in TWIN_METRIC_TABLE:
        where = f"twin metric {s.mid} ({s.name})"
        if not PROM_NAME.match(s.name):
            errs.append(f"{where}: name is not Prometheus-legal")
        if not s.name.startswith("obs_twin_"):
            errs.append(f"{where}: missing the obs_twin_ namespace prefix")
        if s.kind not in ("counter", "gauge"):
            errs.append(f"{where}: twin gauges must be counter/gauge, "
                        f"got {s.kind!r}")
        if s.kind == "counter" and not s.name.endswith(COUNTER_SUFFIXES):
            errs.append(
                f"{where}: counters must end in "
                f"{'/'.join(COUNTER_SUFFIXES)} (Prometheus convention)")
        if s.unit not in UNITS:
            errs.append(f"{where}: undeclared unit {s.unit!r}")
        if s.labels != "none":
            errs.append(f"{where}: twin gauges are scalar (labels "
                        f"'none'), got {s.labels!r}")
        if not s.help.strip():
            errs.append(f"{where}: empty help string")
    twin_names = [s.name for s in TWIN_METRIC_TABLE]
    for name in sorted(set(twin_names) & set(names)):
        errs.append(f"twin metric name {name!r} collides with the "
                    "in-graph table")
    for name in sorted({n for n in twin_names if twin_names.count(n) > 1}):
        errs.append(f"duplicate twin metric name {name!r}")
    return errs


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", default=None,
                    help="write a dcg.lint_report.v1 report here (the "
                         "schema shared by lint_graph / validate_chaos / "
                         "validate_workload)")
    args = ap.parse_args(argv)

    errs = lint_table()
    if args.json:
        from distributed_cluster_gpus_tpu.analysis import report

        rep = report.make_report(
            "check_metrics_schema", ["obs.metrics.METRIC_TABLE"],
            [report.violation(e, rule="metrics-schema",
                              where="obs/metrics.py") for e in errs])
        report.write_report(rep, args.json)
    if errs:
        for e in errs:
            print(f"FAIL: {e}", file=sys.stderr)
        return 1
    from distributed_cluster_gpus_tpu.obs.metrics import METRIC_TABLE

    print(f"metric registry OK: {len(METRIC_TABLE)} metrics, "
          f"ids 0..{len(METRIC_TABLE) - 1}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

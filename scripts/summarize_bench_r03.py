"""Summarize the round-3 on-chip bench artifacts as a markdown table.

    python scripts/summarize_bench_r03.py

Reads every bench_results/*_r03.json the recovery suite banked and prints
(a) the headline table (config, events/s, platform) and (b) the sweep
grid if present — ready to paste into docs/perf_notes.md.  Files that are
missing, half-written, or CPU-fallback are listed separately so the
table never silently mixes platforms.
"""

import glob
import json
import os

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NORTH_STAR_PER_CHIP = 1e6 / 8.0


def main():
    rows, skipped = [], []
    for path in sorted(glob.glob(os.path.join(HERE, "bench_results",
                                              "*_r03.json"))):
        name = os.path.basename(path).replace("_r03.json", "")
        try:
            with open(path) as f:
                d = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            skipped.append((name, f"unreadable: {e!r}"))
            continue
        plat = d.get("platform")
        if plat not in ("tpu", "axon"):
            skipped.append((name, f"platform={plat}"))
            continue
        if "sweep" in d:
            print(f"\n### sweep ({name})\n")
            print("| rollouts | job_cap | events/s |")
            print("|---|---|---|")
            for r in d["sweep"]:
                print(f"| {r['rollouts']} | {r['job_cap']} | "
                      f"{r['events_per_sec']:,.0f} |")
            print()
        for r in d.get("configs_measured") or d.get("sweep") or [{
                **d.get("config", {}),
                "events_per_sec": d.get("value", 0.0)}]:
            rows.append((name, r.get("rollouts"), r.get("job_cap"),
                         r["events_per_sec"]))

    if rows:
        print("| stage | R | J | events/s | vs 125k/chip |")
        print("|---|---|---|---|---|")
        for name, rr, jj, v in rows:
            print(f"| {name} | {rr} | {jj} | {v:,.0f} | "
                  f"{v / NORTH_STAR_PER_CHIP:.2f}x |")
    else:
        print("no on-chip artifacts found")
    if skipped:
        print("\nnot included:")
        for name, why in skipped:
            print(f"- {name}: {why}")


if __name__ == "__main__":
    main()

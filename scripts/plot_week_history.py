"""Learner-trajectory figures for the canonical week run.

    python scripts/plot_week_history.py [--history runs/week_chsac/history.json]
                                        [--outdir eval_figures/week_chsac]

Renders critic loss, entropy temperature alpha, and the per-constraint
CMDP lambdas over training chunks — the long-horizon stability evidence
the round-2 verdict asked for (lambda dynamics, replay aging, f64 clock
under training).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

SURFACE = "#fcfcfb"
TEXT = "#0b0b0b"
TEXT2 = "#52514e"
GRID = "#e4e3df"
SERIES = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100", "#e87ba4"]


def _style(ax):
    ax.set_facecolor(SURFACE)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=TEXT2, labelsize=9)
    ax.yaxis.grid(True, color=GRID, linewidth=0.8)
    ax.set_axisbelow(True)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--history", default="runs/week_chsac/history.json")
    ap.add_argument("--outdir", default="eval_figures/week_chsac")
    a = ap.parse_args(argv)

    with open(a.history) as f:
        h = json.load(f)
    rows = h["chunks"]
    if not rows:
        raise SystemExit("history has no training chunks yet")
    os.makedirs(a.outdir, exist_ok=True)
    chunks = [r["chunk"] for r in rows]
    frac = 100.0 * h.get("t_reached", 0.0) / h.get("duration", 604800.0)

    def panel(key, ylabel, fname, log=False, series_names=None):
        fig, ax = plt.subplots(figsize=(5.8, 3.2), dpi=150)
        fig.patch.set_facecolor(SURFACE)
        _style(ax)
        vals = [r[key] for r in rows]
        if isinstance(vals[0], list):
            for i in range(len(vals[0])):
                label = (series_names[i] if series_names
                         and i < len(series_names) else f"[{i}]")
                ax.plot(chunks, [v[i] for v in vals], lw=1.6,
                        color=SERIES[i % len(SERIES)], label=label)
            ax.legend(frameon=False, fontsize=8, labelcolor=TEXT2)
        else:
            ax.plot(chunks, vals, lw=1.6, color=SERIES[0])
        if log:
            ax.set_yscale("log")
        ax.set_xlabel("training chunk (4,096 events each)",
                      color=TEXT2, fontsize=9)
        ax.set_ylabel(ylabel, color=TEXT2, fontsize=9)
        ax.set_title(f"week run · {h.get('critic_arch')} critic · "
                     f"{frac:.0f}% of 7 d — {ylabel}",
                     color=TEXT, fontsize=10, loc="left")
        fig.tight_layout()
        path = os.path.join(a.outdir, fname)
        fig.savefig(path, facecolor=SURFACE)
        plt.close(fig)
        print(path)

    from distributed_cluster_gpus_tpu.rl.cmdp import COST_NAMES

    panel("critic_loss", "critic quantile-Huber loss", "critic_loss.png",
          log=True)
    if "alpha" in rows[0]:
        panel("alpha", "entropy temperature alpha", "alpha.png")
    if "lambda" in rows[0]:
        panel("lambda", "CMDP lambda (PID)", "lambda.png",
              series_names=list(COST_NAMES))
    if "actor_loss" in rows[0]:
        panel("actor_loss", "actor loss", "actor_loss.png")


if __name__ == "__main__":
    main()

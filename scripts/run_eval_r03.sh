#!/bin/bash
# Round-3 eval campaign: multi-seed (3) comparisons per config, written
# incrementally so partial progress survives. CPU-forced (tunnel-proof).
set -u
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
S="--seeds 3 --seed0 123"
log() { echo "[eval-r03] $(date -u +%H:%M:%S) $*"; }

log config3;  python eval.py --config 3  $S --duration 3600 --json eval_results/c3.json
log config4;  python eval.py --config 4  $S --duration 3600 --rollouts 8 --json eval_results/c4.json
log config1;  python eval.py --config 1  $S --duration 3600 --json eval_results/c1.json
log config2;  python eval.py --config 2  $S --duration 3600 --json eval_results/c2.json
log config3c; python eval.py --config 3c $S --duration 3600 --json eval_results/c3c.json
log config3s; python eval.py --config 3s $S --duration 3600 --json eval_results/c3s.json
log config4s; python eval.py --config 4s $S --duration 1800 --rollouts 8 --json eval_results/c4s.json
log config5;  python eval.py --config 5 --json eval_results/c5.json
log done

#!/bin/bash
# Round-3 seed-extension campaign: bring every multi-seed eval config from
# 3 seeds (123-125) to 5 (adds 126-127), writing per-config artifacts that
# scripts/merge_eval.py unions into the round eval json.
# CPU-forced; safe to run while the TPU watcher polls.
set -u
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
S="--seeds 2 --seed0 126"
log() { echo "[seed-ext] $(date -u +%H:%M:%S) $*"; }

# serialize behind any already-running eval (one CPU core)
while pgrep -f "python eval.py" > /dev/null; do sleep 60; done

# a killed eval can leave a non-empty but truncated artifact (eval.py
# writes the final path directly); only a parseable artifact counts as done
complete() { [ -s "$1" ] && python -c "import json,sys; json.load(open(sys.argv[1]))" "$1" 2>/dev/null; }

for cfg_dur in "1 3600" "2 3600" "3 3600" "3c 3600" "3s 3600"; do
  set -- $cfg_dur
  out="eval_results/c${1}_s126.json"
  complete "$out" && { log "skip c$1 (exists)"; continue; }
  log "config $1"
  python eval.py --config "$1" $S --duration "$2" --json "$out" \
    || log "config $1 FAILED"
done
# chsac configs (heavier: distributed trainer, rollouts 8) — flags must
# match scripts/run_eval_r03.sh so the seed union aggregates like with like
if ! complete eval_results/c4_s126.json; then
  log "config 4"
  python eval.py --config 4 $S --duration 3600 --rollouts 8 \
    --json eval_results/c4_s126.json || log "config 4 FAILED"
fi
if ! complete eval_results/c4s_s126.json; then
  log "config 4s"
  python eval.py --config 4s $S --duration 1800 --rollouts 8 \
    --json eval_results/c4s_s126.json || log "config 4s FAILED"
fi
missing=0
for c in 1 2 3 3c 3s 4 4s; do
  complete "eval_results/c${c}_s126.json" || { log "c$c extension MISSING"; missing=1; }
done
log "merging"
python scripts/merge_eval.py
[ "$missing" -eq 0 ] && log done || { log "done WITH MISSING EXTENSIONS"; exit 1; }

"""Assemble eval_r04.json from the round-4 ring-campaign artifacts.

    python scripts/assemble_eval_r04.py [--dir eval_results] [--out eval_r04.json]

Unlike scripts/merge_eval.py (which unions SEEDS of a fixed algo list),
the round-4 campaign shards config 5 by ALGORITHM for resumability
(c5_ring_heur.json holds 3 heuristics x 5 seeds; c5_ring_<algo>_s<seed>.json
hold one RL row each), so this joins rows by (seed, algo), verifies every
contributing artifact carries the same run_shape stamp (same engine
layout/workload — the comparability guard), and recomputes the mean±sd
aggregate per algorithm with `merge_eval._aggregate` semantics.
Configs 1-3 (c{n}_r04.json) pass through unchanged.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from merge_eval import _aggregate  # noqa: E402

ALGO_ORDER = ["default_policy", "joint_nf", "eco_route", "chsac_af", "ppo"]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="eval_results")
    ap.add_argument("--out", default="eval_r04.json")
    a = ap.parse_args(argv)

    out = {}
    sources = []

    for n in (1, 2, 3):
        path = os.path.join(a.dir, f"c{n}_r04.json")
        if os.path.exists(path):
            with open(path) as f:
                out[f"config{n}"] = json.load(f)[f"config{n}"]
            sources.append(os.path.basename(path))

    # config 5: join rows by (seed, algo) across the sharded artifacts
    rows_by_seed = {}
    shape = None
    for path in sorted(glob.glob(os.path.join(a.dir, "c5_ring_*.json"))):
        try:
            with open(path) as f:
                entry = json.load(f).get("config5")
        except json.JSONDecodeError:
            print(f"skipping half-written {path}")
            continue
        if not entry:
            continue
        st = entry.get("run_shape")
        if shape is None:
            shape = st
        elif st != shape:
            raise SystemExit(
                f"{path}: run_shape {st} != campaign shape {shape} — "
                "rows are not comparable; re-run the stray artifact")
        for sd, rows in entry["per_seed"].items():
            bucket = rows_by_seed.setdefault(sd, {})
            for r in rows:
                if r["algo"] in bucket:
                    print(f"warning: duplicate ({sd}, {r['algo']}) from "
                          f"{path}; keeping first")
                    continue
                bucket[r["algo"]] = r
        sources.append(os.path.basename(path))

    if rows_by_seed:
        # only seeds with the FULL algo set enter the ranked aggregate;
        # partial seeds (campaign still running) are kept raw + listed
        algos = [al for al in ALGO_ORDER
                 if any(al in b for b in rows_by_seed.values())]
        complete = {sd: [b[al] for al in algos]
                    for sd, b in rows_by_seed.items()
                    if all(al in b for al in algos)}
        partial = sorted(sd for sd in rows_by_seed if sd not in complete)
        if partial:
            print(f"note: seeds {partial} lack some algorithms; excluded "
                  "from the aggregate, kept under per_seed_partial")
        out["config5"] = {
            "per_seed": complete,
            "aggregate": _aggregate(complete),
            "run_shape": shape,
        }
        if partial:
            out["config5"]["per_seed_partial"] = {
                sd: list(rows_by_seed[sd].values()) for sd in partial}

    out["_provenance"] = {
        "assembled_by": "scripts/assemble_eval_r04.py",
        "campaign": "scripts/run_eval_r04.sh",
        "engine_layout": "queue_mode=ring (drop-free overload semantics); "
                         "NOT seed-comparable with eval_r03.json's "
                         "slab-layout rows",
        "sources": sources,
    }
    tmp = a.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=2, default=float)
    os.replace(tmp, a.out)
    print(f"wrote {a.out}: {sorted(k for k in out if not k.startswith('_'))}")


if __name__ == "__main__":
    main()

"""Summarize the on-chip bench artifacts of a round as a markdown table.

    python scripts/summarize_bench.py [--round r04]

Reads every bench_results/*_<round>.json the recovery suite banked and prints
(a) the headline table (config, events/s, platform) and (b) the sweep
grid if present — ready to paste into docs/perf_notes.md.  Files that are
missing, half-written, or CPU-fallback are listed separately so the
table never silently mixes platforms.
"""

import argparse
import glob
import json
import os

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NORTH_STAR_PER_CHIP = 1e6 / 8.0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", default="r04", help="artifact suffix (r03, r04, ...)")
    a = ap.parse_args(argv)
    suffix = f"_{a.round}.json"

    rows, skipped = [], []
    for path in sorted(glob.glob(os.path.join(HERE, "bench_results",
                                              f"*{suffix}"))):
        name = os.path.basename(path).replace(suffix, "")
        try:
            with open(path) as f:
                d = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            skipped.append((name, f"unreadable: {e!r}"))
            continue
        plat = d.get("platform")
        ss = d.get("superstep_sweep")
        if ss:
            # the engine-coalescing sweep is meaningful on any platform
            # (it is banked by CPU-fallback rounds too) — label it rather
            # than dropping it with the platform filter below
            shape = ss.get("shape", {})
            print(f"\n### superstep K sweep ({name} on {plat}: "
                  f"{ss.get('algo')} R={shape.get('rollouts')} "
                  f"J={shape.get('job_cap')})\n")
            # round-7 columns (realized-vs-structural) print when banked;
            # older artifacts (r05/r06) lack them and keep the short table
            has_ratio = any("realized_vs_structural" in r
                            for r in ss.get("rows", []))
            hdr = "| K | events/s | events/iter | step eqns | eqns/event |"
            sep = "|---|---|---|---|---|"
            if has_ratio:
                hdr += " realized x | structural x | realized/structural |"
                sep += "---|---|---|"
            print(hdr)
            print(sep)
            for r in ss.get("rows", []):
                line = (f"| {r.get('superstep_k')} "
                        f"| {r.get('events_per_sec', 0):,.0f} "
                        f"| {r.get('events_per_iteration')} "
                        f"| {r.get('step_body_eqns')} "
                        f"| {r.get('eqns_per_event')} |")
                if has_ratio:
                    line += (f" {r.get('realized_speedup', '')} "
                             f"| {r.get('structural_speedup', '')} "
                             f"| {r.get('realized_vs_structural', '')} |")
                print(line)
            print()
        fp = d.get("fastpath_ab")
        if fp:
            shape = fp.get("shape", {})
            print(f"\n### fast-path eligibility A/B ({name} on {plat}: "
                  f"R={shape.get('rollouts')} J={shape.get('job_cap')} "
                  f"reps={shape.get('reps')}, interleaved medians)\n")
            print("| config | mode | K | legacy ev/s | fast ev/s "
                  "| speedup | legacy eqns | fast eqns |")
            print("|---|---|---|---|---|---|---|---|")
            for r in fp.get("rows", []):
                print(f"| {r.get('config')} | {r.get('mode')} "
                      f"| {r.get('k')} "
                      f"| {r.get('legacy_ev_s', 0):,.0f} "
                      f"| {r.get('fast_ev_s', 0):,.0f} "
                      f"| {r.get('speedup')}x "
                      f"| {r.get('legacy_eqns')} "
                      f"| {r.get('fast_eqns')} |")
            print()
        ob = d.get("obs_overhead")
        if ob:
            shape = ob.get("shape", {})
            print(f"\n### obs telemetry overhead ({name} on {plat}: "
                  f"{ob.get('algo')} K={shape.get('superstep_k')} "
                  f"R={shape.get('rollouts')} J={shape.get('job_cap')})\n")
            print("| obs | events/s | step eqns | overhead |")
            print("|---|---|---|---|")
            print(f"| off | {ob.get('events_per_sec_obs_off', 0):,.0f} "
                  f"| {ob.get('step_body_eqns_obs_off')} | — |")
            print(f"| on | {ob.get('events_per_sec_obs_on', 0):,.0f} "
                  f"| {ob.get('step_body_eqns_obs_on')} "
                  f"| {ob.get('overhead_fraction', 0) * 100:.1f}% |")
            print()
        wp = d.get("workload_probe")
        if wp:
            shape = wp.get("shape", {})
            print(f"\n### trace-replay workload probe ({name} on {plat}: "
                  f"{wp.get('preset')} {wp.get('algo')} "
                  f"R={shape.get('rollouts')} J={shape.get('job_cap')})\n")
            print("| events/s | step eqns | while in body | accrued USD |")
            print("|---|---|---|---|")
            print(f"| {wp.get('events_per_sec', 0):,.0f} "
                  f"| {wp.get('step_body_eqns')} "
                  f"| {wp.get('step_body_while')} "
                  f"| {wp.get('accrued_cost_usd')} |")
            print()
        lr = d.get("lint_report")
        if lr:
            # dcg-lint structural-invariant matrix (round 13): lint
            # status rides the same reporting path as every other banked
            # evidence artifact
            n_err = sum(1 for v in lr.get("violations", [])
                        if v.get("severity") == "error")
            print(f"\n### dcg-lint ({name} on {plat}: "
                  f"{len(lr.get('checked', []))} configs, "
                  f"{'clean' if lr.get('ok') else f'{n_err} error(s)'}, "
                  f"{len(lr.get('allowlisted', []))} allowlisted)\n")
            print("| config | eqns | superstep | planner | status |")
            print("|---|---|---|---|---|")
            for cname, row in (lr.get("matrix") or {}).items():
                print(f"| {cname} | {row.get('eqns')} "
                      f"| {'on' if row.get('superstep_on') else '—'} "
                      f"| {'on' if row.get('planner_on') else 'off'} "
                      f"| {'ok' if row.get('ok') else 'FAIL'} |")
            for v in lr.get("violations", []):
                print(f"- FAIL [{v.get('rule')}] {v.get('config')}: "
                      f"{v.get('message')}")
            print()
        ov = d.get("io_overlap")
        if ov:
            compute = ov.get("compute_s", ov.get("rollout_s"))
            print(f"\n### pipelined io overlap ({name} on {plat})\n")
            print("| wall s | compute s | io s (critical path) "
                  "| io render s (hidden) | overlap |")
            print("|---|---|---|---|---|")
            print(f"| {ov.get('wall_s')} | {compute} "
                  f"| {ov.get('io_s')} | {ov.get('io_render_s')} "
                  f"| {ov.get('overlap_fraction', 0) * 100:.0f}% |")
            print()
        if plat not in ("tpu", "axon"):
            skipped.append((name, f"platform={plat}"))
            continue
        if "sweep" in d:
            # the full grid prints as its own table; the stage's single
            # headline measurement (best of sweep, d["value"]) still joins
            # the headline table below
            if d.get("value") is not None:
                rows.append((name, d.get("config", {}).get("rollouts"),
                             d.get("config", {}).get("job_cap"),
                             d["value"]))
            print(f"\n### sweep ({name})\n")
            print("| rollouts | job_cap | events/s |")
            print("|---|---|---|")
            for r in d["sweep"]:
                v = r.get("events_per_sec")
                if v is None:
                    skipped.append((name, f"sweep row missing events_per_sec: {r}"))
                    continue
                print(f"| {r.get('rollouts')} | {r.get('job_cap')} | {v:,.0f} |")
            print()
            continue
        for r in d.get("configs_measured") or [{
                **d.get("config", {}),
                "events_per_sec": d.get("value")}]:
            v = r.get("events_per_sec")
            if v is None:
                skipped.append((name, f"row missing events_per_sec: {r}"))
                continue
            rows.append((name, r.get("rollouts"), r.get("job_cap"), v))

    if rows:
        print("| stage | R | J | events/s | vs 125k/chip |")
        print("|---|---|---|---|---|")
        for name, rr, jj, v in rows:
            print(f"| {name} | {rr} | {jj} | {v:,.0f} | "
                  f"{v / NORTH_STAR_PER_CHIP:.2f}x |")
    else:
        print("no on-chip artifacts found")
    if skipped:
        print("\nnot included:")
        for name, why in skipped:
            print(f"- {name}: {why}")


if __name__ == "__main__":
    main()

"""Summarize the banked bench artifacts of a round as markdown tables.

    python scripts/summarize_bench.py [--round r04]
    python scripts/summarize_bench.py --trend

Reads every bench_results/*_<round>.json the recovery suite banked and
prints (a) the headline table (config, events/s, platform) and (b) every
probe section present — superstep sweep (with the window-fill column),
fast-path A/B, obs overhead, workload probe, dcg-lint matrix, io
overlap, step-time attribution — ready to paste into docs/perf_notes.md.
Files that are missing, half-written, or CPU-fallback are listed in one
summary section so the table never silently mixes platforms.

``--trend`` renders the cross-round ev/s trend tables from the perf
ledger instead (``bench_results/ledger.jsonl``; built on the fly from
the banked rounds when absent).  File loading and round discovery share
`analysis.ledger` with bench.py's prior-evidence scan and
scripts/perf_ledger.py — ONE loader, one discovery rule, corrupt files
degrade to a reason line, never a traceback.
"""

import argparse
import glob
import os
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

from distributed_cluster_gpus_tpu.analysis import ledger  # noqa: E402

NORTH_STAR_PER_CHIP = 1e6 / 8.0


def _superstep_section(name, plat, ss):
    shape = ss.get("shape", {})
    print(f"\n### superstep K sweep ({name} on {plat}: "
          f"{ss.get('algo')} R={shape.get('rollouts')} "
          f"J={shape.get('job_cap')})\n")
    # round-7 columns (realized-vs-structural) print when banked;
    # older artifacts (r05/r06) lack them and keep the short table.
    # `fill` (mean applied-prefix length / K) is first-class since
    # round 14 — older rows derive it from events_per_iteration.
    rows = ss.get("rows", [])
    has_ratio = any("realized_vs_structural" in r for r in rows)
    hdr = "| K | events/s | events/iter | fill | step eqns | eqns/event |"
    sep = "|---|---|---|---|---|---|"
    if has_ratio:
        hdr += " realized x | structural x | realized/structural |"
        sep += "---|---|---|"
    print(hdr)
    print(sep)
    for r in rows:
        k = r.get("superstep_k")
        fill = r.get("fill")
        if fill is None and r.get("events_per_iteration") is not None \
                and k:
            fill = round(r["events_per_iteration"] / k, 4)
        line = (f"| {k} "
                f"| {r.get('events_per_sec', 0):,.0f} "
                f"| {r.get('events_per_iteration')} "
                f"| {fill if fill is not None else '—'} "
                f"| {r.get('step_body_eqns')} "
                f"| {r.get('eqns_per_event')} |")
        if has_ratio:
            line += (f" {r.get('realized_speedup', '')} "
                     f"| {r.get('structural_speedup', '')} "
                     f"| {r.get('realized_vs_structural', '')} |")
        print(line)
    print()


def _attrib_section(name, plat, reports):
    from distributed_cluster_gpus_tpu.analysis import attrib

    for rep in reports if isinstance(reports, list) else [reports]:
        print(f"\n<!-- step-time attribution ({name} on {plat}) -->")
        print(attrib.format_report(rep))
        print()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", default="r04",
                    help="artifact suffix (r03, r04, ...)")
    ap.add_argument("--trend", action="store_true",
                    help="print the cross-round ev/s trend from the "
                         "perf ledger instead of one round's sections")
    a = ap.parse_args(argv)

    if a.trend:
        path = ledger.ledger_path(HERE)
        records = ledger.read_ledger(path)
        skipped = []
        if not records:
            records, skipped = ledger.build_records(HERE)
        print("\n".join(ledger.format_trend(records)))
        if skipped:
            print("not included: "
                  + "; ".join(f"{rel}: {why}" for rel, why in skipped))
        return

    suffix = f"_{a.round}.json"
    rows, skipped = [], []
    for path in sorted(glob.glob(os.path.join(HERE, "bench_results",
                                              f"*{suffix}"))):
        name = os.path.basename(path).replace(suffix, "")
        rel = os.path.join("bench_results", os.path.basename(path))
        d, reason = ledger.load_banked(HERE, rel)
        if d is None:
            skipped.append((name, reason))
            continue
        plat = d.get("platform")
        ss = d.get("superstep_sweep")
        if ss:
            # the engine-coalescing sweep is meaningful on any platform
            # (it is banked by CPU-fallback rounds too) — label it rather
            # than dropping it with the platform filter below
            _superstep_section(name, plat, ss)
        fp = d.get("fastpath_ab")
        if fp:
            shape = fp.get("shape", {})
            print(f"\n### fast-path eligibility A/B ({name} on {plat}: "
                  f"R={shape.get('rollouts')} J={shape.get('job_cap')} "
                  f"reps={shape.get('reps')}, interleaved medians)\n")
            print("| config | mode | K | legacy ev/s | fast ev/s "
                  "| speedup | legacy eqns | fast eqns |")
            print("|---|---|---|---|---|---|---|---|")
            for r in fp.get("rows", []):
                print(f"| {r.get('config')} | {r.get('mode')} "
                      f"| {r.get('k')} "
                      f"| {r.get('legacy_ev_s', 0):,.0f} "
                      f"| {r.get('fast_ev_s', 0):,.0f} "
                      f"| {r.get('speedup')}x "
                      f"| {r.get('legacy_eqns')} "
                      f"| {r.get('fast_eqns')} |")
            print()
        sg = d.get("sweep_grid_probe")
        if sg:
            ax = sg.get("axes", {})
            print(f"\n### sweep grid vs serial A/B ({name} on {plat}: "
                  f"{sg.get('fleet')} fleet, {sg.get('n_cells')} cells "
                  f"in {sg.get('n_buckets')} buckets, "
                  f"{len(ax.get('rates', []))} rates x "
                  f"{len(ax.get('algos', []))} algos x "
                  f"{len(ax.get('seeds', []))} seeds, "
                  f"reps={sg.get('reps')}, interleaved medians)\n")
            print("| arm | wall s | cells/s | aggregate ev/s |")
            print("|---|---|---|---|")
            for arm in ("serial", "grid"):
                print(f"| {arm} | {sg.get(f'{arm}_wall_s', 0):.2f} "
                      f"| {sg.get(f'{arm}_cells_s', 0):.2f} "
                      f"| {sg.get(f'{arm}_ev_s', 0):,.0f} |")
            print(f"\ngrid speedup {sg.get('speedup_cells')}x on cells/s "
                  f"(rows bit-identical: "
                  f"{sg.get('rows_bit_identical')})\n")
        tl = d.get("twin_latency")
        if tl:
            print(f"\n### twin fork+forecast SLO ({name} on {plat}: "
                  f"{tl.get('fleet')} fleet, {tl.get('n_lanes')} lanes "
                  f"in {tl.get('n_buckets')} buckets, "
                  f"{'/'.join(tl.get('policies', []))} x "
                  f"{'/'.join(tl.get('overlays', []))}, "
                  f"h={tl.get('horizon_s')}s off t0={tl.get('t0_s')}s, "
                  f"reps={tl.get('reps')})\n")
            print("| p50 s | p95 s | forecast events | forecast ev/s |")
            print("|---|---|---|---|")
            print(f"| {tl.get('p50_s', 0):.3f} "
                  f"| {tl.get('p95_s', 0):.3f} "
                  f"| {tl.get('events_forecast', 0):,} "
                  f"| {tl.get('ev_s', 0):,.0f} |")
            print()
        ob = d.get("obs_overhead")
        if ob:
            shape = ob.get("shape", {})
            print(f"\n### obs telemetry overhead ({name} on {plat}: "
                  f"{ob.get('algo')} K={shape.get('superstep_k')} "
                  f"R={shape.get('rollouts')} J={shape.get('job_cap')})\n")
            print("| obs | events/s | step eqns | overhead |")
            print("|---|---|---|---|")
            print(f"| off | {ob.get('events_per_sec_obs_off', 0):,.0f} "
                  f"| {ob.get('step_body_eqns_obs_off')} | — |")
            print(f"| on | {ob.get('events_per_sec_obs_on', 0):,.0f} "
                  f"| {ob.get('step_body_eqns_obs_on')} "
                  f"| {ob.get('overhead_fraction', 0) * 100:.1f}% |")
            print()
        wp = d.get("workload_probe")
        if wp:
            shape = wp.get("shape", {})
            print(f"\n### trace-replay workload probe ({name} on {plat}: "
                  f"{wp.get('preset')} {wp.get('algo')} "
                  f"R={shape.get('rollouts')} J={shape.get('job_cap')})\n")
            print("| events/s | step eqns | while in body | accrued USD |")
            print("|---|---|---|---|")
            print(f"| {wp.get('events_per_sec', 0):,.0f} "
                  f"| {wp.get('step_body_eqns')} "
                  f"| {wp.get('step_body_while')} "
                  f"| {wp.get('accrued_cost_usd')} |")
            print()
        lr = d.get("lint_report")
        if lr:
            # dcg-lint structural-invariant matrix (round 13): lint
            # status rides the same reporting path as every other banked
            # evidence artifact
            n_err = sum(1 for v in lr.get("violations", [])
                        if v.get("severity") == "error")
            print(f"\n### dcg-lint ({name} on {plat}: "
                  f"{len(lr.get('checked', []))} configs, "
                  f"{'clean' if lr.get('ok') else f'{n_err} error(s)'}, "
                  f"{len(lr.get('allowlisted', []))} allowlisted)\n")
            print("| config | eqns | superstep | planner | status |")
            print("|---|---|---|---|---|")
            for cname, row in (lr.get("matrix") or {}).items():
                print(f"| {cname} | {row.get('eqns')} "
                      f"| {'on' if row.get('superstep_on') else '—'} "
                      f"| {'on' if row.get('planner_on') else 'off'} "
                      f"| {'ok' if row.get('ok') else 'FAIL'} |")
            for v in lr.get("violations", []):
                print(f"- FAIL [{v.get('rule')}] {v.get('config')}: "
                      f"{v.get('message')}")
            print()
        pa = d.get("phase_attrib")
        if pa:
            _attrib_section(name, plat, pa)
        ov = d.get("io_overlap")
        if ov:
            compute = ov.get("compute_s", ov.get("rollout_s"))
            print(f"\n### pipelined io overlap ({name} on {plat})\n")
            print("| wall s | compute s | io s (critical path) "
                  "| io render s (hidden) | overlap |")
            print("|---|---|---|---|---|")
            print(f"| {ov.get('wall_s')} | {compute} "
                  f"| {ov.get('io_s')} | {ov.get('io_render_s')} "
                  f"| {ov.get('overlap_fraction', 0) * 100:.0f}% |")
            print()
        if plat not in ("tpu", "axon"):
            skipped.append((name, f"platform={plat}"))
            continue
        if "sweep" in d:
            # the full grid prints as its own table; the stage's single
            # headline measurement (best of sweep, d["value"]) still joins
            # the headline table below
            if d.get("value") is not None:
                rows.append((name, d.get("config", {}).get("rollouts"),
                             d.get("config", {}).get("job_cap"),
                             d["value"]))
            print(f"\n### sweep ({name})\n")
            print("| rollouts | job_cap | events/s |")
            print("|---|---|---|")
            for r in d["sweep"]:
                v = r.get("events_per_sec")
                if v is None:
                    skipped.append((name, f"sweep row missing events_per_sec: {r}"))
                    continue
                print(f"| {r.get('rollouts')} | {r.get('job_cap')} | {v:,.0f} |")
            print()
            continue
        for r in d.get("configs_measured") or [{
                **d.get("config", {}),
                "events_per_sec": d.get("value")}]:
            v = r.get("events_per_sec")
            if v is None:
                skipped.append((name, f"row missing events_per_sec: {r}"))
                continue
            rows.append((name, r.get("rollouts"), r.get("job_cap"), v))

    if rows:
        print("| stage | R | J | events/s | vs 125k/chip |")
        print("|---|---|---|---|---|")
        for name, rr, jj, v in rows:
            print(f"| {name} | {rr} | {jj} | {v:,.0f} | "
                  f"{v / NORTH_STAR_PER_CHIP:.2f}x |")
    else:
        print("no on-chip artifacts found")
    if skipped:
        print("\nnot included:")
        for name, why in skipped:
            print(f"- {name}: {why}")


if __name__ == "__main__":
    main()

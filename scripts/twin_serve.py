"""Resident digital-twin serving loop (docs/twin.md).

    python scripts/twin_serve.py --base BASE.json --store STORE_DIR
        [--fleet duo|paper|single_dc] [--segments DIR] [--requests DIR]
        [--stdin] [--out OUT_DIR] [--algo default_policy]
        [--duration 7200] [--chunk-steps 1024] [--ckpt-every 1]
        [--seed 0] [--poll-s 0.2] [--max-idle-s S] [--exit-when-done]

One process, three duties, one loop:

* **ingest** — tail ``--segments`` for ``*.json`` trace segments
  (lexicographic order == append order; a file named ``CLOSE`` closes
  the trace), validate + append each through `twin.ingest.TraceCursor`
  (a FAILing segment is reported and skipped — the twin never ingests
  corruption), then advance the warm state to the data frontier,
  checkpointing at chunk cadence through the verified store;
* **serve** — answer queries: ``*.json`` request files in ``--requests``
  (reply written next to each as ``<name>.reply.json``) and/or JSON
  lines on stdin with ``--stdin`` (reply lines on stdout).  Protocol:
  `twin.service.TwinService` (ops ``forecast`` / ``status`` / ``rca``);
* **observe** — rewrite the twin gauges through ``obs/export.py``
  (``metrics.prom`` + ``metrics.jsonl`` in ``--out``) once per loop.

Graceful SIGTERM/SIGINT (`utils.shutdown.graceful_shutdown`): the flag
is polled at the loop boundary; on shutdown the twin commits a final
verified checkpoint and writes ``run_summary.json`` with
``status="interrupted"`` (``completed`` when the trace closed and the
twin drained), then exits ``128 + signum``.  A SIGKILLed twin restarts
from the last verified step and replays the trace tail to
byte-identical state (tests/test_twin.py).
"""

import argparse
import json
import os
import select
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CLOSE_FILE = "CLOSE"


def build_fleet(name: str):
    from distributed_cluster_gpus_tpu.configs import (
        build_duo_fleet, build_fleet, build_single_dc_fleet)

    return {"paper": build_fleet, "single_dc": build_single_dc_fleet,
            "duo": build_duo_fleet}[name]()


def _poll_segments(twin, seg_dir, seen):
    """Append unseen segment files in name order; returns #appended."""
    if seg_dir is None or not os.path.isdir(seg_dir):
        return 0
    appended = 0
    for name in sorted(os.listdir(seg_dir)):
        path = os.path.join(seg_dir, name)
        if name in seen or not os.path.isfile(path):
            continue
        if name == CLOSE_FILE:
            seen.add(name)
            twin.cursor.close()
            print(f"[twin] trace closed by {path}", flush=True)
            continue
        if not name.endswith(".json"):
            seen.add(name)
            continue
        seen.add(name)
        fails = twin.cursor.append_file(path)
        if fails:
            for f in fails:
                print(f"FAIL: {f}", file=sys.stderr, flush=True)
        else:
            appended += 1
            print(f"[twin] ingested {name} "
                  f"(watermark t={twin.cursor.watermark_t():g})",
                  flush=True)
    return appended


def _poll_requests(service, req_dir, seen):
    """Answer unseen request files; returns #served."""
    if req_dir is None or not os.path.isdir(req_dir):
        return 0
    from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

    served = 0
    for name in sorted(os.listdir(req_dir)):
        if (name in seen or not name.endswith(".json")
                or name.endswith(".reply.json")):
            continue
        seen.add(name)
        path = os.path.join(req_dir, name)
        try:
            with open(path) as f:
                req = json.load(f)
        except (OSError, ValueError) as e:
            reply = {"ok": False, "error": f"unreadable request: {e}"}
        else:
            reply = service.handle(req)
        dump_json_atomic(path[:-len(".json")] + ".reply.json", reply)
        served += 1
    return served


def _poll_stdin(service, timeout_s):
    """One JSON line -> one reply line; returns (#served, eof)."""
    try:
        ready, _, _ = select.select([sys.stdin], [], [], timeout_s)
    except (OSError, ValueError):
        return 0, True
    if not ready:
        return 0, False
    line = sys.stdin.readline()
    if not line:
        return 0, True
    line = line.strip()
    if not line:
        return 0, False
    try:
        req = json.loads(line)
    except ValueError as e:
        reply = {"ok": False, "error": f"bad request line: {e}"}
    else:
        reply = service.handle(req)
    from distributed_cluster_gpus_tpu.utils.jsonio import clean_nan

    print(json.dumps(clean_nan(reply), sort_keys=True, default=float),
          flush=True)
    return 1, False


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--base", required=True,
                    help="base workload spec JSON (segment 1: stream "
                         "kinds + signals; docs/workloads.md schema)")
    ap.add_argument("--store", required=True,
                    help="verified checkpoint store root (created if "
                         "missing; an existing store resumes the twin)")
    ap.add_argument("--fleet", default="duo",
                    choices=["duo", "paper", "single_dc"])
    ap.add_argument("--segments", default=None,
                    help="directory tailed for appended *.json trace "
                         "segments (a file named CLOSE closes the trace)")
    ap.add_argument("--requests", default=None,
                    help="directory tailed for *.json query files")
    ap.add_argument("--stdin", action="store_true",
                    help="serve JSON-line queries from stdin")
    ap.add_argument("--out", default=None,
                    help="observability dir (metrics.prom/jsonl + "
                         "run_summary.json); default: the store root")
    ap.add_argument("--algo", default="default_policy")
    ap.add_argument("--duration", type=float, default=7200.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chunk-steps", type=int, default=1024)
    ap.add_argument("--ckpt-every", type=int, default=1)
    ap.add_argument("--poll-s", type=float, default=0.2)
    ap.add_argument("--max-idle-s", type=float, default=None,
                    help="exit cleanly after this long with no ingest "
                         "and no queries (CI/test knob)")
    ap.add_argument("--exit-when-done", action="store_true",
                    help="exit once the trace is closed and the twin "
                         "has drained it")
    args = ap.parse_args(argv)

    import jax  # noqa: F401  (platform init before engine imports)

    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.obs.export import (
        write_status_summary, write_twin_metrics)
    from distributed_cluster_gpus_tpu.twin import (TraceCursor, Twin,
                                                   TwinService)
    from distributed_cluster_gpus_tpu.utils.shutdown import \
        graceful_shutdown

    fleet = build_fleet(args.fleet)
    cursor = TraceCursor.from_file(args.base, fleet)
    params = SimParams(algo=args.algo, duration=args.duration,
                       seed=args.seed)
    twin = Twin(fleet, params, cursor, store=args.store,
                chunk_steps=args.chunk_steps, ckpt_every=args.ckpt_every)
    service = TwinService(twin)
    out_dir = args.out or twin.store
    os.makedirs(out_dir, exist_ok=True)
    seen_segments, seen_requests = set(), set()
    stdin_eof = not args.stdin
    last_activity = time.time()
    print(f"[twin] serving: fleet={args.fleet} algo={args.algo} "
          f"store={twin.store} chunk={twin.chunk}", flush=True)

    with graceful_shutdown() as stop:
        while not stop:
            n_seg = _poll_segments(twin, args.segments, seen_segments)
            # bounded per iteration: the shutdown flag and the query
            # queue are polled between bursts even during a long catch-up
            adv = twin.advance(max_chunks=32)
            n_req = _poll_requests(service, args.requests, seen_requests)
            if not stdin_eof:
                n_line, stdin_eof = _poll_stdin(service, args.poll_s)
                n_req += n_line
            write_twin_metrics(out_dir, service.gauges())
            if n_seg or n_req or adv["chunks"]:
                last_activity = time.time()
            if args.exit_when_done and twin.cursor.closed and twin.done:
                break
            if (args.max_idle_s is not None
                    and time.time() - last_activity > args.max_idle_s):
                break
            if stdin_eof:
                time.sleep(args.poll_s)

    # final verified checkpoint + machine-readable status, even on
    # SIGTERM — a resumed twin picks up exactly here
    if twin.store is not None:
        twin.checkpoint()
    write_twin_metrics(out_dir, service.gauges())
    status = "interrupted" if stop else "completed"
    write_status_summary(out_dir, algo=twin.params.algo, fleet=fleet,
                         state=twin.state, status=status)
    print(f"[twin] shutdown: status={status} chunk={twin.chunk} "
          f"forks_served={service.forks_served}", flush=True)
    return stop.exit_code


if __name__ == "__main__":
    sys.exit(main())

"""Canonical 7-day CHSAC-AF run (reference `run.sh:21-24` configuration).

604,800 simulated seconds, inference off, training arrivals Poisson
0.02/s per ingress, log every 20 s, float64 clock, full checkpointing.
Streams the reference CSVs to ``runs/week_chsac/`` and flushes the
learner-metric history (critic loss, entropy alpha, CMDP lambdas, ...)
to ``runs/week_chsac/history.json`` — atomically, every 10 chunks, with
rows tagged by chunk index so a killed run keeps its evidence and a
resumed run merges instead of clobbering (re-run chunks replace their
old rows; the checkpoint itself does not store history).

Critic choice: the reference-shaped one-hot-action critic costs ~0.7 s
per SAC update on this 1-core CPU (~95k updates for the week: ~18 h), so
the CPU run uses ``--critic-arch heads`` (exact marginalization from
joint-action output heads, ~5x cheaper here, ~14x in FLOPs) — a
documented non-reference function class.  On a TPU window run with
DCG_WEEK_CRITIC=onehot for the reference-shaped critic (sub-ms updates
on the MXU).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the axon TPU plugin force-selects itself via jax.config at sitecustomize
# time, overriding the env var — honor an EXACT cpu request (a fallback
# list like "tpu,cpu" must not force CPU)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

FLUSH_EVERY = 10


def main():
    import numpy as np

    import run_sim
    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.rl.train import train_chsac

    out_dir = os.environ.get("DCG_WEEK_OUT", "runs/week_chsac")
    critic = os.environ.get("DCG_WEEK_CRITIC", "heads")
    duration = float(os.environ.get("DCG_WEEK_DURATION", 604800.0))
    seed = os.environ.get("DCG_WEEK_SEED", "123")

    a = run_sim.parse_args([
        "--algo", "chsac_af", "--duration", str(duration),
        "--log-interval", "20", "--seed", seed,
        "--inf-mode", "off", "--trn-mode", "poisson", "--trn-rate", "0.02",
        "--critic-arch", critic, "--out", out_dir,
        "--ckpt-dir", os.path.join(out_dir, "ckpt"),
        # DCG_WEEK_JOB_CAP: the default 512 slab binds when the learned
        # placements hold >512 jobs in flight (seed 124 dropped 17% there);
        # 2048 is the concurrency bound the config-4 eval spec uses
        "--job-cap", os.environ.get("DCG_WEEK_JOB_CAP", "512"),
    ])
    fleet = build_fleet()
    # resolve --queue-cap 0 (auto): drop-free rings for the week backlog
    params = run_sim.finalize_queue_cap(run_sim.build_params(a), fleet)
    os.makedirs(out_dir, exist_ok=True)
    hist_path = os.path.join(out_dir, "history.json")

    # prior evidence from a killed/resumed run; rows this run recomputes
    # (chunk >= the first chunk we see) replace their old versions
    prior_rows = []
    if os.path.exists(hist_path):
        try:
            with open(hist_path) as f:
                prior_rows = json.load(f).get("chunks", [])
        except (json.JSONDecodeError, OSError):
            prior_rows = []  # half-written pre-atomic file; start fresh

    run_rows = []
    seen = {"n_hist": 0, "first_chunk": None, "last_flush": -1}

    def to_jsonable(v):
        arr = np.asarray(v)
        return arr.tolist() if arr.ndim else float(arr)

    def flush(t_now):
        first = seen["first_chunk"]
        kept = [r for r in prior_rows
                if first is None or r.get("chunk", -1) < first]
        payload = {"critic_arch": critic, "duration": duration,
                   "t_reached": t_now, "chunks": kept + run_rows}
        tmp = hist_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, hist_path)
        except OSError as e:
            # the metrics side-channel must never kill the multi-day run;
            # the checkpoint is the durable state, this is evidence only
            print(f"[week] WARNING: history flush failed ({e}); continuing")

    def on_chunk(chunk, state, history):
        if seen["first_chunk"] is None:
            seen["first_chunk"] = chunk
        for h in history[seen["n_hist"]:]:
            run_rows.append({"chunk": chunk,
                             **{k: to_jsonable(v) for k, v in h.items()}})
        grew = len(history) > seen["n_hist"]
        seen["n_hist"] = len(history)
        if grew and chunk - seen["last_flush"] >= FLUSH_EVERY:
            seen["last_flush"] = chunk
            flush(float(np.asarray(state.t)))

    state, agent, history = train_chsac(
        fleet, params, out_dir=out_dir, chunk_steps=4096,
        # honor the reference schedule (one update per transition): a
        # 4096-step chunk of this workload finishes ~1.2k jobs, so the
        # default 256-updates/chunk cap would silently train 4x less
        max_train_steps_per_chunk=2048,
        verbose=True, ckpt_dir=a.ckpt_dir, ckpt_every_chunks=10,
        resume=True, on_chunk=on_chunk)
    flush(float(np.asarray(state.t)))
    n_fin = np.asarray(state.n_finished)
    print(f"week run: t={float(state.t):.0f}s  finished={int(n_fin.sum())} "
          f"dropped={int(state.n_dropped)}  sac_steps={int(agent.sac.step)}")


if __name__ == "__main__":
    main()

"""Offline checkpoint-store verifier (docs/checkpointing.md).

    python scripts/fsck_ckpt.py CKPT_DIR [CKPT_DIR2 ...] [--fast] [--gc]
        [--keep N]

Walks each store in the scripts/validate_chaos.py style — one PASS/FAIL
line per finding, exit 0 only when every committed checkpoint verifies
and no crash debris is stranded:

* every ``step_*`` directory must carry a committed manifest whose
  per-file sha256 digests match the payload (``--fast`` skips the
  content re-hash: structure/commit checks only);
* stranded staging dirs (``step_*_tmp``, orbax tmp dirs) are crash
  debris — reported as FAIL (``--gc`` sweeps them via
  ``gc_checkpoints`` and reports what was removed);
* lenient-parse step names (``step_5``, ``step_5_tmp``-style) that the
  strict ``step_<10 digits>`` rule rejects are reported — they were a
  real resume hazard before round 12;
* a ``aborted/`` forensic bundle inside the store is fsck'd as its own
  store (one level), including its ``abort_context.json`` parse;
* a TWIN store root (``twin_ingest.json`` ingest watermark,
  twin/ingest.py) is recognized: the watermark parses, its schema
  checks, and its chunk must not run ahead of the newest committed step
  — instead of the file being mistaken for stranded debris;
* a POPULATION root (rl/population.py: ``member_*`` dirs and/or a
  ``manifest_store``) recurses — the manifest store and every
  ``member_<k>/ck/<segment>/`` store (with each member's forensic
  bundles) verify individually, and ``--gc`` sweeps staging debris /
  applies retention across the whole zoo via ``gc_population``.

Run as a tier-1 test (tests/test_checkpoint.py::test_fsck_* and
tests/test_population.py::test_fsck_population_*) including negative
cases.
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_LENIENT = re.compile(r"^step_\d+")


def fsck_store(root: str, fast: bool = False, _depth: int = 0):
    """Returns (pass_lines, fail_lines) for one store directory."""
    from distributed_cluster_gpus_tpu.utils.checkpoint import (
        CheckpointCorruptError, _STEP_RE, _is_debris, step_dirname, steps,
        verify_checkpoint)

    ok, bad = [], []
    if not os.path.isdir(root):
        return ok, [f"{root}: not a directory"]
    committed = steps(root)
    for step in committed:
        d = os.path.join(root, step_dirname(step))
        try:
            man = verify_checkpoint(d, digests=not fast)
        except CheckpointCorruptError as e:
            bad.append(str(e))
            continue
        tag = ("legacy (no digest cover)" if man.get("legacy")
               else f"{man.get('n_files', 0)} files, "
                    f"schema v{man.get('schema_version')}")
        ok.append(f"{d}: step {step} verified ({tag})")
    for name in sorted(os.listdir(root)):
        full = os.path.join(root, name)
        if name.endswith("_swap") and _STEP_RE.match(name[:-5]):
            bad.append(f"{full}: interrupted re-save swap (a crash "
                       "between the swap renames; recover with --gc or "
                       "gc_checkpoints — no committed data is lost)")
        elif _is_debris(name):
            bad.append(f"{full}: stranded staging debris (crash "
                       "mid-save; sweep with --gc or gc_checkpoints)")
        elif (os.path.isdir(full) and _LENIENT.match(name)
              and not _STEP_RE.match(name)):
            bad.append(f"{full}: lenient step-like name the strict "
                       "step_<10 digits> rule rejects — not a resumable "
                       "checkpoint")
    # a twin store root carries an ingest watermark next to the step
    # dirs (twin/ingest.py) — recognize and verify it rather than
    # treating the store as an ordinary (or debris-ridden) one
    from distributed_cluster_gpus_tpu.twin.ingest import (
        TWIN_INGEST_FILE, TWIN_INGEST_SCHEMA)

    wm_path = os.path.join(root, TWIN_INGEST_FILE)
    is_twin = os.path.exists(wm_path)
    if is_twin:
        try:
            with open(wm_path) as f:
                wm = json.load(f)
            if wm.get("schema") != TWIN_INGEST_SCHEMA:
                bad.append(f"{wm_path}: unknown watermark schema "
                           f"{wm.get('schema')!r} (expected "
                           f"{TWIN_INGEST_SCHEMA})")
            else:
                chunk = wm.get("chunk")
                if committed and chunk is not None \
                        and int(chunk) > committed[-1]:
                    bad.append(
                        f"{wm_path}: watermark chunk {chunk} beyond the "
                        f"newest committed step {committed[-1]} — the "
                        "watermark was written without its commit")
                else:
                    ok.append(
                        f"{wm_path}: twin store (chunk={chunk} "
                        f"segments={wm.get('segments')} "
                        f"t={wm.get('t')} "
                        f"watermark_t={wm.get('watermark_t')})")
        except (OSError, json.JSONDecodeError, ValueError) as e:
            bad.append(f"{wm_path}: unreadable twin ingest watermark: {e}")
    if not committed and not bad and _depth == 0:
        bad.append(f"{root}: no committed checkpoints"
                   + (" (twin store: the first chunk has not committed "
                      "yet)" if is_twin else ""))
    aborted = os.path.join(root, "aborted")
    if _depth == 0 and os.path.isdir(aborted):
        ctx = os.path.join(aborted, "abort_context.json")
        if os.path.exists(ctx):
            try:
                with open(ctx) as f:
                    doc = json.load(f)
                ok.append(f"{ctx}: kind={doc.get('kind')} "
                          f"chunk={doc.get('chunk')} "
                          f"probes={doc.get('probes')}")
            except (OSError, json.JSONDecodeError) as e:
                bad.append(f"{ctx}: unreadable abort context: {e}")
        sub_ok, sub_bad = fsck_store(aborted, fast=fast, _depth=1)
        ok += sub_ok
        bad += sub_bad
    return ok, bad


def fsck_population(root: str, fast: bool = False):
    """(pass, fail) lines for a population root: the manifest store plus
    every member segment store (each member's forensic ``aborted/``
    bundles included via the per-store walk)."""
    from distributed_cluster_gpus_tpu.utils.checkpoint import (
        POP_MANIFEST_STORE, population_member_stores)

    ok, bad = [], []
    man = os.path.join(root, POP_MANIFEST_STORE)
    if os.path.isdir(man):
        sub_ok, sub_bad = fsck_store(man, fast=fast)
        ok += sub_ok
        bad += sub_bad
    else:
        bad.append(f"{man}: population root has no committed manifest "
                   "store — a killed driver cannot resume")
    mirror = os.path.join(root, "population_manifest.json")
    if os.path.exists(mirror):
        try:
            with open(mirror) as f:
                doc = json.load(f)
            ok.append(f"{mirror}: next_stage={doc.get('next_stage')} "
                      f"members={len(doc.get('members', []))} "
                      f"quarantine={len(doc.get('quarantine', []))}")
        except (OSError, json.JSONDecodeError) as e:
            bad.append(f"{mirror}: unreadable manifest mirror: {e}")
    stores = population_member_stores(root)
    if not stores:
        bad.append(f"{root}: population root with no member stores")
    for _member, store in stores:
        sub_ok, sub_bad = fsck_store(store, fast=fast)
        ok += sub_ok
        bad += sub_bad
    return ok, bad


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("stores", nargs="+", metavar="CKPT_DIR")
    ap.add_argument("--fast", action="store_true",
                    help="skip the per-file digest re-hash")
    ap.add_argument("--gc", action="store_true",
                    help="sweep stranded staging debris (and with --keep, "
                         "prune old verified steps) before reporting")
    ap.add_argument("--keep", type=int, default=0,
                    help="with --gc: keep only the newest N verified steps")
    args = ap.parse_args(argv)

    from distributed_cluster_gpus_tpu.utils.checkpoint import (
        is_population_root)

    rc = 0
    for root in args.stores:
        population = is_population_root(root)
        if args.gc:
            from distributed_cluster_gpus_tpu.utils.checkpoint import (
                gc_checkpoints)

            # recurse=True routes population roots through gc_population
            # (store-relative prefixes in the report) and is a no-op
            # detour for ordinary stores
            rep = gc_checkpoints(root, keep=args.keep or None, recurse=True)
            for name in rep["swept"]:
                print(f"gc: swept {os.path.join(root, name)}")
            for name in rep["pruned"]:
                print(f"gc: pruned {os.path.join(root, name)}")
        ok, bad = (fsck_population(root, fast=args.fast) if population
                   else fsck_store(root, fast=args.fast))
        for line in ok:
            print(f"PASS: {line}")
        for line in bad:
            print(f"FAIL: {line}", file=sys.stderr)
        if bad:
            rc = 1
    if rc == 0:
        print(f"checkpoint store OK: {len(args.stores)} store(s) verified")
    return rc


if __name__ == "__main__":
    sys.exit(main())

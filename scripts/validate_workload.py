"""Lint a workload scenario spec file (docs/workloads.md schema).

    python scripts/validate_workload.py SPEC.json [SPEC2.json ...]
        [--fleet paper|single_dc]

Schema/consistency checks before a spec reaches the compiler (the
style of scripts/check_metrics_schema.py — exit 0 + a one-line summary
when clean, exit 1 with one line per violation otherwise):

* the document parses into the WorkloadSpec schema (unknown keys,
  missing arrays, malformed stream kinds all fail at load);
* ingress names/indices resolve against the chosen fleet and per-ingress
  entries are unique;
* trace streams: timestamps finite, non-negative, NON-DECREASING, and
  size arrays (when given) finite, positive, and length-matched;
* rate timelines: rates finite, >= 0, bin width > 0, periodic timelines
  carry positive total rate;
* synthetic streams: finite non-negative rate, finite amp/period/phase
  with period > 0;
* signals: price/carbon arrays finite and >= 0, carbon's DC axis matches
  the fleet width, bin width > 0;
* the compiled aggregate arrival rate is positive and finite (a spec
  that generates nothing is almost always a mistake — reported as a
  violation unless --allow-empty).

Run as a tier-1 test (tests/test_workload.py::test_validate_workload_*)
including a negative case.  ``--json PATH`` writes a
``dcg.lint_report.v1`` report — the shape all four static checkers
share (docs/static_analysis.md).
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _finite(a) -> bool:
    return bool(np.all(np.isfinite(np.asarray(a, np.float64))))


def lint_spec(path: str, fleet, allow_empty: bool = False):
    """Returns a list of violation strings (empty when the spec is clean)."""
    from distributed_cluster_gpus_tpu.workload.spec import load_workload_json

    errs = []
    try:
        spec = load_workload_json(path, fleet)
    except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
        return [f"{path}: does not parse into the spec schema: {e}"]
    try:
        streams = spec.resolve(fleet.n_ing)
    except ValueError as e:
        return [f"{path}: {e}"]

    seen = set()
    for i, pair in enumerate(streams):
        for jt, st in zip(("inference", "training"), pair):
            # broadcast specs resolve to one shared StreamSpec per jtype:
            # lint (and report) each distinct stream object once
            if id(st) in seen:
                continue
            seen.add(id(st))
            broadcast = (len(spec.streams) == 2
                         and any(st is s for s in spec.streams))
            where = (f"{path}: {jt}" if broadcast
                     else f"{path}: ingress {i} {jt}")
            if st.kind in ("poisson", "sinusoid"):
                if not np.isfinite(st.rate) or st.rate < 0:
                    errs.append(f"{where}: rate must be finite and >= 0 "
                                f"(got {st.rate!r})")
                if st.kind == "sinusoid":
                    if not np.isfinite(st.amp):
                        errs.append(f"{where}: amp must be finite")
                    if not np.isfinite(st.period) or st.period <= 0:
                        errs.append(f"{where}: period must be finite and "
                                    f"> 0 (got {st.period!r})")
                    if not np.isfinite(st.phase_s):
                        errs.append(f"{where}: phase_s must be finite")
            elif st.kind == "trace":
                t = np.asarray(st.times, np.float64).reshape(-1)
                if t.size and not _finite(t):
                    errs.append(f"{where}: trace times must be finite")
                elif t.size and np.any(t < 0):
                    errs.append(f"{where}: trace times must be >= 0")
                elif t.size > 1 and np.any(np.diff(t) < 0):
                    k = int(np.argmax(np.diff(t) < 0))
                    errs.append(f"{where}: trace times must be "
                                f"non-decreasing (first violation at "
                                f"index {k + 1})")
                if st.sizes is not None:
                    s = np.asarray(st.sizes, np.float64).reshape(-1)
                    if s.shape != t.shape:
                        errs.append(f"{where}: {s.size} sizes for "
                                    f"{t.size} times")
                    elif s.size and (not _finite(s) or np.any(s <= 0)):
                        errs.append(f"{where}: trace sizes must be finite "
                                    "and > 0")
            elif st.kind == "rate_timeline":
                r = np.asarray(st.rates, np.float64).reshape(-1)
                if r.size == 0:
                    errs.append(f"{where}: empty rate timeline")
                elif not _finite(r) or np.any(r < 0):
                    errs.append(f"{where}: rates must be finite and >= 0")
                if not np.isfinite(st.bin_s) or st.bin_s <= 0:
                    errs.append(f"{where}: bin_s must be finite and > 0")
                if st.periodic and r.size and r.sum() <= 0:
                    errs.append(f"{where}: periodic timeline needs a "
                                "positive total rate")

    sig = spec.signals
    if sig is not None:
        where = f"{path}: signals"
        if not np.isfinite(sig.bin_s) or sig.bin_s <= 0:
            errs.append(f"{where}: bin_s must be finite and > 0")
        if sig.price is not None:
            pr = np.asarray(sig.price, np.float64).reshape(-1)
            if pr.size == 0 or not _finite(pr) or np.any(pr < 0):
                errs.append(f"{where}: price must be a non-empty finite "
                            ">= 0 array")
        if sig.carbon is not None:
            ca = np.asarray(sig.carbon, np.float64)
            if ca.ndim == 1:
                ca = ca[None, :]
            if ca.ndim != 2 or ca.shape[-1] != fleet.n_dc:
                errs.append(f"{where}: carbon must be [T, {fleet.n_dc}] "
                            f"(or [{fleet.n_dc}]) for this fleet; got "
                            f"shape {np.asarray(sig.carbon).shape}")
            elif not _finite(ca) or np.any(ca < 0):
                errs.append(f"{where}: carbon must be finite and >= 0")

    if not errs:
        rate = spec.mean_rate(fleet.n_ing)
        if not np.isfinite(rate):
            errs.append(f"{path}: aggregate arrival rate is not finite")
        elif rate <= 0 and not allow_empty:
            errs.append(f"{path}: spec generates no arrivals (aggregate "
                        "rate 0); pass --allow-empty if intentional")
    return errs


def lint_append(base_path: str, seg_path: str, fleet):
    """FAIL strings for appending SEGMENT to BASE (the twin ingest
    loop's exact validation — `twin.ingest.TraceCursor`): monotone
    segment times, known ingresses, trace-kind-only streams, size-column
    consistency, and a first event that does NOT precede the base
    trace's last."""
    from distributed_cluster_gpus_tpu.twin.ingest import TraceCursor

    try:
        cursor = TraceCursor.from_file(base_path, fleet)
    except (OSError, ValueError, TypeError, json.JSONDecodeError) as e:
        return [f"{base_path}: base spec does not load: {e}"]
    try:
        with open(seg_path) as f:
            seg = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{seg_path}: unreadable segment: {e}"]
    return cursor.validate_segment(seg, where=seg_path)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("specs", nargs="*", metavar="SPEC.json")
    ap.add_argument("--fleet", default="paper",
                    choices=["paper", "single_dc", "duo"])
    ap.add_argument("--allow-empty", action="store_true",
                    help="accept specs whose aggregate arrival rate is 0")
    ap.add_argument("--append", nargs=2, default=None,
                    metavar=("BASE.json", "SEGMENT.json"),
                    help="validate SEGMENT as an append-only trace "
                         "continuation of BASE (the twin ingest rule: "
                         "rejected if its first event time precedes the "
                         "base trace's last)")
    ap.add_argument("--json", default=None,
                    help="write a dcg.lint_report.v1 report here (the "
                         "schema shared by lint_graph / "
                         "check_metrics_schema / validate_chaos)")
    args = ap.parse_args(argv)
    if not args.specs and not args.append:
        ap.error("nothing to check: pass SPEC.json files and/or --append")

    from distributed_cluster_gpus_tpu.configs import (
        build_duo_fleet, build_fleet, build_single_dc_fleet)

    fleet = {"paper": build_fleet, "single_dc": build_single_dc_fleet,
             "duo": build_duo_fleet}[args.fleet]()
    checked = list(args.specs)
    errs = []
    for path in args.specs:
        errs += lint_spec(path, fleet, allow_empty=args.allow_empty)
    if args.append:
        checked += list(args.append)
        errs += lint_append(args.append[0], args.append[1], fleet)
    if args.json:
        from distributed_cluster_gpus_tpu.analysis import report

        rep = report.make_report(
            "validate_workload", checked,
            [report.violation(e, rule="workload-spec",
                              where=e.split(":", 1)[0]) for e in errs])
        report.write_report(rep, args.json)
    if errs:
        for e in errs:
            print(f"FAIL: {e}", file=sys.stderr)
        return 1
    n = len(args.specs)
    what = (f"{n} file(s)" if not args.append else
            f"{n} file(s) + 1 append" if n else "1 append")
    print(f"workload spec OK: {what} validated against "
          f"the {args.fleet} fleet")
    return 0


if __name__ == "__main__":
    sys.exit(main())

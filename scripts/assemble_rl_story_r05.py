"""Assemble the round-5 hour-scale RL story (VERDICT r04 item 3c).

    python scripts/assemble_rl_story_r05.py

Inputs: eval_r04.json's config-5 aggregate (the five base algorithms at
5 seeds on the drop-free run-shape) + eval_results/rl_story/*.json (the
round-5 chsac variants from scripts/rl_story_r05.py, same run-shape).

Outputs:
  eval_results/rl_story_r05.json      — merged rows + 3-axis Pareto sets
  eval_figures/rl_story_r05/pareto_r05.png — energy x p99 scatter,
      training completions annotated, Pareto-efficient points marked
"""

import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

OUT_JSON = "eval_results/rl_story_r05.json"
OUT_DIR = "eval_figures/rl_story_r05"

# Pareto axes: minimize energy, minimize p99 inference sojourn, maximize
# training completions (the three axes of VERDICT r04 item 3).  Two
# readings of "energy": raw kWh for the hour, and Wh per unit of work
# served (the reference's own efficiency metric) — both frontiers are
# computed and figured.
AXES = ("energy_kwh", "p99_lat_inf_s", "completed_trn")
AXES_NORM = ("wh_per_unit", "p99_lat_inf_s", "completed_trn")


def dominates(a, b, energy_key="energy_kwh"):
    """a dominates b: no worse on all three axes, strictly better on one."""
    ge = (a[energy_key] <= b[energy_key]
          and a["p99_lat_inf_s"] <= b["p99_lat_inf_s"]
          and a["completed_trn"] >= b["completed_trn"])
    gt = (a[energy_key] < b[energy_key]
          or a["p99_lat_inf_s"] < b["p99_lat_inf_s"]
          or a["completed_trn"] > b["completed_trn"])
    return ge and gt


def main():
    base = json.load(open("eval_r04.json"))["config5"]
    rows = []
    for agg in base["aggregate"]:
        rows.append({
            "name": agg["algo"], "n_seeds": agg["n_seeds"],
            "energy_kwh": agg["energy_kwh_mean"],
            "energy_kwh_sd": agg.get("energy_kwh_sd"),
            "p99_lat_inf_s": agg["p99_lat_inf_s_mean"],
            "completed_trn": agg["completed_trn_mean"],
            "completed_inf": agg["completed_inf_mean"],
            "wh_per_unit": agg.get("energy_per_unit_wh_mean"),
            "kind": "base",
        })

    variants = {}
    for path in sorted(glob.glob("eval_results/rl_story/*_s*.json")):
        r = json.load(open(path))
        variants.setdefault(r["variant"], []).append(r)
    for name, rs in sorted(variants.items()):
        rows.append({
            "name": f"chsac_{name}", "n_seeds": len(rs),
            "energy_kwh": float(np.mean([r["energy_kwh"] for r in rs])),
            "energy_kwh_sd": (float(np.std([r["energy_kwh"] for r in rs],
                                           ddof=1)) if len(rs) > 1 else None),
            "p99_lat_inf_s": float(np.mean([r["p99_lat_inf_s"] for r in rs])),
            "completed_trn": float(np.mean([r["completed_trn"] for r in rs])),
            "completed_inf": float(np.mean([r["completed_inf"] for r in rs])),
            "wh_per_unit": float(np.mean([r["energy_per_unit_wh"] for r in rs])),
            "seeds": sorted(r["seed"] for r in rs),
            "kind": "variant",
        })

    # a row with a non-finite axis (e.g. p99 NaN from a too-short run) can
    # never be dominated and would be spuriously starred — exclude it.
    # Axis values can also be None (base rows fetch via agg.get, and the
    # strict-JSON writers emit null for NaN), which np.isfinite rejects
    # with a TypeError — guard None explicitly so the row drops instead
    kept = [r for r in rows
            if all(r[k] is not None and np.isfinite(r[k])
                   for k in AXES + ("wh_per_unit",))]
    for r in rows:
        if r not in kept:
            print(f"  ! dropping {r['name']}: non-finite axis value")
    rows = kept
    for r in rows:
        r["pareto"] = not any(dominates(o, r) for o in rows if o is not r)
        r["pareto_norm"] = not any(
            dominates(o, r, energy_key="wh_per_unit")
            for o in rows if o is not r)
        r["dominates_norm"] = sorted(
            o["name"] for o in rows
            if o is not r and dominates(r, o, energy_key="wh_per_unit"))

    os.makedirs(OUT_DIR, exist_ok=True)
    os.makedirs(os.path.dirname(OUT_JSON), exist_ok=True)
    from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

    dump_json_atomic(OUT_JSON, {
        "note": "hour-scale (3600 s) config-4/5 workload, drop-free "
                "run-shape; base rows = eval_r04.json 5-seed aggregate; "
                "variants = scripts/rl_story_r05.py; pareto computed on "
                "(min energy, min p99_inf, max completed_trn)",
        "rows": rows,
    })

    def panel(energy_key, pareto_key, xlabel, fname, title):
        fig, ax = plt.subplots(figsize=(8.5, 5.5), facecolor="#fcfcfb")
        ax.set_facecolor("#fcfcfb")
        for r in rows:
            on = r[pareto_key]
            is_var = r["kind"] == "variant"
            color = ("#008300" if r["name"].startswith("chsac")
                     else "#2a78d6")
            ax.scatter(r[energy_key], r["p99_lat_inf_s"],
                       s=40 + r["completed_trn"] / 2.0,
                       facecolor=color if on else "none", edgecolor=color,
                       linewidth=1.4, alpha=0.9 if on else 0.6,
                       marker="s" if is_var else "o", zorder=3)
            ax.annotate(f"{r['name']}\n{r['completed_trn']:.0f} trn",
                        (r[energy_key], r["p99_lat_inf_s"]),
                        textcoords="offset points", xytext=(7, 4),
                        fontsize=7.5, color="#52514e")
        ax.set_xlabel(xlabel)
        ax.set_ylabel("p99 inference sojourn (s)")
        ax.set_title(title)
        ax.grid(color="#e4e3df", linewidth=0.6)
        for s in ("top", "right"):
            ax.spines[s].set_visible(False)
        path = os.path.join(OUT_DIR, fname)
        fig.savefig(path, dpi=130, bbox_inches="tight")
        plt.close(fig)
        return path

    p1 = panel("energy_kwh", "pareto",
               "energy (kWh, hour run, mean over seeds)", "pareto_r05.png",
               "hour-scale frontier: raw energy x p99 x training "
               "completions\n(filled = Pareto-efficient; squares = round-5 "
               "chsac variants; size = trn completions)")
    p2 = panel("wh_per_unit", "pareto_norm",
               "energy per unit of work served (Wh/unit, mean over seeds)",
               "pareto_norm_r05.png",
               "hour-scale frontier, work-normalized: Wh/unit x p99 x "
               "training completions\n(filled = Pareto-efficient; squares = "
               "round-5 chsac variants; size = trn completions)")
    print(f"wrote {OUT_JSON}, {p1}, {p2}")
    for r in sorted(rows, key=lambda x: x["energy_kwh"]):
        dom = (f"  dominates[norm]: {','.join(r['dominates_norm'])}"
               if r["dominates_norm"] else "")
        print(f"  {'*' if r['pareto'] else ' '}"
              f"{'N' if r['pareto_norm'] else ' '} {r['name']:>18s}: "
              f"{r['energy_kwh']:6.1f} kWh  {r['wh_per_unit']:.4f} Wh/u  "
              f"p99 {r['p99_lat_inf_s']:.3f}s  trn {r['completed_trn']:.0f} "
              f"({r['n_seeds']} seeds){dom}")


if __name__ == "__main__":
    main()

"""Step-time attribution CLI: where inside the step the wall time goes.

    python scripts/attrib_step.py                          # canonical pair
    python scripts/attrib_step.py --config joint_nf/ring/K4 --reps 5
    python scripts/attrib_step.py --trace-only --config 'joint_nf/*'
    python scripts/attrib_step.py --json out.json

Partitions the step-body jaxpr of each selected canonical config into
named phases (event-min head, selection payload, event-switch payloads,
_commit_plan, post-switch drain, log tail, policy tail, obs block) with
a hard 100%-coverage invariant, then measures each phase with compiled
cumulative-prefix ablations (interleaved medians — the banked r09/r12
A/B methodology).  Default configs are the canonical joint_nf K=1 and
K=4 pair, so the ROADMAP's "the step is dominated by the selection/read
side" claim becomes a measured number.

``--json`` writes the shared ``dcg.lint_report.v1`` shape (the same
report every static checker emits) with the per-config
``dcg.phase_attrib.v1`` documents under ``extra["attrib"]``.  Exit
status: 0 on success (timing-noise warnings included), 1 when any
partition violates coverage or the measured phase sum deviates from the
whole-step time beyond --tolerance, 2 on usage errors.
"""

import argparse
import fnmatch
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_CONFIGS = ["joint_nf/ring/K1", "joint_nf/ring/K4"]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", action="append", default=None,
                    metavar="NAME",
                    help="canonical lint config name or fnmatch glob "
                         "(repeatable; default: the joint_nf K1/K4 pair)")
    ap.add_argument("--trace-only", action="store_true",
                    help="eqn partition only — skip the compiled "
                         "measurement (no XLA compiles)")
    ap.add_argument("--rollouts", type=int, default=8)
    ap.add_argument("--chunk-steps", type=int, default=256)
    ap.add_argument("--warm-chunks", type=int, default=2)
    ap.add_argument("--timed-chunks", type=int, default=1)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="allowed |phase-sum/whole - 1| before the "
                         "measurement is flagged (default 0.10)")
    ap.add_argument("--json", default=None,
                    help="write the dcg.lint_report.v1 report here")
    a = ap.parse_args(argv)

    # honor an explicit JAX_PLATFORMS=cpu request: the axon sitecustomize
    # force-selects itself via jax.config and silently overrides the env
    # var, so the config update is the only way to really get CPU (the
    # same workaround bench.py and run_sim.py carry)
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        import jax

        jax.config.update("jax_platforms", "cpu")
    from distributed_cluster_gpus_tpu.analysis import attrib, lint, report
    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.utils.jaxcache import (
        setup_compile_cache)

    setup_compile_cache()
    patterns = a.config or DEFAULT_CONFIGS
    names = []
    for pat in patterns:
        hits = [c.name for c in lint.canonical_configs()
                if fnmatch.fnmatch(c.name, pat)]
        if not hits:
            ap.error(f"--config {pat!r} matches no canonical config "
                     "(see scripts/lint_graph.py --list-rules for the "
                     "matrix)")
        names += [h for h in hits if h not in names]

    fleet = build_fleet()
    reports, violations = [], []
    for name in names:
        try:
            rep = attrib.attribute_config(
                fleet, name, trace_only=a.trace_only,
                n_rollouts=a.rollouts, chunk_steps=a.chunk_steps,
                warm_chunks=a.warm_chunks, timed_chunks=a.timed_chunks,
                reps=a.reps)
        except attrib.PartitionError as e:
            violations.append(report.violation(
                str(e), rule="attrib-coverage", config=name))
            continue
        reports.append(rep)
        print(attrib.format_report(rep))
        print()
        m = rep.get("measured")
        if m and m["sum_vs_whole"] is not None \
                and abs(m["sum_vs_whole"] - 1.0) > a.tolerance:
            violations.append(report.violation(
                f"measured phase times sum to "
                f"{m['sum_vs_whole'] * 100:.1f}% of the whole-step time "
                f"(tolerance ±{a.tolerance * 100:.0f}%) — rerun with "
                "more --reps/--timed-chunks on a quieter box",
                rule="attrib-sum-vs-whole", config=name))

    rep = report.make_report("attrib_step", names, violations,
                             extra={"attrib": reports})
    if a.json:
        report.write_report(rep, a.json)
        print(f"wrote {a.json}")
    print(rep["summary"])
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

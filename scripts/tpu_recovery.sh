#!/bin/bash
# TPU bench recovery suite: run when the axon tunnel is (back) up.
# Captures, into bench_results/:
#   sweep_r03.json            - R x job_cap sweep (J up to 512), slot-ring replay
#   ablate_scatter_r03.json   - J=512 config, scatter replay (A/B)
#   ablate_nopregen_r03.json  - J=512 config, legacy in-step arrival draws
#                               (round-3 pregen lever attribution)
#   ablate_notrain_r03.json   - J=512 config, SAC gated off (engine+ingest)
#   ablate_chunk2048_r03.json - dispatch-amortization check
#   prof_r03/                 - jax.profiler trace of the J=512 config
# A watcher loop can poll `python -c "import jax; jax.devices()"` (with a
# timeout — a wedged tunnel HANGS, not errors) and invoke this on success.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results

BENCH_SWEEP=1 BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/sweep_r03.json
grep -q '"platform": "tpu"' bench_results/sweep_r03.json || {
  echo "not on TPU; aborting ablations" >&2; exit 1; }

DCG_REPLAY_INGEST=scatter BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/ablate_scatter_r03.json
# round-3 lever attribution: legacy in-step arrival draws (thinning
# while_loop back in the scanned step body) vs the default pregen table
DCG_ARRIVAL_PREGEN=0 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/ablate_nopregen_r03.json
BENCH_WARMUP=2000000000 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/ablate_notrain_r03.json
BENCH_CHUNK=2048 BENCH_CHUNKS=2 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/ablate_chunk2048_r03.json
BENCH_PROFILE=bench_results/prof_r03 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_CHUNKS=2 BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/prof_run_r03.json
echo "recovery suite complete"

#!/bin/bash
# TPU bench recovery suite: run when the axon tunnel is (back) up.
#
# Ordered by evidence value — the tunnel can wedge again mid-suite, so the
# measurements the round actually needs land first:
#   1. key_r05.json            - the north-star config (R=256, J=512) + J=128,
#                                default engine (slot-ring, pregen)
#   2. sweep_r05.json          - full R x job_cap sweep
#   3. ablate_scatter_r05.json - J=512, scatter replay (A/B settles the default)
#   4. ablate_nopregen_r05.json- J=512, legacy in-step arrival draws
#   5. ablate_notrain_r05.json - J=512, SAC gated off (engine+ingest split)
#   6. ablate_chunk2048_r05.json - dispatch-amortization check
#   7. prof_r05/               - jax.profiler trace of the J=512 config
#   8. (optional, WEEK_ONEHOT=1) canonical 7-day chsac_af with the
#      reference-shaped onehot critic — the run reserved for a TPU window
#      in docs/canonical_run.md
#
# Stages are IDEMPOTENT: a stage whose output already holds an on-chip
# result is skipped, so re-invoking after a mid-suite wedge (the watcher
# re-fires on the next good probe) only redoes what's missing.
#
# Every client call is wrapped in `timeout -k`: the tunnel wedges such that
# the client HANGS (not errors), which would otherwise stall the suite, and
# a client stuck past SIGTERM still dies on the KILL follow-up.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results

# the tunnel has reported both 'tpu' and 'axon' as the platform string;
# either means on-chip (bench.py accepts both at probe time)
on_chip() { grep -Eq '"platform": "(tpu|axon)"' "$1" 2>/dev/null; }

# DEADLINE (unix epoch, optional): the round driver runs its own bench on
# the TPU at round end — nothing here may still hold the chip then.  No
# stage starts with < 5 min left, stage timeouts are clipped to the time
# remaining, and the week run sizes itself to the window (it checkpoints,
# so a clipped run still banks resumable progress).
DEADLINE=${DEADLINE:-}
remaining() {
  if [ -z "$DEADLINE" ]; then echo 999999; else
    echo $(( DEADLINE - $(date -u +%s) )); fi
}

# run_stage <timeout_s> <outfile> <env assignments...>
# Skips when <outfile> already holds an on-chip JSON (rc 0) or when the
# deadline is close (rc 2).  Any other outcome — wall timeout (the JSON is
# only printed at the end, so a timeout means a wedge), a labeled
# CPU-fallback result (bench.py's internal probe gave up: tunnel down), or
# a crash — returns 1: no on-chip result is obtainable right now.
# Output goes to a temp file and only replaces <outfile> when something
# was produced, so a wedged retry can't clobber prior failure evidence.
run_stage() {
  local t="$1" out="$2"; shift 2
  if on_chip "$out"; then echo "skip $out (already on-chip)"; return 0; fi
  local left; left=$(remaining)
  if [ "$left" -lt 300 ]; then
    echo "stage $out skipped: deadline in ${left}s" >&2; return 2
  fi
  local clipped=0
  [ "$t" -gt $(( left - 60 )) ] && { t=$(( left - 60 )); clipped=1; }
  env "$@" timeout -k 30 "$t" python bench.py > "$out.tmp"
  local rc=$?
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    rm -f "$out.tmp"
    if [ "$clipped" -eq 1 ]; then
      echo "stage $out cut off by the deadline (rc=$rc)" >&2; return 2
    fi
    echo "stage $out timed out (rc=$rc) - tunnel likely re-wedged" >&2
    return 1
  fi
  if [ ! -s "$out.tmp" ]; then
    echo "stage $out produced no output (rc=$rc)" >&2
    rm -f "$out.tmp"; return 1
  fi
  mv "$out.tmp" "$out"
  on_chip "$out" || { echo "stage $out not on TPU (rc=$rc)" >&2; return 1; }
}

# A stage that can't produce an on-chip result right now means the tunnel
# is gone (or the bench is broken): abort the suite immediately (exit 3)
# instead of grinding the remaining stages through probe retries and CPU
# fallbacks — the watcher's cheap 90 s probes find the next window and
# re-fire, skipping whatever is already banked.  Deadline skips (rc 2)
# continue: they cost nothing and the week stage has its own gate.
n_skipped=0
stage() {
  run_stage "$@"
  case $? in
    0) ;;
    2) n_skipped=$((n_skipped + 1)) ;;
    *) echo "aborting suite; watcher will resume on the next window" >&2
       exit 3 ;;
  esac
}

stage 3600 bench_results/key_r05.json \
  BENCH_ROLLOUTS=256 BENCH_PROBE_TIMEOUT=240

stage 7200 bench_results/sweep_r05.json \
  BENCH_SWEEP=1 BENCH_PROBE_TIMEOUT=240
# A/B that settles the replay-ingest default (slot-ring vs scatter)
stage 2400 bench_results/ablate_scatter_r05.json \
  DCG_REPLAY_INGEST=scatter BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_PROBE_TIMEOUT=240 BENCH_COST=0
# round-3 lever attribution: legacy in-step arrival draws (thinning
# while_loop back in the scanned step body) vs the default pregen table
stage 2400 bench_results/ablate_nopregen_r05.json \
  DCG_ARRIVAL_PREGEN=0 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_PROBE_TIMEOUT=240 BENCH_COST=0
stage 2400 bench_results/ablate_notrain_r05.json \
  BENCH_WARMUP=2000000000 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_PROBE_TIMEOUT=240 BENCH_COST=0
stage 2400 bench_results/ablate_chunk2048_r05.json \
  BENCH_CHUNK=2048 BENCH_CHUNKS=2 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_PROBE_TIMEOUT=240 BENCH_COST=0
# scaling story beyond the sweep grid: BASELINE config-5-shaped 1024-way
# rollout batch, and the canonical-week backlog slab (J=8192, the slab the
# heuristics' week runs need — docs/canonical_run.md)
stage 2400 bench_results/scale_r1024_r05.json \
  BENCH_ROLLOUTS=1024 BENCH_JOB_CAP=128 BENCH_PROBE_TIMEOUT=240
# round-4 queue-ring A/B: same J=512 config with the round-3 all-in-slab
# queue layout (rings are the default in every other stage)
stage 2400 bench_results/ablate_slabqueue_r05.json \
  BENCH_QUEUE_MODE=slab BENCH_ROLLOUTS=256 BENCH_JOB_CAP=512 \
  BENCH_PROBE_TIMEOUT=240 BENCH_COST=0
# the canonical-week backlog shape, both layouts: rings carry the backlog
# at J=256 (small slab + deep queues) vs the r03 J=8192 slab
stage 2400 bench_results/weekshape_ring_r05.json \
  BENCH_ROLLOUTS=64 BENCH_JOB_CAP=256 BENCH_QUEUE_CAP=8192 BENCH_CHUNKS=2 \
  BENCH_PROBE_TIMEOUT=240
stage 2400 bench_results/bigslab_j8192_r05.json \
  BENCH_QUEUE_MODE=slab BENCH_ROLLOUTS=64 BENCH_JOB_CAP=8192 BENCH_CHUNKS=2 \
  BENCH_PROBE_TIMEOUT=240
stage 2400 bench_results/prof_run_r05.json \
  BENCH_PROFILE=bench_results/prof_r05 BENCH_ROLLOUTS=256 \
  BENCH_JOB_CAP=512 BENCH_CHUNKS=2 BENCH_PROBE_TIMEOUT=240
echo "bench stages complete ($n_skipped deadline-skipped)"

if [ "${WEEK_ONEHOT:-0}" = "1" ]; then
  done_marker=runs/week_chsac_onehot_tpu/history.json
  if [ -s "$done_marker" ] && \
     python - "$done_marker" <<'EOF'
import json, sys
h = json.load(open(sys.argv[1]))
sys.exit(0 if h.get("t_reached", 0) >= h.get("duration", 604800.0) else 1)
EOF
  then
    echo "skip week onehot run (already complete)"
  else
    # deadline first — the TPU gate probe below holds the chip, so it must
    # not run at all inside the driver's bench window
    left=$(remaining)
    if [ "$left" -lt 1800 ]; then
      echo "skipping week run: only ${left}s before the deadline" >&2; exit 2
    fi
    # week_chsac.py has no platform probe of its own: gate on the tunnel
    # still answering so a silent CPU fallback can't burn the 8 h timeout
    # writing CPU-paced results into a dir whose name claims TPU
    timeout -k 15 240 python -c \
      "import jax; assert jax.devices()[0].platform in ('tpu','axon')" || {
      echo "tunnel gone before week run - will retry on next probe" >&2
      exit 2; }
    week_t=28800
    left=$(remaining)
    [ "$week_t" -gt $(( left - 300 )) ] && week_t=$(( left - 300 ))
    echo "starting canonical-week chsac_af (onehot critic) on TPU (${week_t}s)"
    # checkpointed + resumable: a re-fire after a timeout continues the run
    # (log appends so a retry can't clobber the previous failure evidence)
    DCG_WEEK_CRITIC=onehot DCG_WEEK_OUT=runs/week_chsac_onehot_tpu \
      timeout -k 30 "$week_t" python scripts/week_chsac.py \
      >> bench_results/week_onehot_tpu.log 2>&1 \
      && echo "week onehot run complete" \
      || { echo "week onehot run failed/timed out - will retry on next probe" >&2
           exit 2; }
  fi
fi
[ "$n_skipped" -gt 0 ] && {
  echo "recovery suite incomplete ($n_skipped deadline-skipped stages)" >&2; exit 4; }
echo "recovery suite complete"

#!/bin/bash
# TPU bench recovery suite: run when the axon tunnel is (back) up.
# Captures, into bench_results/:
#   sweep_r02_postopt.json      - R x job_cap sweep, slot-ring replay
#   ablate_scatter_r02.json     - best config, scatter replay (A/B)
#   ablate_notrain_r02.json     - best config, SAC gated off (engine+ingest)
#   ablate_chunk2048_r02.json   - dispatch-amortization check
#   prof_r02/                   - jax.profiler trace of the best config
# A watcher loop can poll `python -c "import jax; jax.devices()"` (with a
# timeout — a wedged tunnel HANGS, not errors) and invoke this on success.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results

BENCH_SWEEP=1 BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/sweep_r02_postopt.json
grep -q '"platform": "tpu"' bench_results/sweep_r02_postopt.json || {
  echo "not on TPU; aborting ablations" >&2; exit 1; }

DCG_REPLAY_INGEST=scatter BENCH_ROLLOUTS=256 BENCH_JOB_CAP=128 \
  BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/ablate_scatter_r02.json
BENCH_WARMUP=2000000000 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=128 \
  BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/ablate_notrain_r02.json
BENCH_CHUNK=2048 BENCH_CHUNKS=2 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=128 \
  BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/ablate_chunk2048_r02.json
BENCH_PROFILE=bench_results/prof_r02 BENCH_ROLLOUTS=256 BENCH_JOB_CAP=128 \
  BENCH_CHUNKS=2 BENCH_PROBE_TIMEOUT=240 python bench.py \
  > bench_results/prof_run_r02.json
echo "recovery suite complete"

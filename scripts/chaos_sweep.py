"""Chaos sweep: every scheduling/DVFS algorithm under rising failure rates
or held-out chaos-curriculum presets.

    python scripts/chaos_sweep.py                     # default rate sweep
    python scripts/chaos_sweep.py --rates 0,1,2,4 --duration 900
    python scripts/chaos_sweep.py --algos default_policy,eco_route
    python scripts/chaos_sweep.py --presets held_out  # curriculum presets
    python scripts/chaos_sweep.py --presets held_out --workload flash_crowd
    python scripts/chaos_sweep.py --presets held_out \
        --algos default_policy,joint_nf,chsac_af --warm-ckpt runs/campaign/ck

Two sweep axes share one artifact:

* ``--rates``: stochastic per-DC outages at ``rate`` failures per
  DC-hour (MTBF = 3600/rate, MTTR = configs.paper.CHAOS_MTTR_S) on the
  canonical config-4 workload — the original chaos axis.
* ``--presets``: chaos-curriculum presets (``fault.CHAOS_PRESETS``;
  the ``held_out`` alias expands to ``fault.HELD_OUT_PRESETS``, the
  three evaluation-only regimes no training preset references) —
  the held-out evaluation axis for chaos-trained policies.  Compose
  with ``--workload flash_crowd`` (or any workload preset/spec) so
  chaos and bursty traffic are exercised together, and point
  ``--warm-ckpt`` at a campaign's checkpoint dir to score the
  chaos-trained CHSAC policy (actor/encoder grafted via
  ``rl.train.warm_sac_from_checkpoint``) against the heuristics.

The workload realization AND the fault realization are pure functions
of the seed, so every algorithm in a cell faces the identical incident
sequence — the comparison isolates how the *policies* degrade:
availability, migration success, jobs failed outright, drops, energy,
SLA latency, completions.

Rows are idempotent (cells already in the JSON are skipped), so a
killed sweep resumes where it stopped without recomputing finished
cells.  Artifact: eval_results/chaos_sweep.json (strict JSON writer,
NaN -> null).
"""

import argparse
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

if "cpu" in os.environ["JAX_PLATFORMS"]:
    jax.config.update("jax_platforms", "cpu")
from distributed_cluster_gpus_tpu.utils.jaxcache import (  # noqa: E402
    setup_compile_cache)

setup_compile_cache()  # share the cache with the test/bench harnesses

OUT = "eval_results/chaos_sweep.json"
# canonical resume keying + algorithm set live in sweep/spec.py since
# round 16 (ONE rule shared with the grid driver, so a mixed artifact —
# grid rows next to serial rows — resumes correctly under either
# driver); re-exported here for the existing import sites.  The key
# includes seed/duration/mttr: re-running with a different --seed/
# --duration/--mttr must COMPUTE those cells, not skip same-named cells
# banked under the old values (legacy rows without the fields key as the
# flag-less defaults).
from distributed_cluster_gpus_tpu.sweep.spec import (  # noqa: E402
    ALL_ALGOS, cell_key, load_done)


def tiny_spec(duration: float):
    """CI-affordable sweep world: the 2-DC duo fleet of the fault/obs
    test suites with scaled-down arrivals (--tiny).  One builder shared
    with the grid driver (sweep.spec.duo_base) so the CI world cannot
    drift between the serial and one-program paths."""
    from distributed_cluster_gpus_tpu.sweep.spec import duo_base

    return duo_base(duration)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rates", default="0,0.5,1,2",
                    help="comma-separated outage rates (failures/DC/hour); "
                         "0 = fault-free baseline row; ignored when "
                         "--presets is given")
    ap.add_argument("--presets", default=None,
                    help="comma-separated chaos-curriculum preset names "
                         "(fault.CHAOS_PRESETS) — or 'held_out' for the "
                         "three evaluation-only presets; switches the "
                         "sweep axis from rates to presets")
    ap.add_argument("--stage", type=int, default=0,
                    help="curriculum severity stage for --presets cells")
    ap.add_argument("--duration", type=float,
                    default=float(os.environ.get("DCG_CHAOS_DURATION", 600.0)))
    ap.add_argument("--algos", default=",".join(ALL_ALGOS))
    ap.add_argument("--seed", type=int, default=123)
    ap.add_argument("--mttr", type=float, default=None,
                    help="s; default configs.paper.CHAOS_MTTR_S")
    ap.add_argument("--chunk-steps", type=int, default=4096)
    ap.add_argument("--json", default=OUT)
    ap.add_argument("--workload", default=None, metavar="PRESET|SPEC.json",
                    help="compose a workload scenario (workload/ presets "
                         "or a JSON spec) with the chaos axis — e.g. "
                         "flash_crowd exercises outages under a 10x "
                         "arrival spike")
    ap.add_argument("--warm-ckpt", default=None, metavar="CKPT_DIR",
                    help="warm-start chsac_af cells from a training "
                         "checkpoint (e.g. a chaos campaign's last "
                         "healthy segment): actor/encoder grafted, "
                         "critic fresh — the chaos-trained-policy row. "
                         "A POPULATION root (rl/population.py) "
                         "auto-selects the leaderboard winner's newest "
                         "verified checkpoint (logged; a corrupt winner "
                         "store falls through to the runner-up)")
    ap.add_argument("--rollouts", type=int, default=2,
                    help="chsac_af rollouts when --warm-ckpt is given "
                         "(the distributed trainer is the init_sac path; "
                         "rollout 0 keeps the shared workload "
                         "realization)")
    ap.add_argument("--tiny", action="store_true",
                    help="2-DC duo fleet with scaled-down arrivals "
                         "instead of the config-4 paper world (CI / "
                         "smoke affordability)")
    ap.add_argument("--obs", action="store_true",
                    help="compile every sweep point with in-graph telemetry "
                         "(SimParams.obs_enabled): each row gains the "
                         "run-health watchdog totals (watchdog_violations "
                         "must stay 0; watchdog_pressure counts ring/slab "
                         "saturation steps under the injected outages)")
    ap.add_argument("--grid", choices=["auto", "off"], default="auto",
                    help="'auto' (default) delegates every grid-"
                         "expressible cell to the one-program sweep "
                         "compiler (sweep/, bit-identical rows, same "
                         "artifact + resume keys); chsac_af and "
                         "--warm-ckpt cells always take this script's "
                         "serial path.  'off' forces the legacy serial "
                         "loop for everything")
    a = ap.parse_args(argv)

    from distributed_cluster_gpus_tpu.configs.paper import CHAOS_MTTR_S
    from distributed_cluster_gpus_tpu.evaluation import (
        baseline_config, run_algo)
    from distributed_cluster_gpus_tpu.fault import (
        HELD_OUT_PRESETS, make_chaos_preset)
    from distributed_cluster_gpus_tpu.models import FaultParams
    from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

    algos = [s.strip() for s in a.algos.split(",") if s.strip()]
    mttr = a.mttr if a.mttr is not None else CHAOS_MTTR_S

    spec = (tiny_spec(a.duration) if a.tiny
            else baseline_config(4, a.duration))
    fleet, base = spec["fleet"], spec["base"]
    base = dataclasses.replace(base, seed=a.seed, duration=a.duration)
    workload_name = None
    if a.workload:
        from distributed_cluster_gpus_tpu.workload import (
            PRESETS, load_workload_json, make_preset)

        if a.workload in PRESETS:
            wl = make_preset(a.workload, fleet, horizon_s=a.duration) \
                if a.workload == "flash_crowd" else make_preset(a.workload,
                                                                fleet)
        else:
            wl = load_workload_json(a.workload, fleet)
        base = dataclasses.replace(base, workload=wl)
        workload_name = wl.name

    done = load_done(a.json)

    # the chaos axis: (label, FaultParams builder) per sweep point
    if a.presets:
        names = []
        for s in a.presets.split(","):
            s = s.strip()
            if not s:
                continue
            # the alias expands wherever it appears, not only alone
            names.extend(HELD_OUT_PRESETS if s == "held_out" else [s])
        cells = [(("preset", name),
                  FaultParams(curriculum=make_chaos_preset(
                      name, duration_s=a.duration, stage=a.stage)))
                 for name in names]
    else:
        rates = [float(r) for r in a.rates.split(",") if r.strip() != ""]
        # one outage-window budget across all rates (identical timeline
        # shapes -> identical HLO per algorithm class, compile paid once);
        # the ONE lowering rule shared with the grid compiler, so the two
        # drivers' incident sequences can never drift apart
        from distributed_cluster_gpus_tpu.sweep.spec import rate_fault_params

        by_rate = rate_fault_params(rates, a.duration, mttr)
        cells = [(("rate", rate), by_rate[rate]) for rate in rates]

    init_sac = None
    if a.warm_ckpt:
        from distributed_cluster_gpus_tpu.utils.checkpoint import (
            is_population_root)

        if is_population_root(a.warm_ckpt):
            # a population root: graft from the leaderboard winner's
            # newest verified checkpoint (rank fall-through + in-store
            # corrupt-step fallback both log their choices)
            from distributed_cluster_gpus_tpu.rl.population import (
                leaderboard_winner_ckpt)

            donor, _step, member = leaderboard_winner_ckpt(a.warm_ckpt)
            print(f"--warm-ckpt {a.warm_ckpt}: population root — "
                  f"grafting leaderboard member {member} from {donor}")
            a.warm_ckpt = donor

    def warm_start():
        """Lazy one-time policy graft from --warm-ckpt."""
        nonlocal init_sac
        if init_sac is None:
            from distributed_cluster_gpus_tpu.rl.train import (
                make_agent, warm_sac_from_checkpoint)

            cfg = make_agent(fleet, dataclasses.replace(
                base, algo="chsac_af")).cfg
            init_sac = warm_sac_from_checkpoint(
                cfg, a.warm_ckpt, jax.random.key(a.seed))
        return init_sac

    # the note must let a reader actually reproduce the artifact: the
    # interpolated fields alone cannot reconstruct --rates/--presets/
    # --algos/--warm-ckpt, so record the full invocation verbatim
    import shlex

    argv_note = " ".join(shlex.quote(x)
                         for x in (argv if argv is not None
                                   else sys.argv[1:]))
    note = ("chaos sweep: stochastic per-DC outages (rate rows: "
            "failures/DC/hour, MTTR %.0fs) and/or chaos-curriculum "
            "presets (preset rows, stage %d), seed %d, duration "
            "%.0fs, workload %s; identical workload + fault "
            "realization across algorithms in each cell; "
            "reproduce: python scripts/chaos_sweep.py %s"
            % (mttr, a.stage, a.seed, a.duration,
               workload_name or "legacy", argv_note)).rstrip()

    def save():
        dump_json_atomic(a.json, {"note": note,
                                  "rows": list(done.values())})

    # expressible cells run as a handful of vmapped programs through the
    # grid compiler (bit-identical rows, same artifact + cell_key resume
    # scheme); the serial loop below then picks up whatever is left —
    # chsac_af / --warm-ckpt cells and anything already banked
    if a.grid == "auto":
        from distributed_cluster_gpus_tpu import sweep

        grid_algos = tuple(al for al in algos
                           if al not in sweep.GRID_INEXPRESSIBLE)
        if grid_algos:
            gkw = dict(algos=grid_algos, seeds=(a.seed,),
                       duration=a.duration, mttr=mttr, stage=a.stage,
                       fleet="duo" if a.tiny else "paper", obs=a.obs,
                       workload=a.workload)
            if a.presets:
                gkw.update(axis="presets", presets=tuple(
                    s.strip() for s in a.presets.split(",") if s.strip()))
            else:
                gkw.update(axis="rates", rates=tuple(rates))
            g = sweep.SweepGrid(**gkw)
            errs = sweep.validate_grid(g, where="--grid auto")
            if errs:
                print("grid delegation skipped (serial fallback): "
                      + "; ".join(errs))
            else:
                sweep.run_grid(g, a.json, chunk_steps=a.chunk_steps,
                               note=note)
                done = load_done(a.json)

    for (axis, value), fp in cells:
        for algo in algos:
            warm = bool(algo == "chsac_af" and a.warm_ckpt)
            # seed/duration (and mttr for rate cells) ride on every row:
            # they are part of cell_key, so resume can tell a --seed 7
            # re-run apart from the banked default
            row_id = {"rate": value if axis == "rate" else None,
                      "preset": value if axis == "preset" else None,
                      "algo": algo, "seed": a.seed,
                      "duration": a.duration}
            if axis == "rate":
                row_id["mttr"] = mttr
            if workload_name:
                row_id["workload"] = workload_name
            if axis == "preset":
                row_id["stage"] = a.stage
            if warm:
                row_id["warm_ckpt"] = a.warm_ckpt
            if a.tiny:
                row_id["fleet"] = "duo"
            if cell_key(row_id) in done:
                print(f"skip {axis}={value} {algo} (done)")
                continue
            params = dataclasses.replace(base, algo=algo, faults=fp,
                                         obs_enabled=a.obs)
            kw = {}
            if warm:
                # the distributed trainer (the init_sac path) shards
                # rollouts over every device — round the request up to
                # a whole multiple of the mesh
                n_dev = len(jax.devices())
                r = max(2, a.rollouts)
                kw = {"init_sac": warm_start(),
                      "rollouts": -(-r // n_dev) * n_dev}
            s = run_algo(fleet, params, chunk_steps=a.chunk_steps, **kw)
            row = s.row()
            row.update(row_id)
            done[cell_key(row)] = row
            save()
            obs_msg = (f"  viol {row['watchdog_violations']:>2} "
                       f"press {row['watchdog_pressure']:>5}"
                       if a.obs else "")
            mig = row.get("migration_success_rate")
            print(f"  {axis}={value!s:>26} {algo:>15s}: "
                  f"avail {row.get('availability', 1.0):.4f}  "
                  f"mig {('%.2f' % mig) if mig is not None else ' nan'}  "
                  f"failed {row.get('n_fault_failed', 0):>3}  "
                  f"drop {row['dropped']:>4}  "
                  f"p99i {row['p99_lat_inf_s']:7.3f}s  "
                  f"done {row['completed_inf']}+{row['completed_trn']}"
                  f"{obs_msg}")
    save()
    print(f"chaos sweep complete -> {a.json}")


if __name__ == "__main__":
    main()

"""Chaos sweep: every scheduling/DVFS algorithm under rising failure rates.

    python scripts/chaos_sweep.py                     # default sweep
    python scripts/chaos_sweep.py --rates 0,1,2,4 --duration 900
    python scripts/chaos_sweep.py --algos default_policy,eco_route

Each sweep point runs one algorithm on the canonical config-4 workload
with stochastic per-DC outages at ``rate`` failures per DC-hour
(MTBF = 3600/rate, MTTR = configs.paper.CHAOS_MTTR_S), through the
fault/ subsystem (docs/faults.md).  The workload realization AND the
fault realization are pure functions of the seed, so every algorithm at
a given rate faces the identical incident sequence — the comparison
isolates how the *policies* degrade: availability, jobs migrated off
dead DCs, jobs failed outright, energy, latency, completions.

Rows are idempotent ((rate, algo) pairs already in the JSON are
skipped), so a killed sweep resumes where it stopped.  Artifact:
eval_results/chaos_sweep.json (strict JSON, NaN -> null).
"""

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

if "cpu" in os.environ["JAX_PLATFORMS"]:
    jax.config.update("jax_platforms", "cpu")
try:  # share the persistent compile cache with the test/bench harnesses
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
except Exception:  # noqa: BLE001 - cache is an optimization only
    pass

OUT = "eval_results/chaos_sweep.json"
# every non-debug algorithm of the paper world
ALL_ALGOS = ("default_policy", "cap_uniform", "cap_greedy", "joint_nf",
             "bandit", "carbon_cost", "eco_route", "chsac_af")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rates", default="0,0.5,1,2",
                    help="comma-separated outage rates (failures/DC/hour); "
                         "0 = fault-free baseline row")
    ap.add_argument("--duration", type=float,
                    default=float(os.environ.get("DCG_CHAOS_DURATION", 600.0)))
    ap.add_argument("--algos", default=",".join(ALL_ALGOS))
    ap.add_argument("--seed", type=int, default=123)
    ap.add_argument("--mttr", type=float, default=None,
                    help="s; default configs.paper.CHAOS_MTTR_S")
    ap.add_argument("--chunk-steps", type=int, default=4096)
    ap.add_argument("--json", default=OUT)
    ap.add_argument("--obs", action="store_true",
                    help="compile every sweep point with in-graph telemetry "
                         "(SimParams.obs_enabled): each row gains the "
                         "run-health watchdog totals (watchdog_violations "
                         "must stay 0; watchdog_pressure counts ring/slab "
                         "saturation steps under the injected outages)")
    a = ap.parse_args(argv)

    from distributed_cluster_gpus_tpu.configs.paper import (
        CHAOS_MTTR_S, build_chaos_faults)
    from distributed_cluster_gpus_tpu.evaluation import (
        baseline_config, run_algo)
    from distributed_cluster_gpus_tpu.models import FaultParams
    from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

    rates = [float(r) for r in a.rates.split(",") if r.strip() != ""]
    algos = [s.strip() for s in a.algos.split(",") if s.strip()]
    mttr = a.mttr if a.mttr is not None else CHAOS_MTTR_S

    spec = baseline_config(4, a.duration)
    fleet, base = spec["fleet"], spec["base"]
    base = dataclasses.replace(base, seed=a.seed)

    done = {}
    if os.path.exists(a.json):
        try:
            with open(a.json) as f:
                done = {(r["rate"], r["algo"]): r
                        for r in json.load(f).get("rows", [])}
        except (json.JSONDecodeError, OSError, KeyError, TypeError):
            done = {}

    # one outage-window budget across all rates: identical timeline shapes
    # mean identical HLO per algorithm class, so the persistent compile
    # cache pays each algorithm's compile once for the whole sweep
    pos_rates = [r for r in rates if r > 0]
    k_max = (max(build_chaos_faults(r, a.duration, mttr).max_outages_per_dc
                 for r in pos_rates) if pos_rates else 2)

    def save():
        dump_json_atomic(a.json, {
            "note": "chaos sweep on the config-4 workload: stochastic "
                    "per-DC outages at rate failures/DC/hour, "
                    f"MTTR {mttr:.0f}s, seed {a.seed}, duration "
                    f"{a.duration:.0f}s; identical workload + fault "
                    "realization across algorithms at each rate; "
                    "reproduce: python scripts/chaos_sweep.py",
            "rows": list(done.values()),
        })

    for rate in rates:
        if rate > 0:
            fp = dataclasses.replace(
                build_chaos_faults(rate, a.duration, mttr),
                max_outages_per_dc=k_max)
        else:
            fp = FaultParams()  # enabled-but-empty: the golden baseline
        for algo in algos:
            if (rate, algo) in done:
                print(f"skip rate={rate} {algo} (done)")
                continue
            params = dataclasses.replace(base, algo=algo, faults=fp,
                                         obs_enabled=a.obs)
            s = run_algo(fleet, params, chunk_steps=a.chunk_steps)
            row = s.row()
            row["rate"] = rate
            row["algo"] = algo
            done[(rate, algo)] = row
            save()
            obs_msg = (f"  viol {row['watchdog_violations']:>2} "
                       f"press {row['watchdog_pressure']:>5}"
                       if a.obs else "")
            print(f"  rate={rate:>4} {algo:>15s}: "
                  f"avail {row.get('availability', 1.0):.4f}  "
                  f"migrated {row.get('n_fault_migrated', 0):>4}  "
                  f"failed {row.get('n_fault_failed', 0):>3}  "
                  f"{row['energy_kwh']:7.2f} kWh  "
                  f"done {row['completed_inf']}+{row['completed_trn']}"
                  f"{obs_msg}")
    save()
    print(f"chaos sweep complete -> {a.json}")


if __name__ == "__main__":
    main()

"""Round-5 hour-scale RL story runs (VERDICT r04 item 3).

    python scripts/rl_story_r05.py <variant> <seed> [<seed> ...]

Variants (all: chsac_af on the BASELINE config-4 workload, rollouts=8,
duration 3600, the round-4 drop-free run-shape so rows merge with
eval_r04.json's 5-seed cold rows):

  warm  — policy warm-start: encoder+actor grafted from the canonical-week
          checkpoint (runs/week_chsac_capped_r04/ckpt) via
          `rl.train.warm_sac_from_checkpoint`; critic/lambda/alpha fresh.
  ewK   — reward energy weight K (e.g. ew4, ew16): r = -K*E_unit + 0.05/n
          (`SimParams.rl_energy_weight`; K=1 is the reference reward).
  dense — 256 SAC steps per chunk instead of the harness default 8
          (~22k updates/hour-run vs ~680: 30x closer to the reference's
          one-update-per-transition schedule, which the harness cannot
          afford on one CPU core).
  Combinable with underscores: warm_ew4, dense_ew16, warm_dense, ...

One artifact per (variant, seed): eval_results/rl_story/<variant>_s<seed>.json
(skipped if it already exists — idempotent).  Merge + figure:
scripts/assemble_rl_story_r05.py.
"""

import dataclasses
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

if "cpu" in os.environ["JAX_PLATFORMS"]:
    jax.config.update("jax_platforms", "cpu")

WEEK_CKPT = "runs/week_chsac_capped_r04/ckpt"
OUT_DIR = "eval_results/rl_story"


def main():
    seeds = [int(s) for s in sys.argv[2:]] or [123]
    tokens = sys.argv[1].split("_")
    ew_tokens = [t for t in tokens if re.fullmatch(r"ew\d+(?:\.\d+)?", t)]
    bad = [t for t in tokens if t not in ("warm", "dense") + tuple(ew_tokens)]
    if bad or len(ew_tokens) > 1 or len(set(tokens)) != len(tokens) or not tokens:
        sys.exit(f"unknown variant {sys.argv[1]!r} (tokens: warm, dense, "
                 "ewK — each at most once)")
    warm = "warm" in tokens
    dense = "dense" in tokens
    w = float(ew_tokens[0][2:]) if ew_tokens else 1.0
    # canonical order so 'ew4_warm' and 'warm_ew4' share one artifact/label
    variant = "_".join([t for t in ("warm", "dense") if t in tokens]
                       + ew_tokens)

    from distributed_cluster_gpus_tpu.evaluation import baseline_config, run_algo
    from distributed_cluster_gpus_tpu.parallel.rollout import constraints_from_params
    from distributed_cluster_gpus_tpu.rl.sac import SACConfig
    from distributed_cluster_gpus_tpu.rl.train import warm_sac_from_checkpoint
    from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

    os.makedirs(OUT_DIR, exist_ok=True)
    duration = float(os.environ.get("DCG_RL_STORY_DURATION", 3600.0))
    spec = baseline_config(4, duration)
    fleet, base = spec["fleet"], spec["base"]

    for seed in seeds:
        out_path = os.path.join(OUT_DIR, f"{variant}_s{seed}.json")
        if os.path.exists(out_path):
            print(f"skip {variant} seed {seed} (done)")
            continue
        params = dataclasses.replace(base, seed=seed, rl_energy_weight=w)
        init_sac = None
        if warm:
            cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc),
                            n_dc=fleet.n_dc, n_g=params.max_gpus_per_job,
                            batch=params.rl_batch,
                            constraints=constraints_from_params(params),
                            critic_arch=params.critic_arch)
            init_sac = warm_sac_from_checkpoint(cfg, WEEK_CKPT,
                                                jax.random.key(seed))
        print(f"=== {variant} seed {seed} (w={w}, warm={warm}, dense={dense})")
        s = run_algo(fleet, params, chunk_steps=4096, rollouts=8,
                     init_sac=init_sac,
                     sac_steps_per_chunk=256 if dense else None)
        row = s.row()
        row["variant"] = variant
        row["rl_energy_weight"] = w
        row["warm_start"] = warm
        row["seed"] = seed
        # strict JSON: a NaN p99 from a degenerate run must land as null,
        # not a bare NaN token that breaks jq/JS consumers
        dump_json_atomic(out_path, row)
        print(f"  {variant} s{seed}: {s.energy_kwh:.1f} kWh, "
              f"p99_inf {s.p99_lat_inf_s:.3f}s, "
              f"done {s.completed_inf}+{s.completed_trn}, "
              f"Wh/unit {s.energy_per_unit_wh:.4f} -> {out_path}")


if __name__ == "__main__":
    main()

"""Merge the per-config eval campaign artifacts into eval_r03.json.

    python scripts/merge_eval_r03.py [--dir eval_results] [--out eval_r03.json]

Each input file is one `eval.py --json` artifact (c1.json, c3c.json, ...);
the merge is a plain key union (configs are disjoint across files) plus a
small provenance header.
"""

import argparse
import glob
import json
import os
import sys


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="eval_results")
    ap.add_argument("--out", default="eval_r03.json")
    a = ap.parse_args(argv)

    merged = {}
    files = sorted(glob.glob(os.path.join(a.dir, "*.json")))
    if not files:
        sys.exit(f"no artifacts under {a.dir}")
    for path in files:
        try:
            with open(path) as f:
                data = json.load(f)
        except json.JSONDecodeError:
            print(f"skipping half-written {path}")
            continue
        for k, v in data.items():
            if k in merged:
                print(f"warning: duplicate key {k} (from {path}); keeping first")
                continue
            merged[k] = v
    merged["_provenance"] = {
        "script": "scripts/run_eval_r03.sh",
        "sources": [os.path.basename(p) for p in files],
    }
    tmp = a.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, indent=2, default=float)
    os.replace(tmp, a.out)
    print(f"wrote {a.out}: {sorted(k for k in merged if not k.startswith('_'))}")


if __name__ == "__main__":
    main()

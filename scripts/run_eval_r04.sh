#!/bin/bash
# Round-4 eval campaign: the config-4/5 comparison RE-RUN on the ring
# queue layout (drop-free overload semantics — the reference's unbounded
# queues), because rows are only seed-comparable on one engine run-shape
# (artifact run_shape stamp).  Ordered cheap -> expensive and written
# incrementally so partial progress survives a kill; each stage skips
# itself if its artifact already exists (idempotent re-fire).
set -u
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
log() { echo "[eval-r04] $(date -u +%H:%M:%S) $*"; }
have() { python -c "import json,sys; json.load(open(sys.argv[1]))" "$1" 2>/dev/null; }

D="--duration 3600 --chunk-steps 4096"

# stage 0: configs 1-3 on the ring layout, 3 seeds (all-heuristic, cheap)
for c in 1 2 3; do
  out="eval_results/c${c}_r04.json"
  if ! have "$out"; then
    log "config $c x3 seeds"
    python eval.py --config "$c" $D --seeds 3 --json "$out" || exit 1
  fi
done

# stage 1: the three heuristic families on config 5's workload, 5 seeds
if ! have eval_results/c5_ring_heur.json; then
  log heuristics x5 seeds
  python eval.py --config 5 --algos default_policy,joint_nf,eco_route \
    $D --seeds 5 --json eval_results/c5_ring_heur.json || exit 1
fi

# stage 2: the two RL algorithms, one seed per artifact (resumable).
# Seed-major order: the assembler only aggregates seeds with the FULL
# algo set, so completing (chsac, ppo) pairs maximizes usable seeds if
# the clock runs out mid-campaign.
for seed in 123 124 125 126 127; do
  for algo in chsac_af ppo; do
    out="eval_results/c5_ring_${algo}_s${seed}.json"
    if have "$out"; then log "skip $algo seed $seed (done)"; continue; fi
    log "$algo seed $seed"
    python eval.py --config 5 --algos "$algo" $D --rollouts 8 \
      --seeds 1 --seed0 "$seed" --json "$out" || exit 1
  done
done

log "assembling eval_r04.json"
python scripts/assemble_eval_r04.py
log done

"""Lint a chaos-curriculum spec file (docs/faults.md schema).

    python scripts/validate_chaos.py SPEC.json [SPEC2.json ...]
        [--duration 3600] [--fleet paper|single_dc]

Schema/consistency checks before a curriculum reaches the timeline
compiler (the style of scripts/validate_workload.py — exit 0 + a
one-line summary when clean, exit 1 with one line per violation
otherwise):

* the document parses into the ChaosCurriculum schema (unknown keys,
  missing enabling rates, malformed stages all fail at load);
* range sanity the dataclass cannot judge alone: outage curricula whose
  worst-stage expected downtime exceeds the expected uptime (the fleet
  would be down more than up — almost always a spec typo), derate caps
  below the fleet's lowest ladder step, WAN multipliers so large the
  retransmit fold overflows a float32;
* window-budget truncation: with --duration, each enabled family's
  expected incident count at the harshest stage must fit its static
  ``max_*`` budget (a truncated schedule silently goes quiet mid-run —
  use ``ChaosCurriculum.sized_for`` or raise the budget);
* the curriculum draws *something*: a spec with every family disabled
  is reported unless --allow-empty.

Run as a tier-1 test (tests/test_chaos.py::test_validate_chaos_*)
including a negative case.  ``--json PATH`` writes a
``dcg.lint_report.v1`` report — the shape all four static checkers
share (docs/static_analysis.md).
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def lint_curriculum(path: str, freq_levels, duration: float = 0.0,
                    allow_empty: bool = False):
    """Returns a list of violation strings (empty when the spec is clean)."""
    from distributed_cluster_gpus_tpu.fault.curriculum import load_chaos_json

    errs = []
    try:
        cur = load_chaos_json(path)
    except OSError as e:
        return [f"{path}: cannot read spec file: {e}"]
    except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
        return [f"{path}: does not parse into the curriculum schema: {e}"]

    rs = cur.max_rate_scale()
    ms = max(s.mttr_scale for s in cur.stages)
    if not (cur.outages_on or cur.derates_on or cur.wan_on):
        if not allow_empty:
            errs.append(f"{path}: every incident family is disabled (no "
                        "positive rate); pass --allow-empty if intentional")
        return errs

    if cur.outages_on:
        # worst case: shortest possible uptime against longest repair
        up, down = cur.mtbf_lo_s / rs, cur.mttr_hi_s * ms
        if down > up:
            errs.append(
                f"{path}: outages: worst-stage expected downtime "
                f"({down:.0f}s) exceeds expected uptime ({up:.0f}s) — the "
                "fleet would be down more than up")
    if cur.derates_on:
        f_min = float(np.min(np.asarray(freq_levels)))
        sev = max(s.severity_scale for s in cur.stages)
        if cur.derate_f_hi ** sev < f_min:
            errs.append(
                f"{path}: derates: every drawn cap (<= "
                f"{cur.derate_f_hi ** sev:.3f} at max severity) falls below "
                f"the fleet's lowest ladder step {f_min} — all windows clamp "
                "to the floor; widen [f_lo, f_hi]")
    if cur.wan_on:
        sev = max(s.severity_scale for s in cur.stages)
        worst = (1.0 + (cur.wan_mult_hi - 1.0) * sev) / (1.0 - cur.wan_loss_hi)
        if not np.isfinite(np.float32(worst)) or worst > 1e6:
            errs.append(
                f"{path}: wan: worst-case effective multiplier {worst:.3g} "
                "is unusably large (latency fold is float32)")

    if duration > 0:
        def check_budget(what, expected, budget):
            if expected > budget:
                errs.append(
                    f"{path}: {what}: expected ~{expected:.1f} windows per "
                    f"target over {duration:.0f}s at the harshest stage but "
                    f"the budget is {budget} — the schedule truncates "
                    "(size with ChaosCurriculum.sized_for or raise max_*)")

        if cur.outages_on:
            cycle = cur.mtbf_lo_s / rs + cur.mttr_lo_s
            check_budget("outages", duration / cycle, cur.max_outages_per_dc)
        if cur.derates_on:
            check_budget("derates",
                         duration / 3600.0 * cur.derate_rate_per_dc_hour * rs,
                         cur.max_derates_per_dc)
        if cur.wan_on:
            check_budget("wan",
                         duration / 3600.0 * cur.wan_rate_per_edge_hour * rs,
                         cur.max_wan_per_edge)
    return errs


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("specs", nargs="+", metavar="SPEC.json")
    ap.add_argument("--fleet", default="paper",
                    choices=["paper", "single_dc"])
    ap.add_argument("--duration", type=float, default=0.0,
                    help="s; > 0 additionally checks the window budgets "
                         "cover a run of this length without truncation")
    ap.add_argument("--allow-empty", action="store_true",
                    help="accept curricula with every incident family off")
    ap.add_argument("--json", default=None,
                    help="write a dcg.lint_report.v1 report here (the "
                         "schema shared by lint_graph / "
                         "check_metrics_schema / validate_workload)")
    args = ap.parse_args(argv)

    from distributed_cluster_gpus_tpu.configs import (
        build_fleet, build_single_dc_fleet)

    fleet = build_fleet() if args.fleet == "paper" else build_single_dc_fleet()
    errs = []
    for path in args.specs:
        errs += lint_curriculum(path, fleet.freq_levels,
                                duration=args.duration,
                                allow_empty=args.allow_empty)
    if args.json:
        from distributed_cluster_gpus_tpu.analysis import report

        rep = report.make_report(
            "validate_chaos", list(args.specs),
            [report.violation(e, rule="chaos-spec",
                              where=e.split(":", 1)[0]) for e in errs])
        report.write_report(rep, args.json)
    if errs:
        for e in errs:
            print(f"FAIL: {e}", file=sys.stderr)
        return 1
    print(f"chaos spec OK: {len(args.specs)} file(s) validated against "
          f"the {args.fleet} fleet")
    return 0


if __name__ == "__main__":
    sys.exit(main())

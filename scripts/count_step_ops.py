"""jaxpr op census for the scanned step program: counts by primitive CLASS.

    python scripts/count_step_ops.py [--k 1,4,8] [--json PATH]

The step is op-count bound (docs/perf_notes.md): wall time tracks how many
small fused kernels the scan body dispatches, so structural regressions
matter even when every golden stays green.  The eqn ceilings in
tests/test_perf_structure.py pin the SCALAR total; this census splits it
by primitive class — scatter / gather / select / while / cond / dot — so
a regression is caught by KIND: a handler re-growing a private in-branch
write chain shows up as +selects (K=1 masked writes) or +scatters (K-row
plans), a sneaking host round-trip as +while, a lost shared-commit merge
as +scatter-per-field.

Three consumers, one counter — and since PR 13 the counter itself lives
in `distributed_cluster_gpus_tpu.analysis.walker` (the linter, the
ceiling pins, and this census share ONE flattening rule):
* CLI — prints the census table per (algo, layout, K) and optionally
  writes JSON;
* bench.py — banks `census_matrix()` into the round JSON (`op_census`
  key) next to the superstep sweep, so banked rounds are diffable by op
  class;
* tests/test_perf_structure.py::test_op_census_smoke — tier-1 smoke: the
  census runs, classes partition sanely, and the write-plan program's
  headline counts hold.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the census classes and the counter itself live in analysis.walker —
# THE one shared flatten/visit core (the linter, the ceiling pins, and
# this census must flatten jaxprs identically or banked censuses stop
# being comparable to the pinned ceilings); re-exported here so existing
# consumers (bench.py, tests) keep their import surface
from distributed_cluster_gpus_tpu.analysis.walker import (  # noqa: E402,F401
    CENSUS_CLASSES, op_census)


def step_census(fleet, algo, queue_mode="ring", superstep_k=1,
                obs_enabled=False):
    """Census of the main event-scan body for one engine configuration.

    Trace shape matches tests/test_perf_structure._trace (so "eqns" is
    the pinned number); per_event = eqns / K is the superstep's
    amortized-cost metric."""
    import jax

    from distributed_cluster_gpus_tpu.analysis.walker import main_scan_body
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

    params = SimParams(algo=algo, duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0,
                       queue_mode=queue_mode, queue_cap=256,
                       superstep_k=superstep_k, obs_enabled=obs_enabled)
    eng = Engine(fleet, params)
    st = init_state(jax.random.key(0), fleet, params)
    jpr = jax.make_jaxpr(lambda s: eng._run_chunk(s, None, 8))(st)
    body = main_scan_body(jpr, 8).params["jaxpr"].jaxpr
    census = op_census(body)
    census["per_event"] = round(census["eqns"] / superstep_k, 1)
    return census


def census_matrix(fleet=None, algos=("joint_nf", "default_policy"),
                  layouts=("ring", "slab"), ks=(1, 4, 8)):
    """The banked census rows: [{algo, queue_mode, superstep_k, census}].

    K>1 rows only exist for the ring layout at the bench shape (the
    superstep sweep's configuration); every (algo, layout) gets its K=1
    row."""
    if fleet is None:
        from distributed_cluster_gpus_tpu.configs import build_fleet

        fleet = build_fleet()
    rows = []
    for algo in algos:
        for qm in layouts:
            for k in ks:
                if k > 1 and (qm != "ring" or algo != algos[0]):
                    continue
                rows.append({
                    "algo": algo, "queue_mode": qm, "superstep_k": k,
                    "census": step_census(fleet, algo, queue_mode=qm,
                                          superstep_k=k),
                })
    return rows


def eligibility_configs(fleet=None):
    """The named config families of the eligibility census, as real
    SimParams (faults / signal workloads attached, not simulated flags —
    if `static_ineligibility` ever starts reading them, the census and
    its regression test see the true answer)."""
    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.configs.paper import (
        build_incident_faults)
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.workload import make_preset

    if fleet is None:
        fleet = build_fleet()
    base = dict(duration=600.0, log_interval=20.0, inf_mode="sinusoid",
                inf_rate=6.0, trn_mode="poisson", trn_rate=0.1,
                job_cap=128, seed=0)
    return [
        ("joint_nf", SimParams(algo="joint_nf", **base)),
        ("default_policy", SimParams(algo="default_policy", **base)),
        ("carbon_cost+signals",
         SimParams(algo="carbon_cost",
                   workload=make_preset("legacy_signals", fleet), **base)),
        ("eco_route+signals",
         SimParams(algo="eco_route",
                   workload=make_preset("legacy_signals", fleet), **base)),
        ("default_policy+faults",
         SimParams(algo="default_policy",
                   faults=build_incident_faults(10.0, 20.0), **base)),
        ("bandit", SimParams(algo="bandit", **base)),
        ("bandit+faults",
         SimParams(algo="bandit",
                   faults=build_incident_faults(10.0, 20.0), **base)),
        ("weighted_router",
         SimParams(algo="joint_nf",
                   router_weights=(1.0, 1.0, 0.0, 0.0, 1.0), **base)),
        ("chsac_af", SimParams(algo="chsac_af", **base)),
        ("chsac_af+elastic",
         SimParams(algo="chsac_af", elastic_scaling=True, **base)),
        ("chsac_af+faults",
         SimParams(algo="chsac_af",
                   faults=build_incident_faults(10.0, 20.0), **base)),
    ]


def eligibility_report(fleet=None):
    """Per-config fast-path eligibility rows (round 12).

    One row per named config family: which program each compiles
    (superstep at K>1, write-plan commit) and, when a static gate
    rejects it, the gate's reason strings verbatim from
    `Engine.static_ineligibility`.  tests/test_perf_structure.py pins
    this matrix so the ineligibility residue never silently regrows."""
    from distributed_cluster_gpus_tpu.sim.engine import static_ineligibility

    rows = []
    for name, params in eligibility_configs(fleet):
        inel = static_ineligibility(params)
        rows.append({
            "config": name,
            "algo": params.algo,
            "superstep_eligible": not inel["superstep"],
            "superstep_reasons": list(inel["superstep"]),
            "planner_eligible": not inel["planner"],
            "planner_reasons": list(inel["planner"]),
        })
    return rows


def _fmt_eligibility(rows):
    head = (f"{'config':<24}{'superstep':>10}{'planner':>9}  "
            "rejected by")
    lines = [head, "-" * 78]
    for r in rows:
        why = r["superstep_reasons"] + r["planner_reasons"]
        gate = why[0].split(":")[0] if why else "—"
        lines.append(
            f"{r['config']:<24}"
            f"{'yes' if r['superstep_eligible'] else 'NO':>10}"
            f"{'yes' if r['planner_eligible'] else 'NO':>9}  {gate}")
    return "\n".join(lines)


def _fmt_table(rows):
    cols = ["eqns", "per_event", "scatter", "gather", "select", "dus",
            "reduce", "dot", "while", "cond", "scan", "other"]
    head = f"{'config':<28}" + "".join(f"{c:>10}" for c in cols)
    lines = [head, "-" * len(head)]
    for r in rows:
        name = f"{r['algo']}/{r['queue_mode']}/K{r['superstep_k']}"
        c = r["census"]
        lines.append(f"{name:<28}"
                     + "".join(f"{c.get(k, 0):>10}" for k in cols))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--k", default="1,4,8",
                    help="comma-separated superstep K values (ring only)")
    ap.add_argument("--algos", default="joint_nf,default_policy")
    ap.add_argument("--json", default=None,
                    help="also write the census rows to this JSON path")
    ap.add_argument("--eligibility", action="store_true",
                    help="print the per-config fast-path eligibility "
                         "matrix (which program compiles, which static "
                         "gate rejected it and why) instead of the op "
                         "census")
    args = ap.parse_args(argv)

    if args.eligibility:
        rows = eligibility_report()
        print(_fmt_eligibility(rows))
        for r in rows:
            for why in r["superstep_reasons"] + r["planner_reasons"]:
                print(f"  {r['config']}: {why}")
        if args.json:
            with open(args.json, "w") as f:
                json.dump(rows, f, indent=1, sort_keys=True)
                f.write("\n")
            print(f"wrote {args.json}")
        return 0

    rows = census_matrix(
        algos=tuple(args.algos.split(",")),
        ks=tuple(int(k) for k in args.k.split(",")))
    print(_fmt_table(rows))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

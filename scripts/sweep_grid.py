"""Sweep-grid driver: the scenario grid as a few vmapped programs.

    python scripts/sweep_grid.py --spec grid.json
    python scripts/sweep_grid.py --spec grid.json --validate
    python scripts/sweep_grid.py --rates 0,1,2 --algos default_policy,\
eco_route --seeds 123,124 --tiny --duration 300
    python scripts/sweep_grid.py --presets held_out --workload flash_crowd
    python scripts/sweep_grid.py --spec grid.json --columnar out_dir/

The one-program counterpart of ``scripts/chaos_sweep.py`` (which
delegates here when its grid is expressible): cells are bucketed by
compiled-program signature and each bucket runs as ONE
``jit(vmap(...))`` — shard_map over the ``('dcn','rollout')`` mesh with
``--mesh`` — so a hundreds-of-cells study pays a handful of Python
dispatch sequences instead of one per cell.  Rows are bit-identical to
the serial driver's (tests/test_sweep.py pins it) and land in the same
strict-JSON artifact schema with the same ``cell_key`` resume rule, so
the two drivers can share (and resume) one artifact.  ``--columnar``
additionally writes the binary columnar shards + manifest
(docs/sweep.md).  chsac_af cells are grid-inexpressible (online
training) and run through the serial ``run_algo`` path into the same
artifact; ``--serial`` forces every cell down that path (the A/B
reference arm).
"""

import argparse
import os
import shlex
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

if "cpu" in os.environ["JAX_PLATFORMS"]:
    jax.config.update("jax_platforms", "cpu")
from distributed_cluster_gpus_tpu.utils.jaxcache import (  # noqa: E402
    setup_compile_cache)

setup_compile_cache()

OUT = "eval_results/sweep_grid.json"


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--spec", default=None, metavar="GRID.json",
                    help="declarative SweepGrid spec file (docs/sweep.md "
                         "schema); inline axis flags below override "
                         "nothing when given")
    ap.add_argument("--validate", action="store_true",
                    help="lint the grid and exit (0 clean / 1 violations "
                         "— the validate_chaos.py contract)")
    ap.add_argument("--rates", default="0,0.5,1,2",
                    help="comma-separated outage rates (failures/DC/hour)")
    ap.add_argument("--presets", default=None,
                    help="chaos-curriculum preset names (or 'held_out'); "
                         "switches the axis from rates to presets")
    ap.add_argument("--stage", type=int, default=0)
    ap.add_argument("--algos", default=None,
                    help="comma list (default: every non-debug algorithm)")
    ap.add_argument("--seeds", default="123",
                    help="comma-separated workload/fault seeds")
    ap.add_argument("--duration", type=float, default=600.0)
    ap.add_argument("--mttr", type=float, default=None,
                    help="s; default configs.paper.CHAOS_MTTR_S")
    ap.add_argument("--workload", default=None, metavar="PRESET|SPEC.json")
    ap.add_argument("--tiny", action="store_true",
                    help="2-DC duo fleet instead of the config-4 paper "
                         "world")
    ap.add_argument("--obs", action="store_true",
                    help="compile every cell with in-graph telemetry")
    ap.add_argument("--chunk-steps", type=int, default=4096)
    ap.add_argument("--json", default=OUT)
    ap.add_argument("--columnar", default=None, metavar="DIR",
                    help="also write binary columnar shards + manifest "
                         "here (docs/sweep.md layout)")
    ap.add_argument("--mesh", action="store_true",
                    help="shard bucket lanes over the ('dcn','rollout') "
                         "device mesh (buckets whose lane count does not "
                         "divide the mesh fall back to single-device "
                         "vmap)")
    ap.add_argument("--serial", action="store_true",
                    help="force the serial run_algo path for every cell "
                         "(the grid-vs-serial A/B reference arm)")
    a = ap.parse_args(argv)

    from distributed_cluster_gpus_tpu import sweep
    from distributed_cluster_gpus_tpu.configs.paper import CHAOS_MTTR_S

    if a.spec:
        # a malformed spec file (unknown keys, bad JSON) is a lint
        # finding, not a traceback — validate_chaos.py style
        try:
            grid = sweep.load_sweep_json(a.spec)
        except (ValueError, OSError) as e:
            print(f"FAIL: {a.spec}: {e}")
            return 1
        where = a.spec
    else:
        kw = dict(duration=a.duration, stage=a.stage,
                  mttr=a.mttr if a.mttr is not None else CHAOS_MTTR_S,
                  fleet="duo" if a.tiny else "paper", obs=a.obs,
                  workload=a.workload,
                  seeds=tuple(int(s) for s in a.seeds.split(",")
                              if s.strip()))
        if a.algos:
            kw["algos"] = tuple(s.strip() for s in a.algos.split(",")
                                if s.strip())
        if a.presets:
            kw["axis"] = "presets"
            kw["presets"] = tuple(s.strip() for s in a.presets.split(",")
                                  if s.strip())
        else:
            kw["axis"] = "rates"
            kw["rates"] = tuple(float(r) for r in a.rates.split(",")
                                if r.strip() != "")
        grid = sweep.SweepGrid(**kw)
        where = "<flags>"

    errs = sweep.validate_grid(grid, where=where)
    if errs:
        for e in errs:
            print(f"FAIL: {e}", file=sys.stderr)
        return 1
    if a.validate:
        print(f"sweep grid OK: {len(sweep.grid_cells(grid))} cell(s)")
        return 0

    # self-describing artifact: the exact reproduce command (satellite
    # rule — interpolated fields alone cannot reconstruct the axes)
    argv_note = " ".join(shlex.quote(x)
                         for x in (argv if argv is not None
                                   else sys.argv[1:]))
    note = (f"sweep grid ({grid.axis} axis, fleet {grid.fleet}, duration "
            f"{grid.duration:.0f}s); one vmapped program per bucket, rows "
            f"bit-identical to the serial driver; reproduce: python "
            f"scripts/sweep_grid.py {argv_note}")

    mesh = None
    if a.mesh:
        from distributed_cluster_gpus_tpu.parallel import make_mesh

        mesh = make_mesh()
    res = sweep.run_grid(grid, a.json, chunk_steps=a.chunk_steps,
                         columnar_dir=a.columnar, mesh=mesh, note=note,
                         serial=a.serial)
    print(f"sweep grid complete -> {a.json} ({res['ran']} ran in "
          f"{res['buckets']} bucket(s) + {res['serial_cells']} serial, "
          f"{res['skipped']} resumed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

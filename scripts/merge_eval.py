"""Merge the per-config eval campaign artifacts into one eval_r{N}.json.

    python scripts/merge_eval.py [--dir eval_results] [--out eval_r04.json]

Each input file is one `eval.py --json` artifact (c1.json, c3c.json, ...).
Only top-level ``config*`` keys are merged (the directory also holds
learner-metric histories with unrelated schemas).  When the same config
appears in several files — a seed-extension campaign writes e.g. c3.json
(seeds 123-125) and c3_s126.json (seeds 126-127) — their ``per_seed``
maps are unioned and the mean±sd aggregate is recomputed over the union
with the same semantics as ``evaluation.compare_seeds`` (sd is NaN below
2 finite samples).
"""

import argparse
import glob
import json
import math
import os
import sys


def _aggregate(per_seed):
    """Recompute compare_seeds' mean±sd rows over a per_seed union."""
    seeds = sorted(per_seed, key=lambda s: int(s))
    if not seeds:
        return []
    n_algos = len(per_seed[seeds[0]])
    for sd in seeds:
        names = [r.get("algo") for r in per_seed[sd]]
        ref = [r.get("algo") for r in per_seed[seeds[0]]]
        if names != ref:
            raise SystemExit(
                f"per-seed algo lists disagree across files: seed {sd} has "
                f"{names}, seed {seeds[0]} has {ref} — the extension run was "
                "made with a different algo list; re-run it to match")
    out = []
    for i in range(n_algos):
        rows = [per_seed[sd][i] for sd in seeds]
        agg = {"algo": rows[0].get("algo"), "n_seeds": len(seeds)}
        for k in rows[0]:
            vals = [r.get(k) for r in rows]
            if not all(isinstance(v, (int, float)) and
                       not isinstance(v, bool) for v in vals):
                if any(v is None for v in vals) and isinstance(
                        rows[0].get(k), (int, float)):
                    print(f"warning: metric {k} missing from some seeds of "
                          f"algo {agg['algo']}; dropped from the aggregate")
                continue
            finite = [float(v) for v in vals if not math.isnan(v)]
            n = len(finite)
            mean = sum(finite) / n if n else float("nan")
            if n > 1:
                var = sum((v - mean) ** 2 for v in finite) / (n - 1)
                sd = math.sqrt(var)
            else:
                sd = float("nan")
            agg[f"{k}_mean"] = mean
            agg[f"{k}_sd"] = sd
            if n != len(vals):
                agg[f"{k}_n_finite"] = n
        out.append(agg)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="eval_results")
    ap.add_argument("--out", default="eval_r03.json")
    a = ap.parse_args(argv)

    merged = {}
    extended = set()
    contributed = []  # only files that supplied at least one config* key
    files = sorted(glob.glob(os.path.join(a.dir, "*.json")))
    if not files:
        sys.exit(f"no artifacts under {a.dir}")
    for path in files:
        try:
            with open(path) as f:
                data = json.load(f)
        except json.JSONDecodeError:
            print(f"skipping half-written {path}")
            continue
        if not isinstance(data, dict):
            print(f"skipping non-dict artifact {path}")
            continue
        took_any = False
        for k, v in data.items():
            if not k.startswith("config"):
                continue
            took_any = True
            if k not in merged:
                merged[k] = v
                continue
            old, new = merged[k], v
            if not (isinstance(old, dict) and "per_seed" in old and
                    isinstance(new, dict) and "per_seed" in new):
                print(f"warning: duplicate key {k} (from {path}) without "
                      "per_seed maps; keeping first")
                continue
            # seeds are only comparable if the runs were shaped alike: any
            # top-level metadata beyond the per_seed/aggregate payload
            # (e.g. a future duration/rollouts stamp) must agree
            meta_keys = (set(old) | set(new)) - {"per_seed", "aggregate"}
            for mk in sorted(meta_keys):
                if old.get(mk) != new.get(mk):
                    print(f"warning: {k}: field {mk!r} differs across files "
                          f"({old.get(mk)!r} vs {new.get(mk)!r} in {path}) — "
                          "unioned seeds may not be comparable")
            dup = set(old["per_seed"]) & set(new["per_seed"])
            if dup:
                print(f"warning: {k}: seeds {sorted(dup)} in both files; "
                      f"keeping the first file's rows")
            union = {**new["per_seed"], **old["per_seed"]}
            merged[k] = {**old, "per_seed": union,
                         "aggregate": _aggregate(union)}
            extended.add(k)
        if took_any:
            contributed.append(os.path.basename(path))
    merged["_provenance"] = {
        "merged_by": "scripts/merge_eval.py",
        "dir": a.dir,
        "sources": contributed,
        "seed_extended": sorted(extended),
    }
    tmp = a.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, indent=2, default=float)
    os.replace(tmp, a.out)
    print(f"wrote {a.out}: {sorted(k for k in merged if not k.startswith('_'))}"
          + (f" (seed-extended: {sorted(extended)})" if extended else ""))


if __name__ == "__main__":
    main()

"""Round-4 canonical-week campaign: every heuristic family, 3 seeds,
drop-free queue rings.

    JAX_PLATFORMS=cpu python scripts/week_campaign_r04.py

The reference's headline configuration (604,800 s, inference off, training
Poisson 0.02/s per ingress — `/root/reference/run.sh:21-24`) with the
round-4 ring layout: waiting jobs queue unboundedly-in-effect (auto-sized
rings) exactly like the reference's Python lists, so `dropped == 0` is an
assertion, not an aspiration — closing VERDICT r03 items 4 (overload
parity) and 6 (week-scale rankings at >= 3 seeds/family).

Writes eval_results/week_r04.json incrementally ((algo, seed) rows skip
themselves when already present — idempotent re-fire), and streams seed
123's CSVs to runs/week_r04/<algo>/ for the queue-length figures.
"""

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

OUT = "eval_results/week_r04.json"
SEEDS = (123, 124, 125)
FAMILIES = [
    ("default_policy", 0.0),
    ("joint_nf", 0.0),
    ("eco_route", 0.0),
    ("carbon_cost", 0.0),
    ("bandit", 0.0),
    # 40 kW: the r03 cap, INFEASIBLE under drop-free overload (the
    # saturated fleet at the DVFS floor draws ~69 kW) — kept as the
    # expected-failure rows.  75 kW sits between the floor and the
    # uncapped ~82 kW peak: the feasible-cap demonstration.
    ("cap_uniform", 40_000.0),
    ("cap_greedy", 40_000.0),
    ("cap_uniform", 75_000.0),
    ("cap_greedy", 75_000.0),
]


def main():
    import jax
    import numpy as np

    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.sim.engine import auto_queue_cap
    from distributed_cluster_gpus_tpu.sim.io import run_simulation

    jax.config.update("jax_enable_x64", True)  # float64 week clock

    fleet = build_fleet()
    done = {}
    if os.path.exists(OUT):
        try:
            with open(OUT) as f:
                done = json.load(f).get("runs", {})
        except (json.JSONDecodeError, OSError):
            done = {}

    def flush():
        tmp = OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "note": "canonical week, ring layout (drop-free), "
                        "3 seeds/family; reproduce: python run_sim.py "
                        "--algo <algo> --duration 604800 --log-interval 20 "
                        "--inf-mode off --trn-mode poisson --trn-rate 0.02 "
                        "--seed <seed> [--power-cap 40000] --job-cap 2048",
                "runs": done,
            }, f, indent=2, default=float)
        os.replace(tmp, OUT)

    for algo, cap in FAMILIES:
        for seed in SEEDS:
            # 40 kW rows keep their original (pre-suffix) keys
            suffix = f"_cap{int(cap) // 1000}" if cap not in (0.0, 40_000.0) else ""
            key = f"{algo}{suffix}_s{seed}"
            if key in done:
                print(f"skip {key}")
                continue
            params = SimParams(
                algo=algo, duration=604_800.0, log_interval=20.0,
                inf_mode="off", trn_mode="poisson", trn_rate=0.02,
                power_cap=cap, job_cap=2048, seed=seed,
                time_dtype="float64")
            params = dataclasses.replace(
                params, queue_cap=auto_queue_cap(params, fleet))
            out_dir = (f"runs/week_r04/{algo}{suffix}" if seed == 123 else None)
            t0 = time.time()
            st = run_simulation(fleet, params, out_dir=out_dir,
                                chunk_steps=4096)
            wall = time.time() - t0
            kwh = float(np.asarray(st.dc.energy_j).sum()) / 3.6e6
            units = float(np.asarray(st.units_finished).sum())
            row = {
                "algo": algo, "seed": seed, "power_cap": cap or None,
                "finished": int(np.asarray(st.n_finished).sum()),
                "dropped": int(st.n_dropped),
                "queued_at_end": int(np.asarray(
                    st.queues.tail - st.queues.head).sum()),
                "kwh": kwh,
                "wh_per_unit": kwh * 1000.0 / max(units, 1e-9),
                "mean_kw": kwh * 3.6e6 / 604_800.0 / 1000.0,
                "queue_cap": params.queue_cap,
                "wall_s": round(wall, 1),
            }
            done[key] = row
            flush()
            print(f"{key}: finished={row['finished']} dropped="
                  f"{row['dropped']} queued={row['queued_at_end']} "
                  f"Wh/unit={row['wh_per_unit']:.4f} wall={wall:.0f}s")
    print("week campaign complete")


if __name__ == "__main__":
    main()

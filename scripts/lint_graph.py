"""dcg-lint CLI: run the jaxpr rule engine over the canonical configs.

    python scripts/lint_graph.py                      # full matrix
    python scripts/lint_graph.py --rule no-while-in-step,prng-key-reuse
    python scripts/lint_graph.py --config 'joint_nf/*' --json out.json
    python scripts/lint_graph.py --update-baselines   # re-bank ceilings
    python scripts/lint_graph.py --list-rules

Exit status: 0 when every selected config passes every selected rule
(allowlisted hits are reported but do not fail); 1 when any
error-severity violation remains; 2 on usage errors.

The JSON report is ``dcg.lint_report.v1`` — the same shape
check_metrics_schema.py / validate_chaos.py / validate_workload.py emit
— and bench.py banks it per round as a zero-cost evidence artifact.

``--update-baselines`` re-traces the matrix, rewrites
distributed_cluster_gpus_tpu/analysis/baselines.json (the GENERATED eqn
ceilings tests/test_perf_structure.py enforces — never hand-edit it),
and prints the per-config per-class diff so a ceiling move is always a
reviewed structure diff, not a silent constant edit.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_matrix(rep):
    head = (f"{'config':<32}{'eqns':>7}{'superstep':>11}{'planner':>9}"
            f"{'viol':>6}{'allow':>7}  status")
    lines = [head, "-" * len(head)]
    for name, row in rep["matrix"].items():
        lines.append(
            f"{name:<32}{row['eqns']:>7}"
            f"{'on' if row['superstep_on'] else '—':>11}"
            f"{'on' if row['planner_on'] else 'off':>9}"
            f"{row['violations']:>6}{row['allowlisted']:>7}  "
            f"{'ok' if row['ok'] else 'FAIL'}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rule", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--config", default=None,
                    help="comma-separated fnmatch globs over canonical "
                         "config names (default: all)")
    ap.add_argument("--json", default=None,
                    help="write the dcg.lint_report.v1 report here")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog (id, severity, doc)")
    ap.add_argument("--update-baselines", action="store_true",
                    help="re-trace the matrix and regenerate "
                         "analysis/baselines.json, printing the per-class "
                         "diff")
    ap.add_argument("--baselines-out", default=None,
                    help="with --update-baselines: write here instead of "
                         "the in-tree analysis/baselines.json")
    args = ap.parse_args(argv)

    from distributed_cluster_gpus_tpu.analysis import lint, rules

    if args.list_rules:
        for rid, r in sorted(rules.RULES.items()):
            print(f"{rid:<28} [{r.severity}]"
                  + ("  (traces under x64)" if r.needs_x64 else ""))
            print(f"    {r.doc}")
        return 0

    if args.update_baselines:
        try:
            old = lint.load_baselines()
        except (OSError, ValueError):
            old = None
        new = lint.generate_baselines()
        path = args.baselines_out or lint.BASELINES_PATH
        lint.dump_baselines(new, path)
        diff = lint.diff_baselines(old, new)
        if diff:
            print("baseline drift (old -> new):")
            for line in diff:
                print(f"  {line}")
        else:
            print("baselines unchanged")
        print(f"wrote {path} ({len(new['configs'])} entries)")
        return 0

    rule_ids = args.rule.split(",") if args.rule else None
    config_names = args.config.split(",") if args.config else None
    try:
        rep = lint.run_lint(config_names=config_names, rule_ids=rule_ids)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not rep["checked"]:
        print(f"error: no canonical config matches {args.config!r}",
              file=sys.stderr)
        return 2

    print(_fmt_matrix(rep))
    for v in rep["violations"]:
        print(f"FAIL [{v['rule']}] {v['config']}: {v['message']}\n"
              f"     at {v['where']}", file=sys.stderr)
    for a in rep["allowlisted"]:
        print(f"allow [{a['rule']}] {a['config']}: {a['message'].splitlines()[0][:100]}\n"
              f"     reason: {a['reason']}")
    print(rep["summary"])
    if args.json:
        from distributed_cluster_gpus_tpu.analysis.report import write_report

        write_report(rep, args.json)
        print(f"wrote {args.json}")
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())

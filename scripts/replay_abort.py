"""Replay a forensic abort bundle and minimize the failing window.

    python scripts/replay_abort.py CKPT_DIR/aborted \
        [--fleet paper|single_dc|duo] [--no-bisect] [--no-state-check] \
        [--force] [--json OUT.json] [-- any run_sim.py flags...]

The bundle (``ckpt_dir/aborted``, written by the trainer abort path) is
self-contained evidence: a forensic checkpoint of the tripping chunk's
end state plus ``abort_context.json`` (probe, chunk index, chaos
stage/reseed, params fingerprint).  This CLI rebuilds the aborted run's
(fleet, params) from the SAME run_sim.py flags the run used, applies the
context's chaos stage/reseed override, checks the params fingerprint
(refusing a mismatched world unless --force), and then:

1. restores the newest VERIFIED healthy checkpoint before the tripping
   chunk (corrupt ones are skipped via the fallback chain),
2. re-executes forward and asserts the SAME probe trips at the SAME
   chunk, byte-comparing the re-executed state to the forensic snapshot,
3. bisects inside the failing chunk to the minimal scan-step window.

Output: PASS/FAIL lines in the scripts/validate_chaos.py style, the
replay report as JSON (--json), exit 0 only when the trip reproduced.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_world(a, rest):
    """(fleet, params) from run_sim.py's own builders — the only way to
    guarantee the replay params match what the CLI-launched run used."""
    import dataclasses

    import run_sim

    rs = run_sim.parse_args(rest)
    if a.fleet == "single_dc":
        rs.single_dc = True
    if rs.single_dc and a.fleet == "paper":
        a.fleet = "single_dc"
    from distributed_cluster_gpus_tpu.configs import (
        build_fleet, build_single_dc_fleet)

    if a.fleet == "duo":
        from distributed_cluster_gpus_tpu.configs.paper import build_duo_fleet

        fleet = build_duo_fleet()
    elif a.fleet == "single_dc":
        fleet = build_single_dc_fleet()
    else:
        fleet = build_fleet()
    params = run_sim.build_params(rs)
    workload = run_sim.build_workload_spec(rs, fleet, params)
    if workload is not None:
        params = dataclasses.replace(params, workload=workload)
    faults = run_sim.build_fault_params(rs, fleet)
    if faults is not None:
        params = dataclasses.replace(params, faults=faults)
    params = run_sim.finalize_queue_cap(params, fleet, max(1, rs.rollouts))
    return fleet, params, rs


def apply_chaos_context(params, ctx):
    """Force the curriculum to the aborted segment's stage/reseed — the
    campaign driver ramps/reseeds beyond what the CLI flags encode."""
    import dataclasses

    chaos = ctx.get("chaos")
    if chaos is None or params.faults is None \
            or params.faults.curriculum is None:
        return params
    cur = params.faults.curriculum
    cur = cur.at_stage(int(chaos["stage"])).reseeded(int(chaos["reseed"]))
    return dataclasses.replace(
        params, faults=dataclasses.replace(params.faults, curriculum=cur))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        epilog="flags after the known ones are parsed as run_sim.py flags "
               "(rebuild the aborted run's exact configuration)")
    ap.add_argument("bundle", metavar="BUNDLE_DIR",
                    help="forensic bundle dir (the run's ckpt_dir/aborted) "
                         "— or, with --member, a population root")
    ap.add_argument("--member", type=int, default=None, metavar="K",
                    help="treat BUNDLE_DIR as a population-campaign root "
                         "(rl/population.py) and replay member K's newest "
                         "quarantine bundle (located via the quarantine "
                         "log; same fingerprint enforcement and PASS/FAIL "
                         "contract as a direct bundle path)")
    ap.add_argument("--fleet", default="paper",
                    choices=["paper", "single_dc", "duo"])
    ap.add_argument("--no-bisect", action="store_true",
                    help="skip the minimal-window bisection")
    ap.add_argument("--no-state-check", action="store_true",
                    help="skip the byte-compare against the forensic state")
    ap.add_argument("--force", action="store_true",
                    help="replay despite a params-fingerprint mismatch")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="write the replay report as strict JSON")
    a, rest = ap.parse_known_args(argv)

    from distributed_cluster_gpus_tpu.utils.jaxcache import (
        setup_compile_cache)

    setup_compile_cache()
    from distributed_cluster_gpus_tpu.sim.replay import (
        ReplayError, load_abort_context, replay_abort)

    if a.member is not None:
        from distributed_cluster_gpus_tpu.rl.population import (
            PopulationError, locate_member_bundle)

        try:
            bundle = locate_member_bundle(a.bundle, a.member)
        except PopulationError as e:
            print(f"FAIL: {e}", file=sys.stderr)
            return 2
        print(f"member {a.member} bundle: "
              f"{os.path.relpath(bundle, a.bundle)}")
        a.bundle = bundle
    try:
        ctx = load_abort_context(a.bundle)
    except ReplayError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 2
    print(f"bundle: kind={ctx['kind']} chunk={ctx['chunk']} "
          f"probes={ctx['probes']} reason={ctx['reason'][:120]}")
    fleet, params, _rs = build_world(a, rest)
    params = apply_chaos_context(params, ctx)
    try:
        report = replay_abort(fleet, params, a.bundle,
                              bisect=not a.no_bisect,
                              check_state=not a.no_state_check,
                              force=a.force, verbose=True)
    except ReplayError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    if a.json:
        from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

        dump_json_atomic(a.json, report)
    print(f"PASS: trip reproduced at chunk {report['chunk']} "
          f"(probes {report['probes']}, restored step "
          f"{report['restored_step']})")
    if "window_steps" in report:
        print(f"minimal window: {report['window_steps']} of "
              f"{report['chunk_steps']} scan steps "
              f"(probes {report['window_probes']})")
    if report.get("state_match") is not None:
        if report["state_match"]:
            print("state vs forensic snapshot: bit-exact")
        else:
            # the trip reproduced but the re-executed state diverges —
            # the determinism claim FAILED; automation gating on the
            # exit code must see it
            print("FAIL: state vs forensic snapshot MISMATCH: "
                  + ", ".join(report["state_mismatches"]), file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

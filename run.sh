#!/usr/bin/env bash
# Batch driver: run every algorithm on the paper multi-DC config, then plot.
# Working counterpart of the reference's run.sh/multi_dc.bat (whose algo
# names were stale vs its own CLI — SURVEY.md §7.4.5); this one is generated
# from the actual `run_sim.py --algo` choices.
set -euo pipefail

DURATION="${DURATION:-3600}"
LOG_INTERVAL="${LOG_INTERVAL:-20}"
OUT_ROOT="${OUT_ROOT:-runs}"
INF_MODE="${INF_MODE:-sinusoid}"; INF_RATE="${INF_RATE:-6.0}"
TRN_MODE="${TRN_MODE:-poisson}";  TRN_RATE="${TRN_RATE:-0.02}"
ALGOS="${ALGOS:-default_policy cap_uniform cap_greedy joint_nf bandit carbon_cost eco_route chsac_af}"

mkdir -p "$OUT_ROOT"
for algo in $ALGOS; do
    out="$OUT_ROOT/$algo"
    echo "=== $algo -> $out"
    extra=""
    case "$algo" in
        cap_uniform|cap_greedy) extra="--power-cap ${POWER_CAP:-150000}" ;;
        chsac_af) extra="--ckpt-dir $out/ckpt" ;;
    esac
    python run_sim.py --algo "$algo" --duration "$DURATION" \
        --log-interval "$LOG_INTERVAL" \
        --inf-mode "$INF_MODE" --inf-rate "$INF_RATE" \
        --trn-mode "$TRN_MODE" --trn-rate "$TRN_RATE" \
        --out "$out" --quiet $extra
done

./plot.sh "$OUT_ROOT"

"""CLI entry: run one simulation (any of the nine algorithms) and write CSVs.

Flag-for-flag counterpart of the reference CLI
(`/root/reference/run_sim_paper.py:11-114`), with the deliberate fixes noted
in SURVEY.md §7.4: `--elastic-scaling` is a real store_true flag (the
reference's `type=bool` version could never be enabled), and
`--control-interval` is honored by being the log/control tick (the reference
parsed it but never scheduled it).  `--upgr-device` is gone: device placement
is JAX's job (the policy runs on whatever `jax.devices()` offers).

Extra flags beyond the reference: `--rollouts N` vmaps N independent worlds
and streams CSVs from rollout 0 (the others feed the RL replay), and
`--chunk-steps` sizes the scan chunk.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The axon TPU plugin overrides JAX_PLATFORMS via jax.config at sitecustomize
# time; honor an explicit cpu request from the environment anyway.
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    import jax

    jax.config.update("jax_platforms", "cpu")



def parse_args(argv=None):
    p = argparse.ArgumentParser(description="TPU-native geo-DC DVFS/scheduling simulator")
    p.add_argument("--algo", default="default_policy",
                   choices=["default_policy", "cap_uniform", "cap_greedy", "joint_nf",
                            "bandit", "carbon_cost", "eco_route", "chsac_af", "debug",
                            "ppo"])
    p.add_argument("--duration", type=float, default=3600.0, help="simulated seconds")
    p.add_argument("--log-interval", type=float, default=20.0)
    p.add_argument("--out", default="runs/out", help="output dir for CSV logs")
    p.add_argument("--seed", type=int, default=123)
    # arrivals
    p.add_argument("--inf-mode", default="sinusoid", choices=["off", "poisson", "sinusoid"])
    p.add_argument("--inf-rate", type=float, default=6.0)
    p.add_argument("--inf-amp", type=float, default=0.6)
    p.add_argument("--inf-period", type=float, default=300.0)
    p.add_argument("--trn-mode", default="poisson", choices=["off", "poisson", "sinusoid"])
    p.add_argument("--trn-rate", type=float, default=0.3)
    p.add_argument("--workload", default=None, metavar="PRESET|SPEC.json",
                   help="workload scenario (workload/ subsystem, "
                        "docs/workloads.md): a preset name "
                        "(flash_crowd, diurnal_flash_week, legacy_signals) "
                        "or a JSON spec file (lint with "
                        "scripts/validate_workload.py).  Overrides the "
                        "--inf-*/--trn-* synthetic fields; adds "
                        "time-varying price/carbon columns to cluster_log "
                        "when the spec declares signal timelines")
    p.add_argument("--workload-observe", action="store_true",
                   help="extend the RL observation vector with the "
                        "workload's sampled price + per-DC carbon "
                        "signals (chsac_af/ppo)")
    # allocation policy
    p.add_argument("--policy", default="energy_aware", choices=["energy_aware", "perf_first"])
    p.add_argument("--max-gpus-per-job", type=int, default=8)
    p.add_argument("--no-inf-priority", action="store_true")
    p.add_argument("--reserve-inf-gpus", type=int, default=0,
                   help="per-DC GPUs training jobs may never occupy")
    p.add_argument("--dvfs-low", type=float, default=0.6)
    p.add_argument("--dvfs-high", type=float, default=1.0)
    # controllers
    p.add_argument("--power-cap", type=float, default=0.0, help="W; 0 disables")
    p.add_argument("--control-interval", type=float, default=0.0,
                   help="s; 0 -> use --log-interval (reference behavior)")
    p.add_argument("--eco-objective", default="energy", choices=["energy", "carbon", "cost"])
    p.add_argument("--router-weights", default=None, metavar="LAT,EN,CO2,USD,Q",
                   help="5 comma-separated weights (latency_s, energy_j, "
                        "carbon_g, cost_usd, queue_len): route arrivals by "
                        "the weighted DC score instead of uniform-random "
                        "(non-RL, non-eco_route algorithms; the reference's "
                        "RouterPolicy made live)")
    # debug algo
    p.add_argument("--num_fixed_gpus", type=int, default=1)
    p.add_argument("--fixed_freq", type=float, default=None)
    # RL / constraints
    p.add_argument("--elastic-scaling", action="store_true")
    p.add_argument("--sla_p99_ms", type=float, default=500.0)
    p.add_argument("--energy_budget_j", type=float, default=None)
    p.add_argument("--power-cap-constraint", type=float, default=None,
                   help="power constraint target for the CMDP (defaults to --power-cap)")
    p.add_argument("--rl-buffer", type=int, default=200_000)
    p.add_argument("--rl-batch", type=int, default=256)
    p.add_argument("--rl-warmup", type=int, default=1_000)
    p.add_argument("--rl-energy-weight", type=float, default=1.0,
                   help="weight on the reward's energy term (1.0 = the "
                        "reference reward; >1 steers chsac_af toward "
                        "energy at the cost of throughput)")
    p.add_argument("--critic-arch", default="onehot",
                   choices=["onehot", "heads"],
                   help="onehot = reference-shaped critic (one-hot action "
                        "input); heads = per-joint-action output heads "
                        "(~14x cheaper exact marginalization)")
    p.add_argument("--offline-dataset", default=None, metavar="NPZ",
                   help="pretrain the chsac_af agent from an offline npz "
                        "dataset (reference schema; build one with "
                        "`python -m distributed_cluster_gpus_tpu.rl.offline`) "
                        "before the online run")
    p.add_argument("--offline-steps", type=int, default=5_000,
                   help="SAC updates for --offline-dataset pretraining")
    # fault injection (fault/ subsystem, docs/faults.md)
    p.add_argument("--fault-outage", action="append", default=[],
                   metavar="DC:START:END",
                   help="declarative DC outage window (repeatable); DC is "
                        "a fleet name or index, times in simulated seconds")
    p.add_argument("--fault-derate", action="append", default=[],
                   metavar="DC:START:END:FCAP",
                   help="straggler window: clamp the DC's DVFS ladder to "
                        "the level nearest FCAP (repeatable)")
    p.add_argument("--fault-wan", action="append", default=[],
                   metavar="ING:DC:START:END:MULT[:LOSS]",
                   help="WAN edge degradation window: multiply the "
                        "(ingress, DC) latency/transfer by MULT, plus an "
                        "optional packet-loss fraction folded in as "
                        "1/(1-LOSS) retransmits (repeatable)")
    p.add_argument("--fault-mtbf", type=float, default=0.0,
                   help="s; > 0 enables stochastic per-DC outages with "
                        "this mean time between failures")
    p.add_argument("--fault-mttr", type=float, default=300.0,
                   help="s; mean time to repair for stochastic outages")
    p.add_argument("--fault-max-outages", type=int, default=4,
                   help="stochastic outage windows drawn per DC")
    # chaos curricula (fault/curriculum.py, docs/faults.md)
    p.add_argument("--chaos", default=None, metavar="PRESET|SPEC.json",
                   help="randomized chaos curriculum: a preset name "
                        "(fault.CHAOS_PRESETS, e.g. mixed_ramp, "
                        "gentle_outages, wan_storm, held_out_*) or a JSON "
                        "spec file (lint with scripts/validate_chaos.py). "
                        "Per-lane MTBF/MTTR/derate/WAN distributions are "
                        "drawn from the rollout's fault key and lowered "
                        "into the same timeline the --fault-* windows "
                        "compile to; window budgets auto-size to "
                        "--duration")
    p.add_argument("--chaos-stage", type=int, default=0,
                   help="severity stage of the curriculum to run (0-based;"
                        " the campaign driver ramps through all stages)")
    # self-healing training campaign (rl/campaign.py)
    p.add_argument("--campaign", action="store_true",
                   help="chsac_af: train through the chaos curriculum's "
                        "severity stages with the obs watchdog as the "
                        "abort gate — a tripped segment rolls back to "
                        "the last healthy checkpoint and retries with a "
                        "reseeded curriculum (bounded by "
                        "--campaign-retries); implies --obs "
                        "--obs-watchdog raise and defaults --chaos to "
                        "the canonical mixed_ramp curriculum")
    p.add_argument("--campaign-retries", type=int, default=2,
                   help="total extra attempts across the campaign (with "
                        "--population: the PER-MEMBER quarantine budget)")
    p.add_argument("--campaign-backoff", type=float, default=1.0,
                   help="s; base host backoff before a retry (doubles)")
    # population-based chaos training (rl/population.py)
    p.add_argument("--population", type=int, default=0, metavar="N",
                   help="chsac_af: train an N-member population through "
                        "the chaos curriculum instead of one serial "
                        "campaign — per-member fault isolation (a "
                        "watchdog/divergence trip quarantines only the "
                        "tripping member), PBT exploit/explore on "
                        "held-out chaos metrics at every stage boundary, "
                        "atomic population_manifest.json resume, and a "
                        "population_summary.json leaderboard under --out; "
                        "implies --obs --obs-watchdog raise and the "
                        "canonical --chaos curriculum like --campaign")
    p.add_argument("--pbt-quantile", type=float, default=0.25,
                   help="bottom score quantile grafted from the "
                        "leaderboard winner at each PBT interval "
                        "(0 disables exploit/explore)")
    p.add_argument("--pbt-perturb", type=float, default=0.0,
                   help="log-normal sigma for lr/alpha hyperparameter "
                        "jitter across members (0 = members differ only "
                        "by curriculum reseed)")
    # observability (obs/ subsystem, docs/observability.md)
    p.add_argument("--obs", action="store_true",
                   help="enable in-graph telemetry + streaming exporters: "
                        "compiles the engine with SimParams.obs_enabled "
                        "(metric counters/EMAs/histograms + run-health "
                        "probes in the scanned step) and writes "
                        "metrics.prom, metrics.jsonl and run_summary.json "
                        "into --out next to the CSV logs")
    p.add_argument("--obs-watchdog", default="warn",
                   choices=["off", "warn", "raise"],
                   help="run-health watchdog mode: 'warn' logs new "
                        "invariant violations / capacity pressure per "
                        "chunk, 'raise' aborts the run at the chunk "
                        "boundary that tripped a HARD probe")
    p.add_argument("--obs-trace", default=None, metavar="FILE",
                   help="write a chrome-trace JSON of the host phase "
                        "spans (dispatch/rollout/io/train) to FILE — "
                        "open in Perfetto or chrome://tracing; works "
                        "for every algo including the RL trainers.  "
                        "Combined with --profile the file is rewritten "
                        "after the run as ONE merged timeline: host "
                        "phase lanes + the jax.profiler device trace "
                        "(obs.trace.merge_chrome_trace)")
    # engine shape
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint dir (chsac_af): saves + auto-resumes. "
                        "Saves commit atomically with a digest manifest "
                        "and resume walks a verified fallback chain "
                        "(docs/checkpointing.md; offline check: "
                        "scripts/fsck_ckpt.py)")
    p.add_argument("--ckpt-every", type=int, default=50, help="chunks between saves")
    p.add_argument("--ckpt-keep", type=int, default=0,
                   help="keep only the newest N verified checkpoints "
                        "(0 = keep all); stale crash-staging debris is "
                        "swept after every save either way")
    p.add_argument("--no-resume", action="store_true")
    p.add_argument("--single-dc", action="store_true", help="1-DC/1-ingress debug fleet")
    p.add_argument("--time-dtype", default="auto",
                   choices=["auto", "float32", "float64"],
                   help="simulated-clock dtype; auto promotes to float64 when "
                        "duration > 1e5 s (f32 ulp at t=6e5 is ~0.06 s — too "
                        "coarse for ms-scale inference latencies)")
    p.add_argument("--job-cap", type=int, default=512,
                   help="slab slots for concurrently PLACED jobs (in WAN "
                        "transfer / running); waiting jobs live in the "
                        "queue rings, not the slab")
    p.add_argument("--queue-cap", type=int, default=0,
                   help="per-(DC, jtype) queue-ring depth; 0 = auto-size "
                        "from duration x arrival rate so the default run "
                        "queues every arrival like the reference "
                        "(drop-free) instead of dropping on overflow")
    p.add_argument("--queue-mode", default="ring", choices=["ring", "slab"],
                   help="'ring': waiting jobs in per-DC FIFO rings (O(1) "
                        "queue ops, small slab); 'slab': pre-round-4 "
                        "layout with QUEUED rows in the slab")
    p.add_argument("--superstep-k", type=int, default=1,
                   help="events coalesced per scan step (1-16): each "
                        "iteration applies the longest commuting prefix "
                        "(up to K events) through ONE unified select-free "
                        "handler — no singleton program rides along, so "
                        "under vmap nothing executes twice (round 7); "
                        "1 = the exact legacy one-event-per-step program, "
                        "and events are applied identically across K — "
                        "bit-identical across any chunking too (the "
                        "workload compiler's pregen is chunk-invariant "
                        "since round 10). "
                        "configs.paper.SUPERSTEP_K_CANONICAL is the "
                        "measured sweet spot; fault + signal-timeline "
                        "runs are eligible since round 12, while "
                        "chsac_af/bandit/weighted-routing runs fall "
                        "back to singleton with a printed reason")
    p.add_argument("--chunk-steps", type=int, default=4096)
    p.add_argument("--rollouts", type=int, default=1,
                   help="vmapped parallel worlds (chsac_af only for now)")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run into DIR "
                        "(view with TensorBoard/xprof)")
    return p.parse_args(argv)


def resolve_time_dtype(a) -> str:
    if a.time_dtype == "auto":
        return "float64" if a.duration > 1e5 else "float32"
    return a.time_dtype


def build_params(a):
    from distributed_cluster_gpus_tpu.models import SimParams

    time_dtype = resolve_time_dtype(a)
    if time_dtype == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)
    return SimParams(
        # PPO rides the chsac_af engine hooks (act-at-arrival, transition
        # emission) with its own update — the trainer keys on them
        algo="chsac_af" if a.algo == "ppo" else a.algo,
        duration=a.duration,
        log_interval=(a.control_interval if a.control_interval > 0 else a.log_interval),
        policy_name=a.policy, max_gpus_per_job=a.max_gpus_per_job,
        inf_priority=not a.no_inf_priority,
        reserve_inf_gpus=a.reserve_inf_gpus,
        dvfs_low=a.dvfs_low, dvfs_high=a.dvfs_high,
        inf_mode=a.inf_mode, inf_rate=a.inf_rate, inf_amp=a.inf_amp,
        inf_period=a.inf_period,
        trn_mode=a.trn_mode, trn_rate=a.trn_rate,
        power_cap=a.power_cap, eco_objective=a.eco_objective,
        router_weights=(tuple(float(w) for w in a.router_weights.split(","))
                        if a.router_weights else None),
        num_fixed_gpus=a.num_fixed_gpus, fixed_freq=a.fixed_freq,
        elastic_scaling=a.elastic_scaling,
        sla_p99_ms=a.sla_p99_ms, energy_budget_j=a.energy_budget_j,
        power_cap_constraint=a.power_cap_constraint,
        rl_buffer=a.rl_buffer, rl_batch=a.rl_batch, rl_warmup=a.rl_warmup,
        rl_energy_weight=a.rl_energy_weight,
        critic_arch=a.critic_arch,
        job_cap=a.job_cap, seed=a.seed, time_dtype=time_dtype,
        queue_mode=a.queue_mode, queue_cap=max(0, a.queue_cap),
        superstep_k=a.superstep_k,
        obs_enabled=a.obs,
    )


def build_chaos_curriculum(a):
    """--chaos PRESET|SPEC.json -> ChaosCurriculum (or None)."""
    if not a.chaos:
        if a.chaos_stage:
            raise SystemExit("--chaos-stage requires --chaos")
        return None
    from distributed_cluster_gpus_tpu.fault import (
        CHAOS_PRESETS, load_chaos_json, make_chaos_preset)

    if a.chaos in CHAOS_PRESETS:
        cur = make_chaos_preset(a.chaos, duration_s=a.duration)
    elif os.path.exists(a.chaos):
        cur = load_chaos_json(a.chaos).sized_for(a.duration)
    else:
        raise SystemExit(
            f"--chaos {a.chaos!r}: not a preset "
            f"({', '.join(sorted(CHAOS_PRESETS))}) and no such spec file")
    if a.chaos_stage:
        if not 0 <= a.chaos_stage < len(cur.stages):
            raise SystemExit(
                f"--chaos-stage {a.chaos_stage} out of range: the "
                f"curriculum has {len(cur.stages)} stage(s)")
        cur = cur.at_stage(a.chaos_stage)
    return cur


def build_fault_params(a, fleet):
    """--fault-*/--chaos flags -> FaultParams (or None when none is set).

    DC/ingress tokens accept fleet names or integer indices; a chaos
    curriculum composes with declarative windows (both lower into the
    same timeline).
    """
    curriculum = build_chaos_curriculum(a)
    if not (a.fault_outage or a.fault_derate or a.fault_wan
            or a.fault_mtbf > 0 or curriculum is not None):
        return None
    from distributed_cluster_gpus_tpu.models import FaultParams

    def resolve(tok, names, what):
        if tok in names:
            return names.index(tok)
        try:
            i = int(tok)
        except ValueError:
            raise SystemExit(
                f"--fault-*: unknown {what} {tok!r}; choices: "
                f"{', '.join(names)} (or an index 0..{len(names) - 1})")
        if not 0 <= i < len(names):
            raise SystemExit(
                f"--fault-*: {what} index {i} out of range for this fleet "
                f"(0..{len(names) - 1})")
        return i

    def dc_idx(tok):
        return resolve(tok, fleet.dc_names, "DC")

    def ing_idx(tok):
        return resolve(tok, fleet.ingress_names, "ingress")

    def fields(flag, spec, want, usage):
        parts = spec.split(":")
        if len(parts) not in want:
            raise SystemExit(f"{flag} {spec!r}: expected {usage}")
        return parts

    def num(flag, spec, tok, what):
        try:
            return float(tok)
        except ValueError:
            raise SystemExit(f"{flag} {spec!r}: {what} {tok!r} is not a number")

    outages, derates, wan = [], [], []
    for spec in a.fault_outage:
        dc, s, e = fields("--fault-outage", spec, (3,), "DC:START:END")
        outages.append((dc_idx(dc), num("--fault-outage", spec, s, "START"),
                        num("--fault-outage", spec, e, "END")))
    for spec in a.fault_derate:
        dc, s, e, f_cap = fields("--fault-derate", spec, (4,),
                                 "DC:START:END:FCAP")
        derates.append((dc_idx(dc), num("--fault-derate", spec, s, "START"),
                        num("--fault-derate", spec, e, "END"),
                        num("--fault-derate", spec, f_cap, "FCAP")))
    for spec in a.fault_wan:
        parts = fields("--fault-wan", spec, (5, 6),
                       "ING:DC:START:END:MULT[:LOSS]")
        ing, dc, s, e, mult = parts[:5]
        loss = (num("--fault-wan", spec, parts[5], "LOSS")
                if len(parts) > 5 else 0.0)
        wan.append((ing_idx(ing), dc_idx(dc),
                    num("--fault-wan", spec, s, "START"),
                    num("--fault-wan", spec, e, "END"),
                    num("--fault-wan", spec, mult, "MULT"), loss))
    return FaultParams(
        outages=tuple(outages), derates=tuple(derates), wan=tuple(wan),
        mtbf_s=a.fault_mtbf, mttr_s=a.fault_mttr,
        max_outages_per_dc=a.fault_max_outages, curriculum=curriculum)


def build_workload_spec(a, fleet, params=None):
    """--workload PRESET|SPEC.json -> WorkloadSpec (or None).

    ``--workload-observe`` forces the signal timelines into the RL
    observation vector regardless of what the preset/spec declares.
    ``params`` (the already-built SimParams) feeds the presets that
    derive their arrival streams from the synthetic fields
    (legacy_signals), so --inf-*/--trn-* flags are honored.
    """
    if not a.workload:
        if a.workload_observe:
            raise SystemExit("--workload-observe requires --workload")
        return None
    from distributed_cluster_gpus_tpu.workload import (
        PRESETS, load_workload_json, make_preset)

    if a.workload in PRESETS:
        kw = {"observe": True} if a.workload_observe else {}
        if a.workload == "legacy_signals" and params is not None:
            kw["params"] = params
        return make_preset(a.workload, fleet, **kw)
    if not os.path.exists(a.workload):
        raise SystemExit(
            f"--workload {a.workload!r}: not a preset "
            f"({', '.join(sorted(PRESETS))}) and no such spec file")
    spec = load_workload_json(a.workload, fleet)
    if a.workload_observe:
        import dataclasses

        if spec.signals is None:
            raise SystemExit("--workload-observe: the spec declares no "
                             "signal timelines to observe")
        spec = dataclasses.replace(
            spec, signals=dataclasses.replace(spec.signals, observe=True))
    return spec


def finalize_queue_cap(params, fleet, rollouts: int = 1):
    """Resolve --queue-cap 0 into the drop-free auto size."""
    if params.queue_cap > 0 or params.queue_mode != "ring":
        return params
    import dataclasses

    from distributed_cluster_gpus_tpu.sim.engine import auto_queue_cap

    return dataclasses.replace(
        params, queue_cap=auto_queue_cap(params, fleet, rollouts))


def main(argv=None):
    a = parse_args(argv)
    # after argument parsing so --help/argparse errors never import jax
    from distributed_cluster_gpus_tpu.utils.jaxcache import setup_compile_cache

    setup_compile_cache()
    from distributed_cluster_gpus_tpu.configs import build_fleet, build_single_dc_fleet
    from distributed_cluster_gpus_tpu.utils.validators import validate_gpus
    from distributed_cluster_gpus_tpu.utils.logging import get_logger

    if a.population and a.campaign:
        raise SystemExit("--population and --campaign are mutually "
                         "exclusive: the population driver IS the "
                         "campaign, N-wide")
    if a.population < 0:
        raise SystemExit("--population must be >= 1 (or omitted)")
    if a.population and a.obs_trace:
        # the population driver runs N independent trainer loops; no
        # single host-phase timeline exists to render — rejecting beats
        # completing "successfully" without the requested artifact
        raise SystemExit("--obs-trace with --population is not supported "
                         "(per-member run dirs carry the per-segment "
                         "artifacts) — drop the flag")
    if a.campaign or a.population:
        # --population rides the same gating: chaos default, obs
        # implication, watchdog guards
        which = "--population" if a.population else "--campaign"
        if a.algo != "chsac_af":
            raise SystemExit(f"{which} requires --algo chsac_af (the "
                             "driver trains the CHSAC agent)")
        if not a.chaos:
            # default to the canonical training curriculum so
            # `--algo chsac_af --campaign` works out of the box
            from distributed_cluster_gpus_tpu.configs.paper import (
                CHAOS_CURRICULUM_CANONICAL)

            a.chaos = CHAOS_CURRICULUM_CANONICAL
        if a.chaos_stage:
            # the campaign ramps through EVERY stage itself; accepting
            # the flag would silently run a different experiment
            raise SystemExit(f"--chaos-stage with {which}: the "
                             "driver ramps through all curriculum "
                             "stages itself — drop the flag (or run a "
                             f"single stage without {which})")
        if a.obs_watchdog == "off":
            # the watchdog IS the campaign's abort gate; silently
            # training through invariant violations defeats the point
            raise SystemExit(f"{which} with --obs-watchdog off: the "
                             "driver's abort gate is the watchdog — "
                             "drop the flag (implies raise) or run "
                             f"without {which}")
        # --campaign implies --obs + raise (before the --obs-watchdog
        # guard below)
        a.obs = True
        if a.obs_watchdog == "warn":
            a.obs_watchdog = "raise"
    if a.obs_watchdog != "warn" and not a.obs:
        raise SystemExit("--obs-watchdog requires --obs (the watchdog reads "
                         "the in-graph probe counters telemetry carries)")
    fleet = build_single_dc_fleet() if a.single_dc else build_fleet()
    params = build_params(a)
    workload = build_workload_spec(a, fleet, params)
    if workload is not None:
        import dataclasses

        params = dataclasses.replace(params, workload=workload)
    faults = build_fault_params(a, fleet)
    if faults is not None:
        import dataclasses

        params = dataclasses.replace(params, faults=faults)
    params = finalize_queue_cap(params, fleet, max(1, a.rollouts))
    os.makedirs(a.out, exist_ok=True)
    log = get_logger(a.out)
    for w in validate_gpus(fleet, strict=False):
        print(f"[gpu-validate] {w}")
        log.warning("gpu-validate: %s", w)
    if params.superstep_k > 1:
        # eligibility is a pure function of SimParams (no Engine, no
        # device): surface a silent-singleton compile BEFORE the run
        from distributed_cluster_gpus_tpu.sim.engine import (
            static_ineligibility)

        for why in static_ineligibility(params)["superstep"]:
            msg = f"falling back to singleton: {why}"
            print(msg)
            log.warning(msg)

    import contextlib

    if a.profile:
        from distributed_cluster_gpus_tpu.obs.trace import trace

        prof_ctx = trace(a.profile)
    else:
        prof_ctx = contextlib.nullcontext()

    from distributed_cluster_gpus_tpu.utils.shutdown import graceful_shutdown

    with prof_ctx, graceful_shutdown() as shutdown:
        timer = _run(a, fleet, params, log, shutdown)
    if a.obs_trace and a.profile and timer is not None:
        # one Perfetto-loadable timeline: the host phase spans merged
        # with the device trace the profiler just flushed (stop_trace
        # ran when prof_ctx exited, so the *.trace.json.gz exists now)
        from distributed_cluster_gpus_tpu.obs.trace import (
            merge_chrome_trace)

        path = merge_chrome_trace(timer, a.profile, a.obs_trace)
        msg = f"merged host+device trace: {path}"
        print(msg)
        log.info(msg)
    if shutdown.requested:
        # artifacts are flushed and run_summary.json says "interrupted";
        # exit nonzero (128 + signum, the shell convention) so wrappers
        # and schedulers see the interruption
        msg = (f"interrupted by signal {shutdown.signum}: artifacts "
               f"flushed, exiting {shutdown.exit_code}")
        print(msg)
        log.warning(msg)
        sys.exit(shutdown.exit_code)


def _offline_pretrain(a, fleet, params):
    """Pretrained agent from ``--offline-dataset``, or None.

    Skipped when a checkpoint is about to be resumed: the restore would
    overwrite the learner state and silently discard the pretrain compute.
    """
    if not a.offline_dataset:
        return None
    if a.ckpt_dir and not a.no_resume:
        from distributed_cluster_gpus_tpu.utils.checkpoint import latest_step

        if latest_step(a.ckpt_dir, verified=True) is not None:
            if not a.quiet:
                print("skipping offline pretrain: resuming from checkpoint")
            return None
    from distributed_cluster_gpus_tpu.rl.train import make_agent, train_offline

    agent = make_agent(fleet, params)
    m = train_offline(agent, a.offline_dataset, a.offline_steps,
                      verbose=not a.quiet)
    if m is not None and not a.quiet:
        print(f"offline pretrain done: {int(agent.sac.step)} updates, "
              f"critic_loss={float(m['critic_loss']):.4f}")
    return agent


def _run(a, fleet, params, log, shutdown=None):
    t0 = time.time()
    from distributed_cluster_gpus_tpu.obs.trace import maybe_span_timer

    timer = maybe_span_timer(a.obs_trace)
    obs_cfg = None
    if a.obs:
        from distributed_cluster_gpus_tpu.obs.export import ObsConfig

        obs_cfg = ObsConfig(out_dir=a.out, watchdog=a.obs_watchdog)
    try:
        state, extra = _dispatch(a, fleet, params, timer, obs_cfg, shutdown)
    except BaseException:
        # the spans recorded so far are the most useful artifact of a
        # failed run (incl. a WatchdogError abort) — save before unwinding
        if a.obs_trace:
            timer.save_chrome_trace(a.obs_trace)
        raise

    import numpy as np

    if state is None:  # population run: per-member summaries live under
        wall = time.time() - t0  # member_*/; the leaderboard is the result
        msg = f"done{extra}; {wall:.1f}s wall -> artifacts in {a.out}"
        print(msg)
        log.info(msg)
        return timer

    n_fin = np.asarray(state.n_finished)
    wall = time.time() - t0
    fault_msg = ""
    if state.fault is not None:
        from distributed_cluster_gpus_tpu.evaluation import fault_metrics

        fm = fault_metrics(fleet, state)
        fault_msg = (f" faults: {fm['n_outages']} outages "
                     f"(avail {fm['availability']:.4f}), "
                     f"{fm['n_fault_preempted']} preempted / "
                     f"{fm['n_fault_migrated']} migrated / "
                     f"{fm['n_fault_failed']} failed;")
    obs_msg = ""
    if a.obs and state.telemetry is not None:
        from distributed_cluster_gpus_tpu.obs.health import split_counts

        rep = split_counts(np.asarray(state.telemetry.viol))
        where = (f"per-segment dirs under {a.out} (campaign_summary.json)"
                 if a.campaign else
                 f"{a.out} (metrics.prom, metrics.jsonl, run_summary.json)")
        obs_msg = (f" obs: {rep.violation_total} violations / "
                   f"{rep.pressure_total} pressure steps, exporters in "
                   f"{where};")
    if a.obs_trace:
        path = timer.save_chrome_trace(a.obs_trace)
        obs_msg += f" chrome-trace: {path};"
    msg = (f"done: t={float(state.t):.0f}s sim, {int(state.n_events)} events, "
           f"{int(n_fin[0])} inference + {int(n_fin[1])} training jobs finished, "
           f"{int(state.n_dropped)} dropped{extra};{fault_msg}{obs_msg} "
           f"{wall:.1f}s wall -> logs in {a.out}")
    print(msg)
    log.info(msg)
    return timer


def _dispatch(a, fleet, params, timer, obs_cfg, shutdown=None):
    """Run the selected algo; returns (final SimState, summary suffix)."""
    if a.population:
        from distributed_cluster_gpus_tpu.rl.campaign import DivergenceConfig
        from distributed_cluster_gpus_tpu.rl.population import (
            PopulationConfig, run_population)

        agents, report = run_population(
            fleet, params, out_dir=a.out, chunk_steps=a.chunk_steps,
            config=PopulationConfig(
                n_members=a.population,
                member_retries=a.campaign_retries,
                exploit_quantile=a.pbt_quantile,
                perturb_scale=a.pbt_perturb,
                backoff_s=a.campaign_backoff,
                watchdog=a.obs_watchdog,
                divergence=DivergenceConfig()),
            resume=not a.no_resume,
            verbose=not a.quiet, shutdown=shutdown)
        lead = report["leaderboard"]
        extra = (f", population {report['status']}: "
                 f"{a.population} members over {report['n_stages']} "
                 f"stage(s), {len(report['quarantine'])} quarantine "
                 f"event(s), winner member "
                 f"{lead[0]['member'] if lead else '-'} "
                 f"(leaderboard in {a.out}/population_summary.json)")
        # no single SimState summarizes an N-member zoo: _run prints the
        # population line on its own when state is None
        return None, extra
    if a.campaign:
        from distributed_cluster_gpus_tpu.rl.campaign import (
            CampaignConfig, run_campaign)

        state, agent, report = run_campaign(
            fleet, params, out_dir=a.out,
            ckpt_dir=a.ckpt_dir or os.path.join(a.out, "ckpt"),
            chunk_steps=a.chunk_steps,
            config=CampaignConfig(retries=a.campaign_retries,
                                  backoff_s=a.campaign_backoff,
                                  watchdog=a.obs_watchdog),
            verbose=not a.quiet, shutdown=shutdown)
        extra = (f", campaign {report['status']}: "
                 f"{len(report['attempts'])} attempt(s) over "
                 f"{report['n_stages']} stage(s), "
                 f"{report['retries_used']} retr(ies), "
                 f"{int(agent.sac.step)} train steps")
    elif a.algo == "ppo":
        from distributed_cluster_gpus_tpu.rl.train import train_ppo

        state, trainer, hist = train_ppo(
            fleet, params, n_rollouts=max(1, a.rollouts), out_dir=a.out,
            chunk_steps=a.chunk_steps, verbose=not a.quiet,
            ckpt_dir=a.ckpt_dir, ckpt_every_chunks=a.ckpt_every,
            ckpt_keep=a.ckpt_keep,
            resume=not a.no_resume, timer=timer, obs=obs_cfg,
            shutdown=shutdown)
        extra = (f", {len(hist)} ppo updates over "
                 f"{max(1, a.rollouts)} rollouts")
    elif a.algo == "chsac_af" and a.rollouts > 1:
        from distributed_cluster_gpus_tpu.rl.train import train_chsac_distributed

        pre = _offline_pretrain(a, fleet, params)
        state, trainer, hist = train_chsac_distributed(
            fleet, params, n_rollouts=a.rollouts, out_dir=a.out,
            chunk_steps=a.chunk_steps, verbose=not a.quiet,
            ckpt_dir=a.ckpt_dir, ckpt_every_chunks=a.ckpt_every,
            ckpt_keep=a.ckpt_keep,
            resume=not a.no_resume,
            init_sac=pre.sac if pre is not None else None,
            timer=timer, obs=obs_cfg, shutdown=shutdown)
        extra = f", {int(trainer.sac.step)} train steps over {a.rollouts} rollouts"
    elif a.algo == "chsac_af":
        from distributed_cluster_gpus_tpu.rl.train import train_chsac

        agent = _offline_pretrain(a, fleet, params)
        state, agent, hist = train_chsac(
            fleet, params, out_dir=a.out, chunk_steps=a.chunk_steps,
            verbose=not a.quiet, ckpt_dir=a.ckpt_dir,
            ckpt_every_chunks=a.ckpt_every, ckpt_keep=a.ckpt_keep,
            resume=not a.no_resume,
            agent=agent, timer=timer, obs=obs_cfg, shutdown=shutdown)
        extra = f", {int(agent.sac.step)} train steps"
    else:
        from distributed_cluster_gpus_tpu.sim.io import run_simulation

        state = run_simulation(fleet, params, out_dir=a.out,
                               chunk_steps=a.chunk_steps,
                               progress=not a.quiet,
                               timer=timer, obs=obs_cfg,
                               shutdown=shutdown)
        extra = ""
    return state, extra


if __name__ == "__main__":
    main()

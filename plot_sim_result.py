"""Multi-run comparison plots from cluster_log.csv / job_log.csv.

Figure-for-figure capability parity with the reference's offline analysis
suite (`/root/reference/plot_sim_result.py:398-502`): 11 figure families
comparing any number of runs —

  total_power_vs_time, cumulative_energy_vs_time, utilization_vs_time,
  queue_lengths_vs_time (+ interpolated CSV table), latency histograms and
  boxen plots per job type, energy-vs-latency scatter, total-energy bar,
  throughput_vs_time (binned completions), energy_by_load bar,
  avg_latency + throughput summary, completed_jobs_by_type bar.

Usage:
    python plot_sim_result.py --run sac=runs/chsac --run joint=runs/joint \
        --outdir figs [--bin 60] [--scaledown 1000] [--pdf]
"""

import argparse
import os
from typing import Dict, Tuple

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

try:
    import seaborn as sns

    sns.set_theme(style="whitegrid")
    HAS_SNS = True
except Exception:  # pragma: no cover
    HAS_SNS = False


def load_run(run_dir: str, readafter: float = 0.0) -> Tuple[pd.DataFrame, pd.DataFrame]:
    """Load the two run CSVs, optionally dropping rows before ``readafter``.

    ``readafter`` mirrors the reference loader's parameter of the same name
    (`/root/reference/plot_sim_result.py:10` — declared there but never
    applied; made live here, the repo's usual treatment of dead reference
    knobs): cluster rows with ``time_s < readafter`` and jobs *finishing*
    before ``readafter`` are excluded, so RL warmup does not pollute latency
    histograms and summary stats.
    """
    cl = pd.read_csv(os.path.join(run_dir, "cluster_log.csv"))
    jb = pd.read_csv(os.path.join(run_dir, "job_log.csv"))
    if readafter > 0:
        cl = cl[cl["time_s"] >= readafter].reset_index(drop=True)
        jb = jb[jb["finish_s"] >= readafter].reset_index(drop=True)
    return cl, jb


def aggregate_cluster(cl: pd.DataFrame) -> pd.DataFrame:
    """Per-timestamp system totals (power, energy, units, util, queues)."""
    g = cl.groupby("time_s")
    out = pd.DataFrame({
        "power_W": g["power_W"].sum(),
        "energy_kJ": g["energy_kJ"].sum(),
        "acc_job_unit": g["acc_job_unit"].sum(),
        "busy": g["busy"].sum(),
        "free": g["free"].sum(),
        "q_inf": g["q_inf"].sum(),
        "q_train": g["q_train"].sum(),
    })
    out["util"] = out["busy"] / (out["busy"] + out["free"]).clip(lower=1)
    return out.reset_index()


def _save(fig, outdir, name, pdf=False):
    path = os.path.join(outdir, f"{name}.{'pdf' if pdf else 'png'}")
    fig.savefig(path, dpi=130, bbox_inches="tight")
    plt.close(fig)
    print(f"wrote {path}")


def _tscale(t, scaledown):
    return t / scaledown if scaledown > 1 else t


def fig_lines(runs: Dict[str, pd.DataFrame], col, title, ylabel, outdir,
              name, scaledown, pdf, cumulative=False):
    fig, ax = plt.subplots(figsize=(9, 4.5))
    for rname, agg in runs.items():
        y = agg[col].cumsum() if cumulative else agg[col]
        ax.plot(_tscale(agg["time_s"], scaledown), y, label=rname, lw=1.2)
    ax.set_xlabel(f"time ({'ks' if scaledown > 1 else 's'})")
    ax.set_ylabel(ylabel)
    ax.set_title(title)
    ax.legend()
    _save(fig, outdir, name, pdf)


def fig_queue_lengths(runs, outdir, scaledown, pdf):
    fig, axes = plt.subplots(2, 1, figsize=(9, 7), sharex=True)
    for rname, agg in runs.items():
        axes[0].plot(_tscale(agg["time_s"], scaledown), agg["q_inf"], label=rname, lw=1.0)
        axes[1].plot(_tscale(agg["time_s"], scaledown), agg["q_train"], label=rname, lw=1.0)
    axes[0].set_ylabel("inference queue")
    axes[1].set_ylabel("training queue")
    axes[1].set_xlabel(f"time ({'ks' if scaledown > 1 else 's'})")
    axes[0].set_title("queue lengths vs time")
    axes[0].legend()
    _save(fig, outdir, "queue_lengths_vs_time", pdf)
    # interpolated comparison table on a common grid (reference writes a CSV)
    grid = None
    cols = {}
    for rname, agg in runs.items():
        t = agg["time_s"].to_numpy()
        if grid is None:
            grid = np.linspace(t.min(), t.max(), 200)
        cols[f"{rname}_q_inf"] = np.interp(grid, t, agg["q_inf"])
        cols[f"{rname}_q_train"] = np.interp(grid, t, agg["q_train"])
    pd.DataFrame({"time_s": grid, **cols}).to_csv(
        os.path.join(outdir, "queue_lengths_vs_time_table.csv"), index=False)


def fig_latency_dists(jobs: Dict[str, pd.DataFrame], outdir, pdf):
    for jtype in ("inference", "training"):
        tag = "infer" if jtype == "inference" else "train"
        sel = {r: j[j["type"] == jtype]["latency_s"] for r, j in jobs.items()}
        sel = {r: s for r, s in sel.items() if len(s)}
        if not sel:
            continue
        fig, ax = plt.subplots(figsize=(8, 4.5))
        for rname, s in sel.items():
            ax.hist(s, bins=60, alpha=0.5, label=rname, density=True)
        ax.set_xlabel("latency (s)")
        ax.set_ylabel("density")
        ax.set_title(f"{jtype} sojourn-time distribution")
        ax.legend()
        _save(fig, outdir, f"latency_hist_{tag}", pdf)

        df = pd.concat([s.to_frame().assign(run=r) for r, s in sel.items()])
        fig, ax = plt.subplots(figsize=(8, 4.5))
        if HAS_SNS:
            sns.boxenplot(data=df, x="run", y="latency_s", ax=ax)
        else:
            ax.boxplot([s.to_numpy() for s in sel.values()],
                       tick_labels=list(sel.keys()))
        ax.set_yscale("log")
        ax.set_title(f"{jtype} latency spread")
        _save(fig, outdir, f"latency_boxen_{tag}", pdf)


def fig_energy_latency_scatter(jobs, outdir, pdf):
    fig, ax = plt.subplots(figsize=(7, 5))
    for rname, jb in jobs.items():
        e = jb["E_pred"] * jb["size"] / 3.6e6  # kWh/job
        ax.scatter(jb["latency_s"], e, s=4, alpha=0.35, label=rname)
    ax.set_xscale("log")
    ax.set_yscale("log")
    ax.set_xlabel("job latency (s)")
    ax.set_ylabel("job energy (kWh)")
    ax.set_title("energy vs latency per job")
    ax.legend(markerscale=3)
    _save(fig, outdir, "energy_per_job_scatter", pdf)


def fig_total_energy_bar(runs, outdir, pdf):
    names = list(runs)
    totals = [runs[r]["energy_kJ"].iloc[-1] / 3600.0 for r in names]  # kWh
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.bar(names, totals)
    ax.set_ylabel("total energy (kWh)")
    ax.set_title("total fleet energy")
    for i, v in enumerate(totals):
        ax.text(i, v, f"{v:.1f}", ha="center", va="bottom")
    _save(fig, outdir, "total_energy_bar", pdf)


def fig_throughput(jobs, outdir, bin_s, scaledown, pdf):
    fig, ax = plt.subplots(figsize=(9, 4.5))
    for rname, jb in jobs.items():
        if not len(jb):
            continue
        t = jb["finish_s"]
        edges = np.arange(0, t.max() + bin_s, bin_s)
        counts, _ = np.histogram(t, bins=edges)
        ax.plot(_tscale(edges[:-1], scaledown), counts / bin_s, label=rname, lw=1.2)
    ax.set_xlabel(f"time ({'ks' if scaledown > 1 else 's'})")
    ax.set_ylabel("completions/s")
    ax.set_title(f"throughput (bin {bin_s}s)")
    ax.legend()
    _save(fig, outdir, "throughput_vs_time", pdf)


def fig_energy_by_load(runs, jobs, outdir, pdf):
    names = list(runs)
    vals = []
    for r in names:
        units = jobs[r]["size"].sum()
        kwh = runs[r]["energy_kJ"].iloc[-1] / 3600.0
        vals.append(kwh / max(units, 1e-9) * 1e3)
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.bar(names, vals)
    ax.set_ylabel("Wh per work unit")
    ax.set_title("energy per unit of processed load")
    _save(fig, outdir, "energy_by_load", pdf)


def fig_avg_latency_throughput(jobs, outdir, pdf):
    names = list(jobs)
    fig, axes = plt.subplots(1, 2, figsize=(10, 4))
    for jtype, ax in zip(("inference", "training"), axes):
        means = [jobs[r][jobs[r]["type"] == jtype]["latency_s"].mean() for r in names]
        ax.bar(names, means)
        ax.set_title(f"mean {jtype} latency (s)")
    _save(fig, outdir, "avg_latency_throughput", pdf)


def fig_completed_by_type(jobs, outdir, pdf):
    names = list(jobs)
    inf = [int((jobs[r]["type"] == "inference").sum()) for r in names]
    trn = [int((jobs[r]["type"] == "training").sum()) for r in names]
    x = np.arange(len(names))
    fig, ax = plt.subplots(figsize=(7, 4))
    ax.bar(x - 0.2, inf, width=0.4, label="inference")
    ax.bar(x + 0.2, trn, width=0.4, label="training")
    ax.set_xticks(x, names)
    ax.set_ylabel("completed jobs")
    ax.set_title("completed jobs by type")
    ax.legend()
    _save(fig, outdir, "completed_jobs_by_type", pdf)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--run", action="append", required=True,
                    metavar="NAME=DIR", help="repeatable")
    ap.add_argument("--outdir", default="figs")
    ap.add_argument("--bin", type=float, default=60.0, help="throughput bin (s)")
    ap.add_argument("--scaledown", type=float, default=1.0,
                    help="divide time axis (e.g. 1000 -> ks)")
    ap.add_argument("--pdf", action="store_true")
    ap.add_argument("--readafter", type=float, default=0.0,
                    help="drop cluster rows / job finishes before this sim "
                         "time (s) — excludes RL warmup from figures")
    a = ap.parse_args(argv)
    os.makedirs(a.outdir, exist_ok=True)

    runs_raw = dict(r.split("=", 1) for r in a.run)
    aggs, jobs = {}, {}
    for name, d in runs_raw.items():
        cl, jb = load_run(d, readafter=a.readafter)
        aggs[name] = aggregate_cluster(cl)
        jobs[name] = jb

    fig_lines(aggs, "power_W", "total fleet power", "W", a.outdir,
              "total_power_vs_time", a.scaledown, a.pdf)
    fig_lines(aggs, "energy_kJ", "cumulative fleet energy", "kJ", a.outdir,
              "cumulative_energy_vs_time", a.scaledown, a.pdf)
    fig_lines(aggs, "util", "fleet GPU utilization", "fraction busy", a.outdir,
              "utilization_vs_time", a.scaledown, a.pdf)
    fig_queue_lengths(aggs, a.outdir, a.scaledown, a.pdf)
    fig_latency_dists(jobs, a.outdir, a.pdf)
    fig_energy_latency_scatter(jobs, a.outdir, a.pdf)
    fig_total_energy_bar(aggs, a.outdir, a.pdf)
    fig_throughput(jobs, a.outdir, a.bin, a.scaledown, a.pdf)
    fig_energy_by_load(aggs, jobs, a.outdir, a.pdf)
    fig_avg_latency_throughput(jobs, a.outdir, a.pdf)
    fig_completed_by_type(jobs, a.outdir, a.pdf)


if __name__ == "__main__":
    main()

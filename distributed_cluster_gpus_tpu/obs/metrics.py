"""In-graph telemetry: the metric registry and the TelemetryState pytree.

The registry is STATIC — a module-level table of every metric the engine
can expose, each with a stable integer id, a kind, a unit, and a label
scheme.  A concrete run enables a subset (`build_registry`) whose order
and per-metric sizes define the layout of the flat f32 snapshot vector
the engine emits at every log tick (`Engine._obs_snapshot`).  Exporters
(`obs.export`) and the schema linter (`scripts/check_metrics_schema.py`)
consume the same table, so a metric renamed or re-id'd in one place
breaks loudly everywhere.

Everything here is compile-gated behind ``SimParams.obs_enabled``: with
the default (False) no TelemetryState exists, the engine never touches
this module inside the step, and the traced program is the exact
pre-obs program.  With obs on, updates are plain masked arithmetic
(where/one-hot adds) — no cond/switch, so the superstep's select-free
structural pin holds unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax.numpy as jnp
from flax import struct

# event-kind axis of obs_events_by_kind_total (mirrors engine EV_* order)
KIND_NAMES = ("finish", "xfer", "arrival", "log", "fault")

# allowed units — the schema linter rejects anything else
UNITS = ("steps", "events", "jobs", "gpus", "ratio", "watts", "joules",
         "seconds", "violations", "usd_per_kwh", "g_per_kwh", "usd",
         "grams")

# label schemes -> how a metric's flat size is derived from the run shape
LABEL_SCHEMES = ("none", "dc", "kind", "jtype", "dc_bin", "l", "probe")


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One metric in the static table.

    ``mid`` is the STABLE id: append-only, never reused, never reordered
    — exporters and banked artifacts key on it across versions.
    """

    mid: int
    name: str
    kind: str  # counter | gauge | ema | histogram
    unit: str
    labels: str  # one of LABEL_SCHEMES
    help: str
    fault_only: bool = False  # present only in fault-enabled programs
    signal_only: bool = False  # only when workload signal timelines are on


# ---------------------------------------------------------------------------
# THE metric table.  Append new metrics at the end with the next free id.
# ---------------------------------------------------------------------------

METRIC_TABLE: Tuple[MetricSpec, ...] = (
    MetricSpec(0, "obs_steps_total", "counter", "steps", "none",
               "scan iterations executed (superstep: iterations, not events)"),
    MetricSpec(1, "obs_events_total", "counter", "events", "none",
               "simulation events applied (== SimState.n_events)"),
    MetricSpec(2, "obs_events_by_kind_total", "counter", "events", "kind",
               "events applied per kind (finish/xfer/arrival/log/fault)"),
    MetricSpec(3, "obs_dropped_total", "counter", "jobs", "none",
               "arrivals dropped at a full slab/ring (== n_dropped)"),
    MetricSpec(4, "obs_finished_total", "counter", "jobs", "jtype",
               "completed jobs per type (== n_finished)"),
    MetricSpec(5, "obs_queue_depth_inf", "gauge", "jobs", "dc",
               "inference jobs waiting per DC"),
    MetricSpec(6, "obs_queue_depth_train", "gauge", "jobs", "dc",
               "training jobs waiting per DC"),
    MetricSpec(7, "obs_busy_gpus", "gauge", "gpus", "dc",
               "GPUs busy per DC"),
    MetricSpec(8, "obs_util", "gauge", "ratio", "dc",
               "instantaneous utilization busy/total per DC"),
    MetricSpec(9, "obs_power_w", "gauge", "watts", "dc",
               "step-entry power draw per DC (the accrual's power)"),
    MetricSpec(10, "obs_energy_j", "counter", "joules", "dc",
               "accumulated energy per DC (== SimState.dc.energy_j)"),
    MetricSpec(11, "obs_wan_inflight", "gauge", "jobs", "none",
               "jobs in WAN transfer (slab rows with status XFER)"),
    MetricSpec(12, "obs_power_ema_w", "ema", "watts", "dc",
               "per-step EMA of DC power (alpha = SimParams.obs_ema_alpha)"),
    MetricSpec(13, "obs_events_per_step_ema", "ema", "events", "none",
               "per-step EMA of events applied per scan iteration"),
    MetricSpec(14, "obs_queue_depth_hist", "histogram", "jobs", "dc_bin",
               "per-DC total queue depth, log2 bins over steps"),
    MetricSpec(15, "obs_superstep_l_hist", "histogram", "events", "l",
               "superstep applied-prefix length L per iteration (bin 0 = "
               "no-op/end-clamp step)"),
    MetricSpec(16, "obs_queue_hw", "gauge", "jobs", "dc",
               "high-water mark of per-DC total queue depth"),
    MetricSpec(17, "obs_slab_hw", "gauge", "jobs", "none",
               "high-water mark of occupied job-slab rows"),
    MetricSpec(18, "obs_slab_inuse", "gauge", "jobs", "none",
               "occupied job-slab rows (status != EMPTY)"),
    MetricSpec(19, "obs_watchdog_violations_total", "counter", "violations",
               "probe", "run-health probe trips per probe (obs.health)"),
    MetricSpec(20, "obs_fault_downtime_s", "counter", "seconds", "dc",
               "accumulated per-DC outage seconds", fault_only=True),
    MetricSpec(21, "obs_price_usd_per_kwh", "gauge", "usd_per_kwh", "none",
               "sampled energy price at the log tick (workload signal "
               "timeline)", signal_only=True),
    MetricSpec(22, "obs_carbon_g_per_kwh", "gauge", "g_per_kwh", "dc",
               "sampled per-DC carbon intensity at the log tick",
               signal_only=True),
    MetricSpec(23, "obs_energy_cost_usd_total", "counter", "usd", "dc",
               "accumulated energy cost per DC (price integral over the "
               "exact inter-event energy accrual)", signal_only=True),
    MetricSpec(24, "obs_carbon_emitted_g_total", "counter", "grams", "dc",
               "accumulated gCO2 per DC (carbon-intensity integral)",
               signal_only=True),
)


# ---------------------------------------------------------------------------
# Twin serving gauges (twin/ + scripts/twin_serve.py).  HOST-side: these
# are computed by the serving loop and exported through
# `obs.export.write_twin_metrics`, never emitted by the in-graph
# snapshot — a deliberately SEPARATE table, so appending twin gauges can
# never change the engine's snapshot width or re-key banked artifacts
# laid out by METRIC_TABLE.  Ids are contiguous within this table.
# ---------------------------------------------------------------------------

TWIN_METRIC_TABLE: Tuple[MetricSpec, ...] = (
    MetricSpec(0, "obs_twin_ingest_lag_s", "gauge", "seconds", "none",
               "trace-seconds between the ingested watermark and the "
               "warm twin clock (0 once the trace is closed/exhausted)"),
    MetricSpec(1, "obs_twin_state_age_s", "gauge", "seconds", "none",
               "wall seconds since the twin last accepted a chunk"),
    MetricSpec(2, "obs_twin_forks_served_total", "counter", "events",
               "none", "forecast queries served since the twin started"),
    MetricSpec(3, "obs_twin_fork_p95_s", "gauge", "seconds", "none",
               "p95 fork+forecast wall latency over the recent query "
               "window (the twin_latency SLO's live gauge)"),
)


@dataclasses.dataclass(frozen=True)
class RegistryEntry:
    spec: MetricSpec
    size: int
    offset: int  # start index in the flat snapshot vector


def _scheme_size(scheme: str, *, n_dc: int, n_bins: int, n_l: int,
                 n_probes: int) -> int:
    return {"none": 1, "dc": n_dc, "kind": len(KIND_NAMES), "jtype": 2,
            "dc_bin": n_dc * n_bins, "l": n_l, "probe": n_probes}[scheme]


def build_registry(*, n_dc: int, n_bins: int, superstep_k: int,
                   faults_on: bool,
                   signals_on: bool = False) -> List[RegistryEntry]:
    """The enabled metric list for one engine specialization, with the
    flat snapshot layout (offsets) exporters slice by."""
    from .health import N_PROBES

    n_l = superstep_k + 1  # L in [0, K]; bin 0 = the no-op/end-clamp step
    out, off = [], 0
    for spec in METRIC_TABLE:
        if spec.fault_only and not faults_on:
            continue
        if spec.signal_only and not signals_on:
            continue
        size = _scheme_size(spec.labels, n_dc=n_dc, n_bins=n_bins, n_l=n_l,
                            n_probes=N_PROBES)
        out.append(RegistryEntry(spec=spec, size=size, offset=off))
        off += size
    return out


def registry_for(fleet, params) -> List[RegistryEntry]:
    """The registry for one (fleet, SimParams) — the single derivation the
    engine, the RL trainers, and standalone exporters all share, so a
    sink built next to an engine always agrees on the snapshot layout."""
    return build_registry(
        n_dc=fleet.n_dc, n_bins=params.obs_qdepth_bins,
        superstep_k=params.superstep_k,
        faults_on=params.faults is not None and params.faults.enabled,
        signals_on=(params.workload is not None
                    and params.workload.signals is not None))


def registry_width(registry: List[RegistryEntry]) -> int:
    return registry[-1].offset + registry[-1].size if registry else 0


def label_values(entry: RegistryEntry, *, dc_names, n_bins: int,
                 probe_names) -> List[Tuple[Tuple[str, str], ...]]:
    """Per-element label tuples, in flat-snapshot order, for exporters."""
    s = entry.spec.labels
    if s == "none":
        return [()]
    if s == "dc":
        return [(("dc", d),) for d in dc_names]
    if s == "kind":
        return [(("kind", k),) for k in KIND_NAMES]
    if s == "jtype":
        return [(("jtype", t),) for t in ("inference", "training")]
    if s == "dc_bin":
        return [(("dc", d), ("bin", str(b)))
                for d in dc_names for b in range(n_bins)]
    if s == "l":
        return [(("l", str(i)),) for i in range(entry.size)]
    if s == "probe":
        return [(("probe", p),) for p in probe_names]
    raise ValueError(f"unknown label scheme {s!r}")


# ---------------------------------------------------------------------------
# TelemetryState — the in-graph accumulator pytree carried in SimState.
# ---------------------------------------------------------------------------

@struct.dataclass
class TelemetryState:
    """Per-rollout telemetry accumulators (only when obs_enabled).

    Everything is updated with unconditional masked arithmetic inside
    the scanned step — one-hot adds, EMAs, maxima — never inside a
    cond/switch branch, so the obs-on program stays select-free under
    the superstep and adds no branch-divergent work under vmap.
    """

    steps: jnp.ndarray  # i32 scan iterations
    events_by_kind: jnp.ndarray  # [5] i32 (EV_* order)
    ema_power: jnp.ndarray  # [n_dc] f32
    ema_events: jnp.ndarray  # f32 events applied per iteration
    hist_qdepth: jnp.ndarray  # [n_dc, B] i32 log2-binned total queue depth
    hist_l: jnp.ndarray  # [K+1] i32 applied-prefix-length distribution
    hw_qdepth: jnp.ndarray  # [n_dc] i32 queue-depth high-water mark
    hw_slab: jnp.ndarray  # i32 slab-occupancy high-water mark
    viol: jnp.ndarray  # [N_PROBES] i32 watchdog probe trips


def init_telemetry(*, n_dc: int, n_bins: int, superstep_k: int
                   ) -> TelemetryState:
    from .health import N_PROBES

    zi = lambda shape=(): jnp.zeros(shape, jnp.int32)  # noqa: E731
    return TelemetryState(
        steps=zi(), events_by_kind=zi((len(KIND_NAMES),)),
        ema_power=jnp.zeros((n_dc,), jnp.float32),
        ema_events=jnp.float32(0.0),
        hist_qdepth=zi((n_dc, n_bins)),
        hist_l=zi((superstep_k + 1,)),
        hw_qdepth=zi((n_dc,)), hw_slab=zi(),
        viol=zi((N_PROBES,)),
    )

"""obs/ — in-graph telemetry, streaming exporters, and a run-health watchdog.

Four parts (docs/observability.md):

* :mod:`obs.metrics` — the static metric registry (stable ids, units,
  label schemes) and the ``TelemetryState`` pytree carried in ``SimState``
  when ``SimParams.obs_enabled`` is set (compile-gated: the default
  program is untouched).
* :mod:`obs.health`  — in-graph invariant probes (non-finite power/energy,
  queue-ring over/underflow, job conservation) accumulated as violation
  counters, surfaced per chunk by the host-side ``Watchdog``.
* :mod:`obs.export`  — Prometheus text-format snapshots, a JSONL metric
  stream, and ``run_summary.json``, rendered off the critical path on a
  ``sim.io.AsyncLineDrain`` worker (``ObsSink``).
* :mod:`obs.trace`   — structured spans (``PhaseTimer``, absorbed from
  ``utils.profiling``) with chrome-trace JSON export for Perfetto.

Only :mod:`obs.metrics`/:mod:`obs.health` symbols are re-exported eagerly:
``models.structs`` imports ``TelemetryState`` from here at package-import
time, so this ``__init__`` must never (transitively) import the engine.
Import :mod:`obs.export` / :mod:`obs.trace` as submodules.
"""

from .health import (HARD_PROBES, N_PROBES, PRESSURE_PROBES, PROBE_NAMES,
                     DivergenceError, RunAbort, Watchdog, WatchdogError)
from .metrics import (METRIC_TABLE, MetricSpec, TelemetryState,
                      build_registry, init_telemetry, registry_for,
                      registry_width)

__all__ = [
    "HARD_PROBES", "N_PROBES", "PRESSURE_PROBES", "PROBE_NAMES",
    "Watchdog", "WatchdogError", "RunAbort", "DivergenceError",
    "METRIC_TABLE", "MetricSpec", "TelemetryState",
    "build_registry", "init_telemetry", "registry_for", "registry_width",
]

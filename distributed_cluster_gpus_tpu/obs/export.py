"""Streaming telemetry exporters: Prometheus text, JSONL, run_summary.json.

The engine emits one flat f32 snapshot row per log tick (the ``obs`` /
``obs_valid`` emission keys, layout defined by `obs.metrics.build_registry`).
`ObsSink` consumes the SAME host-side emission chunks the CSV drain gets —
the one batched ``jax.device_get`` `sim.io.run_simulation` already pays —
and renders three artifacts off the critical path on its own
`sim.io.AsyncLineDrain` worker:

* ``metrics.prom``  — Prometheus text-format snapshot of the LATEST tick,
  atomically rewritten per chunk (point a file-based scraper at it);
* ``metrics.jsonl`` — one JSON object per log tick, append-only stream;
* ``run_summary.json`` — written at finalize: the run's job/energy totals
  (exactly `evaluation._summarize`'s numbers — same code path), the final
  metric values, and the watchdog report.

Histogram metrics export per-bin gauges with a ``bin``/``l`` label (NOT
cumulative ``_bucket`` series — documented in docs/observability.md).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

import numpy as np

from ..utils.jsonio import clean_nan, dump_json_atomic
from .health import PROBE_NAMES, Watchdog, WatchdogReport, split_counts
from .metrics import RegistryEntry, label_values, registry_width

PROM_FILE = "metrics.prom"
JSONL_FILE = "metrics.jsonl"
SUMMARY_FILE = "run_summary.json"

SUMMARY_SCHEMA = "dcg.run_summary.v1"


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Host-side export configuration (the --obs-* CLI flags).

    The in-graph half is ``SimParams.obs_enabled`` — a run with exporters
    but obs_enabled=False is a configuration error (`ObsSink` raises),
    never a silent no-op.
    """

    out_dir: str
    prometheus: bool = True
    jsonl: bool = True
    summary: bool = True
    watchdog: str = "warn"  # off | warn | raise
    prefix: str = "dcg"
    #: population-campaign member label: watchdog log lines carry it and
    #: a raised WatchdogError identifies the tripping member, so the
    #: population driver quarantines one member instead of the fleet
    member: Optional[int] = None


def _prom_type(kind: str) -> str:
    return {"counter": "counter", "gauge": "gauge", "ema": "gauge",
            "histogram": "gauge"}[kind]


def superstep_fill(hist_l: np.ndarray) -> Dict[str, float]:
    """Window-fill numbers from an ``obs_superstep_l_hist`` vector.

    ``fill`` is applied events per scan iteration / K over ALL
    iterations — the SAME denominator as bench.py's sweep
    ``events_per_iteration / K`` and the ledger's derived field, so the
    three surfaces trend one number (no-op/end-clamp iterations count;
    they are ~absent in bench probes, which never reach the horizon).
    ``mean_l`` is the mean applied-prefix length over FIRED iterations
    only (bin 0 excluded) — the fused-window *quality* read, which is
    what "fill 2.9/4 at K=4" quotes on a clamp-free run.
    """
    hist = np.asarray(hist_l, dtype=np.float64)
    k = len(hist) - 1
    total = float(hist.sum())
    fired = float(hist[1:].sum())
    applied = float((np.arange(len(hist)) * hist).sum())
    return {"k": k, "iterations": total, "fired": fired,
            "mean_l": round(applied / fired, 4) if fired > 0 else 0.0,
            "fill": (round(applied / total / k, 4)
                     if total > 0 and k > 0 else 0.0)}


def derived_metrics(registry: List[RegistryEntry],
                    row: np.ndarray) -> Dict[str, float]:
    """Export-time metrics DERIVED from a snapshot row (never in-graph:
    deriving at export keeps the step program and its eqn ceilings
    untouched).  Currently: ``obs_superstep_fill``, the mean-L/K window
    fill from the cumulative ``obs_superstep_l_hist``."""
    out: Dict[str, float] = {}
    for entry in registry:
        if entry.spec.name == "obs_superstep_l_hist":
            hist = row[entry.offset:entry.offset + entry.size]
            out["obs_superstep_fill"] = superstep_fill(hist)["fill"]
    return out


def render_prometheus(registry: List[RegistryEntry], row: np.ndarray,
                      t: float, *, dc_names, n_bins: int,
                      prefix: str = "dcg") -> str:
    """One snapshot row -> Prometheus text format (HELP/TYPE + samples)."""
    out = [f"# dcg snapshot at sim t={t:.3f}s"]
    for entry in registry:
        spec = entry.spec
        name = f"{prefix}_{spec.name}"
        vals = row[entry.offset:entry.offset + entry.size]
        out.append(f"# HELP {name} {spec.help} [{spec.unit}]")
        out.append(f"# TYPE {name} {_prom_type(spec.kind)}")
        for labels, v in zip(
                label_values(entry, dc_names=dc_names, n_bins=n_bins,
                             probe_names=PROBE_NAMES), vals):
            lab = ("{" + ",".join(f'{k}="{v_}"' for k, v_ in labels) + "}"
                   if labels else "")
            fv = float(v)
            out.append(f"{name}{lab} {fv:.10g}")
    for name, v in derived_metrics(registry, row).items():
        out.append(f"# HELP {prefix}_{name} export-derived gauge "
                   "(obs.export.derived_metrics) [ratio]")
        out.append(f"# TYPE {prefix}_{name} gauge")
        out.append(f"{prefix}_{name} {float(v):.10g}")
    return "\n".join(out) + "\n"


def row_to_record(registry: List[RegistryEntry], row: np.ndarray,
                  t: float) -> Dict:
    """One snapshot row -> the JSONL record {t, <metric>: scalar|list}."""
    rec: Dict[str, object] = {"t": round(float(t), 6)}
    for entry in registry:
        vals = row[entry.offset:entry.offset + entry.size]
        if entry.size == 1:
            rec[entry.spec.name] = float(vals[0])
        else:
            rec[entry.spec.name] = [float(v) for v in vals]
    return rec


def final_metrics(registry: List[RegistryEntry],
                  row: Optional[np.ndarray]) -> Dict:
    if row is None:
        return {}
    out = {k: v for k, v in row_to_record(registry, row, 0.0).items()
           if k != "t"}
    out.update(derived_metrics(registry, row))
    return out


#: run_summary.json ``status`` values: a run either completed, was
#: deliberately aborted by a run-health gate (watchdog / divergence), or
#: was interrupted by SIGTERM/SIGINT and shut down gracefully
RUN_STATUSES = ("completed", "aborted", "interrupted")


def write_run_summary(path: str, *, algo: str, fleet, state,
                      registry: List[RegistryEntry],
                      last_row: Optional[np.ndarray],
                      report: Optional[WatchdogReport],
                      watchdog_mode: str,
                      status: str = "completed",
                      host_phases: Optional[Dict] = None) -> Dict:
    """Machine-readable end-of-run record; totals == evaluation's exactly.

    The totals dict is produced by `evaluation._summarize` itself (lazy
    import — evaluation imports sim.io at module level), so a perf gate
    diffing run_summary.json against an eval artifact can never see a
    rounding skew between the two.  ``status`` records HOW the run ended
    (:data:`RUN_STATUSES`) — campaign drivers and sweep resumers key off
    it, so an aborted/interrupted run is never mistaken for a result.

    ``host_phases`` (round 14) surfaces the host loop's per-phase wall
    seconds — dispatch / rollout / io / io_render / obs_render — as
    first-class fields, so the perf ledger can attribute wall time per
    RUN, not just per bench probe.  ``superstep`` derives the window
    fill (mean-L/K) from the final cumulative ``hist_l`` telemetry.
    """
    from ..evaluation import _summarize

    if status not in RUN_STATUSES:
        raise ValueError(f"unknown run status {status!r}; choices: "
                         f"{RUN_STATUSES}")
    totals = _summarize(algo, fleet, state).row()
    if report is None and state.telemetry is not None:
        report = split_counts(np.asarray(state.telemetry.viol))
    summary = {
        "schema": SUMMARY_SCHEMA,
        "algo": algo,
        "status": status,
        "sim_t_s": float(np.asarray(state.t)),
        "n_events": int(np.asarray(state.n_events)),
        "totals": totals,
        "watchdog": {
            "mode": watchdog_mode,
            "violations": report.violations if report else None,
            "pressure": report.pressure if report else None,
        },
        "host_phases": {k: round(float(v), 6)
                        for k, v in sorted((host_phases or {}).items())},
        "final_metrics": final_metrics(registry, last_row),
    }
    if state.telemetry is not None:
        summary["superstep"] = superstep_fill(
            np.asarray(state.telemetry.hist_l))
    dump_json_atomic(path, summary)
    return summary


def write_status_summary(out_dir: str, *, algo: str, fleet, state,
                         status: str,
                         host_phases: Optional[Dict] = None) -> str:
    """Minimal ``run_summary.json`` for runs WITHOUT an ObsSink.

    The graceful-shutdown and abort paths must leave a machine-readable
    status even when telemetry is off — same schema, empty metric
    section, watchdog fields from the state if it carries counters.
    Returns the path written.
    """
    path = os.path.join(out_dir, SUMMARY_FILE)
    write_run_summary(path, algo=algo, fleet=fleet, state=state,
                      registry=[], last_row=None, report=None,
                      watchdog_mode="off", status=status,
                      host_phases=host_phases)
    return path


def write_twin_metrics(out_dir: str, gauges: Dict[str, float],
                       prefix: str = "dcg") -> None:
    """Export the twin serving gauges through the standard paths.

    ``gauges`` maps metric names from `obs.metrics.TWIN_METRIC_TABLE`
    (the `twin.service.TwinService.gauges` dict) to values.  Writes the
    ObsSink artifacts for a HOST-side metric set: ``metrics.prom`` is
    atomically rewritten (tmp + rename — a file scraper never sees a
    torn snapshot) and ``metrics.jsonl`` gets one wall-stamped append.
    Unknown gauge names raise — the table is the schema, exactly as for
    the in-graph registry."""
    import time

    from .metrics import TWIN_METRIC_TABLE

    by_name = {s.name: s for s in TWIN_METRIC_TABLE}
    unknown = set(gauges) - set(by_name)
    if unknown:
        raise ValueError(f"unknown twin gauges {sorted(unknown)}; "
                         "declare them in obs.metrics.TWIN_METRIC_TABLE")
    wall = time.time()
    out = [f"# dcg twin gauges at wall={wall:.3f}"]
    for spec in TWIN_METRIC_TABLE:
        if spec.name not in gauges:
            continue
        name = f"{prefix}_{spec.name}"
        out.append(f"# HELP {name} {spec.help} [{spec.unit}]")
        out.append(f"# TYPE {name} {_prom_type(spec.kind)}")
        out.append(f"{name} {float(gauges[spec.name]):.10g}")
    prom_path = os.path.join(out_dir, PROM_FILE)
    tmp = prom_path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(out) + "\n")
    os.replace(tmp, prom_path)
    rec = {"wall_s": round(wall, 3)}
    rec.update({k: float(v) for k, v in sorted(gauges.items())})
    with open(os.path.join(out_dir, JSONL_FILE), "a") as f:
        f.write(json.dumps(clean_nan(rec)) + "\n")


def host_phase_seconds(timer=None, csv_render_s: Optional[float] = None,
                       obs_render_s: Optional[float] = None
                       ) -> Dict[str, float]:
    """Normalize a host loop's wall-time split for ``run_summary.json``.

    ``timer`` is the loop's `obs.trace.PhaseTimer` (dispatch / rollout /
    io / ingest / train totals); ``csv_render_s`` is the CSV drain
    worker's hidden render time and ``obs_render_s`` the exporter
    worker's — both measured off the critical path, so they are reported
    as their own fields instead of riding a timer phase.
    """
    out: Dict[str, float] = {}
    if timer is not None:
        out = {f"{name}_s": secs for name, secs in timer.totals.items()}
    if csv_render_s is not None:
        out["io_render_s"] = out.get("io_render_s", 0.0) + csv_render_s
    if obs_render_s is not None:
        out["obs_render_s"] = out.get("obs_render_s", 0.0) + obs_render_s
    return out


class ObsSink:
    """Per-run exporter pipeline + watchdog driver.

    ``submit_host(host_emissions)`` enqueues one chunk of HOST-side
    emissions (already device_get — share the CSV drain's fetch) on a
    background `AsyncLineDrain`; rendering never blocks the dispatch
    loop.  ``check(viol)`` runs the watchdog on the cumulative probe
    counters (cheap, on the critical path by design — a 'raise' watchdog
    must stop the run at the chunk that tripped).  ``finalize(state)``
    flushes the worker, writes run_summary.json, and returns the
    artifact paths.
    """

    def __init__(self, cfg: ObsConfig, registry: List[RegistryEntry], *,
                 fleet, params, algo: Optional[str] = None,
                 jsonl_watermark: Optional[int] = None):
        if not params.obs_enabled:
            raise ValueError(
                "ObsSink requires SimParams.obs_enabled=True — the engine "
                "compiled without telemetry emits no obs rows to export")
        from ..sim.io import AsyncLineDrain

        self.cfg = cfg
        self.registry = registry
        self.fleet = fleet
        self.params = params
        self.algo = algo or params.algo
        self.watchdog = Watchdog(mode=cfg.watchdog, member=cfg.member)
        self._width = registry_width(registry)
        self._last_row: Optional[np.ndarray] = None
        self._last_t = 0.0
        self.rows_exported = 0
        os.makedirs(cfg.out_dir, exist_ok=True)
        self.prom_path = os.path.join(cfg.out_dir, PROM_FILE)
        self.jsonl_path = os.path.join(cfg.out_dir, JSONL_FILE)
        self.summary_path = os.path.join(cfg.out_dir, SUMMARY_FILE)
        if cfg.jsonl:
            if jsonl_watermark is None:
                # fresh run: truncate any stale stream from a previous run
                open(self.jsonl_path, "w").close()
            else:
                # checkpoint resume: the stream keeps its pre-crash prefix
                # and appends from the restored tick — same byte-watermark
                # semantics as `sim.io.CSVWriters.truncate_to` (rows a
                # crashed run wrote past its last checkpoint re-run on
                # resume and would otherwise appear twice)
                want = int(jsonl_watermark)
                size = (os.path.getsize(self.jsonl_path)
                        if os.path.exists(self.jsonl_path) else 0)
                if size == 0:
                    open(self.jsonl_path, "a").close()
                elif 0 <= want < size:
                    os.truncate(self.jsonl_path, want)
        self._drain = AsyncLineDrain(self._render_chunk, name="obs drain")

    @classmethod
    def open(cls, cfg: ObsConfig, *, fleet, params,
             algo: Optional[str] = None, state=None,
             jsonl_watermark: Optional[int] = None) -> "ObsSink":
        """Build a sink next to an engine run (the one construction path
        `sim.io.run_simulation` and the RL trainers share).

        The registry is derived independently of any engine attribute so a
        ``params.obs_enabled=False`` misuse hits the designed configuration
        error, never an AttributeError.  When ``state`` carries telemetry
        (a restored checkpoint), the watchdog baseline is primed from its
        cumulative counters so historical trips are not re-reported as NEW.
        ``jsonl_watermark`` (the checkpoint's ``obs_jsonl`` byte offset)
        resumes ``metrics.jsonl`` instead of truncating it.
        """
        from .metrics import registry_for

        sink = cls(cfg, registry_for(fleet, params), fleet=fleet,
                   params=params, algo=algo, jsonl_watermark=jsonl_watermark)
        if state is not None and state.telemetry is not None:
            sink.watchdog.prime(np.asarray(state.telemetry.viol))
        return sink

    # -- background worker --------------------------------------------------

    def _render_chunk(self, em) -> Dict[str, int]:
        valid = np.asarray(em.get("obs_valid"))
        rows = np.asarray(em.get("obs"))
        ts = np.asarray(em.get("t"))
        idx = np.nonzero(valid)[0]
        if rows.ndim != 2 or rows.shape[1] != self._width:
            raise ValueError(
                f"obs emission width {rows.shape} does not match the "
                f"registry layout ({self._width} values)")
        if len(idx) == 0:
            return {"obs_rows": 0}
        if self.cfg.jsonl:
            with open(self.jsonl_path, "a") as f:
                for i in idx:
                    rec = row_to_record(self.registry, rows[i],
                                        float(ts[i]))
                    rec.update(derived_metrics(self.registry, rows[i]))
                    f.write(json.dumps(clean_nan(rec)) + "\n")
        self._last_row, self._last_t = rows[idx[-1]], float(ts[idx[-1]])
        if self.cfg.prometheus:
            text = render_prometheus(
                self.registry, self._last_row, self._last_t,
                dc_names=self.fleet.dc_names,
                n_bins=self.params.obs_qdepth_bins, prefix=self.cfg.prefix)
            tmp = self.prom_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, self.prom_path)
        self.rows_exported += len(idx)
        return {"obs_rows": len(idx)}

    # -- critical-path API --------------------------------------------------

    def submit_host(self, host_emissions) -> None:
        if "obs" in host_emissions:
            self._drain.submit(host_emissions)

    def check(self, viol_totals) -> WatchdogReport:
        return self.watchdog.check(viol_totals)

    def offsets(self) -> Dict[str, int]:
        """Checkpoint watermark for the JSONL stream (CSVWriters parity).

        Flushes the background worker first: rows for chunks the trainer
        has already dispatched must be ON DISK before the byte offset is
        read, or a resumed run would truncate past-checkpoint rows that
        were actually pre-checkpoint."""
        if not self.cfg.jsonl:
            return {"obs_jsonl": 0}
        self._drain.flush()
        return {"obs_jsonl": (os.path.getsize(self.jsonl_path)
                              if os.path.exists(self.jsonl_path) else 0)}

    def close(self, abort: bool = False) -> None:
        self._drain.close(abort=abort)

    def finalize(self, state, status: str = "completed",
                 host_phases: Optional[Dict] = None) -> Dict[str, str]:
        """Flush the worker and write run_summary.json; returns paths.

        ``status`` stamps how the run ended ("completed" | "aborted" |
        "interrupted").  On the abort/interrupt paths the final check
        below cannot re-raise: a tripping check already advanced the
        NEW-trip baseline before raising, so re-checking the same totals
        is quiet — finalize always flushes and always writes.
        ``host_phases`` (see :func:`host_phase_seconds`) lands in the
        summary as first-class wall-time attribution fields; the
        exporter worker's own render seconds are folded in here (the
        worker is closed by this point, so the total is final).
        """
        self._drain.close()
        host_phases = dict(host_phases or {})
        host_phases["obs_render_s"] = (host_phases.get("obs_render_s", 0.0)
                                       + self._drain.render_seconds)
        paths = {}
        if self.cfg.prometheus and os.path.exists(self.prom_path):
            paths["prometheus"] = self.prom_path
        if self.cfg.jsonl:
            paths["jsonl"] = self.jsonl_path
        if state.telemetry is not None:
            # final authoritative check on the end state (covers the last
            # chunk even when the caller never called check())
            self.check(np.asarray(state.telemetry.viol))
        if self.cfg.summary:
            write_run_summary(
                self.summary_path, algo=self.algo, fleet=self.fleet,
                state=state, registry=self.registry,
                last_row=self._last_row, report=self.watchdog.report,
                watchdog_mode=self.cfg.watchdog, status=status,
                host_phases=host_phases)
            paths["summary"] = self.summary_path
        return paths

"""Run-health watchdog: in-graph invariant probes + the host-side monitor.

`probe_step` runs INSIDE the scanned step (obs-enabled programs only): a
fixed battery of invariant checks reduced to a [N_PROBES] 0/1 increment
vector that the engine adds into ``TelemetryState.viol`` every step.
Probes are plain array comparisons — no cond, no host callback — so a
violation costs nothing until the host looks.

The host-side `Watchdog` reads the accumulated counters once per chunk
(`sim.io.run_simulation` fetches the ``viol`` leaf alongside the ``done``
read it already does) and reports NEW trips since the previous chunk.
Two severities:

* HARD probes are invariant violations — a correct engine never trips
  them on any workload.  ``mode="raise"`` raises `WatchdogError` at the
  chunk boundary; ``mode="warn"`` logs and keeps running.
* PRESSURE probes (full rings, full slab) are capacity saturation —
  legal behavior (arrivals drop, by design), but the first thing an
  operator wants to see when throughput sags.  They warn, never raise.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Dict, List, Optional, Sequence

import numpy as np

# probe indices (stable, append-only — exporters label by name)
P_NONFINITE_POWER = 0
P_NONFINITE_ENERGY = 1
P_RING_NEGATIVE = 2
P_RING_OVERFLOW = 3
P_JOB_CONSERVATION = 4
P_RING_FULL = 5
P_SLAB_FULL = 6
N_PROBES = 7

PROBE_NAMES = (
    "nonfinite_power",
    "nonfinite_energy",
    "ring_negative",
    "ring_overflow",
    "job_conservation",
    "ring_full",
    "slab_full",
)
HARD_PROBES = (P_NONFINITE_POWER, P_NONFINITE_ENERGY, P_RING_NEGATIVE,
               P_RING_OVERFLOW, P_JOB_CONSERVATION)
PRESSURE_PROBES = (P_RING_FULL, P_SLAB_FULL)


def probe_step(*, powers, energy_j, t, ring_cnt, ring_cap: int,
               arrived, placed, ring_queued, finished, dropped, failed,
               job_cap: int):
    """[N_PROBES] i32 per-step increments (1 where the probe trips).

    All arguments are device arrays from the END of the step (post every
    event/post-switch write), so the conservation ledger is closed:

        arrived == placed(slab) + queued(rings) + finished + dropped
                   + failed(fault)

    ``ring_cnt`` is the [n_dc, 2] tail-head occupancy (pass zeros for
    slab mode, where waiting jobs live in the slab and count as placed).
    Pure jnp arithmetic — importable without the engine.
    """
    import jax.numpy as jnp

    probes = [jnp.int32(0)] * N_PROBES
    probes[P_NONFINITE_POWER] = ~jnp.all(jnp.isfinite(powers))
    probes[P_NONFINITE_ENERGY] = (~jnp.all(jnp.isfinite(energy_j))
                                  | ~jnp.isfinite(t))
    probes[P_RING_NEGATIVE] = jnp.any(ring_cnt < 0)
    probes[P_RING_OVERFLOW] = jnp.any(ring_cnt > ring_cap)
    probes[P_JOB_CONSERVATION] = (
        arrived != placed + ring_queued + finished + dropped + failed)
    probes[P_RING_FULL] = jnp.any(ring_cnt == ring_cap)
    probes[P_SLAB_FULL] = placed >= job_cap
    return jnp.stack([jnp.asarray(x, jnp.int32) for x in probes])


class RunAbort(RuntimeError):
    """Deliberate run-health abort (watchdog trip / divergence probe).

    The trainer loops and ``run_simulation`` distinguish this family
    from a crash: on a RunAbort they still FLUSH the drains/exporters,
    write ``run_summary.json`` with ``status="aborted"``, and (trainers)
    save a forensic checkpoint before re-raising — an abort is a
    decision, not a failure, and its artifacts are the post-mortem.

    ``member`` labels the population-campaign member whose gate tripped
    (None outside a population run) — the population driver quarantines
    exactly that member instead of aborting the whole fleet.
    """

    member: Optional[int] = None


class WatchdogError(RunAbort):
    """A HARD invariant probe tripped and the watchdog mode is 'raise'.

    ``probes`` names the tripping probe(s) (:data:`PROBE_NAMES` entries)
    — the forensic abort context records them so a replay can assert the
    SAME probe reproduces, not just "some abort happened".
    """

    def __init__(self, msg: str, probes: Sequence[str] = (),
                 member: Optional[int] = None):
        super().__init__(msg)
        self.probes = tuple(probes)
        self.member = member


class DivergenceError(RunAbort):
    """A training-divergence probe tripped (rl/campaign.py monitors).

    ``probe`` names the tripping metric probe; ``config`` carries the
    :class:`~..rl.campaign.DivergenceConfig` thresholds in force, so the
    forensic replay re-runs the gate with identical settings.
    """

    def __init__(self, msg: str, probe: Optional[str] = None, config=None,
                 member: Optional[int] = None):
        super().__init__(msg)
        self.probe = probe
        self.config = config
        self.member = member


@dataclasses.dataclass
class WatchdogReport:
    """Totals at the last check, split by severity."""

    violations: Dict[str, int]  # hard probes only
    pressure: Dict[str, int]

    @property
    def violation_total(self) -> int:
        return sum(self.violations.values())

    @property
    def pressure_total(self) -> int:
        return sum(self.pressure.values())


def split_counts(viol_totals: Sequence[int]) -> WatchdogReport:
    v = np.asarray(viol_totals, np.int64).reshape(-1)
    if v.shape[0] != N_PROBES:
        raise ValueError(f"expected {N_PROBES} probe counters, got {v.shape}")
    return WatchdogReport(
        violations={PROBE_NAMES[i]: int(v[i]) for i in HARD_PROBES},
        pressure={PROBE_NAMES[i]: int(v[i]) for i in PRESSURE_PROBES},
    )


class Watchdog:
    """Per-chunk monitor over the accumulated probe counters.

    ``mode``: "off" (never look), "warn" (log new trips), "raise"
    (WatchdogError on any new HARD trip; pressure still only warns).
    ``log`` is any callable taking a message string (default: print to
    stderr via the package logger-style prefix).  ``member`` labels a
    population-campaign member: log lines are prefixed and a raised
    WatchdogError carries the label, so per-member accounting survives
    through the abort path.
    """

    def __init__(self, mode: str = "warn", log=None,
                 member: Optional[int] = None):
        if mode not in ("off", "warn", "raise"):
            raise ValueError(f"unknown watchdog mode {mode!r}")
        self.mode = mode
        self.member = member
        tag = "watchdog" if member is None else f"watchdog:member_{member:02d}"
        self._log = log or (lambda msg: print(f"[{tag}] {msg}",
                                              file=sys.stderr))
        self._last = np.zeros(N_PROBES, np.int64)
        self.report: Optional[WatchdogReport] = None

    def prime(self, viol_totals) -> None:
        """Set the NEW-trip baseline without reporting.

        A resumed run restores cumulative ``TelemetryState.viol`` from the
        checkpoint; without priming, the first ``check`` would re-report
        (and in 'raise' mode re-abort on) the entire restored history.
        """
        self._last = np.asarray(viol_totals, np.int64).reshape(-1).copy()

    def check(self, viol_totals) -> WatchdogReport:
        """Inspect cumulative counters; warn/raise on NEW trips."""
        totals = np.asarray(viol_totals, np.int64).reshape(-1)
        report = split_counts(totals)
        self.report = report
        if self.mode == "off":
            self._last = totals
            return report
        new = totals - self._last
        self._last = totals
        hard_new: List[str] = [
            f"{PROBE_NAMES[i]} (+{int(new[i])}, total {int(totals[i])})"
            for i in HARD_PROBES if new[i] > 0]
        press_new = [
            f"{PROBE_NAMES[i]} (+{int(new[i])} steps, total {int(totals[i])})"
            for i in PRESSURE_PROBES if new[i] > 0]
        if press_new:
            self._log("capacity pressure: " + ", ".join(press_new))
        if hard_new:
            msg = "INVARIANT VIOLATION: " + ", ".join(hard_new)
            if self.member is not None:
                msg = f"member {self.member}: {msg}"
            self._log(msg)
            if self.mode == "raise":
                raise WatchdogError(
                    msg, probes=[PROBE_NAMES[i] for i in HARD_PROBES
                                 if new[i] > 0],
                    member=self.member)
        return report

"""Structured span tracing: phase timers + chrome-trace (Perfetto) export.

Absorbed ``utils.profiling`` (whose deprecation shim was deleted in
round 10 — import from here): `PhaseTimer` keeps
its phase/summary API — every host loop in the repo (run_simulation, the
RL trainers, bench probes) times its phases through one of these — and
grows structured spans: with ``record_spans=True`` every phase exit
appends a (name, start, duration) record, exportable as chrome-trace
JSON (`save_chrome_trace`) viewable in Perfetto / chrome://tracing.

Phases double as span categories: dispatch / rollout / io / io_render /
ingest / train are the names the loops already use; anything else works.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler trace of the enclosed region."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Accumulate wall seconds per phase; device-fenced on exit.

    ``record_spans=True`` additionally stores one span per phase() exit
    for chrome-trace export.  Spans are host-side wall time (the fence
    makes a phase's span cover the device work it waited on).
    """

    def __init__(self, record_spans: bool = False):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.record_spans = record_spans
        self.spans: List[Tuple[str, float, float]] = []  # (name, t0, dur) s
        self._origin = time.perf_counter()

    @contextlib.contextmanager
    def phase(self, name: str, fence=None):
        """Time the enclosed block; ``fence`` is a zero-arg callable returning
        the array(s) to block on, evaluated at block EXIT (a bare array would
        be the stale pre-block value — the async dispatch would be attributed
        to whichever later phase happens to block first)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if fence is not None:
                jax.block_until_ready(fence() if callable(fence) else fence)
            dur = time.perf_counter() - t0
            self.totals[name] += dur
            self.counts[name] += 1
            if self.record_spans:
                self.spans.append((name, t0 - self._origin, dur))

    def add_span(self, name: str, seconds: float) -> None:
        """Record an externally-measured span (e.g. the async CSV worker's
        hidden render time) into the totals — and, when recording, as one
        synthetic span at the current time."""
        self.totals[name] += seconds
        self.counts[name] += 1
        if self.record_spans:
            # back-date the span by its duration, clamped to the trace
            # origin (a worker's accumulated time can exceed the elapsed
            # wall when it predates this timer)
            t0 = max(0.0, time.perf_counter() - self._origin - seconds)
            self.spans.append((name, t0, seconds))

    def summary(self) -> str:
        rows = sorted(self.totals.items(), key=lambda kv: -kv[1])
        total = sum(self.totals.values()) or 1.0
        return "\n".join(
            f"{name:>12s}: {secs:8.3f}s ({100 * secs / total:5.1f}%) "
            f"x{self.counts[name]}"
            for name, secs in rows)

    # -- chrome-trace export ------------------------------------------------

    def chrome_trace(self, pid: int = 0) -> Dict:
        """The spans as a chrome-trace JSON object (Perfetto-loadable).

        Phases are complete ("X") events on one host thread; io_render
        (worker-side time) is distinguished only by name — the trace is
        a phase timeline, not a thread dump.
        """
        events = [{
            "name": name, "ph": "X", "cat": "host",
            "ts": round(t0 * 1e6, 3), "dur": round(dur * 1e6, 3),
            "pid": pid, "tid": 0,
        } for name, t0, dur in self.spans]
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"source": "distributed_cluster_gpus_tpu.obs.trace"}}

    def save_chrome_trace(self, path: str, pid: int = 0) -> str:
        """Write the chrome-trace JSON; returns the path."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(pid=pid), f)
        return path


def maybe_span_timer(trace_path: Optional[str]) -> PhaseTimer:
    """A PhaseTimer that records spans iff a chrome-trace path was asked."""
    return PhaseTimer(record_spans=trace_path is not None)


def sim_progress(t: float, end: float, extra: str = "",
                 width: int = 40) -> str:
    """One-line progress string over simulated time (tqdm-style)."""
    frac = min(1.0, max(0.0, t / max(end, 1e-9)))
    filled = int(frac * width)
    bar = "#" * filled + "-" * (width - filled)
    return f"[{bar}] sim {t:,.0f}/{end:,.0f}s ({100 * frac:5.1f}%) {extra}"

"""Structured span tracing: phase timers + chrome-trace (Perfetto) export.

Absorbed ``utils.profiling`` (whose deprecation shim was deleted in
round 10 — import from here): `PhaseTimer` keeps
its phase/summary API — every host loop in the repo (run_simulation, the
RL trainers, bench probes) times its phases through one of these — and
grows structured spans: with ``record_spans=True`` every phase exit
appends a (name, start, duration) record, exportable as chrome-trace
JSON (`save_chrome_trace`) viewable in Perfetto / chrome://tracing.

Phases double as span categories: dispatch / rollout / io / io_render /
ingest / train are the names the loops already use; anything else works.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler trace of the enclosed region."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Accumulate wall seconds per phase; device-fenced on exit.

    ``record_spans=True`` additionally stores one span per phase() exit
    for chrome-trace export.  Spans are host-side wall time (the fence
    makes a phase's span cover the device work it waited on).
    """

    def __init__(self, record_spans: bool = False):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.record_spans = record_spans
        self.spans: List[Tuple[str, float, float]] = []  # (name, t0, dur) s
        self._origin = time.perf_counter()

    @contextlib.contextmanager
    def phase(self, name: str, fence=None):
        """Time the enclosed block; ``fence`` is a zero-arg callable returning
        the array(s) to block on, evaluated at block EXIT (a bare array would
        be the stale pre-block value — the async dispatch would be attributed
        to whichever later phase happens to block first)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if fence is not None:
                jax.block_until_ready(fence() if callable(fence) else fence)
            dur = time.perf_counter() - t0
            self.totals[name] += dur
            self.counts[name] += 1
            if self.record_spans:
                self.spans.append((name, t0 - self._origin, dur))

    def add_span(self, name: str, seconds: float) -> None:
        """Record an externally-measured span (e.g. the async CSV worker's
        hidden render time) into the totals — and, when recording, as one
        synthetic span at the current time."""
        self.totals[name] += seconds
        self.counts[name] += 1
        if self.record_spans:
            # back-date the span by its duration, clamped to the trace
            # origin (a worker's accumulated time can exceed the elapsed
            # wall when it predates this timer)
            t0 = max(0.0, time.perf_counter() - self._origin - seconds)
            self.spans.append((name, t0, seconds))

    def summary(self) -> str:
        rows = sorted(self.totals.items(), key=lambda kv: -kv[1])
        total = sum(self.totals.values()) or 1.0
        return "\n".join(
            f"{name:>12s}: {secs:8.3f}s ({100 * secs / total:5.1f}%) "
            f"x{self.counts[name]}"
            for name, secs in rows)

    # -- chrome-trace export ------------------------------------------------

    def chrome_trace(self, pid: int = 0) -> Dict:
        """The spans as a chrome-trace JSON object (Perfetto-loadable).

        Phases are complete ("X") events on one host thread; io_render
        (worker-side time) is distinguished only by name — the trace is
        a phase timeline, not a thread dump.
        """
        events = [{
            "name": name, "ph": "X", "cat": "host",
            "ts": round(t0 * 1e6, 3), "dur": round(dur * 1e6, 3),
            "pid": pid, "tid": 0,
        } for name, t0, dur in self.spans]
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"source": "distributed_cluster_gpus_tpu.obs.trace"}}

    def save_chrome_trace(self, path: str, pid: int = 0) -> str:
        """Write the chrome-trace JSON; returns the path."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(pid=pid), f)
        return path


def maybe_span_timer(trace_path: Optional[str]) -> PhaseTimer:
    """A PhaseTimer that records spans iff a chrome-trace path was asked."""
    return PhaseTimer(record_spans=trace_path is not None)


# ---------------------------------------------------------------------------
# unified timeline: host phase spans + jax.profiler device trace
# ---------------------------------------------------------------------------

def _newest_device_trace(profile_dir: str) -> Optional[str]:
    """The newest ``*.trace.json.gz`` under a jax.profiler log dir
    (layout: <dir>/plugins/profile/<run>/<host>.trace.json.gz)."""
    import glob

    hits = glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                     recursive=True)
    return max(hits, key=os.path.getmtime) if hits else None


def load_device_trace(profile_dir: str):
    """(traceEvents, reason): the device trace's chrome events, or
    ``([], why)`` when none is loadable — degradation, never a raise."""
    import gzip

    path = _newest_device_trace(profile_dir)
    if path is None:
        return [], f"no *.trace.json.gz under {profile_dir}"
    try:
        with gzip.open(path, "rt") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [], f"{path}: unreadable ({type(e).__name__}: {e})"
    events = (doc.get("traceEvents", doc)
              if isinstance(doc, dict) else doc)
    if not isinstance(events, list):
        return [], f"{path}: no traceEvents array"
    return events, None


def merge_chrome_trace(timer: PhaseTimer, profile_dir: Optional[str],
                       path: str, host_pid: int = 0,
                       device_pid: int = 1) -> str:
    """ONE Perfetto-loadable file: host phase spans + device trace.

    The `--obs-trace` host timeline (PhaseTimer spans) and the
    `--profile` jax.profiler device trace used to be two files in two
    tools; this merges them so a dispatch-wall investigation sees both
    lanes at once.  The two clocks are independent (the profiler stamps
    its own epoch), so each lane is zero-aligned at its own trace start
    — good enough to eyeball per-chunk dispatch vs device occupancy,
    and the caveat is recorded in ``otherData``.  A missing or corrupt
    device trace degrades to the host-only timeline with the reason
    recorded, never an error: this runs on the post-run artifact path.
    Returns the path written.
    """
    host = timer.chrome_trace(pid=host_pid)
    events = list(host["traceEvents"])
    meta = [{"name": "process_name", "ph": "M", "pid": host_pid,
             "args": {"name": "host phases (obs.trace.PhaseTimer)"}}]
    note = None
    if profile_dir:
        dev, note = load_device_trace(profile_dir)
        if dev:
            ts0 = min((e["ts"] for e in dev
                       if isinstance(e.get("ts"), (int, float))),
                      default=0.0)
            named_pids = set()
            for e in dev:
                e = dict(e)
                if isinstance(e.get("ts"), (int, float)):
                    e["ts"] = round(e["ts"] - ts0, 3)
                # keep the profiler's own pid/tid lanes, offset past the
                # host pid so the two never collide in the UI — metadata
                # events included, or a profiler process_name at pid 0
                # would relabel the host lane
                e["pid"] = device_pid + int(e.get("pid", 0) or 0)
                if e.get("ph") == "M":
                    if e.get("name") == "process_name":
                        named_pids.add(e["pid"])
                    meta.append(e)
                    continue
                events.append(e)
            for pid in sorted({e["pid"] for e in events
                               if e.get("pid", 0) >= device_pid}
                              - named_pids):
                meta.append({"name": "process_name", "ph": "M",
                             "pid": pid,
                             "args": {"name": "device (jax.profiler)"}})
    out = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "distributed_cluster_gpus_tpu.obs.trace",
            "alignment": ("host and device lanes are independently "
                          "zero-aligned at their own trace start (no "
                          "shared clock)"),
        },
    }
    if note:
        out["otherData"]["device_trace"] = note
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    # a full device trace is easily 100 MB of events; a ``.gz`` target
    # writes the (Perfetto-loadable) gzipped form instead
    if path.endswith(".gz"):
        import gzip

        with gzip.open(path, "wt") as f:
            json.dump(out, f)
    else:
        with open(path, "w") as f:
            json.dump(out, f)
    return path


def sim_progress(t: float, end: float, extra: str = "",
                 width: int = 40) -> str:
    """One-line progress string over simulated time (tqdm-style)."""
    frac = min(1.0, max(0.0, t / max(end, 1e-9)))
    filled = int(frac * width)
    bar = "#" * filled + "-" * (width - filled)
    return f"[{bar}] sim {t:,.0f}/{end:,.0f}s ({100 * frac:5.1f}%) {extra}"

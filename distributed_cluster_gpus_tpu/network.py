"""WAN topology: directed latency graph + all-pairs shortest-path precompute.

Capability parity with `/root/reference/simcore/network.py` (Ingress/Edge/
Graph.shortest_path_latency returning latency, path, bottleneck bandwidth and
summed egress cost).  The TPU-first difference: the graph is tiny (16 nodes),
so Dijkstra runs once on the host at config time and the results are embedded
as constant [n_ingress, n_dc] matrices that the jitted simulator gathers from
— no graph traversal ever happens on device.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class Ingress:
    """An entry gateway (POP/edge) where jobs arrive."""

    name: str
    region: str


@dataclass
class Edge:
    to: str
    latency_ms: float
    capacity_gbps: float = math.inf
    cost_per_gb: float = 0.0


@dataclass
class Graph:
    """Directed WAN graph keyed by node name (ingress or DC)."""

    adj: Dict[str, List[Edge]] = field(default_factory=dict)
    # lazily-built all-pairs solution; dropped whenever the graph mutates
    _apsp: Optional[tuple] = field(default=None, repr=False, compare=False)

    def add_edge(self, u: str, v: str, latency_ms: float,
                 capacity_gbps: float = math.inf, cost_per_gb: float = 0.0) -> None:
        self.adj.setdefault(u, []).append(Edge(v, latency_ms, capacity_gbps, cost_per_gb))
        self._apsp = None

    def _all_pairs(self):
        """Dense all-pairs shortest paths by latency, built in one shot.

        The WAN graph is tiny (16 nodes in the paper world), queried for
        every (ingress, DC) pair at config time, and never mutated after
        construction — so instead of a per-query heap search this solves
        the whole problem at once: adjacency is packed into dense [N, N]
        latency/capacity/cost matrices and a vectorized Floyd–Warshall
        relaxation (one `dist[:, k] + dist[k, :]` outer sum per pivot,
        strict-improvement updates) produces the distance matrix plus a
        next-hop matrix from which any path is replayed hop by hop.

        Returns (names, index, dist_ms, nxt, cap, edge_cost).
        """
        if self._apsp is not None:
            return self._apsp
        names = list(dict.fromkeys(
            [u for u in self.adj]
            + [e.to for es in self.adj.values() for e in es]))
        index = {n: i for i, n in enumerate(names)}
        n = len(names)
        lat = np.full((n, n), np.inf)
        cap = np.zeros((n, n))
        edge_cost = np.zeros((n, n))
        for u, edges in self.adj.items():
            for e in edges:
                i, j = index[u], index[e.to]
                if e.latency_ms < lat[i, j]:  # keep the best parallel edge
                    lat[i, j] = e.latency_ms
                    cap[i, j] = e.capacity_gbps
                    edge_cost[i, j] = e.cost_per_gb
        dist = lat.copy()
        np.fill_diagonal(dist, 0.0)
        # nxt[i, j] = first hop on the best known i -> j path (-1: none)
        nxt = np.where(np.isfinite(lat), np.arange(n)[None, :], -1)
        np.fill_diagonal(nxt, np.arange(n))
        for k in range(n):
            via = dist[:, k, None] + dist[None, k, :]
            better = via < dist
            dist = np.where(better, via, dist)
            nxt = np.where(better, nxt[:, k, None], nxt)
        self._apsp = (names, index, dist, nxt, cap, edge_cost)
        return self._apsp

    def shortest_path_latency(self, src: str, dst: str) -> Tuple[float, List[str], float, float]:
        """Minimum-latency route lookup against the all-pairs solution.

        Returns (latency_s, path_nodes, bottleneck_gbps, sum_cost_per_gb);
        bottleneck 0.0 means "unconstrained" (all edges infinite capacity),
        matching the reference convention
        (`/root/reference/simcore/network.py:33-62` — same contract,
        different algorithm: see `_all_pairs`).
        """
        names, index, dist, nxt, cap, edge_cost = self._all_pairs()
        s, d = index.get(src), index.get(dst)
        if s is None or d is None or not math.isfinite(dist[s, d]):
            # unreachable keeps the reference's (inf, [], 0.0, inf) shape
            return math.inf, [], 0.0, math.inf
        path, bottleneck, cost_sum = [src], math.inf, 0.0
        i = s
        while i != d:
            j = int(nxt[i, d])
            bottleneck = min(bottleneck, cap[i, j])
            cost_sum += edge_cost[i, j]
            path.append(names[j])
            i = j
        return (dist[s, d] / 1000.0, path,
                0.0 if bottleneck is math.inf else bottleneck, cost_sum)


def precompute_net_matrices(
    graph: Graph,
    ingress_names: List[str],
    dc_names: List[str],
    payload_gb: Tuple[float, float] = (0.05, 5.0),
):
    """All-pairs (ingress -> DC) network constants for the jitted engine.

    Returns a dict of numpy arrays:
      net_lat_s   [n_ing, n_dc]        propagation latency (s); inf if no path
      transfer_s  [n_ing, n_dc, 2]     lat + payload_gb[jtype]/bottleneck
      bottleneck  [n_ing, n_dc]        Gbps (0 = unconstrained)
      cost_per_gb [n_ing, n_dc]        summed egress cost along path
    """
    n_ing, n_dc = len(ingress_names), len(dc_names)
    net_lat = np.full((n_ing, n_dc), np.inf, dtype=np.float64)
    bneck = np.zeros((n_ing, n_dc), dtype=np.float64)
    cost = np.full((n_ing, n_dc), np.inf, dtype=np.float64)
    xfer = np.full((n_ing, n_dc, 2), np.inf, dtype=np.float64)
    for i, ing in enumerate(ingress_names):
        for d, dc in enumerate(dc_names):
            lat_s, path, bn, c = graph.shortest_path_latency(ing, dc)
            net_lat[i, d] = lat_s
            bneck[i, d] = bn
            cost[i, d] = c
            if math.isinf(lat_s):
                continue
            for j, gb in enumerate(payload_gb):
                extra = gb / bn if bn > 0.0 else 0.0
                xfer[i, d, j] = lat_s + extra
    return {
        "net_lat_s": net_lat,
        "transfer_s": xfer,
        "bottleneck_gbps": bneck,
        "cost_per_gb": cost,
    }


def loss_latency_multiplier(loss: float) -> float:
    """Effective latency/transfer multiplier of a lossy WAN path.

    Packet loss is folded into the fault model's single per-edge latency
    multiplier via the expected-retransmit count of a Bernoulli-loss
    channel: each unit of payload crosses the path ``1 / (1 - loss)``
    times on average, stretching both the effective propagation latency
    seen by a job and its bulk-transfer time by the same factor.  Used by
    ``fault/schedule.py`` when compiling ``FaultParams.wan`` windows.
    """
    if not 0.0 <= loss < 1.0:
        raise ValueError(f"loss must be in [0, 1), got {loss}")
    return 1.0 / (1.0 - loss)


def apply_wan_degradation(matrices: dict, mult: np.ndarray) -> dict:
    """Degraded copies of `precompute_net_matrices` output.

    ``mult`` is an ``[n_ing, n_dc]`` latency/transfer multiplier (the
    fault subsystem's ``FaultState.wan_mult`` snapshot, or a hand-built
    what-if matrix).  Host-side analysis counterpart of the engine's
    per-gather multiplication — lets routing-table consumers (e.g. a
    weighted-router sweep) score the same degraded world the simulator
    realizes.
    """
    out = dict(matrices)
    out["net_lat_s"] = matrices["net_lat_s"] * mult
    out["transfer_s"] = matrices["transfer_s"] * mult[..., None]
    return out


@dataclasses.dataclass(frozen=True)
class RouterPolicy:
    """DC-scoring weight vector for ingress routing.

    API parity with `/root/reference/simcore/router.py:4-9`, where the
    constructed policy's weights are stored but never consulted (routing is
    per-algorithm — SURVEY.md §7.4.3).  Here the weights are *live*:
    `score()` combines the per-DC factors and
    :func:`distributed_cluster_gpus_tpu.sim.algos.route_weighted` routes an
    arrival by them.
    """

    w_latency: float = 1.0
    w_energy: float = 0.0
    w_carbon: float = 0.0
    w_cost: float = 0.0
    w_queue: float = 0.0

    def score(self, latency_s, energy_j, carbon_g, cost_usd, queue_len):
        """Lower is better; inputs are per-DC arrays (numpy or jax)."""
        return (self.w_latency * latency_s + self.w_energy * energy_j
                + self.w_carbon * carbon_g + self.w_cost * cost_usd
                + self.w_queue * queue_len)

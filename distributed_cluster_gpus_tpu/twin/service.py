"""The twin's query protocol: strict-JSON requests -> strict-JSON replies.

`TwinService` wraps a warm `Twin` and answers three operations —
the protocol `scripts/twin_serve.py` speaks over request files or
stdin lines:

* ``{"op": "forecast", "policies": [...], "overlays": [{...}], "horizon_s": H}``
  -> `twin.fork.forecast` per-lane rows + deltas.  The service records
  each query's wall time; the running p95 feeds the ``obs_twin_fork_p95_s``
  gauge and the ``twin_latency`` SLO is the bench-probe version of the
  same measurement (bench.py).
* ``{"op": "status"}`` -> the ingest watermark doc plus service counters
  (forks served, ingest lag, warm-state age).
* ``{"op": "rca", "steps": [lo, hi]}`` -> incident root-cause replay on
  the twin's OWN store: the window is copied out via
  `sim.replay.copy_store_window` (the evidence is never mutated, and a
  long-lived store is not copied whole), step ``lo`` is restored, the
  twin's exact chunk program re-advances ``hi - lo`` chunks over the
  cursor's (append-only, hence superset) trace tables, and the result is
  byte-compared against stored step ``hi`` with the replay layer's
  `_tree_mismatches` rule.  ``reproduced: false`` means the history was
  not a pure function of (checkpoint, trace) — the post-mortem headline.

Every reply is ``{"ok": bool, "op": ..., ...}``; handler errors are
caught and returned as ``{"ok": false, "error": ...}`` so one bad query
can never take the resident service down.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

from .fork import Overlay, forecast

#: rolling window for the fork-latency p95 gauge
_WALL_WINDOW = 64


class TwinService:
    def __init__(self, twin):
        self.twin = twin
        self.forks_served = 0
        self._fork_walls: List[float] = []
        self.started_wall = time.time()

    # ------------------------------------------------------------------
    # gauges (obs.export.write_twin_metrics reads this)
    # ------------------------------------------------------------------

    def fork_p95_s(self) -> float:
        if not self._fork_walls:
            return float("nan")
        w = sorted(self._fork_walls[-_WALL_WINDOW:])
        return float(w[min(len(w) - 1, int(0.95 * len(w)))])

    def gauges(self) -> Dict[str, float]:
        """The twin gauge set (docs/observability.md, twin section)."""
        t = self.twin
        return {
            "obs_twin_ingest_lag_s": float(t.ingest_lag_s()),
            "obs_twin_state_age_s": float(
                max(0.0, time.time() - t.last_accept_wall)),
            "obs_twin_forks_served_total": float(self.forks_served),
            "obs_twin_fork_p95_s": self.fork_p95_s(),
        }

    # ------------------------------------------------------------------
    # the protocol
    # ------------------------------------------------------------------

    def handle(self, req: Dict) -> Dict:
        """One request dict -> one reply dict; never raises."""
        op = req.get("op") if isinstance(req, dict) else None
        try:
            if op == "forecast":
                return self._forecast(req)
            if op == "status":
                return self._status()
            if op == "rca":
                return self._rca(req)
            return {"ok": False, "op": op,
                    "error": f"unknown op {op!r}; choices: "
                             "forecast, status, rca"}
        except Exception as e:  # one bad query must not kill the twin
            return {"ok": False, "op": op,
                    "error": f"{type(e).__name__}: {e}"}

    def _forecast(self, req: Dict) -> Dict:
        policies = list(req.get("policies") or [self.twin.params.algo])
        overlays = [Overlay.from_dict(d) if isinstance(d, dict)
                    else Overlay(kind=str(d))
                    for d in (req.get("overlays") or [{}])]
        horizon_s = float(req.get("horizon_s", 3600.0))
        chunk_steps = int(req.get("chunk_steps",
                                  self.twin.chunk_steps))
        t0 = time.time()
        out = forecast(self.twin, policies, overlays, horizon_s,
                       chunk_steps=chunk_steps)
        wall = time.time() - t0
        self.forks_served += 1
        self._fork_walls.append(wall)
        del self._fork_walls[:-_WALL_WINDOW]
        return {"ok": True, "op": "forecast", "wall_s": round(wall, 6),
                "result": out}

    def _status(self) -> Dict:
        doc = self.twin.watermark_doc()
        doc.update(self.gauges())
        doc["done"] = self.twin.done
        doc["uptime_s"] = round(time.time() - self.started_wall, 3)
        return {"ok": True, "op": "status", "result": doc}

    def _rca(self, req: Dict) -> Dict:
        lo, hi = (int(x) for x in req["steps"])
        out_dir = req.get("out_dir")
        return {"ok": True, "op": "rca",
                "result": twin_rca(self.twin, lo, hi, out_dir=out_dir)}


def twin_rca(twin, lo: int, hi: int, out_dir: Optional[str] = None) -> Dict:
    """Windowed determinism replay of the twin's own history (see the
    module docstring).  Returns the replay report dict."""
    from ..sim.replay import _tree_mismatches, copy_store_window
    from ..utils.checkpoint import restore_latest, steps

    if twin.store is None:
        raise ValueError("rca needs a twin with a checkpoint store")
    committed = steps(twin.store)
    if lo not in committed or hi not in committed or not lo < hi:
        raise ValueError(
            f"rca window [{lo}, {hi}] not committed; store has steps "
            f"{committed[:3]}..{committed[-3:]}" if committed else
            f"rca window [{lo}, {hi}]: store has no committed steps")
    tmp = None
    if out_dir is None:
        tmp = out_dir = tempfile.mkdtemp(prefix="twin_rca_")
    try:
        ck = os.path.join(out_dir, "ckpt_window")
        copied = copy_store_window(twin.store, ck, lo, hi)
        like = {"state": twin.state}
        step_lo, trees = restore_latest(ck, like=like, max_step=lo)
        assert step_lo == lo
        st = trees["state"]
        # the twin's exact chunk program over the (append-only, hence
        # superset) trace tables: accepted history re-runs byte-exactly
        trace = twin.cursor.device_tables()
        run = twin._runner(trace)
        for _ in range(lo, hi):
            st = run(st, trace)
        step_hi, trees_hi = restore_latest(ck, like=like, max_step=hi)
        assert step_hi == hi
        mism = _tree_mismatches(st, trees_hi["state"])
        return {"schema": "dcg.twin_rca.v1", "steps": [lo, hi],
                "chunks_replayed": hi - lo, "copied_steps": copied,
                "reproduced": not mism, "mismatches": mism[:20],
                "t_lo": float(np.asarray(trees["state"].t)),
                "t_hi": float(np.asarray(trees_hi["state"].t))}
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)

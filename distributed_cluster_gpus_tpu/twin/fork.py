"""Forked forecasting: warm state x (policies x scenario overlays).

A what-if query forks the twin's warm SimState into N x M lanes — one
per (candidate policy, scenario overlay) — and races them ahead of real
time to ``t0 + horizon_s`` as vmapped programs, reusing sweep's
bucketing-by-program-signature (`sweep.compiler`): lanes whose SimParams
(modulo seed/faults), static-ineligibility residue, faults-enabled flag
and state leaf signature agree run as ONE ``jit(vmap(chunk))`` loop.
Engines are shared with the sweep layer's ``_ENGINE_CACHE``; overlay
specs and fault programs are cached per (base spec, overlay, window) so
a repeated query retraces nothing — the fork+forecast latency SLO
(bench.py ``twin_latency``) depends on it.

Overlays (all windowed RELATIVE to the fork time ``t0``, so the warm
past is untouched):

* ``price_spike`` — the base ``SignalSpec`` price timeline, materialized
  to the forecast horizon and scaled by ``factor`` over
  ``[t0+start_s, t0+start_s+duration_s)`` (`workload.presets
  .add_flash_crowd` windowing).
* ``blackout`` — a ``HELD_OUT_PRESETS`` chaos curriculum
  (`fault.make_chaos_preset`) lowered into a FRESH fault program
  injected into the forked state (the warm loop's exact
  ``fold_in(key, 0x0FA17)`` realization rule).
* ``flash_crowd`` — target inference streams become a ``rate_timeline``
  carrying the base rate plus a ``mult`` x window
  (`workload.presets.add_flash_crowd`).

Streams an overlay changes are re-primed at ``t0`` with draw #0 of
their dedicated chain — at ``t0 = 0`` this reproduces ``init_state``
byte-for-byte, which is what pins the fork rows to serial ``run_algo``
rows (tests/test_twin.py).  Trace streams are never re-primed (their
carries ARE the replay cursor) and ride the cursor's runtime tables;
beyond the ingest watermark a forecast sees a quiet trace — the defined
semantics of racing ahead of real time.

``chsac_af`` trains online between chunks (a learner update is not a
plain chunk loop) — the same residue as ``sweep.GRID_INEXPRESSIBLE`` —
so those lanes take the serial path: a from-scratch ``run_algo``
counterfactual over the concatenated ingested trace.

Per-lane results reuse ``evaluation._summarize`` on the sweep's
on-device-reduced summary inputs; ``delta`` is each lane's row minus
the baseline lane (the twin's own algo, no overlay), which shares the
warm prefix — so deltas isolate the forecast window's divergence.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..sweep.compiler import (GRID_INEXPRESSIBLE, _lane_view,
                              _summary_inputs)
from ..workload.presets import add_flash_crowd
from ..workload.spec import SignalSpec, StreamSpec, WorkloadSpec

#: policies the one-program fork cannot express (sweep's exact residue)
FORK_INEXPRESSIBLE = GRID_INEXPRESSIBLE

OVERLAY_KINDS = ("none", "price_spike", "blackout", "flash_crowd")


@dataclasses.dataclass(frozen=True)
class Overlay:
    """One scenario overlay, windowed relative to the fork time t0."""

    kind: str = "none"
    # price_spike
    factor: float = 3.0
    # price_spike / flash_crowd window (relative to t0)
    start_s: float = 0.0
    duration_s: float = 3600.0
    # flash_crowd
    mult: float = 10.0
    bin_s: float = 300.0
    ingress: Optional[str] = None  # None -> every applicable ingress
    # blackout
    preset: str = "held_out_regional_blackout"
    stage: int = 0

    def __post_init__(self):
        if self.kind not in OVERLAY_KINDS:
            raise ValueError(f"unknown overlay kind {self.kind!r}; "
                             f"choices: {OVERLAY_KINDS}")

    @property
    def name(self) -> str:
        if self.kind == "none":
            return "none"
        if self.kind == "price_spike":
            return f"price_spike_x{self.factor:g}_{self.duration_s:g}s"
        if self.kind == "blackout":
            return self.preset
        return f"flash_crowd_x{self.mult:g}_{self.duration_s:g}s"

    @classmethod
    def from_dict(cls, d: dict) -> "Overlay":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown overlay keys {sorted(unknown)}")
        return cls(**d)


# ---------------------------------------------------------------------------
# overlay lowering (cached: repeated queries must not retrace)
# ---------------------------------------------------------------------------

def _materialize(arr: np.ndarray, periodic: bool, need: int,
                 pad: Optional[float]) -> np.ndarray:
    """Timeline out to ``need`` bins as a NON-periodic array equal to
    the original realization on every bin before ``need``.  ``pad``
    None extends the last bin (SignalSpec semantics); a value pads with
    it (rate_timeline's end-of-timeline silence is pad=0)."""
    arr = np.asarray(arr, np.float64)
    T = arr.shape[0]
    if need <= T:
        return arr.copy()
    if periodic:
        reps = math.ceil(need / T)
        return np.tile(arr, (reps,) + (1,) * (arr.ndim - 1))[:need].copy()
    fill = np.full((need - T,) + arr.shape[1:],
                   arr[-1] if pad is None else pad, np.float64)
    return np.concatenate([arr, fill])


def overlay_spec(spec: WorkloadSpec, fleet, ov: Overlay, t0: float,
                 t_end: float) -> WorkloadSpec:
    """The overlay-applied workload spec (identity-cached per window)."""
    if ov.kind in ("none", "blackout"):
        return spec
    key = (id(spec), ov, round(float(t0), 6), round(float(t_end), 6))
    cached = _SPEC_CACHE.get(key)
    if cached is not None:
        return cached
    if ov.kind == "price_spike":
        out = _price_spike_spec(spec, ov, t0, t_end)
    else:
        out = _flash_crowd_spec(spec, fleet, ov, t0, t_end)
    _SPEC_CACHE[key] = out
    return out


_SPEC_CACHE: Dict[Tuple, WorkloadSpec] = {}
_FAULT_CACHE: Dict[Tuple, object] = {}


def _price_spike_spec(spec, ov: Overlay, t0, t_end) -> WorkloadSpec:
    sig = spec.signals
    if sig is None or sig.price is None:
        raise ValueError(
            "price_spike overlay needs a base SignalSpec with a price "
            "timeline (the twin's base spec has none)")
    need = max(1, math.ceil(t_end / sig.bin_s))
    price = _materialize(sig.price, sig.periodic, need, pad=None)
    price = add_flash_crowd(price, sig.bin_s, t0 + ov.start_s,
                            ov.duration_s, ov.factor)
    carbon = sig.carbon
    if carbon is not None and np.asarray(carbon).ndim > 1:
        carbon = _materialize(carbon, sig.periodic, need, pad=None)
    sig2 = SignalSpec(price=price, carbon=carbon, bin_s=sig.bin_s,
                      periodic=False, observe=sig.observe)
    return dataclasses.replace(spec, signals=sig2,
                               name=f"{spec.name}+{ov.name}")


def _flash_crowd_spec(spec, fleet, ov: Overlay, t0, t_end) -> WorkloadSpec:
    pairs = [list(p) for p in spec.resolve(fleet.n_ing)]
    if ov.ingress is None:
        targets = range(fleet.n_ing)
    else:
        if ov.ingress not in fleet.ingress_names:
            raise ValueError(
                f"unknown ingress {ov.ingress!r}; fleet has "
                f"{', '.join(fleet.ingress_names)}")
        targets = [fleet.ingress_names.index(ov.ingress)]
    applied = 0
    for i in targets:
        st = pairs[i][0]  # the inference stream carries the crowd
        if st.kind == "poisson":
            bin_s = ov.bin_s
            need = max(1, math.ceil(t_end / bin_s))
            rates = np.full((need,), max(0.0, st.rate), np.float64)
        elif st.kind == "rate_timeline":
            bin_s = st.bin_s
            need = max(1, math.ceil(t_end / bin_s))
            rates = _materialize(st.rates, st.periodic, need, pad=0.0)
        else:
            continue  # off / sinusoid / trace lanes are not spiked
        rates = add_flash_crowd(rates, bin_s, t0 + ov.start_s,
                                ov.duration_s, ov.mult)
        pairs[i][0] = StreamSpec(kind="rate_timeline", rates=rates,
                                 bin_s=bin_s, periodic=False)
        applied += 1
    if not applied:
        raise ValueError(
            "flash_crowd overlay found no poisson/rate_timeline "
            "inference stream to spike (trace streams are never "
            "re-primed at fork)")
    return dataclasses.replace(
        spec, streams=tuple(tuple(p) for p in pairs),
        name=f"{spec.name}+{ov.name}")


def overlay_faults(base_faults, ov: Overlay, t_end: float):
    if ov.kind != "blackout":
        return base_faults
    key = (ov, round(float(t_end), 6))
    fp = _FAULT_CACHE.get(key)
    if fp is None:
        from ..fault import make_chaos_preset
        from ..models import FaultParams

        fp = _FAULT_CACHE[key] = FaultParams(
            curriculum=make_chaos_preset(ov.preset, duration_s=t_end,
                                         stage=ov.stage))
    return fp


# ---------------------------------------------------------------------------
# fork-time state fixups
# ---------------------------------------------------------------------------

def _stream_eq(a: StreamSpec, b: StreamSpec) -> bool:
    if a is b:
        return True
    for f in dataclasses.fields(StreamSpec):
        va, vb = getattr(a, f.name), getattr(b, f.name)
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            if va is None or vb is None:
                if va is not vb:
                    return False
            elif not np.array_equal(va, vb):
                return False
        elif va != vb:
            return False
    return True


def _integrated_rate_host(st: StreamSpec, t: float) -> float:
    """Lambda(t) of a rate_timeline on the host (re-prime anchor)."""
    rates = np.asarray(st.rates, np.float64).reshape(-1)
    T = rates.shape[0]
    qc = np.concatenate([[0.0], np.cumsum(rates * st.bin_s)])
    edges = np.arange(T + 1, dtype=np.float64) * st.bin_s
    if st.periodic:
        period = T * st.bin_s
        wraps = np.floor(t / period)
        rem = t - wraps * period
        return float(wraps * qc[-1] + np.interp(rem, edges, qc))
    return float(np.interp(min(t, T * st.bin_s), edges, qc))


def _reinit_streams(state, program, changed: Sequence[int], t0: float):
    """Re-prime draw #0 of every overlay-changed stream at ``t0``.

    Byte-exact `WorkloadProgram.init_clocks` at ``t0 = 0`` (the golden
    anchor); at a warm ``t0`` the changed stream starts fresh there —
    the overlay's "this hits now" semantics."""
    import jax
    import jax.numpy as jnp

    from ..ops.arrivals import next_interarrival

    td = state.t.dtype
    na, cum = state.next_arrival, state.arr_cum
    ep, cnt = state.arr_epoch, state.arr_count
    for s in changed:
        st = program.flat[s]
        ing, jt = divmod(s, 2)
        k0 = jax.random.fold_in(jax.random.fold_in(state.arr_key, s), 0)
        if st.kind in ("off", "poisson", "sinusoid"):
            gap = next_interarrival(k0, program._arr_p(st),
                                    jnp.asarray(t0 + st.phase_s, td))
            nxt = (jnp.asarray(t0, td) + gap).astype(td)
            c = jnp.zeros((), td)
        elif st.kind == "rate_timeline":
            e0 = jax.random.exponential(k0).astype(td)
            c = jnp.asarray(_integrated_rate_host(st, t0), td) + e0
            nxt = program._invert_timeline(s, c[None])[0].astype(td)
        else:
            raise ValueError(
                f"overlay changed trace stream {s} — trace carries are "
                "the replay cursor and cannot be re-primed")
        na = na.at[ing, jt].set(nxt)
        cum = cum.at[ing, jt].set(c)
        ep = ep.at[ing, jt].set(nxt)
        cnt = cnt.at[ing, jt].set(jnp.int32(1))
    return state.replace(next_arrival=na, arr_cum=cum, arr_epoch=ep,
                         arr_count=cnt)


def _fork_lane_state(twin, eng_l, p, t0: float):
    """One lane's state: the warm state + overlay fixups (never mutates
    the warm state — SimState is an immutable pytree and every fixup is
    a functional ``replace``)."""
    import jax
    import jax.numpy as jnp

    st = twin.state
    # a blackout overlay carries its OWN fault program -> realize it
    # fresh with the warm loop's exact key rule; any other lane keeps
    # the twin's live fault state (same FaultParams object)
    if (p.faults is not None and p.faults.enabled
            and p.faults is not twin.params.faults):
        from ..fault import init_fault_state

        st = st.replace(fault=init_fault_state(
            jax.random.fold_in(st.key, 0x0FA17), p.faults,
            n_dc=twin.fleet.n_dc, n_ing=twin.fleet.n_ing,
            freq_levels=twin.fleet.freq_levels, tdtype=st.t.dtype))
    base_flat = twin.engine.workload.flat
    over_flat = eng_l.workload.flat
    changed = [s for s in range(len(base_flat))
               if not _stream_eq(base_flat[s], over_flat[s])]
    if changed:
        st = _reinit_streams(st, eng_l.workload, changed, t0)
    # the lane's horizon is t0 + horizon, not the twin's duration
    return st.replace(done=jnp.bool_(False))


# ---------------------------------------------------------------------------
# the forecast
# ---------------------------------------------------------------------------

def _lane_engine(fleet, p):
    """Engine shared through sweep's cache (same level-1 key rule)."""
    from ..sim.engine import Engine, static_ineligibility
    from ..sweep.compiler import _ENGINE_CACHE

    inel = static_ineligibility(p)
    gkey = (dataclasses.replace(p, seed=0, faults=None),
            p.faults is not None and p.faults.enabled,
            tuple(sorted(inel["superstep"])),
            tuple(sorted(inel["planner"])))
    eng = _ENGINE_CACHE.get((fleet, gkey))
    if eng is None:
        eng = _ENGINE_CACHE[(fleet, gkey)] = Engine(fleet, p)
    return eng, gkey


def _run_fork_bucket(eng, states_list, trace, chunk_steps: int,
                     max_chunks: int):
    """Stack lanes, race them to ``done`` as one vmapped program."""
    import jax
    import jax.numpy as jnp

    states = jax.tree.map(lambda *xs: jnp.stack(xs), *states_list)
    cache = getattr(eng, "_twin_fork_cache", None)
    if cache is None:
        cache = eng._twin_fork_cache = {}
    sig = tuple((tuple(leaf.shape), str(leaf.dtype))
                for leaf in jax.tree.leaves(states))
    tsig = tuple(sorted((s, t[0].shape[0], t[1] is not None)
                        for s, t in trace.items()))
    run = cache.get((sig, chunk_steps, tsig))
    if run is None:
        pregen = eng.arrival_pregen

        def chunk(st, tr):
            pre = eng.workload.tables(st, chunk_steps, inversion=pregen,
                                      trace=tr)
            step = eng._step_super if eng.superstep_on else eng._step

            def body(s_, _):
                s2, _em = step(s_, None, pre=pre)
                return s2, None

            st, _ = jax.lax.scan(body, st, None, length=chunk_steps)
            return eng.workload.advance_carries(st, pre, inversion=pregen)

        run = cache[(sig, chunk_steps, tsig)] = jax.jit(
            jax.vmap(chunk, in_axes=(0, None)))
    n = 0
    while not bool(np.asarray(states.done).all()):
        states = run(states, trace)
        n += 1
        if n >= max_chunks:
            raise RuntimeError(
                f"fork bucket: {max_chunks} chunks without draining — "
                "horizon/chunk_steps mismatch?")
    return states, n


def _delta(row: Dict, base_row: Dict) -> Dict:
    """Numeric row deltas vs the baseline lane, strict-JSON only: a
    non-finite metric (e.g. training latency with the training stream
    off -> NaN) is dropped rather than emitted as NaN, which is not
    valid JSON for a service reply."""
    out = {}
    for k, v in row.items():
        b = base_row.get(k)
        if isinstance(v, (int, float)) and isinstance(b, (int, float)) \
                and not isinstance(v, bool) \
                and math.isfinite(v) and math.isfinite(b):
            out[k] = v - b
    return out


def forecast(twin, policies: Sequence[str], overlays: Sequence[Overlay],
             horizon_s: float, chunk_steps: int = 1024,
             max_chunks: int = 10_000) -> Dict:
    """policies x overlays forked off the warm state -> per-lane rows.

    Returns a strict-JSON-able dict: ``lanes`` (policy, overlay, row,
    delta vs the baseline lane, bucket signature), ``events_forecast``
    (simulated events across forked lanes — the SLO probe's ev/s
    numerator), ``chunks``, and the window."""
    import jax

    from ..evaluation import _summarize

    fleet = twin.fleet
    t0 = float(np.asarray(twin.state.t))
    t_end = t0 + float(horizon_s)
    policies = list(policies) or [twin.params.algo]
    overlays = list(overlays) or [Overlay()]
    base_lane = (twin.params.algo, Overlay())
    lane_defs: List[Tuple[str, Overlay]] = []
    if base_lane not in [(a, o) for a in policies for o in overlays]:
        lane_defs.append(base_lane)
    lane_defs += [(a, o) for a in policies for o in overlays]

    serial_defs = [(a, o) for a, o in lane_defs
                   if a in FORK_INEXPRESSIBLE]
    vmap_defs = [(a, o) for a, o in lane_defs
                 if a not in FORK_INEXPRESSIBLE]

    # group vmapped lanes by compiled-program signature (level 1), then
    # by state leaf signature (level 2) — sweep's exact bucketing rule
    groups: Dict[Tuple, List[Tuple[str, Overlay, object, object]]] = {}
    engines: Dict[Tuple, object] = {}
    for algo, ov in vmap_defs:
        spec_l = overlay_spec(twin.cursor.spec, fleet, ov, t0, t_end)
        p = dataclasses.replace(
            twin.params, algo=algo, duration=float(t_end),
            workload=spec_l,
            faults=overlay_faults(twin.params.faults, ov, t_end))
        eng_l, gkey = _lane_engine(fleet, p)
        engines[gkey] = eng_l
        groups.setdefault(gkey, []).append((algo, ov, p, eng_l))

    trace = twin.cursor.device_tables()
    warm_events = int(np.asarray(twin.state.n_events))
    rows: Dict[Tuple[str, str], Dict] = {}
    bucket_sigs: List[str] = []
    events = 0
    chunks = 0
    for gkey, members in groups.items():
        eng_l = engines[gkey]
        lanes, sigs = [], []
        for algo, ov, p, _e in members:
            st = _fork_lane_state(twin, eng_l, p, t0)
            sig = tuple((tuple(leaf.shape), str(leaf.dtype))
                        for leaf in jax.tree.leaves(st))
            lanes.append((algo, ov, st))
            sigs.append(sig)
        by_sig: Dict[Tuple, List[Tuple[str, Overlay, object]]] = {}
        for lane, sig in zip(lanes, sigs):
            by_sig.setdefault(sig, []).append(lane)
        for bucket in by_sig.values():
            states, n = _run_fork_bucket(
                eng_l, [st for _, _, st in bucket], trace, chunk_steps,
                max_chunks)
            chunks += n
            host = jax.device_get(_summary_inputs(states))
            events += int(np.sum(host["n_events"])) \
                - warm_events * len(bucket)
            bsig = f"{bucket[0][0]}/x{len(bucket)}"
            bucket_sigs.append(bsig)
            for i, (algo, ov, _st) in enumerate(bucket):
                s = _summarize(algo, fleet, _lane_view(host, i))
                row = s.row()
                rows[(algo, ov.name)] = {"policy": algo,
                                         "overlay": ov.name,
                                         "bucket": bsig,
                                         "serial": False,
                                         "row": row}

    for algo, ov in serial_defs:
        rows[(algo, ov.name)] = {"policy": algo, "overlay": ov.name,
                                 "bucket": "serial", "serial": True,
                                 "row": _serial_forecast(twin, algo, ov,
                                                         t0, t_end)}

    base_row = rows[(base_lane[0], base_lane[1].name)]["row"]
    lanes_out = []
    for algo, ov in lane_defs:
        lane = rows[(algo, ov.name)]
        lane["delta"] = _delta(lane["row"], base_row)
        lanes_out.append(lane)
    return {"t0": t0, "horizon_s": float(horizon_s), "t_end": t_end,
            "baseline": {"policy": base_lane[0], "overlay": "none"},
            "lanes": lanes_out, "buckets": sorted(set(bucket_sigs)),
            "events_forecast": events, "chunks": chunks}


def _serial_forecast(twin, algo: str, ov: Overlay, t0: float,
                     t_end: float) -> Dict:
    """The FORK_INEXPRESSIBLE path: a from-scratch `run_algo`
    counterfactual over the concatenated ingested trace (online RL
    trains through the whole window — it cannot adopt a warm non-RL
    state mid-flight).  Slow by design; documented in docs/twin.md."""
    from ..evaluation import run_algo

    spec_c = twin.cursor.concatenated_spec()
    spec_l = overlay_spec(spec_c, twin.fleet, ov, t0, t_end)
    p = dataclasses.replace(
        twin.params, algo=algo, duration=float(t_end), workload=spec_l,
        faults=overlay_faults(twin.params.faults, ov, t_end))
    return run_algo(twin.fleet, p).row()

"""Incremental trace ingestion: append-only segments -> warm twin state.

The workload compiler's per-chunk tables normally bake a trace stream's
``(times, sizes)`` into device constants — appending an event would
retrace every chunk program.  :class:`TraceCursor` instead owns the
trace as RUNTIME arrays at a fixed power-of-two capacity (+inf-padded
times) with a dynamic ``n_valid`` bound, handed to
``WorkloadProgram.tables(trace=...)`` per chunk: appends within
capacity re-upload data but never retrace; a capacity doubling retraces
once and is amortized geometrically.

:class:`Twin` advances the warm state chunk-by-chunk with a
SPECULATIVE accept/rollback rule at the data frontier: a chunk is run
against the current (possibly still-growing) trace and accepted iff no
trace stream consumed past its ``n_valid`` bound — post-chunk
``arr_count[s] <= n_valid[s]``.  Because the engine processes events in
time order and a pending real arrival is part of event selection, an
accepted chunk gathered only real entries and left a real
``next_arrival`` carry, so it is byte-identical to the same chunk of a
batch run over the (eventually) concatenated trace.  A rejected chunk
leaves the warm state untouched — the twin has caught up to the live
trace and waits for the next segment (``close()`` lifts the bound once
the trace is known complete).

Accepted chunks checkpoint at chunk cadence through the verified store
(`utils.checkpoint`: staged payload, sha256 manifest, COMMIT marker,
fallback chain), plus an atomically-rewritten ``twin_ingest.json``
watermark at the store root (schema ``dcg.twin_ingest.v1`` — also how
``fsck_ckpt.py`` recognizes a twin store).  A SIGKILLed twin resumes
from the last verified step and replays the trace tail to byte-identical
state: every accepted chunk is a pure function of (restored state,
consumed trace prefix).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import signal as _signal
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.jsonio import dump_json_atomic
from ..workload.spec import JTYPE_NAMES, WorkloadSpec, workload_from_dict

TWIN_INGEST_FILE = "twin_ingest.json"
TWIN_INGEST_SCHEMA = "dcg.twin_ingest.v1"

#: checkpoint metadata schema stamped into each committed step
TWIN_CKPT_SCHEMA = "dcg.twin_ckpt.v1"

#: test hook (tests/test_twin.py): SIGKILL the process after this many
#: COMMITTED twin checkpoints — the sweep driver's
#: ``DCG_SWEEP_TEST_KILL_AFTER`` idiom, applied to the ingest loop.
KILL_ENV = "DCG_TWIN_TEST_KILL_AFTER"


def _capacity(n: int) -> int:
    cap = 16
    while cap < n:
        cap *= 2
    return cap


def _resolve_ingress_names(doc: dict, fleet, where: str) -> List[str]:
    """In-place ingress-name -> index resolution (load_workload_json's
    rule); returns FAIL strings instead of raising."""
    fails = []
    raw = doc.get("streams")
    if isinstance(raw, list):
        for entry in raw:
            if not isinstance(entry, dict):
                fails.append(f"{where}: stream entries must be objects")
                continue
            ing = entry.get("ingress")
            if isinstance(ing, str):
                if ing not in fleet.ingress_names:
                    fails.append(
                        f"{where}: unknown ingress {ing!r}; fleet has "
                        f"{', '.join(fleet.ingress_names)}")
                else:
                    entry["ingress"] = fleet.ingress_names.index(ing)
    return fails


class TraceCursor:
    """Append-only arrival trace, compiled to fixed-capacity tables.

    Built from the BASE spec document (segment 1 — the full
    ``docs/workloads.md`` schema: stream kinds, signals); subsequent
    segments are spec-shaped documents whose ``trace`` streams extend
    the base streams' ``times``/``sizes``.  `append` validates each
    segment (monotone times, continuation after the base's last event,
    known ingresses, size-column consistency) and applies it atomically
    — any FAIL line rejects the whole segment.
    """

    def __init__(self, fleet, base_doc: dict, where: str = "base"):
        self.fleet = fleet
        doc = dict(base_doc)
        fails = _resolve_ingress_names(doc, fleet, where)
        if fails:
            raise ValueError("; ".join(fails))
        self.spec: WorkloadSpec = workload_from_dict(doc, n_ing=fleet.n_ing)
        flat = tuple(self.spec.resolve(fleet.n_ing)[i][j]
                     for i in range(fleet.n_ing) for j in (0, 1))
        self.flat = flat
        # host-side truth per trace stream: concatenated times/sizes
        self._times: Dict[int, np.ndarray] = {}
        self._sizes: Dict[int, Optional[np.ndarray]] = {}
        for s, st in enumerate(flat):
            if st.kind == "trace":
                self._times[s] = np.asarray(st.times, np.float64).reshape(-1)
                self._sizes[s] = (
                    None if st.sizes is None
                    else np.asarray(st.sizes, np.float32).reshape(-1))
        self.segments = 1
        self.closed = False
        self._dev: Dict[int, Tuple] = {}  # s -> (times_dev, sizes_dev, cap)

    @classmethod
    def from_file(cls, path: str, fleet) -> "TraceCursor":
        import json

        with open(path) as f:
            doc = json.load(f)
        return cls(fleet, doc, where=path)

    # ------------------------------------------------------------------
    # appends
    # ------------------------------------------------------------------

    def _label(self, s: int) -> str:
        return (f"{self.fleet.ingress_names[s // 2]}/"
                f"{JTYPE_NAMES[s % 2]}")

    def validate_segment(self, seg_doc: dict,
                         where: str = "segment") -> List[str]:
        """FAIL strings for one segment document (empty == appendable)."""
        fails, _ = self._check(seg_doc, where)
        return fails

    def _check(self, seg_doc: dict, where: str):
        fails: List[str] = []
        doc = dict(seg_doc)
        if doc.get("signals") is not None:
            fails.append(f"{where}: segments must not carry signals "
                         "(the base spec owns them)")
            doc.pop("signals")
        fails += _resolve_ingress_names(doc, self.fleet, where)
        if fails:
            return fails, {}
        try:
            seg = workload_from_dict(doc, n_ing=self.fleet.n_ing)
        except (ValueError, TypeError) as e:
            return [f"{where}: {e}"], {}
        seg_flat = tuple(seg.resolve(self.fleet.n_ing)[i][j]
                         for i in range(self.fleet.n_ing) for j in (0, 1))
        updates: Dict[int, Tuple[np.ndarray, Optional[np.ndarray]]] = {}
        for s, st in enumerate(seg_flat):
            if st.kind == "off":
                continue
            lbl = f"{where}: {self._label(s)}"
            if st.kind != "trace":
                fails.append(f"{lbl}: segment stream kind {st.kind!r} "
                             "(segments may only append trace events)")
                continue
            if s not in self._times:
                fails.append(f"{lbl}: base stream is "
                             f"{self.flat[s].kind!r}, not a trace — "
                             "cannot append trace events")
                continue
            times = np.asarray(st.times, np.float64).reshape(-1)
            sizes = (None if st.sizes is None
                     else np.asarray(st.sizes, np.float32).reshape(-1))
            if times.size and np.any(np.diff(times) < 0):
                fails.append(f"{lbl}: segment times must be non-decreasing")
                continue
            base_t = self._times[s]
            if times.size and base_t.size and times[0] < base_t[-1]:
                fails.append(
                    f"{lbl}: segment first event t={times[0]:g} precedes "
                    f"the base trace's last t={base_t[-1]:g}")
                continue
            if (self._sizes[s] is None) != (sizes is None):
                fails.append(
                    f"{lbl}: size column mismatch (base "
                    f"{'has' if self._sizes[s] is not None else 'lacks'} "
                    "explicit sizes, segment "
                    f"{'lacks' if sizes is None else 'has'} them)")
                continue
            if sizes is not None and sizes.shape != times.shape:
                fails.append(f"{lbl}: {sizes.shape[0]} sizes for "
                             f"{times.shape[0]} times")
                continue
            updates[s] = (times, sizes)
        return fails, updates

    def append(self, seg_doc: dict, where: str = "segment") -> List[str]:
        """Validate + apply one segment; returns FAIL strings (empty ==
        applied).  Application is atomic: any FAIL rejects it whole."""
        if self.closed:
            return [f"{where}: trace is closed"]
        fails, updates = self._check(seg_doc, where)
        if fails:
            return fails
        for s, (times, sizes) in updates.items():
            self._times[s] = np.concatenate([self._times[s], times])
            if sizes is not None:
                self._sizes[s] = np.concatenate([self._sizes[s], sizes])
            self._dev.pop(s, None)  # re-upload (and maybe re-pad) lazily
        self.segments += 1
        return []

    def append_file(self, path: str) -> List[str]:
        import json

        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            return [f"{path}: unreadable segment: {e}"]
        return self.append(doc, where=path)

    def close(self) -> None:
        """Mark the trace complete: the speculative bound lifts and the
        twin may run past the last event (streams go quiet for good)."""
        self.closed = True

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------

    def n_valid(self) -> Dict[int, int]:
        return {s: int(t.size) for s, t in self._times.items()}

    def watermark_t(self) -> float:
        """Covered horizon: min over trace streams of the last ingested
        event time (inf when closed or no trace streams)."""
        if self.closed or not self._times:
            return float("inf")
        return float(min((t[-1] if t.size else 0.0)
                         for t in self._times.values()))

    def fingerprint(self) -> str:
        h = hashlib.sha256()
        for s in sorted(self._times):
            h.update(np.int64(s).tobytes())
            h.update(self._times[s].tobytes())
            if self._sizes[s] is not None:
                h.update(self._sizes[s].tobytes())
        return h.hexdigest()

    def device_tables(self) -> Dict[int, Tuple]:
        """{s: (times [cap] f64 dev, sizes [cap] f32 dev | None,
        n_valid i32)} — the `tables(trace=...)` override.  Capacity is
        the power-of-two pad (static shape: jit programs key on it);
        ``n_valid`` is the dynamic frontier."""
        import jax.numpy as jnp

        out = {}
        for s, times in self._times.items():
            n = times.size
            cached = self._dev.get(s)
            if cached is None:
                cap = _capacity(n)
                t_pad = np.full((cap,), np.inf, np.float64)
                t_pad[:n] = times
                sizes = self._sizes[s]
                s_dev = None
                if sizes is not None:
                    s_pad = np.zeros((cap,), np.float32)
                    s_pad[:n] = sizes
                    s_dev = jnp.asarray(s_pad)
                cached = self._dev[s] = (jnp.asarray(t_pad), s_dev, cap)
            out[s] = (cached[0], cached[1], jnp.int32(n))
        return out

    def concatenated_spec(self) -> WorkloadSpec:
        """The full ingested trace baked as a plain (batch) spec — the
        reference a batch run compiles, and the serial-path
        (chsac_af) forecast input."""
        pairs = []
        for i in range(self.fleet.n_ing):
            pair = []
            for j in (0, 1):
                s = i * 2 + j
                st = self.flat[s]
                if s in self._times:
                    st = dataclasses.replace(
                        st, times=self._times[s].copy(),
                        sizes=(None if self._sizes[s] is None
                               else self._sizes[s].copy()))
                pair.append(st)
            pairs.append(tuple(pair))
        return WorkloadSpec(streams=tuple(pairs), signals=self.spec.signals,
                            name=f"{self.spec.name}+{self.segments}seg")


class Twin:
    """The warm resident state: one engine, one live trace, one store."""

    def __init__(self, fleet, params, cursor: TraceCursor,
                 store: Optional[str] = None, chunk_steps: int = 1024,
                 ckpt_every: int = 1):
        import jax

        from ..sim.engine import Engine, init_state
        from .fork import FORK_INEXPRESSIBLE

        if params.algo in FORK_INEXPRESSIBLE:
            raise ValueError(
                f"twin warm loop cannot run algo {params.algo!r} (online "
                "RL trains between chunks); serve it as a serial-path "
                "forecast policy instead")
        for s, nv in cursor.n_valid().items():
            if nv == 0:
                raise ValueError(
                    f"base trace stream {cursor._label(s)} is empty: the "
                    "twin primes its arrival clock (draw #0) from the "
                    "base spec, so an empty stream would stay silent "
                    "forever regardless of later appends — use kind "
                    "'off', or start the twin from the first real "
                    "segment")
        if params.workload is not cursor.spec:
            params = dataclasses.replace(params, workload=cursor.spec)
        self.fleet = fleet
        self.params = params
        self.cursor = cursor
        self.store = os.path.abspath(store) if store else None
        self.chunk_steps = int(chunk_steps)
        self.ckpt_every = max(1, int(ckpt_every))
        self.engine = Engine(fleet, params)
        self.root_key = jax.random.key(params.seed)
        self.state = init_state(self.root_key, fleet, params,
                                workload=self.engine.workload)
        self.chunk = 0
        self.fingerprint = self._config_fingerprint()
        self.last_accept_wall = time.time()
        self._runners = {}
        self._commits = 0
        if self.store is not None:
            from ..utils.checkpoint import steps

            if steps(self.store):
                self._restore()

    def _config_fingerprint(self) -> str:
        from ..utils.checkpoint import config_fingerprint

        return config_fingerprint(self.fleet, self.params)

    # ------------------------------------------------------------------
    # resume
    # ------------------------------------------------------------------

    def _restore(self) -> None:
        from ..utils.checkpoint import (restore_latest, step_dirname,
                                        verify_checkpoint)

        step, trees = restore_latest(self.store, like={"state": self.state})
        meta = verify_checkpoint(
            os.path.join(self.store, step_dirname(step))).get(
                "metadata") or {}
        saved = meta.get("fingerprint")
        if saved and saved != self.fingerprint:
            raise RuntimeError(
                f"twin store {self.store} was written by a different "
                f"(fleet, params) world: {saved[:12]} != "
                f"{self.fingerprint[:12]}")
        self.state = trees["state"]
        self.chunk = int(step)

    # ------------------------------------------------------------------
    # the speculative chunk loop
    # ------------------------------------------------------------------

    def _runner(self, trace):
        """Cached jitted chunk fn keyed by the trace capacity signature
        (appends within capacity re-use the compiled program)."""
        import jax

        eng = self.engine
        sig = tuple(sorted(
            (s, t[0].shape[0], t[1] is not None) for s, t in trace.items()))
        run = self._runners.get(sig)
        if run is None:
            n_steps = self.chunk_steps
            pregen = eng.arrival_pregen

            def chunk(st, tr):
                # mirrors Engine._run_chunk exactly, with the runtime
                # trace override riding the pregen tables
                pre = eng.workload.tables(st, n_steps, inversion=pregen,
                                          trace=tr)
                step = eng._step_super if eng.superstep_on else eng._step

                def body(s_, _):
                    s2, _em = step(s_, None, pre=pre)
                    return s2, None

                st, _ = jax.lax.scan(body, st, None, length=n_steps)
                return eng.workload.advance_carries(st, pre,
                                                    inversion=pregen)

            run = self._runners[sig] = jax.jit(chunk)
        return run

    def _accepted(self, post_state) -> bool:
        """A chunk is sound iff no trace stream consumed past its
        ingested frontier: post-chunk ``arr_count[s] <= n_valid[s]``
        implies every gathered entry AND the pending next-arrival carry
        were real data — byte-identical to the batch run."""
        if self.cursor.closed:
            return True
        counts = np.asarray(post_state.arr_count).reshape(-1)
        for s, nv in self.cursor.n_valid().items():
            if int(counts[s]) > nv:
                return False
        return True

    @property
    def done(self) -> bool:
        return bool(np.asarray(self.state.done))

    def advance(self, max_chunks: Optional[int] = None) -> Dict:
        """Run accepted chunks until the data frontier (or ``done``).

        Returns ``{"chunks": n_accepted, "frontier": bool}`` —
        ``frontier`` True when the twin stopped because the next chunk
        would need trace data that has not been ingested yet."""
        ran = 0
        frontier = False
        while (max_chunks is None or ran < max_chunks) and not self.done:
            trace = self.cursor.device_tables()
            post = self._runner(trace)(self.state, trace)
            if not self._accepted(post):
                frontier = True
                break
            self.state = post
            self.chunk += 1
            self.last_accept_wall = time.time()
            ran += 1
            if self.store is not None and self.chunk % self.ckpt_every == 0:
                self.checkpoint()
        return {"chunks": ran, "frontier": frontier}

    # ------------------------------------------------------------------
    # the verified store + watermark
    # ------------------------------------------------------------------

    def ingest_lag_s(self) -> float:
        """Trace-seconds between the ingested frontier and the warm
        clock (0 when the trace is closed/exhausted)."""
        wm = self.cursor.watermark_t()
        if not np.isfinite(wm):
            return 0.0
        return max(0.0, wm - float(np.asarray(self.state.t)))

    def watermark_doc(self) -> Dict:
        counts = np.asarray(self.state.arr_count).reshape(-1)
        return {
            "schema": TWIN_INGEST_SCHEMA,
            "chunk": self.chunk,
            "t": float(np.asarray(self.state.t)),
            "n_events": int(np.asarray(self.state.n_events)),
            "segments": self.cursor.segments,
            "closed": self.cursor.closed,
            "watermark_t": self.cursor.watermark_t(),
            "ingest_lag_s": self.ingest_lag_s(),
            "n_valid": {str(s): n for s, n in self.cursor.n_valid().items()},
            "consumed": {str(s): int(counts[s])
                         for s in self.cursor.n_valid()},
            "trace_fingerprint": self.cursor.fingerprint(),
            "fingerprint": self.fingerprint,
        }

    def checkpoint(self) -> str:
        """Commit the warm state through the verified store + rewrite
        the ingest watermark; the SIGKILL test hook fires AFTER the
        commit, so a killed twin always resumes from a verified step."""
        from ..utils.checkpoint import save_checkpoint

        if self.store is None:
            raise ValueError("twin has no checkpoint store")
        meta = dict(self.watermark_doc())
        meta["schema"] = TWIN_CKPT_SCHEMA
        path = save_checkpoint(self.store, self.chunk, metadata=meta,
                               state=self.state)
        dump_json_atomic(os.path.join(self.store, TWIN_INGEST_FILE),
                         self.watermark_doc())
        self._commits += 1
        kill_after = os.environ.get(KILL_ENV)
        if kill_after and self._commits >= int(kill_after):
            os.kill(os.getpid(), _signal.SIGKILL)
        return path

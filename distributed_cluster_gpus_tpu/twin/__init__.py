"""twin/ — the resident digital-twin serving mode (ROADMAP item 5).

A twin is a long-lived what-if service on a live arrival trace:

* :mod:`.ingest` — `TraceCursor` (append-only trace segments, validated
  and compiled into fixed-capacity device tables) + `Twin` (the warm
  state advanced chunk-by-chunk through the verified checkpoint store,
  speculative accept/rollback at the data frontier, byte-identical
  crash resume).
* :mod:`.fork` — warm-state forks: N candidate policies x M scenario
  overlays raced ahead of real time as vmapped lanes (sweep's
  bucketing-by-program-signature), per-lane forecast deltas from
  ``evaluation._summarize``.
* :mod:`.service` — the strict-JSON query protocol (forecast / status /
  rca) `scripts/twin_serve.py` speaks.

docs/twin.md covers the service lifecycle, query schema, the
fork+forecast latency SLO (``bench_results/twin_r19.json``,
ledger kind ``twin_latency``) and the RCA workflow.
"""

from .fork import FORK_INEXPRESSIBLE, Overlay, forecast  # noqa: F401
from .ingest import (  # noqa: F401
    TWIN_INGEST_FILE,
    TWIN_INGEST_SCHEMA,
    TraceCursor,
    Twin,
)
from .service import TwinService, twin_rca  # noqa: F401

"""Graceful SIGTERM/SIGINT shutdown for the host loops.

A preempted pod, a Ctrl-C, or a batch-scheduler eviction should not
strand buffered CSV rows, half-written checkpoints, or a missing
``run_summary.json``.  The contract:

* :func:`graceful_shutdown` installs signal handlers that only SET A
  FLAG (:class:`ShutdownFlag`) — no exception is thrown into arbitrary
  stack frames, so jit dispatch, orbax saves, and the background
  writers are never interrupted mid-operation.
* The host loops (``sim.io.run_simulation``, the ``rl.train`` trainer
  loops) poll the flag once per chunk boundary; when set they stop
  dispatching, flush the AsyncLineDrain/ObsSink pipelines, save a final
  checkpoint (trainers), and write ``run_summary.json`` with
  ``status="interrupted"``.
* The CLI (``run_sim.py``) then exits nonzero (``128 + signum``, the
  shell convention), so schedulers and wrappers see the interruption.

A second signal while the first is still flushing falls through to the
previous handler (default: kill) — the escape hatch when a flush hangs.
"""

from __future__ import annotations

import contextlib
import signal
import threading
from typing import Optional


class ShutdownFlag:
    """Latched shutdown request set by a signal handler.

    ``requested`` flips True at the first signal; ``signum`` records
    which one.  ``exit_code`` follows the shell convention (128 +
    signum).  Thread-safe by virtue of the GIL (single latched write).
    """

    def __init__(self):
        self.requested = False
        self.signum: Optional[int] = None

    def trip(self, signum: int) -> None:
        self.requested = True
        if self.signum is None:
            self.signum = signum

    @property
    def exit_code(self) -> int:
        return 128 + self.signum if self.signum is not None else 0

    def __bool__(self) -> bool:
        return self.requested


@contextlib.contextmanager
def graceful_shutdown(signums=(signal.SIGTERM, signal.SIGINT)):
    """Context manager yielding a :class:`ShutdownFlag` armed on entry.

    The FIRST delivery of each signal latches the flag; the handler
    then re-installs the previous disposition, so a SECOND delivery
    (operator insists) takes the default path — typically terminating a
    flush that wedged.  Handlers are restored on exit.  Outside the
    main thread (where CPython forbids ``signal.signal``) this yields
    an inert flag instead of failing, so library callers can pass a
    flag unconditionally.
    """
    flag = ShutdownFlag()
    if threading.current_thread() is not threading.main_thread():
        yield flag
        return
    prev = {}

    def handler(signum, frame):
        flag.trip(signum)
        # one graceful chance: the next delivery acts like we never
        # caught it (default disposition = terminate the flush too)
        signal.signal(signum, prev[signum])

    for s in signums:
        prev[s] = signal.signal(s, handler)
    try:
        yield flag
    finally:
        for s, h in prev.items():
            # only restore if our handler is still installed (it swaps
            # itself out after the first delivery)
            if signal.getsignal(s) is handler:
                signal.signal(s, h)

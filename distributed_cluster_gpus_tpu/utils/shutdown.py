"""Graceful SIGTERM/SIGINT shutdown for the host loops.

A preempted pod, a Ctrl-C, or a batch-scheduler eviction should not
strand buffered CSV rows, half-written checkpoints, or a missing
``run_summary.json``.  The contract:

* :func:`graceful_shutdown` installs signal handlers that only SET A
  FLAG (:class:`ShutdownFlag`) — no exception is thrown into arbitrary
  stack frames, so jit dispatch, orbax saves, and the background
  writers are never interrupted mid-operation.
* The host loops (``sim.io.run_simulation``, the ``rl.train`` trainer
  loops) poll the flag once per chunk boundary; when set they stop
  dispatching, flush the AsyncLineDrain/ObsSink pipelines, save a final
  checkpoint (trainers), and write ``run_summary.json`` with
  ``status="interrupted"``.
* The CLI (``run_sim.py``) then exits nonzero (``128 + signum``, the
  shell convention), so schedulers and wrappers see the interruption.

A second signal while the first is still flushing falls through to the
previous handler (default: kill) — the escape hatch when a flush hangs.
:func:`defer_signals` carves out the one place that escape hatch must
not fire mid-operation: the checkpoint commit critical section
(``utils.checkpoint.save_checkpoint``) blocks SIGTERM/SIGINT delivery
until the staged step has renamed into place, so the operator's second
signal kills the process *between* commits, never inside one.  (SIGKILL
still cannot be deferred — the atomic commit makes that crash safe; the
deferral just makes it rare.)
"""

from __future__ import annotations

import contextlib
import signal
import threading
from typing import Optional


class ShutdownFlag:
    """Latched shutdown request set by a signal handler.

    ``requested`` flips True at the first signal; ``signum`` records
    which one.  ``exit_code`` follows the shell convention (128 +
    signum).  Thread-safe by virtue of the GIL (single latched write).
    """

    def __init__(self):
        self.requested = False
        self.signum: Optional[int] = None

    def trip(self, signum: int) -> None:
        self.requested = True
        if self.signum is None:
            self.signum = signum

    @property
    def exit_code(self) -> int:
        return 128 + self.signum if self.signum is not None else 0

    def __bool__(self) -> bool:
        return self.requested


@contextlib.contextmanager
def defer_signals(signums=(signal.SIGTERM, signal.SIGINT)):
    """Defer delivery of ``signums`` for the duration of the block.

    Used around critical sections that must not be killed mid-operation
    by a signal's *default* disposition — after `graceful_shutdown`'s
    first latched signal re-installs the previous handler, a second
    SIGTERM would terminate the process wherever it happens to be,
    including inside a checkpoint commit.

    The deferral is Python-level, not an OS sigmask: a temporary handler
    records arrivals, and on exit the previous disposition is restored
    and each recorded signal is re-delivered to it — a callable handler
    is invoked, ``SIG_DFL`` is re-raised via ``os.kill`` (taking the
    default path, e.g. terminate — *between* commits now), ``SIG_IGN``
    drops.  This works in multi-threaded processes (the drain/exporter
    workers): CPython runs signal handlers on the main thread regardless
    of which thread the kernel picked, so masking only the main thread's
    sigmask would NOT stop delivery — recording at the handler layer
    does.  Off the main thread (where ``signal.signal`` is forbidden)
    this is a no-op; the commit stays crash-consistent either way, the
    deferral just makes the mid-commit kill not happen when avoidable.
    """
    if threading.current_thread() is not threading.main_thread():
        yield
        return
    pending = []
    prev = {}

    def record(signum, frame):
        # record EVERY arrival (no dedup): under graceful_shutdown the
        # first SIGTERM latches and the second must still reach the
        # restored default disposition — the operator's escape hatch
        pending.append(signum)

    for s in signums:
        try:
            prev[s] = signal.signal(s, record)
        except (ValueError, OSError):  # unsupported signal on platform
            pass
    try:
        yield
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
        for signum in pending:
            # re-deliver through the disposition CURRENT at this point —
            # a latch handler that swaps itself out on the first
            # delivery (graceful_shutdown) leaves the second delivery to
            # the default path, exactly as live delivery would
            h = signal.getsignal(signum)
            if callable(h):
                h(signum, None)
            elif h == signal.SIG_DFL:
                import os

                os.kill(os.getpid(), signum)
            # SIG_IGN (or None: handler installed by non-Python code):
            # drop — we cannot meaningfully re-deliver


@contextlib.contextmanager
def graceful_shutdown(signums=(signal.SIGTERM, signal.SIGINT)):
    """Context manager yielding a :class:`ShutdownFlag` armed on entry.

    The FIRST delivery of each signal latches the flag; the handler
    then re-installs the previous disposition, so a SECOND delivery
    (operator insists) takes the default path — typically terminating a
    flush that wedged.  Handlers are restored on exit.  Outside the
    main thread (where CPython forbids ``signal.signal``) this yields
    an inert flag instead of failing, so library callers can pass a
    flag unconditionally.
    """
    flag = ShutdownFlag()
    if threading.current_thread() is not threading.main_thread():
        yield flag
        return
    prev = {}

    def handler(signum, frame):
        flag.trip(signum)
        # one graceful chance: the next delivery acts like we never
        # caught it (default disposition = terminate the flush too)
        signal.signal(signum, prev[signum])

    for s in signums:
        prev[s] = signal.signal(s, handler)
    try:
        yield flag
    finally:
        for s, h in prev.items():
            # only restore if our handler is still installed (it swaps
            # itself out after the first delivery)
            if signal.getsignal(s) is handler:
                signal.signal(s, h)

"""Deprecated shim: moved to :mod:`distributed_cluster_gpus_tpu.obs.trace`.

`PhaseTimer` grew structured spans + chrome-trace export and now lives in
the obs/ subsystem (docs/observability.md §tracing) next to the metric
registry and exporters.  This module re-exports the public names with a
`DeprecationWarning` so external callers keep working; in-tree call
sites import ``obs.trace`` directly.
"""

from __future__ import annotations

import warnings

from ..obs.trace import PhaseTimer, sim_progress, trace  # noqa: F401

warnings.warn(
    "distributed_cluster_gpus_tpu.utils.profiling is deprecated; import "
    "PhaseTimer/sim_progress/trace from "
    "distributed_cluster_gpus_tpu.obs.trace instead",
    DeprecationWarning, stacklevel=2)

__all__ = ["PhaseTimer", "sim_progress", "trace"]

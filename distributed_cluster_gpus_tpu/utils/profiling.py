"""Profiling hooks: jax.profiler traces + simple phase timers.

The reference's only "tracing" is a tqdm bar over simulated time
(`simulator_paper_multi.py:136-151`).  Here: (a) `trace()` wraps a code
region in a `jax.profiler` trace (view in TensorBoard / xprof), (b)
`PhaseTimer` collects wall-time per named phase (rollout, ingest, train,
io) with jax.block_until_ready fencing, (c) `sim_progress` is a host
callback printing simulated-time progress like the reference's bar.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler trace of the enclosed region."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Accumulate wall seconds per phase; device-fenced on exit."""

    def __init__(self):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str, fence=None):
        """Time the enclosed block; ``fence`` is a zero-arg callable returning
        the array(s) to block on, evaluated at block EXIT (a bare array would
        be the stale pre-block value — the async dispatch would be attributed
        to whichever later phase happens to block first)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if fence is not None:
                jax.block_until_ready(fence() if callable(fence) else fence)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def summary(self) -> str:
        rows = sorted(self.totals.items(), key=lambda kv: -kv[1])
        total = sum(self.totals.values()) or 1.0
        return "\n".join(
            f"{name:>12s}: {secs:8.3f}s ({100 * secs / total:5.1f}%) "
            f"x{self.counts[name]}"
            for name, secs in rows)


def sim_progress(t: float, end: float, extra: str = "",
                 width: int = 40) -> str:
    """One-line progress string over simulated time (tqdm-style)."""
    frac = min(1.0, max(0.0, t / max(end, 1e-9)))
    filled = int(frac * width)
    bar = "#" * filled + "-" * (width - filled)
    return f"[{bar}] sim {t:,.0f}/{end:,.0f}s ({100 * frac:5.1f}%) {extra}"

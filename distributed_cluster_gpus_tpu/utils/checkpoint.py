"""Orbax checkpointing for the full training pipeline.

The reference has NO model/optimizer checkpointing at all (SURVEY.md §5
"Checkpoint / resume": only per-job preempt dicts and an unwired npz
offline-dataset path).  This module adds real checkpoint/resume as a
first-class capability: one call saves the complete pytree of
{SAC learner state, replay buffer, simulator state(s), CMDP multipliers,
host PRNG key} and restores it bit-exactly, so a long training run (or a
preempted TPU slice) resumes mid-stream.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _is_key(x) -> bool:
    return isinstance(x, jax.Array) and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)


def _to_host(x):
    """Device leaf -> numpy; typed PRNG keys unwrap to their uint32 data."""
    if _is_key(x):
        return np.asarray(jax.random.key_data(x))
    return np.asarray(x)


def _rewrap(like, restored):
    """Restored numpy leaf -> typed key when the live structure holds one."""
    if _is_key(like):
        return jax.random.wrap_key_data(jnp_asarray_u32(restored))
    return restored


def jnp_asarray_u32(x):
    import jax.numpy as jnp

    return jnp.asarray(x, dtype=jnp.uint32)


def save_checkpoint(path: str, step: int, **trees: Any) -> str:
    """Save named pytrees under ``path/step_<N>`` (e.g. sac=, replay=, states=).

    Returns the checkpoint directory written.  Device arrays are fetched to
    host automatically; shardings are NOT persisted — restore re-places
    arrays with `jax.device_put` under the caller's mesh.
    """
    path = os.path.abspath(path)
    ckpt_dir = os.path.join(path, f"step_{step:010d}")
    host_trees = jax.tree.map(_to_host, dict(trees))
    ckptr = _ckptr()
    ckptr.save(ckpt_dir, host_trees, force=True)
    ckptr.wait_until_finished()  # orbax saves are async; finalize before return
    return ckpt_dir


def latest_step(path: str) -> Optional[int]:
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return None
    steps = [int(d.split("_")[1]) for d in os.listdir(path)
             if d.startswith("step_") and d.split("_")[1].isdigit()]
    return max(steps) if steps else None


def restore_checkpoint(path: str, step: Optional[int] = None,
                       like: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Restore the named pytrees saved by :func:`save_checkpoint`.

    ``like`` (same structure as the saved dict) restores leaves with matching
    dtypes/pytree structure — pass the live objects to get typed dataclasses
    back instead of raw dicts.
    """
    path = os.path.abspath(path)
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {path}")
    ckpt_dir = os.path.join(path, f"step_{step:010d}")
    if like is not None:
        host_like = jax.tree.map(_to_host, dict(like))
        restored = _ckptr().restore(ckpt_dir, target=host_like)
        # graft restored leaves back onto the typed structures (rewrapping
        # PRNG key leaves to their typed dtype)
        return jax.tree.map(_rewrap, dict(like), restored)
    return _ckptr().restore(ckpt_dir)

"""Durable, verified checkpointing for the full training pipeline.

The reference has NO model/optimizer checkpointing at all (SURVEY.md §5
"Checkpoint / resume": only per-job preempt dicts and an unwired npz
offline-dataset path).  This module adds real checkpoint/resume as a
first-class capability — one call saves the complete pytree of
{SAC learner state, replay buffer, simulator state(s), CMDP multipliers,
host PRNG key} and restores it bit-exactly — and, since round 12, makes
the store *crash-consistent and verified* (docs/checkpointing.md):

* **Atomic commit.**  A save stages into ``step_<N>_tmp``, writes a
  ``manifest.json`` (schema version, per-file content digests, run
  metadata), fsyncs, drops a ``COMMIT`` marker, and only then renames
  the staging dir to ``step_<N>``.  A process killed at ANY point
  (SIGKILL, OOM, disk-full — exactly the conditions the shutdown and
  campaign machinery exists for) leaves either the previous store
  untouched plus ``*_tmp`` debris, or the fully committed new step —
  never a half-written ``step_*`` dir that resume would pick up.
* **Verification.**  :func:`verify_checkpoint` re-hashes every payload
  file against the manifest; :func:`latest_step` grows a
  ``verified=True`` mode and the restore paths walk a *fallback chain*
  — a corrupt or uncommitted checkpoint is skipped with a logged
  reason and the next older verified step restores instead.
* **Retention + debris sweep.**  :func:`gc_checkpoints` removes stale
  staging dirs and (optionally) prunes committed steps beyond a
  keep-last-N budget.
* **Crash-injection points.**  ``DCG_CKPT_CRASH_POINT`` (one of
  :data:`CRASH_POINTS`) makes the save crash deterministically at that
  phase — ``DCG_CKPT_CRASH_MODE=raise`` (default) raises
  :class:`CheckpointCrashInjected`, ``=kill`` SIGKILLs the process —
  the hook the crash-consistency harness in tests/test_checkpoint.py
  drives.

Manifest schema-version policy: readers accept any
``schema_version <= SCHEMA_VERSION`` (additive fields only within a
version); a manifest written by a NEWER version refuses to load with an
upgrade message rather than guessing.  Pre-manifest checkpoints (schema
version 0, "legacy") are still accepted: orbax's own atomic finalize
marker stands in for the commit check, with no digest cover.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import re
import shutil
import signal
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

SCHEMA = "dcg.ckpt_manifest.v1"
SCHEMA_VERSION = 1
MANIFEST_FILE = "manifest.json"
COMMIT_FILE = "COMMIT"
#: orbax's own finalize marker — presence means orbax completed its save
#: (it renames its internal tmp dir only after writing this), the commit
#: evidence legacy (pre-manifest) checkpoints are accepted on
_ORBAX_MARKER = "_CHECKPOINT_METADATA"

#: committed checkpoint directories, strictly: exactly ``step_`` + 10
#: digits.  Staging dirs (``step_<N>_tmp``), orbax tmp dirs
#: (``*.orbax-checkpoint-tmp-*``) and hand-made ``step_5``-style names
#: never parse — the lenient ``split("_")[1].isdigit()`` rule this
#: replaces returned a mid-save staging dir as a real step.
_STEP_RE = re.compile(r"^step_(\d{10})$")

#: save phases the crash-injection env hook can kill the process after
#: (in commit order): payload staged, manifest written, COMMIT marker
#: written (rename still pending), and step renamed into place.
CRASH_POINTS = ("staged", "manifest", "marker", "committed")

_log = logging.getLogger("dcg.checkpoint")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory failed verification (uncommitted, missing
    payload files, or digest mismatch).  The fallback chain catches this
    and degrades to the next older step."""


class CheckpointCrashInjected(RuntimeError):
    """Deterministic crash raised by the DCG_CKPT_CRASH_POINT hook."""


def _crash_env() -> Tuple[Optional[str], str]:
    point = os.environ.get("DCG_CKPT_CRASH_POINT") or None
    mode = os.environ.get("DCG_CKPT_CRASH_MODE", "raise")
    if point is not None and point not in CRASH_POINTS:
        raise ValueError(
            f"DCG_CKPT_CRASH_POINT={point!r}: unknown injection point; "
            f"choices: {', '.join(CRASH_POINTS)}")
    if mode not in ("raise", "kill"):
        raise ValueError(f"DCG_CKPT_CRASH_MODE={mode!r}: raise or kill")
    return point, mode


def _maybe_crash(phase: str, want: Optional[str], mode: str) -> None:
    if want != phase:
        return
    if mode == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    raise CheckpointCrashInjected(
        f"injected crash after checkpoint phase {phase!r}")


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _is_key(x) -> bool:
    return isinstance(x, jax.Array) and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)


def _to_host(x):
    """Device leaf -> numpy; typed PRNG keys unwrap to their uint32 data."""
    if _is_key(x):
        return np.asarray(jax.random.key_data(x))
    return np.asarray(x)


def _rewrap(like, restored):
    """Restored numpy leaf -> typed key when the live structure holds one."""
    if _is_key(like):
        return jax.random.wrap_key_data(jnp_asarray_u32(restored))
    return restored


def jnp_asarray_u32(x):
    import jax.numpy as jnp

    return jnp.asarray(x, dtype=jnp.uint32)


def to_host_tree(tree: Any) -> Any:
    """Pytree -> host numpy snapshot (typed PRNG keys unwrap to uint32).

    The leaves are plain copies on the host, so the snapshot survives a
    later donated dispatch consuming the live buffers — the forensic
    replay's bisection re-runs a chunk from one snapshot many times."""
    return jax.tree.map(_to_host, tree)


def from_host_tree(like: Any, host: Any) -> Any:
    """Inverse of :func:`to_host_tree`: re-wrap a host snapshot against a
    structurally identical live template (PRNG key leaves re-typed).
    ``like`` is consulted for leaf *kinds* only — donated/deleted buffers
    are fine as templates."""
    return jax.tree.map(_rewrap, like, host)


# ---------------------------------------------------------------------------
# store layout helpers
# ---------------------------------------------------------------------------

def step_dirname(step: int) -> str:
    return f"step_{step:010d}"


def _staging_name(step: int) -> str:
    return step_dirname(step) + "_tmp"


def _is_debris(name: str) -> bool:
    """Staging / tmp debris a crash can strand in a store directory."""
    return (name.endswith("_tmp") and name.startswith("step_")) \
        or ".orbax-checkpoint-tmp" in name


def steps(path: str) -> List[int]:
    """Committed step numbers under ``path``, ascending (strict names)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return []
    out = []
    for d in os.listdir(path):
        m = _STEP_RE.match(d)
        if m and os.path.isdir(os.path.join(path, d)):
            out.append(int(m.group(1)))
    return sorted(out)


def _hash_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return "sha256:" + h.hexdigest()


def _payload_files(ckpt_dir: str) -> Iterator[str]:
    """Relative (posix) paths of every payload file under ``ckpt_dir`` —
    everything except our manifest and commit marker."""
    for root, _dirs, files in os.walk(ckpt_dir):
        for f in sorted(files):
            rel = os.path.relpath(os.path.join(root, f), ckpt_dir)
            rel = rel.replace(os.sep, "/")
            if rel in (MANIFEST_FILE, COMMIT_FILE):
                continue
            yield rel


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    # directory fsync makes the rename/create durable; some filesystems
    # refuse O_RDONLY dir fds — best effort, the manifest digests still
    # catch a torn commit on the read side
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# fingerprinting (manifest/run metadata + forensic replay identity check)
# ---------------------------------------------------------------------------

def config_fingerprint(*objs: Any) -> str:
    """Stable content digest of static run configuration objects.

    Canonicalizes dataclasses (field order), dicts (sorted keys),
    sequences, numpy/jax arrays (dtype + shape + bytes) and falls back
    to ``repr`` for scalars.  Used to stamp checkpoints with the
    (fleet, params) identity so a forensic replay can refuse to run
    against a different world than the one that aborted."""
    h = hashlib.sha256()

    def feed(x):
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            h.update(type(x).__name__.encode())
            for f in dataclasses.fields(x):
                h.update(f.name.encode())
                feed(getattr(x, f.name))
        elif isinstance(x, dict):
            h.update(b"{")
            for k in sorted(x, key=str):
                h.update(str(k).encode())
                feed(x[k])
            h.update(b"}")
        elif isinstance(x, (list, tuple)):
            h.update(b"[")
            for v in x:
                feed(v)
            h.update(b"]")
        elif isinstance(x, (np.ndarray, jax.Array)):
            a = np.asarray(x)
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(np.ascontiguousarray(a).tobytes())
        else:
            h.update(repr(x).encode())

    for o in objs:
        feed(o)
    return "sha256:" + h.hexdigest()[:32]


# ---------------------------------------------------------------------------
# save: stage -> manifest -> marker -> rename (the atomic commit)
# ---------------------------------------------------------------------------

def save_checkpoint(path: str, step: int, metadata: Optional[Dict] = None,
                    **trees: Any) -> str:
    """Save named pytrees under ``path/step_<N>`` (e.g. sac=, replay=).

    Returns the committed checkpoint directory.  Device arrays are
    fetched to host automatically; shardings are NOT persisted — restore
    re-places arrays with ``jax.device_put`` under the caller's mesh.

    The write is crash-consistent: the payload stages into
    ``step_<N>_tmp``, a ``manifest.json`` (schema version, per-file
    sha256 digests, ``metadata``) and a ``COMMIT`` marker are written
    and fsynced, and the staging dir renames into place as the last
    action — a crash at any point leaves no committed-but-partial step
    (``gc_checkpoints`` sweeps the stranded staging dir).  SIGTERM/
    SIGINT delivery is deferred across the whole critical section
    (:func:`~.shutdown.defer_signals`) so an operator's second signal —
    which takes the default kill disposition — cannot land mid-commit.
    """
    from .shutdown import defer_signals

    crash_point, crash_mode = _crash_env()
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    final = os.path.join(path, step_dirname(step))
    staging = os.path.join(path, _staging_name(step))
    host_trees = jax.tree.map(_to_host, dict(trees))
    with defer_signals():
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        ckptr = _ckptr()
        ckptr.save(staging, host_trees, force=True)
        ckptr.wait_until_finished()  # orbax saves are async; finalize first
        _maybe_crash("staged", crash_point, crash_mode)

        files = {}
        total = 0
        for rel in _payload_files(staging):
            full = os.path.join(staging, rel)
            files[rel] = _hash_file(full)
            total += os.path.getsize(full)
        manifest = {
            "schema": SCHEMA,
            "schema_version": SCHEMA_VERSION,
            "step": int(step),
            "trees": sorted(trees),
            "n_files": len(files),
            "total_bytes": int(total),
            "files": files,
            "metadata": metadata or {},
        }
        from .jsonio import clean_nan

        man_path = os.path.join(staging, MANIFEST_FILE)
        with open(man_path, "w") as f:
            json.dump(clean_nan(manifest), f, indent=2, default=float)
            f.flush()
            os.fsync(f.fileno())
        _maybe_crash("manifest", crash_point, crash_mode)

        marker = os.path.join(staging, COMMIT_FILE)
        with open(marker, "w") as f:
            f.write("committed\n")
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(staging)
        _maybe_crash("marker", crash_point, crash_mode)

        if os.path.isdir(final):
            # re-save of an existing step: journal-style swap.  The old
            # committed dir moves to `step_<N>_swap` — NOT a `*_tmp`
            # debris name, so a crash between the two renames strands a
            # RECOVERABLE pair (old payload in _swap, new fully-marked
            # payload in _tmp) that `gc_checkpoints` rolls forward (tmp
            # committed -> promote) or back (restore the swap); either
            # way no committed checkpoint is ever lost
            old = final + "_swap"
            if os.path.isdir(old):
                shutil.rmtree(old)
            os.rename(final, old)
            os.rename(staging, final)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(staging, final)
        _fsync_dir(path)
        _maybe_crash("committed", crash_point, crash_mode)
    return final


# ---------------------------------------------------------------------------
# verify + fallback walk
# ---------------------------------------------------------------------------

def verify_checkpoint(ckpt_dir: str, digests: bool = True) -> Dict:
    """Check one checkpoint directory; return its manifest dict.

    Raises :class:`CheckpointCorruptError` when the directory is missing,
    uncommitted (no COMMIT marker next to a manifest), lists payload
    files that are absent or whose content digest mismatches, or carries
    a manifest from a newer schema version.  Pre-manifest (legacy)
    checkpoints are accepted on orbax's own finalize marker and return a
    synthesized ``schema_version=0`` manifest with ``legacy=True``.

    ``digests=False`` skips the content re-hash (structure checks only)
    — the fast mode for per-save retention scans over large stores.
    """
    ckpt_dir = os.path.abspath(ckpt_dir)
    if not os.path.isdir(ckpt_dir):
        raise CheckpointCorruptError(f"{ckpt_dir}: not a directory")
    man_path = os.path.join(ckpt_dir, MANIFEST_FILE)
    if not os.path.exists(man_path):
        if os.path.exists(os.path.join(ckpt_dir, _ORBAX_MARKER)):
            return {"schema": SCHEMA, "schema_version": 0, "legacy": True,
                    "trees": [], "files": {}, "metadata": {}}
        raise CheckpointCorruptError(
            f"{ckpt_dir}: no {MANIFEST_FILE} and no orbax finalize marker "
            "— uncommitted or torn checkpoint")
    try:
        with open(man_path) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(
            f"{ckpt_dir}: unreadable manifest: {e}") from e
    if man.get("schema") != SCHEMA:
        raise CheckpointCorruptError(
            f"{ckpt_dir}: unknown manifest schema {man.get('schema')!r}")
    if int(man.get("schema_version", 0)) > SCHEMA_VERSION:
        raise CheckpointCorruptError(
            f"{ckpt_dir}: manifest schema_version "
            f"{man.get('schema_version')} is newer than this reader "
            f"({SCHEMA_VERSION}) — upgrade before restoring")
    if not os.path.exists(os.path.join(ckpt_dir, COMMIT_FILE)):
        raise CheckpointCorruptError(
            f"{ckpt_dir}: manifest present but no {COMMIT_FILE} marker — "
            "uncommitted checkpoint")
    files = man.get("files", {})
    for rel, want in files.items():
        full = os.path.join(ckpt_dir, rel.replace("/", os.sep))
        if not os.path.exists(full):
            raise CheckpointCorruptError(
                f"{ckpt_dir}: payload file {rel} missing")
        if digests and _hash_file(full) != want:
            raise CheckpointCorruptError(
                f"{ckpt_dir}: payload file {rel} digest mismatch "
                "(bit rot or tampering)")
    return man


def _skip(on_skip, ckpt_dir: str, reason: Exception) -> None:
    msg = f"skipping checkpoint {ckpt_dir}: {reason}"
    _log.warning(msg)
    if on_skip is not None:
        on_skip(ckpt_dir, str(reason))


def fallback_steps(path: str, on_skip=None, max_step: Optional[int] = None,
                   digests: bool = True) -> Iterator[int]:
    """Yield VERIFIED step numbers newest-first, logging skipped ones.

    The fallback chain every restore path walks: an uncommitted, torn,
    or bit-rotted checkpoint is skipped with a logged reason instead of
    crashing the resume.  ``max_step`` bounds the walk (forensic replay
    restores strictly before the tripping chunk)."""
    path = os.path.abspath(path)
    for step in reversed(steps(path)):
        if max_step is not None and step > max_step:
            continue
        ckpt_dir = os.path.join(path, step_dirname(step))
        try:
            verify_checkpoint(ckpt_dir, digests=digests)
        except CheckpointCorruptError as e:
            _skip(on_skip, ckpt_dir, e)
            continue
        yield step


def latest_step(path: str, verified: bool = False,
                on_skip=None) -> Optional[int]:
    """Newest committed step under ``path`` (None when the store is empty).

    ``verified=True`` additionally digest-checks each candidate and
    skips uncommitted/corrupt directories — the mode every resume and
    rollback path uses, so a crash mid-save can never be selected as
    the "last healthy" checkpoint."""
    if verified:
        return next(iter(fallback_steps(path, on_skip=on_skip)), None)
    all_steps = steps(path)
    return all_steps[-1] if all_steps else None


def _restore_dir(ckpt_dir: str, like: Optional[Dict[str, Any]]):
    if like is not None:
        host_like = jax.tree.map(_to_host, dict(like))
        restored = _ckptr().restore(ckpt_dir, target=host_like)
        # graft restored leaves back onto the typed structures (rewrapping
        # PRNG key leaves to their typed dtype)
        return jax.tree.map(_rewrap, dict(like), restored)
    return _ckptr().restore(ckpt_dir)


def restore_checkpoint(path: str, step: Optional[int] = None,
                       like: Optional[Dict[str, Any]] = None,
                       verify: bool = True,
                       on_skip=None) -> Dict[str, Any]:
    """Restore the named pytrees saved by :func:`save_checkpoint`.

    ``like`` (same structure as the saved dict) restores leaves with
    matching dtypes/pytree structure — pass the live objects to get
    typed dataclasses back instead of raw dicts.

    ``step=None`` walks the verified fallback chain newest-first and
    restores the first checkpoint that passes verification (corrupt ones
    are skipped with a logged reason).  An explicit ``step`` restores
    exactly that step, verifying it first (``verify=False`` skips the
    digest re-hash when the caller already verified)."""
    path = os.path.abspath(path)
    if step is None:
        step, out = restore_latest(path, like=like, on_skip=on_skip)
        return out
    ckpt_dir = os.path.join(path, step_dirname(step))
    if verify:
        verify_checkpoint(ckpt_dir)
    return _restore_dir(ckpt_dir, like)


def restore_latest(path: str, like: Optional[Dict[str, Any]] = None,
                   max_step: Optional[int] = None,
                   on_skip=None) -> Tuple[int, Dict[str, Any]]:
    """(step, restored trees) of the newest restorable checkpoint.

    Walks the verified fallback chain; a candidate that verifies but
    fails to read back (I/O error mid-restore) is also skipped with a
    logged reason.  Raises FileNotFoundError when nothing under ``path``
    restores.  Structural mismatches (ValueError/KeyError/TypeError from
    a ``like`` that no longer matches the saved layout) propagate — they
    indicate a version problem every older step shares, and the trainers
    turn them into actionable errors."""
    for step in fallback_steps(path, on_skip=on_skip, max_step=max_step):
        ckpt_dir = os.path.join(path, step_dirname(step))
        try:
            return step, _restore_dir(ckpt_dir, like)
        except OSError as e:
            _skip(on_skip, ckpt_dir, e)
    raise FileNotFoundError(f"no restorable checkpoints under {path}")


# ---------------------------------------------------------------------------
# retention + debris sweep
# ---------------------------------------------------------------------------

def _recover_swaps(path: str, report: Dict[str, List[str]]) -> None:
    """Roll an interrupted re-save swap forward or back (never lose it).

    A crash between `rename(step_N, step_N_swap)` and
    `rename(step_N_tmp, step_N)` leaves no committed ``step_N`` but two
    recoverable dirs: the OLD committed payload in ``_swap`` and the new
    one (fully marked iff the commit reached the rename) in ``_tmp``.
    Promote the staging dir when it carries a manifest + COMMIT marker,
    otherwise restore the swap — either way a committed ``step_N``
    exists again before the debris sweep can touch the ``_tmp``."""
    for name in sorted(os.listdir(path)):
        if not (name.endswith("_swap") and _STEP_RE.match(name[:-5])):
            continue
        swap = os.path.join(path, name)
        final = os.path.join(path, name[:-5])
        staging = final + "_tmp"
        if os.path.isdir(final):
            # swap completed (or a fresh save superseded it): stale copy
            shutil.rmtree(swap, ignore_errors=True)
            report["swept"].append(name)
            continue
        promoted = False
        if (os.path.exists(os.path.join(staging, MANIFEST_FILE))
                and os.path.exists(os.path.join(staging, COMMIT_FILE))):
            try:
                os.rename(staging, final)
                promoted = True
            except OSError:
                pass
        if promoted:
            shutil.rmtree(swap, ignore_errors=True)
            report["recovered"].append(f"{name} -> promoted staged re-save")
        else:
            os.rename(swap, final)
            report["recovered"].append(f"{name} -> restored prior commit")
        _log.warning("gc: recovered interrupted re-save swap %s", name)


#: population-campaign layout markers (rl/population.py): a population
#: root holds ``member_<k>/`` directories whose ``ck/<segment>/`` subdirs
#: are ordinary verified stores, plus a ``manifest_store`` the population
#: manifest commits through.
_MEMBER_RE = re.compile(r"^member_(\d{2,})$")
POP_MANIFEST_STORE = "manifest_store"


def is_population_root(path: str) -> bool:
    """True when ``path`` looks like a population-campaign root (has
    ``member_*`` dirs or a committed population manifest store)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return False
    if os.path.isdir(os.path.join(path, POP_MANIFEST_STORE)):
        return True
    return any(_MEMBER_RE.match(d)
               and os.path.isdir(os.path.join(path, d))
               for d in os.listdir(path))


def population_member_stores(pop_root: str) -> List[Tuple[str, str]]:
    """Every per-segment checkpoint store under a population root.

    Returns ``[(member_name, store_dir), ...]`` sorted by member then
    segment — one entry per ``member_<k>/ck/<segment>/`` directory (the
    stores the member's training segments committed into; each may also
    hold an ``aborted/`` forensic bundle, which stays INSIDE the store
    like any single-learner run's).
    """
    pop_root = os.path.abspath(pop_root)
    out: List[Tuple[str, str]] = []
    if not os.path.isdir(pop_root):
        return out
    for name in sorted(os.listdir(pop_root)):
        if not _MEMBER_RE.match(name):
            continue
        ck = os.path.join(pop_root, name, "ck")
        if not os.path.isdir(ck):
            continue
        for seg in sorted(os.listdir(ck)):
            d = os.path.join(ck, seg)
            if os.path.isdir(d):
                out.append((name, d))
    return out


def gc_population(pop_root: str, keep: Optional[int] = None,
                  prune_corrupt: bool = False,
                  digests: bool = True) -> Dict[str, Dict[str, List[str]]]:
    """:func:`gc_checkpoints` across a whole population root.

    Sweeps staging debris (and applies keep-last-N retention per member
    SEGMENT store) in every ``member_*/ck/*`` store plus the population
    ``manifest_store`` — the one call ``fsck_ckpt.py --gc`` and the
    population driver use so no member's crash debris outlives the
    campaign.  Returns ``{store_path: gc report}``.
    """
    pop_root = os.path.abspath(pop_root)
    reports: Dict[str, Dict[str, List[str]]] = {}
    man = os.path.join(pop_root, POP_MANIFEST_STORE)
    if os.path.isdir(man):
        # retention never applies to the manifest store: older intervals
        # are the resume fallback chain
        reports[man] = gc_checkpoints(man, keep=None,
                                      prune_corrupt=prune_corrupt,
                                      digests=digests)
    for _member, store in population_member_stores(pop_root):
        reports[store] = gc_checkpoints(store, keep=keep,
                                        prune_corrupt=prune_corrupt,
                                        digests=digests)
    return reports


def gc_checkpoints(path: str, keep: Optional[int] = None,
                   prune_corrupt: bool = False,
                   digests: bool = True,
                   recurse: bool = False) -> Dict[str, List[str]]:
    """Clean a checkpoint store; returns a report of what happened.

    * ``recovered``: interrupted re-save swaps rolled forward/back
      (:func:`_recover_swaps`) — always runs first, so the debris sweep
      can never eat the only copy of a committed step.
    * ``swept``: stale staging debris (``step_*_tmp``, orbax tmp dirs) —
      always removed; a crash mid-save strands exactly these.
    * ``pruned``: with ``keep=N``, committed steps older than the N
      newest verified ones (corrupt dirs never count toward the budget,
      so retention can't delete the only restorable step).
    * ``corrupt``: dirs that failed verification while filling the keep
      budget — reported, removed only with ``prune_corrupt=True``.
    * ``kept``: the committed steps still present afterwards.

    Without ``keep``/``prune_corrupt`` the call is a pure sweep — no
    per-step verification runs, so the trainers can afford it after
    every save regardless of store size.  With retention on, candidates
    are digest-verified newest-first and the walk STOPS once ``keep``
    verified steps are found — everything older prunes without being
    hashed, bounding the per-save cost to the keep window
    (``digests=False`` downgrades to structure-only checks).

    Single-writer stores only (the trainers save synchronously from one
    process); a concurrent writer's live staging dir would be swept.

    ``recurse=True`` additionally walks a population root's
    ``member_*/ck/*`` stores (and its ``manifest_store``) via
    :func:`gc_population`, folding their reports into this one with
    store-relative prefixes — so one call cleans a whole policy zoo.
    """
    path = os.path.abspath(path)
    report: Dict[str, List[str]] = {"recovered": [], "swept": [],
                                    "pruned": [], "corrupt": [], "kept": []}
    if not os.path.isdir(path):
        return report
    if recurse and is_population_root(path):
        for store, rep in gc_population(path, keep=keep,
                                        prune_corrupt=prune_corrupt,
                                        digests=digests).items():
            rel = os.path.relpath(store, path)
            for k in report:
                report[k] += [os.path.join(rel, name) for name in rep[k]]
        return report
    _recover_swaps(path, report)
    for name in sorted(os.listdir(path)):
        if _is_debris(name):
            shutil.rmtree(os.path.join(path, name), ignore_errors=True)
            report["swept"].append(name)
    if keep is not None and keep > 0:
        n_verified = 0
        for step in reversed(steps(path)):
            d = os.path.join(path, step_dirname(step))
            if n_verified >= keep:
                shutil.rmtree(d, ignore_errors=True)
                report["pruned"].append(step_dirname(step))
                continue
            try:
                verify_checkpoint(d, digests=digests)
            except CheckpointCorruptError as e:
                report["corrupt"].append(step_dirname(step))
                _log.warning("gc: corrupt checkpoint %s: %s", d, e)
                if prune_corrupt:
                    shutil.rmtree(d, ignore_errors=True)
                continue
            n_verified += 1
        report["pruned"].reverse()  # oldest-first, like the store listing
        report["corrupt"].reverse()
    elif prune_corrupt:
        for step in steps(path):
            d = os.path.join(path, step_dirname(step))
            try:
                verify_checkpoint(d, digests=digests)
            except CheckpointCorruptError as e:
                report["corrupt"].append(step_dirname(step))
                _log.warning("gc: corrupt checkpoint %s: %s", d, e)
                shutil.rmtree(d, ignore_errors=True)
    report["kept"] = [step_dirname(s) for s in steps(path)]
    return report

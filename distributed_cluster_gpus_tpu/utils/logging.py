"""Singleton rotating-file logger (observability parity with the reference's
`simcore/logger_config.py`: "SIMU_DC" logger, project.log, 5 MB x 3 backups,
DEBUG level)."""

from __future__ import annotations

import logging
import os
from logging.handlers import RotatingFileHandler

_LOGGER_NAME = "SIMU_DC_TPU"
_loggers: dict[str, logging.Logger] = {}


def get_logger(log_dir: str | None = None) -> logging.Logger:
    """One rotating-file logger per log_dir (cached per directory)."""
    log_dir = os.path.abspath(log_dir or os.getcwd())
    if log_dir in _loggers:
        return _loggers[log_dir]
    logger = logging.getLogger(f"{_LOGGER_NAME}.{len(_loggers)}")
    logger.setLevel(logging.DEBUG)
    logger.propagate = False
    os.makedirs(log_dir, exist_ok=True)
    handler = RotatingFileHandler(
        os.path.join(log_dir, "project.log"),
        maxBytes=5 * 1024 * 1024,
        backupCount=3,
        encoding="utf-8",
    )
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    logger.addHandler(handler)
    _loggers[log_dir] = logger
    return logger

from .logging import get_logger
from .validators import validate_gpus

__all__ = ["get_logger", "validate_gpus"]

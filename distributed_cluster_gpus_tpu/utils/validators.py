"""Fleet configuration sanity checks.

Capability parity with `/root/reference/simcore/validators.py:5-46`: negative
power values, sleep > idle, alpha outside [1, 5], and TDP over/under-shoot,
with warn-or-raise semantics.  Operates on the FleetSpec arrays instead of
GPUType objects.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..models.structs import FleetSpec


def validate_gpus(spec: FleetSpec, tdp: Optional[np.ndarray] = None,
                  strict: bool = False) -> List[str]:
    """Return a list of warnings; raise ValueError when strict and non-empty.

    ``tdp`` is an optional [n_dc] array of declared TDP/TBP Watts.
    """
    msgs: List[str] = []
    seen = set()
    for d, gpu in enumerate(spec.gpu_names):
        # Dedup repeated (model, TDP) pairs, but never skip a DC whose own
        # declared TDP differs — per-DC tdp entries must each be checked.
        key = (gpu, None if tdp is None else float(tdp[d]))
        if key in seen:
            continue
        seen.add(key)
        prefix = f"[GPUType:{gpu}]"
        pi, pp, ps, al = (
            float(spec.p_idle[d]),
            float(spec.p_peak[d]),
            float(spec.p_sleep[d]),
            float(spec.gpu_alpha[d]),
        )
        if pi < 0 or pp < 0 or ps < 0:
            msgs.append(f"{prefix} negative power value (p_idle={pi}, p_peak={pp}, p_sleep={ps}).")
        if ps > pi + 1e-6:
            msgs.append(f"{prefix} p_sleep ({ps} W) > p_idle ({pi} W); check the config/measurements.")
        if not (1.0 <= al <= 5.0):
            msgs.append(f"{prefix} alpha={al} outside [1, 5]; should be fit from measured data.")
        if tdp is not None:
            total = pi + pp
            t = float(tdp[d])
            if total > t + 1e-6:
                msgs.append(
                    f"{prefix} p_idle + p_peak = {total:.1f} W > TDP {t:.1f} W. "
                    f"Set p_peak ~ (TDP - p_idle) for the baseline model."
                )
            if total < 0.5 * t:
                msgs.append(f"{prefix} p_idle + p_peak = {total:.1f} W << TDP {t:.1f} W (<=50%).")
    if strict and msgs:
        raise ValueError("GPU config validation failed:\n" + "\n".join(msgs))
    return msgs

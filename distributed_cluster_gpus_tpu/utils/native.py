"""ctypes loader for the native runtime pieces (C++ in /native).

Builds lazily with `make` on first use (g++ is in the image; no pybind11 —
plain C ABI via ctypes per the environment constraints) and degrades to
None so every caller keeps a pure-Python fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcsv_writer.so")
_lib = None
_tried = False


def csv_writer_lib() -> Optional[ctypes.CDLL]:
    """The csv-writer shared library, building it if needed; None on failure."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("DCG_TPU_NO_NATIVE"):
        return None
    try:
        if not os.path.exists(_LIB_PATH):
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.write_cluster_rows.restype = ctypes.c_int64
        lib.write_cluster_rows.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p,
        ]
        lib.write_job_rows.restype = ctypes.c_int64
        lib.write_job_rows.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p,
        ]
        _lib = lib
    except Exception:
        _lib = None
    return _lib

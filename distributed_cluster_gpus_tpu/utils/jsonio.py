"""Strict-JSON artifact writing shared by the eval harness and scripts.

`json.dump` emits bare ``NaN``/``Infinity`` tokens for non-finite floats —
valid Python-json, invalid JSON, and a hard parse error for jq/JS
consumers of the eval artifacts.  Every artifact writer goes through
:func:`clean_nan` (non-finite -> null) so a NaN p99 from a short run can
never corrupt a downstream pipeline.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any


def clean_nan(obj: Any) -> Any:
    """Recursively replace non-finite floats with None (JSON null)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: clean_nan(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [clean_nan(v) for v in obj]
    return obj


def dump_json_atomic(path: str, obj: Any, **kwargs) -> None:
    """Strict-JSON atomic write: clean NaNs, write ``path.tmp``, rename.

    ``kwargs`` pass through to ``json.dump`` (default indent=2,
    default=float — the artifact conventions of this repo's scripts).
    """
    kwargs.setdefault("indent", 2)
    kwargs.setdefault("default", float)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(clean_nan(obj), f, **kwargs)
    os.replace(tmp, path)

"""One shared persistent-XLA-compile-cache setup for every entry point.

The test harness (tests/conftest.py), bench.py, and the CLIs/scripts
all want the same thing: jit compiles cached on disk under the repo's
``.jax_cache`` so re-runs of unchanged programs skip XLA.  One helper
so the location and threshold cannot drift between entry points
(bench.py and conftest predate this module and keep their inline
copies — they must configure the cache before any package import).
"""

from __future__ import annotations

import os

#: repo root (this file lives at <root>/distributed_cluster_gpus_tpu/utils/)
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def setup_compile_cache(root: str = _ROOT) -> None:
    """Point jax's persistent compilation cache at ``<root>/.jax_cache``.

    Call AFTER argument parsing (imports jax) and before the first
    compile.  Failures are swallowed — the cache is an optimization.
    """
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(root, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:  # noqa: BLE001 - cache is an optimization only
        pass

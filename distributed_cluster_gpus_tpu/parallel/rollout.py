"""Vmapped multi-rollout simulation + shard_map data-parallel RL training.

The unit of scale is a *rollout*: one independent simulated world (its own
PRNG stream, its own SimState).  R rollouts stack into a leading batch axis
(vmap), the axis shards across the mesh, and each device:

1. scans its local rollouts ``chunk_steps`` events forward (policy acting
   inside the scan, batched through the same MXU matmuls);
2. scatters the chunk's transition stream into its *local* replay shard
   (experience never crosses devices — only gradients do);
3. runs one SAC train step on a local sample with `lax.pmean` gradient
   allreduce over the mesh axis.

This is the TPU-native analog of the torch/NCCL "N actors + DDP learner"
pattern, except actors and learner are one fused jitted program and the
interconnect traffic is exactly one gradient allreduce per train step.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.structs import FleetSpec, SimParams, SimState
from ..rl.cmdp import N_COSTS, constraints_from_params
from ..rl.replay import ReplayState, replay_add_chunk, replay_init
from ..rl.sac import (SACConfig, SACState, make_policy_apply, sac_init,
                      sac_train_step, sac_zero_metrics)
from ..sim.engine import Engine, init_state
from .mesh import (batch_axes, batch_pspec, make_mesh, rollout_sharding,
                   shard_map_compat)


def batched_init(fleet: FleetSpec, params: SimParams, n_rollouts: int,
                 seed: Optional[int] = None, workload=None) -> SimState:
    """Stack R independent SimStates along a leading rollout axis.

    Rollout 0 gets the UN-split ``key(seed)`` — exactly the stream a
    single-world run of the same seed sees — so distributed-trainer
    results are workload-comparable with single-rollout and heuristic
    runs (the eval harness summarizes rollout 0).  Rollouts 1..R-1 get
    independent streams from a folded chain.

    ``workload``: pass ``engine.workload`` when an Engine exists so
    trace/timeline constant tables upload once, not per init site.
    """
    base = jax.random.key(params.seed if seed is None else seed)
    if n_rollouts == 1:
        keys = base[None]
    else:
        rest = jax.random.split(jax.random.fold_in(base, 0x5eed),
                                n_rollouts - 1)
        keys = jnp.concatenate([base[None], rest])
    # one compiled workload program shared by every vmapped lane (the
    # per-lane keys vary; the spec constants do not)
    if workload is None:
        from ..workload.compiler import compile_workload

        workload = compile_workload(fleet, params)
    return jax.vmap(
        lambda k: init_state(k, fleet, params, workload=workload))(keys)


def replicated_init(fleet: FleetSpec, params: SimParams, n: int,
                    seed: Optional[int] = None, workload=None) -> SimState:
    """Stack ``n`` IDENTICAL SimStates along a leading lane axis.

    The fair-comparison counterpart of :func:`batched_init`: every lane
    starts from the SAME PRNG stream, so the workload and fault
    realizations are bit-identical across lanes and only what the caller
    varies per lane (e.g. the per-member policy weights of a population
    leaderboard eval) can make their trajectories diverge.
    """
    if workload is None:
        from ..workload.compiler import compile_workload

        workload = compile_workload(fleet, params)
    key = jax.random.key(params.seed if seed is None else seed)
    return jax.vmap(
        lambda _: init_state(key, fleet, params, workload=workload)
    )(jnp.arange(n))


def _flatten_rl(rl: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """[R_local, n_steps, ...] emission stack -> [R_local * n_steps, ...]."""
    return jax.tree.map(lambda a: a.reshape((-1,) + a.shape[2:]), rl)


def _stream_keys(engine):
    """Emission keys the rollout-0 host stream carries: the CSV schemas,
    plus the fault log and obs telemetry rows when the engine emits them
    (the stream must mirror the single-rollout emission dict so
    drain_emissions / ObsSink see the same shape either way)."""
    keys = ("t", "cluster_valid", "cluster", "job_valid", "job")
    if engine.faults_on:
        keys += ("fault_valid", "fault")
    if engine.obs_on:
        keys += ("obs", "obs_valid")
    return keys


class DistributedTrainer:
    """chsac_af training sharded over a device mesh.

    One fused program per call to :meth:`train_chunk`: R rollouts advance
    ``chunk_steps`` events and the policy takes ``sac_steps_per_chunk``
    gradient steps.  SAC params/opt state are replicated; SimStates and
    replay shards are device-local.

    chsac_af is statically superstep-ineligible (every arrival/finish
    raises a policy-tail request), so a ``SimParams.superstep_k > 1``
    compiles the same singleton program here and ``n_events`` stays
    exactly ``R * chunk_steps`` per chunk — the invariant the metrics
    and tests rely on.  Heuristic rollout sweeps that want coalescing go
    through ``Engine.run_chunk`` directly (see bench.py's superstep
    sweep), where ``chunk_steps`` counts scan ITERATIONS and
    ``n_events`` reports the true event count.
    """

    def __init__(self, fleet: FleetSpec, params: SimParams,
                 n_rollouts: int,
                 mesh: Optional[Mesh] = None,
                 replay_capacity_per_shard: int = 50_000,
                 sac_steps_per_chunk: int = 1,
                 seed: int = 0,
                 stream_rollout0: bool = False):
        assert params.algo == "chsac_af"
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        assert n_rollouts % n_dev == 0, (
            f"n_rollouts={n_rollouts} must divide over {n_dev} devices")
        self.fleet, self.params = fleet, params
        self.n_rollouts = n_rollouts
        self.sac_steps_per_chunk = sac_steps_per_chunk
        # stream_rollout0: also return rollout 0's cluster/job emission
        # stream each chunk so the CLI can write reference CSVs while the
        # other R-1 worlds feed the replay (run_sim.py --rollouts N).
        self.stream_rollout0 = stream_rollout0
        self.rollout0_emissions = None

        obs_dim = params.obs_dim(fleet.n_dc)
        self.cfg = SACConfig(
            obs_dim=obs_dim, n_dc=fleet.n_dc, n_g=params.max_gpus_per_job,
            batch=params.rl_batch,
            constraints=constraints_from_params(params),
            critic_arch=params.critic_arch,
        )
        self.engine = Engine(fleet, params,
                             policy_apply=make_policy_apply(self.cfg))

        # fold_in: rollout 0 consumes the raw key(seed) (workload parity
        # with single-world runs, see batched_init) — the learner chain
        # must not split that same key or its sampling keys collide with
        # rollout 0's sim keys bit-for-bit
        key = jax.random.fold_in(jax.random.key(seed), 0x7A31)
        k_sac, self._host_key = jax.random.split(key)
        self.sac: SACState = sac_init(self.cfg, k_sac)

        # device-local replay shards live as one array with a leading
        # device axis sharded over the mesh
        rb1 = replay_init(replay_capacity_per_shard, obs_dim,
                          fleet.n_dc, params.max_gpus_per_job, N_COSTS)
        self.replay: ReplayState = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (n_dev,) + a.shape), rb1)

        self.states: SimState = batched_init(fleet, params, n_rollouts,
                                             seed,
                                             workload=self.engine.workload)
        # pin shardings
        shard = rollout_sharding(self.mesh)
        repl = NamedSharding(self.mesh, P())
        self.states = jax.device_put(self.states, shard)
        self.replay = jax.device_put(self.replay, shard)
        self.sac = jax.device_put(self.sac, repl)
        self._step_fns = {}

    # ------------------------------------------------------------------

    def _build_step(self, chunk_steps: int):
        """shard_map program: local rollout scan + replay ingest + SAC steps.

        Collectives name every mesh axis (``("dcn", "rollout")`` on a
        2-axis mesh), so gradient sync lowers to the hierarchical
        ICI-then-DCN pattern on multi-host meshes and a plain ICI
        allreduce on one host."""
        mesh, cfg, engine = self.mesh, self.cfg, self.engine
        ax = batch_axes(mesh)
        n_sac = self.sac_steps_per_chunk
        warmup = self.params.rl_warmup
        stream0 = self.stream_rollout0

        def local_step(states, replay, sac, key):
            # states: [R_local, ...]; replay: [1, ...] local shard; sac: replicated
            replay = jax.tree.map(lambda a: a[0], replay)

            states, emissions = jax.vmap(
                lambda st: engine._run_chunk(st, sac, chunk_steps))(states)
            replay = replay_add_chunk(replay, _flatten_rl(emissions["rl"]))

            # gate learning on warmup with a mesh-agreed predicate (pmin):
            # shards accumulate transitions at different rates, and the
            # collectives inside sac_train_step must run on all shards or
            # none.  Until every shard is warmed up, updates are skipped and
            # zero-valued metrics keep the output structure static.
            # n_seen (monotone experience count), not size: ring garbage
            # tails can cap size below capacity and deadlock a size gate
            warmed = jax.lax.pmin(replay.n_seen, ax) >= warmup

            def one_sac(sac_c, k):
                # replay is loop-invariant (closure, not carry) so XLA can
                # hoist the sample CDF out of the scan

                def train(op):
                    s, kk = op
                    return sac_train_step(cfg, s, replay, kk, axis_name=ax)

                def skip(op):
                    s, _ = op
                    return s, sac_zero_metrics(cfg, s)

                sac_c, metrics = jax.lax.cond(warmed, train, skip, (sac_c, k))
                return sac_c, metrics

            keys = jax.random.split(jax.random.fold_in(key, jax.lax.axis_index(ax)),
                                    n_sac)
            sac, metrics = jax.lax.scan(one_sac, sac, keys)
            metrics = jax.tree.map(lambda a: a[-1], metrics)
            # metrics identical across shards after pmean'd grads? losses are
            # shard-local; average them for reporting
            metrics = jax.lax.pmean(metrics, ax)
            n_finished = jax.lax.psum(jnp.sum(states.n_finished), ax)
            n_events = jax.lax.psum(jnp.sum(states.n_events), ax)
            metrics = dict(metrics, n_finished=n_finished, n_events=n_events,
                           warmed=warmed,
                           replay_size=jax.lax.pmax(replay.size, ax))
            replay = jax.tree.map(lambda a: a[None], replay)
            # rollout 0's CSV stream (global rollout 0 = shard 0, local 0):
            # every shard emits its local rollout 0 with a leading [1] axis so
            # the stacked global output is [n_dev, ...]; the host keeps row 0.
            stream = {k: emissions[k][0][None]
                      for k in _stream_keys(engine)} if stream0 else {}
            return states, replay, sac, metrics, stream

        shard = batch_pspec(mesh)
        repl = P()
        fn = shard_map_compat(
            local_step, mesh=mesh,
            in_specs=(shard, shard, repl, repl),
            out_specs=(shard, shard, repl, repl, shard),
            check_vma=False,
        )
        # donate the batched sim states + replay shards: both are rebound
        # every chunk, and an undonated dispatch copies the whole carry
        # (the queue rings alone are ~1.3 GB at week-scale queue_cap x 8
        # rollouts — same aliasing lever as Engine._run_chunk_jit)
        return jax.jit(fn, donate_argnums=(0, 1))

    def train_chunk(self, chunk_steps: int = 1024):
        """Advance all rollouts one chunk + train; returns host metrics dict.

        With ``stream_rollout0`` the chunk's rollout-0 cluster/job emission
        stream lands in ``self.rollout0_emissions`` (drain with
        `sim.io.drain_emissions`).
        """
        if chunk_steps not in self._step_fns:
            self._step_fns[chunk_steps] = self._build_step(chunk_steps)
        self._host_key, k = jax.random.split(self._host_key)
        self.states, self.replay, self.sac, metrics, stream = self._step_fns[chunk_steps](
            self.states, self.replay, self.sac, k)
        if self.stream_rollout0:
            self.rollout0_emissions = jax.tree.map(lambda a: a[0], stream)
        return metrics

    @property
    def all_done(self) -> bool:
        return bool(jnp.all(self.states.done))

    # -- checkpoint / resume -------------------------------------------------

    def save(self, ckpt_dir: str, step: int, metadata=None, **extra) -> str:
        """Checkpoint the full batched pipeline (SAC, replay shards, R sim
        states, host PRNG key) plus any caller pytrees (e.g. the CSV byte
        watermark) — one atomic verified save (staging dir + manifest +
        commit rename), so a crash can never leave the trainer state and
        its companions at different steps, or a partial step that resume
        would pick up.  ``metadata`` lands in the manifest."""
        from ..utils.checkpoint import save_checkpoint

        return save_checkpoint(ckpt_dir, step, metadata=metadata,
                               sac=self.sac, replay=self.replay,
                               states=self.states, key=self._host_key, **extra)

    def restore(self, ckpt_dir: str, step: Optional[int] = None,
                extra_like: Optional[dict] = None):
        """Restore the latest verified (or given) step; re-places arrays
        under the mesh shardings.  ``step=None`` walks the fallback chain
        — an uncommitted/corrupt newest checkpoint is skipped with a
        logged reason.  Returns (step, extras dict per ``extra_like``)."""
        from ..utils.checkpoint import restore_checkpoint, restore_latest

        like = {"sac": self.sac, "replay": self.replay,
                "states": self.states, "key": self._host_key}
        like.update(extra_like or {})
        if step is None:
            step, out = restore_latest(ckpt_dir, like=like)
        else:
            out = restore_checkpoint(ckpt_dir, step, like=like)
        shard = rollout_sharding(self.mesh)
        repl = NamedSharding(self.mesh, P())
        self.sac = jax.device_put(out["sac"], repl)
        self.replay = jax.device_put(out["replay"], shard)
        self.states = jax.device_put(out["states"], shard)
        self._host_key = out["key"]
        return step, {k: out[k] for k in (extra_like or {})}


class PPOTrainer:
    """On-policy PPO sharded over the mesh (BASELINE config 5 shape).

    Each device scans its local rollouts one chunk, then the chunk's
    transition stream IS the training batch — masked, fixed-shape, no
    replay.  Gradients pmean over the rollout axis; params stay replicated.

    Notes on the API:

    * The engine's RL hooks (act-at-arrival, transition emission) are keyed
      on ``algo == "chsac_af"``; PPO rides the same hooks with its own
      policy/update, so any ``params.algo`` is coerced to ``"chsac_af"``
      here — callers don't need to know the hook name.
    * ``PPOConfig`` takes no discount ``gamma``: episodes are single-step
      (``done=True`` on every transition, reference
      ``simulator_paper_multi.py:799``), so the return IS the reward and a
      discount would have nothing to multiply.
    """

    def __init__(self, fleet: FleetSpec, params: SimParams,
                 n_rollouts: int,
                 mesh: Optional[Mesh] = None,
                 seed: int = 0,
                 stream_rollout0: bool = False):
        import dataclasses

        from ..rl.ppo import PPOConfig, make_ppo_policy_apply, ppo_init

        if params.algo != "chsac_af":
            params = dataclasses.replace(params, algo="chsac_af")
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        assert n_rollouts % n_dev == 0
        self.fleet, self.params = fleet, params
        self.n_rollouts = n_rollouts
        # mirror DistributedTrainer: emit rollout 0's cluster/job stream for
        # reference-CSV writing (run_sim.py --algo ppo)
        self.stream_rollout0 = stream_rollout0
        self.rollout0_emissions = None

        self.cfg = PPOConfig(
            obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
            n_g=params.max_gpus_per_job,
            constraints=constraints_from_params(params),
        )
        self.engine = Engine(fleet, params,
                             policy_apply=make_ppo_policy_apply(self.cfg))
        self.ppo = ppo_init(
            self.cfg, jax.random.fold_in(jax.random.key(seed), 0x7A31))
        self.states: SimState = batched_init(fleet, params, n_rollouts,
                                             seed,
                                             workload=self.engine.workload)

        shard = rollout_sharding(self.mesh)
        repl = NamedSharding(self.mesh, P())
        self.states = jax.device_put(self.states, shard)
        self.ppo = jax.device_put(self.ppo, repl)
        self._step_fns = {}

    def _build_step(self, chunk_steps: int):
        from ..rl.ppo import ppo_update

        mesh, cfg, engine = self.mesh, self.cfg, self.engine
        stream0 = self.stream_rollout0

        ax = batch_axes(mesh)

        def local_step(states, ppo):
            states, emissions = jax.vmap(
                lambda st: engine._run_chunk(st, ppo, chunk_steps))(states)
            batch = _flatten_rl(emissions["rl"])
            ppo, metrics = ppo_update(cfg, ppo, batch, axis_name=ax)
            # losses are shard-local: pmean for reporting (counts psum) so
            # the P() out_spec really is replicated
            n_tr = jax.lax.psum(metrics.pop("n_transitions"), ax)
            metrics = jax.lax.pmean(metrics, ax)
            metrics = dict(
                metrics,
                n_transitions=n_tr,
                n_events=jax.lax.psum(jnp.sum(states.n_events), ax),
                n_finished=jax.lax.psum(jnp.sum(states.n_finished), ax),
            )
            stream = {k: emissions[k][0][None]
                      for k in _stream_keys(engine)} if stream0 else {}
            return states, ppo, metrics, stream

        shard, repl = batch_pspec(mesh), P()
        fn = shard_map_compat(local_step, mesh=mesh,
                              in_specs=(shard, repl),
                              out_specs=(shard, repl, repl, shard),
                              check_vma=False)
        # donate the batched sim states (rebound every chunk; see
        # DistributedTrainer._build_step)
        return jax.jit(fn, donate_argnums=(0,))

    def train_chunk(self, chunk_steps: int = 1024):
        if chunk_steps not in self._step_fns:
            self._step_fns[chunk_steps] = self._build_step(chunk_steps)
        self.states, self.ppo, metrics, stream = self._step_fns[chunk_steps](
            self.states, self.ppo)
        if self.stream_rollout0:
            self.rollout0_emissions = jax.tree.map(lambda a: a[0], stream)
        return metrics

    @property
    def all_done(self) -> bool:
        return bool(jnp.all(self.states.done))

    # -- checkpoint / resume (mirrors DistributedTrainer) ------------------

    def save(self, ckpt_dir: str, step: int, metadata=None, **extra) -> str:
        from ..utils.checkpoint import save_checkpoint

        return save_checkpoint(ckpt_dir, step, metadata=metadata,
                               ppo=self.ppo, states=self.states, **extra)

    def restore(self, ckpt_dir: str, step: Optional[int] = None,
                extra_like: Optional[dict] = None):
        from ..utils.checkpoint import restore_checkpoint, restore_latest

        like = {"ppo": self.ppo, "states": self.states}
        like.update(extra_like or {})
        if step is None:
            # verified fallback chain (corrupt steps skipped with a log)
            step, out = restore_latest(ckpt_dir, like=like)
        else:
            out = restore_checkpoint(ckpt_dir, step, like=like)
        shard = rollout_sharding(self.mesh)
        self.ppo = jax.device_put(out["ppo"], NamedSharding(self.mesh, P()))
        self.states = jax.device_put(out["states"], shard)
        return step, {k: out[k] for k in (extra_like or {})}


def engine_shard_parity(fleet: FleetSpec, params: SimParams, mesh: Mesh,
                        n_rollouts: int, chunk_steps: int = 32) -> None:
    """Assert the vmapped engine chunk is bit-identical on one device vs
    shard_mapped over ``mesh`` (raises on any mismatching leaf).

    Uses a deterministic elementwise policy stub: the real actor's bf16
    matmul reduction order changes with the per-device batch shape (B=R on
    one device vs B=R/n per device), which can flip a *sampled* action —
    so bitwise parity is a property of the sharded ENGINE program, which
    is what this checks.  Shared by tests/test_parallel.py and the
    driver's `__graft_entry__.dryrun_multichip`.

    Superstep engines (``params.superstep_k > 1``, non-RL) are accepted:
    there ``chunk_steps`` counts scan iterations and each pre-``done``
    iteration fires AT LEAST one event (the unified body's slot 0), so
    the exact-count invariant relaxes to a lower bound while the
    bit-parity assertion stays leaf-exact.
    """
    import numpy as np

    def stub_policy(pp, obs, m_dc, m_g, key):
        # deterministic, elementwise, mask-respecting: first allowed dc/g
        return (jnp.argmax(m_dc).astype(jnp.int32),
                jnp.argmax(m_g).astype(jnp.int32))

    eng = Engine(fleet, params, policy_apply=stub_policy)
    states = batched_init(fleet, params, n_rollouts, workload=eng.workload)
    run = jax.vmap(lambda st: eng._run_chunk(st, None, chunk_steps)[0])

    mesh1 = make_mesh(1)
    out1 = jax.jit(run)(jax.device_put(
        states, NamedSharding(mesh1, P(*mesh1.axis_names))))
    spec = batch_pspec(mesh)
    outN = jax.jit(shard_map_compat(
        run, mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False))(jax.device_put(states, rollout_sharding(mesh)))

    total_events = int(np.asarray(out1.n_events).sum())
    if eng.superstep_on:
        assert total_events >= n_rollouts * chunk_steps
    else:
        assert total_events == n_rollouts * chunk_steps
    for a, b in zip(jax.tree.leaves(out1), jax.tree.leaves(outN)):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):  # typed PRNG keys
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

"""Scale-out layer: device mesh, sharded rollouts, distributed RL training.

The reference is a single Python process with no distributed backend
(SURVEY.md §5 "Distributed communication backend: absent").  Here the
communication backend is the JAX runtime itself: rollouts are vmapped into a
batch axis, that axis is sharded across a `jax.sharding.Mesh` (ICI within a
slice, DCN across hosts), and RL gradients allreduce with `lax.pmean` inside
`shard_map` — the TPU-native equivalent of a NCCL/MPI data-parallel loop.
"""

from .mesh import make_mesh, rollout_sharding  # noqa: F401
from .rollout import (DistributedTrainer, batched_init,  # noqa: F401
                      engine_shard_parity)

"""Mesh construction + sharding specs for the rollout batch axis.

Axes:

* ``rollout`` — data parallelism over the devices of one ICI domain (a
  TPU slice); gradient allreduce rides ICI.
* ``dcn`` (optional) — the inter-host / inter-slice axis (SURVEY.md §5
  "distributed communication backend").  With a 2-axis mesh the rollout
  batch shards over BOTH axes and every collective names both, so XLA
  lowers gradient sync to the hierarchical pattern (reduce-scatter over
  ICI, allreduce over DCN, all-gather over ICI) that multi-host TPU
  deployments want.  On one host the axis still compiles and executes
  (the "dcn" hops are just more ICI), which is how the CPU dryrun tests
  validate the multi-host program without a cluster.

For a real multi-host run, build the mesh from
`jax.experimental.mesh_utils.create_hybrid_device_mesh` (which knows the
physical host topology) and pass it in; `make_mesh(dcn=k)` reshapes the
flat device list, which is correct whenever `jax.devices()` enumerates
hosts contiguously (it does for TPU pods).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROLLOUT_AXIS = "rollout"
DCN_AXIS = "dcn"


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` across the supported JAX range.

    Newer releases expose it as ``jax.shard_map(..., check_vma=...)``;
    older ones (<= 0.4.x) only have ``jax.experimental.shard_map.shard_map``
    with the equivalent knob spelled ``check_rep``.  All call sites pass
    the same (mesh, in_specs, out_specs) surface either way.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def make_mesh(n_devices: Optional[int] = None, axis: str = ROLLOUT_AXIS,
              dcn: int = 1) -> Mesh:
    """Mesh over the first ``n_devices`` devices (all by default).

    ``dcn=1`` (default): 1-D mesh, pure rollout data parallelism —
    collectives are one allreduce riding ICI.  ``dcn=k``: 2-D
    ``(dcn, rollout)`` mesh of shape (k, n/k) for multi-host scale-out.
    """
    devs = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    if dcn <= 1:
        return Mesh(np.asarray(devs), (axis,))
    n = len(devs)
    if n % dcn:
        raise ValueError(f"{n} devices do not split into dcn={dcn} groups")
    return Mesh(np.asarray(devs).reshape(dcn, n // dcn), (DCN_AXIS, axis))


def batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh axes the rollout batch shards over (and collectives name)."""
    return tuple(mesh.axis_names)


def batch_pspec(mesh: Mesh) -> P:
    """PartitionSpec sharding the leading (rollout) axis over the mesh.

    Canonicalized: a 1-axis mesh yields ``P("rollout")`` — older JAX keeps
    ``P(("rollout",))`` as a distinct (unequal) spec, so the tuple form is
    only used when the batch really shards over several axes."""
    ax = batch_axes(mesh)
    return P(ax if len(ax) > 1 else ax[0])


def rollout_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (rollout) axis of every leaf across the whole mesh
    — both axes of a ``(dcn, rollout)`` mesh, just ``rollout`` of a 1-D one.
    """
    return NamedSharding(mesh, batch_pspec(mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

"""Mesh construction + sharding specs for the rollout batch axis.

Axes:

* ``rollout`` — data parallelism over the devices of one ICI domain (a
  TPU slice); gradient allreduce rides ICI.
* ``dcn`` (optional) — the inter-host / inter-slice axis (SURVEY.md §5
  "distributed communication backend").  With a 2-axis mesh the rollout
  batch shards over BOTH axes and every collective names both, so XLA
  lowers gradient sync to the hierarchical pattern (reduce-scatter over
  ICI, allreduce over DCN, all-gather over ICI) that multi-host TPU
  deployments want.  On one host the axis still compiles and executes
  (the "dcn" hops are just more ICI), which is how the CPU dryrun tests
  validate the multi-host program without a cluster.

For a real multi-host run, build the mesh from
`jax.experimental.mesh_utils.create_hybrid_device_mesh` (which knows the
physical host topology) and pass it in; `make_mesh(dcn=k)` reshapes the
flat device list, which is correct whenever `jax.devices()` enumerates
hosts contiguously (it does for TPU pods).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROLLOUT_AXIS = "rollout"
DCN_AXIS = "dcn"


def make_mesh(n_devices: Optional[int] = None, axis: str = ROLLOUT_AXIS,
              dcn: int = 1) -> Mesh:
    """Mesh over the first ``n_devices`` devices (all by default).

    ``dcn=1`` (default): 1-D mesh, pure rollout data parallelism —
    collectives are one allreduce riding ICI.  ``dcn=k``: 2-D
    ``(dcn, rollout)`` mesh of shape (k, n/k) for multi-host scale-out.
    """
    devs = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    if dcn <= 1:
        return Mesh(np.asarray(devs), (axis,))
    n = len(devs)
    if n % dcn:
        raise ValueError(f"{n} devices do not split into dcn={dcn} groups")
    return Mesh(np.asarray(devs).reshape(dcn, n // dcn), (DCN_AXIS, axis))


def batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh axes the rollout batch shards over (and collectives name)."""
    return tuple(mesh.axis_names)


def rollout_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (rollout) axis of every leaf across the whole mesh
    — both axes of a ``(dcn, rollout)`` mesh, just ``rollout`` of a 1-D one.
    """
    return NamedSharding(mesh, P(batch_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

"""Mesh construction + sharding specs for the rollout batch axis."""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROLLOUT_AXIS = "rollout"


def make_mesh(n_devices: Optional[int] = None, axis: str = ROLLOUT_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (all by default).

    Rollout batch parallelism is a single mesh axis: collectives are pure
    allreduce (gradient pmean), which rides ICI bidirectionally regardless of
    the physical torus layout, so no 2-D axis split is needed until
    multi-host DCN enters (then: ("dcn", "rollout") with generalized
    device order via jax.make_mesh's allow_split_physical_axes).
    """
    devs = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def rollout_sharding(mesh: Mesh, axis: str = ROLLOUT_AXIS) -> NamedSharding:
    """Shard the leading (rollout) axis of every leaf across the mesh."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

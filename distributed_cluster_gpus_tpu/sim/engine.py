"""Event-exact scanned simulation engine.

The reference drains a heapq of 5 event types
(`/root/reference/simcore/simulator_paper_multi.py:423-467`).  That shape
doesn't map to XLA, so this engine reformulates the same continuous-time
semantics as a `lax.scan` whose every step:

1. computes the next event time as a vectorized min over (a) the per-ingress
   arrival clocks, (b) projected finish times of all running jobs in the slab,
   (c) pending WAN-transfer completions, (d) the log/control tick;
2. accrues energy (E += P * dt) and utilisation (busy * dt) per DC over the
   exact inter-event gap, and advances every running job's `units_done` by
   dt / T(n, f) — because remaining time is recomputed from `units_done`
   each step, mid-job DVFS changes need no event invalidation: the
   reference's `ev_gen` lazy-invalidation race machinery is eliminated by
   construction (SURVEY.md §5 "race detection");
3. dispatches exactly one event through `lax.switch` (ties break
   finish < xfer < arrival < log, then lowest index; coincident events
   resolve on consecutive zero-dt steps).

State is one pytree (`SimState`), so whole rollouts vmap across a batch axis
and shard across a device mesh.  Emissions (cluster rows, job rows, RL
transitions) stream out of the scan as fixed-shape per-step records with
validity flags; the host drains them into the reference's two CSV schemas.

Known divergences from the reference (deliberate, SURVEY.md §7.4):
* `cap_uniform` in the reference is behaviorally inert: its ΔP estimate uses
  per-job `f_used`, which a DC-ladder change never touches, so every ΔP is 0
  and the controller exits immediately.  Here it implements the *intended*
  semantics: lowering a DC one ladder step clamps every running job in that
  DC to the new frequency, and ΔP is the exact resulting power drop.
* `cap_greedy` reproduces the reference's full atom-ladder semantics
  (`freq_load_agg.py:44-80` + the apply loop at
  `simulator_paper_multi.py:282-316`): every adjacent ladder step below a
  running job's current frequency is an atom scored by its own-endpoint
  ρ = ΔP/ΔV, and applying an atom sets the job's frequency directly to the
  atom's lower endpoint — a multi-step JUMP whenever a deeper step is
  cheaper, which with the paper's coefficients is the norm (ρ shrinks
  monotonically down every ladder), with exact power re-estimation after
  each applied atom.  Tie-breaking differs (reference: stable sort in dc/
  job declaration order; here: first flat (job, step) index).
* the control tick runs every `log_interval` like the reference (its
  `--control-interval` flag is parsed but never scheduled).
* arrivals that find the job slab full are counted in `n_dropped` (the
  reference's Python lists are unbounded; size `SimParams.job_cap` to the
  workload).
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..fault.state import FK_DC_DOWN, FK_DC_UP, FK_DERATE, FK_WAN
from ..models.structs import (
    ALGO_BANDIT,
    ALGO_CAP_GREEDY,
    ALGO_CAP_UNIFORM,
    ALGO_CARBON_COST,
    ALGO_CHSAC_AF,
    ALGO_DEBUG,
    ALGO_ECO_ROUTE,
    ALGO_JOINT_NF,
    DCArrays,
    FleetSpec,
    JobSlab,
    JobStatus,
    LatWindow,
    QRec,
    QueueRings,
    SimParams,
    SimState,
)
from ..ops.bandit import bandit_init, bandit_select, bandit_update
from ..ops.optimizers import min_n_for_sla
from ..ops.physics import fmul_pinned, step_time_s, task_power_w
from . import algos

# event kinds (tie-break order: earlier kind wins at equal times).
# EV_FAULT only exists in fault-enabled programs (SimParams.faults set);
# it loses ties to the four base kinds, so a finish coincident with an
# outage onset completes before the preemption sweep (zero-dt steps).
EV_FINISH, EV_XFER, EV_ARRIVAL, EV_LOG, EV_FAULT = 0, 1, 2, 3, 4

BIG = 2**30  # plain int: a module-level jnp array would init the JAX
# backend at import time (hangs CLI entry points when the TPU tunnel is down)


def static_ineligibility(params: SimParams) -> dict:
    """Why a config cannot compile each fast-path program (round 12).

    Returns ``{"superstep": [reasons], "planner": [reasons]}`` — empty
    lists mean the gate opens.  A pure function of ``SimParams`` (no
    workload compile, no device), so CLIs can report eligibility before
    building an Engine, and the census tool / regression tests pin that
    these lists never silently regrow.  The residue after round 12:

    * superstep — chsac_af (the policy tail acts on every event, so
      steps are singleton by construction), bandit (its per-finish
      reward update and per-start select thread one BanditState through
      the events, an ordering the fused handler does not reproduce),
      and weighted routing (its DC score reads queue lengths, which
      earlier in-window events at other DCs can change).  Fault and
      signal-timeline runs became eligible in round 12: EV_FAULT
      windows degenerate to L=1 through a masked slot-0 handler (fused
      windows additionally require no PREEMPTED backlog, so the
      migration sweep stays per-event), and the fused body now accrues
      the price/carbon cost integral per sub-step.
    * planner — EMPTY.  The round-9 holdouts all landed in round 12:
      bandit rides the plan's ``bandit`` carry (the switch output
      select is part of the cond primitive) + the masked drain's
      predicated select/update; fault runs keep the EV_FAULT branch's
      whole-array masked writes in-branch (like the log tick) while
      the row events plan; chsac+elastic relocates the reallocation
      sweep to right after the commit (same position, same values).
    """
    superstep = []
    if params.algo == ALGO_CHSAC_AF:
        superstep.append("rl_policy_tail: chsac_af raises a policy-tail "
                         "request on every arrival/finish, so steps are "
                         "singleton by construction")
    if params.algo == ALGO_BANDIT:
        superstep.append("bandit_state: the per-finish reward update and "
                         "per-start select thread one BanditState through "
                         "the events in order")
    if params.router_weights is not None:
        superstep.append("queue_coupled_routing: --router-weights scores "
                         "read queue lengths, which earlier in-window "
                         "events at other DCs can change")
    return {"superstep": superstep, "planner": []}


# ---------------------------------------------------------------------------
# TPU-friendly single-index updates and tiny-axis reductions.
#
# Under vmap, `arr.at[j].set(v)` lowers to a batched dynamic scatter and
# `segment_sum` to a batched scatter-add — both serialize badly on TPU and
# dominated the profiled step time (~12 ms/step at [R=256, J=256]).  A masked
# whole-array select and a one-hot contraction compute the same values as
# pure elementwise/reduce ops that vectorize across the rollout batch.
# ---------------------------------------------------------------------------

def _mask1(arr, j):
    m = jnp.arange(arr.shape[0]) == j
    if arr.ndim > 1:
        m = m.reshape((arr.shape[0],) + (1,) * (arr.ndim - 1))
    return m


def set_at(arr, j, v):
    """`arr.at[j].set(v)` as a masked write (v broadcasts over row shape)."""
    return jnp.where(_mask1(arr, j), v, arr)


def add_at(arr, j, v):
    """`arr.at[j].add(v)` as a masked write."""
    return jnp.where(_mask1(arr, j), arr + v, arr)


def set_at2(arr, i, j, v):
    """`arr.at[i, j].set(v)` for 2-D arr."""
    m = (jnp.arange(arr.shape[0]) == i)[:, None] & (jnp.arange(arr.shape[1]) == j)[None, :]
    return jnp.where(m, v, arr)


def add_at2(arr, i, j, v):
    """`arr.at[i, j].add(v)` for 2-D arr."""
    m = (jnp.arange(arr.shape[0]) == i)[:, None] & (jnp.arange(arr.shape[1]) == j)[None, :]
    return jnp.where(m, arr + v, arr)


def slab_write(jobs: JobSlab, j, _pred=None, **fields) -> JobSlab:
    """Write several JobSlab fields at slot j with one shared mask.

    ``_pred`` (scalar bool) additionally gates every write — the
    building block of predicated commits that run unconditionally under
    vmap but only take effect on lanes where the condition holds."""
    def mask(arr):
        m = _mask1(arr, j)
        return m if _pred is None else m & _pred

    return jobs.replace(**{
        k: jnp.where(mask(getattr(jobs, k)), v, getattr(jobs, k))
        for k, v in fields.items()
    })


def tree_sum_last(x):
    """Sum over the last axis with a FIXED halving-tree association.

    `jnp.sum` lowers to an XLA reduce whose accumulation order is
    implementation-defined and varies with the surrounding fusion context
    — measured on CPU: the same [n_dc, J] power sum rounds to different
    f32 ulps in differently-structured programs, which breaks the
    superstep's bit-identity-with-K=1 guarantee (and any other cross-
    program golden).  Explicit elementwise adds pin one association that
    XLA must honor; log2(J) adds cost the same FLOPs as the reduce."""
    n = x.shape[-1]
    p = 1
    while p < n:
        p *= 2
    if p != n:  # zero-pad to a power of two (x + 0.0 is exact)
        x = jnp.concatenate(
            [x, jnp.zeros(x.shape[:-1] + (p - n,), x.dtype)], axis=-1)
    while p > 1:
        p //= 2
        x = x[..., :p] + x[..., p:]
    return x[..., 0]


def dc_count(vals, dc_idx, n_dc: int):
    """Integer `segment_sum(vals, dc_idx)` over the tiny DC axis.

    Integer sums are exact under ANY reduce order, so the fixed-tree
    association `dc_sum` pins (a float-rounding fence) buys nothing here
    — one native int32 reduce replaces the log2(J) explicit add tree
    (~20 fewer eqns per site in the op-count-bound step).  Use for
    counts only; float accumulators stay on :func:`dc_sum`."""
    m = dc_idx[None, :] == jnp.arange(n_dc)[:, None]
    return jnp.sum(jnp.where(m, vals[None, :].astype(jnp.int32),
                             jnp.int32(0)),
                   axis=-1)


def dc_sum(vals, dc_idx, n_dc: int):
    """`segment_sum(vals, dc_idx)` over the tiny DC axis as a masked reduce.

    [n_dc, J] compare + f32 sum — NOT an einsum/one-hot matmul: TPU matmuls
    multiply in bf16 by default, which rounds integer counts above 256 and
    silently corrupts GPU/queue accounting.  Elementwise select + a
    fixed-order tree sum stays exact in f32 (and bit-stable across program
    structures — see :func:`tree_sum_last`)."""
    m = dc_idx[None, :] == jnp.arange(n_dc)[:, None]
    return tree_sum_last(jnp.where(m, vals[None, :].astype(jnp.float32), 0.0))

CLUSTER_COLS = (
    "time_s", "freq", "busy", "free", "run_total", "run_inf", "run_train",
    "q_inf", "q_train", "util_inst", "util_avg", "acc_job_unit", "power_W",
    "energy_kJ",
)
JOB_COLS = (
    "jid", "ingress", "type", "size", "dc", "f_used", "n_gpus", "net_lat_s",
    "start_s", "finish_s", "latency_s", "preempt_count", "T_pred", "P_pred",
    "E_pred",
)
# extra cluster columns appended (in this order) when faults are enabled;
# the fault_log.csv record layout lives with its writer (io.FAULT_LOG_HEADER)
FAULT_CLUSTER_COLS = ("up", "derate_f")
# extra cluster columns appended when the workload declares price/carbon
# signal timelines (after the fault columns if both are enabled): the
# sampled energy price and the DC's carbon intensity at the log tick
SIGNAL_CLUSTER_COLS = ("price_usd_kwh", "carbon_g_kwh")


def auto_queue_cap(params: SimParams, fleet: FleetSpec,
                   rollouts: int = 1) -> int:
    """Per-(dc, jtype) ring depth that can absorb the whole run's arrivals.

    The reference queues arrivals unboundedly
    (`/root/reference/simcore/models.py:61-62`); rings restore that
    behavior as long as no single ring overflows.  The safe bound is the
    total arrival count (routing can concentrate every job on one DC —
    e.g. eco_route), padded 30% for rate fluctuation and clamped to
    [1024, 2^18] with a ~2 GiB total-ring-memory guard across rollouts
    (record bytes follow the run's time dtype — float64 on long-horizon
    runs).  Steady-state runs never come near the bound; the clamps only
    bite unbounded-duration shapes (e.g. trainer duration=1e9), where a
    queue this deep means the workload itself is divergent.
    """
    if params.workload is not None:
        rate = params.workload.mean_rate(fleet.n_ing)
    else:
        rate = 0.0
        if params.inf_mode != "off":
            rate += params.inf_rate * fleet.n_ing
        if params.trn_mode != "off":
            rate += params.trn_rate * fleet.n_ing
    need = int(min(params.duration, 1e7) * rate * 1.3) + 1024
    rec_bytes = QRec.N_FIELDS * (8 if params.time_dtype == "float64" else 4)
    mem_cap = max(1024, int((2 << 30)
                            // (max(1, rollouts) * fleet.n_dc * 2
                               * rec_bytes)))
    return int(max(1024, min(need, 1 << 18, mem_cap)))


def init_state(key, fleet: FleetSpec, params: SimParams,
               workload=None) -> SimState:
    """Fresh SimState at t=0 with primed arrival clocks.

    ``workload`` accepts an already-compiled WorkloadProgram (pass
    ``engine.workload`` when an Engine exists) so big trace/timeline
    constant tables are not resolved and uploaded twice per run; None
    compiles one from ``params`` — same values either way."""
    from ..workload.compiler import compile_workload

    J = params.job_cap
    n_dc, n_ing = fleet.n_dc, fleet.n_ing
    td = params.tdtype
    obs_dim = params.obs_dim(n_dc)

    key, k_arr = jax.random.split(key)
    # initial clocks are draw #0 of each stream's dedicated chain (the same
    # chain the pregenerated tables continue, so the whole realized
    # workload is a pure function of this key); the workload compiler owns
    # the draw for every stream kind (legacy synthetic fields included)
    if workload is None:
        workload = compile_workload(fleet, params)
    clocks = workload.init_clocks(k_arr, td)

    zf = lambda shape=(): jnp.zeros(shape, dtype=td)  # noqa: E731
    zi = lambda shape=(): jnp.zeros(shape, dtype=jnp.int32)  # noqa: E731

    jobs = JobSlab(
        status=zi((J,)), jtype=zi((J,)), ingress=zi((J,)), dc=zi((J,)),
        seq=zi((J,)),
        size=jnp.zeros((J,), jnp.float32), units_done=jnp.zeros((J,), jnp.float32),
        n=zi((J,)), f_idx=zi((J,)),
        t_ingress=zf((J,)), t_avail=zf((J,)), t_start=zf((J,)),
        net_lat_s=jnp.zeros((J,), jnp.float32),
        preempt_count=zi((J,)), preempt_t=zf((J,)),
        total_preempt_time=jnp.zeros((J,), jnp.float32),
        spu=jnp.zeros((J,), jnp.float32),
        watts=jnp.zeros((J,), jnp.float32),
        rl_obs0=jnp.zeros((J, obs_dim), jnp.float32),
        rl_a_dc=zi((J,)), rl_a_g=zi((J,)),
        rl_mask_dc0=jnp.zeros((J, n_dc), bool),
        rl_mask_g0=jnp.zeros((J, params.max_gpus_per_job), bool),
        rl_valid=jnp.zeros((J,), bool),
    )
    dc = DCArrays(
        busy=zi((n_dc,)),
        cur_f_idx=jnp.full((n_dc,), fleet.default_f_idx, dtype=jnp.int32),
        energy_j=zf((n_dc,)),
        util_gpu_time=zf((n_dc,)),
        acc_job_unit=jnp.zeros((n_dc,), jnp.float32),
    )
    lat = LatWindow(
        buf=jnp.zeros((2, params.lat_window), jnp.float32),
        count=zi((2,)),
        ptr=zi((2,)),
    )
    # queue rings (queue_mode "ring"); a 1-deep dummy keeps the pytree
    # structure identical in "slab" mode without measurable cost
    if params.queue_mode == "ring" and params.queue_cap < 1:
        raise ValueError(
            "queue_cap < 1 with queue_mode='ring': 0 is the CLI auto-size "
            "sentinel — resolve it first (run_sim.finalize_queue_cap / "
            "engine.auto_queue_cap)")
    Q = params.queue_cap if params.queue_mode == "ring" else 1
    queues = QueueRings(
        recs=jnp.zeros((n_dc, 2, Q, QRec.N_FIELDS), td),
        head=zi((n_dc, 2)),
        tail=zi((n_dc, 2)),
    )
    signals = None
    if workload.signals is not None:
        from ..models.structs import SignalState

        signals = SignalState(cost_usd=jnp.zeros((n_dc,), jnp.float32),
                              carbon_g=jnp.zeros((n_dc,), jnp.float32))
    telemetry = None
    if params.obs_enabled:
        from ..obs.metrics import init_telemetry

        telemetry = init_telemetry(n_dc=n_dc, n_bins=params.obs_qdepth_bins,
                                   superstep_k=params.superstep_k)
    fault = None
    if params.faults is not None and params.faults.enabled:
        from ..fault.schedule import init_fault_state

        # fold_in (not split): the main PRNG chain is untouched, so an
        # enabled-but-empty schedule realizes the exact fault-free run,
        # and vmapped per-rollout keys give independent stochastic draws
        fault = init_fault_state(
            jax.random.fold_in(key, 0x0FA17), params.faults,
            n_dc=n_dc, n_ing=n_ing, freq_levels=fleet.freq_levels, tdtype=td)
    return SimState(
        fault=fault,
        telemetry=telemetry,
        signals=signals,
        t=zf(), key=key, jid_counter=jnp.int32(1),
        started_accrual=jnp.bool_(False), t_first=zf(),
        dc=dc, jobs=jobs,
        next_arrival=clocks["next_arrival"].astype(td),
        arr_key=k_arr,
        arr_count=jnp.ones((n_ing, 2), jnp.int32),  # draw #0 spent above
        arr_cum=clocks["arr_cum"].astype(td),
        arr_epoch=clocks["arr_epoch"].astype(td),
        next_log_t=jnp.asarray(params.log_interval, dtype=td),
        lat=lat,
        bandit=bandit_init(n_dc, 2, fleet.n_f),
        queues=queues,
        n_events=zi(), n_finished=zi((2,)),
        units_finished=jnp.zeros((2,), jnp.float32), n_dropped=zi(),
        done=jnp.bool_(False),
    )


class Engine:
    """Compiled stepper for one (fleet, params) specialization.

    ``policy_apply(policy_params, obs, mask_dc, mask_g, key) -> (a_dc, a_g)``
    is required for algo == chsac_af and ignored otherwise.
    """

    def __init__(self, fleet: FleetSpec, params: SimParams,
                 policy_apply: Optional[Callable] = None):
        if params.algo == ALGO_CHSAC_AF and policy_apply is None:
            raise ValueError("chsac_af requires a policy_apply callable")
        self.fleet = fleet
        self.params = params
        self.policy_apply = policy_apply
        # the workload compiler owns every arrival draw and the
        # price/carbon signal timelines (workload/ subsystem, round 10);
        # legacy synthetic params compile through it unchanged
        from ..workload.compiler import compile_workload

        self.workload = compile_workload(fleet, params)
        self.signals = self.workload.signals  # CompiledSignals | None
        self.signals_on = self.signals is not None
        # device constants
        self.freq_levels = jnp.asarray(fleet.freq_levels)
        self.total_gpus = jnp.asarray(fleet.total_gpus)
        self.E_grid = jnp.asarray(fleet.E_grid)
        # grid searches must honor the per-job GPU cap (reference bounds
        # best_nf_grid/_score_dc_for_job by policy.max_gpus_per_job)
        self.E_grid_cap = self.E_grid[:, :, :min(fleet.n_max, params.max_gpus_per_job), :]
        self.transfer_s = jnp.asarray(fleet.transfer_s)
        self.net_lat_s = jnp.asarray(fleet.net_lat_s)
        self.power = jax.tree.map(jnp.asarray, fleet.power)
        self.latency = jax.tree.map(jnp.asarray, fleet.latency)
        self.p_idle = jnp.asarray(fleet.p_idle)
        self.p_sleep = jnp.asarray(fleet.p_sleep)
        self.power_gating = jnp.asarray(fleet.power_gating)
        # Arrival generator selection (see workload.compiler): every
        # stream is pregenerated ahead of the scan and consumed by cursor
        # — there are no in-step draws in ANY mode.  The flag only picks
        # the |amp| <= 1 sinusoid backend: True (default) = the parallel
        # epoch-anchored inversion; DCG_ARRIVAL_PREGEN=0 = the sequential
        # thinning replay, which realizes the exact historical in-step
        # draw sequence (A/B + legacy-golden compatibility).  Poisson/off
        # streams realize identical bytes either way.
        self.arrival_pregen = os.environ.get(
            "DCG_ARRIVAL_PREGEN", "1") not in ("0", "off")
        # queue layout (static): rings keep waiting jobs out of the slab
        self.ring = params.queue_mode == "ring"
        # fault injection (static): False compiles the exact fault-free
        # program — every fault site below is `if self.faults_on`-gated so
        # the op-count/structure guards and golden outputs are untouched
        self.faults_on = params.faults is not None and params.faults.enabled
        # in-graph telemetry (obs/ subsystem): same compile-gating contract
        # — obs_enabled=False traces the exact pre-obs program (no
        # TelemetryState leaves, no obs emission keys); True appends the
        # `_obs_update` block (masked arithmetic only, never a cond, so
        # the superstep's select-free pin holds) and one flat snapshot
        # row per step whose layout is the static metric registry
        self.obs_on = params.obs_enabled
        if self.obs_on:
            from ..obs.metrics import registry_for

            self.obs_registry = registry_for(fleet, params)
        # fast-path eligibility (round 12): one reasons-based gate for
        # both compile-time fast paths.  K == 1 compiles the exact legacy
        # step — nothing below changes the traced program.  K > 1
        # compiles the fused multi-event superstep for every config whose
        # commutation predicate (`_superstep_select`) is sound — since
        # round 12 that includes fault runs (EV_FAULT windows degenerate
        # to L=1 through a masked slot-0 `_handle_fault`; fused windows
        # additionally require no PREEMPTED backlog) and signal-timeline
        # runs (the fused body accrues the price/carbon cost integral per
        # sub-step, and the eco scores sample the signals at each slot's
        # own event time, exactly like the singleton).  The residue
        # (chsac_af / bandit / weighted routing) runs singleton with the
        # reason recorded in `self.ineligibility` — run_sim prints it and
        # scripts/count_step_ops.py --eligibility reports the matrix.
        self.K = params.superstep_k
        self.ineligibility = static_ineligibility(params)
        self.superstep_on = (params.superstep_k > 1
                             and not self.ineligibility["superstep"])
        # write-plan commit (round 9; universal since round 12).  Under
        # vmap every `lax.switch` branch executes every step, so each
        # handler's private `slab_write` chain (and for chsac the policy
        # tail's route/materialize/start chains) ran every iteration.
        # With planner_on the handlers are pure PLANNERS: a branch
        # computes a fixed-shape WritePlan (row index, per-field scalar
        # values, per-group predicates) and the switch selects SCALARS —
        # its output select is part of the cond primitive, not extra ops
        # — and ONE shared commit applies the merged plan (`_commit_plan`;
        # chsac adds `_commit_tail` for the policy-tail dispatch, which
        # absorbed the round-3 shared `_start_job`).  Round 12 closed the
        # last three holdouts (bandit / faults / chsac+elastic — see
        # `static_ineligibility`), so EVERY config now plans; the legacy
        # round-8 program stays compilable by forcing `planner_on = False`
        # (the byte-identity goldens in tests/test_write_plan.py do).
        self.planner_on = not self.ineligibility["planner"]
        # donate the carried SimState: without it every dispatch copies the
        # whole state (incl. the queue rings — 160 MB at week-scale
        # queue_cap, a measured 3x CPU slowdown); callers all rebind
        # `state = run_chunk(state, ...)`, never reuse the input
        self._run_chunk_jit = jax.jit(
            self._run_chunk,
            static_argnames=("n_steps", "pregen", "attrib_stop"),
            donate_argnums=(0,))

    # ---------------- vector helpers over the slab ----------------

    def _up(self, state: SimState):
        """[n_dc] capacity mask (None when faults are compiled out)."""
        return state.fault.dc_up if self.faults_on else None

    def _job_coeffs(self, jobs: JobSlab):
        pc = jax.tree.map(lambda a: a[jobs.dc, jobs.jtype], self.power)
        tc = jax.tree.map(lambda a: a[jobs.dc, jobs.jtype], self.latency)
        return pc, tc

    def _run_T(self, jobs: JobSlab):
        """Per-slot seconds-per-unit at current (n, f); inf where not running.

        Reads the slab's cached ``spu`` (refreshed wherever a RUNNING job's
        (n, f) change) instead of re-evaluating coeff gathers + the T
        polynomial every step — the step is op-count bound (perf notes)."""
        return jnp.where(jobs.status == JobStatus.RUNNING, jobs.spu, jnp.inf)

    def _job_power(self, jobs: JobSlab):
        """Per-slot Watts for running jobs (0 elsewhere); cached like spu."""
        return jnp.where(jobs.status == JobStatus.RUNNING, jobs.watts, 0.0)

    def _row_TP(self, dcj, jt, n, f_idx):
        """Scalar (seconds-per-unit, watts) for one job at (dc, jtype, n, f)."""
        pc = jax.tree.map(lambda a: a[dcj, jt], self.power)
        tc = jax.tree.map(lambda a: a[dcj, jt], self.latency)
        f = self.freq_levels[f_idx]
        return (jnp.asarray(step_time_s(n, f, tc), jnp.float32),
                jnp.asarray(task_power_w(n, f, pc), jnp.float32))

    def _dc_power(self, jobs: JobSlab, busy, up=None):
        """[n_dc] paper-model power: sum of running job power + idle/sleep.

        A down DC draws nothing (``up`` mask): its jobs were preempted at
        outage onset, and the idle/sleep floor is off with the power."""
        p_job = self._job_power(jobs)
        active = dc_sum(p_job, jobs.dc, self.fleet.n_dc)
        # fmul_pinned: power feeds the energy accumulator, which must round
        # identically across program structures (superstep bit-identity)
        idle = fmul_pinned(self.total_gpus - busy,
                           jnp.where(self.power_gating, self.p_sleep,
                                     self.p_idle))
        if up is not None:
            idle = jnp.where(up, idle, 0.0)
        return active + idle

    def _queue_lens(self, state: SimState):
        """([n_dc] q_inf, [n_dc] q_train).

        Ring mode: two O(1) counter reads.  Slab mode: two [n_dc, J]
        masked reductions over the QUEUED rows."""
        if self.ring:
            cnt = state.queues.tail - state.queues.head
            q_inf, q_trn = cnt[:, 0], cnt[:, 1]
            if self.params.elastic_scaling and self.params.algo == ALGO_CHSAC_AF:
                # elastic resume failures awaiting ring migration sit
                # QUEUED in the slab (`_migrate_elastic_queued`) — count
                # them so obs/CSVs never under-report the queue
                jobs = state.jobs
                queued = jobs.status == JobStatus.QUEUED
                q_inf = q_inf + dc_count(queued & (jobs.jtype == 0), jobs.dc,
                                         self.fleet.n_dc).astype(q_inf.dtype)
                q_trn = q_trn + dc_count(queued & (jobs.jtype == 1), jobs.dc,
                                         self.fleet.n_dc).astype(q_trn.dtype)
            return q_inf, q_trn
        jobs = state.jobs
        queued = jobs.status == JobStatus.QUEUED
        q_inf = dc_count(queued & (jobs.jtype == 0), jobs.dc, self.fleet.n_dc)
        q_trn = dc_count(queued & (jobs.jtype == 1), jobs.dc, self.fleet.n_dc)
        return q_inf, q_trn

    # ---------------- queue rings (queue_mode == "ring") ----------------
    #
    # One ring per (dc, jtype); a record is one [QRec.N_FIELDS] row in the
    # state's time dtype.  Push/peek/pop are single dynamic row accesses —
    # under vmap these lower to per-lane gathers/scatters of ~11 scalars,
    # the price paid for keeping every waiting job OUT of the O(J)
    # whole-slab step ops (and for O(1) queue-length reads).  The slab
    # layout stays available as queue_mode="slab" for on-chip A/B.

    def _rec_pack(self, td, size, seq, ingress, t_ingress, t_avail,
                  net_lat_s, units_done=0.0, t_start=0.0, preempt_count=0,
                  preempt_t=0.0, total_preempt_time=0.0):
        vals = [jnp.float32(0.0)] * QRec.N_FIELDS
        vals[QRec.SIZE] = size
        vals[QRec.SEQ] = seq
        vals[QRec.INGRESS] = ingress
        vals[QRec.T_INGRESS] = t_ingress
        vals[QRec.T_AVAIL] = t_avail
        vals[QRec.NET_LAT_S] = net_lat_s
        vals[QRec.UNITS_DONE] = units_done
        vals[QRec.T_START] = t_start
        vals[QRec.PREEMPT_COUNT] = preempt_count
        vals[QRec.PREEMPT_T] = preempt_t
        vals[QRec.TOTAL_PREEMPT_TIME] = total_preempt_time
        return jnp.stack([jnp.asarray(v, td) for v in vals])

    def _rec_from_slab(self, jobs: JobSlab, j):
        td = jobs.t_ingress.dtype
        return self._rec_pack(
            td, jobs.size[j], jobs.seq[j], jobs.ingress[j],
            jobs.t_ingress[j], jobs.t_avail[j], jobs.net_lat_s[j],
            jobs.units_done[j], jobs.t_start[j], jobs.preempt_count[j],
            jobs.preempt_t[j], jobs.total_preempt_time[j])

    def _ring_push(self, state: SimState, dcj, jt, rec, enabled) -> SimState:
        """Append ``rec`` to ring (dcj, jt); a full ring counts a drop."""
        q = state.queues
        Q = q.recs.shape[2]
        cnt = q.tail[dcj, jt] - q.head[dcj, jt]
        ok = enabled & (cnt < Q)
        pos = jnp.mod(q.tail[dcj, jt], Q)
        # uniform index dtype: a Python-literal 0 weak-types to int64 under
        # jax_enable_x64 and dynamic_slice rejects the mix
        idx = (dcj.astype(jnp.int32), jt.astype(jnp.int32),
               pos.astype(jnp.int32), jnp.int32(0))
        cur = jax.lax.dynamic_slice(q.recs, idx, (1, 1, 1, QRec.N_FIELDS))
        upd = jnp.where(ok, rec.astype(q.recs.dtype).reshape(1, 1, 1, -1), cur)
        q = q.replace(
            recs=jax.lax.dynamic_update_slice(q.recs, upd, idx),
            tail=add_at2(q.tail, dcj, jt,
                         jnp.where(ok, jnp.int32(1), jnp.int32(0))),
        )
        return state.replace(
            queues=q,
            n_dropped=state.n_dropped + jnp.where(enabled & ~ok,
                                                  jnp.int32(1),
                                                  jnp.int32(0)))

    def _ring_peek1(self, state: SimState, dcj, jt):
        """(head record, nonempty) for ring (dcj, jt)."""
        q = state.queues
        Q = q.recs.shape[2]
        pos = jnp.mod(q.head[dcj, jt], Q)
        rec = jax.lax.dynamic_slice(
            q.recs,
            (dcj.astype(jnp.int32), jt.astype(jnp.int32),
             pos.astype(jnp.int32), jnp.int32(0)),
            (1, 1, 1, QRec.N_FIELDS)).reshape(-1)
        return rec, (q.tail[dcj, jt] - q.head[dcj, jt]) > 0

    def _ring_head(self, state: SimState, dcj, busy=None, up=None):
        """FIFO head of dcj's rings honoring inference priority.

        Returns (rec, jt_sel, found) — the ring-mode counterpart of
        `_next_queued` (same priority and free-GPU gating; FIFO is push
        order, i.e. the reference's append/pop(0) order)."""
        rec_i, has_i = self._ring_peek1(state, dcj, jnp.int32(0))
        rec_t, has_t = self._ring_peek1(state, dcj, jnp.int32(1))
        if busy is not None:
            has_i = has_i & (self._free_for(busy, dcj, jnp.int32(0), up) > 0)
            has_t = has_t & (self._free_for(busy, dcj, jnp.int32(1), up) > 0)
        if self.params.inf_priority:
            jt = jnp.where(has_i, jnp.int32(0), jnp.int32(1))
        else:
            jt = jnp.where(has_t, jnp.int32(1), jnp.int32(0))
        rec = jnp.where(jt == 0, rec_i, rec_t)
        return rec, jt, has_i | has_t

    def _ring_pop(self, state: SimState, dcj, jt, enabled) -> SimState:
        q = state.queues
        return state.replace(queues=q.replace(
            head=add_at2(q.head, dcj, jt,
                         jnp.where(enabled, jnp.int32(1), jnp.int32(0)))))

    def _materialize(self, state: SimState, slot, rec, dcj, jt,
                     pred) -> SimState:
        """Write a ring record back into slab ``slot`` (predicated).

        The row is left as QUEUED; every caller starts it in the same step
        under the same predicate (`_start_job` sets RUNNING), so the
        transient status is never observed."""
        f32 = lambda i: rec[i].astype(jnp.float32)  # noqa: E731
        i32 = lambda i: rec[i].astype(jnp.int32)  # noqa: E731
        jobs = slab_write(
            state.jobs, slot, _pred=pred,
            status=JobStatus.QUEUED,
            jtype=jt,
            ingress=i32(QRec.INGRESS),
            dc=dcj,
            seq=i32(QRec.SEQ),
            size=f32(QRec.SIZE),
            units_done=f32(QRec.UNITS_DONE),
            n=0,
            f_idx=self.fleet.default_f_idx,
            t_ingress=rec[QRec.T_INGRESS],
            t_avail=rec[QRec.T_AVAIL],
            t_start=rec[QRec.T_START],
            net_lat_s=f32(QRec.NET_LAT_S),
            preempt_count=i32(QRec.PREEMPT_COUNT),
            preempt_t=rec[QRec.PREEMPT_T],
            total_preempt_time=f32(QRec.TOTAL_PREEMPT_TIME),
            rl_valid=False,
        )
        return state.replace(jobs=jobs)

    def _obs(self, state: SimState):
        q_inf, q_trn = self._queue_lens(state)
        kw = {}
        if self.signals_on and self.signals.observe:
            # observed signal timelines extend the obs vector (see
            # SimParams.obs_dim): the policy sees the live price/carbon
            kw = {"price": self.signals.price_at(state.t),
                  "ci": self.signals.carbon_at(state.t)}
        return algos.rl_obs(self.fleet, state.t, state.dc.busy,
                            state.dc.cur_f_idx, q_inf, q_trn, **kw)

    def _masks(self, state: SimState, p99_pair=None, reserve=0):
        return algos.rl_masks(self.params, self.fleet, state.dc.busy,
                              state.lat.buf, state.lat.count, p99_pair,
                              reserve, up=self._up(state))

    def _hour(self, t):
        return jnp.clip(((t % 86400.0) // 3600.0).astype(jnp.int32), 0, 23)

    def _signal_kw(self, t, dcj=None):
        """Time-varying price/carbon samples for the eco decision sites.

        Signals off (the legacy world) returns {} — the callee falls back
        to the static hourly price table / per-DC carbon map and the
        traced program is untouched.  ``dcj`` given samples the scalar CI
        of one DC (admission); None returns the [n_dc] vector (routing).
        """
        if not self.signals_on:
            return {}
        ci = self.signals.carbon_at(t)
        return {"price": self.signals.price_at(t),
                "ci": ci if dcj is None else ci[dcj]}

    def _free_for(self, busy, dcj, jt, up=None):
        """Free GPUs at dcj available to a job of type jt.

        Training jobs may not dip into the per-DC inference reserve
        (`SimParams.reserve_inf_gpus` — live version of the reference's
        dead `policy.py:13` knob).  Default 0 compiles to the plain
        free-GPU count.

        ``up`` (fault capacity mask) is the single admission choke point
        of the fault subsystem: a down DC reports 0 free GPUs, so every
        start/drain/admit path — all gated on free > 0 — refuses it."""
        free = self.total_gpus[dcj] - busy[dcj]
        r = self.params.reserve_inf_gpus
        if r > 0:
            free = jnp.where(jt == 1, jnp.maximum(0, free - r), free)
        if up is not None:
            free = jnp.where(up[dcj], free, 0)
        return free

    # ---------------- admission ----------------

    def _chsac_nf(self, dcj, jt, free, a_g):
        """THE chsac sizing rule: n = clamp(action+1, 1, min(free, cap)),
        f = energy-argmin at that n.  Single definition shared by the
        in-branch and deferred admit/commit paths (they were validated
        bit-exact against each other)."""
        n = jnp.maximum(1, jnp.minimum(
            a_g + 1, jnp.minimum(free, self.params.max_gpus_per_job)))
        f_idx = algos.best_energy_f_idx_at_n(self.E_grid, dcj, jt, n)
        return n.astype(jnp.int32), f_idx.astype(jnp.int32)

    def _decide_nf_core(self, state: SimState, dcj, jt, free, cur_f, t_evt,
                        q_inf_len=None):
        """The non-RL, non-bandit admission dispatch — the ONE copy shared
        by the singleton `_decide_nf` and the superstep `_decide_nf_super`
        (a second copy would be a bit-identity divergence hazard).

        ``q_inf_len`` None computes the heuristic path's queue-length
        input from the state; the superstep passes the constant 0 its
        commutation predicate guarantees."""
        p, fleet = self.params, self.fleet
        algo = p.algo
        if algo == ALGO_JOINT_NF:
            n, f_idx = algos.admit_joint_nf(fleet, self.E_grid_cap, dcj, jt)
            new_dc_f = cur_f
        elif algo == ALGO_CARBON_COST:
            n, f_idx = algos.admit_carbon_cost(
                fleet, self.E_grid_cap, dcj, jt, self._hour(t_evt),
                **self._signal_kw(t_evt, dcj))
            new_dc_f = cur_f
        elif algo == ALGO_DEBUG:
            n = jnp.int32(p.num_fixed_gpus)
            if p.fixed_freq is not None:
                f_idx = jnp.int32(algos.f_idx_of(fleet, p.fixed_freq))
            else:
                f_idx = algos.best_energy_f_idx_at_n(self.E_grid, dcj, jt, n)
            new_dc_f = cur_f
        else:  # default_policy, cap_uniform, cap_greedy, eco_route
            if q_inf_len is None:
                q_inf, _ = self._queue_lens(state)
                q_inf_len = q_inf[dcj]
            n, new_dc_f = algos.heuristic_select(p, fleet, jt, free, cur_f,
                                                 q_inf_len)
            f_idx = new_dc_f
        return n, f_idx, new_dc_f

    def _decide_nf(self, state: SimState, j, key):
        """Per-algo (n, f_idx, new_dc_f_idx, bandit') for starting job j now.

        Mirrors the xfer_done dispatch (`simulator_paper_multi.py:602-676`).
        Caller guarantees free > 0 at jobs.dc[j].
        """
        p = self.params
        jobs = state.jobs
        dcj, jt = jobs.dc[j], jobs.jtype[j]
        free = self._free_for(state.dc.busy, dcj, jt, self._up(state))
        cur_f = state.dc.cur_f_idx[dcj]
        bandit = state.bandit
        algo = p.algo

        if algo == ALGO_BANDIT:
            n = jnp.minimum(free, p.max_gpus_per_job)
            bandit, f_idx = bandit_select(bandit, dcj, jt)
            new_dc_f = cur_f
        elif algo == ALGO_CHSAC_AF:
            n, f_idx = self._chsac_nf(dcj, jt, free, jobs.rl_a_g[j])
            new_dc_f = cur_f
        else:
            n, f_idx, new_dc_f = self._decide_nf_core(state, dcj, jt, free,
                                                      cur_f, state.t)
        return n.astype(jnp.int32), f_idx.astype(jnp.int32), new_dc_f, bandit

    def _start_job(self, state: SimState, j, n, f_idx, new_dc_f,
                   enabled=None) -> SimState:
        """`_start_job_with_nf` parity: clamp n to free, mark RUNNING.

        ``enabled`` (scalar bool) predicates every write: the chsac step
        runs ONE shared instance of this commit serving both the
        xfer-admission and the post-finish queue-drain (at most one can
        fire per step), instead of paying the whole write chain once per
        switch branch under vmap.

        PARITY COPIES (round 9): the clamp / `_row_TP` refresh /
        first-start stamp / preempt-interval close below are replicated
        expression-for-expression in the planner paths —
        `_drain_queues.decide_start_vals` (+ its two masked bodies),
        `_plan_xfer`, and `_commit_tail` — which serve configs where
        this legacy commit no longer compiles.  A semantic change here
        (e.g. the faults derate clamp, resume accounting) must be made
        in ALL of them; tests/test_write_plan.py's planner-vs-legacy
        byte goldens catch drift on the configs that compile both."""
        jobs = state.jobs
        dcj = jobs.dc[j]
        free = self._free_for(state.dc.busy, dcj, jobs.jtype[j],
                              self._up(state))
        n = jnp.maximum(1, jnp.minimum(n, free))
        if self.faults_on:
            # straggler derating clamps every start's frequency (the job's
            # AND the DC ladder setting) to the DC's current cap
            cap = state.fault.derate_f_idx[dcj]
            f_idx = jnp.minimum(f_idx, cap)
            new_dc_f = jnp.minimum(new_dc_f, cap)
        # units_done is NOT reset: fresh jobs arrive with 0 and a preempted
        # job resumed from the queue keeps its accumulated progress (the
        # reference's preempt_ckpt {units_done, f_used, gpus} is implicit in
        # the slab — progress is continuously maintained).  t_start is only
        # stamped on the first start (arrival placement resets it to 0); a
        # resuming preempted job closes its preempt-wait interval here.
        first_start = jobs.t_start[j] <= 0.0
        resuming = jobs.preempt_t[j] > 0.0
        spu, watts = self._row_TP(dcj, jobs.jtype[j], n, f_idx)
        jobs = slab_write(
            jobs, j, _pred=enabled,
            status=JobStatus.RUNNING,
            n=n,
            f_idx=f_idx,
            spu=spu,
            watts=watts,
            t_start=jnp.where(first_start, state.t, jobs.t_start[j]),
            total_preempt_time=jobs.total_preempt_time[j] + jnp.where(
                resuming, jnp.asarray(state.t - jobs.preempt_t[j], jnp.float32), 0.0),
            preempt_t=0.0,
        )
        if enabled is None:
            busy = add_at(state.dc.busy, dcj, n)
            cur_f = set_at(state.dc.cur_f_idx, dcj, new_dc_f)
        else:
            busy = add_at(state.dc.busy, dcj, jnp.where(enabled, n, 0))
            cur_f = jnp.where(_mask1(state.dc.cur_f_idx, dcj) & enabled,
                              new_dc_f, state.dc.cur_f_idx)
        dc = state.dc.replace(busy=busy, cur_f_idx=cur_f)
        return state.replace(jobs=jobs, dc=dc)

    # Ring mutations and the branched step body are kept strictly apart:
    # a `lax.cond`/`lax.switch` branch that writes `queues.recs` forces a
    # whole-array select of the ring buffer every step (measured: 4 ev/s
    # at queue_cap 227k vs 2.5k ev/s at 1k on CPU — the select defeats
    # the scan carry's in-place aliasing).  Branches therefore only EMIT
    # a push request; `_step` applies at most one predicated `_ring_push`
    # after the event switch, so `recs` flows through every branch
    # untouched and XLA elides the select(p, x, x).  (Pops touch only the
    # [n_dc, 2] head counters and peeks only read — both branch-safe.)
    #
    # The elastic-scaling path (`_commit_place` with queue_on_full=True,
    # reached inside the finish branch via `_elastic_reallocate`) makes
    # data-dependent pushes a single post-switch request cannot express;
    # instead of pushing in-branch it leaves resume failures QUEUED in
    # the slab and the step's post-switch `_migrate_elastic_queued`
    # drains them into the rings, FIFO, a bounded few per step — so no
    # branch writes `queues.recs` in ANY configuration (pinned by
    # tests/test_perf_structure.py::test_no_ring_writes_inside_branches).

    def _zero_push(self, td):
        return {"enabled": jnp.bool_(False), "dcj": jnp.int32(0),
                "jt": jnp.int32(0),
                "rec": jnp.zeros((QRec.N_FIELDS,), td)}

    def _admit_or_queue(self, state: SimState, j, key):
        """xfer_done handler body: start if the DC has free GPUs, else queue.

        Ring mode moves the waiting job out of the slab entirely (its slot
        frees for new arrivals) via an emitted push request; slab mode
        marks the row QUEUED in place.  Returns (state, push_req)."""
        dcj = state.jobs.dc[j]
        jt = state.jobs.jtype[j]
        free = self._free_for(state.dc.busy, dcj, jt, self._up(state))
        zero = self._zero_push(state.t.dtype)

        def start(st):
            n, f_idx, new_dc_f, bandit = self._decide_nf(st, j, key)
            st = st.replace(bandit=bandit)
            return self._start_job(st, j, n, f_idx, new_dc_f), zero

        def queue(st):
            if not self.ring:
                return st.replace(
                    jobs=slab_write(st.jobs, j, status=JobStatus.QUEUED)), zero
            rec = self._rec_from_slab(st.jobs, j)
            st = st.replace(jobs=slab_write(st.jobs, j, status=JobStatus.EMPTY))
            return st, {"enabled": jnp.bool_(True), "dcj": dcj.astype(jnp.int32),
                        "jt": jt.astype(jnp.int32), "rec": rec}

        return jax.lax.cond(free > 0, start, queue, state)

    def _admit_or_queue_deferred(self, state: SimState, j):
        """chsac xfer handler: queue-on-full applied here, the start itself
        emitted as a request for the step's single shared `_start_job`
        (n comes from the stored routing action, f from the energy grid —
        no policy evaluation and no randomness consumed)."""
        dcj = state.jobs.dc[j]
        jt = state.jobs.jtype[j]
        free = self._free_for(state.dc.busy, dcj, jt, self._up(state))
        can = free > 0
        n, f_idx = self._chsac_nf(dcj, jt, free, state.jobs.rl_a_g[j])
        push = self._zero_push(state.t.dtype)
        if self.ring:
            rec = self._rec_from_slab(state.jobs, j)
            state = state.replace(jobs=slab_write(
                state.jobs, j, _pred=~can, status=JobStatus.EMPTY))
            push = {"enabled": ~can, "dcj": dcj.astype(jnp.int32),
                    "jt": jt.astype(jnp.int32), "rec": rec}
        else:
            state = state.replace(jobs=slab_write(
                state.jobs, j, _pred=~can, status=JobStatus.QUEUED))
        sreq = {"enabled": can, "j": j.astype(jnp.int32),
                "n": n, "f_idx": f_idx,
                "new_dc_f": state.dc.cur_f_idx[dcj]}
        return state, sreq, push

    # ---------------- queue drain (after a finish) ----------------

    def _next_queued(self, jobs: JobSlab, dcj, busy=None, up=None):
        """FIFO pop candidate honoring inference priority. Returns (j, found).

        With ``busy`` given, candidates a start could not serve right now
        are skipped: an inference job needs >= 1 raw-free GPU, a training
        job >= 1 GPU beyond the inference reserve — so a reserve-blocked
        training queue head never starves queued inference work behind it
        (the reserved GPUs exist precisely for that work)."""
        queued = (jobs.status == JobStatus.QUEUED) & (jobs.dc == dcj)
        seq_inf = jnp.where(queued & (jobs.jtype == 0), jobs.seq, BIG)
        seq_trn = jnp.where(queued & (jobs.jtype == 1), jobs.seq, BIG)
        j_inf, j_trn = jnp.argmin(seq_inf), jnp.argmin(seq_trn)
        has_inf, has_trn = seq_inf[j_inf] < BIG, seq_trn[j_trn] < BIG
        if busy is not None:
            has_inf = has_inf & (self._free_for(busy, dcj, jnp.int32(0), up) > 0)
            has_trn = has_trn & (self._free_for(busy, dcj, jnp.int32(1), up) > 0)
        if self.params.inf_priority:
            j = jnp.where(has_inf, j_inf, j_trn)
        else:
            j = jnp.where(has_trn, j_trn, j_inf)
        found = has_inf | has_trn
        return j, found

    def _drain_queues(self, state: SimState, dcj, key, enabled,
                      masked: bool = False, xfer=None) -> SimState:
        """Start queued jobs while GPUs are free (`simulator_paper_multi.py:839-927`).

        Bounded loop: every admitted job takes >= 1 GPU and queues are only
        non-empty when the DC was full, so the freed GPU count bounds the
        number of admissions.  Non-chsac algorithms only: chsac_af drains at
        most one job per finish (reference `break` at :890) through a fresh
        policy action in the step's policy tail (`_policy_tail.do_drain`).

        Runs AFTER the event switch, predicated on ``enabled`` (the step
        fired a finish) — inside the finish branch its ring pops would
        force whole-ring selects at the switch (see the ring-mutation
        note above `_zero_push`).  Bit-exact relocation: nothing else in
        the step touches state between the finish handler's tail and the
        switch output.

        ``masked=True`` (the unified superstep body since round 7; every
        planner program since round 9) replaces the per-iteration
        `lax.cond` with predicated writes — identical values (computing
        the decision on a disabled iteration and masking the writes is
        exact; bandit's select/update threads through the loop carry as
        predicated state updates, and fault programs apply the
        straggler-derate clamp exactly like `_start_job`), but the
        traced program carries no `cond` primitive.  Round 9 also MERGES
        the ring body's materialize + start pair: the ring head is only
        eligible when its DC can start it (the peek is busy-gated), so
        the legacy pair's QUEUED transient is never observable and one
        predicated write chain commits the popped record straight to
        RUNNING with the decided (n, f) and refreshed physics —
        bit-equal values, ~150 fewer step-body eqns.  ``masked=False``
        keeps the legacy cond bodies (the forced-gate golden program).

        ``xfer`` (round 12, fault-free planner programs): iteration 0
        doubles as the step's xfer-admission start — ``{"on": scalar
        bool (the step fired an xfer), "j": the xfer row}``.  The SAME
        decide/start chain serves both paths, so `_plan_xfer` carries no
        `_decide_nf` copy of its own (the round-9 "next levers" ~100-eqn
        item).  Sound because the xfer-admit and queue-drain requests
        are mutually exclusive per step (at most one of finish/xfer
        fires), so the direct slot never displaces a drain iteration.
        """
        p = self.params
        assert p.algo != ALGO_CHSAC_AF, "chsac_af drains in _policy_tail"
        assert xfer is None or masked, (
            "the xfer direct-start rides the masked bodies only")
        assert xfer is None or not self.faults_on, (
            "fault programs keep the xfer start in _plan_xfer: it must "
            "land before the migration sweep")

        k_drain = max(p.max_gpus_per_job, min(p.num_fixed_gpus, p.job_cap))

        def decide_start_vals(st, dc_j, jt_sel, t_evt):
            """(n, f, new_dc_f, spu, watts, free, bandit'): `_decide_nf`
            + `_start_job`'s clamp/physics for a row at (dc_j, jt_sel) —
            reading the scalars directly replaces the slab gathers.
            ``bandit'`` is None except under ALGO_BANDIT, where it is
            the post-select state the caller commits predicated."""
            free = self._free_for(st.dc.busy, dc_j, jt_sel, self._up(st))
            bandit2 = None
            if p.algo == ALGO_BANDIT:
                n_d = jnp.minimum(free, p.max_gpus_per_job)
                bandit2, f_d = bandit_select(st.bandit, dc_j, jt_sel)
                new_dc_f = st.dc.cur_f_idx[dc_j]
            else:
                n_d, f_d, new_dc_f = self._decide_nf_core(
                    st, dc_j, jt_sel, free, st.dc.cur_f_idx[dc_j], t_evt)
            n_st = jnp.maximum(1, jnp.minimum(n_d.astype(jnp.int32), free))
            f_d = f_d.astype(jnp.int32)
            new_dc_f = new_dc_f.astype(jnp.int32)
            if self.faults_on:
                # `_start_job` parity: straggler derating clamps every
                # start's frequency (job AND DC ladder) to the DC's cap
                cap = st.fault.derate_f_idx[dc_j]
                f_d = jnp.minimum(f_d, cap)
                new_dc_f = jnp.minimum(new_dc_f, cap)
            spu, watts = self._row_TP(dc_j, jt_sel, n_st, f_d)
            return n_st, f_d, new_dc_f, spu, watts, free, bandit2

        def commit_bandit(st, bandit2, ok):
            if bandit2 is None:
                return st
            # predicated arm-select commit: exactly the legacy cond
            # body's `st.replace(bandit=...)` on the ok path
            return st.replace(bandit=jax.tree.map(
                lambda a, b: jnp.where(ok, a, b), bandit2, st.bandit))

        def body_ring_masked(i, st):
            rec, jt_sel, found = self._ring_head(st, dcj, st.dc.busy,
                                                 self._up(st))
            slot = jnp.argmax(st.jobs.status == JobStatus.EMPTY)
            ok = enabled & found & (st.jobs.status[slot] == JobStatus.EMPTY)
            dc_t = dcj
            if xfer is not None:
                direct = xfer["on"] & (i == 0)
                jx = xfer["j"]
                rec = jnp.where(direct, self._rec_from_slab(st.jobs, jx),
                                rec)
                jt_sel = jnp.where(direct, st.jobs.jtype[jx], jt_sel)
                dc_t = jnp.where(direct, st.jobs.dc[jx], dcj)
                slot = jnp.where(direct, jx, slot)
            n_st, f_d, new_dc_f, spu, watts, free, bandit2 = (
                decide_start_vals(st, dc_t, jt_sel, st.t))
            if xfer is not None:
                ok = jnp.where(direct, free > 0, ok)
            f32r = lambda k: rec[k].astype(jnp.float32)  # noqa: E731
            i32r = lambda k: rec[k].astype(jnp.int32)  # noqa: E731
            t_start0 = rec[QRec.T_START]
            resuming = rec[QRec.PREEMPT_T] > 0.0
            jobs = slab_write(
                st.jobs, slot, _pred=ok,
                status=JobStatus.RUNNING,
                jtype=jt_sel,
                ingress=i32r(QRec.INGRESS),
                dc=dc_t,
                seq=i32r(QRec.SEQ),
                size=f32r(QRec.SIZE),
                units_done=f32r(QRec.UNITS_DONE),
                n=n_st,
                f_idx=f_d,
                spu=spu,
                watts=watts,
                t_ingress=rec[QRec.T_INGRESS],
                t_avail=rec[QRec.T_AVAIL],
                t_start=jnp.where(t_start0 <= 0.0, st.t, t_start0),
                net_lat_s=f32r(QRec.NET_LAT_S),
                preempt_count=i32r(QRec.PREEMPT_COUNT),
                preempt_t=jnp.asarray(0.0, st.t.dtype),
                total_preempt_time=f32r(QRec.TOTAL_PREEMPT_TIME)
                + jnp.where(resuming,
                            jnp.asarray(st.t - rec[QRec.PREEMPT_T],
                                        jnp.float32), 0.0),
                rl_valid=False,
            )
            dc = st.dc.replace(
                busy=add_at(st.dc.busy, dc_t, jnp.where(ok, n_st, 0)),
                cur_f_idx=jnp.where(_mask1(st.dc.cur_f_idx, dc_t) & ok,
                                    new_dc_f, st.dc.cur_f_idx))
            st = commit_bandit(st.replace(jobs=jobs, dc=dc), bandit2, ok)
            # pop AFTER the (n, f) decision: `_decide_nf`'s queue-length
            # input counts the job being started, same as slab mode.
            # The direct xfer start popped nothing.
            pop_ok = ok if xfer is None else ok & ~direct
            return self._ring_pop(st, dcj, jt_sel, pop_ok)

        def body_slab_masked(i, st):
            j, found = self._next_queued(st.jobs, dcj, st.dc.busy,
                                         self._up(st))
            ok = enabled & found
            dc_t = dcj
            if xfer is not None:
                direct = xfer["on"] & (i == 0)
                j = jnp.where(direct, xfer["j"], j)
                dc_t = jnp.where(direct, st.jobs.dc[j], dcj)
            jt_sel = st.jobs.jtype[j]
            n_st, f_d, new_dc_f, spu, watts, free, bandit2 = (
                decide_start_vals(st, dc_t, jt_sel, st.t))
            if xfer is not None:
                ok = jnp.where(direct, free > 0, ok)
            t_start0 = st.jobs.t_start[j]
            resuming = st.jobs.preempt_t[j] > 0.0
            jobs = slab_write(
                st.jobs, j, _pred=ok,
                status=JobStatus.RUNNING,
                n=n_st,
                f_idx=f_d,
                spu=spu,
                watts=watts,
                t_start=jnp.where(t_start0 <= 0.0, st.t, t_start0),
                total_preempt_time=st.jobs.total_preempt_time[j]
                + jnp.where(resuming,
                            jnp.asarray(st.t - st.jobs.preempt_t[j],
                                        jnp.float32), 0.0),
                preempt_t=jnp.asarray(0.0, st.t.dtype),
            )
            dc = st.dc.replace(
                busy=add_at(st.dc.busy, dc_t, jnp.where(ok, n_st, 0)),
                cur_f_idx=jnp.where(_mask1(st.dc.cur_f_idx, dc_t) & ok,
                                    new_dc_f, st.dc.cur_f_idx))
            return commit_bandit(st.replace(jobs=jobs, dc=dc), bandit2, ok)

        def body_ring(i, st):
            rec, jt_sel, found = self._ring_head(st, dcj, st.dc.busy,
                                                 self._up(st))
            slot = jnp.argmax(st.jobs.status == JobStatus.EMPTY)
            ok = enabled & found & (st.jobs.status[slot] == JobStatus.EMPTY)
            st = self._materialize(st, slot, rec, dcj, jt_sel, pred=ok)

            def start(s):
                n, f_idx, new_dc_f, bandit = self._decide_nf(
                    s, slot, jax.random.fold_in(key, i))
                s = s.replace(bandit=bandit)
                return self._start_job(s, slot, n, f_idx, new_dc_f)

            st = jax.lax.cond(ok, start, lambda s: s, st)
            return self._ring_pop(st, dcj, jt_sel, ok)

        def body_slab(i, st):
            # admissibility (raw free for inference, reserve-adjusted for
            # training) is folded into the pop itself
            j, found = self._next_queued(st.jobs, dcj, st.dc.busy,
                                         self._up(st))
            ok = enabled & found

            def start(s):
                n, f_idx, new_dc_f, bandit = self._decide_nf(s, j, jax.random.fold_in(key, i))
                s = s.replace(bandit=bandit)
                return self._start_job(s, j, n, f_idx, new_dc_f)

            return jax.lax.cond(ok, start, lambda s: s, st)

        if masked:
            body = body_ring_masked if self.ring else body_slab_masked
        else:
            body = body_ring if self.ring else body_slab
        return jax.lax.fori_loop(0, k_drain, body, state)

    def _commit_place(self, state: SimState, j, obs, m_dc, m_g, a_dc, a_g,
                      queue_on_full: bool) -> SimState:
        """Apply an already-sampled policy action to job j: route + size +
        start (or fall back).

        ``queue_on_full=False`` (queue drain): the job is left untouched —
        still QUEUED at its current DC — when the chosen DC has no free GPUs.
        ``queue_on_full=True`` (elastic resume): the job joins the chosen
        DC's queue instead (our fix for the reference's ignored resume
        failure, SURVEY.md §7.4)."""
        free_tgt = self._free_for(state.dc.busy, a_dc, state.jobs.jtype[j],
                                  self._up(state))

        def commit(st):
            jobs = slab_write(
                st.jobs, j,
                dc=a_dc,
                rl_obs0=obs[None, :],
                rl_a_dc=a_dc,
                rl_a_g=a_g,
                rl_mask_dc0=m_dc[None, :],
                rl_mask_g0=m_g[None, :],
                rl_valid=True,
            )
            st = st.replace(jobs=jobs)
            jt = jobs.jtype[j]

            def start(s):
                n, f_idx = self._chsac_nf(a_dc, jt, free_tgt, a_g)
                return self._start_job(s, j, n, f_idx, s.dc.cur_f_idx[a_dc])

            def queue(s):
                # resume failure: the job (progress and all) waits QUEUED in
                # the slab at its chosen DC — in ring mode too, where the
                # step's post-switch `_migrate_elastic_queued` moves it into
                # the DC's ring.  Pushing the ring HERE (inside the finish
                # branch of the event switch) would force the whole-ring
                # select the rest of the engine avoids (ring-mutation note
                # above `_zero_push`); its RL trace is re-selected at drain
                # time like any queued job either way.
                return s.replace(
                    jobs=slab_write(s.jobs, j, status=JobStatus.QUEUED))

            return jax.lax.cond(free_tgt > 0, start, queue, st)

        if queue_on_full:
            return commit(state)
        return jax.lax.cond(free_tgt > 0, commit, lambda s: s, state)

    def _commit_place_deferred(self, state: SimState, j, obs, m_dc, m_g,
                               a_dc, a_g, pred):
        """`_commit_place(queue_on_full=False)` with the start emitted as a
        request for the step's shared `_start_job` instead of running its
        own copy; all writes predicated on ``pred & free_tgt > 0`` (the
        job stays untouched-QUEUED otherwise, same as the cond version)."""
        free_tgt = self._free_for(state.dc.busy, a_dc, state.jobs.jtype[j],
                                  self._up(state))
        ok = pred & (free_tgt > 0)
        jobs = slab_write(
            state.jobs, j, _pred=ok,
            dc=a_dc,
            rl_obs0=obs[None, :],
            rl_a_dc=a_dc,
            rl_a_g=a_g,
            rl_mask_dc0=m_dc[None, :],
            rl_mask_g0=m_g[None, :],
            rl_valid=True,
        )
        state = state.replace(jobs=jobs)
        jt = state.jobs.jtype[j]
        n, f_idx = self._chsac_nf(a_dc, jt, free_tgt, a_g)
        sreq = {"enabled": ok, "j": j.astype(jnp.int32),
                "n": n, "f_idx": f_idx,
                "new_dc_f": state.dc.cur_f_idx[a_dc]}
        return state, sreq

    # ---------------- write-plan commit (round 9) ----------------
    #
    # Handlers as pure planners + one shared commit per step (compile-
    # gated by `self.planner_on`, see __init__).  A WritePlan is a fixed-
    # shape pytree: one slab row index, per-field values, and four group
    # predicates — a slab field belongs to the groups that may write it,
    # and at most one group fires per field per step, so a single merged
    # value per field suffices:
    #
    #   place — arrival placement (the XFER row init; 9 place-only fields)
    #   start — a start-to-RUNNING commit (xfer admission: n/f/physics)
    #   evict — a status retire/queue write (finish, xfer queue-on-full)
    #   fin   — finish accounting (units_done clamp, rl_valid clear,
    #           busy release, counters, latency window, acc_job_unit)
    #
    # The commit applies the merged plan with exactly ONE masked write
    # per slab field (pinned by test_perf_structure), one busy/ladder
    # refresh, and one latency-window push.  Values and write conditions
    # replicate the legacy handlers expression-for-expression — the plan
    # only RELOCATES writes out of the switch branches — so planner
    # programs realize bit-identical runs (byte-compared goldens in
    # test_perf_structure).  The K>1 superstep feeds the same commit
    # with [K]-row plans (`_superstep_apply`): rows scatter with
    # mode="drop" there, while the K=1 layout keeps the TPU-friendly
    # masked whole-array writes (see the module note above `_mask1`).

    def _zero_plan(self, td, state: Optional[SimState] = None):
        """The identity WritePlan.  ``state`` must be the branch's input
        state when the config threads extra state through the plan —
        bandit carries its whole (tiny) BanditState in the plan, so the
        identity plan is the branch state's own bandit (the switch
        output select is part of the cond primitive, not extra ops)."""
        z32 = jnp.int32(0)
        zf = jnp.float32(0.0)
        zt = jnp.asarray(0.0, td)
        no = jnp.bool_(False)
        plan = {
            "row": z32,
            "place": no, "start": no, "evict": no, "fin": no,
            "status_val": z32,
            "jtype": z32, "ingress": z32, "dc": z32, "seq": z32,
            "size": zf, "units_done": zf,
            "n": z32, "f_idx": z32, "spu": zf, "watts": zf,
            "t_ingress": zt, "t_avail": zt, "t_start": zt,
            "net_lat_s": zf, "preempt_t": zt,
            "total_preempt_time": zf,
            "dc_row": z32, "busy_delta": z32,
            "dcf": no, "dcf_val": z32,
            "acc_add": zf,
            "fin_jt": z32, "fin_size": zf, "sojourn": zf,
        }
        if self.params.algo == ALGO_BANDIT:
            assert state is not None, "bandit plans carry state.bandit"
            plan["bandit"] = state.bandit
        return plan

    def _commit_plan(self, state: SimState, plan) -> SimState:
        """Apply one step's merged WritePlan.

        Scalar plan (`row` 0-d): the K=1 path — one masked [J] write per
        slab field.  [K]-row plan: the superstep path — one scatter per
        field with disabled rows dropped out of bounds (bit-equal to the
        round-8 deferred-scatter block; rows are pairwise-distinct or
        duplicate-with-equal-values, so update order is irrelevant).
        The four loop-owned fields of the superstep's in-order sub-step
        loop (status / units_done / spu / watts, plus the busy/energy/
        util accumulators it carries) are excluded from K-row plans —
        later sub-steps read them, so they cannot defer."""
        p, fleet = self.params, self.fleet
        jobs = state.jobs
        J = jobs.status.shape[0]
        pl, stt, fin = plan["place"], plan["start"], plan["fin"]
        if plan["row"].ndim == 0:
            # Whether any scalar plan can carry a START group (round 12):
            # the xfer admission rides iteration 0 of the shared masked
            # drain for fault-free programs (`_drain_queues` ``xfer=``),
            # and chsac starts through `_commit_tail` — only the non-RL
            # fault program's `_plan_xfer` still plans its start (its
            # start must land BEFORE the migration sweep, the position
            # the drain relocation cannot give it).  Compiling the dead
            # start writes out saves ~6 [J] selects per step.
            has_start = self.faults_on and p.algo != ALGO_CHSAC_AF
            if not has_start:
                stt = jnp.bool_(False)
            m = jnp.arange(J) == plan["row"]
            m_pl = m & pl
            m_ps = m & (pl | stt) if has_start else m_pl
            m_status = (m & (pl | stt | plan["evict"]) if has_start
                        else m & (pl | plan["evict"]))
            m_pf = m & (pl | fin)

            def w(arr, mask, val):
                return jnp.where(mask, val, arr)

            jobs = jobs.replace(
                status=w(jobs.status, m_status, plan["status_val"]),
                jtype=w(jobs.jtype, m_pl, plan["jtype"]),
                ingress=w(jobs.ingress, m_pl, plan["ingress"]),
                dc=w(jobs.dc, m_pl, plan["dc"]),
                seq=w(jobs.seq, m_pl, plan["seq"]),
                size=w(jobs.size, m_pl, plan["size"]),
                units_done=w(jobs.units_done, m_pf, plan["units_done"]),
                n=w(jobs.n, m_ps, plan["n"]),
                f_idx=w(jobs.f_idx, m_ps, plan["f_idx"]),
                t_ingress=w(jobs.t_ingress, m_pl, plan["t_ingress"]),
                t_avail=w(jobs.t_avail, m_pl, plan["t_avail"]),
                t_start=w(jobs.t_start, m_ps, plan["t_start"]),
                net_lat_s=w(jobs.net_lat_s, m_pl, plan["net_lat_s"]),
                preempt_count=w(jobs.preempt_count, m_pl, 0),
                preempt_t=w(jobs.preempt_t, m_ps, plan["preempt_t"]),
                total_preempt_time=w(jobs.total_preempt_time, m_ps,
                                     plan["total_preempt_time"]),
                rl_valid=w(jobs.rl_valid, m_pf, False),
            )
            if has_start:
                m_st = m & stt
                jobs = jobs.replace(
                    spu=w(jobs.spu, m_st, plan["spu"]),
                    watts=w(jobs.watts, m_st, plan["watts"]),
                )
            # dc refresh: one busy delta (start +n / finish -n; the fin
            # clamp replicates the legacy maximum over the whole vector,
            # an identity on the untouched non-negative entries)
            dmask = jnp.arange(fleet.n_dc) == plan["dc_row"]
            busy = state.dc.busy + jnp.where(
                dmask & ((fin | stt) if has_start else fin),
                plan["busy_delta"], 0)
            busy = jnp.where(fin, jnp.maximum(0, busy), busy)
            if has_start:
                cur_f = jnp.where(dmask & plan["dcf"], plan["dcf_val"],
                                  state.dc.cur_f_idx)
            else:
                cur_f = state.dc.cur_f_idx
            acc = jnp.where(dmask & fin,
                            state.dc.acc_job_unit + plan["acc_add"],
                            state.dc.acc_job_unit)
            # latency-window push + finish counters
            jt = plan["fin_jt"]
            m2 = (jnp.arange(2) == jt) & fin
            lat = state.lat
            ptr = lat.ptr[jt]
            lat = LatWindow(
                buf=jnp.where(
                    m2[:, None]
                    & (jnp.arange(p.lat_window)[None, :] == ptr),
                    plan["sojourn"], lat.buf),
                count=jnp.where(m2, lat.count + 1, lat.count),
                ptr=jnp.where(m2, (ptr + 1) % p.lat_window, lat.ptr),
            )
            n_fin = jnp.where(m2, state.n_finished + 1, state.n_finished)
            units_fin = jnp.where(m2,
                                  state.units_finished + plan["fin_size"],
                                  state.units_finished)
            extra = {}
            if "bandit" in plan:
                # bandit rides the plan whole: the finish branch's reward
                # update / identity elsewhere (the xfer-admission select
                # runs in the shared drain, after this commit — exactly
                # the legacy in-branch order)
                extra["bandit"] = plan["bandit"]
            return state.replace(
                jobs=jobs,
                dc=state.dc.replace(busy=busy, cur_f_idx=cur_f,
                                    acc_job_unit=acc),
                lat=lat, n_finished=n_fin, units_finished=units_fin,
                **extra)

        # ---- [K]-row plan (superstep deferred scatters) ----
        K = plan["row"].shape[0]
        OOB = jnp.int32(J)
        row = plan["row"]
        r_pl = jnp.where(pl, row, OOB)
        r_ps = jnp.where(pl | stt, row, OOB)
        r_pf = jnp.where(pl | fin, row, OOB)
        jobs = jobs.replace(
            jtype=jobs.jtype.at[r_pl].set(plan["jtype"], mode="drop"),
            ingress=jobs.ingress.at[r_pl].set(plan["ingress"], mode="drop"),
            dc=jobs.dc.at[r_pl].set(plan["dc"], mode="drop"),
            seq=jobs.seq.at[r_pl].set(plan["seq"], mode="drop"),
            size=jobs.size.at[r_pl].set(plan["size"], mode="drop"),
            t_ingress=jobs.t_ingress.at[r_pl].set(plan["t_ingress"],
                                                  mode="drop"),
            t_avail=jobs.t_avail.at[r_pl].set(plan["t_avail"], mode="drop"),
            net_lat_s=jobs.net_lat_s.at[r_pl].set(plan["net_lat_s"],
                                                  mode="drop"),
            preempt_count=jobs.preempt_count.at[r_pl].set(
                jnp.zeros((K,), jnp.int32), mode="drop"),
            n=jobs.n.at[r_ps].set(plan["n"], mode="drop"),
            f_idx=jobs.f_idx.at[r_ps].set(plan["f_idx"], mode="drop"),
            t_start=jobs.t_start.at[r_ps].set(plan["t_start"], mode="drop"),
            preempt_t=jobs.preempt_t.at[r_ps].set(plan["preempt_t"],
                                                  mode="drop"),
            total_preempt_time=jobs.total_preempt_time.at[r_ps].set(
                plan["total_preempt_time"], mode="drop"),
            rl_valid=jobs.rl_valid.at[r_pf].set(
                jnp.zeros((K,), bool), mode="drop"),
        )
        dc_st = state.dc.replace(
            cur_f_idx=state.dc.cur_f_idx.at[
                jnp.where(plan["dcf"], plan["dc_row"],
                          jnp.int32(fleet.n_dc))].set(
                plan["dcf_val"], mode="drop"),
            acc_job_unit=state.dc.acc_job_unit.at[
                jnp.where(fin, plan["dc_row"], jnp.int32(fleet.n_dc))].add(
                plan["acc_add"], mode="drop"),
        )
        jt_rows_f = jnp.where(fin, plan["fin_jt"], jnp.int32(2))
        lat = state.lat
        # sequential ptr evolution: slot k's write position is the entry
        # pointer plus the same-jtype finishes applied before it
        fin_before = jnp.sum(
            (plan["fin_jt"][None, :] == plan["fin_jt"][:, None])
            & fin[None, :] & np.tril(np.ones((K, K), bool), -1),
            axis=1, dtype=jnp.int32)
        ptr_v = jnp.mod(lat.ptr[plan["fin_jt"]] + fin_before, p.lat_window)
        lat = LatWindow(
            buf=lat.buf.at[jt_rows_f, ptr_v].set(plan["sojourn"],
                                                 mode="drop"),
            count=lat.count.at[jt_rows_f].add(1, mode="drop"),
            # (ptr0 + n) % W == n successive (ptr + 1) % W updates
            ptr=jnp.mod(lat.ptr.at[jt_rows_f].add(1, mode="drop"),
                        p.lat_window),
        )
        # units_finished: left-fold FROM THE ACCUMULATOR in slot order (a
        # duplicate-index float scatter-add has unspecified accumulation
        # order, and pre-summing contributions would change the
        # association; the singleton path computes ((u + s_a) + s_b)...)
        contrib = jnp.where(fin, plan["fin_size"], 0.0)
        units_fin = state.units_finished
        for k in range(K):
            units_fin = units_fin + jnp.where(
                np.arange(2, dtype=np.int32) == plan["fin_jt"][k],
                contrib[k], 0.0)
        return state.replace(
            jobs=jobs, dc=dc_st, lat=lat,
            n_finished=state.n_finished.at[jt_rows_f].add(1, mode="drop"),
            units_finished=units_fin)

    def _plan_finish(self, state: SimState, j, pp=None):
        """Planner `_handle_finish`: same captures and accounting values,
        emitted as a WritePlan + job-log row (+ the chsac partial RL
        record) instead of in-branch writes.  The slab is untouched here,
        so every read is naturally the pre-retire row the legacy handler
        captured up front."""
        p = self.params
        jobs = state.jobs
        dcj, jt, n = jobs.dc[j], jobs.jtype[j], jobs.n[j]
        f_used = self.freq_levels[jobs.f_idx[j]]
        size_j = jobs.size[j]
        t = state.t

        # accumulated units: tpt * (finish_time mod log_interval)
        span = jnp.asarray(t % p.log_interval, dtype=jnp.float32)
        acc = self._acc_job_unit_for(jobs, j, span)

        T_pred = jobs.spu[j]
        P_pred = jobs.watts[j]
        E_pred = T_pred * P_pred
        sojourn = jnp.maximum(0.0, t - jobs.t_start[j]).astype(jnp.float32)

        job_row = jnp.stack([
            jobs.seq[j].astype(jnp.float32),
            jobs.ingress[j].astype(jnp.float32),
            jt.astype(jnp.float32),
            size_j,
            dcj.astype(jnp.float32),
            f_used,
            n.astype(jnp.float32),
            jobs.net_lat_s[j],
            jnp.asarray(jobs.t_start[j], jnp.float32),
            jnp.asarray(t, jnp.float32),
            sojourn,
            jobs.preempt_count[j].astype(jnp.float32),
            T_pred, P_pred, E_pred,
        ])

        plan = self._zero_plan(t.dtype, state)
        plan.update(
            row=j.astype(jnp.int32),
            evict=jnp.bool_(True), fin=jnp.bool_(True),
            status_val=jnp.int32(JobStatus.EMPTY),
            units_done=size_j,
            dc_row=dcj.astype(jnp.int32),
            busy_delta=-n,
            acc_add=acc,
            fin_jt=jt.astype(jnp.int32), fin_size=size_j, sojourn=sojourn,
        )
        if p.algo == ALGO_BANDIT:
            # reward update for the finished arm (legacy `_handle_finish`
            # order: before the post-finish drain's selects, which read
            # the updated counts — the commit applies this plan first)
            plan["bandit"] = bandit_update(state.bandit, dcj, jt,
                                           jobs.f_idx[j], E_pred)

        fin = None
        if p.algo == ALGO_CHSAC_AF:
            E_unit_kwh = E_pred / 3.6e6
            n_act = jnp.maximum(1, jobs.rl_a_g[j] + 1)
            # fmul_pinned: the reward lands in replay records the
            # planner-vs-legacy goldens byte-compare — both product
            # terms must round once in every compiled program (dcg-lint
            # unfenced-float-product).  The RUNTIME factor must be the
            # first arg: a constant `a` lets XLA fold the `a * 0.0`
            # fence away (see the physics.fmul_pinned docstring)
            r = (fmul_pinned(E_unit_kwh, -p.rl_energy_weight)
                 + fmul_pinned(1.0 / n_act.astype(jnp.float32), 0.05))
            tc = jax.tree.map(lambda a: a[dcj, jt], self.latency)
            n_min = min_n_for_sla(size_j, f_used, tc, p.sla_p99_ms,
                                  p.max_gpus_per_job)
            gpu_over = jnp.maximum(0, n - n_min).astype(jnp.float32)
            fin = {
                "valid": jobs.rl_valid[j],
                "s0": jobs.rl_obs0[j],
                "a_dc": jobs.rl_a_dc[j],
                "a_g": jobs.rl_a_g[j],
                "mask_dc0": jobs.rl_mask_dc0[j],
                "mask_g0": jobs.rl_mask_g0[j],
                "r": r,
                "gpu_over": gpu_over,
                "jt": jt,
                "dcj": dcj,
                "slot": j.astype(jnp.int32),
                "sojourn": sojourn,
            }
        return plan, job_row, fin

    def _plan_xfer(self, state: SimState, j):
        """Planner `_admit_or_queue` (non-RL algos).

        Fault-free programs (round 12): the branch only plans the
        queue-on-full EVICT; the START rides iteration 0 of the step's
        shared masked drain (`_drain_queues` ``xfer=``), so ONE
        decide/start chain serves both the xfer admission and the queue
        drain and the branch carries no `_decide_nf` copy of its own —
        the round-9 "next levers" ~100-eqn selection/read-side item.

        Fault programs keep the round-9 in-plan start (decide + clamp +
        physics as two predicate groups): the xfer start must land
        BEFORE the migration sweep (the legacy in-branch position),
        which the post-sweep drain relocation cannot give it.  Bandit
        admissions dispatch through `bandit_select` here — exactly the
        legacy `_decide_nf` arm — with the pull-count update riding the
        plan's bandit carry, committed only when the start fires (the
        legacy cond runs the select on the start path only)."""
        p = self.params
        jobs = state.jobs
        td = state.t.dtype
        dcj = jobs.dc[j].astype(jnp.int32)
        jt = jobs.jtype[j].astype(jnp.int32)
        free = self._free_for(state.dc.busy, dcj, jt, self._up(state))
        can = free > 0
        q_status = JobStatus.EMPTY if self.ring else JobStatus.QUEUED
        plan = self._zero_plan(td, state)
        push = self._zero_push(td)
        if self.ring:
            push = {"enabled": ~can, "dcj": dcj, "jt": jt,
                    "rec": self._rec_from_slab(jobs, j)}
        if not self.faults_on:
            plan.update(row=j.astype(jnp.int32), evict=~can,
                        # explicit int32: a Python-literal weak-types to
                        # int64 under jax_enable_x64 and the event switch
                        # rejects the branch-type mismatch
                        status_val=jnp.int32(q_status))
            return plan, push
        cur_f = state.dc.cur_f_idx[dcj]
        if p.algo == ALGO_BANDIT:
            n_d = jnp.minimum(free, p.max_gpus_per_job)
            bandit2, f_d = bandit_select(state.bandit, dcj, jt)
            new_dc_f = cur_f
            plan["bandit"] = jax.tree.map(
                lambda a, b: jnp.where(can, a, b), bandit2, state.bandit)
        else:
            n_d, f_d, new_dc_f = self._decide_nf_core(state, dcj, jt, free,
                                                      cur_f, state.t)
        # `_start_job` parity: clamp to free, straggler-derate clamp,
        # refresh cached physics, stamp t_start on first start / close a
        # preempt-wait interval
        n_st = jnp.maximum(1, jnp.minimum(n_d.astype(jnp.int32), free))
        f_d = f_d.astype(jnp.int32)
        new_dc_f = new_dc_f.astype(jnp.int32)
        cap = state.fault.derate_f_idx[dcj]
        f_d = jnp.minimum(f_d, cap)
        new_dc_f = jnp.minimum(new_dc_f, cap)
        spu, watts = self._row_TP(dcj, jt, n_st, f_d)
        t_start0 = jobs.t_start[j]
        resuming = jobs.preempt_t[j] > 0.0
        tpt = jobs.total_preempt_time[j] + jnp.where(
            resuming, jnp.asarray(state.t - jobs.preempt_t[j], jnp.float32),
            0.0)
        plan.update(
            row=j.astype(jnp.int32),
            start=can, evict=~can,
            status_val=jnp.where(can, jnp.int32(JobStatus.RUNNING),
                                 jnp.int32(q_status)),
            n=n_st, f_idx=f_d, spu=spu, watts=watts,
            t_start=jnp.where(t_start0 <= 0.0, state.t, t_start0),
            total_preempt_time=tpt,
            dc_row=dcj, busy_delta=n_st,
            dcf=can, dcf_val=new_dc_f,
        )
        return plan, push

    def _plan_xfer_deferred(self, state: SimState, j):
        """Planner `_admit_or_queue_deferred` (chsac): queue-on-full as a
        plan evict, the start as a request for `_commit_tail`."""
        jobs = state.jobs
        td = state.t.dtype
        dcj = jobs.dc[j].astype(jnp.int32)
        jt = jobs.jtype[j].astype(jnp.int32)
        free = self._free_for(state.dc.busy, dcj, jt, self._up(state))
        can = free > 0
        n, f_idx = self._chsac_nf(dcj, jt, free, jobs.rl_a_g[j])
        plan = self._zero_plan(td, state)
        push = self._zero_push(td)
        if self.ring:
            plan.update(row=j.astype(jnp.int32), evict=~can,
                        status_val=jnp.int32(JobStatus.EMPTY))
            push = {"enabled": ~can, "dcj": dcj, "jt": jt,
                    "rec": self._rec_from_slab(jobs, j)}
        else:
            plan.update(row=j.astype(jnp.int32), evict=~can,
                        status_val=jnp.int32(JobStatus.QUEUED))
        sreq = dict(
            self._zero_sreq_plan(td),
            enabled=can, j=j.astype(jnp.int32), n=n, f_idx=f_idx,
            new_dc_f=state.dc.cur_f_idx[dcj], dcj=dcj, jt=jt,
            t_start0=jobs.t_start[j], preempt_t0=jobs.preempt_t[j],
            tpt0=jobs.total_preempt_time[j])
        return plan, sreq, push

    def _plan_arrival(self, state: SimState, ing, jt, key, pre=None):
        """Planner `_handle_arrival`: identical workload draws, routing,
        and stream-clock advance; the placement is a plan row instead of
        an in-branch 17-field write chain.  Returns
        (state, plan, slot, route_pending, push_req)."""
        assert pre is not None, "arrival draws live in the pregen tables"
        p, fleet = self.params, self.fleet
        td = state.t.dtype
        stream = ing * 2 + jt
        k_route = key
        idx = jnp.minimum(state.arr_count[ing, jt] - pre["c0"][stream],
                          pre["sizes"].shape[1] - 1)
        size = pre["sizes"][stream, idx]
        t_next_arr = pre["tnext"][stream, idx].astype(td)

        up = self._up(state)
        defer_route = p.algo == ALGO_CHSAC_AF
        if defer_route:
            dc_sel = jnp.int32(0)  # placeholder; tail overwrites
        elif p.algo == ALGO_ECO_ROUTE:
            dc_sel = algos.route_eco(p, fleet, self.E_grid_cap, jt, size,
                                     self._hour(state.t), up=up,
                                     **self._signal_kw(state.t))
        elif p.router_weights is not None:
            from ..network import RouterPolicy

            q_inf, q_trn = self._queue_lens(state)
            dc_sel = algos.route_weighted(
                RouterPolicy(*p.router_weights), fleet, self.E_grid_cap,
                ing, jt, size, self._hour(state.t), q_inf + q_trn, up=up,
                **self._signal_kw(state.t))
        elif self.faults_on:
            dc_sel = algos.route_random_up(k_route, up)
        else:
            dc_sel = algos.route_random(k_route, fleet.n_dc)

        slot = jnp.argmax(state.jobs.status == JobStatus.EMPTY)
        has_slot = state.jobs.status[slot] == JobStatus.EMPTY

        if defer_route:
            t_avail = jnp.asarray(jnp.inf, td)
            net_lat = jnp.float32(0.0)
        else:
            transfer = self.transfer_s[ing, dc_sel, jt]
            net_lat = self.net_lat_s[ing, dc_sel]
            if self.faults_on:
                # degraded WAN edge stretches propagation + transfer
                # alike.  fmul_pinned: the stretched transfer feeds the
                # t_avail event time, which the K=1 and fused-superstep
                # programs must round identically (the PR 2 FMA-
                # contraction drift class — dcg-lint unfenced-float-
                # product found this one unpinned)
                wm = state.fault.wan_mult[ing, dc_sel]
                transfer = fmul_pinned(transfer, wm)
                net_lat = fmul_pinned(net_lat, wm)
            t_avail = state.t + transfer.astype(td)
        jid = state.jid_counter

        plan = self._zero_plan(td, state)
        plan.update(
            row=slot.astype(jnp.int32),
            place=has_slot,
            status_val=jnp.int32(JobStatus.XFER),
            jtype=jt.astype(jnp.int32), ingress=ing.astype(jnp.int32),
            dc=dc_sel.astype(jnp.int32), seq=jid,
            size=size,
            f_idx=jnp.int32(fleet.default_f_idx),
            t_ingress=state.t, t_avail=t_avail,
            net_lat_s=net_lat,
        )
        push = self._zero_push(td)
        if self.ring and not defer_route:
            # slab full: the routed arrival spills to its DC's ring (the
            # documented early-drain divergence, see `_handle_arrival`);
            # applied post-switch, a full ring counts the drop there
            rec = self._rec_pack(td, size, jid, ing, state.t, t_avail,
                                 net_lat)
            push = {"enabled": ~has_slot, "dcj": dc_sel.astype(jnp.int32),
                    "jt": jt.astype(jnp.int32), "rec": rec}
            n_drop_inc = jnp.int32(0)
        else:
            n_drop_inc = jnp.where(has_slot, jnp.int32(0), jnp.int32(1))

        state = state.replace(
            jid_counter=jid + jnp.int32(1),
            next_arrival=set_at2(state.next_arrival, ing, jt, t_next_arr),
            arr_count=add_at2(state.arr_count, ing, jt, 1),
            n_dropped=state.n_dropped + n_drop_inc,
        )
        return state, plan, slot, has_slot & defer_route, push

    def _zero_sreq_plan(self, td):
        """`_zero_sreq` extended with the start-commit's source scalars
        (`_commit_tail` re-derives `_start_job`'s stamping from these
        instead of re-reading the slab after a materialize)."""
        return dict(
            self._zero_sreq(),
            dcj=jnp.int32(0), jt=jnp.int32(0),
            t_start0=jnp.asarray(0.0, td),
            preempt_t0=jnp.asarray(0.0, td),
            tpt0=jnp.float32(0.0))

    def _zero_tail_plan(self, td):
        obs_dim = self.params.obs_dim(self.fleet.n_dc)
        z32 = jnp.int32(0)
        zf = jnp.float32(0.0)
        zt = jnp.asarray(0.0, td)
        no = jnp.bool_(False)
        return {
            "row": z32,
            "mat": no,   # ring-drain materialize (rec -> slab fields)
            "rt": no,    # route transfer stamp (t_avail, net_lat_s)
            "rl": no,    # dc retarget + RL trace fields
            "jtype": z32, "ingress": z32, "dc": z32, "seq": z32,
            "size": zf, "units_done": zf,
            "t_ingress": zt, "t_avail": zt, "net_lat_s": zf,
            "preempt_count": z32, "preempt_t": zt,
            "t_start": zt, "total_preempt_time": zf,
            "rl_obs0": jnp.zeros((obs_dim,), jnp.float32),
            "rl_a_dc": z32, "rl_a_g": z32,
            "rl_mask_dc0": jnp.zeros((self.fleet.n_dc,), bool),
            "rl_mask_g0": jnp.zeros((self.params.max_gpus_per_job,), bool),
        }

    def _commit_tail(self, state: SimState, tplan, sreq, row) -> SimState:
        """The chsac step's second (and last) commit: the policy tail's
        route / ring-drain materialize writes merged with the step's one
        start request into a single masked write per slab field.

        ``row`` is the step's START row (the xfer row on EV_XFER steps,
        else the tail plan's row); the tail-plan groups (mat/rt/rl) mask
        on ``tplan["row"]`` separately.  The rows coincide on every
        ordinary step, but a promoted migration drain can land on an
        EV_XFER step (fault programs): the legacy tail then materializes
        the migrated record into its slot while the merged start serves
        the xfer row — leaving the record stranded QUEUED — and the two
        masks reproduce that bug-compatibly (start wins where the rows
        coincide, exactly the legacy materialize-then-start overwrite
        order).  Replaces the round-3 shared `_start_job` commit: its
        clamp / physics-refresh / stamping expressions run here
        unchanged, reading the start-source scalars the dispatcher
        planned (`_zero_sreq_plan`)."""
        jobs = state.jobs
        J = jobs.status.shape[0]
        mat, rt, rl = tplan["mat"], tplan["rt"], tplan["rl"]
        en = sreq["enabled"]
        # `_start_job` parity (clamp, straggler-derate clamp, cached
        # physics, stamps)
        free = self._free_for(state.dc.busy, sreq["dcj"], sreq["jt"],
                              self._up(state))
        n = jnp.maximum(1, jnp.minimum(sreq["n"], free))
        f_start = sreq["f_idx"]
        new_dc_f = sreq["new_dc_f"]
        if self.faults_on:
            cap = state.fault.derate_f_idx[sreq["dcj"]]
            f_start = jnp.minimum(f_start, cap)
            new_dc_f = jnp.minimum(new_dc_f, cap)
        spu, watts = self._row_TP(sreq["dcj"], sreq["jt"], n, f_start)
        t_start = jnp.where(sreq["t_start0"] <= 0.0, state.t,
                            sreq["t_start0"])
        tpt = sreq["tpt0"] + jnp.where(
            sreq["preempt_t0"] > 0.0,
            jnp.asarray(state.t - sreq["preempt_t0"], jnp.float32), 0.0)

        m_t = jnp.arange(J) == tplan["row"]
        m_s = jnp.arange(J) == row
        m_rl = m_t & rl
        m_en = m_s & en

        def w(arr, mask, val):
            if arr.ndim > 1:
                mask = mask[:, None]
            return jnp.where(mask, val, arr)

        def w2(arr, en_val, mat_val):
            """Start-group value at the start row, materialize value at
            the tail row; the start wins where the rows coincide (the
            legacy materialize-then-start overwrite order)."""
            m_mat2 = m_t & mat
            if arr.ndim > 1:
                return jnp.where(m_en[:, None], en_val,
                                 jnp.where(m_mat2[:, None], mat_val, arr))
            return jnp.where(m_en, en_val,
                             jnp.where(m_mat2, mat_val, arr))

        if self.ring:
            m_mat = m_t & mat
            m_mr = m_t & (mat | rt)
            jobs = jobs.replace(
                status=w2(jobs.status, jnp.int32(JobStatus.RUNNING),
                          jnp.int32(JobStatus.QUEUED)),
                jtype=w(jobs.jtype, m_mat, tplan["jtype"]),
                ingress=w(jobs.ingress, m_mat, tplan["ingress"]),
                seq=w(jobs.seq, m_mat, tplan["seq"]),
                size=w(jobs.size, m_mat, tplan["size"]),
                units_done=w(jobs.units_done, m_mat, tplan["units_done"]),
                n=w2(jobs.n, n, jnp.int32(0)),
                f_idx=w2(jobs.f_idx, f_start,
                         jnp.int32(self.fleet.default_f_idx)),
                t_ingress=w(jobs.t_ingress, m_mat, tplan["t_ingress"]),
                t_avail=w(jobs.t_avail, m_mr, tplan["t_avail"]),
                t_start=w2(jobs.t_start, t_start, tplan["t_start"]),
                net_lat_s=w(jobs.net_lat_s, m_mr, tplan["net_lat_s"]),
                preempt_count=w(jobs.preempt_count, m_mat,
                                tplan["preempt_count"]),
                preempt_t=w2(jobs.preempt_t,
                             jnp.asarray(0.0, state.t.dtype),
                             tplan["preempt_t"]),
                total_preempt_time=w2(jobs.total_preempt_time, tpt,
                                      tplan["total_preempt_time"]),
                dc=w(jobs.dc, m_rl, tplan["dc"]),
                spu=w(jobs.spu, m_en, spu),
                watts=w(jobs.watts, m_en, watts),
                rl_obs0=w(jobs.rl_obs0, m_rl, tplan["rl_obs0"][None, :]),
                rl_a_dc=w(jobs.rl_a_dc, m_rl, tplan["rl_a_dc"]),
                rl_a_g=w(jobs.rl_a_g, m_rl, tplan["rl_a_g"]),
                rl_mask_dc0=w(jobs.rl_mask_dc0, m_rl,
                              tplan["rl_mask_dc0"][None, :]),
                rl_mask_g0=w(jobs.rl_mask_g0, m_rl,
                             tplan["rl_mask_g0"][None, :]),
                rl_valid=w(jobs.rl_valid, m_mat | m_rl, True),
            )
        else:
            # slab layout: no drain re-materialize exists (the queued row
            # already lives in the slab), so the ``mat`` group is
            # statically dead and the start/route writes compile alone
            jobs = jobs.replace(
                status=w(jobs.status, m_en, JobStatus.RUNNING),
                n=w(jobs.n, m_en, n),
                f_idx=w(jobs.f_idx, m_en, f_start),
                t_avail=w(jobs.t_avail, m_t & rt, tplan["t_avail"]),
                t_start=w(jobs.t_start, m_en, t_start),
                net_lat_s=w(jobs.net_lat_s, m_t & rt, tplan["net_lat_s"]),
                preempt_t=w(jobs.preempt_t, m_en,
                            jnp.asarray(0.0, state.t.dtype)),
                total_preempt_time=w(jobs.total_preempt_time, m_en, tpt),
                dc=w(jobs.dc, m_rl, tplan["dc"]),
                spu=w(jobs.spu, m_en, spu),
                watts=w(jobs.watts, m_en, watts),
                rl_obs0=w(jobs.rl_obs0, m_rl, tplan["rl_obs0"][None, :]),
                rl_a_dc=w(jobs.rl_a_dc, m_rl, tplan["rl_a_dc"]),
                rl_a_g=w(jobs.rl_a_g, m_rl, tplan["rl_a_g"]),
                rl_mask_dc0=w(jobs.rl_mask_dc0, m_rl,
                              tplan["rl_mask_dc0"][None, :]),
                rl_mask_g0=w(jobs.rl_mask_g0, m_rl,
                             tplan["rl_mask_g0"][None, :]),
                rl_valid=w(jobs.rl_valid, m_rl, True),
            )
        dmask = jnp.arange(self.fleet.n_dc) == sreq["dcj"]
        busy = state.dc.busy + jnp.where(dmask & en, n, 0)
        cur_f = jnp.where(dmask & en, new_dc_f, state.dc.cur_f_idx)
        return state.replace(
            jobs=jobs,
            dc=state.dc.replace(busy=busy, cur_f_idx=cur_f))

    def _chsac_place(self, state: SimState, j, key, queue_on_full: bool,
                     pp=None) -> SimState:
        """Fresh policy action for job j (elastic-resume path; the step's
        shared policy tail handles the arrival/drain cases)."""
        obs = self._obs(state)
        if self.params.reserve_inf_gpus > 0:
            reserve = jnp.where(state.jobs.jtype[j] == 1,
                                self.params.reserve_inf_gpus, 0)
        else:
            reserve = 0
        m_dc, m_g = self._masks(state, reserve=reserve)
        a_dc, a_g = self.policy_apply(pp, obs, m_dc, m_g, key)
        return self._commit_place(state, j, obs, m_dc, m_g, a_dc, a_g,
                                  queue_on_full)

    # ---------------- power-cap control (log tick) ----------------

    def _control(self, state: SimState, pred=None) -> SimState:
        """``pred`` (scalar bool, unified superstep body only): every write
        additionally gated — the controller runs unconditionally but only
        takes effect when the step really fired a log tick.  ``None`` (the
        K=1 legacy program) traces the untouched cond-dispatched body."""
        p = self.params
        if p.power_cap <= 0:
            return state
        if p.algo in (ALGO_ECO_ROUTE, ALGO_CARBON_COST):
            # downclock idle DCs to min frequency (reference :221-226)
            idle = state.dc.busy == 0
            m = idle if pred is None else idle & pred
            return state.replace(dc=state.dc.replace(
                cur_f_idx=jnp.where(m, 0, state.dc.cur_f_idx)))
        if p.algo not in (ALGO_CAP_UNIFORM, ALGO_CAP_GREEDY):
            return state

        total_p = tree_sum_last(self._dc_power(state.jobs, state.dc.busy,
                                               self._up(state)))
        need = total_p > p.power_cap - p.cap_margin_w

        if p.algo == ALGO_CAP_UNIFORM:
            fn = self._cap_uniform
        else:
            fn = self._cap_greedy
        if pred is not None:
            # select-free dispatch: the while_loops below gate their
            # initial liveness on ``need & pred`` — zero iterations when
            # the controller should not run, identical state out
            return fn(state, gate=need & pred)
        return jax.lax.cond(need, fn, lambda s: s, state)

    def _cap_uniform(self, state: SimState, gate=None) -> SimState:
        """Uniform DC downclock: repeatedly lower the DC with the largest ΔP.

        Intended semantics (see module docstring): a DC ladder step clamps
        every running job in that DC to the new frequency.  The while_loop
        terminates because every applied step lowers a ladder index (at most
        n_dc * (n_f - 1) iterations).

        ``gate`` (unified superstep body): scalar predicate folded into
        the loop's liveness and every in-body write, replacing the
        per-iteration `lax.cond` — same values, no cond primitive.
        """
        p = self.params

        def power_if_clamped(jobs, dc_idx, level):
            """Total power of running jobs in dc_idx if clamped to <= level."""
            pc, _ = self._job_coeffs(jobs)
            f_clamped = self.freq_levels[jnp.minimum(jobs.f_idx, level)]
            pw = task_power_w(jobs.n, f_clamped, pc)
            mask = (jobs.status == JobStatus.RUNNING) & (jobs.dc == dc_idx)
            return tree_sum_last(jnp.where(mask, pw, 0.0))

        def body(carry):
            st, deficit, live = carry
            # ΔP for lowering each DC one step from its current ladder index
            def dp_for(d):
                cur = st.dc.cur_f_idx[d]
                p_now = power_if_clamped(st.jobs, d, cur)
                p_lo = power_if_clamped(st.jobs, d, jnp.maximum(cur - 1, 0))
                return jnp.where(cur > 0, p_now - p_lo, 0.0)

            dps = jax.vmap(dp_for)(jnp.arange(self.fleet.n_dc))
            best = jnp.argmax(dps)
            best_dp = dps[best]

            ok = best_dp > 1e-9

            def apply(s, g):
                new_level = jnp.maximum(s.dc.cur_f_idx[best] - 1, 0)
                in_dc = (s.jobs.status == JobStatus.RUNNING) & (s.jobs.dc == best)
                if g is not None:
                    in_dc = in_dc & g
                new_f_idx = jnp.where(
                    in_dc, jnp.minimum(s.jobs.f_idx, new_level), s.jobs.f_idx)
                # refresh the clamped jobs' cached physics at the new f
                pc, tc = self._job_coeffs(s.jobs)
                f = self.freq_levels[new_f_idx]
                jobs = s.jobs.replace(
                    f_idx=new_f_idx,
                    spu=jnp.where(in_dc, step_time_s(s.jobs.n, f, tc),
                                  s.jobs.spu).astype(jnp.float32),
                    watts=jnp.where(in_dc, task_power_w(s.jobs.n, f, pc),
                                    s.jobs.watts).astype(jnp.float32))
                dcm = _mask1(s.dc.cur_f_idx, best)
                if g is not None:
                    dcm = dcm & g
                dc = s.dc.replace(
                    cur_f_idx=jnp.where(dcm, new_level, s.dc.cur_f_idx))
                return s.replace(jobs=jobs, dc=dc)

            if gate is None:
                st = jax.lax.cond(ok, lambda s: apply(s, None),
                                  lambda s: s, st)
            else:
                st = apply(st, ok)
            deficit = deficit - jnp.where(ok, best_dp, 0.0)
            return st, deficit, ok & (deficit > 1e-6)

        total_p = tree_sum_last(self._dc_power(state.jobs, state.dc.busy,
                                               self._up(state)))
        deficit = jnp.maximum(0.0, total_p - p.power_cap)
        live0 = deficit > 1e-6
        if gate is not None:
            live0 = gate & live0
        st, _, _ = jax.lax.while_loop(
            lambda c: c[2],
            lambda c: body(c),
            (state, deficit, live0),
        )
        return st

    def _cap_greedy(self, state: SimState, gate=None) -> SimState:
        """Reference-exact atom-ladder downclock (see module docstring).

        Each iteration scores EVERY adjacent ladder step (k -> k-1) below
        every running job's current level by that step's own-endpoint
        ρ = ΔP/ΔV, applies the globally cheapest one by setting the job's
        frequency to the step's LOWER endpoint (a multi-step jump when the
        cheapest step lies deeper than one notch — with the paper physics
        ρ is monotonically cheaper down-ladder, so jobs characteristically
        slam toward f_min one at a time, exactly like the reference's
        sorted-atom pass), re-estimates total power exactly, and repeats
        while over cap.  Equivalence with the reference's
        build-sort-apply-rebuild loop holds because an atom's ρ depends
        only on its own job's (n, coeffs) — applying one job's atom never
        changes another's scores, so globally-cheapest-first visits atoms
        in the same order the sorted pass does (modulo ties).
        """
        p = self.params
        levels = self.freq_levels
        n_f = levels.shape[0]

        def body(carry):
            st, live = carry
            jobs = st.jobs
            pc, tc = self._job_coeffs(jobs)
            pc2 = jax.tree.map(lambda a: a[:, None], pc)
            tc2 = jax.tree.map(lambda a: a[:, None], tc)
            n2 = jobs.n[:, None]
            P_all = task_power_w(n2, levels[None, :], pc2)  # [J, n_f]
            T_all = step_time_s(n2, levels[None, :], tc2)
            V_all = 1.0 / T_all
            # column k-1 <-> atom (level k -> level k-1), k = 1..n_f-1
            dP = jnp.maximum(0.0, P_all[:, 1:] - P_all[:, :-1])
            dV = jnp.maximum(0.0, V_all[:, 1:] - V_all[:, :-1])
            running = jobs.status == JobStatus.RUNNING
            below = jnp.arange(1, n_f)[None, :] <= jobs.f_idx[:, None]
            can = running[:, None] & below & (dV > 0)
            rho = jnp.where(can, dP / jnp.maximum(dV, 1e-12), jnp.inf)
            flat = rho.reshape(-1)
            idx = jnp.argmin(flat)
            ok = jnp.isfinite(flat[idx])
            j = idx // (n_f - 1)
            tgt = idx % (n_f - 1)  # new level index = atom's lower endpoint

            def apply(s, g):
                m = _mask1(s.jobs.f_idx, j)
                if g is not None:
                    m = m & g
                return s.replace(jobs=s.jobs.replace(
                    f_idx=jnp.where(m, tgt.astype(jnp.int32), s.jobs.f_idx),
                    spu=jnp.where(m, T_all[j, tgt].astype(jnp.float32),
                                  s.jobs.spu),
                    watts=jnp.where(m, P_all[j, tgt].astype(jnp.float32),
                                    s.jobs.watts)))

            if gate is None:
                st = jax.lax.cond(ok, lambda s: apply(s, None),
                                  lambda s: s, st)
            else:
                st = apply(st, ok)
            total_p = tree_sum_last(self._dc_power(st.jobs, st.dc.busy,
                                                   self._up(st)))
            still = ok & (total_p > p.power_cap)
            return st, still

        total_p0 = tree_sum_last(self._dc_power(state.jobs, state.dc.busy,
                                                self._up(state)))

        def cond(carry):
            _, live = carry
            return live

        live0 = total_p0 > p.power_cap
        if gate is not None:
            live0 = gate & live0
        st, _ = jax.lax.while_loop(cond, body, (state, live0))
        return st

    # ---------------- event handlers ----------------

    def _acc_job_unit_for(self, jobs: JobSlab, j, span):
        """acc_job_unit += (1 / T(n, f_used)) * span for job j's DC."""
        return span / jobs.spu[j]  # caller guarantees j is RUNNING

    def _handle_finish(self, state: SimState, j, key, pp=None):
        p, fleet = self.params, self.fleet
        jobs = state.jobs
        # capture the finishing job's fields, then free GPUs and retire the
        # slot immediately — the reference pops the job from running_jobs
        # before computing P_now / next-state obs (:703-707, :741-743, :788)
        dcj, jt, n = jobs.dc[j], jobs.jtype[j], jobs.n[j]
        f_idx_j = jobs.f_idx[j]
        f_used = self.freq_levels[f_idx_j]
        size_j = jobs.size[j]
        seq_j, ing_j = jobs.seq[j], jobs.ingress[j]
        net_lat_j, t_start_j = jobs.net_lat_s[j], jobs.t_start[j]
        preempt_j = jobs.preempt_count[j]
        rl_valid_j, rl_obs0_j = jobs.rl_valid[j], jobs.rl_obs0[j]
        rl_a_dc_j, rl_a_g_j = jobs.rl_a_dc[j], jobs.rl_a_g[j]
        rl_mask_dc0_j, rl_mask_g0_j = jobs.rl_mask_dc0[j], jobs.rl_mask_g0[j]
        t = state.t

        # accumulated units: tpt * (finish_time mod log_interval) (reference :711)
        span = jnp.asarray(t % p.log_interval, dtype=jnp.float32)
        acc = self._acc_job_unit_for(jobs, j, span)

        dc = state.dc.replace(
            busy=jnp.maximum(0, add_at(state.dc.busy, dcj, -n)),
            acc_job_unit=add_at(state.dc.acc_job_unit, dcj, acc),
        )
        state = state.replace(
            dc=dc,
            jobs=slab_write(jobs, j, status=JobStatus.EMPTY, rl_valid=False),
            n_finished=add_at(state.n_finished, jt, 1),
            units_finished=add_at(state.units_finished, jt, size_j),
        )

        # predicted per-unit tuple at (n, f_used) — T and P are exactly the
        # slab's cached physics for the (still-pre-retire) row
        T_pred = jobs.spu[j]
        P_pred = jobs.watts[j]
        E_pred = T_pred * P_pred

        sojourn = jnp.maximum(0.0, t - t_start_j).astype(jnp.float32)

        # sliding latency window push
        lat = state.lat
        ptr = lat.ptr[jt]
        lat = LatWindow(
            buf=set_at2(lat.buf, jt, ptr, sojourn),
            count=add_at(lat.count, jt, 1),
            ptr=set_at(lat.ptr, jt, (ptr + 1) % p.lat_window),
        )
        state = state.replace(lat=lat)

        # bandit reward update (reference :825-827)
        if p.algo == ALGO_BANDIT:
            state = state.replace(
                bandit=bandit_update(state.bandit, dcj, jt, f_idx_j, E_pred))

        # job log row
        job_row = jnp.stack([
            seq_j.astype(jnp.float32),
            ing_j.astype(jnp.float32),
            jt.astype(jnp.float32),
            size_j,
            dcj.astype(jnp.float32),
            f_used,
            n.astype(jnp.float32),
            net_lat_j,
            jnp.asarray(t_start_j, jnp.float32),
            jnp.asarray(t, jnp.float32),
            sojourn,
            preempt_j.astype(jnp.float32),
            T_pred, P_pred, E_pred,
        ])

        # RL transition partial record.  The expensive next-state features
        # (s1 obs, masks, p99, P_now) are NOT computed here: under vmap every
        # switch branch executes every step, so they would be paid on every
        # event — the step's shared policy tail (`_policy_tail`) computes
        # them once per step and completes the record.
        fin = None
        if p.algo == ALGO_CHSAC_AF:
            # reference computes (E_pred*size/3.6e6)/(size+eps); the size cancels
            E_unit_kwh = E_pred / 3.6e6
            n_act = jnp.maximum(1, rl_a_g_j + 1)
            # rl_energy_weight = 1.0 reproduces the reference reward
            # exactly; fmul_pinned as in `_plan_finish` (the legacy and
            # planner arms must round the reward identically; runtime
            # factor first, or the fence folds)
            r = (fmul_pinned(E_unit_kwh, -p.rl_energy_weight)
                 + fmul_pinned(1.0 / n_act.astype(jnp.float32), 0.05))
            tc = jax.tree.map(lambda a: a[dcj, jt], self.latency)
            n_min = min_n_for_sla(size_j, f_used, tc, p.sla_p99_ms, p.max_gpus_per_job)
            gpu_over = jnp.maximum(0, n - n_min).astype(jnp.float32)
            fin = {
                "valid": rl_valid_j,
                "s0": rl_obs0_j,
                "a_dc": rl_a_dc_j,
                "a_g": rl_a_g_j,
                "mask_dc0": rl_mask_dc0_j,
                "mask_g0": rl_mask_g0_j,
                "r": r,
                "gpu_over": gpu_over,
                "jt": jt,
                "dcj": dcj,
                "slot": j.astype(jnp.int32),  # freed this step; the policy
                # tail's ring drain re-materializes the queue head into it
                "sojourn": sojourn,
            }

        # elastic re-allocation of training jobs (chsac_af + --elastic-scaling;
        # reference `simulator_paper_multi.py:830-837, 389-409, 498-534`).
        # Divergence (documented): the transition's s1/masks AND its P_now
        # cost (costs[1]) are computed in the policy tail AFTER this
        # reallocation — the state the policy next acts in — where the
        # reference snapshots both before it (:741-743, :788 vs :830).
        # Identical whenever elastic scaling is off.
        if p.algo == ALGO_CHSAC_AF and p.elastic_scaling:
            k_elastic, key = jax.random.split(key)
            n_run_trn = jnp.sum((state.jobs.status == JobStatus.RUNNING)
                                & (state.jobs.jtype == 1))
            state = jax.lax.cond(
                (jt == 1) & (n_run_trn > 1),
                lambda st: self._elastic_reallocate(st, k_elastic, pp=pp),
                lambda st: st,
                state)

        # queue drain: chsac_af defers to the policy tail (one shared
        # policy evaluation per step); other algos drain here in slab mode
        # but post-switch in ring mode (ring pops must stay out of switch
        # branches — ring-mutation note above `_zero_push`; slab drains
        # touch no ring arrays, and in-branch they cost nothing on steps
        # that aren't finishes in the non-vmapped case)
        if p.algo != ALGO_CHSAC_AF and not self.ring:
            state = self._drain_queues(state, dcj, key,
                                       enabled=jnp.bool_(True))
        return state, job_row, fin

    # ---------------- elastic scaling (chsac_af) ----------------

    def _elastic_reallocate(self, state: SimState, key, pp=None) -> SimState:
        """Preempt ALL running training jobs, then let the policy re-place
        each one (possibly at a different DC with a different GPU count).

        Fixes the reference's ignored-resume-failure quirk (SURVEY.md §7.4):
        a job whose chosen DC has no free GPUs is QUEUED there instead of
        silently lost.  Progress (`units_done`) carries over by construction.
        """
        jobs = state.jobs
        trn_running = (jobs.status == JobStatus.RUNNING) & (jobs.jtype == 1)
        n_preempt = jnp.sum(trn_running, dtype=jnp.int32)

        # preempt: free GPUs, mark PREEMPTED, bump counters
        freed = dc_sum(jnp.where(trn_running, jobs.n, 0), jobs.dc,
                       self.fleet.n_dc).astype(jnp.int32)
        jobs = jobs.replace(
            status=jnp.where(trn_running, JobStatus.PREEMPTED, jobs.status),
            preempt_count=jobs.preempt_count + trn_running.astype(jnp.int32),
            preempt_t=jnp.where(trn_running, state.t, jobs.preempt_t),
            n=jnp.where(trn_running, 0, jobs.n),
        )
        if self.faults_on:
            # outage-preempted rows awaiting fault migration are also
            # PREEMPTED and share the FIFO argmin below — bound the
            # re-place loop by the full eligible set so none of the newly
            # preempted training jobs is left beyond the loop.  (A row
            # whose DC is still down is re-placed through the policy like
            # any other; the action masks already exclude down DCs.)
            n_preempt = jnp.sum(jobs.status == JobStatus.PREEMPTED,
                                dtype=jnp.int32)
        state = state.replace(
            jobs=jobs,
            dc=state.dc.replace(busy=jnp.maximum(0, state.dc.busy - freed)))

        # re-place each preempted job in FIFO order via a fresh policy action
        def body(i, st):
            jb = st.jobs
            pre = jb.status == JobStatus.PREEMPTED
            seq = jnp.where(pre, jb.seq, BIG)
            j = jnp.argmin(seq)
            return jax.lax.cond(
                seq[j] < BIG,
                lambda s: self._chsac_place(s, j, jax.random.fold_in(key, i),
                                            queue_on_full=True, pp=pp),
                lambda s: s,
                st)

        # strong-i32 bounds: the dynamic-trip while counter follows the
        # bound dtypes here (unlike static fori_loop counters, which jax
        # canonicalizes internally — see the lint allowlist)
        return jax.lax.fori_loop(jnp.int32(0), n_preempt, body, state)

    # compile-time bound on elastic-resume-failure ring migrations per step.
    # One training finish's `_elastic_reallocate` can fail up to n_preempt
    # re-placements AT ONCE, so the true backlog bound is job_cap (every
    # failure holds a slab slot), NOT this constant: a burst of k failures
    # drains over ceil(k / ELASTIC_MIGRATE_PER_STEP) steps, and only the
    # slab's finite capacity keeps the backlog bounded if fresh finishes
    # keep failing faster than the drain.  While pending, the rows stay
    # visible as QUEUED slab rows (`_queue_lens` counts them) but do hold
    # their slots — a near-full slab can drop arrivals during those steps
    # that an immediate push would not have (transient, bounded by the
    # drain time).  Fault-outage preemption bursts do NOT ride this path:
    # they drain through `_migrate_fault_preempted` under its own
    # FAULT_MIGRATE_PER_STEP bound.
    ELASTIC_MIGRATE_PER_STEP = 2

    def _migrate_elastic_queued(self, state: SimState) -> SimState:
        """Move elastic resume failures from the slab into their DC rings.

        Ring mode keeps every waiting job in the rings; the ONE source of
        persistent QUEUED slab rows is `_commit_place(queue_on_full=True)`
        (elastic resume to a full DC), which must not push in-branch (ring-
        mutation note above `_zero_push`).  This runs post-switch every step
        (compiled only for elastic+ring configs), migrating the lowest-seq
        QUEUED rows via the same predicated `_ring_push` the event switch's
        shared apply uses.  FIFO divergence vs pushing at the elastic event
        itself: an arrival spilling to the same ring in the ceil(k/2) steps
        a k-failure burst takes to drain lands ahead of the preempted jobs —
        bounded by the drain time and negligible next to queue waits (same
        class as the spilled-arrival note in `_handle_arrival.drop`).

        A row whose target ring is FULL is left QUEUED in the slab (retried
        every step) rather than pushed-and-dropped: unlike an arrival spill,
        the job here still owns a slab slot it can safely keep waiting in.
        Room is part of the argmin eligibility, so a blocked row does not
        head-of-line-block rows bound for rings that have space.
        """
        Q = state.queues.recs.shape[2]
        for _ in range(self.ELASTIC_MIGRATE_PER_STEP):  # unrolled: no while
            jb = state.jobs
            has_room = (state.queues.tail - state.queues.head) < Q  # [n_dc, 2]
            eligible = (jb.status == JobStatus.QUEUED) & has_room[jb.dc, jb.jtype]
            seq = jnp.where(eligible, jb.seq, BIG)
            j = jnp.argmin(seq)
            found = seq[j] < BIG
            dcj = jb.dc[j].astype(jnp.int32)
            jt = jb.jtype[j].astype(jnp.int32)
            rec = self._rec_from_slab(jb, j)
            state = state.replace(jobs=slab_write(
                jb, j, _pred=found, status=JobStatus.EMPTY))
            state = self._ring_push(state, dcj, jt, rec, enabled=found)
        return state

    # ---------------- fault injection (SimParams.faults) ----------------

    def _handle_fault(self, state: SimState, pred=None):
        """Fire the timeline's next fault transition (EV_FAULT branch body).

        Everything is a predicated masked update — no ring writes, no
        conds — so the branch stays cheap under vmap and the structural
        guards hold.  Returns ``(state, recovered, dc)``: ``recovered``
        requests a queue drain at ``dc`` (re-admission of work that waited
        out the outage), routed through the same REQ_DRAIN machinery a
        finish uses.

        ``pred`` (scalar bool, unified superstep body only): every write
        additionally gated — the handler runs unconditionally but only
        takes effect when slot 0 really fired a fault transition (fault
        windows degenerate to L=1; fused windows never contain one).
        ``None`` traces the untouched legacy branch body.

        Semantics per kind:
        * DC_DOWN: every RUNNING job at the DC is preempted (GPUs freed,
          progress kept); the capacity mask drops, so placement, drains,
          and routing refuse the DC until recovery.  Preempted rows wait
          PREEMPTED in the slab and `_migrate_fault_preempted` re-homes
          them to up DCs (or fails them when none exists).  In-flight WAN
          transfers toward the DC are NOT cancelled — they land, find 0
          free GPUs, and queue at the DC until recovery (deliberate: the
          reference world's xfer-then-queue order).
        * DC_UP: capacity restored; queued work re-admits via the drain
          request (and subsequent finish-triggered drains).
        * DERATE: the DC's ladder cap drops to `value`; running jobs and
          the DC ladder setting are clamped immediately (cached physics
          refreshed), new starts clamp in `_start_job`.  The off event
          raises the cap back; already-clamped jobs keep their frequency
          until a controller or restart raises it.
        * WAN: the (ingress, dc) edge multiplier is set to `value`
          (latency + transfer stretch; off event restores 1.0).
        """
        fs = state.fault
        i = fs.cursor
        kind, x, val = fs.kind[i], fs.idx[i], fs.value[i]
        n_dc, n_ing = self.fleet.n_dc, self.fleet.n_ing
        dc_iota = jnp.arange(n_dc, dtype=jnp.int32)
        is_down = kind == FK_DC_DOWN
        is_up = kind == FK_DC_UP
        is_der = kind == FK_DERATE
        is_wan = kind == FK_WAN
        if pred is not None:
            # masked dispatch: folding pred into the four kind flags
            # gates every write below (they are all kind-derived)
            is_down = is_down & pred
            is_up = is_up & pred
            is_der = is_der & pred
            is_wan = is_wan & pred

        jobs = state.jobs
        # outage onset: preempt all RUNNING jobs at DC x, free their GPUs
        hit = is_down & (jobs.status == JobStatus.RUNNING) & (jobs.dc == x)
        freed = dc_sum(jnp.where(hit, jobs.n, 0), jobs.dc,
                       n_dc).astype(jnp.int32)
        n_hit = jnp.sum(hit).astype(jnp.int32)
        jobs = jobs.replace(
            status=jnp.where(hit, JobStatus.PREEMPTED, jobs.status),
            preempt_count=jobs.preempt_count + hit.astype(jnp.int32),
            preempt_t=jnp.where(hit, state.t, jobs.preempt_t),
            n=jnp.where(hit, 0, jobs.n),
        )

        # derate onset: clamp running jobs at DC x and refresh physics
        lvl = val.astype(jnp.int32)
        der = is_der & (jobs.status == JobStatus.RUNNING) & (jobs.dc == x)
        new_f = jnp.where(der, jnp.minimum(jobs.f_idx, lvl), jobs.f_idx)
        pc, tc = self._job_coeffs(jobs)
        fv = self.freq_levels[new_f]
        jobs = jobs.replace(
            f_idx=new_f,
            spu=jnp.where(der, step_time_s(jobs.n, fv, tc),
                          jobs.spu).astype(jnp.float32),
            watts=jnp.where(der, task_power_w(jobs.n, fv, pc),
                            jobs.watts).astype(jnp.float32),
        )

        at_x = dc_iota == x
        dc = state.dc.replace(
            busy=jnp.maximum(0, state.dc.busy - freed),
            cur_f_idx=jnp.where(at_x & is_der,
                                jnp.minimum(state.dc.cur_f_idx, lvl),
                                state.dc.cur_f_idx),
        )

        edge_iota = (jnp.arange(n_ing, dtype=jnp.int32)[:, None] * n_dc
                     + dc_iota[None, :])
        # outage nesting: overlapping windows (declarative x stochastic) each
        # fire their own down/up pair; the DC is up only at depth 0, so an
        # inner window's recovery cannot prematurely restore the DC, and an
        # onset only counts as a new outage from depth 0
        delta = ((at_x & is_down).astype(jnp.int32)
                 - (at_x & is_up).astype(jnp.int32))
        depth = jnp.maximum(0, fs.down_depth + delta)
        fs = fs.replace(
            cursor=i + (jnp.int32(1) if pred is None
                        else jnp.where(pred, jnp.int32(1), jnp.int32(0))),
            dc_up=depth == 0,
            down_depth=depth,
            derate_f_idx=jnp.where(at_x & is_der, lvl, fs.derate_f_idx),
            wan_mult=jnp.where(is_wan & (edge_iota == x), val, fs.wan_mult),
            n_outages=fs.n_outages + (at_x & is_down
                                      & (fs.down_depth == 0)).astype(jnp.int32),
            n_preempted=fs.n_preempted + n_hit,
        )
        state = state.replace(jobs=jobs, dc=dc, fault=fs)
        # a nested up-event (outage windows overlapped) leaves the DC down;
        # only the depth-0 recovery requests the re-admission drain
        return state, is_up & (depth[x] == 0), x.astype(jnp.int32)

    # per-step bound on outage-preempted-job migrations (same post-switch
    # predicated-push pattern as ELASTIC_MIGRATE_PER_STEP; the true backlog
    # bound is job_cap — one onset can preempt every running job at a DC)
    FAULT_MIGRATE_PER_STEP = 2

    def _migrate_fault_preempted(self, state: SimState) -> SimState:
        """Drain outage-preempted jobs toward surviving capacity.

        Runs post-switch every step (compiled only when faults are on).
        Post-switch, PREEMPTED rows exist only from outage onsets (the
        elastic path re-places its transient preemptions inside the
        finish branch), so each iteration takes the lowest-seq PREEMPTED
        row and re-queues it, progress and all, at the up DC with the
        most free GPUs (FIFO per step; ring mode also requires ring room
        — a room-less row waits and retries).  Rows whose own DC
        recovered before their turn re-queue the same way — their
        recovered DC is typically the free-GPU argmax — because NOTHING
        else consumes PREEMPTED under the heuristic algorithms (only
        chsac+elastic does); `n_migrated` counts only genuine re-homes
        to a different DC.  With NO up DC in the fleet the job is
        dropped and counted in ``n_failed`` — the "no capacity exists"
        outcome the chaos metrics report.

        Returns ``(state, tgt_last, fired_any)`` so the step can promote
        a queue-drain request at the migration target: a re-queued job at
        an otherwise idle DC would wait forever (queues drain on finishes
        at the DC, its own recovery, or the RL tail — and arrivals admit
        themselves without consulting the queue).  Both per-step
        migrations pick the same free-GPU argmax target unless its ring
        fills mid-step, so draining the last target covers the step.
        """
        tgt_last, fired_any = jnp.int32(0), jnp.bool_(False)
        for _ in range(self.FAULT_MIGRATE_PER_STEP):
            jb, fs = state.jobs, state.fault
            pending = jb.status == JobStatus.PREEMPTED
            seq = jnp.where(pending, jb.seq, BIG)
            j = jnp.argmin(seq)
            found = seq[j] < BIG
            jt = jb.jtype[j].astype(jnp.int32)
            free = (self.total_gpus - state.dc.busy).astype(jnp.int32)
            if self.ring:
                Q = state.queues.recs.shape[2]
                cnt = state.queues.tail - state.queues.head
                cand = fs.dc_up & (cnt[:, jt] < Q)
            else:
                cand = fs.dc_up
            tgt = jnp.argmax(jnp.where(cand, free, -1)).astype(jnp.int32)
            ok = found & cand[tgt]
            fail = found & ~jnp.any(fs.dc_up)
            state = state.replace(fault=fs.replace(
                n_migrated=fs.n_migrated
                + (ok & (tgt != jb.dc[j])).astype(jnp.int32),
                n_failed=fs.n_failed + fail.astype(jnp.int32)))
            if self.ring:
                rec = self._rec_from_slab(jb, j)
                state = state.replace(jobs=slab_write(
                    jb, j, _pred=ok | fail, status=JobStatus.EMPTY))
                state = self._ring_push(state, tgt, jt, rec, enabled=ok)
            else:
                state = state.replace(jobs=slab_write(
                    jb, j, _pred=ok, status=JobStatus.QUEUED, dc=tgt))
                state = state.replace(jobs=slab_write(
                    state.jobs, j, _pred=fail, status=JobStatus.EMPTY))
            tgt_last = jnp.where(ok, tgt, tgt_last)
            fired_any = fired_any | ok
        return state, tgt_last, fired_any

    def _handle_xfer(self, state: SimState, j, key):
        return self._admit_or_queue(state, j, key)

    def _handle_arrival(self, state: SimState, ing, jt, key, pre=None):
        """Returns (state, slot, route_pending).

        For chsac_af the routing decision is deferred to the step's shared
        policy tail: the job is written into the slab with placeholder
        dc/t_avail/net_lat_s (t_avail=+inf can never win the next-event min
        before the tail overwrites it in the same step) and
        ``route_pending`` is set.  Other algorithms route here.

        The workload draws are consumed by cursor from the pregenerated
        ``pre`` table (`workload.compiler`) — two gathers replace the
        fold/split/size-sample/thinning-loop chain, which under vmap was
        paid every step whether or not the event was an arrival.
        """
        assert pre is not None, "arrival draws live in the pregen tables"
        p, fleet = self.params, self.fleet
        # workload draws (size of this arrival + next gap) come from the
        # dedicated per-stream chain so the realized arrival process is
        # identical across algorithms; only routing randomness (k_route)
        # rides the per-event key, which CAN diverge across algorithms
        stream = ing * 2 + jt
        k_route = key
        # cursor into the pregenerated table: arrivals consumed since
        # chunk entry.  <= n_steps - 1 whenever this branch is selected
        # (each step fires at most one arrival); the clip only guards
        # the speculative vmap execution of non-arrival steps.
        idx = jnp.minimum(state.arr_count[ing, jt] - pre["c0"][stream],
                          pre["sizes"].shape[1] - 1)
        size = pre["sizes"][stream, idx]
        t_next_arr = pre["tnext"][stream, idx].astype(state.t.dtype)

        up = self._up(state)
        defer_route = p.algo == ALGO_CHSAC_AF
        if defer_route:
            dc_sel = jnp.int32(0)  # placeholder; tail overwrites
        elif p.algo == ALGO_ECO_ROUTE:
            dc_sel = algos.route_eco(p, fleet, self.E_grid_cap, jt, size,
                                     self._hour(state.t), up=up,
                                     **self._signal_kw(state.t))
        elif p.router_weights is not None:
            # weighted ingress routing (--router-weights): the reference's
            # decorative RouterPolicy made live (SURVEY.md §7.4.3)
            from ..network import RouterPolicy

            q_inf, q_trn = self._queue_lens(state)
            dc_sel = algos.route_weighted(
                RouterPolicy(*p.router_weights), fleet, self.E_grid_cap,
                ing, jt, size, self._hour(state.t), q_inf + q_trn, up=up,
                **self._signal_kw(state.t))
        elif self.faults_on:
            dc_sel = algos.route_random_up(k_route, up)
        else:
            dc_sel = algos.route_random(k_route, fleet.n_dc)

        slot = jnp.argmax(state.jobs.status == JobStatus.EMPTY)
        has_slot = state.jobs.status[slot] == JobStatus.EMPTY

        if defer_route:
            t_avail = jnp.asarray(jnp.inf, state.t.dtype)
            net_lat = jnp.float32(0.0)
        else:
            transfer = self.transfer_s[ing, dc_sel, jt]
            net_lat = self.net_lat_s[ing, dc_sel]
            if self.faults_on:
                # degraded WAN edge stretches propagation + transfer alike
                wm = state.fault.wan_mult[ing, dc_sel]
                transfer = transfer * wm
                net_lat = net_lat * wm
            t_avail = state.t + transfer.astype(state.t.dtype)
        jid = state.jid_counter

        zero_push = self._zero_push(state.t.dtype)

        def place(st):
            jobs = slab_write(
                st.jobs, slot,
                status=JobStatus.XFER,
                jtype=jt,
                ingress=ing,
                dc=dc_sel,
                seq=jid,
                size=size,
                units_done=0.0,
                n=0,
                f_idx=fleet.default_f_idx,
                t_ingress=st.t,
                t_avail=t_avail,
                t_start=0.0,
                net_lat_s=net_lat,
                preempt_count=0,
                preempt_t=0.0,
                total_preempt_time=0.0,
                rl_valid=False,
            )
            return st.replace(jobs=jobs), zero_push

        def drop(st):
            if self.ring and not defer_route:
                # slab full: the routed arrival waits in its DC's ring with
                # its transfer stamped (t_avail).  Divergence (documented,
                # docs/architecture.md): a spilled job becomes drain-eligible
                # immediately, so under extreme overload it can start up to
                # transfer_s earlier than the reference's xfer_done-then-
                # queue order — negligible next to the queue wait that a
                # full system implies, and it can never deadlock a ring
                # behind an un-transferred head.  The push itself is
                # APPLIED post-switch (ring-mutation note, `_zero_push`);
                # a full ring counts the drop there.
                rec = self._rec_pack(
                    st.t.dtype, size, jid, ing, st.t, t_avail, net_lat)
                return st, {"enabled": jnp.bool_(True),
                            "dcj": dc_sel.astype(jnp.int32),
                            "jt": jt.astype(jnp.int32), "rec": rec}
            # chsac defers routing to the policy tail, which writes into the
            # slab slot — with no slot the arrival is dropped (size job_cap
            # to the placed-job bound; rings keep that bound small)
            return st.replace(n_dropped=st.n_dropped + 1), zero_push

        state, push_req = jax.lax.cond(has_slot, place, drop, state)

        # advance this stream's clock (and its chain counter)
        state = state.replace(
            jid_counter=jid + jnp.int32(1),
            next_arrival=set_at2(state.next_arrival, ing, jt, t_next_arr),
            arr_count=add_at2(state.arr_count, ing, jt, 1),
        )
        return state, slot, has_slot & defer_route, push_req

    def _pregen_arrivals(self, state: SimState, n_steps: int,
                         inversion: bool = True):
        """Pre-draw every arrival the next ``n_steps`` events could consume.

        Delegates to the workload compiler (`workload.compiler
        .WorkloadProgram.tables`): the streams are pure per-(ingress,
        jtype) recursions over dedicated fold-in chains, so the whole
        chunk's table — sizes and next-arrival clocks for every stream
        kind (synthetic, trace replay, rate timelines) — is generated
        ahead of the event scan and consumed by cursor.  No workload
        draw, and in particular no thinning `while_loop`, exists inside
        the step body; under vmap every lane used to pay that loop's max
        trip count on every step, arrival or not.

        The generators are chunk-invariant (left-fold carries +
        epoch-anchored inversion — see the compiler docstring), so chunk
        boundaries and superstep K no longer move any arrival bit.

        A chunk of ``n_steps`` steps fires at most ``n_steps`` arrivals
        per stream, so ``n_steps`` draws per stream always suffice.

        Returns {"sizes": [S, n_steps] f32, "tnext": [S, n_steps] td,
        "cum": [S, n_steps] td, "c0": [S] i32}, S = n_ing * 2 streams in
        ``ing * 2 + jt`` order.
        """
        return self.workload.tables(state, n_steps, inversion=inversion)

    def _handle_log(self, state: SimState, powers_hint=None, pred=None):
        """``powers_hint``: the accrual's `_dc_power` result for this step.
        Valid only when no power-cap controller can mutate state between
        the accrual and this tick (power_cap <= 0, a static property) —
        then nothing a log event touches changes job watts or busy.

        ``pred`` (scalar bool, unified superstep body only): all state
        writes masked, rows zeroed when the step did not fire a log tick;
        ``None`` traces the untouched legacy body."""
        p, fleet = self.params, self.fleet
        state = self._control(state, pred=pred)
        jobs = state.jobs

        # accumulate processed units for all running jobs over the interval
        tpt = jnp.where(jobs.status == JobStatus.RUNNING, 1.0 / jobs.spu, 0.0)
        acc = dc_sum(fmul_pinned(tpt, p.log_interval), jobs.dc, fleet.n_dc)
        if pred is not None:
            # masked accumulate: x + 0.0 is exact (the accumulator never
            # goes negative, so no -0.0 + 0.0 sign flip)
            acc = jnp.where(pred, acc, 0.0)
        dc = state.dc.replace(acc_job_unit=state.dc.acc_job_unit + acc)
        state = state.replace(dc=dc)

        running = jobs.status == JobStatus.RUNNING
        one = jnp.where(running, jnp.int32(1), jnp.int32(0))
        run_tot = dc_count(one, jobs.dc, fleet.n_dc)
        run_inf = dc_count(jnp.where(jobs.jtype == 0, one, jnp.int32(0)),
                           jobs.dc, fleet.n_dc)
        q_inf, q_trn = self._queue_lens(state)
        busy = state.dc.busy
        total = self.total_gpus
        util_inst = busy / jnp.maximum(total, 1)
        elapsed = jnp.maximum(1e-9, state.t - state.t_first)
        util_avg = state.dc.util_gpu_time / (total * elapsed)
        if powers_hint is not None and p.power_cap <= 0:
            power_now = powers_hint
        else:
            power_now = self._dc_power(jobs, busy, self._up(state))

        rows = jnp.stack([
            jnp.full((fleet.n_dc,), state.t, dtype=jnp.float32),
            self.freq_levels[state.dc.cur_f_idx],
            busy.astype(jnp.float32),
            (total - busy).astype(jnp.float32),
            run_tot.astype(jnp.float32),
            run_inf.astype(jnp.float32),
            (run_tot - run_inf).astype(jnp.float32),
            q_inf.astype(jnp.float32),
            q_trn.astype(jnp.float32),
            util_inst.astype(jnp.float32),
            jnp.asarray(util_avg, jnp.float32),
            state.dc.acc_job_unit,
            power_now.astype(jnp.float32),
            jnp.asarray(state.dc.energy_j / 1000.0, jnp.float32),
        ], axis=-1)  # [n_dc, 14]
        if self.faults_on:
            # FAULT_CLUSTER_COLS: capacity mask + effective ladder cap
            rows = jnp.concatenate([
                rows,
                state.fault.dc_up.astype(jnp.float32)[:, None],
                self.freq_levels[state.fault.derate_f_idx][:, None],
            ], axis=-1)
        if self.signals_on:
            # SIGNAL_CLUSTER_COLS: the price/carbon samples at this tick
            price_t = jnp.asarray(self.signals.price_at(state.t),
                                  jnp.float32)
            ci_t = jnp.asarray(self.signals.carbon_at(state.t), jnp.float32)
            rows = jnp.concatenate([
                rows,
                jnp.full((fleet.n_dc, 1), price_t, jnp.float32),
                ci_t[:, None],
            ], axis=-1)

        next_log_t = state.next_log_t + jnp.asarray(p.log_interval,
                                                    state.t.dtype)
        if pred is not None:
            rows = jnp.where(pred, rows, 0.0)
            next_log_t = jnp.where(pred, next_log_t, state.next_log_t)
        state = state.replace(next_log_t=next_log_t)
        return state, rows

    # ---------------- in-graph telemetry (obs/, compile-gated) -------------

    def _obs_update(self, state: SimState, powers, fired, kind_counts):
        """Fold one step's telemetry into ``state.telemetry`` (obs_on only).

        Runs at the very END of a step — after every event handler,
        post-switch push, migration, and policy-tail commit — so the
        job-conservation ledger the health probes check is closed.
        Masked arithmetic only (one-hot adds, EMAs, maxima): no
        cond/switch, so the superstep program stays select-free and the
        obs-on cost is a fixed per-step eqn count pinned by
        test_perf_structure.  Returns ``(state, snapshot_row)`` — the
        [registry_width] f32 metric vector in registry order, emitted
        with ``obs_valid`` on log ticks.

        ``fired`` is the number of events this step applied (0/1
        singleton, L for the superstep); ``kind_counts`` is its [5]
        per-kind split (EV_* order).
        """
        from ..obs.health import probe_step

        p = self.params
        tel = state.telemetry
        alpha = jnp.float32(p.obs_ema_alpha)
        fired = fired.astype(jnp.int32)

        q_inf, q_trn = self._queue_lens(state)
        qtot = (q_inf + q_trn).astype(jnp.int32)
        B = p.obs_qdepth_bins
        bin_idx = jnp.clip(
            jnp.floor(jnp.log2(qtot.astype(jnp.float32) + 1.0)),
            0, B - 1).astype(jnp.int32)
        placed = jnp.sum(state.jobs.status != JobStatus.EMPTY,
                         dtype=jnp.int32)
        wan = jnp.sum(state.jobs.status == JobStatus.XFER, dtype=jnp.int32)

        ring_cap = state.queues.recs.shape[2]
        if self.ring:
            ring_cnt = state.queues.tail - state.queues.head
            ring_queued = jnp.sum(ring_cnt, dtype=jnp.int32)
        else:
            # slab mode: waiting jobs are QUEUED slab rows (counted in
            # ``placed``); zero occupancy keeps the ring probes silent
            ring_cnt = jnp.zeros_like(state.queues.tail)
            ring_queued = jnp.int32(0)
        failed = (state.fault.n_failed if self.faults_on else jnp.int32(0))
        viol_inc = probe_step(
            powers=powers, energy_j=state.dc.energy_j, t=state.t,
            ring_cnt=ring_cnt, ring_cap=ring_cap,
            arrived=state.jid_counter - 1, placed=placed,
            ring_queued=ring_queued,
            finished=jnp.sum(state.n_finished, dtype=jnp.int32),
            dropped=state.n_dropped, failed=failed, job_cap=p.job_cap)

        tel = tel.replace(
            steps=tel.steps + 1,
            events_by_kind=tel.events_by_kind + kind_counts,
            # fmul_pinned: the EMA products feed carried accumulators
            # that metrics.jsonl byte-compares across program structures
            # (planner-vs-legacy, K=1-vs-superstep) — an FMA-contracted
            # arm would round the fold differently per program (dcg-lint
            # unfenced-float-product found these unpinned).  The runtime
            # delta is the FIRST arg: alpha is a traced constant, and a
            # constant-side fence folds away
            ema_power=tel.ema_power
            + fmul_pinned(powers.astype(jnp.float32) - tel.ema_power,
                          alpha),
            ema_events=tel.ema_events
            + fmul_pinned(fired.astype(jnp.float32) - tel.ema_events,
                          alpha),
            hist_qdepth=tel.hist_qdepth
            + (bin_idx[:, None] == jnp.arange(B)[None, :]),
            hist_l=tel.hist_l
            + (jnp.arange(tel.hist_l.shape[0]) == fired),
            hw_qdepth=jnp.maximum(tel.hw_qdepth, qtot),
            hw_slab=jnp.maximum(tel.hw_slab, placed),
            viol=tel.viol + viol_inc,
        )
        state = state.replace(telemetry=tel)

        # snapshot row: values keyed by registry name, concatenated in
        # registry order — `obs.metrics.METRIC_TABLE` is the one place
        # names/ids/layout live, and check_metrics_schema lints it
        vals = {
            "obs_steps_total": tel.steps,
            "obs_events_total": state.n_events,
            "obs_events_by_kind_total": tel.events_by_kind,
            "obs_dropped_total": state.n_dropped,
            "obs_finished_total": state.n_finished,
            "obs_queue_depth_inf": q_inf,
            "obs_queue_depth_train": q_trn,
            "obs_busy_gpus": state.dc.busy,
            "obs_util": state.dc.busy / jnp.maximum(self.total_gpus, 1),
            "obs_power_w": powers,
            "obs_energy_j": state.dc.energy_j,
            "obs_wan_inflight": wan,
            "obs_power_ema_w": tel.ema_power,
            "obs_events_per_step_ema": tel.ema_events,
            "obs_queue_depth_hist": tel.hist_qdepth,
            "obs_superstep_l_hist": tel.hist_l,
            "obs_queue_hw": tel.hw_qdepth,
            "obs_slab_hw": tel.hw_slab,
            "obs_slab_inuse": placed,
            "obs_watchdog_violations_total": tel.viol,
        }
        if self.faults_on:
            vals["obs_fault_downtime_s"] = state.fault.downtime
        if self.signals_on:
            vals["obs_price_usd_per_kwh"] = self.signals.price_at(state.t)
            vals["obs_carbon_g_per_kwh"] = self.signals.carbon_at(state.t)
            vals["obs_energy_cost_usd_total"] = state.signals.cost_usd
            vals["obs_carbon_emitted_g_total"] = state.signals.carbon_g
        row = jnp.concatenate([
            jnp.asarray(vals[e.spec.name], jnp.float32).reshape(-1)
            for e in self.obs_registry])
        return state, row

    # ---------------- the step ----------------

    def _step(self, state: SimState, policy_params, pre=None,
              attrib_stop=None):
        # ``attrib_stop`` (analysis/attrib.py): return early at a named
        # phase boundary with the phase's live outputs as the emission —
        # everything traced so far stays reachable, so XLA cannot DCE the
        # work the ablation arm is supposed to measure.  The stop is a
        # static Python value: None compiles the exact production step.
        p, fleet = self.params, self.fleet
        pp = policy_params  # threaded explicitly into the handlers below
        end = jnp.asarray(p.duration, state.t.dtype)

        jobs = state.jobs
        runT = self._run_T(jobs)  # [J], inf where not running

        rem_units = jnp.maximum(0.0, jobs.size - jobs.units_done)
        # fmul_pinned (here and at every replica of this expression,
        # see `_superstep_select`/`_superstep_apply`): event times
        # must round identically in every program structure
        t_fin_all = jnp.where(jnp.isfinite(runT),
                              state.t + fmul_pinned(rem_units, runT),
                              jnp.inf)
        j_fin = jnp.argmin(t_fin_all)

        t_av_all = jnp.where(jobs.status == JobStatus.XFER,
                             jobs.t_avail, jnp.inf)
        j_x = jnp.argmin(t_av_all)
        t_x = t_av_all[j_x]

        arr_flat = state.next_arrival.reshape(-1)
        a_idx = jnp.argmin(arr_flat)
        t_arr = arr_flat[a_idx]
        # int32 casts: under jax_enable_x64 (float64 clock runs) argmin
        # yields int64, which must not leak into the int32 slab fields
        ing = (a_idx // 2).astype(jnp.int32)
        jt_arr = (a_idx % 2).astype(jnp.int32)

        t_log = state.next_log_t

        cands = [jnp.asarray(t_fin_all[j_fin], state.t.dtype),
                 jnp.asarray(t_x, state.t.dtype),
                 jnp.asarray(t_arr, state.t.dtype),
                 t_log]
        if self.faults_on:
            # next fault transition: one gather at the timeline cursor
            cands.append(state.fault.times[state.fault.cursor])
        cand = jnp.stack(cands)
        kind = jnp.argmin(cand)  # ties: finish < xfer < arrival < log
        t_next = cand[kind]

        past_end = (t_next > end) | ~jnp.isfinite(t_next) | state.done
        t_adv = jnp.where(past_end, end, t_next)

        # ---- accrual over [t, t_adv] (skipped before the first event) ----
        dt = jnp.maximum(0.0, t_adv - state.t)
        dt_f = jnp.asarray(dt, jnp.float32)
        powers = self._dc_power(jobs, state.dc.busy, self._up(state))
        # fmul_pinned: the accumulator products must round once,
        # everywhere — the superstep fused path replays this accrual per
        # sub-step (`_superstep_apply`) and FMA contraction in one program
        # but not the other would break bit-identity across K
        e_inc = fmul_pinned(powers, dt)
        u_inc = fmul_pinned(state.dc.busy, dt)
        accrue = state.started_accrual & ~state.done
        dc = state.dc.replace(
            energy_j=state.dc.energy_j + jnp.where(accrue, e_inc, 0.0),
            util_gpu_time=state.dc.util_gpu_time
            + jnp.where(accrue, u_inc, 0.0),
        )
        # progress advance for running jobs
        prog = jnp.where(jnp.isfinite(runT), dt_f / jnp.where(jnp.isfinite(runT), runT, 1.0), 0.0)
        jobs = jobs.replace(
            units_done=jnp.minimum(jobs.size, jobs.units_done + prog))
        if self.signals_on:
            # cost/carbon integrals ride the same exact inter-event gaps
            # as the energy accrual; the price/CI sample is the interval
            # START (piecewise-constant timelines, docs/workloads.md)
            kwh_inc = jnp.asarray(e_inc, jnp.float32) / 3.6e6
            sg = state.signals
            state = state.replace(signals=sg.replace(
                cost_usd=sg.cost_usd + jnp.where(
                    accrue,
                    fmul_pinned(kwh_inc, self.signals.price_at(state.t)),
                    0.0),
                carbon_g=sg.carbon_g + jnp.where(
                    accrue,
                    fmul_pinned(kwh_inc, self.signals.carbon_at(state.t)),
                    0.0)))
        state = state.replace(
            dc=dc, jobs=jobs, t=t_adv,
            started_accrual=jnp.bool_(True),
            t_first=jnp.where(state.started_accrual, state.t_first, t_adv),
        )
        if self.faults_on:
            # downtime accrues over the same exact inter-event gaps as
            # energy/util (dt is 0 once done, so no over-count at the end)
            fs = state.fault
            state = state.replace(fault=fs.replace(
                downtime=fs.downtime + jnp.where(fs.dc_up, 0.0, dt)))

        state = state.replace(done=state.done | past_end)

        is_rl = p.algo == ALGO_CHSAC_AF
        if is_rl:
            key, k_ev, k_act = jax.random.split(state.key, 3)
        else:  # keep the non-RL per-event key sequence unchanged
            key, k_ev = jax.random.split(state.key)
            k_act = None
        state = state.replace(key=key)

        if attrib_stop == "head":
            # event-min head + inter-event accrual only; kind/t_next keep
            # the argmin chain live under DCE
            return state, {"kind": kind, "t_next": t_next}

        n_dc_cols = (len(CLUSTER_COLS)
                     + (len(FAULT_CLUSTER_COLS) if self.faults_on else 0)
                     + (len(SIGNAL_CLUSTER_COLS) if self.signals_on else 0))
        zero_cluster = jnp.zeros((fleet.n_dc, n_dc_cols), jnp.float32)
        zero_job = jnp.zeros((len(JOB_COLS),), jnp.float32)
        zero_fin = self._zero_fin() if is_rl else None
        planner = self.planner_on
        if is_rl:
            zero_sreq = (self._zero_sreq_plan(state.t.dtype) if planner
                         else self._zero_sreq())
        else:
            zero_sreq = None
        zero_plan = self._zero_plan(state.t.dtype, state) if planner else None
        zero_push = self._zero_push(state.t.dtype)
        REQ_NONE, REQ_ROUTE, REQ_DRAIN = jnp.int32(0), jnp.int32(1), jnp.int32(2)

        # Branches return (state, plan, cluster, job_row, job_valid, fin,
        # req_kind, req_idx, push_req).  ``fin`` is the partial
        # RL-transition record of a finish event (chsac only); ``req``
        # defers the step's policy-dependent placement work (arrival
        # routing / post-finish queue drain) to the shared `_policy_tail`
        # — and for non-RL algos the post-switch `_drain_queues` — so (a)
        # the policy network, obs, masks, and latency percentiles are
        # evaluated ONCE per step (under vmap every branch body executes
        # every step) and (b) no branch ever WRITES `queues.recs`
        # (``push_req`` carries the step's at most one ring push out to a
        # shared predicated apply — the ring-mutation note above
        # `_zero_push`).  With `self.planner_on` (round 9) the branches'
        # slab/dc/counter writes ride ``plan`` instead — the one shared
        # `_commit_plan` right after the switch applies them (write-plan
        # note above `_zero_plan`); legacy configurations omit the plan
        # slot entirely and compile the round-8 program.

        def do_finish(st):
            if planner:
                plan, row, fin = self._plan_finish(st, j_fin, pp=pp)
                if is_rl:
                    return (st, plan, zero_cluster, row, jnp.bool_(True),
                            fin, REQ_DRAIN, fin["dcj"], zero_sreq,
                            zero_push)
                return (st, plan, zero_cluster, row, jnp.bool_(True), None,
                        REQ_DRAIN, plan["dc_row"], zero_push)
            # exact retirement: mark the finishing job's units complete
            st = st.replace(jobs=st.jobs.replace(
                units_done=jnp.where(_mask1(st.jobs.units_done, j_fin),
                                     st.jobs.size, st.jobs.units_done)))
            dcj_fin = st.jobs.dc[j_fin]
            st, row, fin = self._handle_finish(st, j_fin, k_ev, pp=pp)
            if is_rl:
                return (st, zero_cluster, row, jnp.bool_(True), fin,
                        REQ_DRAIN, fin["dcj"], zero_sreq, zero_push)
            return (st, zero_cluster, row, jnp.bool_(True), None,
                    REQ_DRAIN, dcj_fin.astype(jnp.int32), zero_push)

        def do_xfer(st):
            if planner and is_rl:
                plan, sreq, push = self._plan_xfer_deferred(st, j_x)
                return (st, plan, zero_cluster, zero_job, jnp.bool_(False),
                        zero_fin, REQ_NONE, jnp.int32(0), sreq, push)
            if planner:
                plan, push = self._plan_xfer(st, j_x)
                return (st, plan, zero_cluster, zero_job, jnp.bool_(False),
                        zero_fin, REQ_NONE, jnp.int32(0), push)
            if is_rl:
                # start deferred to the step's shared _start_job commit
                st, sreq, push = self._admit_or_queue_deferred(st, j_x)
                return (st, zero_cluster, zero_job, jnp.bool_(False),
                        zero_fin, REQ_NONE, jnp.int32(0), sreq, push)
            st, push = self._handle_xfer(st, j_x, k_ev)
            return (st, zero_cluster, zero_job, jnp.bool_(False), zero_fin,
                    REQ_NONE, jnp.int32(0), push)

        def do_arrival(st):
            if planner:
                st, plan, slot, pending, push = self._plan_arrival(
                    st, ing, jt_arr, k_ev, pre=pre)
                kind_r = jnp.where(pending, REQ_ROUTE, REQ_NONE)
                out = (st, plan, zero_cluster, zero_job, jnp.bool_(False),
                       zero_fin, kind_r, slot.astype(jnp.int32))
                return out + (zero_sreq, push) if is_rl else out + (push,)
            st, slot, pending, push = self._handle_arrival(st, ing, jt_arr,
                                                           k_ev, pre=pre)
            kind_r = jnp.where(pending, REQ_ROUTE, REQ_NONE)
            out = (st, zero_cluster, zero_job, jnp.bool_(False), zero_fin,
                   kind_r, slot.astype(jnp.int32))
            return out + (zero_sreq, push) if is_rl else out + (push,)

        def do_log(st):
            # the log tick keeps its in-branch writes in planner mode too:
            # it touches no slab row (the cap controllers' whole-array
            # clamps and [n_dc] accumulators are not row plans)
            st, rows = self._handle_log(st, powers_hint=powers)
            out = (st, rows, zero_job, jnp.bool_(False), zero_fin,
                   REQ_NONE, jnp.int32(0))
            if planner:
                out = out[:1] + (zero_plan,) + out[1:]
            return out + (zero_sreq, zero_push) if is_rl else out + (zero_push,)

        def do_fault(st):
            # the fault branch keeps its in-branch writes in planner mode
            # too (like the log tick): `_handle_fault` is whole-array
            # masked updates — preemption sweeps, capacity/derate/WAN
            # masks — not a row plan; the branch contributes an identity
            # plan and the shared commit applies nothing for it
            st, recovered, dcx = self._handle_fault(st)
            if not is_rl and not self.ring and not planner:
                # slab-mode legacy heuristics drain in-branch, like a
                # finish does (planner slab drains post-commit, before the
                # migration sweep — the equivalent position)
                st = self._drain_queues(st, dcx, k_ev, enabled=recovered)
            kind_r = jnp.where(recovered, REQ_DRAIN, REQ_NONE)
            if is_rl:
                # the policy-tail drain materializes the recovered DC's
                # queue head into a free slab slot (a finish supplies its
                # own freed slot here; a recovery must find one)
                slot = jnp.argmax(st.jobs.status == JobStatus.EMPTY)
                fin_f = dict(zero_fin, slot=slot.astype(jnp.int32))
                out = (st, zero_cluster, zero_job, jnp.bool_(False), fin_f,
                       kind_r, dcx, zero_sreq, zero_push)
            else:
                out = (st, zero_cluster, zero_job, jnp.bool_(False),
                       zero_fin, kind_r, dcx, zero_push)
            if planner:
                out = out[:1] + (zero_plan,) + out[1:]
            return out

        def no_op(st):
            out = (st, zero_cluster, zero_job, jnp.bool_(False), zero_fin,
                   REQ_NONE, jnp.int32(0))
            if planner:
                out = out[:1] + (zero_plan,) + out[1:]
            return out + (zero_sreq, zero_push) if is_rl else out + (zero_push,)

        # Branch selection: 4 event kinds (5 with faults), or no-op when the
        # next event lies beyond end_time (the final accrual above already
        # ran) or we were already done.
        branches = [do_finish, do_xfer, do_arrival, do_log]
        if self.faults_on:
            # fault_log emission row: gathered at the pre-fire cursor
            fs0 = state.fault
            fault_row = jnp.stack([
                jnp.asarray(state.t, jnp.float32),
                fs0.kind[fs0.cursor].astype(jnp.float32),
                fs0.idx[fs0.cursor].astype(jnp.float32),
                fs0.value[fs0.cursor],
            ])
            branches.append(do_fault)
        branches.append(no_op)
        branch = jnp.where(state.done, len(branches) - 1, kind)

        out = jax.lax.switch(branch, branches, state)
        plan = None
        if planner and is_rl:
            (state, plan, cluster, job_row, job_valid, fin,
             req_kind, req_idx, sreq_evt, push_req) = out
        elif planner:
            (state, plan, cluster, job_row, job_valid, fin,
             req_kind, req_idx, push_req) = out
        elif is_rl:
            (state, cluster, job_row, job_valid, fin,
             req_kind, req_idx, sreq_evt, push_req) = out
        else:
            (state, cluster, job_row, job_valid, fin,
             req_kind, req_idx, push_req) = out

        def _attrib_aux():
            # every switch output the later phases consume, kept live
            aux = {"cluster": cluster, "job": job_row,
                   "job_valid": job_valid, "req_kind": req_kind,
                   "req_idx": req_idx, "push": push_req}
            if is_rl:
                aux["sreq"] = sreq_evt
            return aux

        if attrib_stop == "switch":
            aux = _attrib_aux()
            if plan is not None:
                aux["plan"] = plan
            return state, aux

        if planner:
            # THE shared slab commit: one masked write per slab field for
            # the whole event switch (write-plan note above `_zero_plan`)
            state = self._commit_plan(state, plan)

        # chsac+elastic (planner, round 12): the finish branch's
        # reallocation sweep relocates to right after the commit — the
        # same position the legacy program runs it (post-retire, inside
        # the finish branch, before the pushes/migrations/tail), with the
        # same key derivation (`_handle_finish` splits its event key) and
        # the same predicate evaluated on the identical post-retire state
        if is_rl and planner and p.elastic_scaling:
            k_elastic, _ = jax.random.split(k_ev)
            n_run_trn = jnp.sum((state.jobs.status == JobStatus.RUNNING)
                                & (state.jobs.jtype == 1))
            state = jax.lax.cond(
                (branch == EV_FINISH) & (fin["jt"] == 1) & (n_run_trn > 1),
                lambda st: self._elastic_reallocate(st, k_elastic, pp=pp),
                lambda st: st,
                state)
        if attrib_stop == "commit":  # planner configs only (attrib gates)
            return state, _attrib_aux()
        # non-RL planner (fault-free): the xfer-admission start rides
        # iteration 0 of the shared masked drain below (round 12) — at
        # most one of the xfer-admit / queue-drain requests is active per
        # step, so ONE decide/start chain serves both
        xreq = None
        if not is_rl and planner and not self.faults_on:
            xreq = {"on": branch == EV_XFER, "j": j_x.astype(jnp.int32)}
        if not is_rl and planner and self.faults_on and not self.ring:
            # slab fault programs drain their finish/recovery request
            # BEFORE the migration sweep — the legacy in-branch position
            # (nothing touches state between the commit and this drain)
            state = self._drain_queues(state, req_idx, k_ev,
                                       enabled=req_kind == REQ_DRAIN,
                                       masked=True)
        # the step's single shared ring push (at most one branch enables it)
        if self.ring:
            state = self._ring_push(state, push_req["dcj"], push_req["jt"],
                                    push_req["rec"],
                                    enabled=push_req["enabled"])
        # elastic resume failures wait in the slab as QUEUED (the one path
        # that would otherwise write rings inside the event switch); move
        # them into their DC's rings here, FIFO, a bounded few per step
        if is_rl and self.ring and p.elastic_scaling:
            state = self._migrate_elastic_queued(state)
        # outage-preempted jobs drain toward surviving capacity (or fail
        # when none exists) — same post-switch predicated-push pattern
        if self.faults_on:
            state, mig_tgt, mig_fired = self._migrate_fault_preempted(state)
            # a migration step with no other pending request promotes a
            # drain at the target DC, so a re-queued job at an idle DC
            # starts instead of waiting for a finish that may never come.
            # (An RL step already carrying a route/drain request keeps it;
            # the migrated job then waits for the target's next drain
            # trigger, which the policy sees coming via the queue-length
            # obs.)
            promote = (req_kind == REQ_NONE) & mig_fired
            if is_rl or not planner:
                req_kind = jnp.where(promote, REQ_DRAIN, req_kind)
                req_idx = jnp.where(promote, mig_tgt, req_idx)
            if is_rl:
                # the tail's drain materializes into fin["slot"]; only the
                # finish/fault branches stocked it with a real EMPTY slot
                free_slot = jnp.argmax(state.jobs.status == JobStatus.EMPTY)
                fin = dict(fin, slot=jnp.where(
                    promote, free_slot.astype(jnp.int32), fin["slot"]))
            elif not planner and not self.ring:
                # slab-mode legacy heuristics drained their finish/fault
                # REQ_DRAIN in-branch; the promoted migration drain runs
                # here
                state = self._drain_queues(state, req_idx, k_ev,
                                           enabled=promote)
        # non-RL queue drain after a finish (chsac drains in the tail).
        # Planner programs drain post-switch in BOTH layouts — the finish
        # branch only plans, so its in-branch slab drain is gone — through
        # the merged masked body (no cond; bit-equal relocation: nothing
        # touches state between the commit and this drain).  Ring fault
        # programs MERGE the promoted migration drain into the one
        # masked call, exactly like the legacy ring merge into req_kind
        # (value-identical: promote requires req_kind == REQ_NONE, so at
        # most one target is live — and ONE drain loop, not two, keeps
        # the fault planner's step cost at the legacy program's).  The
        # slab fault layout already drained its finish/recovery request
        # above (before the migration sweep, the legacy in-branch
        # position), so only the promoted drain remains here.  Legacy
        # slab mode keeps the in-branch drain; legacy ring mode drains
        # here with the cond body.
        if not is_rl and planner:
            if self.faults_on and not self.ring:
                state = self._drain_queues(state, mig_tgt, k_ev,
                                           enabled=promote, masked=True)
            elif self.faults_on:
                state = self._drain_queues(
                    state, jnp.where(promote, mig_tgt, req_idx), k_ev,
                    enabled=(req_kind == REQ_DRAIN) | promote,
                    masked=True)
            else:
                state = self._drain_queues(state, req_idx, k_ev,
                                           enabled=req_kind == REQ_DRAIN,
                                           masked=True, xfer=xreq)
        elif not is_rl and self.ring:
            state = self._drain_queues(state, req_idx, k_ev,
                                       enabled=req_kind == REQ_DRAIN)

        if attrib_stop == "drain":
            return state, _attrib_aux()

        emission = {
            "t": jnp.asarray(state.t, jnp.float32),
            "cluster_valid": branch == EV_LOG,
            "cluster": cluster,
            "job_valid": job_valid,
            "job": job_row,
        }
        if self.faults_on:
            emission["fault_valid"] = branch == EV_FAULT
            emission["fault"] = fault_row
        if attrib_stop == "emit":
            # log tail: the per-step emission assembly (the policy tail's
            # pending start request stays live for the RL delta)
            return state, (dict(emission, _sreq=sreq_evt) if is_rl
                           else emission)
        if is_rl and planner:
            state, rl_em, tplan, sreq_tail = self._policy_tail_planned(
                state, req_kind, req_idx, fin, k_act, pp)
            emission["rl"] = rl_em
            # the step's second (and last) commit: the tail dispatch's
            # route/materialize plan merged with the step's one start
            # request — at most one of the xfer-admit (event switch) /
            # route / queue-drain (tail switch) paths is active, and the
            # start always targets the same row the tail plan wrote
            sreq = jax.tree.map(
                lambda a, b: jnp.where(branch == EV_XFER, a, b),
                sreq_evt, sreq_tail)
            row = jnp.where(branch == EV_XFER, sreq_evt["j"], tplan["row"])
            state = self._commit_tail(state, tplan, sreq, row)
        elif is_rl:
            state, rl_em, sreq_tail = self._policy_tail(
                state, req_kind, req_idx, fin, k_act, pp)
            emission["rl"] = rl_em
            # the step's single shared start-commit: at most one of the
            # xfer-admit (event switch) / queue-drain (tail switch)
            # requests can be enabled in any step
            sreq = jax.tree.map(
                lambda a, b: jnp.where(branch == EV_XFER, a, b),
                sreq_evt, sreq_tail)
            state = self._start_job(state, sreq["j"], sreq["n"],
                                    sreq["f_idx"], sreq["new_dc_f"],
                                    enabled=sreq["enabled"])

        if attrib_stop == "tail":  # RL configs only (attrib gates)
            return state, emission

        state = state.replace(
            n_events=state.n_events + jnp.where(state.done, jnp.int32(0),
                                                jnp.int32(1)))
        if self.obs_on:
            # ``branch`` indexes EV_* for fired steps; the no-op branch
            # only runs when done, which zeroes both counters here
            fired = (~state.done).astype(jnp.int32)
            # boolean mask (not a weak-int where): stays int32 under
            # jax_enable_x64 AND keeps the obs block's eqn count equal
            # to the K>1 fold's (the K-independence pin)
            kind_counts = (~state.done
                           & (jnp.arange(5) == branch)).astype(jnp.int32)
            state, obs_row = self._obs_update(state, powers, fired,
                                              kind_counts)
            emission["obs"] = obs_row
            emission["obs_valid"] = branch == EV_LOG
        return state, emission

    def _zero_sreq(self):
        return {"enabled": jnp.bool_(False), "j": jnp.int32(0),
                "n": jnp.int32(0), "f_idx": jnp.int32(0),
                "new_dc_f": jnp.int32(0)}

    def _zero_fin(self):
        obs_dim = self.params.obs_dim(self.fleet.n_dc)
        return {
            "valid": jnp.bool_(False),
            "s0": jnp.zeros((obs_dim,), jnp.float32),
            "a_dc": jnp.int32(0),
            "a_g": jnp.int32(0),
            "mask_dc0": jnp.zeros((self.fleet.n_dc,), bool),
            "mask_g0": jnp.zeros((self.params.max_gpus_per_job,), bool),
            "r": jnp.float32(0.0),
            "gpu_over": jnp.float32(0.0),
            "jt": jnp.int32(0),
            "dcj": jnp.int32(0),
            "slot": jnp.int32(0),
            "sojourn": jnp.float32(0.0),
        }

    def _tail_head(self, state: SimState, req_kind, req_idx, fin, k_act, pp):
        """The policy tail's shared head (chsac_af): obs / masks / one
        batched two-window percentile / ONE policy forward, plus the
        completed RL-transition emission record.  Shared verbatim by the
        legacy `_policy_tail` and the planner `_policy_tail_planned` so
        the two dispatch styles cannot drift."""
        # both windows' p99 from ONE batched top_k: the g-mask SLO-slack
        # heuristic and the transition's latency cost share it
        perc2 = jax.vmap(
            lambda b, c: algos.windowed_percentile(b, c, 99.0)
        )(state.lat.buf, state.lat.count)
        obs = self._obs(state)
        if self.params.reserve_inf_gpus > 0:
            # masks must reflect what the commit will accept: when the
            # pending decision (route / drain) concerns a TRAINING job, the
            # per-DC inference reserve shrinks every visible free count
            if self.ring:
                _, jt_drain, _ = self._ring_head(state, req_idx,
                                                 state.dc.busy,
                                                 self._up(state))
            else:
                j_drain, _ = self._next_queued(state.jobs, req_idx,
                                               state.dc.busy,
                                               self._up(state))
                jt_drain = state.jobs.jtype[j_drain]
            jt_req = jnp.where(req_kind == 1, state.jobs.jtype[req_idx],
                               jnp.where(req_kind == 2, jt_drain, 0))
            extra = jnp.where(jt_req == 1, self.params.reserve_inf_gpus, 0)
        else:
            extra = 0
        m_dc, m_g = self._masks(state, p99_pair=perc2, reserve=extra)
        a_dc, a_g = self.policy_apply(pp, obs, m_dc, m_g, k_act)

        # emission features on the pre-commit state
        p99_ms = jnp.where(state.lat.count[fin["jt"]] >= 5,
                           perc2[fin["jt"]] * 1000.0, fin["sojourn"] * 1000.0)
        P_now = self._dc_power(state.jobs, state.dc.busy,
                               self._up(state))[fin["dcj"]]
        rl_em = {
            "valid": fin["valid"],
            "s0": fin["s0"],
            "s1": obs,
            "a_dc": fin["a_dc"],
            "a_g": fin["a_g"],
            "mask_dc0": fin["mask_dc0"],
            "mask_g0": fin["mask_g0"],
            "r": fin["r"],
            "costs": jnp.stack(
                [p99_ms, P_now, fin["gpu_over"],
                 jnp.asarray(jnp.sum(state.dc.energy_j), jnp.float32)]),
            "mask_dc": m_dc,
            "mask_g": m_g,
        }
        return obs, m_dc, m_g, a_dc, a_g, rl_em

    def _policy_tail(self, state: SimState, req_kind, req_idx, fin, k_act,
                     pp):
        """The step's single shared policy evaluation (chsac_af only).

        Computes obs / masks / latency percentiles / the policy action once
        (`_tail_head`), then (a) commits a deferred arrival routing or
        post-finish queue drain per ``req_kind`` and (b) completes the
        finish branch's RL transition record (s1 = the state the policy
        acts in here, i.e. post-retire pre-drain — matching the
        reference's obs snapshot at `simulator_paper_multi.py:788`).
        Legacy dispatch (planner_on=False): branches write the slab
        in-branch and the start rides the round-3 shared `_start_job`."""
        obs, m_dc, m_g, a_dc, a_g, rl_em = self._tail_head(
            state, req_kind, req_idx, fin, k_act, pp)

        zero_sreq = self._zero_sreq()

        def do_none(st):
            return st, zero_sreq

        def do_route(st):
            slot = req_idx
            jt_s = st.jobs.jtype[slot]
            ing_s = st.jobs.ingress[slot]
            transfer = self.transfer_s[ing_s, a_dc, jt_s]
            net_lat = self.net_lat_s[ing_s, a_dc]
            if self.faults_on:
                # fmul_pinned: feeds the t_avail event time, like the
                # identical stretch in `_plan_arrival` (dcg-lint
                # unfenced-float-product)
                wm = st.fault.wan_mult[ing_s, a_dc]
                transfer = fmul_pinned(transfer, wm)
                net_lat = fmul_pinned(net_lat, wm)
            jobs = slab_write(
                st.jobs, slot,
                dc=a_dc,
                t_avail=st.t + transfer.astype(st.t.dtype),
                net_lat_s=net_lat,
                rl_obs0=obs[None, :],
                rl_a_dc=a_dc,
                rl_a_g=a_g,
                rl_mask_dc0=m_dc[None, :],
                rl_mask_g0=m_g[None, :],
                rl_valid=True,
            )
            return st.replace(jobs=jobs), zero_sreq

        def do_drain(st):
            dcj = req_idx
            if not self.ring:
                j, found = self._next_queued(st.jobs, dcj, st.dc.busy,
                                             self._up(st))
                return self._commit_place_deferred(st, j, obs, m_dc, m_g,
                                                   a_dc, a_g, found)
            # ring mode: the head record re-materializes into the slab slot
            # the finish branch just freed (fin["slot"]), predicated on the
            # commit actually starting; otherwise it stays in its ring
            rec, jt_sel, found = self._ring_head(st, dcj, st.dc.busy,
                                                 self._up(st))
            slot = fin["slot"]
            ok = found & (self._free_for(st.dc.busy, a_dc, jt_sel,
                                         self._up(st)) > 0)
            if self.faults_on:
                # a fault-recovery drain borrows no freed slot: require the
                # one it found to still be EMPTY (always true for finishes)
                ok = ok & (st.jobs.status[slot] == JobStatus.EMPTY)
            st = self._materialize(st, slot, rec, dcj, jt_sel, pred=ok)
            st, sreq = self._commit_place_deferred(st, slot, obs, m_dc, m_g,
                                                   a_dc, a_g, ok)
            return self._ring_pop(st, dcj, jt_sel, sreq["enabled"]), sreq

        state, sreq = jax.lax.switch(req_kind, [do_none, do_route, do_drain],
                                     state)
        return state, rl_em, sreq

    def _policy_tail_planned(self, state: SimState, req_kind, req_idx, fin,
                             k_act, pp):
        """`_policy_tail` with planner dispatch (round 9): the same shared
        head, but the route / queue-drain branches return a tail
        WritePlan + start request instead of writing the slab — the
        step's single `_commit_tail` applies the merged result (one
        masked write per slab field, absorbing the shared `_start_job`).
        Only the ring pops (head counters, branch-safe by the ring-write
        rule) stay in-branch."""
        obs, m_dc, m_g, a_dc, a_g, rl_em = self._tail_head(
            state, req_kind, req_idx, fin, k_act, pp)
        td = state.t.dtype
        zero_tplan = self._zero_tail_plan(td)
        zero_sreq = self._zero_sreq_plan(td)

        def do_none(st):
            return st, zero_tplan, zero_sreq

        def do_route(st):
            slot = req_idx
            jt_s = st.jobs.jtype[slot]
            ing_s = st.jobs.ingress[slot]
            transfer = self.transfer_s[ing_s, a_dc, jt_s]
            net_lat = self.net_lat_s[ing_s, a_dc]
            if self.faults_on:
                # fmul_pinned: feeds the t_avail event time, like the
                # identical stretch in `_plan_arrival` (dcg-lint
                # unfenced-float-product)
                wm = st.fault.wan_mult[ing_s, a_dc]
                transfer = fmul_pinned(transfer, wm)
                net_lat = fmul_pinned(net_lat, wm)
            tplan = dict(
                zero_tplan,
                row=slot.astype(jnp.int32),
                rt=jnp.bool_(True), rl=jnp.bool_(True),
                dc=a_dc.astype(jnp.int32),
                t_avail=st.t + transfer.astype(td),
                net_lat_s=net_lat,
                rl_obs0=obs, rl_a_dc=a_dc.astype(jnp.int32),
                rl_a_g=a_g.astype(jnp.int32),
                rl_mask_dc0=m_dc, rl_mask_g0=m_g)
            return st, tplan, zero_sreq

        def do_drain(st):
            dcj = req_idx
            if not self.ring:
                # slab mode: the queued row starts (or stays QUEUED) in
                # place — `_commit_place_deferred`'s dc/RL writes as a
                # plan, its start request completed from slab scalars
                j, found = self._next_queued(st.jobs, dcj, st.dc.busy,
                                             self._up(st))
                jt_s = st.jobs.jtype[j]
                free_tgt = self._free_for(st.dc.busy, a_dc, jt_s,
                                          self._up(st))
                ok = found & (free_tgt > 0)
                n, f_idx = self._chsac_nf(a_dc, jt_s, free_tgt, a_g)
                tplan = dict(
                    zero_tplan,
                    row=j.astype(jnp.int32), rl=ok,
                    dc=a_dc.astype(jnp.int32),
                    rl_obs0=obs, rl_a_dc=a_dc.astype(jnp.int32),
                    rl_a_g=a_g.astype(jnp.int32),
                    rl_mask_dc0=m_dc, rl_mask_g0=m_g)
                sreq = dict(
                    zero_sreq, enabled=ok, j=j.astype(jnp.int32),
                    n=n, f_idx=f_idx, new_dc_f=st.dc.cur_f_idx[a_dc],
                    dcj=a_dc.astype(jnp.int32), jt=jt_s.astype(jnp.int32),
                    t_start0=st.jobs.t_start[j],
                    preempt_t0=st.jobs.preempt_t[j],
                    tpt0=st.jobs.total_preempt_time[j])
                return st, tplan, sreq
            # ring mode: the head record re-materializes into the slab
            # slot the finish branch just freed (fin["slot"]) — as a mat
            # plan, with the start request's stamping sourced from the
            # record itself instead of a second slab read
            rec, jt_sel, found = self._ring_head(st, dcj, st.dc.busy,
                                                 self._up(st))
            slot = fin["slot"]
            free_tgt = self._free_for(st.dc.busy, a_dc, jt_sel,
                                      self._up(st))
            ok = found & (free_tgt > 0)
            if self.faults_on:
                # a fault-recovery drain borrows no freed slot: require the
                # one it found to still be EMPTY (always true for finishes)
                ok = ok & (st.jobs.status[slot] == JobStatus.EMPTY)
            n, f_idx = self._chsac_nf(a_dc, jt_sel, free_tgt, a_g)
            f32r = lambda k: rec[k].astype(jnp.float32)  # noqa: E731
            i32r = lambda k: rec[k].astype(jnp.int32)  # noqa: E731
            tplan = dict(
                zero_tplan,
                row=slot.astype(jnp.int32),
                mat=ok, rl=ok,
                jtype=jt_sel.astype(jnp.int32),
                ingress=i32r(QRec.INGRESS),
                dc=a_dc.astype(jnp.int32),
                seq=i32r(QRec.SEQ),
                size=f32r(QRec.SIZE),
                units_done=f32r(QRec.UNITS_DONE),
                t_ingress=rec[QRec.T_INGRESS],
                t_avail=rec[QRec.T_AVAIL],
                net_lat_s=f32r(QRec.NET_LAT_S),
                preempt_count=i32r(QRec.PREEMPT_COUNT),
                preempt_t=rec[QRec.PREEMPT_T],
                t_start=rec[QRec.T_START],
                total_preempt_time=f32r(QRec.TOTAL_PREEMPT_TIME),
                rl_obs0=obs, rl_a_dc=a_dc.astype(jnp.int32),
                rl_a_g=a_g.astype(jnp.int32),
                rl_mask_dc0=m_dc, rl_mask_g0=m_g)
            sreq = dict(
                zero_sreq, enabled=ok, j=slot.astype(jnp.int32),
                n=n, f_idx=f_idx, new_dc_f=st.dc.cur_f_idx[a_dc],
                dcj=a_dc.astype(jnp.int32), jt=jt_sel.astype(jnp.int32),
                t_start0=rec[QRec.T_START],
                preempt_t0=rec[QRec.PREEMPT_T],
                tpt0=f32r(QRec.TOTAL_PREEMPT_TIME))
            return self._ring_pop(st, dcj, jt_sel, ok), tplan, sreq

        state, tplan, sreq = jax.lax.switch(
            req_kind, [do_none, do_route, do_drain], state)
        return state, rl_em, tplan, sreq

    # ---------------- superstep event coalescing (superstep_k > 1) --------
    #
    # The round-5 cost model proves the engine is op-dispatch bound: each
    # event moves ~37 kB / ~0.16 MFLOP, so wall time tracks the per-step op
    # count times the trip count — and `lax.scan` fires exactly ONE event
    # per step.  The superstep amortizes the fixed step cost by applying up
    # to K events per scan iteration, the same trip-count lever batched
    # accelerator simulators (Brax, EnvPool) pull.
    #
    # Exactness is by construction, not by approximation.  A window of the
    # K earliest pending events is fused ONLY when the commutation
    # predicate proves that applying them through the masked fused handler
    # reproduces the singleton path event for event:
    #
    # * only real finish / xfer / arrival kinds — the window truncates at
    #   the next log/control tick (and faults compile the whole feature
    #   out, see `superstep_on`);
    # * pairwise-DISTINCT DCs — per-DC state (busy, ladder, accruals,
    #   rings) is touched by at most one event, so per-DC effects commute;
    # * NO queued work anywhere — every in-window queue drain is provably
    #   a no-op (a DC's queue can only gain work from in-window events at
    #   OTHER DCs, which its own drain never reads);
    # * nothing an applied event GENERATES (a started job's finish, an
    #   arrival's transfer completion or next stream arrival) may land
    #   inside the window — so the selected window is exactly the true
    #   event-sequence prefix.
    #
    # Round 7 made the K>1 program SELECT-FREE: there is no singleton
    # fallback body any more.  The predicate no longer chooses *which
    # program runs* — it computes the longest commuting prefix length
    # L in [1, K] of the selected window, and ONE unified body applies
    # exactly those L slots through the fused masked handlers, extended
    # with slot-0 singleton semantics (end-of-horizon clamp + done,
    # first-event accrual gating, the log tick's control/acc/row path,
    # and the post-finish queue drain) that are live only on degenerate
    # L=1 windows.  Round 6 ran the fused body AND the whole singleton
    # `_step` under a `lax.cond` — which under vmap lowers to a select
    # executing BOTH bodies every iteration, the measured ~2x overhead
    # that ate the structural win (docs/perf_notes.md round 7).  The
    # semantics are unchanged: the finish < xfer < arrival < log
    # tie-break and every floating-point accumulation order are preserved
    # bit-for-bit (goldens in tests/test_superstep.py, unmodified from
    # round 6).  Bit-identity across K holds across ANY chunking since
    # round 10: the workload compiler's pregen is chunk-invariant
    # (left-fold carries + epoch-anchored inversion), so K changing how
    # many events one chunk covers no longer moves any arrival bit
    # (tests/test_superstep.py::test_chunk_boundary_continuity_exact).
    #
    # Ring discipline: the unified body EMITS up to K push requests (xfer
    # queue-on-full, arrival spill) and `_step_super` applies them after
    # the body — `queues.recs` stays out of every data-dependent select
    # (ring-mutation note above `_zero_push`, generalized from 1 to <= K
    # bounded pushes).  The whole K>1 program carries NO `cond`/`switch`
    # primitive (pinned by test_perf_structure), so nothing is ever
    # traced twice.

    def _decide_nf_super(self, state: SimState, dcj, jt, free, t_evt,
                         q_inf_len):
        """`_decide_nf` for the unified superstep body (non-RL, non-bandit).

        Bit-equal values by construction — same `_decide_nf_core`
        dispatch, the simulated clock at the event equals ``t_evt``, and
        ``q_inf_len`` is the event DC's REAL window-entry inference queue
        length (round 7): exact for a degenerate L=1 window's singleton
        admission, and bit-equal to the round-6 constant 0 on every fused
        slot (in-window events can neither read nor grow the event DC's
        queue — distinct DCs, spills guarded out — and the only consumer,
        perf_first's heuristic, has a queue-empty validity check)."""
        cur_f = state.dc.cur_f_idx[dcj]
        n, f_idx, new_dc_f = self._decide_nf_core(
            state, dcj, jt, free, cur_f, t_evt, q_inf_len=q_inf_len)
        return n.astype(jnp.int32), f_idx.astype(jnp.int32), new_dc_f

    def _superstep_select(self, state: SimState, pre=None,
                          head_only: bool = False):
        """Pick the K earliest pending events; decide fused vs singleton.

        ``head_only`` (analysis/attrib.py): stop after the K-wide
        event-min head — candidate times, key chain, top_k, kind/index
        decode — and return those arrays, skipping the vmapped per-slot
        payload and the commutation predicate.  The traced prefix nests
        inside the full selection, so the attribution deltas telescope.

        The candidate array is laid out [finishes(J), xfers(J),
        arrivals(S), log] so K successive first-minimum argmins reproduce
        the singleton tie-break exactly (time, then kind
        finish < xfer < arrival < log, then lowest index).  All per-slot
        payloads — the arrival's workload draws and routing, the xfer's
        start decision, and every window-stable field of the event's slab
        row (rows are only written by their OWN event, so window-entry
        gathers are exact) — are computed ONCE, batched over the K slots
        with vmap.  Returns stacked [K] payloads plus the scalar
        ``fused_ok`` commutation predicate (see the section comment).

        ``pre`` is the chunk's pregenerated workload table; a direct
        caller (the predicate unit tests) may omit it and a K-wide
        table is built on the spot — same backend flag as run_chunk, so
        cursor addressing makes the values identical to the chunk-wide
        table's."""
        if pre is None:
            pre = self._pregen_arrivals(state, self.K + 1,
                                        inversion=self.arrival_pregen)
        p, fleet = self.params, self.fleet
        K = self.K
        td = state.t.dtype
        J = p.job_cap
        S = fleet.n_ing * 2
        end = jnp.asarray(p.duration, td)
        jobs = state.jobs
        eps = jnp.asarray(jnp.finfo(td).eps, td)

        runT = self._run_T(jobs)
        rem_units = jnp.maximum(0.0, jobs.size - jobs.units_done)
        t_fin_all = jnp.where(jnp.isfinite(runT),
                              state.t + fmul_pinned(rem_units, runT), jnp.inf)
        t_av_all = jnp.where(jobs.status == JobStatus.XFER, jobs.t_avail,
                             jnp.inf)
        arr_flat = state.next_arrival.reshape(-1)
        time_parts = [
            jnp.asarray(t_fin_all, td), jnp.asarray(t_av_all, td),
            jnp.asarray(arr_flat, td), state.next_log_t[None]]
        if self.faults_on:
            # the next fault transition joins the candidate array LAST so
            # it loses ties to every base kind — exactly the singleton's
            # cands order (EV_FAULT tie-break, see the module header)
            time_parts.append(state.fault.times[state.fault.cursor][None])
        times = jnp.concatenate(time_parts)

        # per-event key chain: one split per applied event — exactly the
        # singleton sequence (every non-RL step splits state.key once)
        kc = state.key
        k_ev, k_after = [], []
        for _ in range(K):
            kc, ke = jax.random.split(kc)
            k_after.append(kc)
            k_ev.append(ke)

        # K earliest candidates (+ the first time BEYOND the window, for
        # the finish-separation check) in one top_k: ties break to the
        # lower index, exactly the iterated-argmin (= singleton) order
        neg_t, pos_all = jax.lax.top_k(-times, K + 1)
        pos_v = pos_all[:K].astype(jnp.int32)
        t_v = -neg_t[:K]  # negation is exact: bit-equal to times[pos]
        t_beyond = -neg_t[K]

        # strong int32 kind literals: the nested weak-Python-int chain
        # computes in int64 under jax_enable_x64 (weak-type-promotion)
        log_or_tail = (jnp.int32(3) if not self.faults_on
                       else jnp.where(pos_v == 2 * J + S, jnp.int32(3),
                                      jnp.int32(4)))
        kind_v = jnp.where(pos_v < J, jnp.int32(0),
                           jnp.where(pos_v < 2 * J, jnp.int32(1),
                                     jnp.where(pos_v < 2 * J + S,
                                               jnp.int32(2), log_or_tail))
                           ).astype(jnp.int32)
        j_v = jnp.where(kind_v == 1, pos_v - J,
                        jnp.where(kind_v == 0, pos_v, 0)).astype(jnp.int32)
        a_v = jnp.clip(pos_v - 2 * J, 0, S - 1).astype(jnp.int32)
        ing_v = (a_v // 2).astype(jnp.int32)
        jt_a_v = (a_v % 2).astype(jnp.int32)

        if head_only:
            return {"t": t_v, "kind": kind_v, "j": j_v, "ing": ing_v,
                    "jt_arr": jt_a_v, "t_beyond": t_beyond}

        # window-entry inference queue lengths for the heuristic admission
        # family (`_decide_nf_super`); the grid algos never read the value
        # so they skip the (slab-mode) whole-slab reduction entirely
        if p.algo in (ALGO_JOINT_NF, ALGO_CARBON_COST, ALGO_DEBUG):
            q_inf_entry = None
        else:
            q_inf_entry, _ = self._queue_lens(state)

        def payload(t_k, j, a, ing, jt_a, ke):
            out = {}
            # arrival: workload draws (dedicated per-stream chain,
            # untouched before this stream's single in-window arrival)
            # and routing — exactly `_handle_arrival`'s expressions
            idx = jnp.minimum(state.arr_count[ing, jt_a] - pre["c0"][a],
                              pre["sizes"].shape[1] - 1)
            size_a = pre["sizes"][a, idx]
            t_next_arr = pre["tnext"][a, idx].astype(td)
            up = self._up(state)
            if p.algo == ALGO_ECO_ROUTE:
                # signal timelines sample at the slot's own event time —
                # exactly `_handle_arrival`'s expressions (`_signal_kw`
                # returns {} for the legacy static-table world)
                dc_arr = algos.route_eco(p, fleet, self.E_grid_cap, jt_a,
                                         size_a, self._hour(t_k), up=up,
                                         **self._signal_kw(t_k))
            elif self.faults_on:
                dc_arr = algos.route_random_up(ke, up)
            else:
                dc_arr = algos.route_random(ke, fleet.n_dc)
            transfer = self.transfer_s[ing, dc_arr, jt_a]
            net_lat = self.net_lat_s[ing, dc_arr]
            if self.faults_on:
                # degraded WAN edge stretches propagation + transfer
                # alike; wan_mult is window-constant (fault transitions
                # truncate every window)
                wm = state.fault.wan_mult[ing, dc_arr]
                transfer = transfer * wm
                net_lat = net_lat * wm
            t_avail = t_k + transfer.astype(td)
            out.update(arr_size=size_a, arr_t_next=jnp.asarray(t_next_arr, td),
                       arr_t_avail=t_avail, arr_net_lat=net_lat,
                       dc_arr=dc_arr.astype(jnp.int32))

            # window-stable fields of the event row (a row is only written
            # by its own event, so window-entry values are event-time exact)
            dc_j = jobs.dc[j]
            jt_j = jobs.jtype[j]
            n_j = jobs.n[j]
            f_used = self.freq_levels[jobs.f_idx[j]]
            size_j = jobs.size[j]
            spu_j, watts_j = jobs.spu[j], jobs.watts[j]
            t_start_j = jobs.t_start[j]
            preempt_t_j = jobs.preempt_t[j]
            out.update(dc_j=dc_j, jt_j=jt_j, n_j=n_j, size_j=size_j,
                       spu_j=spu_j, t_start_j=t_start_j,
                       preempt_t_j=preempt_t_j,
                       tpt_j=jobs.total_preempt_time[j])

            # xfer: the start this admission would commit (free GPUs at
            # the event DC are untouched by other in-window events; the
            # fault capacity/derate masks are window-constant)
            free = self._free_for(state.dc.busy, dc_j, jt_j, up)
            q_inf_len = (jnp.int32(0) if q_inf_entry is None
                         else q_inf_entry[dc_j].astype(jnp.int32))
            n_d, f_d, newf_d = self._decide_nf_super(state, dc_j, jt_j,
                                                     free, t_k, q_inf_len)
            n_st = jnp.maximum(1, jnp.minimum(n_d, free))
            if self.faults_on:
                # `_start_job` parity: straggler derating clamps every
                # start's frequency (job AND DC ladder) to the DC's cap
                cap = state.fault.derate_f_idx[dc_j]
                f_d = jnp.minimum(f_d, cap)
                newf_d = jnp.minimum(newf_d, cap.astype(newf_d.dtype))
            spu, watts = self._row_TP(dc_j, jt_j, n_st, f_d)
            out.update(x_can=free > 0, x_n=n_st, x_f=f_d, x_newf=newf_d,
                       x_spu=spu, x_watts=watts,
                       x_t_fin=t_k + fmul_pinned(size_j, spu))

            # finish job-log row, window-stable columns (finish_s and
            # latency_s are patched at apply time from the re-derived t)
            E_pred = spu_j * watts_j
            out["job_row"] = jnp.stack([
                jobs.seq[j].astype(jnp.float32),
                jobs.ingress[j].astype(jnp.float32),
                jt_j.astype(jnp.float32), size_j,
                dc_j.astype(jnp.float32), f_used,
                n_j.astype(jnp.float32), jobs.net_lat_s[j],
                jnp.asarray(t_start_j, jnp.float32), jnp.float32(0.0),
                jnp.float32(0.0),
                jobs.preempt_count[j].astype(jnp.float32),
                spu_j, watts_j, E_pred,
            ])
            if self.ring:
                # queue-push records (xfer queue-on-full / arrival spill;
                # the spill's seq column is patched at apply time).  The
                # SPILL side is provably dead under the current predicate
                # (the >= K-free-slots guard means every fused arrival
                # places) but stays live so relaxing that guard cannot
                # silently drop arrivals.  An
                # XFER row is always a fresh arrival, so its progress /
                # preempt fields are the pack's zero defaults — no gathers
                out["rec_x"] = self._rec_pack(
                    td, size_j, jobs.seq[j], jobs.ingress[j],
                    jobs.t_ingress[j], jobs.t_avail[j], jobs.net_lat_s[j])
                out["rec_a"] = self._rec_pack(td, size_a, 0, ing, t_k,
                                              t_avail, net_lat)
            return out

        pay = jax.vmap(payload)(t_v, j_v, a_v, ing_v, jt_a_v,
                                jnp.stack(k_ev))
        dc_v = jnp.where(kind_v == 2, pay["dc_arr"],
                         pay["dc_j"]).astype(jnp.int32)

        # validity: the applied window is a PREFIX of slots that are
        # (a) real event kinds inside the horizon, (b) at pairwise-
        # distinct DCs, (c) for finishes — at DCs with EMPTY queues (the
        # post-finish drain is then provably a no-op; other DCs' queues
        # are irrelevant because a drain only reads its own DC), and
        # (d) for finishes at window position >= 1 — separated from their
        # sorted neighbors by a float-drift margin: finish times are
        # RE-DERIVED each singleton step from accumulated progress, so
        # the fused path re-derives them too (`_superstep_apply`), and
        # only a > margin gap guarantees the drift cannot reorder the
        # window.  (The position-0 finish re-derives against the
        # untouched window-entry state: bit-equal by definition.)
        base = (kind_v <= 2) & jnp.isfinite(t_v) & (t_v <= end)
        lower_tri = np.tril(np.ones((K, K), bool), -1)  # [k, i]: i < k
        # pairwise-distinct DCs among FINISH/XFER slots only: those read
        # and write per-DC state (busy, ladder, rings, accruals) from
        # window-entry snapshots.  Arrivals are exempt — they read no DC
        # state and only touch the slab — because the >= K-free-slots
        # guard below removes their one DC side effect (the slab-full
        # ring spill) from every fused window.
        fx = kind_v <= 1
        clash = ((dc_v[:, None] == dc_v[None, :])
                 & (fx[:, None] & fx[None, :]) & lower_tri)
        base = base & ~jnp.any(clash, axis=1)
        base = base & jnp.where(
            kind_v == 2,
            jnp.sum(jobs.status == JobStatus.EMPTY, dtype=jnp.int32) >= K,
            True)

        mgn = 64.0 * eps * (jnp.abs(t_v) + 1.0)
        gap = jnp.diff(jnp.concatenate([t_v[:1], t_v, t_beyond[None]]))
        sep = (gap[:-1] > mgn) & (gap[1:] > mgn)
        base = base & jnp.where((kind_v == 0) & (np.arange(K) >= 1), sep,
                                True)

        if self.ring:
            cnt = state.queues.tail - state.queues.head  # [n_dc, 2]
            dc_q_empty = jnp.all(cnt == 0, axis=1)
            fin_ok = dc_q_empty[dc_v]
            if p.policy_name == "perf_first":
                # perf_first's heuristic reads q_inf at the admission DC;
                # the fused path pins it to 0, so it must really be empty
                fin_ok = fin_ok & (cnt[dc_v, 0] == 0)
        else:
            queued = jobs.status == JobStatus.QUEUED
            fin_ok = ~jnp.any(queued[None, :] & (jobs.dc[None, :]
                                                 == dc_v[:, None]), axis=1)
        check_kinds = ((kind_v == 0) if p.policy_name != "perf_first"
                       else (kind_v <= 1))
        base = base & jnp.where(check_kinds, fin_ok, True)

        # generated-event checks: nothing an applied event creates (a
        # started job's finish, an arrival's transfer completion or next
        # stream arrival) may land inside — or tie with the end of — the
        # window.  Evaluated PAIRWISE against every candidate window end
        # so a violating slot TRUNCATES the window instead of killing it:
        # ok[k, e] = "slot k's generated events all land after slot e's
        # time".  Feasibility is monotone (t_e grows with e), so the
        # longest feasible prefix is a cumulative AND.  Stored times
        # (t_avail, next arrival) compare strictly; the started-job
        # finish gets the re-derivation drift margin.
        mgn2 = 64.0 * eps * (jnp.abs(t_v)[None, :]
                             + jnp.abs(pay["x_t_fin"])[:, None] + 1.0)
        ok_x = (~pay["x_can"][:, None]
                | (pay["x_t_fin"][:, None] > t_v[None, :] + mgn2))
        ok_a = ((pay["arr_t_avail"][:, None] > t_v[None, :])
                & (pay["arr_t_next"][:, None] > t_v[None, :]))
        gen_pair = jnp.where((kind_v == 1)[:, None], ok_x,
                             jnp.where((kind_v == 2)[:, None], ok_a, True))
        # slot k only constrains ends e >= k (it is not in shorter
        # windows): mask [k, e] with k > e
        gen_end = jnp.all(gen_pair | lower_tri, axis=0)

        valid_v = jnp.cumprod((base & gen_end).astype(jnp.int32)) == 1
        # int32 even under jax_enable_x64 (sum would promote to int64)
        m = jnp.sum(valid_v, dtype=jnp.int32)

        fused_ok = (m >= 2) & state.started_accrual & ~state.done
        if self.faults_on:
            # migration sweeps are per-EVENT machinery: a fused window
            # would run them once per ITERATION instead.  PREEMPTED rows
            # only exist between an outage onset (a fault transition —
            # which truncates every window to L=1) and the sweep draining
            # them, so requiring an empty backlog makes the per-iteration
            # sweep a provable no-op on every fused window while L=1
            # windows run it exactly once per event, like the singleton.
            fused_ok = fused_ok & ~jnp.any(
                jobs.status == JobStatus.PREEMPTED)
        sel = dict(pay, t=t_v, kind=kind_v, j=j_v, ing=ing_v, jt_arr=jt_a_v,
                   dc=dc_v, valid=valid_v)
        return {"slots": sel, "fused_ok": fused_ok, "m": m,
                "k_after": k_after, "k_ev0": k_ev[0]}

    def _ring_push_many(self, state: SimState, dcj_v, jt_v, rec_v,
                        enabled_v) -> SimState:
        """Apply up to K push requests as ONE batched scatter.

        Sound because a window's pushes target pairwise-distinct DCs (the
        commutation predicate; a degenerate L=1 window enables at most
        slot 0) — the (dc, jt) cells are unique, so counter reads,
        positions, and the scatter are order-independent and bit-equal to
        K sequential `_ring_push` calls.  Disabled slots scatter out of
        bounds with mode="drop"."""
        q = state.queues
        Q = q.recs.shape[2]
        dcj_v = dcj_v.astype(jnp.int32)
        jt_v = jt_v.astype(jnp.int32)
        cnt = q.tail[dcj_v, jt_v] - q.head[dcj_v, jt_v]
        ok = enabled_v & (cnt < Q)
        pos = jnp.mod(q.tail[dcj_v, jt_v], Q).astype(jnp.int32)
        dc_ok = jnp.where(ok, dcj_v, jnp.int32(self.fleet.n_dc))  # OOB drops
        q = q.replace(
            recs=q.recs.at[dc_ok, jt_v, pos].set(
                rec_v.astype(q.recs.dtype), mode="drop"),
            tail=q.tail.at[dc_ok, jt_v].add(1, mode="drop"),
        )
        n_drop = jnp.sum(enabled_v & ~ok, dtype=jnp.int32)
        return state.replace(queues=q, n_dropped=state.n_dropped + n_drop)

    def _superstep_apply(self, state: SimState, sel, pre=None,
                         attrib_stop=None):
        """THE K>1 step body: apply the window's L events through fused
        masked handlers — one program, no cond, no singleton fallback.

        ``attrib_stop`` (analysis/attrib.py) truncates at two internal
        boundaries — ``"apply_loop"`` (after the in-order sub-step
        unroll) and ``"apply_commit"`` (after the K-row WritePlan commit
        + counters + key chain) — returning ``(state, aux, None, None)``
        with the phase's live outputs; the slot-0 tails / emission /
        push-stack assembly is then the caller-visible ``"apply"`` rest.

        Slot 0 always applies with full singleton semantics: its event
        fires unless the next event lies beyond the horizon (then the
        step is `_step`'s final-accrual/no-op, end-clamped), it may be a
        log tick (masked `_handle_log`/`_control`), and a slot-0 finish
        runs the post-event queue drain (masked `_drain_queues` — a
        provable no-op on fused windows, whose predicate requires empty
        queues at finish DCs).  Slots >= 1 apply only when the selection
        proved the prefix commutes (``sel["fused_ok"]`` x per-slot
        validity), and are always plain finish/xfer/arrival kinds.

        One unrolled sub-step per slot — accrual over the exact
        inter-event gap (the same per-segment float accumulation order the
        singleton path produces), then the event's writes predicated on
        the slot's applied flag.  Slot interplay the singleton path
        resolves sequentially (a finish freeing the slab slot a later
        arrival takes) falls out of the in-order unroll.  Three
        structural economies keep the per-event op count low:

        * the in-order loop touches ONLY what later sub-steps read:
          status / units_done / spu / watts, busy, and the incrementally-
          maintained per-DC power vector (one event touches one DC, and
          `dc_sum`'s fixed-tree row sums make the single-row recompute
          bit-equal to the full `_dc_power`);
        * every other slab/DC/counter write lands after the loop as ONE
          K-element scatter (`mode="drop"` on inactive slots) — value-
          equal because a row is only re-read by its own event;
        * event-kind predicates (finish/xfer validity) depend only on the
          selection, so counter deltas, latency-window positions, and the
          K push requests are computed vectorized, outside the loop.
        """
        p, fleet = self.params, self.fleet
        K = self.K
        td = state.t.dtype
        J = p.job_cap
        iota_j = np.arange(J, dtype=np.int32)
        sl = sel["slots"]
        per_gpu_idle = jnp.where(self.power_gating, self.p_sleep, self.p_idle)
        end = jnp.asarray(p.duration, td)

        valid_v = sl["valid"]
        kind_v = sl["kind"]
        t_v = sl["t"]

        # ---- applied-slot masks: the window length L in [1, K] ----
        # Slot 0 is `_step`'s own next-event decode: it fires unless the
        # event lies beyond the horizon / is infinite / we were already
        # done (then this step is the singleton's end-clamped final
        # accrual + no-op).  Slots >= 1 fire only on a proven-commuting
        # prefix, which also implies slot 0 is a plain in-horizon event.
        past_end0 = (t_v[0] > end) | ~jnp.isfinite(t_v[0]) | state.done
        fire0 = ~past_end0
        done_new = state.done | past_end0
        app_v = jnp.concatenate([fire0[None],
                                 sel["fused_ok"] & valid_v[1:]])

        p_f_v = app_v & (kind_v == 0)
        p_x_v = app_v & (kind_v == 1)
        p_a_v = app_v & (kind_v == 2)
        log0 = fire0 & (kind_v[0] == 3)
        en_start_v = p_x_v & sl["x_can"]
        en_q_v = p_x_v & ~sl["x_can"]
        j_v = sl["j"]
        dc_j_v, jt_j_v = sl["dc_j"], sl["jt_j"]

        # arrival job ids: one split of the counter per applied arrival,
        # known before the loop
        jid0 = state.jid_counter
        n_arr_before = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(p_a_v.astype(jnp.int32))[:-1]])
        jid_v = jid0 + n_arr_before

        # ---- the in-order sub-step loop ----
        t_cur = state.t
        # entry power vector: doubles as `_step`'s log-tick powers_hint
        # (a down DC draws nothing — the up mask, None when faults off)
        powers0 = self._dc_power(state.jobs, state.dc.busy,
                                 self._up(state))
        powers = powers0
        busy = state.dc.busy
        energy = state.dc.energy_j
        util = state.dc.util_gpu_time
        jobs = state.jobs
        accrue0 = state.started_accrual & ~state.done
        if self.signals_on:
            cost_usd = state.signals.cost_usd
            carbon_g = state.signals.carbon_g
        if self.faults_on:
            downtime = state.fault.downtime
            dc_up0 = state.fault.dc_up  # window-constant (see select)
        # loop-independent per-slot selects, hoisted vectorized: one [K]
        # where tree + a scalar read per sub-step beats re-selecting
        # scalars inside the unroll (every eqn here is paid K times)
        bdelta_v = jnp.where(p_f_v, -sl["n_j"],
                             jnp.where(en_start_v, sl["x_n"], 0))
        t_k_l, slot_l, has_slot_l = [], [], []
        for k in range(K):
            v = app_v[k]
            j = j_v[k]
            p_f, p_x, p_a = p_f_v[k], p_x_v[k], p_a_v[k]
            en_start = en_start_v[k]
            dc_j = dc_j_v[k]
            size_k = sl["size_j"][k]

            # A finish's event time is RE-DERIVED from the sub-step-entry
            # state — the exact expression the singleton step's next-event
            # min evaluates over the advanced progress; xfer/arrival/log
            # times are STORED state, already exact in the selection.
            rem_j = jnp.maximum(0.0, size_k - jobs.units_done[j])
            t_fin_j = t_cur + fmul_pinned(rem_j, sl["spu_j"][k])
            if k == 0:
                # slot 0 advances the clock even without an event: this is
                # `_step`'s t_adv, end-clamped past the horizon (a slot-0
                # finish re-derives against the untouched entry state —
                # bit-equal to the selection's time by definition)
                t_k = jnp.where(past_end0, end,
                                jnp.where(p_f, jnp.asarray(t_fin_j, td),
                                          jnp.asarray(t_v[0], td)))
                gate = accrue0  # `_step`'s accrue: skip before first event
            else:
                t_k = jnp.where(p_f, jnp.asarray(t_fin_j, td),
                                jnp.where(v, t_v[k], t_cur))
                gate = v

            t_k_l.append(t_k)

            # accrual over (t_cur, t_k] (dt == 0 on unapplied slots, so
            # every accumulator sees an exact +0); pinned as in `_step`.
            # Progress advances UNgated by accrue0 like `_step`'s (dt is
            # the gate: it is 0 exactly when nothing may advance).
            runT = self._run_T(jobs)
            dt = jnp.maximum(0.0, t_k - t_cur)
            dt_f = jnp.asarray(dt, jnp.float32)
            e_inc = fmul_pinned(powers, dt)
            energy = energy + jnp.where(gate, e_inc, 0.0)
            util = util + jnp.where(gate, fmul_pinned(busy, dt), 0.0)
            if self.signals_on:
                # the cost/carbon integrals ride the same exact
                # inter-event gaps as the energy accrual, with the
                # price/CI sampled at the interval START (t_cur before
                # this sub-step advances it) — `_step`'s expressions
                # replayed per sub-step in the same association
                kwh_inc = jnp.asarray(e_inc, jnp.float32) / 3.6e6
                cost_usd = cost_usd + jnp.where(
                    gate,
                    fmul_pinned(kwh_inc, self.signals.price_at(t_cur)),
                    0.0)
                carbon_g = carbon_g + jnp.where(
                    gate,
                    fmul_pinned(kwh_inc, self.signals.carbon_at(t_cur)),
                    0.0)
            if self.faults_on:
                # downtime accrues over the same gaps, UNgated by accrue
                # like `_step`'s (dt is already 0 on unapplied slots)
                downtime = downtime + jnp.where(dc_up0, 0.0, dt)
            prog = jnp.where(jnp.isfinite(runT),
                             dt_f / jnp.where(jnp.isfinite(runT), runT, 1.0),
                             0.0)
            units = jnp.minimum(jobs.size, jobs.units_done + prog)
            t_cur = t_k

            # arrival slot placement (the one loop-dependent predicate)
            slot = jnp.argmax(jobs.status == JobStatus.EMPTY).astype(jnp.int32)
            has_slot = jobs.status[slot] == JobStatus.EMPTY
            slot_l.append(slot)
            has_slot_l.append(has_slot)
            en_pl = p_a & has_slot

            # the four fields later sub-steps read
            m_pl = (iota_j == slot) & en_pl
            mj = iota_j == j
            m_evt = mj & (p_f | p_x)
            m_start = mj & en_start
            # strong int32 status literals (weak Python ints chain to
            # int64 under jax_enable_x64 — weak-type-promotion)
            q_status = jnp.int32(JobStatus.EMPTY if self.ring
                                 else JobStatus.QUEUED)
            status_j = jnp.where(en_start, jnp.int32(JobStatus.RUNNING),
                                 jnp.where(p_f, jnp.int32(JobStatus.EMPTY),
                                           q_status))
            jobs = jobs.replace(
                status=jnp.where(m_pl, jnp.int32(JobStatus.XFER),
                                 jnp.where(m_evt, status_j, jobs.status)),
                units_done=jnp.where(m_pl, 0.0,
                                     jnp.where(mj & p_f, size_k, units)),
                spu=jnp.where(m_start, sl["x_spu"][k], jobs.spu),
                watts=jnp.where(m_start, sl["x_watts"][k], jobs.watts),
            )
            busy = jnp.maximum(0, busy.at[dc_j].add(bdelta_v[k]))

            # incremental power update: only the event DC's row changed
            if k < K - 1:
                prow = tree_sum_last(jnp.where(
                    (jobs.dc == dc_j) & (jobs.status == JobStatus.RUNNING),
                    jobs.watts, 0.0))
                idle_d = fmul_pinned(self.total_gpus[dc_j] - busy[dc_j],
                                     per_gpu_idle[dc_j])
                powers = powers.at[jnp.where(p_f | en_start, dc_j,
                                             jnp.int32(fleet.n_dc))].set(
                    prow + idle_d, mode="drop")

        t_k_v = jnp.stack(t_k_l)
        sojourn_v = jnp.maximum(0.0, t_k_v
                                - sl["t_start_j"]).astype(jnp.float32)
        slot_v = jnp.stack(slot_l)
        has_slot_v = jnp.stack(has_slot_l)
        en_pl_v = p_a_v & has_slot_v
        en_sp_v = p_a_v & ~has_slot_v

        if attrib_stop == "apply_loop":
            # the in-order sub-step unroll only: the loop-carried
            # accumulators and the four slab fields it owns stay live
            aux = {"t_k": t_k_v, "slot": slot_v, "sojourn": sojourn_v,
                   "busy": busy, "energy": energy, "util": util,
                   "powers": powers, "status": jobs.status,
                   "units": jobs.units_done, "spu": jobs.spu,
                   "watts": jobs.watts}
            if self.signals_on:
                aux.update(cost_usd=cost_usd, carbon_g=carbon_g)
            if self.faults_on:
                aux["downtime"] = downtime
            return state, aux, None, None

        # ---- the K-row WritePlan: every deferred slab-field write, the
        # ladder/acc refresh, the latency-window pushes, and the finish
        # counters feed the SAME shared commit the K=1 planner step uses
        # (`_commit_plan`; [K]-row layout = one scatter per field with
        # disabled rows dropped OOB — rows are distinct, or duplicate
        # with equal values — the rl_valid finish+reuse case — so update
        # order is irrelevant).  The in-order loop above owns the four
        # fields later sub-steps read (status/units_done/spu/watts) plus
        # the busy/energy/util accumulators; they are excluded from the
        # plan by the commit's K-row layout.
        t_k_td = t_k_v.astype(td)
        t_start_val = jnp.where(
            en_start_v & (sl["t_start_j"] > 0.0), sl["t_start_j"],
            jnp.where(en_start_v, t_k_td, jnp.zeros((K,), td)))
        tpt_val = jnp.where(
            en_start_v,
            sl["tpt_j"] + jnp.where(
                sl["preempt_t_j"] > 0.0,
                jnp.asarray(t_k_v - sl["preempt_t_j"], jnp.float32), 0.0),
            0.0)
        span_v = jnp.asarray(t_k_v % p.log_interval, jnp.float32)
        acc_v = span_v / sl["spu_j"]
        plan = dict(
            self._zero_plan(td),
            row=jnp.where(p_a_v, slot_v, j_v),
            place=en_pl_v, start=en_start_v, fin=p_f_v,
            jtype=sl["jt_arr"], ingress=sl["ing"], dc=sl["dc_arr"],
            seq=jid_v, size=sl["arr_size"],
            n=jnp.where(en_start_v, sl["x_n"], 0),
            f_idx=jnp.where(en_start_v, sl["x_f"], fleet.default_f_idx),
            t_ingress=t_k_td, t_avail=sl["arr_t_avail"],
            t_start=t_start_val, net_lat_s=sl["arr_net_lat"],
            preempt_t=jnp.zeros((K,), td),
            total_preempt_time=tpt_val,
            dc_row=dc_j_v, dcf=en_start_v, dcf_val=sl["x_newf"],
            acc_add=acc_v,
            fin_jt=jt_j_v, fin_size=sl["size_j"], sojourn=sojourn_v,
        )
        state = state.replace(dc=state.dc.replace(
            busy=busy, energy_j=energy, util_gpu_time=util))
        if self.signals_on:
            state = state.replace(signals=state.signals.replace(
                cost_usd=cost_usd, carbon_g=carbon_g))
        if self.faults_on:
            state = state.replace(fault=state.fault.replace(
                downtime=downtime))
        state = self._commit_plan(state.replace(jobs=jobs), plan)

        ing_rows_a = jnp.where(p_a_v, sl["ing"], jnp.int32(fleet.n_ing))
        state = state.replace(
            jid_counter=jid0 + jnp.sum(p_a_v, dtype=jnp.int32),
            next_arrival=state.next_arrival.at[
                ing_rows_a, sl["jt_arr"]].set(sl["arr_t_next"], mode="drop"),
            arr_count=state.arr_count.at[
                ing_rows_a, sl["jt_arr"]].add(1, mode="drop"),
            t=t_cur,
            # singleton parity: every fired event counts, the end-clamp /
            # post-done no-op does not (app_v[0] is exactly `_step`'s
            # ~done-after condition)
            n_events=state.n_events + jnp.sum(app_v, dtype=jnp.int32),
            done=done_new,
            started_accrual=jnp.bool_(True),
            t_first=jnp.where(state.started_accrual, state.t_first,
                              t_k_l[0]),
        )
        if not self.ring:
            state = state.replace(
                n_dropped=state.n_dropped + jnp.sum(en_sp_v,
                                                    dtype=jnp.int32))

        # key chain advances one split per applied event — and one split
        # on event-less steps (post-done no-ops / the end-clamp), exactly
        # the singleton sequence (`_step` splits unconditionally)
        kd_all = jax.random.key_data(jnp.stack([state.key]
                                               + list(sel["k_after"])))
        state = state.replace(key=jax.random.wrap_key_data(
            kd_all[jnp.maximum(1, jnp.sum(app_v, dtype=jnp.int32))]))

        if attrib_stop == "apply_commit":
            return state, {"t_k": t_k_v, "sojourn": sojourn_v}, None, None

        # ---- slot-0 singleton tails (masked; live only on L=1 windows) --
        # fault transition: `_handle_fault` itself, every write predicated
        # on fault0 (fault events fail `kind <= 2`, so they only ever
        # occupy a degenerate L=1 window's slot 0).  The emission row is
        # gathered at the pre-fire cursor, exactly `_step`'s.
        recovered0, dcx, fault_row = None, None, None
        if self.faults_on:
            fault0 = fire0 & (kind_v[0] == 4)
            fs0 = state.fault
            fault_row = jnp.stack([
                jnp.asarray(state.t, jnp.float32),
                fs0.kind[fs0.cursor].astype(jnp.float32),
                fs0.idx[fs0.cursor].astype(jnp.float32),
                fs0.value[fs0.cursor],
            ])
            state, recovered0, dcx = self._handle_fault(state, pred=fault0)
        # log tick: control + acc_job_unit + cluster row + next_log_t —
        # `_handle_log` itself, every write predicated on log0.  The
        # powers_hint is the entry power vector, exactly `_step`'s.
        state, cluster_rows = self._handle_log(state, powers_hint=powers0,
                                               pred=log0)
        # post-finish queue drain at the finish DC (or the slot-0 fault
        # recovery's re-admission drain).  On fused windows the
        # commutation predicate guarantees empty queues at every finish
        # DC, so the masked drain is a provable no-op there — it is the
        # real singleton drain only on degenerate L=1 finish steps.
        # Fault programs DEFER the drain to `_step_super` (the request
        # below): the K=1 fault-planner ordering it must reproduce runs
        # slab drains before the migration sweep and ring drains after
        # the pushes + sweep.
        if self.faults_on:
            drain_req = {"dcj": jnp.where(recovered0, dcx, dc_j_v[0]),
                         "enabled": p_f_v[0] | recovered0}
        else:
            drain_req = None
            state = self._drain_queues(state, dc_j_v[0], sel["k_ev0"],
                                       enabled=p_f_v[0], masked=True)

        # job-log rows: stable columns from the selection, finish_s /
        # latency_s patched from the re-derived event times
        col15 = np.arange(len(JOB_COLS))
        rows = jnp.where(col15[None, :] == 9,
                         t_k_v.astype(jnp.float32)[:, None],
                         jnp.where(col15[None, :] == 10, sojourn_v[:, None],
                                   sl["job_row"]))
        emission = {
            "t": jnp.asarray(state.t, jnp.float32),
            "cluster_valid": log0,
            "cluster": cluster_rows,
            "job_valid": p_f_v,
            "job": rows,
        }
        if self.faults_on:
            emission["fault_valid"] = fault0
            emission["fault"] = fault_row
        if self.ring:
            rec_a_v = jnp.where(np.arange(QRec.N_FIELDS)[None, :]
                                == QRec.SEQ,
                                jid_v.astype(td)[:, None], sl["rec_a"])
            push_stack = {
                "enabled": en_q_v | en_sp_v,
                "dcj": jnp.where(en_sp_v, sl["dc_arr"], dc_j_v),
                "jt": jnp.where(en_sp_v, sl["jt_arr"], jt_j_v),
                "rec": jnp.where(en_sp_v[:, None], rec_a_v, sl["rec_x"]),
            }
        else:
            zp = self._zero_push(td)
            push_stack = {key: jnp.stack([zp[key]] * K) for key in zp}
        if self.obs_on:
            # telemetry folds in at `_step_super` AFTER the deferred ring
            # pushes land (the conservation probe needs the closed step);
            # stash what only this scope knows under keys the caller pops
            emission["_obs_app"] = app_v
            emission["_obs_kind"] = kind_v
            emission["_obs_powers"] = powers0
            emission["_obs_log0"] = log0
        return state, emission, push_stack, drain_req

    def _step_super(self, state: SimState, policy_params, pre=None,
                    attrib_stop=None):
        """K-wide step: selection, then the ONE unified select-free body
        (`_superstep_apply` — no fused/singleton cond, round 7), then the
        <= K deferred ring pushes as one batched scatter, so
        `queues.recs` never rides a data-dependent select (note above
        `_zero_push`).  Fault programs (round 12) additionally run the
        per-iteration migration sweep and the deferred slot-0 drains
        here, in the K=1 fault-planner order: slab drains before the
        sweep, the ring drain after it — merged with the promoted
        migration drain into one masked call, as in the K=1 planner.
        ``policy_params`` is unused — the superstep is statically non-RL
        (`superstep_on`)."""
        del policy_params  # non-RL only (statically enforced)
        if attrib_stop == "head":
            # the K-wide event-min head only (see _superstep_select)
            return state, self._superstep_select(state, pre,
                                                 head_only=True)
        sel = self._superstep_select(state, pre)
        if attrib_stop == "select":
            # the full selection payload + commutation predicate; the
            # stacked slots keep the vmapped payload live under DCE
            return state, {"slots": sel["slots"],
                           "fused_ok": sel["fused_ok"], "m": sel["m"]}
        state, emission, pushes, dreq = self._superstep_apply(
            state, sel, pre, attrib_stop=attrib_stop)
        if attrib_stop in ("apply_loop", "apply_commit"):
            return state, emission  # the stop's aux dict (see apply)
        if attrib_stop == "apply":
            aux = dict(emission,
                       **{f"_push_{k}": v for k, v in pushes.items()})
            if dreq is not None:
                aux.update(_dreq_dcj=dreq["dcj"],
                           _dreq_enabled=dreq["enabled"])
            return state, aux
        if self.faults_on and not self.ring:
            state = self._drain_queues(state, dreq["dcj"], sel["k_ev0"],
                                       enabled=dreq["enabled"], masked=True)
        if self.ring:
            state = self._ring_push_many(state, pushes["dcj"], pushes["jt"],
                                         pushes["rec"], pushes["enabled"])
        if self.faults_on:
            # outage-preempted backlog drains toward surviving capacity —
            # fused windows are predicated on an EMPTY backlog, so the
            # once-per-iteration sweep is exactly the singleton's
            # once-per-event sweep on every window that can carry one
            state, mig_tgt, mig_fired = self._migrate_fault_preempted(state)
            promote = ~dreq["enabled"] & mig_fired
            if self.ring:
                # ring layout MERGES the deferred slot-0 drain with the
                # promoted migration drain, mirroring the K=1 fault
                # planner: promote requires ~dreq["enabled"], so at most
                # one target is live and ONE decide/start chain serves
                # both (two sequential masked drains cost a second chain)
                state = self._drain_queues(
                    state, jnp.where(promote, mig_tgt, dreq["dcj"]),
                    sel["k_ev0"], enabled=dreq["enabled"] | promote,
                    masked=True)
            else:
                state = self._drain_queues(state, mig_tgt, sel["k_ev0"],
                                           enabled=promote, masked=True)
        if attrib_stop == "drain":
            return state, emission
        if self.obs_on:
            app_v = emission.pop("_obs_app")
            kind_v = emission.pop("_obs_kind")
            powers0 = emission.pop("_obs_powers")
            log0 = emission.pop("_obs_log0")
            fired = jnp.sum(app_v, dtype=jnp.int32)
            kind_counts = jnp.sum(
                (kind_v[:, None] == jnp.arange(5)[None, :])
                & app_v[:, None], axis=0, dtype=jnp.int32)
            state, obs_row = self._obs_update(state, powers0, fired,
                                              kind_counts)
            emission["obs"] = obs_row
            emission["obs_valid"] = log0
        return state, emission

    def run_chunk(self, state: SimState, policy_params, n_steps: int):
        """Jitted ``n_steps``-event advance.  The pregen flag rides the jit
        cache key, so flipping ``self.arrival_pregen`` between calls picks
        the matching generator instead of silently reusing a stale one."""
        return self._run_chunk_jit(state, policy_params, n_steps,
                                   pregen=self.arrival_pregen)

    def _run_chunk(self, state: SimState, policy_params, n_steps: int,
                   pregen: Optional[bool] = None,
                   attrib_stop: Optional[str] = None):
        # With superstep_on, n_steps counts scan ITERATIONS, each advancing
        # up to superstep_k events (n_events tells the truth); a chunk still
        # consumes at most n_steps arrivals per stream (one per iteration),
        # so the pregen table sizing is unchanged.
        #
        # ``attrib_stop`` (analysis/attrib.py only) truncates the step body
        # at a named phase boundary: the scanned step traces exactly its
        # cumulative prefix up to that stop and returns the phase's live
        # outputs as the emission, so prefix programs nest and per-phase
        # eqn/time deltas telescope to the full step.  None (the default,
        # and the only value any production caller passes) compiles the
        # exact unablated program.
        if pregen is None:  # direct (unjitted) callers: trace-time attribute
            pregen = self.arrival_pregen
        pre = self._pregen_arrivals(state, n_steps, inversion=pregen)
        step = self._step_super if self.superstep_on else self._step

        def body(st, _):
            return step(st, policy_params, pre=pre, attrib_stop=attrib_stop)

        state, emissions = jax.lax.scan(body, state, None, length=n_steps)
        # chunk epilogue: commit the cumulative-fold carries the chunk
        # consumed (one gather per stream, zero step-body cost) so the
        # next chunk's pregen re-enters the unsplit fold bit-exactly
        state = self.workload.advance_carries(state, pre, inversion=pregen)
        return state, emissions

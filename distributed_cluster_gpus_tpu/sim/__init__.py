from .engine import Engine, init_state
from .io import CSVWriters, drain_emissions

__all__ = ["Engine", "init_state", "CSVWriters", "drain_emissions"]

"""Host-side emission drain: scan outputs -> the reference's two CSV schemas.

`cluster_log.csv` / `job_log.csv` columns and formatting match the reference
writers (`/root/reference/simcore/simulator_paper_multi.py:413-421, 814-823,
929-948`) so the plotting suite is drop-in compatible.  The engine streams
fixed-shape per-step records with validity flags; this module filters them on
the host and renders rows.
"""

from __future__ import annotations

import csv
import errno
import os
import queue
import threading
import time
from typing import Dict, Iterator, Optional

import numpy as np

from ..fault.state import FAULT_KIND_NAMES, FK_WAN
from ..models.structs import FleetSpec, SimParams, SimState
from .engine import (CLUSTER_COLS, Engine, FAULT_CLUSTER_COLS, JOB_COLS,
                     SIGNAL_CLUSTER_COLS, init_state)

CLUSTER_HEADER = [
    "time_s", "dc", "freq", "busy", "free", "run_total", "run_inf", "run_train",
    "q_inf", "q_train", "util_inst", "util_avg", "acc_job_unit", "power_W",
    "energy_kJ",
]
JOB_HEADER = [
    "jid", "ingress", "type", "size", "dc", "f_used", "n_gpus", "net_lat_s",
    "start_s", "finish_s", "latency_s", "preempt_count", "T_pred", "P_pred",
    "E_pred",
]
# fault_log.csv: one row per fired fault transition (fault-enabled runs)
FAULT_LOG_HEADER = ["time_s", "event", "target", "value"]


class CSVWriters:
    """cluster_log.csv + job_log.csv in ``out_dir`` (reference formatting).

    ``append=True`` keeps existing rows and only writes headers for files
    that don't exist yet — used when resuming from a checkpoint so the
    pre-crash log prefix isn't truncated.

    Row rendering goes through the native C++ writer (`native/csv_writer.cpp`,
    byte-identical printf formats) when the shared library builds; the Python
    csv path below is the fallback (and the oracle the byte-identity test in
    `tests/test_native_csv.py` compares against).  ``use_native=False`` forces
    the Python path.
    """

    def __init__(self, out_dir: str, fleet: FleetSpec, append: bool = False,
                 use_native: bool = True, fault_cols: bool = False,
                 signal_cols: bool = False):
        os.makedirs(out_dir, exist_ok=True)
        self.fleet = fleet
        self.fault_cols = fault_cols
        self.signal_cols = signal_cols
        self.cluster_path = os.path.join(out_dir, "cluster_log.csv")
        self.job_path = os.path.join(out_dir, "job_log.csv")
        self.fault_path = (os.path.join(out_dir, "fault_log.csv")
                           if fault_cols else None)
        self._lib = None
        # the native writer's cluster printf layout is the 14-column base
        # schema; fault- and signal-extended runs (base + FAULT_CLUSTER_COLS
        # / SIGNAL_CLUSTER_COLS) take the Python path for the cluster file
        # (job rows are unchanged)
        if use_native:
            from ..utils.native import csv_writer_lib

            self._lib = csv_writer_lib()
        self._dc_blob = "\n".join(fleet.dc_names).encode()
        self._ing_blob = "\n".join(fleet.ingress_names).encode()
        cluster_header = (CLUSTER_HEADER
                          + (list(FAULT_CLUSTER_COLS) if fault_cols else [])
                          + (list(SIGNAL_CLUSTER_COLS) if signal_cols
                             else []))
        targets = [(self.cluster_path, cluster_header),
                   (self.job_path, JOB_HEADER)]
        if self.fault_path:
            targets.append((self.fault_path, FAULT_LOG_HEADER))
        for path, header in targets:
            if append and os.path.exists(path):
                continue
            with open(path, "w", newline="") as f:
                csv.writer(f).writerow(header)

    # -- crash-consistent resume support ------------------------------------
    #
    # Byte offsets after the last drained chunk act as a watermark: a resumed
    # run truncates both files back to the offsets recorded in the checkpoint,
    # dropping any rows a crashed run appended past its last checkpoint (those
    # chunks re-run and would otherwise appear twice).

    def offsets(self) -> Dict[str, int]:
        out = {"cluster": os.path.getsize(self.cluster_path),
               "job": os.path.getsize(self.job_path)}
        if self.fault_path:
            out["fault"] = os.path.getsize(self.fault_path)
        return out

    def truncate_to(self, offsets: Dict[str, int]) -> None:
        pairs = [(self.cluster_path, "cluster"), (self.job_path, "job")]
        if self.fault_path and "fault" in offsets:
            pairs.append((self.fault_path, "fault"))
        for path, key in pairs:
            size = os.path.getsize(path)
            want = int(offsets[key])
            if 0 < want < size:
                os.truncate(path, want)

    def _cluster_row(self, w, row: np.ndarray, name: str):
        cols = (CLUSTER_COLS
                + (FAULT_CLUSTER_COLS if self.fault_cols else ())
                + (SIGNAL_CLUSTER_COLS if self.signal_cols else ()))
        c = dict(zip(cols, row))
        out = [
            f"{c['time_s']:.3f}", name, f"{c['freq']:.2f}",
            int(c["busy"]), int(c["free"]), int(c["run_total"]),
            int(c["run_inf"]), int(c["run_train"]),
            int(c["q_inf"]), int(c["q_train"]),
            f"{c['util_inst']:.4f}", f"{c['util_avg']:.4f}",
            f"{c['acc_job_unit']:.4f}",
            f"{c['power_W']:.2f}", f"{c['energy_kJ']:.4f}",
        ]
        if self.fault_cols:
            out += [int(c["up"]), f"{c['derate_f']:.2f}"]
        if self.signal_cols:
            out += [f"{c['price_usd_kwh']:.4f}", f"{c['carbon_g_kwh']:.2f}"]
        w.writerow(out)

    def _fault_target(self, kind: int, idx: int) -> str:
        if kind == FK_WAN:
            n_dc = len(self.fleet.dc_names)
            return (f"{self.fleet.ingress_names[idx // n_dc]}"
                    f"->{self.fleet.dc_names[idx % n_dc]}")
        return self.fleet.dc_names[idx]

    def write_fault_chunk(self, faults: np.ndarray, idxs) -> None:
        """Append the chunk's fired fault transitions to fault_log.csv."""
        with open(self.fault_path, "a", newline="") as f:
            w = csv.writer(f)
            for i in idxs:
                t, kind, idx, val = faults[i]
                kind, idx = int(kind), int(idx)
                w.writerow([
                    f"{t:.3f}", FAULT_KIND_NAMES.get(kind, str(kind)),
                    self._fault_target(kind, idx), f"{float(val):.4f}",
                ])

    def _job_row(self, w, row: np.ndarray):
        c = dict(zip(JOB_COLS, row))
        jtype = "inference" if int(c["type"]) == 0 else "training"
        w.writerow([
            int(c["jid"]),
            self.fleet.ingress_names[int(c["ingress"])],
            jtype, f"{c['size']:.4f}",
            self.fleet.dc_names[int(c["dc"])],
            f"{c['f_used']:.3f}", int(c["n_gpus"]),
            f"{c['net_lat_s']:.4f}",
            f"{c['start_s']:.6f}", f"{c['finish_s']:.6f}",
            f"{c['latency_s']:.6f}", int(c["preempt_count"]),
            f"{c['T_pred']:.6f}", f"{c['P_pred']:.2f}", f"{c['E_pred']:.2f}",
        ])

    def write_cluster_chunk(self, cluster: np.ndarray, idxs) -> None:
        """Append all valid log ticks of one chunk under a single open."""
        if self._lib is not None and not self.fault_cols \
                and not self.signal_cols:
            import ctypes

            rows = np.ascontiguousarray(cluster[np.asarray(idxs)], np.float32)
            n = self._lib.write_cluster_rows(
                self.cluster_path.encode(),
                rows.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                rows.shape[0], rows.shape[1], self._dc_blob)
            if n >= 0:
                return
        with open(self.cluster_path, "a", newline="") as f:
            w = csv.writer(f)
            for i in idxs:
                for d, name in enumerate(self.fleet.dc_names):
                    self._cluster_row(w, cluster[i, d], name)

    def write_job_chunk(self, jobs: np.ndarray, idxs) -> None:
        """Append all valid job rows of one chunk under a single open."""
        if self._lib is not None:
            import ctypes

            rows = np.ascontiguousarray(jobs[np.asarray(idxs)], np.float32)
            n = self._lib.write_job_rows(
                self.job_path.encode(),
                rows.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                rows.shape[0], self._ing_blob, self._dc_blob)
            if n >= 0:
                return
        with open(self.job_path, "a", newline="") as f:
            w = csv.writer(f)
            for i in idxs:
                self._job_row(w, jobs[i])


def drain_emissions(emissions: Dict, writers: Optional[CSVWriters]) -> Dict[str, int]:
    """Filter one chunk of stacked per-step emissions; write valid rows.

    Returns counters {"cluster_rows": ..., "job_rows": ...}.  ``emissions``
    leaves have a leading [n_steps] axis.  Superstep runs
    (``SimParams.superstep_k > 1``) widen the job emission to one K-slot
    slab per step ([n_steps, K] flags over [n_steps, K, cols] rows);
    flattening the two leading axes row-major restores the exact
    chronological order the singleton stream emits (in-window slots are
    time-ordered, windows don't overlap).

    Device emissions land on the host through ONE batched
    ``jax.device_get`` of the whole pytree (round 7) — the per-field
    ``np.asarray`` calls each paid their own device round-trip.  Host
    arrays pass through untouched, so the pipelined ``run_simulation``
    loop (which fetches before handing off to the background writer) pays
    no second transfer.
    """
    first = emissions.get("cluster_valid")
    if first is not None and not isinstance(first, np.ndarray):
        import jax

        emissions = jax.device_get(emissions)
    cl_valid = np.asarray(emissions["cluster_valid"])
    job_valid = np.asarray(emissions["job_valid"])
    job_arr = emissions["job"]
    if job_valid.ndim == 2:  # superstep-widened [n_steps, K] slabs
        job_valid = job_valid.reshape(-1)
        job_arr = np.asarray(job_arr).reshape(-1, np.shape(job_arr)[-1])
    fault_valid = (np.asarray(emissions["fault_valid"])
                   if "fault_valid" in emissions else np.zeros(0, bool))
    stats = {"cluster_rows": 0, "job_rows": 0, "fault_rows": 0}
    if writers is None:
        stats["cluster_rows"] = int(cl_valid.sum())
        stats["job_rows"] = int(job_valid.sum())
        stats["fault_rows"] = int(fault_valid.sum())
        return stats
    cl_idx = np.nonzero(cl_valid)[0]
    job_idx = np.nonzero(job_valid)[0]
    fault_idx = np.nonzero(fault_valid)[0]
    if len(cl_idx):
        writers.write_cluster_chunk(np.asarray(emissions["cluster"]), cl_idx)
    if len(job_idx):
        writers.write_job_chunk(np.asarray(job_arr), job_idx)
    if len(fault_idx) and writers.fault_path:
        writers.write_fault_chunk(np.asarray(emissions["fault"]), fault_idx)
    stats["cluster_rows"] = len(cl_idx)
    stats["job_rows"] = len(job_idx)
    stats["fault_rows"] = len(fault_idx)
    return stats


class AsyncLineDrain:
    """Bounded background renderer: line-oriented output off the hot loop.

    One worker thread consumes host-side items FIFO (so output order —
    and therefore byte-identity with a serial drain — is preserved) and
    runs ``drain_fn(item)`` for each.  The queue is bounded
    (``maxsize``): if the producer outruns the disk, the submitting loop
    blocks instead of buffering unboundedly.  Worker exceptions are
    re-raised on the next :meth:`submit` or on :meth:`close` — a failed
    write must not silently truncate output.

    ``render_seconds`` accumulates the worker's wall time, the part of
    host io the pipelined ``run_simulation`` hides behind device compute
    (reported by bench.py's overlap probe).  ``rows`` accumulates
    whatever counter dict ``drain_fn`` returns.

    Transient writer IO errors — EINTR (a signal landed mid-write, e.g.
    the graceful-shutdown SIGTERM) and EAGAIN/EWOULDBLOCK (a saturated
    pipe/NFS mount) — are retried ``io_retries`` times with exponential
    backoff before propagating; anything else (ENOSPC, EIO, render
    bugs) propagates immediately.  A retried chunk re-runs ``drain_fn``
    from the top, so after a PARTIAL write the retry may duplicate the
    interrupted row — acceptable for append-only logs whose alternative
    is losing the whole chunk, and the errno set is chosen so only
    call-was-interrupted cases retry.

    Subclasses/instances: :class:`AsyncCSVDrain` (the reference CSV
    logs) and the obs exporters' sink (`obs.export.ObsSink`) — one
    background-writer implementation, two renderers.
    """

    #: errnos worth retrying: the syscall was interrupted, not refused
    TRANSIENT_ERRNOS = frozenset(
        {errno.EINTR, errno.EAGAIN, errno.EWOULDBLOCK})

    def __init__(self, drain_fn, maxsize: int = 4, name: str = "line drain",
                 io_retries: int = 3, io_backoff_s: float = 0.05):
        self._drain_fn = drain_fn
        self._name = name
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._err: Optional[BaseException] = None
        self._abort = False
        self._io_retries = max(0, io_retries)
        self._io_backoff_s = io_backoff_s
        self.io_retry_count = 0  # total transient-error retries performed
        self.render_seconds = 0.0
        self.rows: Dict[str, int] = {}
        self._worker = threading.Thread(
            target=self._run, daemon=True,
            name=name.replace(" ", "-"))
        self._worker.start()

    def _render_with_retry(self, em):
        for attempt in range(self._io_retries + 1):
            try:
                return self._drain_fn(em)
            except OSError as e:
                if (e.errno not in self.TRANSIENT_ERRNOS
                        or attempt == self._io_retries):
                    raise
                self.io_retry_count += 1
                time.sleep(self._io_backoff_s * (2 ** attempt))

    def _run(self):
        while True:
            em = self._q.get()
            if em is None:
                # account the sentinel too: a flush() AFTER close() must
                # return instead of joining a queue that can never drain
                # (the abort paths flush-then-checkpoint in that order)
                self._q.task_done()
                return
            t0 = time.perf_counter()
            try:
                if self._err is None and not self._abort:
                    stats = self._render_with_retry(em)
                    for k, v in (stats or {}).items():
                        self.rows[k] = self.rows.get(k, 0) + v
            except BaseException as e:  # noqa: BLE001 - forwarded to the host loop
                self._err = e
            finally:
                self.render_seconds += time.perf_counter() - t0
                self._q.task_done()

    def _check(self):
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError(f"background {self._name} failed") from err

    def submit(self, item) -> None:
        """Enqueue one HOST-side item (already device_get where relevant)."""
        self._check()
        self._q.put(item)

    def flush(self) -> None:
        """Block until every submitted item has been rendered.

        Checkpoint support: a byte watermark read while chunks are still
        queued would under-count rows the worker writes moments later —
        and a resumed run re-runs from the checkpoint, so those rows
        would then appear twice.  Deferred worker errors surface here,
        same as :meth:`submit`."""
        self._q.join()
        self._check()

    def close(self, abort: bool = False) -> None:
        """Flush the queue, stop the worker, re-raise any deferred error.

        ``abort=True`` (the caller is already unwinding an exception):
        queued chunks are DROPPED instead of rendered — no multi-chunk
        flush delaying Ctrl-C — and any deferred worker error is
        swallowed so it cannot replace the in-flight exception (the run
        is failing anyway; partially-written output is expected then)."""
        if abort:
            self._abort = True
        self._q.put(None)
        self._worker.join()
        if not abort:
            self._check()


class AsyncCSVDrain(AsyncLineDrain):
    """`AsyncLineDrain` rendering emission chunks into the reference CSVs.

    Thin subclass: holds the :class:`CSVWriters` and defaults
    ``drain_fn`` to :func:`drain_emissions` (the legacy
    ``drain_fn(emissions, writers)`` signature is preserved for tests
    and external callers).  Error-propagation and abort-path semantics
    are the base class's, re-tested in tests/test_io_pipeline.py.
    """

    def __init__(self, writers: Optional[CSVWriters], maxsize: int = 4,
                 drain_fn=None):
        self.writers = writers
        fn = drain_fn or drain_emissions
        super().__init__(lambda em: fn(em, writers), maxsize=maxsize,
                         name="CSV drain")
        self.rows = {"cluster_rows": 0, "job_rows": 0, "fault_rows": 0}


def run_simulation(
    fleet: FleetSpec,
    params: SimParams,
    out_dir: Optional[str] = None,
    chunk_steps: int = 4096,
    max_chunks: int = 10_000,
    policy_apply=None,
    policy_params=None,
    on_chunk=None,
    progress: bool = False,
    timer=None,
    obs=None,
    shutdown=None,
    state0: Optional[SimState] = None,
) -> SimState:
    """Host loop: scan chunks until the simulation clock passes end_time.

    Pipelined (round 7): chunk N+1 is dispatched BEFORE chunk N's
    emissions are fetched, the fetch is one batched ``jax.device_get``
    that overlaps chunk N+1's device compute, and CSV rendering runs on
    a bounded background writer (:class:`AsyncCSVDrain`) — so per chunk
    the wall time is ~max(device rollout, host render) instead of their
    sum, and the only device sync left is the end-of-chunk ``done`` read
    the dispatch order already requires.  The emission stream and final
    state are exactly the serial loop's (same chunks, same order; the
    writer is FIFO), so CSV bytes are unchanged.

    ``on_chunk(state, emissions)`` is an optional hook (used by the RL
    trainer to ingest transitions between chunks and by tests to inspect
    streams).  A hook's return value feeds the NEXT chunk's dispatch — a
    true dependency — so hooked runs keep the legacy serial order and
    produce identical training trajectories by construction.

    ``progress`` prints a simulated-time bar per chunk and a wall-time
    phase breakdown at exit (the reference's tqdm readout,
    `simulator_paper_multi.py:136-151`).  ``timer`` accepts an external
    :class:`~..obs.trace.PhaseTimer` so callers (bench.py's
    overlap probe, the --obs-trace chrome-trace export) can read the
    phase split: "dispatch" (enqueue), "rollout" (waiting on device
    compute), "io" (fetch + handoff, the only io on the critical path)
    and "io_render" (the worker's hidden render time).

    ``obs`` is an optional :class:`~..obs.export.ObsConfig`: the
    telemetry rows the obs-enabled engine emits drain through this same
    pipelined path (one shared ``jax.device_get`` with the CSV chunk,
    rendering on the exporters' own background worker) into a
    Prometheus snapshot, a JSONL stream, and ``run_summary.json``, and
    the run-health watchdog checks the violation counters once per
    chunk.  Requires ``params.obs_enabled`` (ObsSink raises otherwise).

    ``shutdown`` accepts a :class:`~..utils.shutdown.ShutdownFlag`
    (armed by ``utils.shutdown.graceful_shutdown``): when a SIGTERM/
    SIGINT latches it, the loop stops at the next chunk boundary,
    flushes every drained chunk to disk, and stamps ``run_summary.json``
    with ``status="interrupted"`` — so a preempted run's artifacts are
    complete up to the last finished chunk.

    A run-health abort (any ``RunAbort``: a watchdog trip in
    mode="raise", or a divergence probe raised from ``on_chunk``) takes
    the same flush path with ``status="aborted"`` before re-raising:
    the rows rolled out before the trip are the post-mortem and must
    not be stranded in the writer queues.  Any OTHER exception still
    takes the fast abort path (queues dropped).

    ``state0`` replaces the freshly initialized SimState (tests inject
    corrupted states to exercise the probe battery through the real
    host loop; it must match the (fleet, params) shapes).
    Returns the final SimState.
    """
    import jax

    from ..obs.health import RunAbort
    from ..obs.trace import PhaseTimer, sim_progress

    engine = Engine(fleet, params, policy_apply=policy_apply)
    key = jax.random.key(params.seed)
    state = (state0 if state0 is not None
             else init_state(key, fleet, params, workload=engine.workload))
    writers = (CSVWriters(out_dir, fleet, fault_cols=engine.faults_on,
                          signal_cols=engine.signals_on)
               if out_dir else None)
    timer = PhaseTimer() if timer is None else timer
    sink = None
    if obs is not None:
        from ..obs.export import ObsSink

        sink = ObsSink.open(obs, fleet=fleet, params=params, state=state)

    def interrupted() -> bool:
        return shutdown is not None and shutdown.requested

    def host_phases(csv_render_s=None):
        # first-class wall-time attribution for run_summary.json: the
        # timer's dispatch/rollout/io totals plus the background
        # workers' hidden render seconds (obs render is folded in by
        # ObsSink.finalize itself — its worker closes there)
        from ..obs.export import host_phase_seconds

        return host_phase_seconds(timer, csv_render_s=csv_render_s)

    def write_status(status: str, csv_render_s=None) -> None:
        # the no-sink counterpart of finalize(status=...): shutdown and
        # abort must leave a machine-readable status even without --obs
        if sink is None and out_dir:
            from ..obs.export import write_status_summary

            write_status_summary(out_dir, algo=params.algo, fleet=fleet,
                                 state=state, status=status,
                                 host_phases=host_phases(csv_render_s))

    if on_chunk is not None:
        # serial loop: the hook's updated policy_params feed the next
        # dispatch (RL-in-loop), so chunks cannot be dispatched ahead
        status = "completed"
        try:
            for _ in range(max_chunks):
                with timer.phase("rollout", fence=lambda: state.t):
                    state, emissions = engine.run_chunk(state, policy_params,
                                                        n_steps=chunk_steps)
                with timer.phase("io"):
                    if sink is not None:
                        emissions = jax.device_get(emissions)
                        sink.submit_host(emissions)
                    drain_emissions(emissions, writers)
                if sink is not None:
                    sink.check(np.asarray(state.telemetry.viol))
                policy_params = on_chunk(state, emissions) or policy_params
                if progress:
                    print(sim_progress(float(state.t), params.duration,
                                       extra=f"events={int(state.n_events)}"))
                if bool(state.done):
                    break
                if interrupted():
                    status = "interrupted"
                    break
        except RunAbort:
            # deliberate abort (watchdog trip or a divergence probe in
            # the on_chunk hook): everything drained so far is already
            # on its way to disk (this loop drains synchronously) —
            # flush the exporter worker and stamp the summary, re-raise.
            # A flush failure (e.g. a deferred exporter write error)
            # must not mask the abort itself.
            try:
                if sink is not None:
                    sink.finalize(state, status="aborted",
                                  host_phases=host_phases())
                elif out_dir:
                    write_status("aborted")
            except Exception:  # noqa: BLE001 - post-mortem best effort
                if sink is not None:
                    sink.close(abort=True)
            raise
        except BaseException:
            if sink is not None:
                sink.close(abort=True)
            raise
        if sink is not None:
            sink.finalize(state, status=status,
                          host_phases=host_phases())
        else:
            if status != "completed":
                write_status(status)
        if progress:
            print(timer.summary())
        return state

    drainer = AsyncCSVDrain(writers)
    prev_em = None
    status = "completed"

    def flush_tail():
        """Drain the final in-flight chunk through the shared fetch."""
        if prev_em is not None:
            with timer.phase("io"):
                host_em = jax.device_get(prev_em)
                drainer.submit(host_em)
                if sink is not None:
                    sink.submit_host(host_em)

    try:
        for _ in range(max_chunks):
            with timer.phase("dispatch"):
                state, emissions = engine.run_chunk(state, policy_params,
                                                    n_steps=chunk_steps)
            # reference the done (and watchdog) leaves NOW: the next
            # dispatch donates the state's buffers, after which they
            # could not be read back
            done_dev = state.done
            viol_dev = state.telemetry.viol if sink is not None else None
            if prev_em is not None:
                with timer.phase("io"):
                    host_em = jax.device_get(prev_em)
                    drainer.submit(host_em)
                    if sink is not None:
                        sink.submit_host(host_em)
            prev_em = emissions
            # blocks until the in-flight chunk completes — the previous
            # chunk's fetch + render already overlapped that compute, so
            # this wait IS the device rollout time, not added host time
            with timer.phase("rollout"):
                done = bool(done_dev)
            if sink is not None:
                # watchdog on the chunk just completed (mode="raise"
                # stops the run at the chunk boundary that tripped)
                sink.check(np.asarray(viol_dev))
            if progress:
                print(sim_progress(float(state.t), params.duration,
                                   extra=f"events={int(state.n_events)}"))
            if done:
                break
            if interrupted():
                status = "interrupted"
                break
        flush_tail()
    except RunAbort:
        # deliberate abort: flush the chunk(s) already rolled out — the
        # pre-trip stream is the post-mortem — then stamp and re-raise.
        # A flush failure must not mask the abort itself.
        try:
            flush_tail()
            drainer.close()
            if sink is not None:
                sink.finalize(state, status="aborted",
                              host_phases=host_phases(
                                  drainer.render_seconds))
            else:
                write_status("aborted", drainer.render_seconds)
        except Exception:  # noqa: BLE001 - post-mortem flush best effort
            drainer.close(abort=True)
            if sink is not None:
                sink.close(abort=True)
        raise
    except BaseException:
        # already unwinding (dispatch failure, Ctrl-C): stop the writers
        # fast — drop their queues, and do NOT let a deferred writer
        # error replace the in-flight exception
        drainer.close(abort=True)
        if sink is not None:
            sink.close(abort=True)
        raise
    else:
        drainer.close()
        if sink is not None:
            sink.finalize(state, status=status,
                          host_phases=host_phases(
                              drainer.render_seconds))
        elif status != "completed":
            write_status(status, drainer.render_seconds)
    finally:
        # through add_span (not raw totals) so a span-recording timer
        # (--obs-trace) shows the worker's hidden render time in the
        # chrome trace too
        timer.add_span("io_render", drainer.render_seconds)
    if progress:
        print(timer.summary())
    return state

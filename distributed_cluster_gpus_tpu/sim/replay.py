"""Deterministic forensic replay of aborted runs (docs/checkpointing.md).

When a run-health abort fires (watchdog invariant trip in mode="raise",
or a campaign divergence probe), the trainer loops already save a
forensic checkpoint under ``ckpt_dir/aborted`` — since round 12 that
bundle also carries an ``abort_context.json`` (tripping probe, chunk
index, chaos stage/reseed, params fingerprint, trainer schedule).  The
whole pipeline is a pure function of (checkpointed state, seed), so the
bundle is a *self-contained repro*:

* :func:`replay_abort` restores the newest VERIFIED healthy checkpoint
  strictly before the tripping chunk (the fallback chain skips corrupt
  ones), re-executes forward to the failing chunk, asserts the SAME
  probe trips at the SAME chunk, and byte-compares the re-executed
  post-chunk state against the forensic snapshot;
* the bisection then shrinks the failing chunk to the minimal scan-step
  window that still trips — every abort becomes a minimized repro an
  engine bug can be debugged from;
* :func:`replay_run` re-executes a healthy run from a mid-run
  checkpoint into a fresh workspace and reproduces the original CSV
  bytes (chunk-invariance + the byte-watermark resume make this exact).

CLI: ``scripts/replay_abort.py BUNDLE_DIR [flags]``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.checkpoint import (config_fingerprint, from_host_tree,
                                restore_latest, step_dirname, steps,
                                to_host_tree)
from ..utils.jsonio import dump_json_atomic

ABORT_CONTEXT_FILE = "abort_context.json"
ABORT_CONTEXT_SCHEMA = "dcg.abort_context.v1"
REPLAY_REPORT_SCHEMA = "dcg.replay_report.v1"


class ReplayError(RuntimeError):
    """The bundle cannot be replayed (missing context, wrong world, or
    the recorded trip did not reproduce)."""


def write_abort_context(bundle_dir: str, *, error, chunk: int,
                        chunk_steps: int, fleet, params,
                        trees: List[str],
                        train: Optional[Dict] = None) -> str:
    """Serialize everything a replay needs next to the forensic checkpoint.

    ``error`` is the tripping :class:`~..obs.health.RunAbort`; its probe
    attributes (``probes`` on WatchdogError, ``probe``/``config`` on
    DivergenceError) land in the context so the replay can assert the
    identical trip, with identical thresholds, reproduces."""
    from ..obs.health import DivergenceError

    os.makedirs(bundle_dir, exist_ok=True)
    kind = "divergence" if isinstance(error, DivergenceError) else "watchdog"
    probes = list(getattr(error, "probes", ()) or ())
    single = getattr(error, "probe", None)
    if single:
        probes = [single]
    cfg = getattr(error, "config", None)
    cur = params.faults.curriculum if params.faults is not None else None
    doc = {
        "schema": ABORT_CONTEXT_SCHEMA,
        "kind": kind,
        "reason": str(error),
        "probes": probes,
        "chunk": int(chunk),
        "chunk_steps": int(chunk_steps),
        "algo": params.algo,
        "seed": int(params.seed),
        "params_fingerprint": config_fingerprint(fleet, params),
        "chaos": ({"name": cur.name, "stage": int(cur.stage),
                   "reseed": int(cur.reseed)} if cur is not None else None),
        "workload": (params.workload.name
                     if params.workload is not None else None),
        "trees": list(trees),
        "train": train,
        "divergence": (dataclasses.asdict(cfg) if cfg is not None else None),
    }
    path = os.path.join(bundle_dir, ABORT_CONTEXT_FILE)
    dump_json_atomic(path, doc)
    return path


def load_abort_context(bundle_dir: str) -> Dict:
    path = os.path.join(bundle_dir, ABORT_CONTEXT_FILE)
    if not os.path.exists(path):
        raise ReplayError(
            f"{bundle_dir}: no {ABORT_CONTEXT_FILE} — not a forensic abort "
            "bundle (pre-round-12 aborts saved only the checkpoint)")
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != ABORT_CONTEXT_SCHEMA:
        raise ReplayError(
            f"{bundle_dir}: unknown abort-context schema "
            f"{doc.get('schema')!r}")
    return doc


# ---------------------------------------------------------------------------
# trip probes (one per abort kind)
# ---------------------------------------------------------------------------

def _hard_probe_names(viol_before, viol_after) -> List[str]:
    from ..obs.health import HARD_PROBES, PROBE_NAMES

    new = (np.asarray(viol_after, np.int64).reshape(-1)
           - np.asarray(viol_before, np.int64).reshape(-1))
    return [PROBE_NAMES[i] for i in HARD_PROBES if new[i] > 0]


def _divergence_monitor(ctx):
    from ..rl.campaign import DivergenceConfig, DivergenceMonitor

    cfg = ctx.get("divergence")
    if cfg is None:
        return DivergenceMonitor()
    cfg = dict(cfg)
    if "probe_metrics" in cfg:
        cfg["probe_metrics"] = tuple(cfg["probe_metrics"])
    return DivergenceMonitor(DivergenceConfig(**cfg))


class _World:
    """The minimal trainer-loop mirror the replay drives.

    Re-implements exactly the per-chunk order of ``rl.train.train_chsac``
    (rollout -> [watchdog read] -> ingest -> fused train -> divergence
    check) and of the non-RL ``run_simulation`` loop (rollout -> watchdog
    read), with no writers/exporters — the replay only needs state."""

    def __init__(self, fleet, params, ctx):
        import jax

        from .engine import Engine, init_state

        self.trainer = ctx.get("train") is not None
        self.ctx = ctx
        self.params = params
        if self.trainer:
            if params.algo != "chsac_af":
                raise ReplayError(
                    "trainer abort bundle but params.algo != chsac_af")
            from ..rl.train import make_agent

            self.agent = make_agent(fleet, params)
            self.engine = Engine(fleet, params,
                                 policy_apply=self.agent.policy_apply)
        else:
            self.agent = None
            self.engine = Engine(fleet, params)
        self.state = init_state(jax.random.key(params.seed), fleet, params,
                                workload=self.engine.workload)
        # donation-proof template for snapshot rehydration (leaf KINDS
        # only — deleted buffers are fine)
        self._template = self._tree()

    def _tree(self):
        t = {"sim": self.state}
        if self.trainer:
            t.update(sac=self.agent.sac, replay=self.agent.replay,
                     key=self.agent.key)
        return t

    def _like_for(self, names):
        """Typed restore templates for the checkpoint trees ``names``
        (the saved layout must restore against matching structures)."""
        from ..rl.train import _wm_like

        m = {"sim": self.state, "csv": _wm_like(self.params)}
        if self.trainer:
            m.update(sac=self.agent.sac, replay=self.agent.replay,
                     key=self.agent.key)
        unsupported = [n for n in names if n not in m]
        if unsupported:
            raise ReplayError(
                f"unsupported checkpoint trees {unsupported}: replay "
                "drives the single-learner chsac trainer and engine-only "
                "bundles (mesh-sharded 'states' bundles are forensic "
                "evidence, not replayable here)")
        return {n: m[n] for n in names}

    def restore_healthy(self, ckpt_root: str, max_step: int):
        """Newest verified step <= max_step (or None: fresh init)."""
        like = self._like_for(self.ctx["trees"])
        try:
            step, out = restore_latest(ckpt_root, like=like,
                                       max_step=max_step)
        except FileNotFoundError:
            return None
        self.state = out["sim"]
        if self.trainer:
            self.agent.sac = out["sac"]
            self.agent.replay = out["replay"]
            self.agent.key = out["key"]
        return step

    def snapshot(self):
        return to_host_tree(self._tree())

    def rehydrate(self, snap):
        t = from_host_tree(self._template, snap)
        self.state = t["sim"]
        if self.trainer:
            self.agent.sac = t["sac"]
            self.agent.replay = t["replay"]
            self.agent.key = t["key"]

    def viol(self):
        if self.state.telemetry is None:
            raise ReplayError(
                "watchdog replay needs params.obs_enabled=True (the probe "
                "counters live in TelemetryState) — the aborted run had it")
        return np.asarray(self.state.telemetry.viol).copy()

    def run_chunk(self, n_steps: int, train: bool = True):
        """One mirrored chunk; returns the chunk's training metrics (or
        None).  ``train=False`` stops after the rollout — the watchdog
        abort fires before ingest/train, so its reproduce/bisect paths
        must not advance the learner past what the original run did."""
        self.state, emissions = self.engine.run_chunk(
            self.state, self.agent.sac if self.trainer else None,
            n_steps=n_steps)
        if not (self.trainer and train):
            return None
        tr = self.ctx["train"]
        n_new = int(np.asarray(emissions["rl"]["valid"]).sum())
        self.agent.ingest_chunk(emissions["rl"])
        n_want = min(n_new // max(int(tr["train_every_n"]), 1),
                     int(tr["max_train_steps_per_chunk"]))
        if not n_want:
            return None
        metrics, _ = self.agent.train_steps(
            n_want, int(tr["max_train_steps_per_chunk"]))
        return metrics


def _tree_mismatches(a, b) -> List[str]:
    """Key-paths of bitwise-differing leaves (PRNG keys via key_data,
    NaNs equal) — the same comparison rule as the golden suites'."""
    import jax

    bad = []

    def eq(path, x, y):
        x, y = np.asarray(x), np.asarray(y)
        if not np.array_equal(x, y, equal_nan=True):
            bad.append(jax.tree_util.keystr(path))

    jax.tree_util.tree_map_with_path(eq, to_host_tree(a), to_host_tree(b))
    return bad


def replay_abort(fleet, params, bundle_dir: str, *, bisect: bool = True,
                 check_state: bool = True, force: bool = False,
                 verbose: bool = False) -> Dict:
    """Re-execute the failing chunk of a forensic abort bundle.

    ``fleet``/``params`` must be the aborted run's (the context's params
    fingerprint is checked; ``force=True`` downgrades a mismatch to a
    warning for post-hoc what-if replays).  Returns a replay report dict;
    raises :class:`ReplayError` when the recorded trip does NOT
    reproduce — a non-reproducing abort means the failure was not a pure
    function of the checkpointed state (e.g. host-side data corruption),
    which is itself the post-mortem headline.

    The report's ``window_steps`` is the minimal number of scan steps
    into the failing chunk that still trips (binary search; the engine's
    chunk-invariance makes a prefix re-run bit-exact, so the bisection
    is sound for the in-graph watchdog probes and a tight upper bound
    for training-divergence probes, whose final verification re-runs the
    minimal window end-to-end)."""
    ctx = load_abort_context(bundle_dir)
    fp = config_fingerprint(fleet, params)
    if fp != ctx["params_fingerprint"]:
        msg = (f"params fingerprint mismatch: bundle {ctx['params_fingerprint']}"
               f" vs rebuilt {fp} — the replay world differs from the "
               "aborted run's (check fleet/params/chaos stage/reseed flags)")
        if not force:
            raise ReplayError(msg)
        print(f"[replay] WARNING: {msg} (--force: continuing)")
    ckpt_root = os.path.dirname(os.path.abspath(bundle_dir))
    chunk_c, n_steps = int(ctx["chunk"]), int(ctx["chunk_steps"])
    kind = ctx["kind"]
    world = _World(fleet, params, ctx)
    if kind == "divergence" and not world.trainer:
        raise ReplayError("divergence abort context without a trainer "
                          "schedule — corrupt bundle")

    restored = world.restore_healthy(ckpt_root, max_step=chunk_c - 1)
    start = restored + 1 if restored is not None else 0
    if verbose:
        print(f"[replay] restored step {restored}; re-running chunks "
              f"{start}..{chunk_c - 1} then reproducing chunk {chunk_c}")
    monitor = _divergence_monitor(ctx) if kind == "divergence" else None
    for _ in range(start, chunk_c):
        world.run_chunk(n_steps)

    snap = world.snapshot()  # chunk-C start (host copies: survives donation)

    def probe(n: int) -> List[str]:
        """Run an n-step prefix of the failing chunk from the snapshot;
        returns the tripping probe names (empty = no trip)."""
        world.rehydrate(snap)
        if kind == "watchdog":
            before = world.viol()
            world.run_chunk(n, train=False)
            return _hard_probe_names(before, world.viol())
        metrics = world.run_chunk(n)
        if metrics is None:
            return []
        from ..obs.health import DivergenceError

        try:
            monitor.check(chunk_c, {k: np.asarray(v)
                                    for k, v in metrics.items()})
        except DivergenceError as e:
            return [e.probe] if e.probe else ["divergence"]
        return []

    tripped = probe(n_steps)
    report: Dict[str, Any] = {
        "schema": REPLAY_REPORT_SCHEMA,
        "kind": kind,
        "chunk": chunk_c,
        "chunk_steps": n_steps,
        "restored_step": restored,
        "expected_probes": ctx["probes"],
        "probes": tripped,
        "reproduced": bool(tripped) and (not ctx["probes"]
                                         or set(tripped) == set(ctx["probes"])),
    }
    if check_state:
        # byte-compare the re-executed post-chunk pipeline against the
        # forensic snapshot — determinism evidence, not just "it tripped"
        bundle_steps = steps(bundle_dir)
        if bundle_steps:
            from ..utils.checkpoint import restore_checkpoint

            names = [n for n in ctx["trees"] if n != "csv"]
            like = dict(world._like_for(ctx["trees"]))
            saved = restore_checkpoint(bundle_dir, bundle_steps[-1],
                                       like=like)
            live = world._tree()
            mism = _tree_mismatches({k: live[k] for k in names},
                                    {k: saved[k] for k in names})
            report["state_match"] = not mism
            report["state_mismatches"] = mism[:20]
    if not report["reproduced"]:
        raise ReplayError(
            f"abort did not reproduce: expected probes {ctx['probes']}, "
            f"replay tripped {tripped or 'nothing'} at chunk {chunk_c} — "
            "the failure was not a pure function of the checkpointed state")
    if bisect:
        lo, hi = 0, n_steps  # probe(lo) clean by construction, probe(hi) trips
        while hi - lo > 1:
            mid = (lo + hi) // 2
            trip_mid = probe(mid)
            if verbose:
                print(f"[replay] bisect: {mid} steps -> "
                      f"{trip_mid or 'clean'}")
            if trip_mid:
                hi = mid
            else:
                lo = mid
        final = probe(hi)  # verify the minimal window end-to-end
        if not final:
            raise ReplayError(
                f"bisection converged on a {hi}-step window that does not "
                "trip on re-verification — the trip is not prefix-monotone")
        report["window_steps"] = hi
        report["window_probes"] = final
    return report


def copy_store_window(src: str, dst: str, lo: Optional[int] = None,
                      hi: Optional[int] = None) -> int:
    """Copy only the COMMITTED steps of ``src`` in ``[lo, hi]`` into a
    fresh store root ``dst`` (inclusive; ``None`` = unbounded).

    A long-lived twin store accumulates thousands of chunk-cadence
    steps; windowed RCA (`twin.service.twin_rca`) and windowed
    :func:`replay_run` must not pay a whole-store ``copytree`` to
    inspect two of them.  Debris and store metadata (ingest watermark
    files) are deliberately left behind — the copy is a valid store
    containing exactly the window.  Returns the number of steps copied.
    """
    want = [s for s in steps(src)
            if (lo is None or s >= lo) and (hi is None or s <= hi)]
    os.makedirs(dst, exist_ok=True)
    for s in want:
        d = os.path.join(dst, step_dirname(s))
        if not os.path.isdir(d):
            shutil.copytree(os.path.join(src, step_dirname(s)), d)
    return len(want)


def replay_run(fleet, params, ckpt_dir: str, src_out_dir: str, out_dir: str,
               step: Optional[int] = None, steps=None, **train_kw):
    """Clean-run replay: resume a chsac run from a (mid-run) checkpoint
    into a fresh workspace, reproducing the original CSV bytes.

    Copies the original CSVs and the checkpoint store into ``out_dir``
    (the evidence is never mutated), optionally prunes the copied store
    back to ``step``, and resumes — the byte-watermark resume truncates
    the logs to the checkpoint and the deterministic engine re-emits the
    identical suffix.  Returns ``train_chsac``'s (state, agent, history).

    ``steps=(lo, hi)`` copies only the committed steps in that range
    (:func:`copy_store_window`) instead of the whole store — RCA on a
    long-lived twin store stays O(window), not O(history).
    """
    from ..utils.checkpoint import steps as _committed

    os.makedirs(out_dir, exist_ok=True)
    for name in ("cluster_log.csv", "job_log.csv", "fault_log.csv"):
        src = os.path.join(src_out_dir, name)
        if os.path.exists(src):
            shutil.copy2(src, os.path.join(out_dir, name))
    ck_copy = os.path.join(out_dir, "ckpt_replay")
    if os.path.isdir(ck_copy):
        shutil.rmtree(ck_copy)
    if steps is not None:
        lo, hi = steps
        if not copy_store_window(ckpt_dir, ck_copy, lo, hi):
            raise ReplayError(
                f"replay window [{lo}, {hi}] holds no committed steps "
                f"of {ckpt_dir}")
    else:
        shutil.copytree(ckpt_dir, ck_copy)
    if step is not None:
        for s in _committed(ck_copy):
            if s > step:
                shutil.rmtree(os.path.join(ck_copy, step_dirname(s)))
    from ..rl.train import train_chsac

    return train_chsac(fleet, params, out_dir=out_dir, ckpt_dir=ck_copy,
                       resume=True, **train_kw)

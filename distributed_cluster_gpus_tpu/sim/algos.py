"""Scheduling / routing / DVFS decision functions — pure, vectorized.

One function per decision point of the nine reference algorithms
(`/root/reference/run_sim_paper.py:78-84`; dispatch sites in
`simcore/simulator_paper_multi.py:543-676, 839-927`).  Everything operates on
gathered per-(dc, jtype) rows of the precomputed [n_dc, 2, n_max, n_f] energy
grids, so each decision is an argmin/gather instead of a Python grid loop.

Preserved reference quirks (see SURVEY.md §7.4):
* `eco_route` only overrides ROUTING; its admission path is the default
  heuristic policy (its computed (n*, f*) hint is stored but never read).
* carbon objective with CI == 0 scores every grid cell 0.0 and therefore
  ties to the first cell (n=1, lowest f).
* Only `eco_route` and `chsac_af` route non-randomly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..models.structs import FleetSpec, SimParams


def f_idx_of(fleet: FleetSpec, value: float) -> int:
    """Nearest ladder index for a frequency value (host-side, config time)."""
    return int(np.argmin(np.abs(fleet.freq_levels - value)))


# ---------------------------------------------------------------------------
# In-DC heuristic allocation (reference simcore/policy.py:16-41)
# ---------------------------------------------------------------------------

def heuristic_select(params: SimParams, fleet: FleetSpec, jtype, free, cur_f_idx, q_inf_len):
    """`select_gpus_and_set_freq` parity: returns (g, new_dc_f_idx).

    Mutating `dc.current_freq` becomes returning the new DC ladder index.
    Callers guarantee free > 0, so g >= 1.
    """
    hi = f_idx_of(fleet, params.dvfs_high)
    lo = f_idx_of(fleet, params.dvfs_low)
    default = fleet.default_f_idx
    g = jnp.maximum(1, jnp.minimum(free, params.max_gpus_per_job))

    is_inf = jtype == 0
    if params.policy_name == "perf_first":
        trn_f = jnp.maximum(cur_f_idx, jnp.where(q_inf_len > 0, hi, default))
        new_f = jnp.where(is_inf, hi, trn_f)
    else:  # energy_aware
        if params.train_scale_out_low_freq:
            scale_out = free >= 2
            trn_f = jnp.where(scale_out, lo, jnp.maximum(cur_f_idx, lo))
        else:
            trn_f = jnp.maximum(cur_f_idx, lo)
        new_f = jnp.where(is_inf, hi, trn_f)
    return g, new_f.astype(jnp.int32)


# ---------------------------------------------------------------------------
# Grid-based admission (joint_nf / carbon_cost / chsac freq pick / debug)
# ---------------------------------------------------------------------------

def _first_min_flat(score):
    """argmin over an [n_max, n_f] grid, first minimum wins (n-major order)."""
    flat = jnp.argmin(score.reshape(-1))
    n_f = score.shape[-1]
    return (flat // n_f + 1).astype(jnp.int32), (flat % n_f).astype(jnp.int32)


def admit_joint_nf(fleet: FleetSpec, E_grid, dc, jtype):
    """(n*, f_idx*) minimising energy per unit over the full grid."""
    return _first_min_flat(E_grid[dc, jtype])


def admit_carbon_cost(fleet: FleetSpec, E_grid, dc, jtype, hour,
                      price=None, ci=None):
    """Cost objective when the hourly price is positive, else carbon.

    Mirrors `simulator_paper_multi.py:622-645`: price is the global hourly
    map; CI defaults to 0.0 for DCs without carbon data (degenerating to the
    first grid cell — preserved quirk).

    ``price``/``ci`` (scalar samples from a workload signal timeline,
    `workload.signals`) override the static hourly table / per-DC map —
    the time-varying-energy path; None keeps the legacy program.
    """
    if price is None:
        price = jnp.asarray(fleet.price_hourly)[hour]
    if ci is None:
        ci = jnp.asarray(fleet.carbon)[dc]
    E = E_grid[dc, jtype]
    score = jnp.where(price > 0.0, E / 3.6e6 * price, E * ci)
    return _first_min_flat(score)


def best_energy_f_idx_at_n(E_grid, dc, jtype, n):
    """argmin_f E at fixed n (chsac_af / debug frequency pick)."""
    return jnp.argmin(E_grid[dc, jtype, n - 1]).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Routing
# ---------------------------------------------------------------------------

def route_random(key, n_dc: int):
    # strong int32 bounds: Python-int bounds clamp through weak int64
    # lanes under jax_enable_x64 (weak-type-promotion, dcg-lint)
    return jax.random.randint(key, (), jnp.int32(0), jnp.int32(n_dc),
                              dtype=jnp.int32)


def route_random_up(key, up):
    """Uniform-random routing over the up DCs only (fault capacity mask).

    Draws rank r in [0, n_up) and maps it to the r-th up DC, so with every
    DC healthy the draw is bit-identical to :func:`route_random` (same
    key, same maxval) — the zero-fault golden property.  With no DC up it
    falls back to DC 0 (the arrival queues there until recovery).
    """
    n_up = jnp.sum(up.astype(jnp.int32))
    # strong int32 minval: a Python-int bound clamps through weak int64
    # under jax_enable_x64 (weak-type-promotion, dcg-lint)
    r = jax.random.randint(key, (), jnp.int32(0), jnp.maximum(n_up, 1),
                           dtype=jnp.int32)
    rank = jnp.cumsum(up.astype(jnp.int32))  # 1-indexed rank among up DCs
    sel = jnp.argmax(rank > r).astype(jnp.int32)
    return jnp.where(n_up > 0, sel, jnp.int32(0)).astype(jnp.int32)


def mask_down_dcs(score, up):
    """Score-mask helper: a down DC can never win a routing argmin."""
    if up is None:
        return score
    return jnp.where(up, score, jnp.inf)


def route_eco(params: SimParams, fleet: FleetSpec, E_grid, jtype, size, hour,
              up=None, price=None, ci=None):
    """Score every DC by its best-(n, f) objective for this job; argmin.

    Parity with `_score_dc_for_job` (`simulator_paper_multi.py:1007-1039`):
    score units are J/job (energy), gCO2/job (carbon) or USD/job (cost);
    first minimum wins over the DC declaration order.

    ``price`` (scalar) / ``ci`` ([n_dc]) are workload signal-timeline
    samples at routing time; None keeps the static legacy tables.
    """
    E = E_grid[:, jtype]  # [n_dc, n_max, n_f]
    if ci is None:
        ci = jnp.asarray(fleet.carbon)  # [n_dc]
    if price is None:
        price = jnp.asarray(fleet.price_hourly)[hour]

    if params.eco_objective == "carbon":
        grid_score = E * ci[:, None, None]
    elif params.eco_objective == "cost":
        grid_score = E / 3.6e6 * price
    else:
        grid_score = E
    # E_unit at each DC's own best cell (first-min, n-major)
    flat = grid_score.reshape(grid_score.shape[0], -1)
    best_cell = jnp.argmin(flat, axis=-1)  # [n_dc]
    E_unit = jnp.take_along_axis(
        E.reshape(E.shape[0], -1), best_cell[:, None], axis=-1
    )[:, 0]

    if params.eco_objective == "carbon":
        dc_score = (E_unit * size) / 3.6e6 * ci
    elif params.eco_objective == "cost":
        dc_score = (E_unit * size) / 3.6e6 * price
    else:
        dc_score = E_unit * size
    return jnp.argmin(mask_down_dcs(dc_score, up)).astype(jnp.int32)


def route_weighted(policy, fleet: FleetSpec, E_grid, ing, jtype, size, hour,
                   q_len, up=None, price=None, ci=None):
    """Route by a :class:`~..network.RouterPolicy` weight vector; argmin DC.

    The reference constructs a RouterPolicy but never reads its weights
    (SURVEY.md §7.4.3); this makes them live: each DC is scored by
    ``w_latency*net_lat + w_energy*E_job + w_carbon*gCO2 + w_cost*USD +
    w_queue*q`` with the energy terms taken at the DC's best (n, f) cell.
    ``price``/``ci`` are workload signal-timeline samples (None = the
    static legacy tables).
    """
    net_lat = jnp.asarray(fleet.net_lat_s)[ing]  # [n_dc]
    E = E_grid[:, jtype]  # [n_dc, n_max, n_f]
    E_unit = jnp.min(E.reshape(E.shape[0], -1), axis=-1)
    E_job = E_unit * size  # J
    if ci is None:
        ci = jnp.asarray(fleet.carbon)
    if price is None:
        price = jnp.asarray(fleet.price_hourly)[hour]
    score = policy.score(
        latency_s=net_lat,
        energy_j=E_job,
        carbon_g=E_job / 3.6e6 * ci,
        cost_usd=E_job / 3.6e6 * price,
        queue_len=q_len.astype(jnp.float32),
    )
    return jnp.argmin(mask_down_dcs(score, up)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# RL observation / masks (chsac_af)
# ---------------------------------------------------------------------------

def windowed_percentile(buf_row, count, q):
    """np.percentile(linear interpolation) over the valid prefix of a ring row.

    ``buf_row`` is [W] with `count` valid entries (order irrelevant for a
    percentile); ``q`` must be a static Python number.  Exact result, but
    computed from a static-size `lax.top_k` instead of a full sort: for a
    high percentile only the top ``ceil((1-q%)·W)+2`` order statistics can
    ever be touched, which turns an O(W log W) per-event sort (the profiled
    hot op of the chsac step) into a cheap fixed-k selection.  (A K-pass
    reduce-max extraction was tried and measured 2.6x SLOWER than top_k on
    CPU at W=512 — top_k's partial selection wins; re-evaluate against a
    TPU profile before touching this again.)
    """
    W = buf_row.shape[0]
    q = float(q)
    K = min(W, int(np.ceil((1.0 - q / 100.0) * W)) + 2)
    m = jnp.minimum(count, W)
    valid = jnp.arange(W) < m
    top = jax.lax.top_k(jnp.where(valid, buf_row, -jnp.inf), K)[0]  # descending
    mf = jnp.maximum(m, 1)
    pos = (q / 100.0) * (mf - 1).astype(buf_row.dtype)
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, mf - 1)
    frac = pos - lo.astype(buf_row.dtype)
    # ascending index i == descending rank (m-1-i); both ranks < K by construction
    s_lo = top[jnp.clip(mf - 1 - lo, 0, K - 1)]
    s_hi = top[jnp.clip(mf - 1 - hi, 0, K - 1)]
    return s_lo * (1.0 - frac) + s_hi * frac


def rl_obs(fleet: FleetSpec, t, busy, cur_f_idx, q_inf_len, q_trn_len,
           price=None, ci=None):
    """[now] + per-DC [total, busy, free, current_f, q_inf, q_trn] (dim 1+6*n_dc).

    With ``price`` (scalar USD/kWh) and ``ci`` ([n_dc] gCO2/kWh) — the
    workload signal samples at decision time — the vector grows by
    1 + n_dc normalized features (``SimParams.obs_dim`` tracks this):
    the policy can then trade latency against the LIVE energy price and
    carbon instead of inferring them from the clock.

    Same feature semantics as the reference `_upgr_obs`
    (`simulator_paper_multi.py:1041-1053`) but normalized to O(1) ranges —
    the reference feeds raw counts (up to 512) and absolute seconds into its
    MLPs, which saturates a fresh policy into near-determinism (measured
    init entropy ~0.005 nats vs ~4.2 uniform).  Deliberate learning-quality
    divergence: time → fraction-of-day, busy/free → fractions of the DC,
    totals and queues → log-compressed.
    """
    total = jnp.asarray(fleet.total_gpus, dtype=jnp.float32)
    busy_f = busy.astype(jnp.float32)
    free = jnp.maximum(0.0, total - busy_f)
    cf = jnp.asarray(fleet.freq_levels)[cur_f_idx]
    feats = jnp.stack(
        [jnp.log1p(total) / 7.0,
         busy_f / total,
         free / total,
         cf,
         jnp.log1p(q_inf_len.astype(jnp.float32)) / 4.0,
         jnp.log1p(q_trn_len.astype(jnp.float32)) / 4.0],
        axis=-1,
    ).reshape(-1)
    t_frac = jnp.asarray((t % 86400.0) / 86400.0, dtype=jnp.float32)
    out = [t_frac[None], feats]
    if price is not None:
        # O(1)-range normalization like the rest of the vector: the paper
        # tariff tops out ~0.25 USD/kWh, grid CI ~1000 gCO2/kWh
        out.append(jnp.asarray(price, jnp.float32)[None] / 0.25)
        out.append(jnp.asarray(ci, jnp.float32) / 1000.0)
    return jnp.concatenate(out)


def rl_masks(params: SimParams, fleet: FleetSpec, busy, lat_buf, lat_count,
             p99_pair=None, reserve=0, up=None):
    """(mask_dc [n_dc], mask_g [n_g]) — parity with `_upgr_masks`.

    DC mask: has free GPUs.  g mask: (i+1) <= max free across DCs; plus the
    SLO-slack heuristic capping g at 1 when the recent p99 (training window
    if it has samples, else inference) is < 0.9 * target.

    ``p99_pair`` ([2] seconds, inference/training) lets a caller that has
    already computed both windowed percentiles (the engine's policy tail
    shares one top_k across masks and the RL cost vector) skip the
    recomputation here.

    ``reserve`` (scalar GPUs) shrinks every DC's visible free count — the
    engine passes `SimParams.reserve_inf_gpus` when the pending decision
    concerns a TRAINING job, so the policy never sees a DC as feasible
    that the placement commit would refuse.

    ``up`` ([n_dc] bool, fault capacity mask) zeroes a down DC's visible
    free count so the policy never routes to it — unless EVERY DC is down,
    where the raw masks are kept (an all-invalid action mask would
    degenerate the policy distribution; the chosen DC just queues the job
    until recovery, same as the heuristic routers' fallback).
    """
    total = jnp.asarray(fleet.total_gpus)
    free = jnp.maximum(0, total - busy - reserve)
    if up is not None:
        free = jnp.where(jnp.any(up), jnp.where(up, free, 0), free)
    mask_dc = free > 0
    max_free = jnp.max(free)
    n_g = params.max_gpus_per_job
    g_range = jnp.arange(1, n_g + 1)
    mask_g = g_range <= max_free

    use_trn = lat_count[1] > 0
    cnt = jnp.where(use_trn, lat_count[1], lat_count[0])
    if p99_pair is None:
        buf = jnp.where(use_trn, lat_buf[1], lat_buf[0])
        p99 = windowed_percentile(buf, cnt, 99.0)
    else:
        p99 = jnp.where(use_trn, p99_pair[1], p99_pair[0])
    slack = (cnt >= 5) & (p99 * 1000.0 < 0.9 * params.sla_p99_ms)
    mask_g = jnp.where(slack, g_range <= jnp.minimum(1, max_free), mask_g)
    return mask_dc, mask_g

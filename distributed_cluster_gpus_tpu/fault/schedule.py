"""Lower a declarative :class:`FaultParams` spec into a FaultState timeline.

Declarative windows become static numpy event pairs; the stochastic mode
appends per-DC outage windows drawn from alternating Exponential(mtbf) /
Exponential(mttr) spans with jax PRNG — traceable, so ``init_fault_state``
vmaps over per-rollout keys and each lane realizes an independent fault
schedule (same spec, different draws).  Everything is merged and sorted
once at init time; the engine then consumes the timeline with a cursor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..network import loss_latency_multiplier
from .state import (FK_DC_DOWN, FK_DC_UP, FK_DERATE, FK_NONE, FK_WAN,
                    FaultParams, FaultState)


def timeline_len(fp: FaultParams, n_dc: int, n_ing: int = 0) -> int:
    """Static timeline length M for a (spec, fleet) pair.

    Always one longer than the real event count: the trailing +inf
    sentinel is where the cursor parks after the last real transition —
    without it, jax's clamped gather would re-read the final (now past)
    entry and the engine would fire it forever as zero-dt steps.
    ``n_ing`` only matters for chaos curricula with WAN incidents (their
    per-edge budget scales with the ingress count).
    """
    n = fp.n_events
    if fp.mtbf_s > 0:
        n += 2 * n_dc * fp.max_outages_per_dc
    if fp.curriculum is not None:
        if fp.curriculum.wan_on and n_ing <= 0:
            raise ValueError(
                "timeline_len needs n_ing for a curriculum with WAN "
                "incidents (per-edge window budget)")
        n += fp.curriculum.n_events(n_dc, n_ing)
    return n + 1


def _declarative_events(fp: FaultParams, n_dc: int, freq_levels: np.ndarray):
    """Static (times, kinds, idxs, values) numpy arrays from the windows."""
    times, kinds, idxs, vals = [], [], [], []

    def add(t, k, i, v):
        times.append(float(t))
        kinds.append(int(k))
        idxs.append(int(i))
        vals.append(float(v))

    n_f = len(freq_levels)
    for dc, s, e in fp.outages:
        add(s, FK_DC_DOWN, dc, 0.0)
        add(e, FK_DC_UP, dc, 0.0)
    for dc, s, e, f_cap in fp.derates:
        lvl = int(np.argmin(np.abs(np.asarray(freq_levels) - f_cap)))
        add(s, FK_DERATE, dc, lvl)
        add(e, FK_DERATE, dc, n_f - 1)  # lift the clamp for new starts
    for ing, dc, s, e, mult, loss in fp.wan:
        edge = ing * n_dc + dc
        add(s, FK_WAN, edge, mult * loss_latency_multiplier(loss))
        add(e, FK_WAN, edge, 1.0)
    return (np.asarray(times, np.float64), np.asarray(kinds, np.int32),
            np.asarray(idxs, np.int32), np.asarray(vals, np.float32))


def _stochastic_outages(key, fp: FaultParams, n_dc: int):
    """Per-DC MTBF/MTTR outage windows -> (times, kinds, idxs, values).

    Window k of DC d starts at ``sum(up[0..k]) + sum(down[0..k-1])`` and
    lasts ``down[k]`` — an alternating renewal process.  Windows beyond
    the run simply never fire (the engine stops firing events past
    ``duration``), so no clamping is needed.
    """
    K = fp.max_outages_per_dc
    k_up, k_down = jax.random.split(key)
    up = jax.random.exponential(k_up, (n_dc, K)) * fp.mtbf_s
    down = jax.random.exponential(k_down, (n_dc, K)) * fp.mttr_s
    start = jnp.cumsum(up, axis=1) + jnp.cumsum(down, axis=1) - down
    end = start + down
    dc = jnp.broadcast_to(jnp.arange(n_dc, dtype=jnp.int32)[:, None],
                          (n_dc, K))
    times = jnp.concatenate([start.reshape(-1), end.reshape(-1)])
    kinds = jnp.concatenate([
        jnp.full((n_dc * K,), FK_DC_DOWN, jnp.int32),
        jnp.full((n_dc * K,), FK_DC_UP, jnp.int32)])
    idxs = jnp.concatenate([dc.reshape(-1), dc.reshape(-1)])
    vals = jnp.zeros((2 * n_dc * K,), jnp.float32)
    return times, kinds, idxs, vals


def init_fault_state(key, fp: FaultParams, *, n_dc: int, n_ing: int,
                     freq_levels, tdtype) -> FaultState:
    """Compile ``fp`` into a fresh all-healthy FaultState timeline.

    ``key`` seeds the stochastic outage draws only (ignored when
    ``fp.mtbf_s == 0``); callers derive it with ``fold_in`` so the main
    simulation PRNG chain is untouched whether or not faults run.
    """
    for dc, *_ in list(fp.outages) + list(fp.derates):
        if not 0 <= dc < n_dc:
            raise ValueError(f"fault window DC index {dc} out of range "
                             f"for this fleet (0..{n_dc - 1})")
    for ing, dc, *_ in fp.wan:
        if not (0 <= ing < n_ing and 0 <= dc < n_dc):
            raise ValueError(f"wan window edge ({ing}, {dc}) out of range "
                             f"for this fleet ({n_ing} ingresses, "
                             f"{n_dc} DCs)")
    freq_levels = np.asarray(freq_levels)
    dt, dk, di, dv = _declarative_events(fp, n_dc, freq_levels)
    parts = [(jnp.asarray(dt), jnp.asarray(dk), jnp.asarray(di),
              jnp.asarray(dv))]
    if fp.mtbf_s > 0:
        parts.append(_stochastic_outages(key, fp, n_dc))
    if fp.curriculum is not None and fp.curriculum.n_events(n_dc, n_ing) > 0:
        from .curriculum import curriculum_events

        # dedicated sub-fold so adding a curriculum leaves the legacy
        # stochastic-outage draws (and their goldens) untouched
        parts.append(curriculum_events(
            jax.random.fold_in(key, 0xC0A1), fp.curriculum,
            n_dc=n_dc, n_ing=n_ing, freq_levels=freq_levels))
    times = jnp.concatenate([p[0] for p in parts])
    kinds = jnp.concatenate([p[1] for p in parts])
    idxs = jnp.concatenate([p[2] for p in parts])
    vals = jnp.concatenate([p[3] for p in parts])

    M = timeline_len(fp, n_dc, n_ing)
    pad = M - times.shape[0]  # >= 1: the cursor's trailing +inf sentinel
    times = jnp.concatenate([times, jnp.full((pad,), jnp.inf)])
    kinds = jnp.concatenate([kinds, jnp.full((pad,), FK_NONE, jnp.int32)])
    idxs = jnp.concatenate([idxs, jnp.zeros((pad,), jnp.int32)])
    vals = jnp.concatenate([vals, jnp.zeros((pad,), jnp.float32)])
    # sort by time with OFF-before-ON tie-break: when one window ends
    # exactly where another begins on the same target (validation allows
    # s1 == e0), the reset must fire before the new clamp or the opening
    # window would be cancelled at its first instant.  Outages are immune
    # (depth counter), but classify them too: at a shared instant a
    # recovery before an onset reads as two incidents, which matches the
    # windows' intent.  (A derate-to-max or WAN-mult-1.0 "on" event is
    # classified off — both are no-ops, so the order is irrelevant.)
    n_f = len(freq_levels)
    is_on = ((kinds == FK_DC_DOWN)
             | ((kinds == FK_DERATE) & (vals != n_f - 1))
             | ((kinds == FK_WAN) & (vals != 1.0)))
    order = jnp.lexsort((is_on.astype(jnp.int32), times))
    zt = lambda shape=(): jnp.zeros(shape, dtype=tdtype)  # noqa: E731
    return FaultState(
        times=times[order].astype(tdtype),
        kind=kinds[order],
        idx=idxs[order],
        value=vals[order],
        cursor=jnp.int32(0),
        dc_up=jnp.ones((n_dc,), bool),
        down_depth=jnp.zeros((n_dc,), jnp.int32),
        derate_f_idx=jnp.full((n_dc,), len(freq_levels) - 1, jnp.int32),
        wan_mult=jnp.ones((n_ing, n_dc), jnp.float32),
        n_preempted=jnp.int32(0),
        n_migrated=jnp.int32(0),
        n_failed=jnp.int32(0),
        n_outages=jnp.zeros((n_dc,), jnp.int32),
        downtime=zt((n_dc,)),
    )

"""Declarative chaos curricula: randomized fault distributions per lane.

A :class:`ChaosCurriculum` describes fault *distributions* instead of
fault *events*: per-DC outage processes with MTBF/MTTR drawn from
log-uniform ranges, straggler (derate) windows with random depth and
duration, and WAN-degradation windows with random latency multipliers
and loss — plus a ladder of :class:`ChaosStage` severity multipliers
that a training campaign ramps through.  It rides
``FaultParams.curriculum`` and lowers (``fault/schedule.py``) into the
SAME sorted FaultState timeline the declarative and stochastic modes
compile to, so the engine's EV_FAULT machinery is untouched: the
curriculum is purely an init-time event generator.

Every draw is traceable jax PRNG arithmetic seeded from the per-rollout
fault key (``init_state`` folds ``0x0FA17`` off the lane key), so a
vmapped batch of rollout lanes realizes INDEPENDENT fault curricula —
different MTBF regimes, different incident sequences — with zero host
involvement, and the whole realization is a pure function of
``(seed, reseed)``.  ``reseed`` is the campaign driver's retry knob: a
diverged campaign resumes from its last healthy checkpoint and re-draws
the chaos under ``reseed + 1`` without touching the workload chain.

Curricula are specified three ways (mirroring ``workload/spec.py``):
python construction, named presets (:data:`CHAOS_PRESETS`, including
the held-out evaluation set :data:`HELD_OUT_PRESETS` that training
presets must never reference), and JSON spec files
(:func:`load_chaos_json`; linted by ``scripts/validate_chaos.py``).

Note: drawn derate/WAN windows use per-target alternating renewals, so
they never overlap among themselves — but they can overlap windows the
same spec declares in ``FaultParams.derates``/``.wan`` (declarative
off-events are stateless resets).  Combine the curriculum with
declarative *outages* freely (those nest by depth); avoid mixing it
with declarative derate/WAN windows on the same targets.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class ChaosStage:
    """One severity rung: multipliers over the curriculum's base ranges.

    * ``rate_scale`` multiplies incident rates (divides MTBF / gaps);
    * ``mttr_scale`` multiplies outage repair times;
    * ``severity_scale`` deepens incidents: derate caps are raised to
      this power (f in (0, 1], so > 1 clamps lower) and WAN multipliers
      stretch as ``1 + (mult - 1) * severity_scale``.
    """

    rate_scale: float = 1.0
    mttr_scale: float = 1.0
    severity_scale: float = 1.0

    def __post_init__(self):
        for k in ("rate_scale", "mttr_scale", "severity_scale"):
            v = getattr(self, k)
            if not (math.isfinite(v) and v > 0):
                raise ValueError(f"stage {k} must be finite and > 0, got {v}")


def ramp_stages(n: int, rate_to: float = 3.0, mttr_to: float = 1.0,
                severity_to: float = 1.5) -> Tuple[ChaosStage, ...]:
    """``n`` stages ramping linearly from 1.0 to the given end scales."""
    if n < 1:
        raise ValueError(f"need at least one stage, got {n}")
    if n == 1:
        return (ChaosStage(),)
    f = lambda a, b, i: a + (b - a) * i / (n - 1)  # noqa: E731
    return tuple(ChaosStage(rate_scale=f(1.0, rate_to, i),
                            mttr_scale=f(1.0, mttr_to, i),
                            severity_scale=f(1.0, severity_to, i))
                 for i in range(n))


@dataclasses.dataclass(frozen=True)
class ChaosCurriculum:
    """Randomized fault-distribution spec (static run shape; hashable).

    Three incident families, each enabled by a positive base rate:

    * **outages** (``mtbf_lo_s > 0``): each DC draws its own MTBF from
      LogUniform[mtbf_lo_s, mtbf_hi_s] and MTTR from
      LogUniform[mttr_lo_s, mttr_hi_s], then realizes an alternating
      Exp(mtbf)/Exp(mttr) renewal of up to ``max_outages_per_dc``
      windows — heterogeneous fleet reliability, not one global rate.
    * **derates** (``derate_rate_per_dc_hour > 0``): straggler windows
      per DC at the given rate, duration Uniform[dur_lo, dur_hi], DVFS
      cap Uniform[f_lo, f_hi] (quantized to the fleet ladder at
      lowering time).
    * **wan** (``wan_rate_per_edge_hour > 0``): per-(ingress, DC)-edge
      degradation windows, latency multiplier Uniform[mult_lo, mult_hi]
      and packet loss Uniform[0, loss_hi] folded in as retransmits.

    ``stages`` is the severity ladder a campaign ramps through;
    ``stage`` selects the active rung (static — a different stage
    re-specializes init, not the step program).  Window budgets
    (``max_*``) are static shapes; :meth:`sized_for` sizes them so a
    run of a given duration is effectively never truncated.
    """

    # outages: per-DC MTBF/MTTR drawn from log-uniform ranges
    mtbf_lo_s: float = 0.0
    mtbf_hi_s: float = 0.0
    mttr_lo_s: float = 120.0
    mttr_hi_s: float = 600.0
    max_outages_per_dc: int = 4
    # straggler (derate) windows
    derate_rate_per_dc_hour: float = 0.0
    derate_dur_lo_s: float = 60.0
    derate_dur_hi_s: float = 600.0
    derate_f_lo: float = 0.4
    derate_f_hi: float = 0.8
    max_derates_per_dc: int = 4
    # WAN degradation windows
    wan_rate_per_edge_hour: float = 0.0
    wan_dur_lo_s: float = 30.0
    wan_dur_hi_s: float = 300.0
    wan_mult_lo: float = 1.5
    wan_mult_hi: float = 4.0
    wan_loss_hi: float = 0.2
    max_wan_per_edge: int = 2
    # severity ramp
    stages: Tuple[ChaosStage, ...] = (ChaosStage(),)
    stage: int = 0
    reseed: int = 0
    name: str = "custom"

    def __post_init__(self):
        def rng(lo, hi, what, min_lo=0.0, strict_lo=False):
            ok_lo = lo > min_lo if strict_lo else lo >= min_lo
            if not (math.isfinite(lo) and math.isfinite(hi)
                    and ok_lo and hi >= lo):
                raise ValueError(
                    f"{what} range [{lo}, {hi}] invalid (need "
                    f"{min_lo} {'<' if strict_lo else '<='} lo <= hi, finite)")

        rng(self.mtbf_lo_s, self.mtbf_hi_s, "mtbf_s")
        if self.outages_on:
            rng(self.mttr_lo_s, self.mttr_hi_s, "mttr_s", strict_lo=True)
        if not (math.isfinite(self.derate_rate_per_dc_hour)
                and self.derate_rate_per_dc_hour >= 0):
            raise ValueError("derate_rate_per_dc_hour must be finite >= 0")
        if self.derates_on:
            rng(self.derate_dur_lo_s, self.derate_dur_hi_s, "derate_dur_s",
                strict_lo=True)
            rng(self.derate_f_lo, self.derate_f_hi, "derate_f",
                strict_lo=True)
            if self.derate_f_hi > 1.0:
                raise ValueError(
                    f"derate_f_hi {self.derate_f_hi} > 1: caps are ladder "
                    "fractions in (0, 1]")
        if not (math.isfinite(self.wan_rate_per_edge_hour)
                and self.wan_rate_per_edge_hour >= 0):
            raise ValueError("wan_rate_per_edge_hour must be finite >= 0")
        if self.wan_on:
            rng(self.wan_dur_lo_s, self.wan_dur_hi_s, "wan_dur_s",
                strict_lo=True)
            rng(self.wan_mult_lo, self.wan_mult_hi, "wan_mult", min_lo=1.0)
            if not (math.isfinite(self.wan_loss_hi)
                    and 0.0 <= self.wan_loss_hi < 1.0):
                raise ValueError(
                    f"wan_loss_hi must be in [0, 1), got {self.wan_loss_hi}")
        for k in ("max_outages_per_dc", "max_derates_per_dc",
                  "max_wan_per_edge"):
            if getattr(self, k) < 1:
                raise ValueError(f"{k} must be >= 1")
        if not self.stages:
            raise ValueError("curriculum needs at least one stage")
        if not 0 <= self.stage < len(self.stages):
            raise ValueError(
                f"stage {self.stage} out of range for {len(self.stages)} "
                "stage(s)")
        if self.reseed < 0:
            raise ValueError("reseed must be >= 0")

    # -- enablement (static python: a disabled family draws nothing and
    #    contributes zero timeline entries, so an all-off curriculum
    #    compiles the exact curriculum-free program) -----------------------

    @property
    def outages_on(self) -> bool:
        return self.mtbf_lo_s > 0

    @property
    def derates_on(self) -> bool:
        return self.derate_rate_per_dc_hour > 0

    @property
    def wan_on(self) -> bool:
        return self.wan_rate_per_edge_hour > 0

    def n_events(self, n_dc: int, n_ing: int) -> int:
        """Static timeline entries this curriculum adds (on + off pairs)."""
        n = 0
        if self.outages_on:
            n += 2 * n_dc * self.max_outages_per_dc
        if self.derates_on:
            n += 2 * n_dc * self.max_derates_per_dc
        if self.wan_on:
            n += 2 * n_ing * n_dc * self.max_wan_per_edge
        return n

    # -- campaign knobs -----------------------------------------------------

    def at_stage(self, stage: int) -> "ChaosCurriculum":
        return dataclasses.replace(self, stage=stage)

    def reseeded(self, reseed: int) -> "ChaosCurriculum":
        return dataclasses.replace(self, reseed=reseed)

    def max_rate_scale(self) -> float:
        return max(s.rate_scale for s in self.stages)

    def sized_for(self, duration_s: float) -> "ChaosCurriculum":
        """Window budgets sized to ~3x the expected incident count over
        ``duration_s`` at the harshest stage, so realized schedules are
        effectively never truncated (same 3x rule as
        ``configs.paper.build_chaos_faults``)."""
        if not (math.isfinite(duration_s) and duration_s > 0):
            raise ValueError(f"duration_s must be finite > 0, got {duration_s}")
        rs = self.max_rate_scale()
        kw = {}
        if self.outages_on:
            cycle = self.mtbf_lo_s / rs + self.mttr_lo_s
            kw["max_outages_per_dc"] = max(
                2, int(np.ceil(3 * duration_s / cycle)) + 1)
        if self.derates_on:
            per_hr = self.derate_rate_per_dc_hour * rs
            kw["max_derates_per_dc"] = max(
                2, int(np.ceil(3 * duration_s / 3600.0 * per_hr)) + 1)
        if self.wan_on:
            per_hr = self.wan_rate_per_edge_hour * rs
            kw["max_wan_per_edge"] = max(
                1, int(np.ceil(3 * duration_s / 3600.0 * per_hr)) + 1)
        return dataclasses.replace(self, **kw)


def curriculum_events(key, cur: ChaosCurriculum, *, n_dc: int, n_ing: int,
                      freq_levels):
    """Draw one lane's chaos incidents -> (times, kinds, idxs, values).

    Traceable (vmappable over per-lane keys); static output length
    ``cur.n_events(n_dc, n_ing)``.  Each enabled family draws an
    alternating renewal per target (windows never overlap per target):
    gap ~ Exp(mean / rate_scale), then the window; windows beyond the
    run land past ``duration`` and simply never fire.
    """
    import jax
    import jax.numpy as jnp

    from .state import FK_DC_DOWN, FK_DC_UP, FK_DERATE, FK_WAN

    st = cur.stages[cur.stage]
    key = jax.random.fold_in(key, cur.reseed)
    k_out, k_der, k_wan = jax.random.split(key, 3)
    freq = jnp.asarray(np.asarray(freq_levels), jnp.float32)
    n_f = int(freq.shape[0])
    parts = []

    def loguniform(k, lo, hi, shape):
        u = jax.random.uniform(k, shape)
        return jnp.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))

    def renewal(k_gap, gap_mean, dur):
        """starts/ends of an alternating gap/window renewal per target."""
        gaps = jax.random.exponential(k_gap, dur.shape) * gap_mean
        start = jnp.cumsum(gaps + dur, axis=1) - dur
        return start, start + dur

    if cur.outages_on:
        k1, k2, k3, k4 = jax.random.split(k_out, 4)
        K = cur.max_outages_per_dc
        mtbf = loguniform(k1, cur.mtbf_lo_s, cur.mtbf_hi_s,
                          (n_dc, 1)) / st.rate_scale
        mttr = loguniform(k2, cur.mttr_lo_s, cur.mttr_hi_s,
                          (n_dc, 1)) * st.mttr_scale
        down = jax.random.exponential(k4, (n_dc, K)) * mttr
        start, end = renewal(k3, mtbf, down)
        dc = jnp.broadcast_to(jnp.arange(n_dc, dtype=jnp.int32)[:, None],
                              (n_dc, K))
        times = jnp.concatenate([start.reshape(-1), end.reshape(-1)])
        kinds = jnp.concatenate([
            jnp.full((n_dc * K,), FK_DC_DOWN, jnp.int32),
            jnp.full((n_dc * K,), FK_DC_UP, jnp.int32)])
        idxs = jnp.concatenate([dc.reshape(-1), dc.reshape(-1)])
        vals = jnp.zeros((2 * n_dc * K,), jnp.float32)
        parts.append((times, kinds, idxs, vals))

    if cur.derates_on:
        k1, k2, k3 = jax.random.split(k_der, 3)
        K = cur.max_derates_per_dc
        gap_mean = 3600.0 / (cur.derate_rate_per_dc_hour * st.rate_scale)
        dur = jax.random.uniform(k2, (n_dc, K), minval=cur.derate_dur_lo_s,
                                 maxval=cur.derate_dur_hi_s)
        start, end = renewal(k1, gap_mean, dur)
        f_cap = jax.random.uniform(k3, (n_dc, K), minval=cur.derate_f_lo,
                                   maxval=cur.derate_f_hi) ** st.severity_scale
        # quantize to the fleet ladder: value = float-encoded max level
        lvl = jnp.argmin(jnp.abs(freq[None, None, :] - f_cap[..., None]),
                         axis=-1).astype(jnp.float32)
        dc = jnp.broadcast_to(jnp.arange(n_dc, dtype=jnp.int32)[:, None],
                              (n_dc, K))
        times = jnp.concatenate([start.reshape(-1), end.reshape(-1)])
        kinds = jnp.full((2 * n_dc * K,), FK_DERATE, jnp.int32)
        idxs = jnp.concatenate([dc.reshape(-1), dc.reshape(-1)])
        vals = jnp.concatenate([lvl.reshape(-1),
                                jnp.full((n_dc * K,), float(n_f - 1),
                                         jnp.float32)])
        parts.append((times, kinds, idxs, vals))

    if cur.wan_on:
        k1, k2, k3, k4 = jax.random.split(k_wan, 4)
        E = n_ing * n_dc
        K = cur.max_wan_per_edge
        gap_mean = 3600.0 / (cur.wan_rate_per_edge_hour * st.rate_scale)
        dur = jax.random.uniform(k2, (E, K), minval=cur.wan_dur_lo_s,
                                 maxval=cur.wan_dur_hi_s)
        start, end = renewal(k1, gap_mean, dur)
        mult = jax.random.uniform(k3, (E, K), minval=cur.wan_mult_lo,
                                  maxval=cur.wan_mult_hi)
        mult = 1.0 + (mult - 1.0) * st.severity_scale
        loss = jax.random.uniform(k4, (E, K), minval=0.0,
                                  maxval=cur.wan_loss_hi)
        # retransmit model folded in traceably (the python-validating
        # network.loss_latency_multiplier is host-only): 1 / (1 - loss)
        val_on = (mult / (1.0 - loss)).astype(jnp.float32)
        edge = jnp.broadcast_to(jnp.arange(E, dtype=jnp.int32)[:, None],
                                (E, K))
        times = jnp.concatenate([start.reshape(-1), end.reshape(-1)])
        kinds = jnp.full((2 * E * K,), FK_WAN, jnp.int32)
        idxs = jnp.concatenate([edge.reshape(-1), edge.reshape(-1)])
        vals = jnp.concatenate([val_on.reshape(-1),
                                jnp.ones((E * K,), jnp.float32)])
        parts.append((times, kinds, idxs, vals))

    if not parts:
        z = jnp.zeros((0,))
        return (z, jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.int32),
                jnp.zeros((0,), jnp.float32))
    return (jnp.concatenate([p[0] for p in parts]),
            jnp.concatenate([p[1] for p in parts]),
            jnp.concatenate([p[2] for p in parts]),
            jnp.concatenate([p[3] for p in parts]))


# ---------------------------------------------------------------------------
# JSON spec files (scripts/validate_chaos.py lints these)
# ---------------------------------------------------------------------------

_SECTION_KEYS = {
    "outages": {"mtbf_lo_s": "mtbf_lo_s", "mtbf_hi_s": "mtbf_hi_s",
                "mttr_lo_s": "mttr_lo_s", "mttr_hi_s": "mttr_hi_s",
                "max_per_dc": "max_outages_per_dc"},
    "derates": {"rate_per_dc_hour": "derate_rate_per_dc_hour",
                "dur_lo_s": "derate_dur_lo_s", "dur_hi_s": "derate_dur_hi_s",
                "f_lo": "derate_f_lo", "f_hi": "derate_f_hi",
                "max_per_dc": "max_derates_per_dc"},
    "wan": {"rate_per_edge_hour": "wan_rate_per_edge_hour",
            "dur_lo_s": "wan_dur_lo_s", "dur_hi_s": "wan_dur_hi_s",
            "mult_lo": "wan_mult_lo", "mult_hi": "wan_mult_hi",
            "loss_hi": "wan_loss_hi", "max_per_edge": "max_wan_per_edge"},
}


def chaos_from_dict(doc: dict) -> ChaosCurriculum:
    """Build a ChaosCurriculum from a parsed JSON document.

    Schema (docs/faults.md):

    .. code-block:: json

        {"name": "...",
         "outages": {"mtbf_lo_s": 600, "mtbf_hi_s": 3600,
                     "mttr_lo_s": 120, "mttr_hi_s": 600, "max_per_dc": 8},
         "derates": {"rate_per_dc_hour": 2, "dur_lo_s": 60, "dur_hi_s": 600,
                     "f_lo": 0.4, "f_hi": 0.8, "max_per_dc": 6},
         "wan": {"rate_per_edge_hour": 1, "dur_lo_s": 30, "dur_hi_s": 300,
                 "mult_lo": 1.5, "mult_hi": 4.0, "loss_hi": 0.2,
                 "max_per_edge": 3},
         "stages": [{"rate_scale": 1.0}, {"rate_scale": 2.0,
                                          "severity_scale": 1.5}]}

    Omitted sections stay disabled; unknown keys are rejected (a typo
    would silently weaken the chaos).
    """
    known = set(_SECTION_KEYS) | {"name", "stages", "stage", "reseed"}
    unknown = set(doc) - known
    if unknown:
        raise ValueError(f"unknown top-level keys {sorted(unknown)}")
    kw = {"name": doc.get("name", "custom")}
    for section, keymap in _SECTION_KEYS.items():
        sd = doc.get(section)
        if sd is None:
            continue
        unknown = set(sd) - set(keymap)
        if unknown:
            raise ValueError(
                f"{kw['name']}/{section}: unknown keys {sorted(unknown)} "
                f"(expected {sorted(keymap)})")
        for k, field in keymap.items():
            if k in sd:
                v = sd[k]
                kw[field] = int(v) if field.startswith("max_") else float(v)
        # a section present without its enabling rate is a spec error for
        # derates/wan (outages enable via mtbf_lo_s, which is mandatory
        # there for the same reason)
        enable = {"outages": "mtbf_lo_s", "derates": "rate_per_dc_hour",
                  "wan": "rate_per_edge_hour"}[section]
        if enable not in sd:
            raise ValueError(
                f"{kw['name']}/{section}: missing {enable!r} — a section "
                "without its rate would silently draw nothing")
    if "stages" in doc:
        stages = []
        for i, sd in enumerate(doc["stages"]):
            unknown = set(sd) - {"rate_scale", "mttr_scale", "severity_scale"}
            if unknown:
                raise ValueError(
                    f"{kw['name']}/stages[{i}]: unknown keys "
                    f"{sorted(unknown)}")
            stages.append(ChaosStage(**{k: float(v) for k, v in sd.items()}))
        kw["stages"] = tuple(stages)
    if "stage" in doc:
        kw["stage"] = int(doc["stage"])
    if "reseed" in doc:
        kw["reseed"] = int(doc["reseed"])
    return ChaosCurriculum(**kw)


def load_chaos_json(path: str) -> ChaosCurriculum:
    with open(path) as f:
        doc = json.load(f)
    cur = chaos_from_dict(doc)
    if doc.get("name") is None:
        cur = dataclasses.replace(cur, name=path)
    return cur


# ---------------------------------------------------------------------------
# Presets: training curricula + the held-out evaluation set
# ---------------------------------------------------------------------------

def _gentle_outages() -> ChaosCurriculum:
    """Outages only, mild and rare — the on-ramp curriculum."""
    return ChaosCurriculum(
        name="gentle_outages",
        mtbf_lo_s=1800.0, mtbf_hi_s=7200.0,
        mttr_lo_s=120.0, mttr_hi_s=300.0,
        stages=ramp_stages(2, rate_to=2.0),
    )


def _mixed_ramp() -> ChaosCurriculum:
    """The canonical training curriculum: all three incident families
    with a 3-stage severity ramp (rates x3, repairs x1.5, depth x1.5)."""
    return ChaosCurriculum(
        name="mixed_ramp",
        mtbf_lo_s=900.0, mtbf_hi_s=3600.0,
        mttr_lo_s=120.0, mttr_hi_s=480.0,
        derate_rate_per_dc_hour=1.0,
        derate_dur_lo_s=120.0, derate_dur_hi_s=600.0,
        derate_f_lo=0.4, derate_f_hi=0.8,
        wan_rate_per_edge_hour=0.5,
        wan_dur_lo_s=60.0, wan_dur_hi_s=300.0,
        wan_mult_lo=1.5, wan_mult_hi=3.0, wan_loss_hi=0.1,
        stages=ramp_stages(3, rate_to=3.0, mttr_to=1.5, severity_to=1.5),
    )


def _wan_storm() -> ChaosCurriculum:
    """WAN-degradation-heavy training curriculum (routing stress)."""
    return ChaosCurriculum(
        name="wan_storm",
        wan_rate_per_edge_hour=4.0,
        wan_dur_lo_s=60.0, wan_dur_hi_s=600.0,
        wan_mult_lo=2.0, wan_mult_hi=6.0, wan_loss_hi=0.3,
        stages=ramp_stages(2, rate_to=2.0, severity_to=1.5),
    )


def _held_out_regional_blackout() -> ChaosCurriculum:
    """Held-out: frequent hard outages with slow repairs — the
    capacity-loss regime (never used by a training preset)."""
    return ChaosCurriculum(
        name="held_out_regional_blackout",
        mtbf_lo_s=600.0, mtbf_hi_s=1800.0,
        mttr_lo_s=300.0, mttr_hi_s=900.0,
    )


def _held_out_stragglers() -> ChaosCurriculum:
    """Held-out: a fleet full of deeply derated stragglers."""
    return ChaosCurriculum(
        name="held_out_stragglers",
        derate_rate_per_dc_hour=6.0,
        derate_dur_lo_s=300.0, derate_dur_hi_s=1200.0,
        derate_f_lo=0.3, derate_f_hi=0.5,
    )


def _held_out_flaky_wan() -> ChaosCurriculum:
    """Held-out: lossy, slow WAN plus occasional outages — the
    degraded-connectivity regime."""
    return ChaosCurriculum(
        name="held_out_flaky_wan",
        mtbf_lo_s=1800.0, mtbf_hi_s=3600.0,
        mttr_lo_s=120.0, mttr_hi_s=300.0,
        wan_rate_per_edge_hour=3.0,
        wan_dur_lo_s=120.0, wan_dur_hi_s=900.0,
        wan_mult_lo=2.0, wan_mult_hi=8.0, wan_loss_hi=0.4,
    )


CHAOS_PRESETS = {
    "gentle_outages": _gentle_outages,
    "mixed_ramp": _mixed_ramp,
    "wan_storm": _wan_storm,
    "held_out_regional_blackout": _held_out_regional_blackout,
    "held_out_stragglers": _held_out_stragglers,
    "held_out_flaky_wan": _held_out_flaky_wan,
}

#: evaluation-only presets: the campaign driver refuses to train on these,
#: so sweep scores on them are genuinely held out
HELD_OUT_PRESETS = ("held_out_regional_blackout", "held_out_stragglers",
                    "held_out_flaky_wan")


def make_chaos_preset(name: str, duration_s: Optional[float] = None,
                      stage: int = 0, reseed: int = 0) -> ChaosCurriculum:
    """Named curriculum, optionally budget-sized for a run duration."""
    if name not in CHAOS_PRESETS:
        raise ValueError(
            f"unknown chaos preset {name!r}; choices: "
            f"{', '.join(sorted(CHAOS_PRESETS))}")
    cur = CHAOS_PRESETS[name]()
    if duration_s is not None:
        cur = cur.sized_for(duration_s)
    if stage:
        cur = cur.at_stage(stage)
    if reseed:
        cur = cur.reseeded(reseed)
    return cur

"""Fault-injection & recovery subsystem for the scanned engine.

Declarative, jit-compatible fault schedules (DC outages, frequency-derating
stragglers, WAN degradation, stochastic MTBF/MTTR clocks) and randomized
chaos curricula (``fault/curriculum.py``) compiled into fixed-shape
timelines threaded through ``SimState`` — see ``docs/faults.md``.
"""

from .curriculum import (  # noqa: F401
    CHAOS_PRESETS,
    HELD_OUT_PRESETS,
    ChaosCurriculum,
    ChaosStage,
    chaos_from_dict,
    load_chaos_json,
    make_chaos_preset,
    ramp_stages,
)
from .schedule import init_fault_state, timeline_len  # noqa: F401
from .state import (  # noqa: F401
    FAULT_KIND_NAMES,
    FK_DC_DOWN,
    FK_DC_UP,
    FK_DERATE,
    FK_NONE,
    FK_WAN,
    FaultParams,
    FaultState,
)

__all__ = [
    "FaultParams", "FaultState", "init_fault_state", "timeline_len",
    "FAULT_KIND_NAMES", "FK_NONE", "FK_DC_DOWN", "FK_DC_UP", "FK_DERATE",
    "FK_WAN",
    "ChaosCurriculum", "ChaosStage", "CHAOS_PRESETS", "HELD_OUT_PRESETS",
    "chaos_from_dict", "load_chaos_json", "make_chaos_preset", "ramp_stages",
]

"""Fault-injection & recovery subsystem for the scanned engine.

Declarative, jit-compatible fault schedules (DC outages, frequency-derating
stragglers, WAN degradation, stochastic MTBF/MTTR clocks) compiled into
fixed-shape timelines threaded through ``SimState`` — see ``docs/faults.md``.
"""

from .schedule import init_fault_state, timeline_len  # noqa: F401
from .state import (  # noqa: F401
    FAULT_KIND_NAMES,
    FK_DC_DOWN,
    FK_DC_UP,
    FK_DERATE,
    FK_NONE,
    FK_WAN,
    FaultParams,
    FaultState,
)

__all__ = [
    "FaultParams", "FaultState", "init_fault_state", "timeline_len",
    "FAULT_KIND_NAMES", "FK_NONE", "FK_DC_DOWN", "FK_DC_UP", "FK_DERATE",
    "FK_WAN",
]

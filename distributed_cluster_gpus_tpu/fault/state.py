"""Fault-model state: declarative spec (static) + compiled timeline (dynamic).

The fault subsystem models the defining property of geo-distributed
infrastructure — things fail — as data, not control flow:

* :class:`FaultParams` is the *declarative spec*: per-DC outage windows,
  per-DC frequency-derating ("straggler") windows, per-WAN-edge latency/
  loss degradation windows, and an optional stochastic mode driven by
  per-DC MTBF/MTTR exponential clocks.  It is a frozen hashable dataclass
  carried on ``SimParams`` so a different fault spec re-specializes the
  compiled step exactly like any other static run-shape knob.
* :class:`FaultState` is the *compiled timeline*: the spec lowered (at
  ``init_state`` time, see ``fault/schedule.py``) into fixed-shape sorted
  event arrays plus the dynamic capacity masks they drive.  It lives
  inside ``SimState``, so whole fault trajectories vmap across rollout
  batches — a vmapped batch of lanes with different stochastic keys
  realizes independent fault schedules with zero host involvement.

The engine consumes the timeline as a fifth event class (``EV_FAULT``)
in its next-event min: ``times[cursor]`` is the next transition, and the
fault branch applies it as predicated mask updates (no ring writes, no
data-dependent shapes).  With ``SimParams.faults`` unset the engine
compiles byte-identically to the fault-free program — zero-fault runs
are bit-identical to the pre-fault engine by construction (pinned by
``tests/test_fault.py::test_zero_fault_schedule_bit_identical``).
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Optional, Tuple

import jax.numpy as jnp
from flax import struct

if TYPE_CHECKING:  # annotation only: curricula ride FaultParams
    from .curriculum import ChaosCurriculum

# fault-event kinds (FaultState.kind codes)
FK_NONE = -1  # padding entry; never fires (time = +inf)
FK_DC_DOWN = 0  # value unused
FK_DC_UP = 1  # value unused
FK_DERATE = 2  # value = max allowed ladder index (float-encoded int)
FK_WAN = 3  # idx = ing * n_dc + dc, value = latency/transfer multiplier

FAULT_KIND_NAMES = {FK_DC_DOWN: "dc_down", FK_DC_UP: "dc_up",
                    FK_DERATE: "derate", FK_WAN: "wan_degrade"}


@dataclasses.dataclass(frozen=True)
class FaultParams:
    """Declarative fault schedule (static run shape; hashable for jit).

    Window entries use simulated seconds and fleet indices:

    * ``outages``: ``(dc, start, end)`` — the DC loses all capacity on
      ``[start, end)``: running jobs are preempted at onset and drained
      through the migration path, placement/routing masks exclude the DC,
      and recovery re-admits its queued work.
    * ``derates``: ``(dc, start, end, f_cap)`` — straggler hardware: the
      DC's effective DVFS ladder is clamped to the level nearest
      ``f_cap`` for the window (running jobs are clamped at onset; jobs
      started during the window are clamped at start; the clamp lifts at
      ``end`` for *new* starts — already-clamped jobs keep their
      frequency until a controller or restart raises it).
    * ``wan``: ``(ingress, dc, start, end, lat_mult, loss)`` — the WAN
      edge's propagation latency and transfer time are multiplied by
      ``lat_mult / (1 - loss)`` for the window (loss is folded into the
      latency multiplier via the retransmit model,
      :func:`~distributed_cluster_gpus_tpu.network.loss_latency_multiplier`).
    * ``mtbf_s > 0`` enables the stochastic mode: each DC additionally
      draws up to ``max_outages_per_dc`` outage windows from alternating
      Exponential(mtbf_s) up-spans and Exponential(mttr_s) down-spans,
      sampled from a dedicated fold of the rollout's PRNG key — so fault
      realizations are a pure function of the seed (identical across
      algorithms, independent across vmapped rollouts).
    """

    enabled: bool = True
    outages: Tuple[Tuple[int, float, float], ...] = ()
    derates: Tuple[Tuple[int, float, float, float], ...] = ()
    wan: Tuple[Tuple[int, int, float, float, float, float], ...] = ()
    mtbf_s: float = 0.0
    mttr_s: float = 300.0
    max_outages_per_dc: int = 4
    # randomized chaos curriculum (fault/curriculum.py): per-lane MTBF/
    # MTTR / derate / WAN-degradation *distributions* with severity
    # stages, lowered into this same timeline at init; None adds nothing
    curriculum: Optional["ChaosCurriculum"] = None

    def __post_init__(self):
        def no_overlap(windows, what):
            # derate/WAN off-events are stateless resets (no nesting
            # counter like outages have), so overlapping windows on one
            # target would restore the resource while a window is still
            # open — reject them at spec time
            for tgt, spans in windows.items():
                spans.sort()
                for (s0, e0), (s1, _) in zip(spans, spans[1:]):
                    if s1 < e0:
                        raise ValueError(
                            f"overlapping {what} windows on target {tgt}: "
                            f"[{s0}, {e0}) and starting {s1}")

        for dc, s, e in self.outages:
            if e <= s:
                raise ValueError(f"outage window ({dc}, {s}, {e}): end <= start")
        derate_by_dc, wan_by_edge = {}, {}
        for dc, s, e, f_cap in self.derates:
            if e <= s:
                raise ValueError(f"derate window ({dc}, {s}, {e}): end <= start")
            if f_cap <= 0:
                raise ValueError(f"derate f_cap must be positive, got {f_cap}")
            derate_by_dc.setdefault(dc, []).append((s, e))
        for ing, dc, s, e, mult, loss in self.wan:
            if e <= s:
                raise ValueError(f"wan window ({ing}->{dc}, {s}, {e}): end <= start")
            if mult < 1.0:
                raise ValueError(f"wan lat_mult must be >= 1, got {mult}")
            if not 0.0 <= loss < 1.0:
                raise ValueError(f"wan loss must be in [0, 1), got {loss}")
            wan_by_edge.setdefault((ing, dc), []).append((s, e))
        no_overlap(derate_by_dc, "derate")
        no_overlap(wan_by_edge, "wan")
        if self.mtbf_s < 0 or self.mttr_s <= 0:
            raise ValueError("mtbf_s must be >= 0 and mttr_s > 0")
        if self.max_outages_per_dc < 1:
            raise ValueError("max_outages_per_dc must be >= 1")

    @property
    def n_events(self) -> int:
        """Static timeline length (each window is an on + an off event)."""
        n = 2 * (len(self.outages) + len(self.derates) + len(self.wan))
        return n  # stochastic events are added per-fleet in schedule.py


@struct.dataclass
class FaultState:
    """Compiled fault timeline + dynamic degradation masks (in SimState).

    The timeline arrays (``times``/``kind``/``idx``/``value``) are sorted
    by time and +inf-padded; ``cursor`` indexes the next un-fired
    transition, so the engine's next-event candidate is one gather.
    """

    times: jnp.ndarray  # [M] time-dtype, sorted ascending, inf padded
    kind: jnp.ndarray  # [M] int32 FK_* codes
    idx: jnp.ndarray  # [M] int32 dc index (or ing * n_dc + dc for FK_WAN)
    value: jnp.ndarray  # [M] f32 (derate ladder index / WAN multiplier)
    cursor: jnp.ndarray  # int32 next timeline entry to fire
    # dynamic degradation masks the engine reads every step
    dc_up: jnp.ndarray  # [n_dc] bool — False while the DC is down
    # outage nesting depth: overlapping windows (declarative x stochastic)
    # may each fire their own down/up pair; the DC is up only at depth 0,
    # so an inner window's recovery cannot prematurely restore the DC
    down_depth: jnp.ndarray  # [n_dc] int32
    derate_f_idx: jnp.ndarray  # [n_dc] int32 max allowed ladder index
    wan_mult: jnp.ndarray  # [n_ing, n_dc] f32 latency/transfer multiplier
    # degraded-mode accounting
    n_preempted: jnp.ndarray  # int32 jobs preempted by outage onsets
    n_migrated: jnp.ndarray  # int32 preempted jobs re-queued at an up DC
    n_failed: jnp.ndarray  # int32 preempted jobs dropped (no up DC existed)
    n_outages: jnp.ndarray  # [n_dc] int32 outage onsets seen
    downtime: jnp.ndarray  # [n_dc] time-dtype accumulated down seconds

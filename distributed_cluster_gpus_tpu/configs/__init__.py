from .paper import (SUPERSTEP_K_CANONICAL, build_duo_fleet, build_fleet,
                    build_single_dc_fleet, superstep_params,
                    DC_GPUS_DISPLAY, GW_ALPHABET)

__all__ = ["build_duo_fleet", "build_fleet", "build_single_dc_fleet",
           "DC_GPUS_DISPLAY",
           "GW_ALPHABET", "SUPERSTEP_K_CANONICAL", "superstep_params"]

from .paper import build_fleet, build_single_dc_fleet, DC_GPUS_DISPLAY, GW_ALPHABET

__all__ = ["build_fleet", "build_single_dc_fleet", "DC_GPUS_DISPLAY", "GW_ALPHABET"]

"""The paper world: fleet, DVFS coefficients, WAN topology, carbon, prices.

Same world facts as the reference (`/root/reference/configs/paper_config.py`),
re-expressed as dense arrays for the jitted engine: 8 DCs (1,488 GPUs across
8 GPU models), a shared 8-level DVFS ladder f in {0.3..1.0}, per-(DC, jtype)
cubic power / hyperbolic latency coefficients, 8 ingress gateways over a WAN
latency graph (collapsed at build time to [n_ing, n_dc] matrices via host
Dijkstra), carbon intensity for 3 DCs and a global hourly energy price.
"""

from __future__ import annotations

import numpy as np

from ..models.structs import FleetSpec, N_JTYPE
from ..network import Graph, Ingress, precompute_net_matrices
from ..ops.optimizers import nf_energy_table
from ..ops.physics import LatencyCoeffs, PowerCoeffs

FREQ_LEVELS = (0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)

# name -> (p_idle, p_peak, p_sleep, alpha)
GPU_TYPES = {
    "A100-SXM4": (50.0, 400.0, 30.0, 3.0),
    "A100-PCIe": (45.0, 300.0, 28.0, 3.0),
    "H100-SXM5": (55.0, 700.0, 35.0, 3.0),
    "H100-PCIe": (45.0, 350.0, 28.0, 3.0),
    "H200-SXM": (60.0, 700.0, 38.0, 3.0),
    "H200-PCIe": (55.0, 600.0, 35.0, 3.0),
    "L4": (15.0, 72.0, 8.0, 3.0),
    "T4": (10.0, 70.0, 6.0, 3.0),
    "A10": (20.0, 150.0, 10.0, 3.0),
    "A30": (25.0, 165.0, 12.0, 3.0),
    "A40": (40.0, 300.0, 25.0, 3.0),
    "L40": (35.0, 300.0, 20.0, 3.0),
    "L40S": (40.0, 350.0, 25.0, 3.0),
}

# dc -> (gpu model, count)
FLEET = {
    "us-west": ("H100-PCIe", 16),
    "us-east": ("A100-PCIe", 32),
    "eu-west": ("L40S", 256),
    "eu-central": ("H100-SXM5", 16),
    "ap-southeast": ("L4", 128),
    "ap-northeast": ("H200-PCIe", 16),
    "sa-east": ("A30", 512),
    "me-central": ("A10", 512),
}

# (dc, jtype) -> ((alpha_p, beta_p, gamma_p), (alpha_t, beta_t, gamma_t))
# jtype: "training" | "inference"
COEFFS = {
    ("us-west", "training"): ((75.0, 80.0, 110.0), (0.0045, 0.032, 0.0012)),
    ("us-west", "inference"): ((95.0, 20.0, 97.0), (0.0090, 0.0018, 0.0007)),
    ("us-east", "training"): ((65.0, 60.0, 90.0), (0.0050, 0.038, 0.0014)),
    ("us-east", "inference"): ((85.0, 18.0, 80.0), (0.0080, 0.0020, 0.0009)),
    ("eu-west", "training"): ((55.0, 40.0, 70.0), (0.0060, 0.045, 0.0018)),
    ("eu-west", "inference"): ((70.0, 15.0, 60.0), (0.0050, 0.020, 0.0010)),
    ("eu-central", "training"): ((90.0, 85.0, 120.0), (0.0042, 0.030, 0.0011)),
    ("eu-central", "inference"): ((100.0, 22.0, 100.0), (0.0085, 0.0017, 0.0007)),
    ("ap-southeast", "training"): ((45.0, 20.0, 40.0), (0.0065, 0.060, 0.0022)),
    ("ap-southeast", "inference"): ((40.0, 12.0, 35.0), (0.0045, 0.025, 0.0012)),
    ("ap-northeast", "training"): ((95.0, 90.0, 125.0), (0.0040, 0.029, 0.0010)),
    ("ap-northeast", "inference"): ((105.0, 25.0, 105.0), (0.0080, 0.0016, 0.0006)),
    ("sa-east", "training"): ((50.0, 35.0, 65.0), (0.0062, 0.050, 0.0019)),
    ("sa-east", "inference"): ((65.0, 14.0, 55.0), (0.0055, 0.022, 0.0011)),
    ("me-central", "training"): ((40.0, 25.0, 50.0), (0.0068, 0.055, 0.0023)),
    ("me-central", "inference"): ((55.0, 12.0, 45.0), (0.0050, 0.023, 0.0012)),
}

# Coefficients calibrated for the 1-DC debug topology (reference single-DC
# variant: us-west with 128 x H100-PCIe).
SINGLE_DC_COEFFS = {
    ("us-west", "training"): ((75.0, 80.0, 110.0), (0.0005, 0.05, 0.0003)),
    ("us-west", "inference"): ((95.0, 20.0, 97.0), (0.002, 0.004, 0.0001)),
}

# Symmetric ingress<->DC latencies (ms). Each entry adds both directions.
WAN_EDGES_MS = [
    ("gw-us-west", "us-west", 12),
    ("gw-us-west", "us-east", 70),
    ("gw-us-west", "eu-central", 110),
    ("gw-us-west", "ap-southeast", 150),
    ("gw-us-east", "us-east", 10),
    ("gw-us-east", "us-west", 70),
    ("gw-us-east", "eu-west", 90),
    ("gw-us-east", "sa-east", 110),
    ("gw-eu-west", "eu-west", 10),
    ("gw-eu-west", "eu-central", 20),
    ("gw-eu-west", "us-east", 90),
    ("gw-eu-west", "ap-northeast", 190),
    ("gw-eu-central", "eu-central", 10),
    ("gw-eu-central", "me-central", 60),
    ("gw-eu-central", "ap-southeast", 170),
    ("gw-ap-southeast", "ap-southeast", 8),
    ("gw-ap-southeast", "ap-northeast", 60),
    ("gw-ap-southeast", "eu-central", 170),
    ("gw-ap-northeast", "ap-northeast", 8),
    ("gw-ap-northeast", "us-west", 130),
    ("gw-ap-northeast", "eu-west", 190),
    ("gw-sa-east", "sa-east", 12),
    ("gw-sa-east", "us-east", 110),
    ("gw-sa-east", "eu-west", 150),
    ("gw-me-central", "me-central", 10),
    ("gw-me-central", "eu-central", 60),
    ("gw-me-central", "ap-southeast", 120),
]

INGRESS_REGIONS = {
    "gw-us-west": "US",
    "gw-us-east": "US",
    "gw-eu-west": "EU",
    "gw-eu-central": "EU",
    "gw-ap-southeast": "APAC",
    "gw-ap-northeast": "APAC",
    "gw-sa-east": "SA",
    "gw-me-central": "ME",
}

CARBON_INTENSITY = {  # gCO2/kWh; DCs not listed default to 0.0
    "us-west": 350.0,
    "eu-central": 220.0,
    "ap-southeast": 500.0,
}


def energy_price_hourly() -> np.ndarray:
    """USD/kWh by hour of day: off-peak 0.12, peak 0.20, evening 0.16."""
    price = np.empty(24, dtype=np.float32)
    price[0:7] = 0.12
    price[7:19] = 0.20
    price[19:24] = 0.16
    return price


# Display-name maps (plotting parity with the reference).
DC_GPUS_DISPLAY = {dc: f"{count} x {gpu}" for dc, (gpu, count) in FLEET.items()}
GW_ALPHABET = {
    "gw-us-west": "A",
    "gw-us-east": "B",
    "gw-sa-east": "C",
    "gw-me-central": "D",
    "gw-eu-west": "E",
    "gw-eu-central": "F",
    "gw-ap-southeast": "G",
    "gw-ap-northeast": "H",
}

JTYPE_NAMES = ("inference", "training")


def _build_spec(fleet, coeffs, edges, ingress_regions, carbon, n_max: int) -> FleetSpec:
    dc_names = tuple(fleet.keys())
    ingress_names = tuple(ingress_regions.keys())
    n_dc = len(dc_names)

    gpu_names, totals, p_idle, p_peak, p_sleep, alpha = [], [], [], [], [], []
    for dc in dc_names:
        gpu, count = fleet[dc]
        pi, pp, ps, al = GPU_TYPES[gpu]
        gpu_names.append(gpu)
        totals.append(count)
        p_idle.append(pi)
        p_peak.append(pp)
        p_sleep.append(ps)
        alpha.append(al)

    pw = np.zeros((n_dc, N_JTYPE, 3), dtype=np.float32)
    lt = np.zeros((n_dc, N_JTYPE, 3), dtype=np.float32)
    for d, dc in enumerate(dc_names):
        for j, jt in enumerate(JTYPE_NAMES):
            pw[d, j], lt[d, j] = coeffs[(dc, jt)]
    power = PowerCoeffs(pw[..., 0], pw[..., 1], pw[..., 2])
    latency = LatencyCoeffs(lt[..., 0], lt[..., 1], lt[..., 2])

    g = Graph()
    for u, v, ms in edges:
        g.add_edge(u, v, ms)
        g.add_edge(v, u, ms)
    net = precompute_net_matrices(g, list(ingress_names), list(dc_names))

    freq = np.asarray(FREQ_LEVELS, dtype=np.float32)
    T, P, E = nf_energy_table(n_max, freq, power, latency)

    return FleetSpec(
        dc_names=dc_names,
        ingress_names=ingress_names,
        gpu_names=tuple(gpu_names),
        total_gpus=np.asarray(totals, dtype=np.int32),
        p_idle=np.asarray(p_idle, dtype=np.float32),
        p_peak=np.asarray(p_peak, dtype=np.float32),
        p_sleep=np.asarray(p_sleep, dtype=np.float32),
        gpu_alpha=np.asarray(alpha, dtype=np.float32),
        power_gating=np.ones(n_dc, dtype=bool),
        freq_levels=freq,
        default_f_idx=len(FREQ_LEVELS) - 1,  # default_freq = 1.0
        power=power,
        latency=latency,
        carbon=np.asarray([carbon.get(dc, 0.0) for dc in dc_names], dtype=np.float32),
        price_hourly=energy_price_hourly(),
        net_lat_s=net["net_lat_s"].astype(np.float32),
        transfer_s=net["transfer_s"].astype(np.float32),
        T_grid=np.asarray(T, dtype=np.float32),
        P_grid=np.asarray(P, dtype=np.float32),
        E_grid=np.asarray(E, dtype=np.float32),
    )


# ---------------------------------------------------------------------------
# Superstep preset (engine event coalescing; docs/perf_notes.md round 6)
# ---------------------------------------------------------------------------

# Canonical superstep width for throughput runs of the heuristic
# algorithms.  Round-7 (select-free unified body) CPU sweep
# (bench_results/superstep_r07.json, 5 interleaved-median reps): K=4
# measures +42% events/s over K=1 and K=8 +31% (the round-6 two-lane
# body managed +16% at K=4 and REGRESSED at K=2/8); K=4 stays canonical
# — it compiles the smaller program and delivers more of its structural
# curve (realized/structural 0.53 vs 0.33; the window fill, ~2.9 vs
# ~3.3 events/iteration on the paper world's 8 DCs, is the binding
# ceiling).  K=1 stays the default everywhere for exact parity with
# earlier rounds; results are bit-identical either way, so this is
# purely a throughput knob (run_sim.py --superstep-k).
SUPERSTEP_K_CANONICAL = 4


def superstep_params(params, k: int = SUPERSTEP_K_CANONICAL):
    """``params`` with the canonical superstep width applied."""
    import dataclasses

    return dataclasses.replace(params, superstep_k=k)


# ---------------------------------------------------------------------------
# Workload presets (workload/ subsystem; docs/workloads.md)
# ---------------------------------------------------------------------------

# Canonical production-shaped scenario for capacity-planning runs: the
# week-horizon multi-region diurnal + flash-crowd + correlated-surge
# workload with weekly tariff / diurnal carbon timelines (ROADMAP item
# 5; the J=8192 one-scan acceptance run).  run_sim.py exposes every
# preset as `--workload NAME`.
WORKLOAD_PRESET_CANONICAL = "diurnal_flash_week"


def week_workload_params(params, fleet, **preset_kw):
    """``params`` with the canonical week scenario applied: the
    `diurnal_flash_week` workload spec, week duration, float64 clock,
    and an hourly log cadence — the shape scripts/campaigns should run
    for trace-driven capacity planning."""
    import dataclasses

    from ..workload import make_preset

    spec = make_preset(WORKLOAD_PRESET_CANONICAL, fleet, **preset_kw)
    return dataclasses.replace(
        params, workload=spec, duration=7 * 86400.0,
        log_interval=3600.0, time_dtype="float64")


# ---------------------------------------------------------------------------
# Chaos / fault-injection presets (fault/ subsystem; docs/faults.md)
# ---------------------------------------------------------------------------

# canonical repair time for stochastic chaos runs: 5 simulated minutes,
# the order of an automated failover + reimage cycle
CHAOS_MTTR_S = 300.0


def build_chaos_faults(rate_per_dc_hour: float, duration: float,
                       mttr_s: float = CHAOS_MTTR_S):
    """Stochastic FaultParams for a chaos run at a given failure rate.

    ``rate_per_dc_hour`` is the expected number of outages per DC per
    simulated hour (MTBF = 3600 / rate); 0 returns an enabled-but-empty
    schedule (the bit-identical golden baseline).  The per-DC window
    budget is sized to ~3x the expected outage count over ``duration`` so
    the realized schedule is effectively never truncated.
    """
    from ..models.structs import FaultParams

    if rate_per_dc_hour <= 0:
        return FaultParams()
    mtbf_s = 3600.0 / rate_per_dc_hour
    expect = duration / (mtbf_s + mttr_s)
    return FaultParams(
        mtbf_s=mtbf_s,
        mttr_s=mttr_s,
        max_outages_per_dc=max(2, int(np.ceil(expect * 3)) + 1),
    )


# Canonical chaos-training curriculum for availability-aware CHSAC
# campaigns (fault/curriculum.py, rl/campaign.py): the mixed_ramp
# preset — all three incident families with a 3-stage severity ramp.
# `run_sim.py --campaign` defaults its --chaos to this.  Held-out
# evaluation runs on fault.HELD_OUT_PRESETS, which no training path
# references (the campaign driver enforces it).
CHAOS_CURRICULUM_CANONICAL = "mixed_ramp"


# a deterministic single-incident scenario on the canonical fleet: the
# largest DC (sa-east, 512 GPUs) goes dark mid-run, eu-west straggles at
# 0.6 of the ladder, and the us-east gateway's shortest edge degrades —
# the smallest schedule that exercises all three fault kinds end to end
def build_incident_faults(t0: float = 600.0, dt: float = 600.0):
    """One outage + one derate + one WAN degradation window from ``t0``."""
    from ..models.structs import FaultParams

    dc_names = tuple(FLEET.keys())
    ing_names = tuple(INGRESS_REGIONS.keys())
    return FaultParams(
        outages=((dc_names.index("sa-east"), t0, t0 + dt),),
        derates=((dc_names.index("eu-west"), t0, t0 + dt, 0.6),),
        wan=((ing_names.index("gw-us-east"), dc_names.index("us-east"),
              t0, t0 + dt, 4.0, 0.2),),
    )


def build_fleet(n_max: int = 8) -> FleetSpec:
    """The canonical 8-DC / 8-ingress paper world."""
    return _build_spec(FLEET, COEFFS, WAN_EDGES_MS, INGRESS_REGIONS, CARBON_INTENSITY, n_max)


def build_single_dc_fleet(n_max: int = 8) -> FleetSpec:
    """The 1-DC debug world: us-west with 128 x H100-PCIe, one gateway."""
    fleet = {"us-west": ("H100-PCIe", 128)}
    edges = [("gw-us-west", "us-west", 12)]
    regions = {"gw-us-west": "US"}
    return _build_spec(fleet, SINGLE_DC_COEFFS, edges, regions, {}, n_max)


def build_duo_fleet(n_max: int = 4) -> FleetSpec:
    """The tiny 2-DC / 2-ingress world the fault/obs/chaos suites (and
    `chaos_sweep.py --tiny`) share: fast compiles, enough topology for
    migration and WAN degradation.  One builder so the shape cannot
    drift between its consumers."""
    fleet = {"us-west": ("H100-PCIe", 16), "us-east": ("A100-PCIe", 16)}
    edges = [e for e in WAN_EDGES_MS
             if e[0] in ("gw-us-west", "gw-us-east")
             and e[1] in ("us-west", "us-east")]
    regions = {k: v for k, v in INGRESS_REGIONS.items()
               if k in ("gw-us-west", "gw-us-east")}
    return _build_spec(fleet, COEFFS, edges, regions, {}, n_max)

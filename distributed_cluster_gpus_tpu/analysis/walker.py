"""One shared flatten/visit core over closed jaxprs.

Every structural consumer in this repo — the eqn-ceiling pins
(tests/test_perf_structure.py), the per-class op census
(scripts/count_step_ops.py), the bench probes (bench.flat_eqn_count),
and the lint rules (analysis.rules) — must flatten a jaxpr with the SAME
rule, or their numbers stop being comparable and a banked census can no
longer be diffed against a pinned ceiling.  This module is that one
rule:

    for each eqn, count it once, then recurse into every sub-jaxpr
    reachable through its params (cond branches, scan/while bodies,
    pjit/closed_call wrappers), including sub-jaxprs nested in
    list/tuple-valued params.

:func:`iter_eqns` is the generalized visitor the lint rules build on: it
yields every equation exactly once together with its *structural
context* — the param path from the root, whether the eqn sits inside a
cond/switch branch (where, under vmap, it executes every step), and
whether it sits inside a scan/while body.  :func:`flat_count`,
:func:`primitives`, and :func:`op_census` are the three historical
consumers re-expressed over the same walk.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple

# census classes: jaxpr primitive names -> the class we report.  Anything
# not listed lands in "other" (the census always partitions: sum of
# classes == eqns).
CENSUS_CLASSES = {
    "scatter": ("scatter", "scatter-add", "scatter-mul", "scatter-min",
                "scatter-max"),
    "gather": ("gather", "dynamic_slice"),
    "select": ("select_n",),
    "while": ("while",),
    "cond": ("cond",),
    "scan": ("scan",),
    "dus": ("dynamic_update_slice",),
    "dot": ("dot_general", "conv_general_dilated"),
    "reduce": ("reduce_sum", "reduce_max", "reduce_min", "reduce_and",
               "reduce_or", "argmax", "argmin", "reduce_precision"),
}
_PRIM_TO_CLASS = {p: c for c, ps in CENSUS_CLASSES.items() for p in ps}


def subjaxprs(eqn):
    """Yield ``(param_name, jaxpr)`` for every sub-jaxpr in an eqn's params.

    The historical flattening rule, verbatim: any param value (or element
    of a list/tuple param) carrying a ``.jaxpr`` attribute — ClosedJaxpr
    params of cond branches, scan/while bodies, pjit wrappers — counts as
    one nested program to recurse into.
    """
    for name, v in eqn.params.items():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for i, x in enumerate(vs):
            if hasattr(x, "jaxpr"):
                label = name if not isinstance(v, (list, tuple)) \
                    else f"{name}[{i}]"
                yield label, x.jaxpr


class EqnCtx(NamedTuple):
    """One equation plus its structural context in the walked program."""

    eqn: object          # jax.core.JaxprEqn
    jaxpr: object        # the (sub-)jaxpr this eqn belongs to
    path: str            # "/"-joined param path from the root jaxpr
    in_branch: bool      # inside a cond/switch branch sub-jaxpr
    in_loop: bool        # inside a scan/while body sub-jaxpr
    depth: int           # sub-jaxpr nesting depth (root = 0)


def iter_eqns(jaxpr, path: str = "", in_branch: bool = False,
              in_loop: bool = False, depth: int = 0) -> Iterator[EqnCtx]:
    """Depth-first walk yielding every eqn exactly once, with context.

    The visit order (eqn before its sub-jaxprs, params in dict order)
    matches the historical counters, so ``sum(1 for _ in iter_eqns(j))
    == flat_count(j)`` by construction.
    """
    for q in jaxpr.eqns:
        yield EqnCtx(q, jaxpr, path, in_branch, in_loop, depth)
        branch = in_branch or q.primitive.name == "cond"
        loop = in_loop or q.primitive.name in ("scan", "while")
        for label, sub in subjaxprs(q):
            sub_path = f"{path}/{q.primitive.name}.{label}" if path \
                else f"{q.primitive.name}.{label}"
            yield from iter_eqns(sub, sub_path, branch, loop, depth + 1)


def iter_jaxprs(jaxpr, path: str = "", in_branch: bool = False,
                in_loop: bool = False):
    """Yield ``(jaxpr, path, in_branch, in_loop)`` for the root and every
    nested sub-jaxpr — for rules that analyze per-scope dataflow (each
    scope's vars are internally consistent; vars never cross scopes)."""
    yield jaxpr, path, in_branch, in_loop
    for q in jaxpr.eqns:
        branch = in_branch or q.primitive.name == "cond"
        loop = in_loop or q.primitive.name in ("scan", "while")
        for label, sub in subjaxprs(q):
            sub_path = f"{path}/{q.primitive.name}.{label}" if path \
                else f"{q.primitive.name}.{label}"
            yield from iter_jaxprs(sub, sub_path, branch, loop)


def flat_count(jaxpr) -> int:
    """Recursively flattened eqn count — the dispatch-bound step's
    first-order cost model (the metric every ceiling pins)."""
    return sum(1 for _ in iter_eqns(jaxpr))


def primitives(jaxpr) -> set:
    """Set of primitive names anywhere in the flattened program."""
    return {c.eqn.primitive.name for c in iter_eqns(jaxpr)}


def op_census(jaxpr, acc=None) -> dict:
    """Recursively flattened per-class eqn counts (+ ``"eqns"`` total).

    Counts every eqn exactly once with the same flattening rule as
    :func:`flat_count`, so ``census["eqns"]`` is directly comparable to
    the pinned ceilings; the classes always partition the total."""
    if acc is None:
        acc = {c: 0 for c in CENSUS_CLASSES}
        acc["other"] = 0
        acc["eqns"] = 0
    for c in iter_eqns(jaxpr):
        acc["eqns"] += 1
        acc[_PRIM_TO_CLASS.get(c.eqn.primitive.name, "other")] += 1
    return acc


def main_scan_body(jpr, length: int):
    """The main event-scan body of a traced ``_run_chunk(..., length)``.

    The largest length-``length`` scan carries the SimState (61+ carries);
    the workload pregen adds its tiny prefix-fold scan (and, for thinning
    streams only, a sequential replay scan) ahead of it.  Returns the
    scan EQN (so callers can read num_consts/num_carry); use ``.params
    ["jaxpr"].jaxpr`` for the body."""
    scans = [q for q in jpr.jaxpr.eqns
             if q.primitive.name == "scan" and q.params["length"] == length]
    if not scans:
        raise ValueError(f"no length-{length} scan in the traced program")
    return max(scans, key=lambda q: len(q.params["jaxpr"].jaxpr.eqns))


def chunk_scans(jpr, length: int):
    """All length-``length`` scan eqns of a traced chunk (event scan +
    pregen prologue folds), largest-body last position preserved."""
    return [q for q in jpr.jaxpr.eqns
            if q.primitive.name == "scan" and q.params["length"] == length]

"""Declarative lint rules over traced step programs.

Each rule turns one structural invariant this repo has already paid to
learn (see docs/static_analysis.md for the full catalog and the bug each
rule is grounded in) into a checked predicate over the traced jaxpr of a
canonical engine configuration.  Rules are registered with an id and a
severity; violations can be suppressed per (rule, config, site) through
``ALLOWLIST`` — every entry MUST carry a written reason, and the test
suite enforces that.

The rules build on :mod:`analysis.walker` (the one shared flattening
rule) plus a cross-scope dataflow graph (:func:`build_graph`): jax
hoists constants and wraps subcomputations in ``pjit``/``cond`` scopes,
so a fence pattern like ``fmul_pinned``'s zero-multiply can be produced
in one scope and consumed in another — per-scope pattern matching alone
would both miss real violations and report false ones.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from typing import Callable, Optional

from . import walker

SEV_ERROR = "error"
SEV_WARN = "warn"

# primitives that round-trip through the host — forbidden anywhere in a
# compiled step program (they serialize the scan and break AOT/TPU runs)
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "callback", "outside_call",
    "host_callback_call", "debug_callback", "debug_print",
})

# PRNG primitives that CONSUME a key (advance/derive from it); using one
# key var in two of these is a correlated-stream bug.  random_wrap /
# random_unwrap / key_data only reinterpret bits and are exempt.
KEY_CONSUMERS = frozenset({
    "random_bits", "random_split", "random_fold_in", "random_gamma",
})

# dataflow chain primitives an accumulator value flows through between a
# product and the carry it lands in (masking, clamping, dtype changes,
# tree reductions); anything else ends the accumulation chain
ACC_CHAIN_PRIMS = frozenset({
    "add", "sub", "select_n", "max", "min", "convert_element_type",
    "reduce_sum", "reduce_min", "reduce_max",
})


def is_literal(v) -> bool:
    return hasattr(v, "val")


def src_of(eqn) -> str:
    """``file.py:line (fn)`` of the user frame that built this eqn."""
    try:
        from jax._src import source_info_util

        s = source_info_util.summarize(eqn.source_info)
        # trim the absolute repo prefix so reports are path-stable
        return s.split("/repo/")[-1] if "/repo/" in s else s
    except Exception:  # noqa: BLE001 - source info is best-effort
        return "?"


@dataclass(frozen=True)
class Violation:
    rule: str
    severity: str
    config: str
    where: str     # jaxpr path and/or source site
    message: str

    def as_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "config": self.config, "where": self.where,
                "message": self.message}


@dataclass(frozen=True)
class Allow:
    """One allowlist entry: (rule, config glob, site substring) -> reason.

    ``reason`` is MANDATORY prose — the linter refuses to load an entry
    without one (tests/test_lint.py pins it), so every suppression in
    this file documents why the hit is deliberate, not just that it is.
    """

    rule: str
    config: str   # fnmatch glob over canonical config names
    match: str    # substring of the violation's where+message
    reason: str

    def covers(self, v: Violation) -> bool:
        return (self.rule == v.rule
                and fnmatch.fnmatch(v.config, self.config)
                and self.match in f"{v.where} {v.message}")


# ---------------------------------------------------------------------------
# The per-rule allowlist.  Keep this SHORT: an entry is a debt note, and
# the reason string is its interest statement.  New entries need the same
# scrutiny as a golden update.
# ---------------------------------------------------------------------------
ALLOWLIST = [
    Allow(
        rule="f32-counter-overflow",
        config="*",
        match="_handle_log",
        reason="next_log_t += log_interval follows SimParams.time_dtype; "
               "float32 is the paper-scale default and float64 is the "
               "documented long-horizon mode (docs/log_schema.md, "
               "TestTimeDtype) — the tick counter is bounded by duration, "
               "not by event count, and the dtype switch is the supported "
               "fix when it is not.",
    ),
    Allow(
        rule="weak-type-promotion",
        config="*",
        match="sinusoid_gap_from_cum",
        reason="jax.lax.fori_loop canonicalizes its trip counter to weak "
               "int64 under jax_enable_x64 regardless of the bound dtypes "
               "(verified: np.int32 bounds still trace an i64 carry) — "
               "not user-pinnable.  The bisection loop's carried VALUES "
               "(gap-time brackets) are explicit f32/td arrays, so the "
               "counter width never reaches state.",
    ),
    Allow(
        rule="no-while-in-step",
        config="chsac_af+elastic*",
        match="_elastic_reallocate",
        reason="elastic scaling re-places a DATA-DEPENDENT number of "
               "preempted training jobs FIFO through the policy network "
               "(engine._elastic_reallocate) — a dynamic-trip loop by "
               "design, bounded by job_cap.  The accepted cost of the "
               "elastic feature (see the ELASTIC_MIGRATE_PER_STEP note); "
               "every other config family keeps the zero-while pin.",
    ),
    Allow(
        rule="unfenced-float-product",
        config="chsac*",
        match="select_action",
        reason="jax.random.categorical's internal gumbel arithmetic "
               "(rl/sac.py select_action) cannot be fenced from user "
               "code; the sampled actions are integers and the chsac "
               "planner-vs-legacy byte-identity goldens "
               "(tests/test_write_plan.py) are the behavioral guard for "
               "the policy tail.",
    ),
    Allow(
        rule="weak-type-promotion",
        config="*",
        match="_drain_queues",
        reason="same jax-internal fori_loop counter as the arrivals "
               "bisection: the drain loop's counter weak-types to int64 "
               "under x64 and cannot be pinned from user code; the drained "
               "state it carries is explicitly typed throughout.",
    ),
]

for _a in ALLOWLIST:
    if not _a.reason.strip():
        raise ValueError(f"allowlist entry {_a.rule}/{_a.config}/{_a.match} "
                         "has no reason — every suppression must say why")


@dataclass
class LintContext:
    """Everything a rule may inspect about one traced configuration."""

    config: str
    params: object
    k: int
    superstep_on: bool
    planner_on: bool
    forced_legacy: bool
    obs_on: bool
    jaxpr: object                 # full traced chunk program (open jaxpr)
    scan_eqn: object              # the main event-scan eqn
    body: object                  # its body jaxpr (the pinned step body)
    scans: list                   # all chunk-length scan eqns
    x64_jaxpr: object = None      # same program traced under enable_x64
    x64_error: Optional[str] = None
    baseline: Optional[dict] = None   # analysis/baselines.json entry
    headroom: float = 0.06
    const_map: Optional[dict] = None  # top-level constvar -> concrete value

    _graph: object = field(default=None, repr=False)

    def graph(self):
        if self._graph is None:
            self._graph = build_graph(self.body)
        return self._graph


class Graph:
    """Cross-scope dataflow over one jaxpr tree.

    ``producers`` maps every var to the eqn that defines it (across all
    nested scopes); ``alias`` maps sub-jaxpr boundary vars to the parent
    vars they are bound to (cond/pjit operands and outputs, scan consts),
    so :meth:`resolve` follows a value through scope walls — jax hoists
    loop-invariant work (including ``fmul_pinned``'s zero-multiply fence)
    out of branches, and rules must see through that."""

    def __init__(self):
        self.producers = {}
        self.alias = {}

    def resolve(self, v):
        seen = set()
        while v in self.alias and id(v) not in seen:
            seen.add(id(v))
            v = self.alias[v]
        return v

    def producer(self, v):
        return self.producers.get(self.resolve(v))


def _bind(graph, sub_vars, parent_vars):
    for s, p in zip(sub_vars, parent_vars):
        if not is_literal(s) and not is_literal(p):
            graph.alias[s] = p


def build_graph(root) -> Graph:
    g = Graph()

    def walk(jaxpr):
        for q in jaxpr.eqns:
            for ov in q.outvars:
                g.producers[ov] = q
            name = q.primitive.name
            subs = list(walker.subjaxprs(q))
            if name == "cond":
                # invars[0] is the branch index; operands feed each branch
                for _, sub in subs:
                    _bind(g, sub.invars, q.invars[1:])
                    _bind(g, q.outvars, sub.outvars)  # per-branch: last wins,
                    # good enough for reachability (branches are exclusive)
            elif name == "scan":
                nc = q.params.get("num_consts", 0)
                for _, sub in subs:
                    _bind(g, sub.invars[:nc], q.invars[:nc])
            elif name == "while":
                pass  # carries change per iteration; no sound alias
            else:
                # pjit / closed_call / custom_* wrappers: 1:1 boundary
                for _, sub in subs:
                    if len(sub.invars) == len(q.invars):
                        _bind(g, sub.invars, q.invars)
                    if len(sub.outvars) == len(q.outvars):
                        _bind(g, q.outvars, sub.outvars)
            for _, sub in subs:
                walk(sub)

    walk(root)
    return g


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    doc: str
    fn: Callable
    needs_x64: bool = False


RULES: dict = {}


def rule(rid: str, severity: str, doc: str, needs_x64: bool = False):
    def deco(fn):
        RULES[rid] = Rule(rid, severity, doc, fn, needs_x64)
        return fn
    return deco


def _v(ctx, rid, where, message) -> Violation:
    return Violation(rule=rid, severity=RULES[rid].severity,
                     config=ctx.config, where=where, message=message)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

@rule("no-while-in-step", SEV_ERROR,
      "No `while` primitive inside the scanned step body: under vmap "
      "every lane pays its max trip count every step, and the workload "
      "compiler pregenerates every stream precisely so no in-step draw "
      "loop exists (PR 3/6 invariant, pinned since round 10).")
def check_no_while_in_step(ctx):
    out = []
    for c in walker.iter_eqns(ctx.body):
        if c.eqn.primitive.name == "while":
            out.append(_v(ctx, "no-while-in-step",
                          f"{c.path or 'step-body'} @ {src_of(c.eqn)}",
                          "while_loop inside the scanned step body"))
    return out


@rule("select-free-superstep", SEV_ERROR,
      "K>1 superstep programs dispatch through ONE unified body — no "
      "cond/switch primitive anywhere in the chunk program.  Round 6's "
      "fused/singleton lax.cond lowered under vmap to a select executing "
      "BOTH bodies every iteration (docs/perf_notes.md round 7).")
def check_select_free_superstep(ctx):
    if ctx.k <= 1 or not ctx.superstep_on:
        return []
    out = []
    for c in walker.iter_eqns(ctx.jaxpr):
        if c.eqn.primitive.name == "cond":
            out.append(_v(ctx, "select-free-superstep",
                          f"{c.path or 'chunk'} @ {src_of(c.eqn)}",
                          f"cond primitive in a K={ctx.k} superstep "
                          "program — the select-free unified body "
                          "regressed to branch dispatch"))
    return out


@rule("host-callback-in-graph", SEV_ERROR,
      "No host round-trip primitives (pure_callback/io_callback/"
      "debug_print/...) inside the compiled chunk: they serialize the "
      "scan, break donation, and hang AOT TPU dispatch.")
def check_host_callback(ctx):
    out = []
    for c in walker.iter_eqns(ctx.jaxpr):
        if c.eqn.primitive.name in CALLBACK_PRIMS:
            out.append(_v(ctx, "host-callback-in-graph",
                          f"{c.path or 'chunk'} @ {src_of(c.eqn)}",
                          f"{c.eqn.primitive.name} primitive in the "
                          "compiled chunk program"))
    return out


def _zero_mul(graph, v):
    """Is (resolved) v the product of ``x * 0.0`` — fmul_pinned's fence?

    The non-zero factor must be a RUNTIME var: a literal-times-literal
    "fence" is constant-folded away by XLA (fmul_pinned docstring), so
    recognizing it here would bless a pin that does not exist."""
    q = graph.producer(v)
    return (q is not None and q.primitive.name == "mul"
            and any(is_literal(x) and _lit_float(x) == 0.0
                    for x in q.invars)
            and any(not is_literal(x) for x in q.invars))


def _lit_float(x):
    try:
        return float(x.val)
    except (TypeError, ValueError):
        return None


@rule("unfenced-float-product", SEV_ERROR,
      "Float products/quotients feeding accumulator chains (accruals, "
      "event times, physics sums) must route through fmul_pinned/"
      "fdiv_pinned: XLA may FMA-contract `x + a*b` (or strength-reduce a "
      "constant division) differently in differently-structured programs, "
      "which broke the K=1-vs-superstep bit-identity goldens in PR 2.")
def check_unfenced_float_product(ctx):
    out = []
    g = ctx.graph()
    for scope, path, _b, _l in walker.iter_jaxprs(ctx.body):
        # backward slice from the scope outputs through accumulator-chain
        # primitives; every add/sub on that slice is accrual-positioned
        seen, adds, stack = set(), [], [v for v in scope.outvars
                                       if not is_literal(v)]
        local = {ov: q for q in scope.eqns for ov in q.outvars}
        while stack:
            v = stack.pop()
            if id(v) in seen or is_literal(v):
                continue
            seen.add(id(v))
            q = local.get(v)
            if q is None or q.primitive.name not in ACC_CHAIN_PRIMS:
                continue
            if q.primitive.name in ("add", "sub"):
                adds.append(q)
            stack.extend(x for x in q.invars if not is_literal(x))
        for q in adds:
            av = q.outvars[0].aval
            if getattr(av.dtype, "kind", "") != "f":
                continue
            ivs = list(q.invars)
            for i, x in enumerate(ivs):
                if is_literal(x):
                    continue
                p = g.producer(x)
                if p is None or p.primitive.name not in ("mul", "div"):
                    continue
                if _zero_mul(g, x):
                    continue  # x IS the fence term
                other = ivs[1 - i]
                if (q.primitive.name == "add" and not is_literal(other)
                        and _zero_mul(g, other)):
                    continue  # pinned: add(a*b, a*0.0)
                out.append(_v(
                    ctx, "unfenced-float-product",
                    f"{path or 'step-body'} @ {src_of(q)}",
                    f"unpinned {p.primitive.name} "
                    f"({src_of(p)}) feeds an accumulator "
                    f"{q.primitive.name} — route the product through "
                    "fmul_pinned/fdiv_pinned (ops.physics)"))
    return out


@rule("duplicate-index-scatter-add", SEV_ERROR,
      "Multi-row scatters claiming `unique_indices=True` with data-"
      "derived indices: XLA is licensed to assume no duplicates, so a "
      "colliding row silently drops an increment (the units_finished "
      "latent-bug class — two same-jtype finishes in one K-window).  "
      "Prove uniqueness (iota/arange rows) or drop the claim.")
def check_duplicate_index_scatter(ctx):
    out = []
    g = ctx.graph()
    for c in walker.iter_eqns(ctx.body):
        q = c.eqn
        if not q.primitive.name.startswith("scatter"):
            continue
        if not q.params.get("unique_indices"):
            continue
        idx = q.invars[1]
        shape = tuple(getattr(idx.aval, "shape", ()))
        # [rows..., index_depth]: rows = prod(all but last axis)
        n_rows = 1
        for d in shape[:-1]:
            n_rows *= d
        if n_rows <= 1:
            continue  # a single index row is trivially unique
        kind, payload = _index_source(g, idx)
        if kind == "eqn" and payload.primitive.name == "iota":
            continue  # an iota row axis: every row distinct
        if kind == "eqn" and payload.primitive.name == "concatenate":
            cols = [_index_source(g, x) for x in payload.invars]
            if any(ck == "eqn" and cp.primitive.name == "iota"
                   for ck, cp in cols):
                continue  # any iota COLUMN makes multi-dim rows distinct
        prim = (payload.primitive.name if kind == "eqn"
                else "literal" if kind == "lit" else "const/invar")
        if kind == "lit":
            continue
        if kind == "var":
            # hoisted out of the scan body: follow the const binding to
            # the top-level constvar and check the CONCRETE rows
            vals = _scan_const_value(ctx, payload)
            if vals is not None:
                import numpy as np

                rows = np.asarray(vals).reshape(n_rows, -1)
                if len(np.unique(rows, axis=0)) == n_rows:
                    continue  # concrete rows verified unique
                prim = "a constant with DUPLICATE rows"
        out.append(_v(
            ctx, "duplicate-index-scatter-add",
            f"{c.path or 'step-body'} @ {src_of(q)}",
            f"{q.primitive.name} over {n_rows} index rows claims "
            f"unique_indices=True but the rows come from {prim} — "
            "duplicates are undefined behavior here"))
    return out


def _index_source(g: Graph, v, depth: int = 10):
    """Where index VALUES ultimately come from, as ``(kind, payload)``:
    ``("eqn", eqn)`` / ``("var", resolved_var)`` / ``("lit", v)``.

    Walks through size-preserving shape ops, literal-offset adds, and
    the jnp negative-index normalization (a select whose value operands
    share one source) — all per-element injective, so uniqueness of the
    source carries to the indices.  An EXPANDING broadcast duplicates
    rows and stops the walk."""
    if depth <= 0:
        return ("unknown", None)
    if is_literal(v):
        return ("lit", v)
    q = g.producer(v)
    if q is None:
        return ("var", g.resolve(v))
    name = q.primitive.name
    if name in ("reshape", "squeeze", "convert_element_type") or (
            name == "broadcast_in_dim"
            and _size(q.outvars[0]) == _size(q.invars[0])):
        return _index_source(g, q.invars[0], depth - 1)
    if name in ("add", "sub"):
        ins = [x for x in q.invars
               if not (is_literal(x) or _size(x) <= 1)]
        if len(ins) == 1:  # offset by a scalar/literal: injective
            return _index_source(g, ins[0], depth - 1)
    if name == "select_n":
        srcs = [_index_source(g, x, depth - 1) for x in q.invars[1:]]
        if srcs and all(s[0] == srcs[0][0] and s[1] is srcs[0][1]
                        for s in srcs[1:]):
            return srcs[0]  # both arms derive from one source
    return ("eqn", q)


def _size(v) -> int:
    n = 1
    for d in getattr(v.aval, "shape", ()):
        n *= d
    return n


def _scan_const_value(ctx, body_var):
    """Concrete value of a step-body scan const, when it is bound
    (directly or through a top-level iota/broadcast) to a constant."""
    if ctx.scan_eqn is None:
        return None
    nc = ctx.scan_eqn.params.get("num_consts", 0)
    outer = None
    for b, o in zip(ctx.body.invars[:nc], ctx.scan_eqn.invars[:nc]):
        if b is body_var:
            outer = o
            break
    if outer is None or is_literal(outer):
        return None
    if ctx.const_map and outer in ctx.const_map:
        return ctx.const_map[outer]
    for q in ctx.jaxpr.eqns:  # top-level producer: iota is static too
        if outer in q.outvars and q.primitive.name == "iota":
            import numpy as np

            av = outer.aval
            return np.broadcast_to(
                np.arange(av.shape[q.params.get("dimension", 0)])
                .reshape([-1 if i == q.params.get("dimension", 0) else 1
                          for i in range(len(av.shape))]),
                av.shape)
    return None


@rule("weak-type-promotion", SEV_ERROR,
      "No weak-typed 64-bit values under jax_enable_x64: a Python "
      "literal that weak-types to int64/float64 computes at a different "
      "width (and rounding) than the x32 program and can leak into "
      "int32/f32 state — the `_plan_xfer status_val` bug class (PR 6).  "
      "Pin literals with explicit dtypes at the site.", needs_x64=True)
def check_weak_type_promotion(ctx):
    if ctx.x64_jaxpr is None:
        msg = ("the program does not trace under jax_enable_x64"
               + (f": {ctx.x64_error}" if ctx.x64_error else ""))
        return [_v(ctx, "weak-type-promotion", "trace", msg)]
    sites = {}
    for c in walker.iter_eqns(ctx.x64_jaxpr):
        for ov in c.eqn.outvars:
            av = ov.aval
            dt = getattr(av, "dtype", None)
            if dt is None or not getattr(av, "weak_type", False):
                continue
            if getattr(dt, "itemsize", 0) != 8 \
                    or getattr(dt, "kind", "") not in "iuf":
                continue
            key = (c.eqn.primitive.name, str(dt), src_of(c.eqn))
            sites[key] = sites.get(key, 0) + 1
    return [
        _v(ctx, "weak-type-promotion", site,
           f"{n} weak {dt} value(s) from `{prim}` under x64 — pin the "
           "Python literal with an explicit dtype (jnp.int32/float32 or "
           "the time dtype)")
        for (prim, dt, site), n in sorted(sites.items(),
                                          key=lambda t: t[0][2])
    ]


def _is_key_var(x) -> bool:
    import jax

    if is_literal(x):
        return False
    try:
        return jax.dtypes.issubdtype(x.aval.dtype, jax.dtypes.prng_key)
    except Exception:  # noqa: BLE001 - non-key extended dtypes
        return False


@rule("prng-key-reuse", SEV_ERROR,
      "A PRNG key consumed by two derivations (bits/split/fold_in, or "
      "two key-taking subcomputations) yields correlated or identical "
      "streams.  fold_in children with distinct static data are fine; "
      "two folds of the same key with the same data, or bits+split off "
      "one key, are bugs.")
def check_prng_key_reuse(ctx):
    # Per-scope, RAW-var analysis, with scopes deduped by object id:
    # jax CACHES identical call sub-jaxprs (two `categorical(k, ...)`
    # sites share one pjit body), so a cross-scope alias map would merge
    # distinct keys and double-count shared bodies.  Within one scope a
    # key-taking call eqn (pjit/custom_* with a sub-jaxpr) counts as a
    # consumer of its key operand — consumption inside the callee is
    # attributed to the call site.
    out = []
    seen_scopes = set()
    for scope, path, _b, _l in walker.iter_jaxprs(ctx.body):
        if id(scope) in seen_scopes:
            continue
        seen_scopes.add(id(scope))
        cons = {}   # raw key var -> [(kind, eqn, path, fold_data)]
        for q in scope.eqns:
            name = q.primitive.name
            is_call = any(True for _ in walker.subjaxprs(q)) \
                and name not in ("cond", "scan", "while")
            if name not in KEY_CONSUMERS and not is_call:
                continue
            for pos, x in enumerate(q.invars):
                if not _is_key_var(x):
                    continue
                fold = None
                if name == "random_fold_in":
                    data = [y for j, y in enumerate(q.invars) if j != pos]
                    if data and is_literal(data[0]):
                        fold = ("lit", _lit_float(data[0]))
                    elif data:
                        fold = ("var", id(data[0]))
                kind = name if name in KEY_CONSUMERS else f"call:{name}"
                cons.setdefault(x, []).append((kind, q, path, fold))
        for key_var, uses in cons.items():
            for i in range(len(uses)):
                for j in range(i + 1, len(uses)):
                    k1, q1, path1, f1 = uses[i]
                    k2, q2, path2, f2 = uses[j]
                    if k1 == k2 == "random_fold_in" and f1 != f2:
                        continue  # distinct children off one parent
                    out.append(_v(
                        ctx, "prng-key-reuse",
                        f"{path1 or 'step-body'} @ {src_of(q1)}",
                        f"one key consumed by both {k1} ({src_of(q1)}) "
                        f"and {k2} ({src_of(q2)})"
                        + (" with identical fold data"
                           if k1 == k2 == "random_fold_in" else "")
                        + " — derive per-use subkeys instead"))
    return out


_FWD_CHAIN = ("select_n", "convert_element_type")


@rule("f32-counter-overflow", SEV_ERROR,
      "A float32 carry incremented by an integer-valued literal stops "
      "counting at 2^24 (ulp > increment): streamed counters must be "
      "int32 or ride the configurable time dtype (the PR 4 caveat).")
def check_f32_counter_overflow(ctx):
    import numpy as np

    nc = ctx.scan_eqn.params.get("num_consts", 0)
    n_carry = ctx.scan_eqn.params.get("num_carry", 0)
    top_invar_carry = {v: i for i, v in
                       enumerate(ctx.body.invars[nc:nc + n_carry])
                       if not is_literal(v)}
    top_outvar_carry = {v: i for i, v in
                        enumerate(ctx.body.outvars[:n_carry])
                        if not is_literal(v)}

    out = []
    for scope, path, _b, _l in walker.iter_jaxprs(ctx.body):
        local = {ov: q for q in scope.eqns for ov in q.outvars}
        scope_outs = {id(v) for v in scope.outvars if not is_literal(v)}
        uses = {}
        for q in scope.eqns:
            for x in q.invars:
                if not is_literal(x):
                    uses.setdefault(x, []).append(q)
        top = scope is ctx.body

        def back_to_invar(v, depth=6):
            """carry index (top scope) / True (nested) if v chains back
            to a scope input through masking/dtype ops."""
            while depth:
                depth -= 1
                if top and v in top_invar_carry:
                    return top_invar_carry[v]
                if not top and v not in local:
                    return True  # scope invar or hoisted const
                q = local.get(v)
                if q is None or q.primitive.name not in _FWD_CHAIN:
                    return None
                nxt = [x for x in q.invars if not is_literal(x)]
                if not nxt:
                    return None
                v = nxt[0]
            return None

        def fwd_to_outvar(v, depth=6):
            while depth:
                depth -= 1
                if top and v in top_outvar_carry:
                    return top_outvar_carry[v]
                if not top and id(v) in scope_outs:
                    return True
                nxt = [q for q in uses.get(v, [])
                       if q.primitive.name in _FWD_CHAIN]
                if not nxt:
                    return None
                v = nxt[0].outvars[0]
            return None

        for q in scope.eqns:
            if q.primitive.name != "add":
                continue
            av = q.outvars[0].aval
            if str(getattr(av, "dtype", "")) != "float32":
                continue
            lits = [x for x in q.invars if is_literal(x)]
            vars_ = [x for x in q.invars if not is_literal(x)]
            if len(lits) != 1 or len(vars_) != 1:
                continue
            lv = _lit_float(lits[0])
            if lv is None or lv < 1 or lv != np.round(lv):
                continue
            src_idx = back_to_invar(vars_[0])
            dst_idx = fwd_to_outvar(q.outvars[0])
            if src_idx is None or dst_idx is None:
                continue
            if top and src_idx != dst_idx:
                continue
            out.append(_v(
                ctx, "f32-counter-overflow",
                f"{path or 'step-body'} @ {src_of(q)}",
                f"float32 carry incremented by {lv:g} — the counter "
                "silently stops at 2^24; use int32 or the configurable "
                "time dtype"))
    return out


@rule("eqn-ceiling-drift", SEV_ERROR,
      "The flattened step-body eqn count is the dispatch-bound step's "
      "first-order cost model; each canonical config is pinned against "
      "analysis/baselines.json (generated, never hand-edited) with a "
      "fixed headroom.  Over the ceiling = a structural regression; far "
      "under = a stale baseline that should be re-banked.")
def check_eqn_ceiling_drift(ctx):
    n = walker.flat_count(ctx.body)
    if ctx.baseline is None:
        return [_v(ctx, "eqn-ceiling-drift", "baselines",
                   f"no baseline entry for config {ctx.config!r} "
                   f"(measured {n} eqns) — run scripts/lint_graph.py "
                   "--update-baselines")]
    base = ctx.baseline["eqns"]
    ceiling = ctx.baseline.get("ceiling") or int(base * (1 + ctx.headroom))
    out = []
    if n > ceiling:
        census = walker.op_census(ctx.body)
        diff = {k: census.get(k, 0) - ctx.baseline.get("census", {}).get(k, 0)
                for k in census
                if census.get(k, 0) != ctx.baseline.get("census", {}).get(k, 0)}
        out.append(_v(ctx, "eqn-ceiling-drift", "step-body",
                      f"step body grew to {n} eqns (baseline {base}, "
                      f"ceiling {ceiling}); per-class drift: {diff} — find "
                      "what re-duplicated work, or re-bank with "
                      "--update-baselines if the growth is accepted"))
    elif n < int(base * 0.85):
        out.append(Violation(
            rule="eqn-ceiling-drift", severity=SEV_WARN, config=ctx.config,
            where="step-body",
            message=f"step body shrank to {n} eqns (baseline {base}) — "
                    "re-bank with --update-baselines to tighten the pin"))
    return out


# ---------------------------------------------------------------------------
# application
# ---------------------------------------------------------------------------

def apply_rules(ctx: LintContext, rule_ids=None):
    """Run (a subset of) the registry over one traced config.

    Returns ``(violations, allowlisted)`` — the second list carries
    (violation, reason) pairs for suppressed hits so reports can show
    the debt, not hide it."""
    violations, allowlisted = [], []
    for rid, r in RULES.items():
        if rule_ids is not None and rid not in rule_ids:
            continue
        for v in r.fn(ctx):
            allow = next((a for a in ALLOWLIST if a.covers(v)), None)
            if allow is not None:
                allowlisted.append((v, allow.reason))
            else:
                violations.append(v)
    return violations, allowlisted

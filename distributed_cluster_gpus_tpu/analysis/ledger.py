"""Continuous perf ledger: every banked round in one append-only JSONL.

Perf evidence was scattered across 16+ banked JSONs — driver-wrapped
``BENCH_rNN.json`` / ``MULTICHIP_rNN.json`` at the repo root plus the
probe artifacts under ``bench_results/`` — with no cross-round trend
view and no regression gate.  This module is the ONE loader and the ONE
round-discovery rule for all of them (bench.py's prior-evidence scan
and scripts/summarize_bench.py import it), and it normalizes every
banked measurement into flat ``dcg.perf_ledger.v1`` records:

    {"schema": "dcg.perf_ledger.v1", "round": 12, "source": "...",
     "kind": "headline|sweep|superstep|obs|workload|fastpath|io_overlap|
              multichip|sweep_grid|phase_attrib|twin_latency",
     "config": "<family string>",
     "platform": "cpu|tpu|axon|None", "ev_s": <float|None>, ...extras}

Design contracts (tests/test_ledger.py):

* **deterministic** — ``build_records`` yields a sorted, stable order
  and ``write_ledger`` serializes with ``sort_keys``; rebuilding from
  the same banked files is byte-identical (no timestamps — the banked
  artifacts themselves are the provenance).
* **idempotent ingest** — ``ingest`` appends only records whose
  identity key ``(source, kind, config)`` is absent (variants like
  obs on/off or fast/legacy must be baked into the config string); a
  second run appends nothing.
* **degradation** — a missing/corrupt/foreign file becomes one skip
  reason (returned, summarized as ONE line by callers), never a
  traceback.
* **gated** — ``check`` compares a current probe against the banked
  best per (kind, config) within the same platform class (cpu never
  cross-compares against tpu/axon) and flags drops beyond the
  threshold; scripts/perf_ledger.py --check exits nonzero on them.

Stdlib-only on purpose: bench.py imports this before the JAX backend is
probed (the probe can hang — VERDICT r01), so the loader must not.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

SCHEMA = "dcg.perf_ledger.v1"
LEDGER_BASENAME = "ledger.jsonl"

#: files under bench_results/ that are not banked measurements
_NON_EVIDENCE = re.compile(r"(\.tmp$|_tmp|^ledger\.jsonl$)")

#: full-pipeline on-chip artifacts the CPU-fallback evidence scan may
#: cite (ablations measure deliberately different pipelines)
_PRIOR_CITABLE = re.compile(r"^(key|sweep)_r\d+\.json$")

_ROUND_RE = re.compile(r"[_A-Za-z]r(\d+)")


def ledger_path(root: str) -> str:
    return os.path.join(root, "bench_results", LEDGER_BASENAME)


def round_of(name: str) -> Optional[int]:
    """Round number from an artifact name (BENCH_r05, fastpath_r12,
    prof_cpu_r05_summary, ...); None when the name carries none."""
    m = _ROUND_RE.search(name)
    return int(m.group(1)) if m else None


def discover(root: str) -> List[str]:
    """THE round-discovery rule: every banked evidence JSON, sorted.

    Repo-root driver wrappers (``BENCH_r*.json``, ``MULTICHIP_r*.json``)
    plus everything under ``bench_results/*.json`` minus staging debris
    (``*.tmp`` partials, ``*_tmp`` checkpoint-staging dirs) and the
    ledger itself.  Paths are returned relative to ``root`` so records
    are machine-independent.
    """
    out = []
    for pat in ("BENCH_r*.json", "MULTICHIP_r*.json"):
        out += [os.path.basename(p)
                for p in glob.glob(os.path.join(root, pat))]
    bdir = os.path.join(root, "bench_results")
    if os.path.isdir(bdir):
        for entry in os.listdir(bdir):
            if not entry.endswith(".json"):
                continue
            if _NON_EVIDENCE.search(entry):
                continue
            out.append(os.path.join("bench_results", entry))
    return sorted(out)


def load_banked(root: str, rel: str) -> Tuple[Optional[dict],
                                              Optional[str]]:
    """One banked artifact -> (normalized doc, skip reason).

    Driver wrappers are unwrapped to their ``parsed`` bench line (the
    wrapper's ``n`` is the authoritative round); a wrapper whose parse
    failed (r01's seed failure) degrades to a skip reason, as does any
    unreadable/foreign file.
    """
    path = os.path.join(root, rel)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return None, f"unreadable: {type(e).__name__}: {e}"
    if not isinstance(doc, dict):
        return None, f"foreign shape: {type(doc).__name__}"
    base = os.path.basename(rel)
    if base.startswith("BENCH_r"):
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            return None, (f"driver wrapper without a parsed bench line "
                          f"(rc={doc.get('rc')})")
        parsed = dict(parsed)
        parsed.setdefault("_round", doc.get("n"))
        return parsed, None
    if base.startswith("MULTICHIP_r"):
        return {"_multichip": {k: doc.get(k) for k in
                               ("n_devices", "ok", "skipped", "rc")},
                "_round": doc.get("n", round_of(base))}, None
    return doc, None


def _rec(source, rnd, kind, config, platform, ev_s, **extras) -> dict:
    rec = {"schema": SCHEMA, "source": source, "round": rnd,
           "kind": kind, "config": config, "platform": platform,
           "ev_s": round(float(ev_s), 1) if ev_s is not None else None}
    rec.update({k: v for k, v in extras.items() if v is not None})
    return rec


def records_from(rel: str, doc: dict) -> List[dict]:
    """Normalize one banked doc into flat ledger records."""
    rnd = doc.get("_round")
    if rnd is None:
        rnd = round_of(os.path.basename(rel))
    plat = doc.get("platform")
    out = []

    mc = doc.get("_multichip")
    if mc is not None:
        out.append(_rec(rel, rnd, "multichip", "virtual_mesh", "tpu"
                        if mc.get("ok") and not mc.get("skipped")
                        else None, None,
                        ok=bool(mc.get("ok")),
                        n_devices=mc.get("n_devices")))
        return out

    # headline (+ per-config rows): the full RL-in-loop pipeline
    if doc.get("value") is not None:
        cfg = doc.get("config", {}) or {}
        rows = doc.get("configs_measured") or doc.get("sweep") or [{
            "rollouts": cfg.get("rollouts"), "job_cap": cfg.get("job_cap"),
            "events_per_sec": doc["value"]}]
        for r in rows:
            if r.get("events_per_sec") is None:
                continue
            out.append(_rec(
                rel, rnd, "headline",
                f"R{r.get('rollouts')}/J{r.get('job_cap')}", plat,
                r["events_per_sec"],
                best=(r.get("events_per_sec") == doc["value"]) or None,
                note=doc.get("note")))

    ss = doc.get("superstep_sweep")
    if ss:
        for r in ss.get("rows", []):
            k = r.get("superstep_k")
            # prefer the banked fill (round 14+): deriving from the
            # independently-rounded events_per_iteration can disagree
            # with it in the 4th decimal; derive only for older rows
            fill = r.get("fill")
            if fill is None and r.get("events_per_iteration") is not None \
                    and k:
                fill = round(r["events_per_iteration"] / k, 4)
            out.append(_rec(
                rel, rnd, "superstep", f"{ss.get('algo')}/K{k}", plat,
                r.get("events_per_sec"),
                eqns=r.get("step_body_eqns"), fill=fill,
                realized_speedup=r.get("realized_speedup")))

    ob = doc.get("obs_overhead")
    if ob:
        shape = ob.get("shape", {})
        cfg = f"{ob.get('algo')}/K{shape.get('superstep_k')}"
        for variant, key in (("off", "events_per_sec_obs_off"),
                             ("on", "events_per_sec_obs_on")):
            if ob.get(key) is None:
                continue
            out.append(_rec(rel, rnd, "obs", f"{cfg}/obs_{variant}",
                            plat, ob[key],
                            overhead_fraction=ob.get(
                                "overhead_fraction")))

    wp = doc.get("workload_probe")
    if wp:
        out.append(_rec(rel, rnd, "workload",
                        f"{wp.get('preset')}/{wp.get('algo')}", plat,
                        wp.get("events_per_sec"),
                        eqns=wp.get("step_body_eqns")))

    fp = doc.get("fastpath_ab")
    if fp:
        for r in fp.get("rows", []):
            cfg = f"{r.get('config')}/{r.get('mode')}/K{r.get('k')}"
            for variant, key in (("fast", "fast_ev_s"),
                                 ("legacy", "legacy_ev_s")):
                if r.get(key) is None:
                    continue
                out.append(_rec(rel, rnd, "fastpath",
                                f"{cfg}/{variant}", plat, r[key],
                                speedup=(r.get("speedup")
                                         if variant == "fast" else None)))

    pab = doc.get("planner_ab")
    if pab:
        for r in pab.get("rows", []) if isinstance(pab, dict) else []:
            cfg = r.get("config") or r.get("algo") or "planner"
            for variant in ("plan", "legacy"):
                key = f"{variant}_ev_s"
                if r.get(key) is not None:
                    out.append(_rec(rel, rnd, "fastpath",
                                    f"{cfg}/planner/{variant}", plat,
                                    r[key]))

    ov = doc.get("io_overlap")
    if ov:
        out.append(_rec(rel, rnd, "io_overlap",
                        f"{ov.get('config', {}).get('algo')}/"
                        f"K{ov.get('config', {}).get('superstep_k')}",
                        plat, None,
                        wall_s=ov.get("wall_s"), io_s=ov.get("io_s"),
                        io_render_s=ov.get("io_render_s"),
                        overlap_fraction=ov.get("overlap_fraction")))

    sg = doc.get("sweep_grid_probe")
    if sg:
        # round-16 sweep-grid A/B: one config string per arm so the
        # grid/serial pair trends (and gates) independently, like the
        # fastpath fast/legacy variants
        cfg = f"{sg.get('fleet')}/{sg.get('n_cells')}cells"
        for variant in ("grid", "serial"):
            if sg.get(f"{variant}_ev_s") is None:
                continue
            out.append(_rec(rel, rnd, "sweep_grid", f"{cfg}/{variant}",
                            plat, sg[f"{variant}_ev_s"],
                            cells_s=sg.get(f"{variant}_cells_s"),
                            n_buckets=sg.get("n_buckets"),
                            speedup=(sg.get("speedup_cells")
                                     if variant == "grid" else None)))

    tl = doc.get("twin_latency")
    if tl:
        # round-19 twin serving SLO: ev_s is forecast events/sec (the
        # higher-is-better throughput the gate trends); the fork+forecast
        # latency quantiles ride along as extras
        cfg = (f"{tl.get('fleet')}/{tl.get('n_lanes')}lanes/"
               f"h{tl.get('horizon_s')}s")
        out.append(_rec(rel, rnd, "twin_latency", cfg, plat,
                        tl.get("ev_s"),
                        p50_s=tl.get("p50_s"), p95_s=tl.get("p95_s"),
                        n_buckets=tl.get("n_buckets"),
                        events_forecast=tl.get("events_forecast")))

    # bench.py banks attribution under "phase_attrib"; the attrib_step
    # CLI's dcg.lint_report.v1 carries the same docs under "attrib"
    pa = doc.get("phase_attrib") or doc.get("attrib")
    if pa:
        for rep in pa if isinstance(pa, list) else [pa]:
            top = rep.get("top_phase") or {}
            m = rep.get("measured") or {}
            out.append(_rec(rel, rnd, "phase_attrib", rep.get("config"),
                            plat, m.get("events_per_sec"),
                            eqns=rep.get("eqns_total"),
                            whole_step_ms=m.get("whole_step_ms"),
                            top_phase=top.get("phase"),
                            top_time_share=top.get("time_share")))
    return out


def build_records(root: str) -> Tuple[List[dict], List[Tuple[str, str]]]:
    """(all records over every discovered banked file, skip reasons)."""
    records, skipped = [], []
    for rel in discover(root):
        doc, reason = load_banked(root, rel)
        if doc is None:
            skipped.append((rel, reason))
            continue
        try:
            recs = records_from(rel, doc)
        except Exception as e:  # noqa: BLE001 - degradation, not death
            skipped.append((rel, f"normalize failed: {e!r}"))
            continue
        if not recs:
            skipped.append((rel, "no measurements recognized"))
        records += recs
    return records, skipped


def record_key(rec: dict) -> Tuple:
    return (rec.get("source"), rec.get("kind"), rec.get("config"))


def dumps(rec: dict) -> str:
    return json.dumps(rec, sort_keys=True)


def write_ledger(path: str, records: Iterable[dict]) -> int:
    """Rewrite the whole ledger deterministically; returns row count."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    n = 0
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for rec in records:
            f.write(dumps(rec) + "\n")
            n += 1
    os.replace(tmp, path)
    return n


def read_ledger(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # a torn tail line is not evidence
    return out


def ingest(root: str, path: Optional[str] = None
           ) -> Dict[str, object]:
    """Append newly-banked rounds to the ledger (idempotent).

    Returns {"added", "total", "skipped": [(file, reason), ...]}.
    """
    path = path or ledger_path(root)
    existing = read_ledger(path)
    seen = {record_key(r) for r in existing}
    records, skipped = build_records(root)
    fresh = [r for r in records if record_key(r) not in seen]
    if fresh:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            for rec in fresh:
                f.write(dumps(rec) + "\n")
    return {"added": len(fresh), "total": len(existing) + len(fresh),
            "skipped": skipped}


def rebuild(root: str, path: Optional[str] = None) -> Dict[str, object]:
    """Regenerate the ledger from scratch — byte-identical per input set."""
    path = path or ledger_path(root)
    records, skipped = build_records(root)
    n = write_ledger(path, records)
    return {"total": n, "skipped": skipped}


# ---------------------------------------------------------------------------
# trend + regression gate
# ---------------------------------------------------------------------------

def platform_class(platform: Optional[str]) -> Optional[str]:
    if platform in ("tpu", "axon"):
        return "chip"
    if platform == "cpu":
        return "cpu"
    return None


def series(records: Iterable[dict]) -> Dict[Tuple, List[dict]]:
    """Group ev/s records into per-(kind, config, platform class) series
    sorted by round (None rounds last) — the trend view's input."""
    out: Dict[Tuple, List[dict]] = {}
    for rec in records:
        if rec.get("ev_s") is None:
            continue
        pc = platform_class(rec.get("platform"))
        if pc is None:
            continue
        out.setdefault((rec["kind"], rec["config"], pc), []).append(rec)
    for key in out:
        out[key].sort(key=lambda r: (r.get("round") is None,
                                     r.get("round"), r.get("source")))
    return out


def check(records: Iterable[dict], current: Iterable[dict], *,
          threshold: float = 0.3, kinds: Tuple[str, ...] = ("headline",)
          ) -> List[dict]:
    """Regression gate: current probe vs the banked best per config.

    ``current`` records (same shape; build with ``records_from``) are
    compared against the best banked ``ev_s`` for the same (kind,
    config) within the same platform class; a drop beyond ``threshold``
    is one violation dict.  Configs with no banked counterpart pass (a
    new shape has no trajectory to regress against).
    """
    best: Dict[Tuple, dict] = {}
    for rec in records:
        if rec.get("ev_s") is None or rec["kind"] not in kinds:
            continue
        pc = platform_class(rec.get("platform"))
        if pc is None:
            continue
        key = (rec["kind"], rec["config"], pc)
        if key not in best or rec["ev_s"] > best[key]["ev_s"]:
            best[key] = rec
    out = []
    for rec in current:
        if rec.get("ev_s") is None or rec["kind"] not in kinds:
            continue
        pc = platform_class(rec.get("platform"))
        key = (rec["kind"], rec["config"], pc)
        prior = best.get(key)
        if prior is None or prior.get("source") == rec.get("source"):
            continue
        floor = prior["ev_s"] * (1.0 - threshold)
        if rec["ev_s"] < floor:
            out.append({
                "kind": rec["kind"], "config": rec["config"],
                "platform_class": pc, "current_ev_s": rec["ev_s"],
                "best_ev_s": prior["ev_s"],
                "best_source": prior["source"],
                "drop_fraction": round(1.0 - rec["ev_s"]
                                       / prior["ev_s"], 4),
                "threshold": threshold,
            })
    return out


def format_trend(records: Iterable[dict]) -> List[str]:
    """The per-config ev/s trend as markdown lines (one table per record
    kind, columns = rounds) — shared by scripts/perf_ledger.py --trend
    and scripts/summarize_bench.py --trend."""
    ss = series(records)
    if not ss:
        return ["no ev/s series in the ledger"]
    by_kind: Dict[str, list] = {}
    for (kind, config, pc), recs in sorted(ss.items()):
        by_kind.setdefault(kind, []).append((config, pc, recs))
    lines = []
    for kind, rows in by_kind.items():
        rounds = sorted({r.get("round") for _, _, recs in rows
                         for r in recs if r.get("round") is not None})
        lines += [f"", f"### {kind} ev/s by round", ""]
        lines.append("| config | platform |"
                     + "".join(f" r{n:02d} |" for n in rounds))
        lines.append("|---" * (2 + len(rounds)) + "|")
        for config, pc, recs in rows:
            by_round = {}
            for r in recs:
                if r.get("round") is not None:
                    by_round[r["round"]] = r["ev_s"]  # last source wins
            cells = "".join(
                f" {by_round[n]:,.0f} |" if n in by_round else " — |"
                for n in rounds)
            lines.append(f"| {config} | {pc} |{cells}")
    lines.append("")
    return lines


# ---------------------------------------------------------------------------
# prior-evidence scan (bench.py's degraded-resilience path)
# ---------------------------------------------------------------------------

def best_prior_on_chip(root: str) -> Tuple[Optional[dict],
                                           List[Tuple[str, str]]]:
    """Strongest comparable on-chip full-pipeline measurement, if any.

    The ONE loader behind ``bench.best_prior_on_chip``: only
    ``bench_results/{key,sweep}_rNN.json`` artifacts are citable (the
    ablations measure deliberately different pipelines), only tpu/axon
    platforms count, and every missing/corrupt/foreign file folds into
    the returned skip list instead of raising.
    """
    best = None
    skipped = []
    bdir = os.path.join(root, "bench_results")
    names = []
    if os.path.isdir(bdir):
        names = sorted(e for e in os.listdir(bdir)
                       if _PRIOR_CITABLE.match(e)
                       and not _NON_EVIDENCE.search(e))
    for name in names:
        rel = os.path.join("bench_results", name)
        doc, reason = load_banked(root, rel)
        if doc is None:
            skipped.append((rel, reason))
            continue
        if doc.get("platform") not in ("tpu", "axon"):
            continue
        try:
            for rec in records_from(rel, doc):
                if rec["kind"] != "headline" or rec["ev_s"] is None:
                    continue
                if best is None or rec["ev_s"] > best["events_per_sec"]:
                    m = re.match(r"^R(.*)/J(.*)$", rec["config"])
                    best = {"events_per_sec": rec["ev_s"],
                            "rollouts": _maybe_int(m.group(1)) if m
                            else None,
                            "job_cap": _maybe_int(m.group(2)) if m
                            else None,
                            "file": rel}
        except Exception as e:  # noqa: BLE001 - scan must not die
            skipped.append((rel, f"normalize failed: {e!r}"))
    return best, skipped


def _maybe_int(tok: str):
    try:
        return int(tok)
    except (TypeError, ValueError):
        return None if tok in (None, "None") else tok

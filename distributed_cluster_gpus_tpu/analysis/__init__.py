"""dcg-lint: declarative static analysis over compiled step programs.

The repo's structural invariants — no in-step while loops, select-free
supersteps, contraction-fenced accrual products, int32 counters, single
PRNG-key consumption, eqn ceilings — as an enforced rule engine that
walks traced jaxprs (docs/static_analysis.md).

Submodules (import these directly; the package init stays import-light
so CLI entry points can load it without touching the JAX backend):

* ``walker``  — the one shared flatten/visit core over closed jaxprs;
* ``rules``   — the rule registry, severities, and the per-rule
  allowlist (every entry carries a written reason);
* ``lint``    — canonical config matrix, baselines store, runner;
* ``report``  — the shared ``dcg.lint_report.v1`` JSON shape.
"""

from . import report, walker  # noqa: F401  (import-light submodules)

__all__ = ["walker", "report", "rules", "lint"]

"""dcg-lint: declarative static analysis over compiled step programs.

The repo's structural invariants — no in-step while loops, select-free
supersteps, contraction-fenced accrual products, int32 counters, single
PRNG-key consumption, eqn ceilings — as an enforced rule engine that
walks traced jaxprs (docs/static_analysis.md).

Since PR 14 the package also carries the perf-observability pair that
stops the op-dispatch wall from being attacked blind:

* ``attrib`` — step-time attribution: the step body partitioned into
  named phases (100%-coverage invariant) and each phase measured with
  compiled ablation prefixes (``dcg.phase_attrib.v1``);
* ``ledger`` — the append-only cross-round perf ledger over every
  banked bench artifact (``dcg.perf_ledger.v1``) with the trend view
  and the ``--check`` regression gate.

Submodules (import these directly; the package init stays import-light
so CLI entry points can load it without touching the JAX backend):

* ``walker``  — the one shared flatten/visit core over closed jaxprs;
* ``rules``   — the rule registry, severities, and the per-rule
  allowlist (every entry carries a written reason);
* ``lint``    — canonical config matrix, baselines store, runner;
* ``report``  — the shared ``dcg.lint_report.v1`` JSON shape;
* ``attrib``  — phase partition + ablation timing (needs JAX);
* ``ledger``  — banked-round loader, ledger.jsonl, trend, regression
  gate (stdlib-only: bench.py's evidence scan imports it pre-backend).
"""

from . import report, walker  # noqa: F401  (import-light submodules)

__all__ = ["walker", "report", "rules", "lint", "attrib", "ledger"]

"""Run the lint rules over every canonical engine configuration.

The canonical matrix covers the config families the repo's perf and
correctness story actually ships — ring+slab layouts, K∈{1,4,8},
planner on and (forced-)off arms, obs on/off, the signal/fault/bandit/
chsac families — at the SAME trace shapes tests/test_perf_structure.py
pins, so the baselines this module generates (analysis/baselines.json)
ARE the eqn ceilings those tests enforce.  Tracing only, no compile: a
full-matrix run costs seconds per config and is banked by bench.py as a
zero-cost evidence artifact.

Entry points:

* :func:`canonical_configs` — the named matrix;
* :func:`trace_config` — one traced config as a rules.LintContext;
* :func:`run_lint` — rules x configs -> a ``dcg.lint_report.v1`` dict;
* :func:`generate_baselines` / :func:`load_baselines` — the generated
  eqn-ceiling store and its ``--update-baselines`` flow.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional

from . import report, rules, walker

BASELINES_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "baselines.json")
BASELINES_SCHEMA = "dcg.lint_baselines.v1"
HEADROOM = 0.06  # ~6% benign-drift headroom over the banked eqn count
CHUNK_STEPS = 8  # the trace shape every ceiling pin uses


@dataclass(frozen=True)
class ConfigSpec:
    """One canonical lint configuration (a named SimParams shape)."""

    name: str
    algo: str = "joint_nf"
    queue_mode: str = "ring"
    k: int = 1
    obs: bool = False
    faults: bool = False
    preset: Optional[str] = None          # workload preset (signals on)
    elastic: bool = False
    router_weights: Optional[tuple] = None
    legacy_planner: bool = False          # force the round-8 golden arm


def canonical_configs():
    C = ConfigSpec
    return [
        C("joint_nf/ring/K1"),
        C("joint_nf/slab/K1", queue_mode="slab"),
        C("joint_nf/ring/K4", k=4),
        C("joint_nf/ring/K8", k=8),
        C("joint_nf/ring/K1+obs", obs=True),
        C("joint_nf/ring/K4+obs", k=4, obs=True),
        C("joint_nf/ring/K1+legacy", legacy_planner=True),
        C("default_policy/ring/K1", algo="default_policy"),
        C("bandit/ring/K1", algo="bandit"),
        C("bandit/slab/K1", algo="bandit", queue_mode="slab"),
        C("fault/ring/K1", algo="default_policy", faults=True),
        C("fault/slab/K1", algo="default_policy", faults=True,
          queue_mode="slab"),
        C("fault/ring/K4", algo="default_policy", faults=True, k=4),
        C("carbon_cost+signals/ring/K1", algo="carbon_cost",
          preset="flash_crowd"),
        C("carbon_cost+signals/ring/K4", algo="carbon_cost",
          preset="flash_crowd", k=4),
        C("eco_route+signals/ring/K1", algo="eco_route",
          preset="flash_crowd"),
        C("weighted_router/ring/K1",
          router_weights=(1.0, 1.0, 0.0, 0.0, 1.0)),
        C("bandit+faults/ring/K1", algo="bandit", faults=True),
        C("chsac_af/ring/K1", algo="chsac_af"),
        C("chsac_af/slab/K1", algo="chsac_af", queue_mode="slab"),
        C("chsac_af/ring/K1+legacy", algo="chsac_af", legacy_planner=True),
        C("chsac_af+elastic/ring/K1", algo="chsac_af", elastic=True),
        C("chsac_af+faults/ring/K1", algo="chsac_af", faults=True),
    ]


def config_by_name(name: str) -> ConfigSpec:
    for c in canonical_configs():
        if c.name == name:
            return c
    raise KeyError(f"unknown canonical config {name!r}")


_POLICY_CACHE: dict = {}


def _chsac_policy(fleet, params):
    """One real SAC policy per (obs_dim, n_dc, n_g) — the traced policy
    tail must be the production network, not a stub, or the chsac rules
    and ceilings lint a program nobody runs."""
    import jax

    from ..rl.cmdp import default_constraints
    from ..rl.sac import SACConfig, make_policy_apply, sac_init

    key = (params.obs_dim(fleet.n_dc), fleet.n_dc, params.max_gpus_per_job)
    if key not in _POLICY_CACHE:
        cfg = SACConfig(obs_dim=key[0], n_dc=key[1], n_g=key[2],
                        constraints=default_constraints(500.0))
        _POLICY_CACHE[key] = (make_policy_apply(cfg),
                              sac_init(cfg, jax.random.key(1)))
    return _POLICY_CACHE[key]


def build_params(fleet, spec: ConfigSpec):
    """The SimParams of one canonical config — the exact trace shape the
    eqn ceilings pin (tests/test_perf_structure._trace)."""
    from ..configs.paper import build_incident_faults
    from ..models import SimParams
    from ..workload import make_preset

    workload = (make_preset(spec.preset, fleet, horizon_s=600.0)
                if spec.preset else None)
    faults = build_incident_faults(10.0, 20.0) if spec.faults else None
    return SimParams(
        algo=spec.algo, duration=1e9, log_interval=20.0,
        inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson", trn_rate=0.1,
        job_cap=128, lat_window=512, seed=0, queue_mode=spec.queue_mode,
        queue_cap=256, superstep_k=spec.k, obs_enabled=spec.obs,
        workload=workload, faults=faults, elastic_scaling=spec.elastic,
        router_weights=spec.router_weights)


def build_engine(fleet, spec: ConfigSpec):
    """Engine + policy params of one canonical config — the single
    construction path the linter and the step-time attribution
    (analysis/attrib.py) share, so both analyze the identical program."""
    from ..sim.engine import Engine

    params = build_params(fleet, spec)
    policy, pp = ((None, None) if spec.algo != "chsac_af"
                  else _chsac_policy(fleet, params))
    eng = Engine(fleet, params, policy_apply=policy)
    if spec.legacy_planner:
        eng.planner_on = False  # the round-8 golden arm (test_write_plan)
    return eng, pp


def trace_config(fleet, spec: ConfigSpec, *, x64: bool = True,
                 baselines: Optional[dict] = None) -> rules.LintContext:
    """Trace one canonical config into a LintContext (no compile)."""
    import jax

    from ..sim.engine import init_state

    eng, pp = build_engine(fleet, spec)
    params = eng.params
    st = init_state(jax.random.key(0), fleet, params, workload=eng.workload)

    def _trace():
        return jax.make_jaxpr(
            lambda s, p: eng._run_chunk(s, p, CHUNK_STEPS))(st, pp)

    jpr = _trace()
    scan_eqn = walker.main_scan_body(jpr, CHUNK_STEPS)
    x64_jaxpr, x64_error = None, None
    if x64:
        try:
            with jax.experimental.enable_x64():
                x64_jaxpr = _trace().jaxpr
        except Exception as e:  # noqa: BLE001 - the failure IS the finding
            x64_error = f"{type(e).__name__}: {e}"
    entry = None
    if baselines is not None:
        entry = baselines.get("configs", {}).get(spec.name)
    return rules.LintContext(
        config=spec.name, params=params, k=spec.k,
        superstep_on=eng.superstep_on, planner_on=eng.planner_on,
        forced_legacy=spec.legacy_planner, obs_on=spec.obs,
        jaxpr=jpr.jaxpr, scan_eqn=scan_eqn,
        body=scan_eqn.params["jaxpr"].jaxpr,
        scans=walker.chunk_scans(jpr, CHUNK_STEPS),
        x64_jaxpr=x64_jaxpr, x64_error=x64_error,
        baseline=entry,
        headroom=(baselines or {}).get("headroom", HEADROOM),
        const_map=dict(zip(jpr.jaxpr.constvars, jpr.consts)))


# ---------------------------------------------------------------------------
# baselines: the generated eqn-ceiling store
# ---------------------------------------------------------------------------

def load_baselines(path: str = BASELINES_PATH) -> dict:
    with open(path) as f:
        b = json.load(f)
    if b.get("schema") != BASELINES_SCHEMA:
        raise ValueError(f"{path}: not a {BASELINES_SCHEMA} file")
    return b


def baseline_entry(config_id: str, baselines: Optional[dict] = None) -> dict:
    b = baselines or load_baselines()
    try:
        return b["configs"][config_id]
    except KeyError:
        raise KeyError(
            f"no baseline for {config_id!r} — run scripts/lint_graph.py "
            "--update-baselines") from None


def ceiling_for(config_id: str, baselines: Optional[dict] = None) -> int:
    """The generated eqn ceiling the structure tests enforce."""
    b = baselines or load_baselines()
    e = baseline_entry(config_id, b)
    return int(e.get("ceiling") or
               e["eqns"] * (1 + b.get("headroom", HEADROOM)))


def measured_for(config_id: str, baselines: Optional[dict] = None) -> int:
    return baseline_entry(config_id, baselines)["eqns"]


def generate_baselines(fleet=None, configs=None) -> dict:
    """Re-trace the canonical matrix and build the baselines document.

    Deterministic: same code -> byte-identical JSON (the round-trip test
    pins it), so ``--update-baselines`` diffs are pure structure diffs."""
    if fleet is None:
        from ..configs import build_fleet

        fleet = build_fleet()
    configs = configs or canonical_configs()
    entries = {}
    for spec in configs:
        ctx = trace_config(fleet, spec, x64=False)
        census = walker.op_census(ctx.body)
        entries[spec.name] = {
            "eqns": census["eqns"],
            "census": {k: v for k, v in sorted(census.items())
                       if k != "eqns"},
        }
    # derived entry: the obs-on eqn DELTA (K-independent by design, see
    # test_obs_on_eqn_overhead_pinned) gets an absolute-slack ceiling —
    # a relative headroom on a small delta would pin to the noise
    if ("joint_nf/ring/K1+obs" in entries
            and "joint_nf/ring/K1" in entries):
        delta = (entries["joint_nf/ring/K1+obs"]["eqns"]
                 - entries["joint_nf/ring/K1"]["eqns"])
        entries["joint_nf/ring/obs-delta"] = {
            "eqns": delta, "ceiling": delta + 50, "derived": True}
    return {"schema": BASELINES_SCHEMA, "headroom": HEADROOM,
            "chunk_steps": CHUNK_STEPS, "configs": entries}


def dump_baselines(b: dict, path: str = BASELINES_PATH) -> None:
    with open(path, "w") as f:
        json.dump(b, f, indent=1, sort_keys=True)
        f.write("\n")


def diff_baselines(old: Optional[dict], new: dict) -> list:
    """Per-config, per-class drift lines for the --update-baselines flow."""
    lines = []
    oldc = (old or {}).get("configs", {})
    for name, e in new["configs"].items():
        o = oldc.get(name)
        if o is None:
            lines.append(f"+ {name}: new entry ({e['eqns']} eqns)")
            continue
        if o["eqns"] == e["eqns"]:
            continue
        cls = {k: e.get("census", {}).get(k, 0) - o.get("census", {}).get(k, 0)
               for k in set(e.get("census", {})) | set(o.get("census", {}))}
        cls = {k: v for k, v in sorted(cls.items()) if v}
        lines.append(f"~ {name}: {o['eqns']} -> {e['eqns']} eqns "
                     f"({'+' if e['eqns'] > o['eqns'] else ''}"
                     f"{e['eqns'] - o['eqns']}); by class: {cls}")
    for name in oldc:
        if name not in new["configs"]:
            lines.append(f"- {name}: entry removed")
    return lines


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------

def run_lint(fleet=None, config_names=None, rule_ids=None,
             baselines: Optional[dict] = None, x64: Optional[bool] = None):
    """Rules x canonical configs -> a ``dcg.lint_report.v1`` dict.

    ``config_names`` filters by fnmatch glob; ``rule_ids`` restricts the
    registry; ``x64=False`` skips the second (enable_x64) trace AND the
    rules that need it — a deliberately skipped trace is not a finding."""
    import fnmatch

    if fleet is None:
        from ..configs import build_fleet

        fleet = build_fleet()
    if baselines is None:
        try:
            baselines = load_baselines()
        except (OSError, ValueError):
            baselines = {"configs": {}}
    selected = [c for c in canonical_configs()
                if not config_names
                or any(fnmatch.fnmatch(c.name, pat) for pat in config_names)]
    if rule_ids is not None:
        unknown = set(rule_ids) - set(rules.RULES)
        if unknown:
            raise KeyError(f"unknown rule id(s): {sorted(unknown)}; "
                           f"known: {sorted(rules.RULES)}")
    if x64 is False:
        rule_ids = {rid for rid, r in rules.RULES.items()
                    if not r.needs_x64
                    and (rule_ids is None or rid in rule_ids)}
    elif x64 is None:
        x64 = any(r.needs_x64 for rid, r in rules.RULES.items()
                  if rule_ids is None or rid in rule_ids)

    violations, allowlisted, matrix = [], [], {}
    for spec in selected:
        ctx = trace_config(fleet, spec, x64=x64, baselines=baselines)
        vs, al = rules.apply_rules(ctx, rule_ids)
        violations += vs
        allowlisted += [dict(v.as_dict(), reason=reason) for v, reason in al]
        matrix[spec.name] = {
            "ok": not any(v.severity == rules.SEV_ERROR for v in vs),
            "violations": len(vs),
            "allowlisted": sum(1 for a in al),
            "eqns": walker.flat_count(ctx.body),
            "superstep_on": ctx.superstep_on,
            "planner_on": ctx.planner_on,
        }
    checked = [s.name for s in selected]
    run_rules = sorted(rule_ids if rule_ids is not None else rules.RULES)
    return report.make_report(
        "lint_graph", checked, violations, allowlisted,
        extra={"rules": run_rules, "matrix": matrix})

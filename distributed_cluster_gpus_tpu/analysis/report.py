"""The one machine-readable report shape every static checker emits.

``dcg.lint_report.v1`` is shared by scripts/lint_graph.py,
scripts/check_metrics_schema.py, scripts/validate_chaos.py, and
scripts/validate_workload.py, so CI and bench banking consume one schema
no matter which checker produced the result:

    {"schema": "dcg.lint_report.v1", "tool": "<checker>", "ok": bool,
     "checked": ["<unit>", ...],
     "violations": [{"rule", "severity", "config", "where", "message"}],
     "allowlisted": [{..., "reason"}],
     "summary": "<one line>"}

``violations`` entries always carry the five keys; checkers without a
rule id use their tool name.  ``ok`` is true iff no error-severity
violation remains after allowlisting.
"""

from __future__ import annotations

import json

SCHEMA = "dcg.lint_report.v1"


def violation(message: str, *, rule: str, severity: str = "error",
              config: str = "", where: str = "") -> dict:
    return {"rule": rule, "severity": severity, "config": config,
            "where": where, "message": message}


def make_report(tool: str, checked, violations, allowlisted=(),
                summary: str = None, extra: dict = None) -> dict:
    violations = [v if isinstance(v, dict) else v.as_dict()
                  for v in violations]
    errors = [v for v in violations if v.get("severity") == "error"]
    rep = {
        "schema": SCHEMA,
        "tool": tool,
        "ok": not errors,
        "checked": list(checked),
        "violations": violations,
        "allowlisted": list(allowlisted),
        "summary": summary or (
            f"{tool}: OK ({len(checked)} unit(s) checked)" if not errors
            else f"{tool}: {len(errors)} error(s), "
                 f"{len(violations) - len(errors)} warning(s) over "
                 f"{len(checked)} unit(s)"),
    }
    if extra:
        rep.update(extra)
    return rep


def write_report(rep: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(rep, f, indent=1, sort_keys=True)
        f.write("\n")

"""Step-time attribution: WHERE inside the step the wall time goes.

Five rounds of structural work attacked the op-dispatch wall blind —
eqn ceilings pin totals and whole-program A/Bs rank configs, but nothing
measured which part of the step body (event-min head, selection payload,
planner payloads, `_commit_plan`, post-switch drain, obs block, log
tail, RL policy tail) actually burns the milliseconds.  This module is
that measurement, in two halves over the SAME phase boundaries:

* **partition** — the step-body jaxpr split into named phases by tracing
  the cumulative-prefix programs the engine's ``attrib_stop`` knob
  compiles (`sim.engine.Engine._step` / `_step_super`).  Prefixes nest
  by construction, so per-phase eqn counts are telescoping deltas and
  the partition covers 100% of step eqns: the hard invariant enforced
  here is ``sum(phase eqns) == flat_count(full body)`` with every delta
  ``>= 0`` (a negative delta would mean a stop broke prefix nesting, i.e.
  unattributed residue), and the full count equals the pinned ceiling's
  measured eqns (tests/test_attrib.py pins it per canonical config).

* **measurement** — each prefix compiled and timed under the banked A/B
  methodology (vmapped batch, interleaved repeats, medians — the r09/r12
  harness): phase ms/step is the per-repeat delta between consecutive
  prefixes, so one CPU-contention spike cannot crown the wrong phase.
  The first-order cost model of a dispatch-bound step predicts
  ``time share == eqn share``; the report carries both, and their ratio
  is the phase's realized dispatch efficiency.

Methodology caveats (recorded in the report): ablated prefixes return
their phase outputs as scan ys to keep the work live under DCE, but XLA
may still fuse differently than in the full program; and a prefix
program never applies events, so its state stalls at the first pending
event — shapes (and therefore dispatch cost) are unchanged, values are
not.  The report schema is ``dcg.phase_attrib.v1``; the CLI is
scripts/attrib_step.py, and bench.py banks it per round (BENCH_ATTRIB).
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from . import lint, walker

SCHEMA = "dcg.phase_attrib.v1"

#: ablation-arm labels, keyed by the engine's internal stop names
PHASE_LABELS = {
    "head": "event_min_head",
    "switch": "event_switch_payloads",
    "commit": "commit_plan",
    "drain": "post_switch_drain",
    "emit": "log_tail",
    "tail": "policy_tail",
    "select": "selection_payload",
    "apply_loop": "apply_substep_loop",
    "apply_commit": "commit_plan",
    "apply": "apply_tails",
}


def phase_stops(engine) -> Tuple[List[str], str]:
    """The ordered ablation stops for one compiled engine, plus the label
    of the final residual phase (everything past the last stop)."""
    from ..sim.engine import ALGO_CHSAC_AF

    if engine.superstep_on:
        stops = ["head", "select", "apply_loop", "apply_commit", "apply",
                 "drain"]
    else:
        stops = ["head", "switch"]
        if engine.planner_on:
            stops.append("commit")
        stops += ["drain", "emit"]
        if engine.params.algo == ALGO_CHSAC_AF:
            stops.append("tail")
    final = "obs_block" if engine.obs_on else "finalize"
    return stops, final


class PartitionError(AssertionError):
    """The phase partition failed its 100%-coverage invariant."""


def _traced_body_eqns(engine, state, pp, stop: Optional[str],
                      chunk_steps: int) -> int:
    import jax

    jpr = jax.make_jaxpr(
        lambda s, p: engine._run_chunk(s, p, chunk_steps,
                                       attrib_stop=stop))(state, pp)
    body = walker.main_scan_body(jpr, chunk_steps).params["jaxpr"].jaxpr
    return walker.flat_count(body)


def phase_partition(engine, state, pp,
                    chunk_steps: int = lint.CHUNK_STEPS) -> dict:
    """Named-phase eqn partition of the step body (trace-only, no compile).

    Returns ``{"phases": [{"phase", "stop", "eqns", "eqn_share"}, ...],
    "eqns_total": N}`` with the coverage invariant enforced: deltas are
    nonnegative and sum exactly to the full body's flattened count.
    """
    stops, final = phase_stops(engine)
    counts = [_traced_body_eqns(engine, state, pp, s, chunk_steps)
              for s in stops]
    total = _traced_body_eqns(engine, state, pp, None, chunk_steps)
    prev, phases = 0, []
    for stop, count in zip(stops, counts):
        delta = count - prev
        if delta < 0:
            raise PartitionError(
                f"phase {stop!r}: prefix eqn count {count} < previous "
                f"{prev} — the stops no longer nest (unattributed "
                "residue)")
        phases.append({"phase": PHASE_LABELS[stop], "stop": stop,
                       "eqns": delta})
        prev = count
    if total - prev < 0:
        raise PartitionError(
            f"final residual negative: full body {total} < last prefix "
            f"{prev}")
    phases.append({"phase": final, "stop": None, "eqns": total - prev})
    covered = sum(ph["eqns"] for ph in phases)
    if covered != total:
        raise PartitionError(
            f"partition covers {covered} of {total} step eqns")
    for ph in phases:
        ph["eqn_share"] = round(ph["eqns"] / max(total, 1), 4)
    return {"phases": phases, "eqns_total": total,
            "chunk_steps": chunk_steps}


def _fold_live(state, aux):
    """Fold a zero-valued reduction of an arm's outputs into the carry.

    Two jobs at once: every ablated phase output feeds the scan carry
    (so XLA cannot DCE the phase's work when the jit discards the
    stacked emissions), and the carry changes per iteration (so XLA's
    loop-invariant code motion cannot hoist a stalled prefix's whole
    body out of the scan — the failure mode that attributed the K=4
    selection payload to the commit).  Nonfinites are masked before the
    sum, so the added term is exactly 0.0 — but the simplifier cannot
    prove it, which is the point.
    """
    import jax
    import jax.numpy as jnp

    leaves = []
    for x in jax.tree.leaves(aux):
        x = jnp.asarray(x)
        if not (jnp.issubdtype(x.dtype, jnp.number)
                or x.dtype == jnp.bool_):
            continue
        xf = x.astype(jnp.float32)
        leaves.append(jnp.sum(jnp.where(jnp.isfinite(xf), xf, 0.0)))
    if not leaves:
        return state
    red = sum(leaves)
    z = jnp.where(jnp.isnan(red), red, 0.0).astype(state.t.dtype)
    return state.replace(t=state.t + z)


def measure_phases(engine, pp, n_rollouts: int = 8,
                   chunk_steps: int = 256, warm_chunks: int = 2,
                   timed_chunks: int = 1, reps: int = 3) -> dict:
    """Compile + time the cumulative-prefix programs; per-phase ms/step.

    Interleaved repeats with per-repeat deltas and medians (the banked
    A/B methodology): every repeat times all arms back-to-back, the
    phase time is the within-repeat difference of consecutive arms, and
    the median over repeats is reported — so a contention spike hits all
    arms of one repeat instead of biasing one phase.  Every arm
    (including the full step) folds its per-step outputs into the carry
    via :func:`_fold_live`, so no phase's work can be DCE'd or hoisted.
    """
    import jax
    import numpy as np

    from ..parallel.rollout import batched_init

    stops, _final = phase_stops(engine)
    arms = stops + [None]

    def one_chunk(s, stop):
        st, em = engine._run_chunk(s, pp, chunk_steps, attrib_stop=stop)
        return _fold_live(st, em)

    runs = {}
    for stop in arms:
        run = jax.jit(jax.vmap(
            lambda s, _stop=stop: one_chunk(s, _stop)))
        states = batched_init(engine.fleet, engine.params, n_rollouts,
                              workload=engine.workload)
        for _ in range(warm_chunks):
            states = run(states)
        jax.block_until_ready(states.t)
        runs[stop] = (run, states)

    wall = {stop: [] for stop in arms}
    ev_rate = []
    for _ in range(reps):
        for stop in arms:
            run, states = runs[stop]
            ev0 = int(np.sum(np.asarray(states.n_events)))
            t0 = time.perf_counter()
            for _ in range(timed_chunks):
                states = run(states)
            jax.block_until_ready(states.t)
            dt = time.perf_counter() - t0
            wall[stop].append(dt)
            runs[stop] = (run, states)
            if stop is None:
                ev = int(np.sum(np.asarray(states.n_events))) - ev0
                ev_rate.append(ev / dt)

    steps = timed_chunks * chunk_steps

    def med(xs):
        return sorted(xs)[len(xs) // 2]

    # per-repeat telescoping deltas, then the median per phase
    deltas = {}
    prev = [0.0] * reps
    for stop in arms:
        cur = wall[stop]
        deltas[stop] = med([c - p for c, p in zip(cur, prev)])
        prev = cur
    whole_ms = med(wall[None]) / steps * 1e3
    phase_ms = {stop: deltas[stop] / steps * 1e3 for stop in arms}
    return {"whole_step_ms": whole_ms, "phase_ms": phase_ms,
            "events_per_sec": med(ev_rate),
            "shape": {"rollouts": n_rollouts, "chunk_steps": chunk_steps,
                      "warm_chunks": warm_chunks,
                      "timed_chunks": timed_chunks, "reps": reps}}


def attribute_config(fleet, config: str, *, trace_only: bool = False,
                     n_rollouts: int = 8, chunk_steps: int = 256,
                     warm_chunks: int = 2, timed_chunks: int = 1,
                     reps: int = 3) -> dict:
    """One canonical lint config -> a ``dcg.phase_attrib.v1`` report.

    ``trace_only`` skips the compiled measurement (the partition alone
    costs seconds; the timing pays one XLA compile per phase arm).
    """
    import jax

    from ..sim.engine import init_state

    spec = lint.config_by_name(config)
    eng, pp = lint.build_engine(fleet, spec)
    st = init_state(jax.random.key(0), fleet, eng.params,
                    workload=eng.workload)
    part = phase_partition(eng, st, pp)
    out = {
        "schema": SCHEMA,
        "config": config,
        "k": eng.K,
        "superstep_on": eng.superstep_on,
        "planner_on": eng.planner_on,
        "obs_on": eng.obs_on,
        "eqns_total": part["eqns_total"],
        "phases": part["phases"],
        "note": ("phase eqns are telescoping deltas of cumulative-prefix "
                 "traces (100% coverage enforced); measured ms/step are "
                 "within-repeat deltas of compiled prefix programs, "
                 "interleaved medians.  predicted_time_share is the "
                 "banked dispatch-bound cost model: time share == eqn "
                 "share.  Caveats: prefix arms keep phase outputs live "
                 "as scan ys but XLA fusion may differ from the full "
                 "program, and ablated states stall at the first "
                 "pending event (shapes, not values, drive dispatch "
                 "cost)."),
    }
    for ph in part["phases"]:
        ph["predicted_time_share"] = ph["eqn_share"]
    if not trace_only:
        m = measure_phases(eng, pp, n_rollouts=n_rollouts,
                           chunk_steps=chunk_steps,
                           warm_chunks=warm_chunks,
                           timed_chunks=timed_chunks, reps=reps)
        whole = m["whole_step_ms"]
        phase_sum = 0.0
        for ph in out["phases"]:
            ms = m["phase_ms"][ph["stop"]]
            ph["ms_per_step"] = round(ms, 6)
            phase_sum += ms
            ph["time_share"] = round(ms / whole, 4) if whole > 0 else None
        out["measured"] = {
            "whole_step_ms": round(whole, 6),
            "phase_sum_ms": round(phase_sum, 6),
            "sum_vs_whole": round(phase_sum / whole, 4) if whole > 0
            else None,
            "events_per_sec": round(m["events_per_sec"], 1),
            **m["shape"],
        }
        timed = [ph for ph in out["phases"]
                 if ph.get("ms_per_step") is not None]
        top = max(timed, key=lambda ph: ph["ms_per_step"])
        out["top_phase"] = {"phase": top["phase"],
                            "ms_per_step": top["ms_per_step"],
                            "time_share": top["time_share"]}
    return out


def format_report(rep: dict) -> str:
    """One attribution report as a markdown table (CLI + perf notes)."""
    lines = [
        f"### step-time attribution: {rep['config']} "
        f"(K={rep['k']}, {'superstep' if rep['superstep_on'] else 'singleton'}"
        f", planner {'on' if rep['planner_on'] else 'off'}, "
        f"{rep['eqns_total']} step eqns)",
        "",
    ]
    measured = "measured" in rep
    hdr = "| phase | eqns | eqn share |"
    sep = "|---|---|---|"
    if measured:
        hdr += " ms/step | time share | time/eqn ratio |"
        sep += "---|---|---|"
    lines += [hdr, sep]
    for ph in rep["phases"]:
        row = (f"| {ph['phase']} | {ph['eqns']} "
               f"| {ph['eqn_share'] * 100:.1f}% |")
        if measured:
            ts = ph.get("time_share")
            ratio = (round(ts / ph["eqn_share"], 2)
                     if ts is not None and ph["eqn_share"] > 0 else "—")
            row += (f" {ph.get('ms_per_step', float('nan')):.4f} "
                    f"| {ts * 100:.1f}% | {ratio} |"
                    if ts is not None else " — | — | — |")
        lines.append(row)
    if measured:
        m = rep["measured"]
        lines.append("")
        lines.append(
            f"whole step {m['whole_step_ms']:.4f} ms; phase sum "
            f"{m['phase_sum_ms']:.4f} ms ({m['sum_vs_whole'] * 100:.1f}% "
            f"of whole); top phase: {rep['top_phase']['phase']} "
            f"({rep['top_phase']['ms_per_step']:.4f} ms/step, "
            f"{rep['top_phase']['time_share'] * 100:.1f}%)")
    return "\n".join(lines)

"""Algorithm comparison harness over the five BASELINE.json configs.

The reference has no benchmark harness (BASELINE.md: "published: {}"); its
workflow is run-N-times-then-plot.  This module makes the comparison a
first-class, reproducible artifact: every algorithm runs the SAME workload
realization — arrival gaps and job sizes come from a dedicated per-stream
PRNG chain in SimState (`engine._handle_arrival`), a pure function of the
seed, so the event streams are bit-identical across algorithms no matter
how their event interleavings diverge — and each run reduces to one summary
row {energy_kwh, mean/p99 latency per type, completed, dropped, energy/unit}
— the metric set BASELINE.json names ("RL policy return vs baseline
policies").

Config shapes (BASELINE.json "configs"):
  1. single-DC, Poisson inference-only, fixed-freq baseline policy
  2. single-DC, Poisson train+inference mix, heuristic DVFS
  3. multi-DC sinusoid arrivals + routing
  4. RL DVFS+placement (chsac_af trained online) vs heuristics, multi-DC
  5. many-way vmapped multi-DC rollouts + PPO, mesh-sharded
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from .configs import build_fleet, build_single_dc_fleet
from .models import SimParams
from .sim.algos import windowed_percentile
from .sim.io import run_simulation


@dataclasses.dataclass
class Summary:
    algo: str
    energy_kwh: float
    completed_inf: int
    completed_trn: int
    dropped: int
    mean_lat_inf_s: float
    p99_lat_inf_s: float
    mean_lat_trn_s: float
    p99_lat_trn_s: float
    energy_per_unit_wh: float
    extra: Dict[str, float] = dataclasses.field(default_factory=dict)

    def row(self) -> Dict:
        d = dataclasses.asdict(self)
        d.update(d.pop("extra"))
        return d


def _lat_stats(lat_buf: np.ndarray, lat_count: np.ndarray, jt: int):
    """(mean, p99) sojourn seconds for job type jt over the sliding window
    (last `lat_window` completions — the same window the RL SLA constraint
    sees)."""
    import jax.numpy as jnp

    m = int(min(lat_count[jt], lat_buf.shape[1]))
    if m == 0:
        return float("nan"), float("nan")
    mean = float(np.mean(lat_buf[jt, :m]))
    p99 = (float(windowed_percentile(jnp.asarray(lat_buf[jt]),
                                     jnp.int32(lat_count[jt]), 99.0))
           if m >= 5 else float("nan"))
    return mean, p99


def fault_metrics(fleet, state) -> Dict[str, float]:
    """Degraded-mode metrics from a fault-enabled run's final state.

    * ``availability``: capacity-weighted uptime fraction — 1 minus the
      GPU-weighted downtime integral over the simulated span (an outage
      of a 512-GPU DC costs more availability than one of a 16-GPU DC).
    * ``mean_recovery_s``: mean realized outage duration (total downtime
      over outage count; an outage still open at end counts its elapsed
      portion).
    * migration accounting: jobs preempted by outages, re-homed to
      surviving DCs, or failed outright (no up DC existed).
    * ``migration_success_rate``: re-homed fraction of the preempted
      jobs — how well the *policy* rescues work off dead capacity (jobs
      still awaiting migration at end count as un-rescued); NaN when
      nothing was ever preempted.
    * ``worst_dc_downtime_s``: the single worst DC's downtime — an
      availability number can hide one DC absorbing every incident.
    * ``interruption_rate``: outage preemptions per completed job — the
      chaos-facing counterpart of completion throughput (how much of
      the delivered work had to survive an interruption).
    """
    fs = state.fault
    if fs is None:
        return {}
    total = np.asarray(fleet.total_gpus, np.float64)
    downtime = np.asarray(fs.downtime, np.float64)
    span = max(float(state.t), 1e-9)
    n_out = int(np.asarray(fs.n_outages).sum())
    n_pre = int(fs.n_preempted)
    n_done = int(np.asarray(state.n_finished).sum())
    return {
        "availability": 1.0 - float((downtime * total).sum())
        / (span * float(total.sum())),
        "downtime_s": float(downtime.sum()),
        "worst_dc_downtime_s": float(downtime.max()) if downtime.size
        else 0.0,
        "n_outages": n_out,
        "mean_recovery_s": (float(downtime.sum()) / n_out if n_out
                            else 0.0),
        "n_fault_preempted": n_pre,
        "n_fault_migrated": int(fs.n_migrated),
        "n_fault_failed": int(fs.n_failed),
        "migration_success_rate": (int(fs.n_migrated) / n_pre if n_pre
                                   else float("nan")),
        "interruption_rate": n_pre / n_done if n_done else float("nan"),
    }


def signal_metrics(state) -> Dict[str, float]:
    """Energy-cost / carbon totals from a signal-enabled run (else {}).

    The accumulators integrate ``P * dt * price(t)`` / ``P * dt * ci(dc,
    t)`` over the exact inter-event gaps (workload/ subsystem), so these
    are the time-varying counterparts of the static ``energy_kwh``
    total — and what run_summary.json / the eval tables report for
    trace-driven price/carbon scenarios.
    """
    if getattr(state, "signals", None) is None:
        return {}
    return {
        "energy_cost_usd": float(np.asarray(state.signals.cost_usd).sum()),
        "carbon_kg": float(np.asarray(state.signals.carbon_g).sum()) / 1e3,
    }


#: held-out chaos leaderboard weights (rl/population.py): availability
#: and migration success dominate (the robustness axes the sweep grades
#: policies on), completions reward delivered work, drops and the
#: energy/price/carbon integrals penalize.  On a shared fault
#: realization (``parallel.rollout.replicated_init`` lanes) availability
#: is policy-independent, so the migration/throughput/energy terms are
#: what actually discriminate members — availability still anchors the
#: score across different realizations (resumed or re-run evals).
CHAOS_SCORE_WEIGHTS = {
    "availability": 100.0,
    "migration_success_rate": 10.0,
    "completed": 1e-3,
    "dropped": -1e-3,
    "energy_kwh": -0.05,
    "energy_cost_usd": -0.1,
    "carbon_kg": -0.1,
}


def chaos_score(row: Dict) -> float:
    """Scalar held-out chaos score of one summary row (higher = better).

    ``row`` is a :meth:`Summary.row` dict (or any dict carrying the same
    keys); missing / NaN components contribute 0 except availability,
    which defaults to 1.0 (a fault-free eval row ranks on the
    throughput/energy terms alone).
    """
    import math

    def val(key, default=0.0):
        v = row.get(key, default)
        if v is None or (isinstance(v, float) and math.isnan(v)):
            return default
        return float(v)

    w = CHAOS_SCORE_WEIGHTS
    return (w["availability"] * val("availability", 1.0)
            + w["migration_success_rate"] * val("migration_success_rate")
            + w["completed"] * (val("completed_inf") + val("completed_trn"))
            + w["dropped"] * val("dropped")
            + w["energy_kwh"] * val("energy_kwh")
            + w["energy_cost_usd"] * val("energy_cost_usd")
            + w["carbon_kg"] * val("carbon_kg"))


def obs_metrics(state) -> Dict[str, int]:
    """Watchdog totals from an obs-enabled run's final state (else {}).

    ``watchdog_violations`` sums the HARD invariant probes (a correct
    engine reports 0 on any workload); ``watchdog_pressure`` sums the
    capacity-saturation probe step counts (full rings/slab — legal, but
    the first thing to look at when throughput sags).
    """
    if getattr(state, "telemetry", None) is None:
        return {}
    from .obs.health import split_counts

    rep = split_counts(np.asarray(state.telemetry.viol))
    return {"watchdog_violations": rep.violation_total,
            "watchdog_pressure": rep.pressure_total}


def _summarize(algo: str, fleet, state, extra: Optional[Dict] = None) -> Summary:
    lat_buf = np.asarray(state.lat.buf)
    lat_count = np.asarray(state.lat.count)
    mean_inf, p99_inf = _lat_stats(lat_buf, lat_count, 0)
    mean_trn, p99_trn = _lat_stats(lat_buf, lat_count, 1)
    units = float(np.asarray(state.units_finished).sum())
    kwh = float(np.asarray(state.dc.energy_j).sum()) / 3.6e6
    extra = dict(extra or {})
    extra.update(fault_metrics(fleet, state))
    extra.update(obs_metrics(state))
    extra.update(signal_metrics(state))
    return Summary(
        algo=algo,
        energy_kwh=kwh,
        completed_inf=int(np.asarray(state.n_finished)[0]),
        completed_trn=int(np.asarray(state.n_finished)[1]),
        dropped=int(np.asarray(state.n_dropped)),
        mean_lat_inf_s=mean_inf,
        p99_lat_inf_s=p99_inf,
        mean_lat_trn_s=mean_trn,
        p99_lat_trn_s=p99_trn,
        energy_per_unit_wh=kwh * 1000.0 / max(units, 1e-9),
        extra=extra,
    )


def run_algo(fleet, params: SimParams, chunk_steps: int = 4096,
             rollouts: int = 1, init_sac=None,
             sac_steps_per_chunk: Optional[int] = None) -> Summary:
    """One algorithm on one workload -> Summary (chsac_af trains online).

    ``rollouts > 1`` evaluates chsac_af through the SAME distributed
    trainer the benchmark and CLI use (the round-2 verdict's Weak #7: the
    configuration being graded must be the configuration being benched):
    R worlds feed the shared learner and the summary is rollout 0, whose
    workload realization is identical to the single-world runs of the
    other algorithms (`batched_init` gives rollout 0 the un-split seed
    key).  ``init_sac`` warm-starts the distributed learner (e.g. a
    policy grafted from a long-horizon checkpoint via
    :func:`warm_sac_from_checkpoint`).
    """
    if params.algo == "chsac_af" and rollouts > 1:
        from .rl.train import train_chsac_distributed

        state0, trainer, _ = train_chsac_distributed(
            fleet, params, n_rollouts=rollouts, out_dir=None,
            chunk_steps=chunk_steps, verbose=False, init_sac=init_sac,
            **({} if sac_steps_per_chunk is None
               else {"sac_steps_per_chunk": sac_steps_per_chunk}))
        return _summarize(params.algo, fleet, state0,
                          {"train_steps": int(trainer.sac.step),
                           "rollouts": rollouts})
    if init_sac is not None or sac_steps_per_chunk is not None:
        # a silently-dropped warm start / update schedule would corrupt
        # the experiment
        raise ValueError("init_sac / sac_steps_per_chunk are only supported "
                         "for chsac_af with rollouts > 1 (the "
                         "distributed-trainer path)")
    if params.algo == "chsac_af":
        from .rl.train import train_chsac

        state, agent, _ = train_chsac(fleet, params, out_dir=None,
                                      chunk_steps=chunk_steps)
        return _summarize(params.algo, fleet, state,
                          {"train_steps": int(agent.sac.step)})
    state = run_simulation(fleet, params, out_dir=None, chunk_steps=chunk_steps)
    return _summarize(params.algo, fleet, state)


def run_ppo(fleet, params: SimParams, chunk_steps: int = 2048,
            rollouts: int = 8, max_chunks: int = 20_000) -> Summary:
    """Train PPO on-policy on the given workload until every rollout reaches
    ``params.duration``; summary is rollout 0 (same workload realization as
    the single-world runs of the other algorithms, via ``batched_init``).

    This is the evaluation path for BASELINE config 5's policy quality —
    PPO ranked on the identical workload the heuristics and chsac_af run —
    as opposed to :func:`eval_config5`, the throughput/scaling measurement.
    """
    from .parallel import make_mesh
    from .parallel.rollout import PPOTrainer

    tr = PPOTrainer(fleet, params, n_rollouts=rollouts, mesh=make_mesh(),
                    seed=params.seed)
    n = 0
    while not tr.all_done and n < max_chunks:
        tr.train_chunk(chunk_steps=chunk_steps)
        n += 1
    import jax

    state0 = jax.tree.map(lambda a: a[0], tr.states)
    return _summarize("ppo", fleet, state0,
                      {"updates": n, "rollouts": rollouts})


def compare(fleet, base: SimParams, algos: Sequence[str],
            chunk_steps: int = 4096, verbose: bool = True,
            rollouts: int = 1) -> List[Summary]:
    """Run every algorithm on the identical workload; sorted by energy."""
    out = []
    for algo in algos:
        if algo == "ppo":
            # not a SimParams algo: PPO rides the chsac_af engine hooks with
            # its own on-policy learner (PPOTrainer coerces params.algo)
            s = run_ppo(fleet, base, chunk_steps, rollouts=max(rollouts, 8))
            out.append(s)
            if verbose:
                print(f"  {'ppo':>15s}: {s.energy_kwh:9.2f} kWh, "
                      f"p99_inf {s.p99_lat_inf_s:8.4f}s, "
                      f"done {s.completed_inf}+{s.completed_trn}, "
                      f"Wh/unit {s.energy_per_unit_wh:.4f}")
            continue
        params = dataclasses.replace(base, algo=algo)
        s = run_algo(fleet, params, chunk_steps, rollouts=rollouts)
        out.append(s)
        if verbose:
            print(f"  {algo:>15s}: {s.energy_kwh:9.2f} kWh, "
                  f"p99_inf {s.p99_lat_inf_s:8.4f}s, "
                  f"done {s.completed_inf}+{s.completed_trn}, "
                  f"Wh/unit {s.energy_per_unit_wh:.4f}")
    return out


def compare_seeds(fleet, base: SimParams, algos: Sequence[str],
                  seeds: Sequence[int], chunk_steps: int = 4096,
                  verbose: bool = True, rollouts: int = 1) -> Dict:
    """`compare` over several seeds -> {"per_seed": ..., "aggregate": ...}.

    The aggregate carries mean and sample-sd of every numeric metric per
    algorithm — the statistical-rigor upgrade the round-2 verdict asked
    for (single-seed rankings flip; mean±sd over >= 3 seeds shows whether
    an ordering is stable).
    """
    per_seed: Dict[int, List[Dict]] = {}
    for sd in seeds:
        if verbose:
            print(f"  -- seed {sd}")
        rows = compare(fleet, dataclasses.replace(base, seed=sd), algos,
                       chunk_steps=chunk_steps, verbose=verbose,
                       rollouts=rollouts)
        per_seed[sd] = [s.row() for s in rows]

    aggregate = []
    for i, algo in enumerate(algos):
        rows = [per_seed[sd][i] for sd in seeds]
        agg: Dict[str, object] = {"algo": algo, "n_seeds": len(seeds)}
        for k in rows[0]:
            vals = [r[k] for r in rows]
            if all(isinstance(v, (int, float)) and not isinstance(v, bool)
                   for v in vals):
                arr = np.asarray(vals, dtype=np.float64)
                finite = arr[~np.isnan(arr)]
                # sd is NaN (not 0.0) below 2 finite samples: "no variance
                # measured" must not read as "zero variance over N seeds"
                agg[f"{k}_mean"] = (float(finite.mean()) if finite.size
                                    else float("nan"))
                agg[f"{k}_sd"] = (float(finite.std(ddof=1))
                                  if finite.size > 1 else float("nan"))
                if finite.size != arr.size:
                    agg[f"{k}_n_finite"] = int(finite.size)
        aggregate.append(agg)
    return {"per_seed": {str(k): v for k, v in per_seed.items()},
            "aggregate": aggregate,
            # run-shape stamp: merged tables are only seed-comparable when
            # these agree (scripts/merge_eval.py warns on mismatch) — in
            # particular queue_mode/queue_cap change the overload service
            # discipline (ring vs pre-round-4 slab drops)
            "run_shape": {
                "duration": base.duration, "rollouts": rollouts,
                "job_cap": base.job_cap, "queue_mode": base.queue_mode,
                "queue_cap": base.queue_cap,
                "inf": [base.inf_mode, base.inf_rate],
                "trn": [base.trn_mode, base.trn_rate],
            }}


# ---------------------------------------------------------------------------
# The five BASELINE configs
# ---------------------------------------------------------------------------

def _with_auto_queue(spec: Dict) -> Dict:
    """Pin the spec's queue-ring depth to the drop-free auto size.

    The canonical rates overload the world by design; the reference queues
    every arrival (`/root/reference/simcore/models.py:61-62`).  Since round
    4 the ring layout restores that semantics PROVIDED the rings are deep
    enough — so every eval spec pins queue_cap explicitly (reproducible,
    and recorded in the artifact metadata so merged tables can detect
    engine-layout mismatches).  Sized for rollouts=8 — the harness's
    distributed-trainer width for chsac/ppo on configs 4/5 — so the
    memory guard holds for the widest run the spec is used in."""
    import dataclasses as _dc

    from .sim.engine import auto_queue_cap

    base = spec["base"]
    if base is not None and base.queue_mode == "ring":
        spec["base"] = _dc.replace(
            base, queue_cap=auto_queue_cap(base, spec["fleet"], rollouts=8))
    return spec


def baseline_config(n: int, duration: float) -> Dict:
    """(fleet, SimParams base, algo list) for BASELINE.json config #n."""
    if n == 1:
        return _with_auto_queue(dict(
            fleet=build_single_dc_fleet(),
            base=SimParams(algo="debug", duration=duration, log_interval=20.0,
                           inf_mode="poisson", inf_rate=4.0, trn_mode="off",
                           num_fixed_gpus=1, fixed_freq=1.0, job_cap=512),
            algos=["debug", "default_policy"],
        ))
    if n == 2:
        return _with_auto_queue(dict(
            fleet=build_single_dc_fleet(),
            base=SimParams(algo="joint_nf", duration=duration, log_interval=20.0,
                           inf_mode="poisson", inf_rate=4.0,
                           trn_mode="poisson", trn_rate=0.05, job_cap=512),
            algos=["default_policy", "joint_nf", "bandit"],
        ))
    if n == 3:
        return _with_auto_queue(dict(
            fleet=build_fleet(),
            base=SimParams(algo="eco_route", duration=duration, log_interval=20.0,
                           inf_mode="sinusoid", inf_rate=6.0,
                           trn_mode="poisson", trn_rate=0.05, job_cap=512),
            algos=["default_policy", "joint_nf", "carbon_cost", "eco_route"],
        ))
    if n == 4:
        return _with_auto_queue(dict(
            fleet=build_fleet(),
            # job_cap 2048, not 512: the slab bounds concurrently PLACED
            # jobs, and chsac_af's policy can legally place every job at
            # n=1 — up to 1,488 concurrent RUNNING jobs on this fleet —
            # where the grid heuristics' larger n keeps concurrency low.
            # 512 made chsac (alone) drop arrivals at the slab while the
            # rings sat empty; 2048 covers the 1-GPU-per-job worst case
            # for every algorithm on the shared spec.
            base=SimParams(algo="chsac_af", duration=duration, log_interval=20.0,
                           inf_mode="sinusoid", inf_rate=6.0,
                           trn_mode="poisson", trn_rate=0.05,
                           rl_warmup=256, rl_batch=256, job_cap=2048),
            algos=["default_policy", "joint_nf", "eco_route", "chsac_af"],
        ))
    if n == 5:
        # Policy quality rides the config-4 workload (identical seeds =>
        # identical arrival realizations across all five algorithms).  PPO
        # rows are only comparable to heuristic/chsac rows produced on the
        # SAME engine run-shape (queue_mode/queue_cap — the artifact's
        # run_shape stamp guards this), so the round-4 campaign reruns the
        # full algo set on the ring layout rather than merging with banked
        # slab-layout rows.  The config's defining 1024-way pjit scaling
        # point is measured by `eval_config5` / `bench.py`, not here.
        spec = baseline_config(4, duration)
        spec["algos"] = ["default_policy", "joint_nf", "eco_route",
                         "chsac_af", "ppo"]
        return spec
    raise ValueError(f"unknown BASELINE config {n}")


def variant_config(name: str, duration: float) -> Dict:
    """Diagnostic / steady-state variants beyond the five BASELINE shapes.

    * ``3c`` — carbon/cost-divergent config 3.  In the paper world
      carbon_cost can NEVER diverge from joint_nf: the hourly price is
      positive at every hour and global, so its admission score
      E*price/3.6e6 is a strict monotone transform of the energy grid —
      identical argmin by construction (and with price 0, a DC with CI>0
      still scores E*CI, again monotone).  The only reachable divergence
      in the reference semantics is price == 0 AND CI == 0: the score
      goes identically zero and the first-minimum tie-break picks grid
      cell (n=1, f=lowest) instead of the energy argmin — the preserved
      reference quirk.  This variant zeroes the hourly price (synthetic
      free-energy hours; not a reference-world fact) so the 5 CI-less DCs
      exercise that quirk cell and the two algorithms genuinely diverge,
      proving the code path live.
    * ``3s`` / ``4s`` — steady-state configs 3/4: the canonical rates
      overload the world by design (training arrivals ~10x service
      capacity; the reference queues them unboundedly, a slab drops them
      — docs/eval_r03.md "drop policy"), so these scale the training rate
      under capacity and size the slab with headroom; dropped must be ~0,
      making the algorithm comparison free of truncation effects.
    """
    if name == "3c":
        spec = baseline_config(3, duration)
        fleet = spec["fleet"]
        zero_price = np.zeros_like(np.asarray(fleet.price_hourly))
        spec["fleet"] = dataclasses.replace(fleet, price_hourly=zero_price)
        spec["base"] = dataclasses.replace(spec["base"],
                                           eco_objective="carbon")
        spec["algos"] = ["joint_nf", "carbon_cost", "eco_route"]
        return _with_auto_queue(spec)
    if name in ("3s", "4s"):
        spec = baseline_config(3 if name == "3s" else 4, duration)
        spec["base"] = dataclasses.replace(
            spec["base"],
            trn_rate=0.004,  # 8 streams * 0.004/s ~ 0.03 jobs/s < capacity
            job_cap=1024,    # headroom over peak jobs-in-system
        )
        return _with_auto_queue(spec)
    raise ValueError(f"unknown variant config {name!r}")


def eval_warmstart(duration: float = 1800.0, pretrain_steps: int = 2000,
                   chunk_steps: int = 4096, verbose: bool = True,
                   critic_arch: Optional[str] = None) -> List[Summary]:
    """Offline warm-start vs cold-start CHSAC-AF on the config-4 workload.

    Pipeline: run eco_route on the identical workload, convert its CSV logs
    to an offline npz (`rl.offline.build_offline_npz_from_logs`), pretrain a
    fresh agent from it, then fine-tune online — compared against the same
    online run from scratch.  Exercises the full offline-RL path the
    reference sketched but never wired (`offline_schema_example.py`,
    `load_offline_npz` both unused there).

    ``critic_arch`` overrides the config-4 default for BOTH arms (the A/B
    stays internally consistent): 'heads' costs ~30x less per update on a
    CPU core, which is what makes the drop-free workload affordable there
    (the ring-layout regime roughly doubled the update count vs r03).
    """
    import os
    import tempfile

    from .rl.offline import build_offline_npz_from_logs
    from .rl.train import make_agent, train_chsac, train_offline

    spec = baseline_config(4, duration)
    fleet, base = spec["fleet"], spec["base"]
    if critic_arch is not None:
        base = dataclasses.replace(base, critic_arch=critic_arch)

    with tempfile.TemporaryDirectory() as td:
        src = dataclasses.replace(base, algo="eco_route")
        run_simulation(fleet, src, out_dir=td, chunk_steps=chunk_steps)
        npz = os.path.join(td, "offline.npz")
        n_rows = build_offline_npz_from_logs(
            td, fleet, npz, sla_p99_ms=base.sla_p99_ms,
            max_gpus_per_job=base.max_gpus_per_job)
        if verbose:
            print(f"  offline dataset: {n_rows} transitions from eco_route")
        warm_agent = make_agent(fleet, base)
        train_offline(warm_agent, npz, pretrain_steps)

    cold = run_algo(fleet, base, chunk_steps)
    cold = dataclasses.replace(cold, algo="chsac_af_cold")
    state, warm_agent, _ = train_chsac(fleet, base, out_dir=None,
                                       chunk_steps=chunk_steps,
                                       agent=warm_agent)
    warm = _summarize("chsac_af_warm", fleet, state,
                      {"train_steps": int(warm_agent.sac.step),
                       "offline_rows": n_rows,
                       "pretrain_steps": pretrain_steps})
    if verbose:
        for s in (cold, warm):
            print(f"  {s.algo:>15s}: {s.energy_kwh:9.2f} kWh, "
                  f"p99_inf {s.p99_lat_inf_s:8.4f}s, "
                  f"done {s.completed_inf}+{s.completed_trn}, "
                  f"Wh/unit {s.energy_per_unit_wh:.4f}")
    return [cold, warm]


def eval_config5(duration_chunks: int = 20, n_rollouts: Optional[int] = None,
                 chunk_steps: int = 512, verbose: bool = True) -> Dict:
    """Config 5: many-way vmapped rollouts + PPO, sharded over the mesh."""
    import jax

    from .parallel import make_mesh
    from .parallel.rollout import PPOTrainer

    fleet = build_fleet()
    n_dev = len(jax.devices())
    if n_rollouts is None:
        n_rollouts = max(64, n_dev * 8)
    params = SimParams(algo="chsac_af", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0,
                       trn_mode="poisson", trn_rate=0.05,
                       job_cap=256, lat_window=512)
    tr = PPOTrainer(fleet, params, n_rollouts=n_rollouts, mesh=make_mesh())
    m = None
    tr.train_chunk(chunk_steps=chunk_steps)  # compile + first chunk
    import time

    t0 = time.perf_counter()
    ev0 = int(np.asarray(tr.states.n_events).sum())
    for i in range(duration_chunks):
        m = tr.train_chunk(chunk_steps=chunk_steps)
        if verbose and i % 5 == 0:
            print(f"  ppo chunk {i}: loss={float(m['loss']):.4f} "
                  f"r_eff={float(m['r_eff_mean']):.4f} "
                  f"transitions={int(m['n_transitions'])}")
    jax.block_until_ready(tr.states)
    wall = time.perf_counter() - t0
    out = {k: float(np.asarray(v).mean()) for k, v in m.items()}
    out["n_rollouts"] = n_rollouts
    out["events_per_sec"] = (int(np.asarray(tr.states.n_events).sum())
                             - ev0) / max(wall, 1e-9)
    out["platform"] = jax.devices()[0].platform
    return out

"""Watchdog-gated self-healing chaos-training campaigns.

A *campaign* trains CHSAC-AF through a chaos curriculum's severity
stages: one full training run per :class:`~..fault.curriculum.ChaosStage`
(mild -> harsh), the SAME learner (SAC state, replay, PRNG) carried
across stages.  Two run-health gates guard every segment:

* the obs **watchdog** in ``raise`` mode — any NEW hard invariant trip
  (NaN power/energy, ring corruption, broken job conservation) aborts
  the segment at the tripping chunk boundary;
* host-side **divergence probes** (:class:`DivergenceMonitor`) over the
  per-chunk training metrics — non-finite or exploding losses, a
  runaway temperature — raised as
  :class:`~..obs.health.DivergenceError` from the trainer's
  ``on_chunk`` hook, i.e. BEFORE the diverged chunk can checkpoint.

On an abort the trainer loop (``rl/train.py``) has already flushed the
exporters, written the segment's ``run_summary.json`` with
``status="aborted"``, and saved a forensic checkpoint under
``.../aborted``; the campaign driver then **self-heals**: it rolls the
learner back to the last HEALTHY ``step_*`` checkpoint (searching the
current segment first, then earlier segments), re-draws the chaos under
``curriculum.reseeded(+1)`` — same workload, fresh fault realization —
waits out an exponential backoff, and retries, under a bounded total
retry budget.  Budget exhausted -> :class:`CampaignError` (the campaign
summary records ``status="failed"``).

Artifacts (``out_dir``): per-segment run dirs (``stage00_try00/...``)
with the usual CSV/exporter files plus a chrome trace per attempt, and
a top-level ``campaign_summary.json`` (strict JSON) recording every
attempt, abort reason, rollback source, and reseed.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional

import numpy as np

from ..models.structs import FleetSpec, SimParams
from ..obs.health import DivergenceError, RunAbort
from ..utils.jsonio import dump_json_atomic
from .train import make_agent, train_chsac

CAMPAIGN_SUMMARY_FILE = "campaign_summary.json"


@dataclasses.dataclass(frozen=True)
class DivergenceConfig:
    """Thresholds for the host-side training-divergence probes.

    All probes run on the per-chunk metrics dict the fused SAC update
    returns; a non-finite value in any probed metric always trips.
    ``critic_loss_max`` bounds the critic TD loss (a chaos curriculum
    that destabilizes the critic shows up here first);
    ``alpha_max`` bounds the entropy temperature (a runaway alpha is
    the classic silent SAC failure — entropy bonus swamps the reward
    and the policy decays to uniform).
    """

    critic_loss_max: float = 1e7
    alpha_max: float = 1e3
    probe_metrics: tuple = ("critic_loss", "actor_loss", "alpha", "entropy")


class DivergenceMonitor:
    """Per-chunk divergence gate driven from the trainer's on_chunk hook.

    ``check(chunk, metrics)`` raises :class:`DivergenceError` on a trip;
    ``metrics=None`` (warmup chunks with no update yet) is a no-op.
    Subclass / replace ``check`` in tests to force deterministic trips.
    ``member`` labels a population-campaign member — the trip message and
    the raised error carry it, so the population driver quarantines the
    one tripping member instead of the fleet.
    """

    def __init__(self, cfg: Optional[DivergenceConfig] = None,
                 member: Optional[int] = None):
        self.cfg = cfg or DivergenceConfig()
        self.member = member
        self.trips = 0

    def _trip(self, chunk: int, why: str, probe: Optional[str] = None):
        self.trips += 1
        who = "" if self.member is None else f"member {self.member}: "
        raise DivergenceError(
            f"{who}training divergence at chunk {chunk}: {why}",
            probe=probe, config=self.cfg, member=self.member)

    def check(self, chunk: int, metrics: Optional[Dict]) -> None:
        if metrics is None:
            return
        for name in self.cfg.probe_metrics:
            if name not in metrics:
                continue
            v = np.asarray(metrics[name], np.float64)
            if not np.all(np.isfinite(v)):
                self._trip(chunk, f"non-finite {name}",
                           probe=f"nonfinite_{name}")
        cl = metrics.get("critic_loss")
        if cl is not None and float(np.asarray(cl)) > self.cfg.critic_loss_max:
            self._trip(chunk, f"critic_loss {float(np.asarray(cl)):.3g} > "
                              f"{self.cfg.critic_loss_max:.3g}",
                       probe="critic_loss_max")
        al = metrics.get("alpha")
        if al is not None and float(np.asarray(al)) > self.cfg.alpha_max:
            self._trip(chunk, f"alpha {float(np.asarray(al)):.3g} > "
                              f"{self.cfg.alpha_max:.3g}",
                       probe="alpha_max")


@dataclasses.dataclass(frozen=True)
class CampaignConfig:
    """Retry/backoff budget and gating knobs for :func:`run_campaign`."""

    retries: int = 2  # total extra attempts across the whole campaign
    backoff_s: float = 0.0  # base host sleep before a retry (doubles)
    watchdog: str = "raise"  # obs watchdog mode for the segments
    divergence: DivergenceConfig = DivergenceConfig()

    def __post_init__(self):
        if self.retries < 0:
            raise ValueError("retries must be >= 0")
        if self.backoff_s < 0:
            raise ValueError("backoff_s must be >= 0")


class CampaignError(RuntimeError):
    """The campaign exhausted its retry budget without completing.

    Carries structured context so automation can triage without scraping
    logs: ``attempts`` is the per-attempt record list the summary also
    holds (stage, reseed, outcome, abort reason/kind, rollback source),
    and ``abort_context`` the path of the LAST attempt's forensic
    ``abort_context.json`` (None when the run had no checkpoint dir) —
    feed it straight to ``scripts/replay_abort.py``.
    """

    def __init__(self, msg: str, attempts: Optional[List[Dict]] = None,
                 abort_context: Optional[str] = None):
        super().__init__(msg)
        self.attempts = list(attempts or [])
        self.abort_context = abort_context


def _abort_bundle(ckpt_dir: Optional[str]) -> tuple:
    """(bundle dir, abort_context.json path) of a segment store's
    forensic bundle — each None when absent (e.g. a checkpoint-less
    run).  The ONE place the bundle layout is known outside the trainer
    that writes it (the population driver shares it)."""
    if not ckpt_dir:
        return None, None
    from ..sim.replay import ABORT_CONTEXT_FILE
    from .train import ABORT_CKPT_SUBDIR

    bundle = os.path.join(ckpt_dir, ABORT_CKPT_SUBDIR)
    if not os.path.isdir(bundle):
        return None, None
    ctx = os.path.join(bundle, ABORT_CONTEXT_FILE)
    return bundle, (ctx if os.path.exists(ctx) else None)


def _latest_healthy(ckpt_dirs: List[str]):
    """(dir, step) of the newest healthy checkpoint, newest segment first.

    Only the ``step_*`` namespace counts — the forensic ``aborted/``
    subtree a RunAbort saves is deliberately invisible here.  "Healthy"
    means VERIFIED since round 12: ``latest_step(verified=True)`` digest-
    checks each candidate and walks past uncommitted/corrupt directories
    (a crash mid-``save_checkpoint`` strands only ``*_tmp`` staging
    debris, but bit rot on the newest step must degrade the rollback to
    the previous one, not turn one abort into a campaign failure).
    """
    from ..utils.checkpoint import latest_step

    for d in reversed(ckpt_dirs):
        step = latest_step(d, verified=True)
        if step is not None:
            return d, step
    return None, None


def _rollback_agent(agent, fleet: FleetSpec, params: SimParams,
                    ckpt_dir: str, step: int, sim_like=None) -> None:
    """Restore the LEARNER side (sac/replay/key) from a checkpoint.

    The simulator state is deliberately discarded: a retry re-inits the
    environment under the reseeded curriculum — keep the brain, restart
    the world.  The checkpoint's sim/csv subtrees are restored against
    a throwaway template purely to satisfy the pytree structure; pass a
    live ``sim_like`` (any state of the run shape — segment shapes are
    stage/reseed-invariant) to skip rebuilding one, which re-compiles
    the workload tables on trace-heavy configs.
    """
    import jax

    from ..utils.checkpoint import restore_checkpoint
    from .train import _wm_like

    if sim_like is None:
        from ..sim.engine import init_state

        sim_like = init_state(jax.random.key(params.seed), fleet, params)
    like = {"sac": agent.sac, "replay": agent.replay, "key": agent.key,
            "sim": sim_like, "csv": _wm_like(params)}
    # _latest_healthy already digest-verified the chosen step
    out = restore_checkpoint(ckpt_dir, step, like=like, verify=False)
    agent.sac, agent.replay, agent.key = out["sac"], out["replay"], out["key"]


def _curriculum_of(params: SimParams):
    if params.faults is None or params.faults.curriculum is None:
        raise ValueError(
            "run_campaign needs params.faults.curriculum (a "
            "ChaosCurriculum) — build one with fault.make_chaos_preset or "
            "load a JSON spec")
    return params.faults.curriculum


def _with_curriculum(params: SimParams, cur) -> SimParams:
    return dataclasses.replace(
        params, faults=dataclasses.replace(params.faults, curriculum=cur))


def run_campaign(
    fleet: FleetSpec,
    params: SimParams,
    out_dir: Optional[str] = None,
    ckpt_dir: Optional[str] = None,
    chunk_steps: int = 2048,
    max_chunks: int = 10_000,
    config: Optional[CampaignConfig] = None,
    monitor: Optional[DivergenceMonitor] = None,
    agent=None,
    verbose: bool = False,
    shutdown=None,
    **train_kw,
):
    """Train CHSAC through the curriculum's severity stages, self-healing.

    Returns ``(state, agent, report)`` where ``state`` is the final
    segment's SimState, ``agent`` the trained CHSAC_AF, and ``report``
    the campaign summary dict (also written to
    ``out_dir/campaign_summary.json``).  Raises :class:`CampaignError`
    when the retry budget runs out (summary still written, with
    ``status="failed"``), and re-raises a SIGTERM-style interruption's
    partial state as a normal return with ``status="interrupted"``.

    Refuses to train on the held-out evaluation presets
    (:data:`~..fault.curriculum.HELD_OUT_PRESETS`) — scores on those
    must stay out-of-distribution.

    ``train_kw`` passes through to :func:`~.train.train_chsac`
    (``train_every_n``, ``max_train_steps_per_chunk``, ...).
    """
    from ..fault.curriculum import HELD_OUT_PRESETS
    from ..obs.export import ObsConfig
    from ..obs.trace import PhaseTimer

    import tempfile

    config = config or CampaignConfig()
    monitor = monitor or DivergenceMonitor(config.divergence)
    cur = _curriculum_of(params)
    tmp_ctx = None
    if out_dir is None and params.obs_enabled:
        # the watchdog gate lives in the per-segment ObsSink, which
        # needs somewhere to export; a summary-less campaign (eval
        # harness use) gets a throwaway scratch dir instead of littering
        # the caller's cwd
        tmp_ctx = tempfile.TemporaryDirectory(prefix="dcg_campaign_")
        out_dir = tmp_ctx.name
    if cur.name in HELD_OUT_PRESETS:
        raise ValueError(
            f"curriculum {cur.name!r} is a held-out evaluation preset; "
            "training on it would contaminate the held-out chaos scores")
    if params.obs_enabled and config.watchdog not in ("off", "warn", "raise"):
        raise ValueError(f"unknown watchdog mode {config.watchdog!r}")
    if agent is None:
        agent = make_agent(fleet, params)

    n_stages = len(cur.stages)
    reseed = cur.reseed
    aborts_left = config.retries
    attempts: List[Dict] = []
    ckpt_dirs: List[str] = []
    state = None
    status = "completed"

    def seg_paths(stage: int, attempt: int):
        tag = f"stage{stage:02d}_try{attempt:02d}"
        seg_out = os.path.join(out_dir, tag) if out_dir else None
        seg_ckpt = (os.path.join(ckpt_dir, tag) if ckpt_dir
                    else (os.path.join(out_dir, "ckpt", tag) if out_dir
                          else None))
        return tag, seg_out, seg_ckpt

    def write_summary(status: str) -> Dict:
        report = {
            "schema": "dcg.campaign_summary.v1",
            "schema_version": 1,
            "status": status,
            "curriculum": cur.name,
            "n_stages": n_stages,
            "retry_budget": config.retries,
            "retries_used": config.retries - aborts_left,
            "watchdog": config.watchdog if params.obs_enabled else "off",
            "attempts": attempts,
        }
        if out_dir:
            dump_json_atomic(os.path.join(out_dir, CAMPAIGN_SUMMARY_FILE),
                             report)
        return report

    try:
        stage = 0
        attempt_no = 0
        while stage < n_stages:
            tag, seg_out, seg_ckpt = seg_paths(stage, attempt_no)
            seg_params = _with_curriculum(
                params, cur.at_stage(stage).reseeded(reseed))
            obs_cfg = (ObsConfig(out_dir=seg_out or out_dir,
                                 watchdog=config.watchdog)
                       if params.obs_enabled else None)
            timer = PhaseTimer(record_spans=True)
            rec = {"stage": stage, "attempt": attempt_no, "reseed": reseed,
                   "dir": tag}
            if verbose:
                print(f"campaign {tag}: stage {stage + 1}/{n_stages} "
                      f"reseed={reseed}")
            try:
                state, agent, history = train_chsac(
                    fleet, seg_params, out_dir=seg_out,
                    chunk_steps=chunk_steps, max_chunks=max_chunks,
                    agent=agent, verbose=verbose, ckpt_dir=seg_ckpt,
                    resume=False, timer=timer, obs=obs_cfg,
                    shutdown=shutdown,
                    on_chunk=lambda c, s, h, _m=monitor: _m.check(
                        c, h[-1] if h else None),
                    **train_kw)
            except RunAbort as e:
                rec.update(outcome="aborted", reason=str(e),
                           kind=("divergence"
                                 if isinstance(e, DivergenceError)
                                 else "watchdog"))
                if seg_out:
                    rec["trace"] = timer.save_chrome_trace(
                        os.path.join(seg_out, "abort_trace.json"))
                attempts.append(rec)
                if seg_ckpt:
                    ckpt_dirs.append(seg_ckpt)
                if aborts_left == 0:
                    write_summary("failed")
                    raise CampaignError(
                        f"campaign retry budget exhausted after "
                        f"{len(attempts)} attempt(s); last abort: {e}",
                        attempts=attempts,
                        abort_context=_abort_bundle(seg_ckpt)[1],
                    ) from e
                # self-heal: roll the learner back to the last healthy
                # checkpoint, re-draw the chaos, back off, retry
                src, step = _latest_healthy(ckpt_dirs)
                if src is not None:
                    # `state` (a completed earlier segment's final
                    # state, shape-identical) doubles as the template
                    _rollback_agent(agent, fleet, seg_params, src, step,
                                    sim_like=state)
                    rec["rollback"] = {"dir": os.path.relpath(
                        src, ckpt_dir or out_dir or "."), "step": step}
                else:
                    # no healthy checkpoint yet: restart the learner fresh
                    agent = make_agent(fleet, params)
                    rec["rollback"] = None
                backoff = config.backoff_s * (
                    2 ** (config.retries - aborts_left))
                if backoff > 0:
                    time.sleep(backoff)
                aborts_left -= 1
                reseed += 1
                attempt_no += 1
                continue
            if seg_ckpt:
                ckpt_dirs.append(seg_ckpt)
            if seg_out:
                rec["trace"] = timer.save_chrome_trace(
                    os.path.join(seg_out, "trace.json"))
            if shutdown is not None and shutdown.requested:
                rec.update(outcome="interrupted")
                attempts.append(rec)
                status = "interrupted"
                break
            rec.update(outcome="completed",
                       sim_t_s=float(np.asarray(state.t)),
                       train_steps=int(agent.sac.step))
            attempts.append(rec)
            stage += 1
            attempt_no += 1

        report = write_summary(status)
        return state, agent, report
    finally:
        if tmp_ctx is not None:
            tmp_ctx.cleanup()

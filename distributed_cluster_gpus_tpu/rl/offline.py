"""Build an offline RL dataset (npz) from a finished run's CSV logs.

Counterpart of `/root/reference/simcore/rl/offline_schema_example.py:6-46`
(unwired there; wired here).  Reconstructs one single-step transition per
completed job from `job_log.csv`, synthesizing the observation from the
nearest `cluster_log.csv` tick at the job's start time — the same
[t] + per-DC [total, busy, free, f, q_inf, q_trn] layout (normalized) the
live engine emits, so a dataset built from logs trains the same networks as
one captured from the replay buffer (`replay.save_offline_npz`).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..models.structs import FleetSpec
from .cmdp import N_COSTS


def build_offline_npz_from_logs(run_dir: str, fleet: FleetSpec, path: str,
                                sla_p99_ms: float = 500.0,
                                max_gpus_per_job: int = 8,
                                limit: Optional[int] = None) -> int:
    """Convert ``run_dir``'s CSVs into an offline npz; returns row count."""
    import pandas as pd

    cl = pd.read_csv(os.path.join(run_dir, "cluster_log.csv"))
    jb = pd.read_csv(os.path.join(run_dir, "job_log.csv"))
    if limit:
        jb = jb.iloc[:limit]
    dc_index = {name: i for i, name in enumerate(fleet.dc_names)}
    n_dc = fleet.n_dc
    total = fleet.total_gpus.astype(np.float32)

    # pivot cluster log into per-tick [n_dc] feature arrays
    ticks = np.sort(cl["time_s"].unique())
    feat = {}
    for col in ("busy", "q_inf", "q_train", "freq", "energy_kJ"):
        pv = cl.pivot_table(index="time_s", columns="dc", values=col,
                            aggfunc="first")
        pv = pv.reindex(columns=list(fleet.dc_names)).sort_index()
        feat[col] = pv.to_numpy(np.float32)
    # cumulative fleet energy (J) per tick, for the energy_total cost
    energy_total_j = np.nansum(feat["energy_kJ"], axis=1) * 1000.0

    def obs_at(t: float) -> np.ndarray:
        k = int(np.clip(np.searchsorted(ticks, t) - 1, 0, len(ticks) - 1))
        busy, q_inf = feat["busy"][k], feat["q_inf"][k]
        q_trn, freq = feat["q_train"][k], feat["freq"][k]
        free = np.maximum(0.0, total - busy)
        cols = np.stack([np.log1p(total) / 7.0, busy / total, free / total,
                         freq, np.log1p(q_inf) / 4.0, np.log1p(q_trn) / 4.0],
                        axis=-1).reshape(-1)
        return np.concatenate([[np.float32((t % 86400.0) / 86400.0)], cols])

    n = len(jb)
    obs_dim = 1 + 6 * n_dc
    s0 = np.zeros((n, obs_dim), np.float32)
    s1 = np.zeros((n, obs_dim), np.float32)
    a_dc = np.zeros((n,), np.int32)
    a_g = np.zeros((n,), np.int32)
    r = np.zeros((n,), np.float32)
    costs = np.zeros((n, N_COSTS), np.float32)
    for i, row in enumerate(jb.itertuples()):
        s0[i] = obs_at(row.start_s)
        s1[i] = obs_at(row.finish_s)
        a_dc[i] = dc_index[row.dc]
        g = int(row.n_gpus)
        a_g[i] = min(max(0, g - 1), max_gpus_per_job - 1)
        e_unit_kwh = row.E_pred / 3.6e6
        r[i] = -e_unit_kwh + 0.05 / max(1, g)
        costs[i, 0] = row.latency_s * 1000.0  # latency (ms) proxy for p99
        costs[i, 1] = row.P_pred
        costs[i, 2] = 0.0  # gpu_over needs the SLA model; left 0 offline
        k = int(np.clip(np.searchsorted(ticks, row.finish_s) - 1, 0,
                        len(ticks) - 1))
        costs[i, 3] = energy_total_j[k]

    np.savez_compressed(
        path,
        s0=s0, s1=s1, a_dc=a_dc, a_g=a_g, r=r,
        done=np.ones((n,), np.float32),
        mask_dc=np.ones((n, n_dc), bool),
        mask_g=np.ones((n, max_gpus_per_job), bool),
        mask_dc0=np.ones((n, n_dc), bool),
        mask_g0=np.ones((n, max_gpus_per_job), bool),
        **{"costs/latency_p99": costs[:, 0], "costs/power": costs[:, 1],
           "costs/gpu_over": costs[:, 2], "costs/energy_total": costs[:, 3]},
    )
    return n


def _main(argv=None):
    """CLI: run CSVs -> offline npz (`--offline-dataset` feeds on this)."""
    import argparse

    # honor an explicit cpu request (the axon TPU plugin force-selects
    # itself via jax.config, silently overriding the env var)
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        import jax

        jax.config.update("jax_platforms", "cpu")

    p = argparse.ArgumentParser(
        description="Build an offline RL dataset (npz) from a run's CSV logs")
    p.add_argument("run_dir", help="directory holding cluster_log.csv + job_log.csv")
    p.add_argument("out", help="output .npz path")
    p.add_argument("--single-dc", action="store_true")
    p.add_argument("--limit", type=int, default=None)
    p.add_argument("--max-gpus-per-job", type=int, default=8,
                   help="must match the run's --max-gpus-per-job (sizes mask_g)")
    p.add_argument("--sla-p99-ms", type=float, default=500.0)
    a = p.parse_args(argv)
    from ..configs import build_fleet, build_single_dc_fleet

    fleet = build_single_dc_fleet() if a.single_dc else build_fleet()
    n = build_offline_npz_from_logs(a.run_dir, fleet, a.out, limit=a.limit,
                                    sla_p99_ms=a.sla_p99_ms,
                                    max_gpus_per_job=a.max_gpus_per_job)
    print(f"wrote {n} transitions to {a.out}")


if __name__ == "__main__":
    _main()

"""On-policy PPO variant for the hybrid (dc, g) scheduling action.

The reference ships only the off-policy CHSAC-AF agent; BASELINE.json's
config 5 ("1024-way vmapped multi-DC rollouts + PPO policy, pjit-sharded")
calls for an on-policy learner that pairs naturally with massive vmapped
rollout batches: collect one scan chunk of transitions from R worlds acting
under the CURRENT policy, then take K clipped-surrogate epochs on that batch
— no replay buffer, no target networks.

Decisions are single-step episodes (as in the reference's SAC formulation,
`simulator_paper_multi.py:799`), so the advantage is simply
``A = r_eff - V(s0)`` with a learned state-value baseline; the CMDP
Lagrangian folds constraint costs into r_eff exactly as the SAC path does,
sharing `cmdp.py`.

Everything is fixed-shape: the chunk's transition stream keeps its validity
mask and every loss term is mask-weighted, so the whole update jits and
shards with pmean gradient allreduce like the SAC update.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct

from .cmdp import CMDPState, ConstraintSpec, cmdp_init, effective_reward, update_lagrange
from .nets import HybridActor, MLPStateEncoder


class ValueCritic(nn.Module):
    """latent -> scalar V(s)."""

    hidden: int = 256
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, latent):
        x = nn.relu(nn.Dense(self.hidden, dtype=self.compute_dtype)(
            latent.astype(self.compute_dtype)))
        v = nn.Dense(1, dtype=self.compute_dtype)(x)
        return v.astype(jnp.float32)[..., 0]


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    obs_dim: int
    n_dc: int
    n_g: int
    latent: int = 256
    lr: float = 3e-4
    clip_eps: float = 0.2
    vf_coef: float = 0.5
    vf_huber_delta: float = 1.0
    ent_coef: float = 0.01
    epochs: int = 4
    grad_clip: float = 0.5
    constraints: Tuple[ConstraintSpec, ...] = ()

    def __post_init__(self):
        assert self.constraints, "PPOConfig needs at least one ConstraintSpec"


@struct.dataclass
class PPOState:
    enc_params: dict
    actor_params: dict
    value_params: dict
    opt_state: optax.OptState
    cmdp: CMDPState
    step: jnp.ndarray


def _modules(cfg: PPOConfig):
    return (MLPStateEncoder(latent=cfg.latent),
            HybridActor(n_dc=cfg.n_dc, n_g=cfg.n_g),
            ValueCritic())


def _tx(cfg: PPOConfig):
    return optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                       optax.adam(cfg.lr))


def ppo_init(cfg: PPOConfig, key) -> PPOState:
    enc, actor, value = _modules(cfg)
    k_e, k_a, k_v = jax.random.split(key, 3)
    obs = jnp.zeros((1, cfg.obs_dim), jnp.float32)
    enc_p = enc.init(k_e, obs)
    lat = enc.apply(enc_p, obs)
    actor_p = actor.init(k_a, lat, jnp.ones((1, cfg.n_dc), bool),
                         jnp.ones((1, cfg.n_g), bool))
    value_p = value.init(k_v, lat)
    params = (enc_p, actor_p, value_p)
    return PPOState(
        enc_params=enc_p, actor_params=actor_p, value_params=value_p,
        opt_state=_tx(cfg).init(params),
        cmdp=cmdp_init(cfg.constraints),
        step=jnp.int32(0),
    )


def make_ppo_policy_apply(cfg: PPOConfig, greedy: bool = False):
    """Engine-compatible policy_apply over PPOState."""
    enc, actor, _ = _modules(cfg)

    def policy_apply(ppo: PPOState, obs, mask_dc, mask_g, key):
        lat = enc.apply(ppo.enc_params, obs[None])
        logp_dc, logp_g = actor.apply(ppo.actor_params, lat,
                                      mask_dc[None], mask_g[None])
        if greedy:
            return (jnp.argmax(logp_dc[0]).astype(jnp.int32),
                    jnp.argmax(logp_g[0]).astype(jnp.int32))
        k1, k2 = jax.random.split(key)
        return (jax.random.categorical(k1, logp_dc[0]).astype(jnp.int32),
                jax.random.categorical(k2, logp_g[0]).astype(jnp.int32))

    return policy_apply


def _logp_of(cfg: PPOConfig, enc_p, actor_p, batch):
    """Joint log-prob/entropy of the stored actions under the ACTION-TIME
    masks (``mask_dc0``/``mask_g0`` captured when the action was sampled —
    the plain ``mask_dc``/``mask_g`` in the emission are s1 masks for the
    SAC target policy and would mis-grade the behavior policy here)."""
    enc, actor, _ = _modules(cfg)
    lat = enc.apply(enc_p, batch["s0"])
    m_dc = batch.get("mask_dc0", batch["mask_dc"])
    m_g = batch.get("mask_g0", batch["mask_g"])
    logp_dc, logp_g = actor.apply(actor_p, lat, m_dc, m_g)
    lp = (jnp.take_along_axis(logp_dc, batch["a_dc"][:, None], axis=-1)[:, 0]
          + jnp.take_along_axis(logp_g, batch["a_g"][:, None], axis=-1)[:, 0])
    ent = (-jnp.sum(jnp.exp(logp_dc) * logp_dc, axis=-1)
           - jnp.sum(jnp.exp(logp_g) * logp_g, axis=-1))
    return lp, ent, lat


def ppo_update(cfg: PPOConfig, ppo: PPOState, batch,
               axis_name: Optional[str] = None):
    """K clipped-surrogate epochs over one on-policy chunk batch.

    ``batch`` is the engine's flattened RL emission stream (leading axis N)
    including ``valid``; invalid rows carry zero weight.  Returns
    (new PPOState, metrics).
    """
    _, _, value = _modules(cfg)
    w = batch["valid"].astype(jnp.float32)
    w_sum = jnp.maximum(jnp.sum(w), 1.0)

    targets = jnp.asarray([c.target for c in cfg.constraints], jnp.float32)
    r_eff = effective_reward(batch["r"], batch["costs"], ppo.cmdp.lam, targets)

    # frozen behavior-policy log-probs (the chunk was collected under ppo)
    old_lp, _, lat0 = _logp_of(cfg, ppo.enc_params, ppo.actor_params, batch)
    old_lp = jax.lax.stop_gradient(old_lp)
    v_old = value.apply(ppo.value_params, lat0)
    adv = r_eff - jax.lax.stop_gradient(v_old)
    # masked advantage normalization
    mean = jnp.sum(adv * w) / w_sum
    var = jnp.sum(w * (adv - mean) ** 2) / w_sum
    if axis_name is not None:
        mean = jax.lax.pmean(mean, axis_name)
        var = jax.lax.pmean(var, axis_name)
    adv = (adv - mean) / jnp.sqrt(var + 1e-8)

    tx = _tx(cfg)

    def loss_fn(params):
        enc_p, actor_p, value_p = params
        lp, ent, lat = _logp_of(cfg, enc_p, actor_p, batch)
        ratio = jnp.exp(lp - old_lp)
        clipped = jnp.clip(ratio, 1.0 - cfg.clip_eps, 1.0 + cfg.clip_eps)
        pg = -jnp.sum(w * jnp.minimum(ratio * adv, clipped * adv)) / w_sum
        v = value.apply(value_p, lat)
        # Huber (not squared) value loss: the Lagrangian penalty makes the
        # regression target r_eff heavy-tailed as lambda ramps (raw-unit
        # constraint violations, e.g. p99 in ms), and squared error lets a
        # few penalized transitions blow up the whole update
        vf = jnp.sum(w * optax.huber_loss(v, r_eff,
                                          delta=cfg.vf_huber_delta)) / w_sum
        ent_mean = jnp.sum(w * ent) / w_sum
        loss = pg + cfg.vf_coef * vf - cfg.ent_coef * ent_mean
        return loss, (pg, vf, ent_mean)

    def epoch(carry, _):
        params, opt_state = carry
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        if axis_name is not None:
            grads = jax.lax.pmean(grads, axis_name)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), (loss, *aux)

    params0 = (ppo.enc_params, ppo.actor_params, ppo.value_params)
    (params, opt_state), traces = jax.lax.scan(
        epoch, (params0, ppo.opt_state), None, length=cfg.epochs)
    enc_p, actor_p, value_p = params

    cmdp, viol = update_lagrange(ppo.cmdp, cfg.constraints, batch["costs"],
                                 axis_name=axis_name, weights=w)
    ppo = ppo.replace(enc_params=enc_p, actor_params=actor_p,
                      value_params=value_p, opt_state=opt_state,
                      cmdp=cmdp, step=ppo.step + 1)
    loss, pg, vf, ent = (t[-1] for t in traces)
    metrics = {"loss": loss, "pg_loss": pg, "vf_loss": vf, "entropy": ent,
               "lambda": cmdp.lam, "violation": viol,
               "n_transitions": jnp.sum(w),
               "r_mean": jnp.sum(w * batch["r"]) / w_sum,  # unpenalized reward
               "r_eff_mean": jnp.sum(w * r_eff) / w_sum}
    return ppo, metrics

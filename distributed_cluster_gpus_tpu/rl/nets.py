"""Flax networks for CHSAC-AF.

TPU-native counterparts of the reference torch modules
(`/root/reference/simcore/rl/encoders.py:5-18`,
`/root/reference/simcore/rl/hybrid_sac.py:10-80`):

* :class:`MLPStateEncoder` — 3-layer ReLU MLP obs -> 256 latent.
* :class:`HybridActor` — two categorical heads (destination DC, GPU count)
  over the shared latent, with masked log-softmax.
* :class:`QuantileCritic` — twin MLPs mapping (latent, onehot(a_dc),
  onehot(a_g)) -> N quantiles of the return distribution (QR-DQN style).

All matmuls run in bfloat16 on the MXU with float32 params/outputs
(`jnp.bfloat16` dtype argument), which is the idiomatic TPU mixed-precision
recipe; sizes (256-wide, batch 256) keep the MXU tiles full.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLPStateEncoder(nn.Module):
    """obs [B, obs_dim] -> latent [B, latent]; 3-layer ReLU MLP."""

    latent: int = 256
    hidden: Sequence[int] = (256, 256)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(self.compute_dtype)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h, dtype=self.compute_dtype)(x))
        x = nn.relu(nn.Dense(self.latent, dtype=self.compute_dtype)(x))
        return x.astype(jnp.float32)


class HybridActor(nn.Module):
    """Latent -> masked categorical logits for the two discrete heads.

    Head sizes: ``n_dc`` (destination DC) and ``n_g`` (GPU count, action g
    encodes n = g + 1).  Returns float32 log-probabilities with invalid
    actions at -inf (masked log-softmax — parity with the reference's
    `masked_softmax` `rl/utils.py:38-47`).
    """

    n_dc: int
    n_g: int
    hidden: int = 256
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, latent, mask_dc, mask_g):
        x = nn.relu(nn.Dense(self.hidden, dtype=self.compute_dtype)(
            latent.astype(self.compute_dtype)))
        logit_dc = nn.Dense(self.n_dc, dtype=self.compute_dtype)(x).astype(jnp.float32)
        logit_g = nn.Dense(self.n_g, dtype=self.compute_dtype)(x).astype(jnp.float32)
        neg = jnp.float32(-1e9)
        logit_dc = jnp.where(mask_dc, logit_dc, neg)
        logit_g = jnp.where(mask_g, logit_g, neg)
        logp_dc = nn.log_softmax(logit_dc, axis=-1)
        logp_g = nn.log_softmax(logit_g, axis=-1)
        return logp_dc, logp_g


class QuantileCritic(nn.Module):
    """Twin quantile critics: (latent, a_dc, a_g) -> [B, 2, n_quantiles].

    One-hot action encoding matches the reference critic input
    (`hybrid_sac.py:52-80`); the twin is a second identically-shaped MLP.
    """

    n_dc: int
    n_g: int
    n_quantiles: int = 32
    hidden: Sequence[int] = (256, 256)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, latent, a_dc, a_g):
        onehot_dc = jnp.eye(self.n_dc, dtype=jnp.float32)[a_dc]
        onehot_g = jnp.eye(self.n_g, dtype=jnp.float32)[a_g]
        x0 = jnp.concatenate([latent, onehot_dc, onehot_g], axis=-1)

        outs = []
        for _ in range(2):
            x = x0.astype(self.compute_dtype)
            for h in self.hidden:
                x = nn.relu(nn.Dense(h, dtype=self.compute_dtype)(x))
            q = nn.Dense(self.n_quantiles, dtype=self.compute_dtype)(x)
            outs.append(q.astype(jnp.float32))
        return jnp.stack(outs, axis=1)  # [B, 2, n_quantiles]

    def all_actions(self, latent):
        """Quantiles for every joint action: [B, 2, n_dc * n_g, n_quantiles].

        Discrete SAC's actor/target terms need Q over *all* actions; instead
        of tiling batch x actions on the host we tile inside the module so
        XLA fuses it into one big MXU matmul.
        """
        B = latent.shape[0]
        n_act = self.n_dc * self.n_g
        acts = jnp.arange(n_act)
        a_dc = acts // self.n_g
        a_g = acts % self.n_g
        lat_t = jnp.repeat(latent, n_act, axis=0)
        q = self(lat_t, jnp.tile(a_dc, B), jnp.tile(a_g, B))
        return q.reshape(B, n_act, 2, -1).transpose(0, 2, 1, 3)


class QuantileCriticHeads(nn.Module):
    """Twin quantile critics with per-joint-action output heads.

    Same role as :class:`QuantileCritic` but a different parameterization:
    latent -> MLP -> Dense(n_dc * n_g * n_quantiles), so the exact
    marginalization over all joint actions costs ONE forward per twin
    instead of a batch x n_actions tiled pass (~14x fewer FLOPs at
    8 x 8 actions) — the classic dueling/DQN-style head layout.  Opt-in via
    ``SACConfig.critic_arch = "heads"``; the default stays the reference's
    one-hot-action-input critic (`hybrid_sac.py:52-80`).
    """

    n_dc: int
    n_g: int
    n_quantiles: int = 32
    hidden: Sequence[int] = (256, 256)
    compute_dtype: jnp.dtype = jnp.bfloat16

    def setup(self):
        n_out = self.n_dc * self.n_g * self.n_quantiles
        self.twins = [
            [nn.Dense(h, dtype=self.compute_dtype) for h in self.hidden]
            + [nn.Dense(n_out, dtype=self.compute_dtype)]
            for _ in range(2)
        ]

    def all_actions(self, latent):
        """[B, 2, n_dc * n_g, n_quantiles] — one forward per twin."""
        B = latent.shape[0]
        n_act = self.n_dc * self.n_g
        outs = []
        for layers in self.twins:
            x = latent.astype(self.compute_dtype)
            for lyr in layers[:-1]:
                x = nn.relu(lyr(x))
            q = layers[-1](x).astype(jnp.float32)
            outs.append(q.reshape(B, n_act, self.n_quantiles))
        return jnp.stack(outs, axis=1)

    def __call__(self, latent, a_dc, a_g):
        """Taken-action quantiles [B, 2, n_quantiles] (gather from heads)."""
        q = self.all_actions(latent)  # [B, 2, A, N]
        idx = (a_dc * self.n_g + a_g)[:, None, None, None]
        return jnp.take_along_axis(
            q, jnp.broadcast_to(idx, (q.shape[0], 2, 1, q.shape[-1])),
            axis=2)[:, :, 0, :]

"""Population-based chaos training: a fault-isolated CHSAC learner zoo.

``rl/campaign.py`` self-heals ONE learner serially — every watchdog or
divergence trip stalls the whole campaign for a rollback + reseeded
retry.  This driver trains a *population* of N CHSAC members through the
same chaos curriculum, each under an independently drawn curriculum
reseed and (optionally) perturbed hyperparameters, with **per-member
fault isolation**:

* every member runs its segments under its own out/checkpoint tree
  (``<pop_root>/member_<k>/``) with a member-labeled watchdog
  (:class:`~..obs.export.ObsConfig` ``member``) and divergence monitor —
  a :class:`~..obs.health.RunAbort` **quarantines only the tripping
  member**: its forensic bundle (abort_context + aborted checkpoint,
  the PR-10 machinery) lands under ``member_<k>/ck/<segment>/aborted``,
  the member rolls back to its last verified-healthy step via the
  fallback chain, re-draws its chaos under ``reseed + 1``, and retries
  under a per-member budget while the rest of the population never
  stops;
* a member whose budget is exhausted — or whose ENTIRE checkpoint store
  fails verification, so there is nothing healthy to roll back to — is
  **culled** and replaced at the next PBT interval by a reseeded clone
  of the best-scoring survivor (weights grafted through
  :func:`~.train.warm_sac_from_checkpoint`);
* at each PBT interval (= curriculum severity stage boundary) the
  members are ranked on **held-out chaos metrics**: every member's
  policy rolls the SAME held-out realization forward as one vmapped
  program (:func:`~..parallel.rollout.replicated_init` lanes — identical
  workload + fault streams, only the per-lane weights differ) and the
  summary rows score through :func:`~..evaluation.chaos_score`
  (availability, migration_success_rate, energy/price/carbon, drops).
  The bottom ``exploit_quantile`` **exploit** (winner weights grafted
  via the warm-checkpoint path) and **explore** (curriculum reseed bump,
  lr/alpha jitter when ``perturb_scale > 0``).

The whole population state — member table, scores, lineage, quarantine
log — commits atomically as one manifest through the verified checkpoint
store (``<pop_root>/manifest_store/step_<i>``: staged dir + sha256
manifest + COMMIT + rename, crash-injectable via DCG_CKPT_CRASH_POINT),
so a killed driver resumes the EXACT member table from the last
committed interval.  ``population_manifest.json`` at the root is a
human-readable mirror of the same document.  Output:
``population_summary.json`` with the reproducible leaderboard —
:func:`evaluate_population` re-runs the held-out eval from the stored
checkpoints and reproduces the ranking.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.structs import FleetSpec, SimParams
from ..obs.health import DivergenceError, RunAbort
from ..utils.checkpoint import (POP_MANIFEST_STORE, gc_population,
                                restore_latest, save_checkpoint, steps)
from ..utils.jsonio import clean_nan, dump_json_atomic
from .campaign import (DivergenceConfig, DivergenceMonitor, _abort_bundle,
                       _curriculum_of, _latest_healthy, _rollback_agent)
from .train import make_agent, train_chsac, warm_sac_from_checkpoint

POPULATION_MANIFEST_FILE = "population_manifest.json"
POPULATION_SUMMARY_FILE = "population_summary.json"
MANIFEST_SCHEMA = "dcg.population_manifest.v1"
SUMMARY_SCHEMA = "dcg.population_summary.v1"


class PopulationError(RuntimeError):
    """The population campaign cannot continue (every member culled, or
    the manifest is unreadable).

    Structured context for automation (same contract as
    :class:`~.campaign.CampaignError`): ``quarantine`` is the
    member-labeled quarantine/attempt history, ``abort_context`` the
    path of the LAST quarantined member's forensic
    ``abort_context.json`` (feed it to ``scripts/replay_abort.py
    --member K``), or None when no bundle was written.
    """

    def __init__(self, msg: str, quarantine: Optional[List[Dict]] = None,
                 abort_context: Optional[str] = None):
        super().__init__(msg)
        self.quarantine = list(quarantine or [])
        self.abort_context = abort_context


@dataclasses.dataclass(frozen=True)
class PopulationConfig:
    """Knobs for :func:`run_population`.

    ``member_retries`` is the PER-MEMBER quarantine budget (the serial
    campaign's ``retries`` was campaign-global; with ``n_members=1`` the
    two coincide).  ``exploit_quantile=0`` disables cross-member weight
    grafts entirely — members stay byte-independent, which is what the
    fault-isolation guarantee is proved against.  ``perturb_scale=0``
    disables hyperparameter jitter (members differ only by seed/reseed);
    > 0 draws log-normal lr / alpha_init factors with that sigma.
    """

    n_members: int = 4
    member_retries: int = 2
    exploit_quantile: float = 0.25
    perturb_scale: float = 0.0
    backoff_s: float = 0.0
    watchdog: str = "raise"
    divergence: DivergenceConfig = DivergenceConfig()
    # held-out leaderboard eval (every PBT interval + the final ranking)
    eval_preset: str = "held_out_regional_blackout"
    eval_duration: float = 120.0
    eval_chunk_steps: int = 512
    eval_max_chunks: int = 256

    def __post_init__(self):
        if self.n_members < 1:
            raise ValueError("n_members must be >= 1")
        if self.member_retries < 0:
            raise ValueError("member_retries must be >= 0")
        if not 0.0 <= self.exploit_quantile < 1.0:
            raise ValueError("exploit_quantile must be in [0, 1)")
        if self.perturb_scale < 0:
            raise ValueError("perturb_scale must be >= 0")
        if self.backoff_s < 0:
            raise ValueError("backoff_s must be >= 0")


# ---------------------------------------------------------------------------
# manifest persistence (verified checkpoint store)
# ---------------------------------------------------------------------------

def save_population_manifest(pop_root: str, step: int, manifest: Dict) -> None:
    """Commit the population manifest atomically through the verified store.

    The strict-JSON bytes ride :func:`~..utils.checkpoint.save_checkpoint`
    (stage -> manifest -> COMMIT -> rename, per-file sha256 digests) into
    ``<pop_root>/manifest_store/step_<step>`` — a SIGKILL at ANY instant
    leaves the previous interval's commit restorable, and the
    DCG_CKPT_CRASH_POINT injection hooks work unchanged.  The
    human-readable ``population_manifest.json`` mirror at the root is a
    derived copy; the store is authoritative for resume.
    """
    payload = np.frombuffer(
        json.dumps(clean_nan(manifest), default=float).encode(),
        np.uint8).copy()
    save_checkpoint(os.path.join(pop_root, POP_MANIFEST_STORE), step=step,
                    metadata={"kind": "population_manifest",
                              "interval_step": int(step)},
                    manifest={"json": payload})
    dump_json_atomic(os.path.join(pop_root, POPULATION_MANIFEST_FILE),
                     manifest)


def load_population_manifest(pop_root: str
                             ) -> Tuple[Optional[int], Optional[Dict]]:
    """(step, manifest) of the newest VERIFIED manifest commit.

    Walks the fallback chain — a torn or bit-rotted newest commit is
    skipped with a logged reason and the previous interval's manifest
    restores instead.  Returns ``(None, None)`` when the store is empty
    or nothing restores.
    """
    store = os.path.join(pop_root, POP_MANIFEST_STORE)
    if not steps(store):
        return None, None
    try:
        step, out = restore_latest(store)
    except FileNotFoundError:
        return None, None
    doc = json.loads(np.asarray(out["manifest"]["json"],
                                np.uint8).tobytes().decode())
    if doc.get("schema") != MANIFEST_SCHEMA:
        raise PopulationError(
            f"{store}: unknown population manifest schema "
            f"{doc.get('schema')!r}")
    return step, doc


# ---------------------------------------------------------------------------
# member bookkeeping
# ---------------------------------------------------------------------------

def _member_seed(base_seed: int, k: int, generation: int = 0) -> int:
    """Deterministic per-(slot, clone-generation) seed — a pure function
    of the base seed, so no member's draw depends on another's fate."""
    return int(base_seed + 7919 * k + 104729 * generation)


def _draw_hyper(base: Dict, base_seed: int, k: int, scale: float,
                salt: int = 0) -> Dict:
    """Log-normal jitter of the perturbable hyperparameters (identity for
    member 0 at init — the unperturbed reference lineage — and whenever
    ``scale == 0``)."""
    if scale <= 0 or (salt == 0 and k == 0):
        return dict(base)
    rng = np.random.default_rng([abs(int(base_seed)), k, salt])
    return {
        "lr": float(base["lr"] * np.exp(rng.normal(0.0, scale))),
        "alpha_init": float(base["alpha_init"]
                            * np.exp(rng.normal(0.0, scale))),
    }


def _apply_hyper(agent, hyper: Dict, reinit: bool = True):
    """Re-specialize an agent to a member's hyperparameters.

    lr / alpha_init are static fields of SACConfig, so a change rebuilds
    the learner state and the jitted update closures; an identity hyper
    leaves the agent untouched (no recompile).  ``reinit=False`` keeps
    the current weights (used right before a warm graft replaces them
    anyway).
    """
    import jax

    from .sac import make_policy_apply, sac_init, sac_train_step

    cfg = dataclasses.replace(agent.cfg, lr=float(hyper["lr"]),
                              alpha_init=float(hyper["alpha_init"]))
    if cfg == agent.cfg:
        return agent
    agent.cfg = cfg
    agent.policy_apply = make_policy_apply(cfg)
    if reinit:
        agent.key, k_init = jax.random.split(agent.key)
        agent.sac = sac_init(cfg, k_init)
    agent._train = jax.jit(
        lambda sac, rb, key: sac_train_step(cfg, sac, rb, key))
    agent._fused = {}
    return agent


def _member_dir(pop_root: str, k: int) -> str:
    return os.path.join(pop_root, f"member_{k:02d}")


def _abs_ckpt_dirs(pop_root: str, rec: Dict) -> List[str]:
    return [os.path.join(pop_root, d) for d in rec["ckpt_dirs"]]


# ---------------------------------------------------------------------------
# held-out leaderboard eval (vmapped lanes, one shared realization)
# ---------------------------------------------------------------------------

def _eval_params(params: SimParams, config: PopulationConfig) -> SimParams:
    from ..fault.curriculum import make_chaos_preset
    from ..models.structs import FaultParams

    cur = make_chaos_preset(config.eval_preset,
                            duration_s=config.eval_duration)
    return dataclasses.replace(
        params, duration=config.eval_duration, obs_enabled=False,
        faults=FaultParams(curriculum=cur))


def eval_members(fleet: FleetSpec, params: SimParams,
                 config: PopulationConfig, sacs: List,
                 cfg=None, cache: Optional[Dict] = None) -> List[Dict]:
    """Held-out chaos eval of ``len(sacs)`` policies as vmapped lanes.

    Every lane starts from the SAME replicated state (identical workload
    and fault realization — :func:`~..parallel.rollout.replicated_init`),
    so the summary rows differ only through the policies.  Returns one
    ``Summary.row()`` dict per policy, each carrying ``score``
    (:func:`~..evaluation.chaos_score`).  Pure function of
    ``(params.seed, config, sacs)`` — re-running from stored checkpoints
    reproduces the ranking bit-for-bit.  ``cache`` (any dict the caller
    keeps) reuses the compiled engine + eval program across PBT
    intervals instead of re-jitting the identical chunk program per
    stage boundary.
    """
    import jax
    import jax.numpy as jnp

    from ..evaluation import _summarize, chaos_score
    from ..parallel.rollout import replicated_init
    from ..sim.engine import Engine
    from .sac import make_policy_apply

    if cfg is None:
        raise ValueError("eval_members needs the members' SACConfig")
    ep = _eval_params(params, config)
    cache = cache if cache is not None else {}
    if "run" not in cache:
        engine = Engine(fleet, ep, policy_apply=make_policy_apply(cfg))
        cache["engine"] = engine
        cache["run"] = jax.jit(jax.vmap(
            lambda st, sac: engine._run_chunk(
                st, sac, config.eval_chunk_steps)[0]))
    engine, run = cache["engine"], cache["run"]
    states = replicated_init(fleet, ep, len(sacs),
                             workload=engine.workload)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *sacs)
    for _ in range(config.eval_max_chunks):
        states = run(states, stacked)
        if bool(jnp.all(states.done)):
            break
    rows = []
    for i in range(len(sacs)):
        st = jax.tree.map(lambda a: a[i], states)
        row = _summarize(f"lane_{i:02d}", fleet, st).row()
        row["score"] = chaos_score(row)
        rows.append(row)
    return rows


def _rank(scored: Dict[int, float]) -> List[int]:
    """Member ids best-first; deterministic tiebreak on the id."""
    return sorted(scored, key=lambda k: (-scored[k], k))


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

def run_population(
    fleet: FleetSpec,
    params: SimParams,
    out_dir: str,
    chunk_steps: int = 2048,
    max_chunks: int = 10_000,
    config: Optional[PopulationConfig] = None,
    monitors: Optional[Dict[int, DivergenceMonitor]] = None,
    resume: bool = True,
    verbose: bool = False,
    shutdown=None,
    **train_kw,
):
    """Train an N-member CHSAC population through the chaos curriculum.

    Returns ``(agents, report)`` — ``agents`` maps member slot to its
    trained :class:`~.agent.CHSAC_AF`, ``report`` is the population
    summary dict (also written to ``out_dir/population_summary.json``).
    ``out_dir`` is the population root; each member lives entirely under
    ``member_<k>/`` in it.  ``monitors`` injects per-slot divergence
    monitors (tests force deterministic trips with it); unlisted slots
    get a fresh member-labeled :class:`DivergenceMonitor`.

    Raises :class:`PopulationError` (summary ``status="failed"``) only
    when EVERY member has been culled — any single member's failure is a
    quarantine-and-replace event, never a campaign abort.  ``resume``
    restores the exact member table from the last committed
    ``population_manifest.json`` interval and each member's weights from
    its last verified-healthy checkpoint.

    ``train_kw`` passes through to :func:`~.train.train_chsac`.
    """
    assert params.algo == "chsac_af", "population driver trains CHSAC-AF"
    config = config or PopulationConfig()
    cur = _curriculum_of(params)
    from ..fault.curriculum import HELD_OUT_PRESETS

    if cur.name in HELD_OUT_PRESETS:
        raise ValueError(
            f"curriculum {cur.name!r} is a held-out evaluation preset; "
            "training the population on it would contaminate the "
            "leaderboard scores")
    if params.obs_enabled and config.watchdog not in ("off", "warn", "raise"):
        raise ValueError(f"unknown watchdog mode {config.watchdog!r}")
    os.makedirs(out_dir, exist_ok=True)
    n_stages = len(cur.stages)
    base_hyper = {"lr": None, "alpha_init": None}

    def fresh_agent(rec):
        a = make_agent(fleet, dataclasses.replace(params,
                                                  seed=int(rec["seed"])))
        if base_hyper["lr"] is None:
            base_hyper["lr"] = float(a.cfg.lr)
            base_hyper["alpha_init"] = float(a.cfg.alpha_init)
        if rec.get("hyper"):
            _apply_hyper(a, rec["hyper"])
        else:
            rec["hyper"] = {"lr": float(a.cfg.lr),
                            "alpha_init": float(a.cfg.alpha_init)}
        return a

    # ---- member table: resume from the committed manifest, else draw ----
    man_step, manifest = (load_population_manifest(out_dir) if resume
                          else (None, None))
    if manifest is not None:
        members = {int(r["member"]): dict(r) for r in manifest["members"]}
        quarantine = list(manifest["quarantine"])
        intervals = list(manifest["intervals"])
        next_stage = int(manifest["next_stage"])
        next_reseed = int(manifest["next_reseed"])
        if verbose:
            print(f"population: resumed manifest step {man_step} "
                  f"(next stage {next_stage}, "
                  f"{len(members)} members)")
    else:
        members = {}
        for k in range(config.n_members):
            members[k] = {
                "member": k,
                "generation": 0,
                "seed": _member_seed(params.seed, k),
                "reseed": int(cur.reseed) + 1000 * k,
                "hyper": None,  # filled from the agent's cfg defaults
                "status": "active",
                "retries_left": config.member_retries,
                "attempts": 0,
                "ckpt_dirs": [],
                "history": [],
                "lineage": [{"event": "init", "seed": None}],
                "score": None,
                "metrics": None,
            }
            members[k]["lineage"][0]["seed"] = members[k]["seed"]
        quarantine = []
        intervals = []
        next_stage = 0
        next_reseed = int(cur.reseed) + 1000 * config.n_members
    # agents rebuild from seeds/hypers, then weights restore from each
    # member's last verified-healthy checkpoint (fresh when none exists).
    # A graft/replacement recorded at the LAST committed interval lives
    # only in the manifest lineage until the member's next checkpoint —
    # re-apply it after the restore (same donor checkpoint, same key
    # chain), or the resumed run would train from pre-graft weights and
    # silently diverge from both the lineage and an uninterrupted run.
    agents: Dict[int, object] = {}
    for k, rec in sorted(members.items()):
        agents[k] = fresh_agent(rec)
        if rec.get("hyper") and config.perturb_scale > 0 and manifest is None:
            rec["hyper"] = _draw_hyper(base_hyper, params.seed, k,
                                       config.perturb_scale)
            _apply_hyper(agents[k], rec["hyper"])
        if manifest is None or rec["status"] != "active":
            continue
        graft_ev = replaced = None
        for ev in rec["lineage"]:
            if ev.get("stage") != next_stage - 1:
                continue
            if ev["event"] in ("exploit", "replace_graft") \
                    and ev.get("donor_ckpt"):
                graft_ev = ev
            elif ev["event"] == "replaced":
                replaced = ev
        if replaced is None and rec["ckpt_dirs"]:
            # a replaced clone starts FRESH (its inherited ckpt_dirs are
            # the culled predecessor's forensics, not its own weights)
            src, step = _latest_healthy(_abs_ckpt_dirs(out_dir, rec))
            if src is not None:
                _rollback_agent(agents[k], fleet, params, src, step)
                if verbose:
                    print(f"population: member {k} restored from "
                          f"{os.path.relpath(src, out_dir)} step {step}")
        if graft_ev is not None:
            import jax

            agents[k].key, kg = jax.random.split(agents[k].key)
            agents[k].sac = warm_sac_from_checkpoint(
                agents[k].cfg,
                os.path.join(out_dir, graft_ev["donor_ckpt"]), kg,
                step=graft_ev.get("donor_step"))
            if verbose:
                print(f"population: member {k} re-applied interval-"
                      f"{next_stage - 1} graft from "
                      f"{graft_ev['donor_ckpt']}")

    def active_ids() -> List[int]:
        return [k for k, r in sorted(members.items())
                if r["status"] == "active"]

    def commit_manifest(stage_done: int) -> None:
        doc = {
            "schema": MANIFEST_SCHEMA,
            "schema_version": 1,
            "curriculum": cur.name,
            "n_stages": n_stages,
            "n_members": config.n_members,
            "next_stage": stage_done + 1,
            "next_reseed": next_reseed,
            "members": [members[k] for k in sorted(members)],
            "quarantine": quarantine,
            "intervals": intervals,
        }
        save_population_manifest(out_dir, stage_done + 1, doc)

    def cull(rec: Dict, reason: str, stage: int) -> None:
        rec["status"] = "culled"
        rec["cull_reason"] = reason
        rec["lineage"].append({"event": "culled", "stage": stage,
                               "reason": reason})
        if verbose:
            print(f"population: member {rec['member']} CULLED at stage "
                  f"{stage}: {reason}")

    def last_abort_context() -> Optional[str]:
        for q in reversed(quarantine):
            if q.get("abort_context"):
                return os.path.join(out_dir, q["abort_context"])
        return None

    def graft(k: int, donor: int, stage: int, event: str) -> bool:
        """Copy the donor's policy (enc+actor) into member k via the
        warm-checkpoint path; False when the donor has no restorable
        checkpoint (the graft is skipped with a lineage note)."""
        import jax

        src, step = _latest_healthy(_abs_ckpt_dirs(out_dir, members[donor]))
        if src is None:
            members[k]["lineage"].append(
                {"event": f"{event}_skipped", "stage": stage,
                 "donor": donor, "reason": "donor store has no verified "
                                           "checkpoint"})
            return False
        agents[k].key, kg = jax.random.split(agents[k].key)
        agents[k].sac = warm_sac_from_checkpoint(agents[k].cfg, src, kg,
                                                 step=step)
        members[k]["lineage"].append(
            {"event": event, "stage": stage, "donor": donor,
             "donor_ckpt": os.path.relpath(src, out_dir),
             "donor_step": int(step)})
        return True

    def run_member_stage(k: int, stage: int) -> None:
        """One member's segment for one stage, with quarantine/retries.

        Everything this touches is member-local (its own agent, dirs,
        reseed chain, retry budget) — the isolation invariant the e2e
        pins as byte-identity of the untouched members.
        """
        nonlocal quarantine
        rec = members[k]
        monitor = (monitors or {}).get(k)
        if monitor is None:
            monitor = DivergenceMonitor(config.divergence, member=k)
        retries_at_stage = 0
        while True:
            attempt = rec["attempts"]
            tag = f"stage{stage:02d}_try{attempt:02d}"
            seg_out = os.path.join(_member_dir(out_dir, k), tag)
            seg_ckpt = os.path.join(_member_dir(out_dir, k), "ck", tag)
            seg_params = dataclasses.replace(
                params, seed=int(rec["seed"]),
                faults=dataclasses.replace(
                    params.faults,
                    curriculum=cur.at_stage(stage).reseeded(
                        int(rec["reseed"]))))
            obs_cfg = None
            if params.obs_enabled:
                from ..obs.export import ObsConfig

                obs_cfg = ObsConfig(out_dir=seg_out,
                                    watchdog=config.watchdog, member=k)
            hist = {"stage": stage, "attempt": attempt,
                    "reseed": int(rec["reseed"]), "dir": tag}
            if verbose:
                print(f"population: member {k} {tag} stage "
                      f"{stage + 1}/{n_stages} reseed={rec['reseed']}")
            try:
                state, _agent, _h = train_chsac(
                    fleet, seg_params, out_dir=seg_out,
                    chunk_steps=chunk_steps, max_chunks=max_chunks,
                    agent=agents[k], verbose=False, ckpt_dir=seg_ckpt,
                    resume=False, obs=obs_cfg, shutdown=shutdown,
                    on_chunk=lambda c, s, h, _m=monitor: _m.check(
                        c, h[-1] if h else None),
                    **train_kw)
            except RunAbort as e:
                bundle, ctx = _abort_bundle(seg_ckpt)
                hist.update(outcome="aborted", reason=str(e),
                            kind=("divergence"
                                  if isinstance(e, DivergenceError)
                                  else "watchdog"))
                rec["history"].append(hist)
                rec["ckpt_dirs"].append(
                    os.path.relpath(seg_ckpt, out_dir))
                q = {"member": k, "stage": stage, "attempt": attempt,
                     "reseed": int(rec["reseed"]), "kind": hist["kind"],
                     "reason": str(e),
                     "bundle": (os.path.relpath(bundle, out_dir)
                                if bundle else None),
                     "abort_context": (os.path.relpath(ctx, out_dir)
                                       if ctx else None)}
                quarantine.append(q)
                if rec["retries_left"] <= 0:
                    q["action"] = "culled"
                    cull(rec, "retry budget exhausted", stage)
                    return
                src, step = _latest_healthy(_abs_ckpt_dirs(out_dir, rec))
                if src is None:
                    if any(steps(d) for d in _abs_ckpt_dirs(out_dir, rec)):
                        # steps exist but NONE verify: the member's whole
                        # store is corrupt — nothing to heal from
                        q["action"] = "culled"
                        cull(rec, "checkpoint store corrupt (no verified "
                                  "step to roll back to)", stage)
                        return
                    # no checkpoint was ever written: restart fresh
                    agents[k] = fresh_agent(rec)
                    q["action"] = "restarted"
                    q["rollback"] = None
                else:
                    _rollback_agent(agents[k], fleet, seg_params, src,
                                    step)
                    q["action"] = "rolled_back"
                    q["rollback"] = {"dir": os.path.relpath(src, out_dir),
                                     "step": int(step)}
                backoff = config.backoff_s * (2 ** retries_at_stage)
                if backoff > 0:
                    time.sleep(backoff)
                rec["retries_left"] -= 1
                rec["reseed"] = int(rec["reseed"]) + 1
                rec["attempts"] += 1
                retries_at_stage += 1
                continue
            rec["ckpt_dirs"].append(os.path.relpath(seg_ckpt, out_dir))
            if shutdown is not None and shutdown.requested:
                hist.update(outcome="interrupted")
                rec["history"].append(hist)
                return
            hist.update(outcome="completed",
                        sim_t_s=float(np.asarray(state.t)),
                        train_steps=int(agents[k].sac.step))
            rec["history"].append(hist)
            rec["attempts"] += 1
            return

    eval_cache: Dict = {}

    def eval_and_pbt(stage: int, final: bool) -> None:
        """Interval barrier: rank actives, replace culled, exploit/explore."""
        nonlocal next_reseed
        ids = active_ids()
        if not ids:
            write_summary("failed", leaderboard=[])
            raise PopulationError(
                "every population member has been culled — no survivor "
                "to exploit or clone from",
                quarantine=quarantine, abort_context=last_abort_context())
        rows = eval_members(fleet, params, config,
                            [agents[k].sac for k in ids],
                            cfg=agents[ids[0]].cfg, cache=eval_cache)
        scored = {}
        for k, row in zip(ids, rows):
            row["member"] = k
            members[k]["score"] = float(row["score"])
            members[k]["metrics"] = {
                key: row.get(key) for key in
                ("availability", "migration_success_rate", "energy_kwh",
                 "energy_cost_usd", "carbon_kg", "completed_inf",
                 "completed_trn", "dropped", "p99_lat_inf_s")}
            scored[k] = float(row["score"])
        ranked = _rank(scored)
        rec_int = {"stage": stage, "scores": scored,
                   "ranking": ranked, "grafts": [], "replaced": []}
        if verbose:
            lead = ", ".join(f"m{k}={scored[k]:.3f}" for k in ranked)
            print(f"population: interval {stage} leaderboard: {lead}")
        winner = ranked[0]
        # replace culled members with reseeded clones of the winner
        for k, rec in sorted(members.items()):
            if rec["status"] != "culled" or rec.get("replaced"):
                continue
            rec["replaced"] = True
            gen = int(rec["generation"]) + 1
            new_rec = {
                "member": k,
                "generation": gen,
                "seed": _member_seed(params.seed, k, gen),
                "reseed": next_reseed,
                "hyper": _draw_hyper(members[winner]["hyper"], params.seed,
                                     k, config.perturb_scale,
                                     salt=stage + 1),
                "status": "active",
                "retries_left": config.member_retries,
                "attempts": rec["attempts"],
                "ckpt_dirs": list(rec["ckpt_dirs"]),
                "history": list(rec["history"]),
                "lineage": rec["lineage"] + [
                    {"event": "replaced", "stage": stage,
                     "donor": winner, "generation": gen}],
                "score": None,
                "metrics": None,
            }
            next_reseed += 1
            members[k] = new_rec
            agents[k] = fresh_agent(new_rec)
            graft(k, winner, stage, "replace_graft")
            rec_int["replaced"].append({"member": k, "donor": winner,
                                        "generation": gen})
        # PBT exploit/explore over the bottom quantile (not after the
        # final stage — the leaderboard must rank what actually trained)
        if not final and config.exploit_quantile > 0 and len(ranked) > 1:
            n_bottom = int(math.floor(len(ranked)
                                      * config.exploit_quantile))
            for k in ranked[len(ranked) - n_bottom:]:
                if k == winner:
                    continue
                if graft(k, winner, stage, "exploit"):
                    members[k]["reseed"] = next_reseed
                    next_reseed += 1
                    if config.perturb_scale > 0:
                        members[k]["hyper"] = _draw_hyper(
                            members[winner]["hyper"], params.seed, k,
                            config.perturb_scale, salt=1000 + stage)
                        _apply_hyper(agents[k], members[k]["hyper"],
                                     reinit=False)
                    members[k]["lineage"].append(
                        {"event": "explore", "stage": stage,
                         "reseed": members[k]["reseed"],
                         "hyper": members[k]["hyper"]})
                    rec_int["grafts"].append({"member": k,
                                              "winner": winner})
        intervals.append(rec_int)

    def write_summary(status: str, leaderboard: List[Dict]) -> Dict:
        report = {
            "schema": SUMMARY_SCHEMA,
            "schema_version": 1,
            "status": status,
            "curriculum": cur.name,
            "n_stages": n_stages,
            "n_members": config.n_members,
            "member_retries": config.member_retries,
            "exploit_quantile": config.exploit_quantile,
            "eval_preset": config.eval_preset,
            "eval_duration": config.eval_duration,
            "leaderboard": leaderboard,
            "members": [members[k] for k in sorted(members)],
            "quarantine": quarantine,
            "intervals": intervals,
        }
        dump_json_atomic(os.path.join(out_dir, POPULATION_SUMMARY_FILE),
                         report)
        return report

    # ---- drive ----
    if manifest is None:
        commit_manifest(-1)  # interval 0 = the drawn initial member table
    status = "completed"
    for stage in range(next_stage, n_stages):
        for k in active_ids():
            run_member_stage(k, stage)
            if shutdown is not None and shutdown.requested:
                break
        if shutdown is not None and shutdown.requested:
            # no eval/PBT on a partial interval: the last committed
            # manifest stays the resume point (the member table a
            # restart restores is exactly the pre-interval one)
            status = "interrupted"
            break
        eval_and_pbt(stage, final=(stage == n_stages - 1))
        commit_manifest(stage)
    leaderboard = []
    order = _rank({k: members[k]["score"] for k in active_ids()
                   if members[k]["score"] is not None})
    for rank, k in enumerate(order):
        entry = {"rank": rank, "member": k,
                 "score": members[k]["score"],
                 "generation": members[k]["generation"],
                 "reseed": members[k]["reseed"],
                 "hyper": members[k]["hyper"],
                 "metrics": members[k]["metrics"]}
        leaderboard.append(entry)
    gc_population(out_dir)  # sweep any crash-staging debris zoo-wide
    report = write_summary(status, leaderboard)
    return agents, report


# ---------------------------------------------------------------------------
# leaderboard reproduction + winner selection (chaos_sweep --warm-ckpt)
# ---------------------------------------------------------------------------

def _load_summary(pop_root: str) -> Dict:
    path = os.path.join(pop_root, POPULATION_SUMMARY_FILE)
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    _step, manifest = load_population_manifest(pop_root)
    if manifest is None:
        raise PopulationError(
            f"{pop_root}: neither {POPULATION_SUMMARY_FILE} nor a "
            "committed population manifest — not a population root")
    return manifest


def locate_member_bundle(pop_root: str, member: int) -> str:
    """Path of member K's newest forensic abort bundle in a population root.

    Prefers the quarantine log (manifest/summary — records every bundle
    in abort order), falling back to a filesystem scan of the member's
    ``ck/*/aborted`` dirs for roots whose manifest is gone.  Raises
    :class:`PopulationError` when the member was never quarantined.
    """
    try:
        doc = _load_summary(pop_root)
    except PopulationError:
        doc = {}
    for q in reversed(doc.get("quarantine", [])):
        if int(q.get("member", -1)) == int(member) and q.get("bundle"):
            bundle = os.path.join(pop_root, q["bundle"])
            if os.path.isdir(bundle):
                return bundle
    # filesystem fallback: newest segment tag wins (tags sort by
    # stage/attempt)
    from ..sim.replay import ABORT_CONTEXT_FILE
    from .train import ABORT_CKPT_SUBDIR

    ck = os.path.join(_member_dir(pop_root, member), "ck")
    if os.path.isdir(ck):
        for seg in sorted(os.listdir(ck), reverse=True):
            bundle = os.path.join(ck, seg, ABORT_CKPT_SUBDIR)
            if os.path.exists(os.path.join(bundle, ABORT_CONTEXT_FILE)):
                return bundle
    raise PopulationError(
        f"{pop_root}: member {member} has no forensic abort bundle "
        "(never quarantined, or the bundle was removed)")


def leaderboard_winner_ckpt(pop_root: str, log=None
                            ) -> Tuple[str, int, int]:
    """(ckpt_dir, step, member) of the leaderboard winner's newest
    verified checkpoint — the donor ``chaos_sweep.py --warm-ckpt`` grafts
    the chaos-trained policy from when pointed at a population root.

    Walks the leaderboard in rank order and, per member, the member's
    segment stores newest-first through the verified fallback chain — a
    winner whose entire store is corrupt falls through to the runner-up
    with a logged reason (same degrade-don't-die contract as every other
    restore path).
    """
    log = log or (lambda msg: print(f"[population] {msg}"))
    doc = _load_summary(pop_root)
    members = {int(r["member"]): r for r in doc["members"]}
    order = [int(e["member"]) for e in doc.get("leaderboard", [])]
    if not order:  # manifest fallback: rank on the recorded scores
        order = _rank({k: r["score"] for k, r in members.items()
                       if r.get("score") is not None})
    if not order:
        raise PopulationError(
            f"{pop_root}: population has no scored members to pick a "
            "winner from")
    for member in order:
        rec = members[member]
        src, step = _latest_healthy(_abs_ckpt_dirs(pop_root, rec))
        if src is not None:
            log(f"warm-ckpt donor: leaderboard member {member} "
                f"(score {rec.get('score')}) -> "
                f"{os.path.relpath(src, pop_root)} step {step}")
            return src, int(step), member
        log(f"leaderboard member {member} has no verified checkpoint "
            "(corrupt or empty store) — falling through to the next rank")
    raise PopulationError(
        f"{pop_root}: no member has a restorable checkpoint",
        quarantine=doc.get("quarantine", []))


def evaluate_population(fleet: FleetSpec, params: SimParams, pop_root: str,
                        config: Optional[PopulationConfig] = None
                        ) -> List[Dict]:
    """Re-run the held-out leaderboard eval from the STORED checkpoints.

    Rebuilds each leaderboard member's policy via
    :func:`~.train.warm_sac_from_checkpoint` (its manifest-recorded
    hyperparameters re-specialize the config first) and replays the same
    vmapped held-out eval — a pure function of ``(params.seed, config)``
    and the stored weights, so the returned ranking must match
    ``population_summary.json``'s.  Returns leaderboard rows (rank
    order), each with ``member`` and ``score``.
    """
    import jax

    config = config or PopulationConfig()
    doc = _load_summary(pop_root)
    members = {int(r["member"]): r for r in doc["members"]}
    ids = [int(e["member"]) for e in doc.get("leaderboard", [])]
    if not ids:
        raise PopulationError(f"{pop_root}: no leaderboard to reproduce")
    sacs, cfg0 = [], None
    for k in ids:
        rec = members[k]
        agent = make_agent(fleet, dataclasses.replace(
            params, seed=int(rec["seed"])))
        if rec.get("hyper"):
            _apply_hyper(agent, rec["hyper"])
        src, step = _latest_healthy(_abs_ckpt_dirs(pop_root, rec))
        if src is None:
            raise PopulationError(
                f"{pop_root}: member {k} has no verified checkpoint to "
                "re-evaluate from", quarantine=doc.get("quarantine", []))
        agent.sac = warm_sac_from_checkpoint(
            agent.cfg, src, jax.random.key(int(rec["seed"])), step=step)
        sacs.append(agent.sac)
        cfg0 = cfg0 or agent.cfg
    rows = eval_members(fleet, params, config, sacs, cfg=cfg0)
    out = []
    for k, row in zip(ids, rows):
        row["member"] = k
        out.append(row)
    out.sort(key=lambda r: (-r["score"], r["member"]))
    for rank, row in enumerate(out):
        row["rank"] = rank
    return out

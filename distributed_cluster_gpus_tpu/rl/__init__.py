"""Deep-RL subsystem: CHSAC-AF (constrained hybrid-action SAC) in JAX/flax.

TPU-native replacement for the reference's torch stack (`simcore/rl/`):
flax modules + optax optimizers, a device-resident replay buffer, a fully
jitted distributional-SAC update, and a PID-Lagrangian CMDP — all pure
pytree-state functions so acting runs *inside* the scanned simulator and
training shards across a device mesh with pjit.
"""

from .nets import HybridActor, MLPStateEncoder, QuantileCritic  # noqa: F401
from .replay import ReplayState, replay_add_chunk, replay_init, replay_sample  # noqa: F401
from .cmdp import CMDPState, ConstraintSpec, cmdp_init, effective_reward, update_lagrange  # noqa: F401
from .sac import SACConfig, SACState, sac_init, sac_train_step, select_action  # noqa: F401
from .agent import CHSAC_AF  # noqa: F401
from .train import train_chsac  # noqa: F401
